#!/usr/bin/env python3
"""Headline benchmark: EC encode + 2-erasure decode, k=8, m=3, 4 MiB stripes.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N, ...}

value        — aggregate device throughput in data-GiB/s for one encode
               plus one degraded decode pass over the stripe batch (the
               north-star BASELINE.json configs 2+3 shape).
vs_baseline  — speedup over the same math on the host CPU via the C++
               native core (the reference's jerasure/ISA-L role:
               table-driven GF(2^8), matrix inverted once, the whole
               batch in one multithreaded matmul call). The host core
               count is recorded in the output — on a 1-vCPU driver host
               the baseline is necessarily single-core.

Measurement methodology (round-1 verdict forced a redesign, and round-2
probing found why: on this tunnel-attached chip `block_until_ready`
returns before remote execution finishes, and a host<->device round trip
costs ~105 ms — both round-1 numbers were artifacts):
- completion is forced by reading back a value that DEPENDS on every
  timed output (async-dispatch + block_until_ready measures dispatch,
  not execution, over the tunnel);
- the fixed round-trip cost cancels exactly by differencing paired
  half/full-length chains (the measured tunnel latency is reported as
  its own metric and still subtracted in the one single-run config);
- every timed iteration consumes a provably distinct input: a pre-staged
  base XORed with a per-iteration salt (the Pallas kernel is opaque to
  XLA fusion, so the salted copy costs one extra HBM write+read of the
  batch — the printed number under-reports the raw kernel, which is the
  honest direction);
- timed kernels return only per-stripe sums (a few bytes) that depend
  on every output word, so XLA cannot elide work and outputs cannot
  accumulate in HBM;
- a roofline tripwire refuses to print a number whose implied HBM
  traffic exceeds the chip's spec bandwidth;
- bit-exactness is checked untimed on a full batch: device parity vs the
  C++ host core, device repair vs the original data, every stripe;
- extra BASELINE.json configs ride along in the same JSON line:
  (1) k=2,m=1 4 KiB single-stripe encode latency,
  (4) batched crc32c over 64 KiB blobs,
  (5) straw2 bulk placement over a 1 K-OSD bucket.

Run with no JAX_PLATFORMS override so the real TPU chip is used.
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from ceph_tpu import native  # noqa: E402
from ceph_tpu.models import datapath  # noqa: E402
from ceph_tpu.ops import crc32c as crc_ops  # noqa: E402
from ceph_tpu.ops import crush as crush_ops  # noqa: E402
from ceph_tpu.ops import gf8, rs  # noqa: E402

K, M = 8, 3
CHUNK = 512 * 1024  # 4 MiB stripe / k
BATCH = 24  # 96 MiB data per dispatch
ERASED = (1, 6)  # two lost data shards
PRESENT = tuple([i for i in range(K) if i not in ERASED] + [K, K + 1])
ITERS = 96  # per-iter cost is ~2 ms; a long chain amortizes the ~100 ms
# tunnel round trip so its run-to-run jitter stays a minor correction
THREADS = os.cpu_count() or 1

# Roofline tripwire. The one real chip is a v5e ("TPU v5 lite"): ~819 GB/s
# HBM. A measured time implying more traffic than the spec allows means the
# timing loop is broken (caching/elision), not that the chip is fast.
HBM_BYTES_PER_S = 819e9
ROOFLINE_SLACK = 1.25  # measurement noise allowance

#: how each _timed_chain estimate was obtained this run ("differenced"
#: = paired-min difference; "conservative" = full chain with fixed
#: costs included) — reported in the output JSON for honesty
_TIMING_MODES: list = []


def _sync(x) -> None:
    """Force actual completion of everything x depends on (device_get of
    a scalar blocks on remote execution; block_until_ready does not)."""
    np.asarray(jax.device_get(jnp.ravel(x)[0]))


def _progress(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def measure_latency() -> float:
    """Fixed host<->device round-trip cost of the readback sync."""
    tiny = jax.jit(lambda x: x + 1)
    t = jnp.zeros(8, jnp.uint32)
    _sync(tiny(t))
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        _sync(tiny(t))
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples))


def _timed_chain(fn, salts,
                 traffic_bytes: float | None = None) -> float:
    """Seconds per call of fn(salt), fixed costs cancelled by
    differencing the MINIMA of half-length and full-length chains.

    fn must return a small array depending on all its work. One readback
    forces the whole chain; per-call cost amortizes the round trip.
    Three half chains and three full chains are timed; the estimate is
    (min(full) - min(half)) / (n - n/2). Each min is the
    least-contended observation of (fixed + iters*dt) on a SHARED
    relay whose throughput swings 3x+ minute to minute (BASELINE.md
    "Tunnel variability"), so the fixed round-trip cost cancels
    exactly — no stale startup-latency subtraction (which once made
    per-iteration time impossibly small and tripped the roofline
    guard) — and a contention stall in any single chain cannot fake a
    small dt (a per-pair difference could; "pick the plausible pair"
    repairs just laundered the artifact into a roofline-level claim).
    Every chain runs distinct salted iterations, so no single-shot
    cache artifact can win. If the difference is non-positive or
    still implies impossible HBM traffic, fall back to the full chain
    with NO subtraction (conservative: overstates cost) and record the
    mode in _TIMING_MODES; only impossible-even-unsubtracted timing
    raises.
    """
    # warm chain: compiles fn AND the scalar sum-tree kernels (their
    # first-use compile otherwise lands inside the timed region)
    warm = [fn(s) for s in salts[:2]]
    _sync(sum(jnp.sum(p.astype(jnp.uint32)) for p in warm))

    def chain(ss) -> float:
        t0 = time.perf_counter()  # clock covers dispatch too — execution
        probes = [fn(s) for s in ss]  # begins at the first enqueue
        acc = sum(jnp.sum(p.astype(jnp.uint32)) for p in probes)
        _sync(acc)
        return time.perf_counter() - t0

    half = len(salts) // 2
    halves = []
    fulls = []
    for _ in range(3):
        halves.append(chain(salts[:half]))
        fulls.append(chain(salts))
    # adaptive resampling under contention: when EITHER population
    # spreads >1.5x, the relay is visibly loaded — sample more windows
    # (fixed policy, bounded at 8 pairs) so the minima stand a chance
    # of catching a quiet one. Both populations are checked: a stall
    # isolated to the half chains would inflate min(halves) and fake a
    # SMALL dt, the exact artifact this estimator exists to avoid.
    # Only adds runtime when the tunnel is bad; tightens, never
    # changes, the estimator.
    while (max(fulls) > 1.5 * min(fulls)
           or max(halves) > 1.5 * min(halves)) and len(fulls) < 8:
        halves.append(chain(salts[:half]))
        fulls.append(chain(salts))
    # difference the MINIMA of the two populations: each min is the
    # least-contended observation of (fixed + n*dt), so their
    # difference estimates dt with the contention spikes of any single
    # pair excluded (a per-pair difference once went near zero when a
    # stall landed in the half chain, and any "pick the plausible
    # pair" repair just launders that artifact into a roofline-level
    # claim)
    dt = (min(fulls) - min(halves)) / (len(salts) - half)
    conservative = min(fulls) / len(salts)  # fixed cost included
    if dt <= 0:
        _TIMING_MODES.append("conservative")
        return conservative
    if traffic_bytes is not None:
        floor = traffic_bytes / (HBM_BYTES_PER_S * ROOFLINE_SLACK)
        if dt < floor:
            if conservative < floor:
                raise RuntimeError(
                    f"implied HBM bandwidth "
                    f"{traffic_bytes / conservative / 1e9:.0f} GB/s "
                    f"exceeds the chip spec "
                    f"{HBM_BYTES_PER_S / 1e9:.0f} GB/s even with no "
                    "fixed-cost subtraction — timing loop is "
                    "measuring dispatch, not execution")
            # transient tunnel weirdness: report the honest slower
            # number rather than a manufactured roofline figure
            _TIMING_MODES.append("conservative")
            return conservative
    _TIMING_MODES.append("differenced")
    return dt


def headline(latency: float) -> dict:
    """Configs 2+3: batched encode + 2-erasure decode, k=8 m=3, 4 MiB."""
    params = datapath.ECParams(k=K, m=M, chunk_bytes=CHUNK)
    surv_rows = [i for i in PRESENT if i < K]
    rmat = gf8.decode_matrix(params.matrix, K, list(PRESENT))

    base = jax.random.bits(jax.random.key(42), (BATCH, K, params.words),
                           dtype=jnp.uint32)
    salts = [jnp.uint32(0x9E3779B9 * (i + 1) & 0xFFFFFFFF)
             for i in range(ITERS)]

    @jax.jit
    def enc_probe_2(b, salt):
        # Pure encode_chunks, the BASELINE config-2 shape (the reference
        # harness ceph_erasure_code_benchmark times encode alone; hinfo
        # CRCs are config 4's job). The salted input forces distinct work
        # per iteration; the scalar sum depends on every parity word so
        # nothing can be elided. b is an argument, not a closure constant
        # (constants ship with the compile request).
        parity = rs.gf_matmul(params.matrix, b ^ salt)
        return jnp.sum(parity, axis=(1, 2))

    @jax.jit
    def dec_probe_2(b, salt):
        surv = (b ^ salt)[:, : len(PRESENT), :]  # shape (B, k, W)
        decoded = rs.gf_matmul(rmat, surv)
        return jnp.sum(decoded, axis=(1, 2))

    # Genuinely fused round trip: encode (m x k) and the 2-erasure
    # repair (k x k) read the SAME k survivor rows in this probe shape,
    # so both matrices STACK into one (m+k, k) GF matmul — one
    # dispatch, one HBM read of the batch, every output row computed
    # in a single pass (round-4 verdict #9: the two-matmul "fusion"
    # relied on XLA to merge the passes and measured SLOWER than
    # unfused; the stacked matrix removes that bet entirely).
    stacked = np.concatenate([params.matrix, rmat])

    @jax.jit
    def roundtrip_probe_2(b, salt):
        out = rs.gf_matmul(stacked, b ^ salt)
        return jnp.sum(out, axis=(1, 2))

    enc_probe = functools.partial(enc_probe_2, base)
    dec_probe = functools.partial(dec_probe_2, base)
    rt_probe = functools.partial(roundtrip_probe_2, base)

    _sync(enc_probe(salts[0]))
    _sync(dec_probe(salts[0]))
    _sync(rt_probe(salts[0]))
    # per-iteration HBM floor: each chain reads the data batch once
    data_bytes = BATCH * K * CHUNK
    dt_enc = _timed_chain(enc_probe, salts,
                          traffic_bytes=data_bytes)
    dt_dec = _timed_chain(dec_probe, salts,
                          traffic_bytes=data_bytes)
    dt = _timed_chain(rt_probe, salts,
                      traffic_bytes=data_bytes)
    # Tripwire floor on HBM traffic per fused iteration: ONE read of
    # the data batch (XLA single-reads it for both fused passes; the
    # salt XOR and the small parity/decoded outputs add more, which
    # only loosens the implied bandwidth below the true figure).
    traffic = data_bytes
    implied = traffic / dt
    if implied > HBM_BYTES_PER_S * ROOFLINE_SLACK:
        raise RuntimeError(
            f"implied HBM bandwidth {implied / 1e9:.0f} GB/s exceeds the "
            f"chip spec {HBM_BYTES_PER_S / 1e9:.0f} GB/s — timing loop is "
            "measuring dispatch, not execution"
        )
    # The unfused framing must pass the SAME tripwire before it may
    # become the headline: each separate chain reads the batch once
    implied_unfused = 2 * data_bytes / (dt_enc + dt_dec)
    if implied_unfused > HBM_BYTES_PER_S * ROOFLINE_SLACK:
        raise RuntimeError(
            f"unfused implied HBM bandwidth {implied_unfused / 1e9:.0f} "
            f"GB/s exceeds the chip spec — timing loop is measuring "
            "dispatch, not execution"
        )
    # work throughput: one encode pass + one decode pass over the
    # batch. The HEADLINE is whichever framing is faster — fused
    # (stacked single dispatch) or the sum of separate dispatches —
    # with BOTH reported under named keys and the winner recorded ONCE
    # in headline_mode (round-4 advisor: no silent metric swaps).
    fused_gibs = 2 * data_bytes / dt / 2**30
    unfused_gibs = 2 * data_bytes / (dt_enc + dt_dec) / 2**30
    gibs_dev, headline_mode = max(
        (fused_gibs, "fused_stacked"), (unfused_gibs, "unfused_sum"))

    # ---- untimed full-batch bit-exactness: encode + repair round trip
    enc = datapath.jit_write_step(params)
    dec = datapath.jit_repair_step(params, PRESENT)
    parity, _ = enc(base)

    @jax.jit
    def build_surviving(data, parity):
        return jnp.concatenate(
            [data[:, surv_rows, :], parity[:, : len(ERASED), :]], axis=1
        )

    decoded, _ = dec(build_surviving(base, parity))
    host_in = rs.unpack_u32(np.asarray(base))  # (B, K, CHUNK)
    host_par = rs.unpack_u32(np.asarray(parity))  # (B, M, CHUNK)
    if not (rs.unpack_u32(np.asarray(decoded)) == host_in).all():
        raise AssertionError("device repair differs from original data")
    flat = np.ascontiguousarray(host_in.transpose(1, 0, 2)).reshape(
        K, BATCH * CHUNK
    )
    want = native.rs_encode(params.matrix, flat, threads=THREADS)
    got_flat = np.ascontiguousarray(host_par.transpose(1, 0, 2)).reshape(
        M, BATCH * CHUNK
    )
    if not (got_flat == want).all():
        raise AssertionError("device parity differs from host reference")

    # ---- honest host baseline: same math, matrix inversion once, whole
    # batch as ONE multithreaded C++ matmul per direction (ISA-L shape).
    surv_flat = np.concatenate(
        [
            np.ascontiguousarray(host_in[:, surv_rows, :].transpose(1, 0, 2)),
            np.ascontiguousarray(
                host_par[:, : len(ERASED), :].transpose(1, 0, 2)
            ),
        ],
        axis=0,
    ).reshape(K, BATCH * CHUNK)
    # median of 5: single-shot timing on a shared single-core VM swings
    # 2x run to run; the median is the honest stable figure
    host_times = []
    for _ in range(5):
        t0 = time.perf_counter()
        native.rs_encode(params.matrix, flat, threads=THREADS)
        native.rs_matmul(rmat, surv_flat, threads=THREADS)
        host_times.append(time.perf_counter() - t0)
    dt_host = sorted(host_times)[len(host_times) // 2]
    gibs_host = 2 * data_bytes / dt_host / 2**30

    return {
        "metric": "ec_encode_plus_2erasure_decode_k8m3_4MiB_stripes",
        "value": round(gibs_dev, 3),
        "unit": "GiB/s",
        "headline_mode": headline_mode,
        "fused_stacked_gibs": round(fused_gibs, 3),
        "unfused_gibs": round(unfused_gibs, 3),
        "vs_baseline": round(gibs_dev / gibs_host, 2),
        "host_gibs": round(gibs_host, 3),
        "host_threads": THREADS,
        "hbm_roofline_frac": round(implied / HBM_BYTES_PER_S, 3),
        "tunnel_latency_ms": round(latency * 1e3, 1),
        "roundtrip_ms": round(dt * 1e3, 2),
        "encode_ms": round(dt_enc * 1e3, 2),
        "decode_ms": round(dt_dec * 1e3, 2),
    }


def config1_small_stripe(latency: float) -> dict:
    """Config 1: RS k=2,m=1, 4 KiB chunks — single-stripe encode."""
    mat = native.rs_matrix_vandermonde(2, 1)
    chunks = np.random.default_rng(7).integers(
        0, 256, (2, 4096), dtype=np.uint8
    )
    reps = 2000
    t0 = time.perf_counter()
    for _ in range(reps):
        native.rs_encode(mat, chunks)
    host_us = (time.perf_counter() - t0) / reps * 1e6

    params = datapath.ECParams(k=2, m=1, chunk_bytes=4096)
    base = jnp.asarray(rs.pack_u32(chunks)[None])

    @jax.jit
    def enc_probe_2(b, salt):
        _, crcs = datapath.write_step(params, b ^ salt)
        return crcs

    enc_probe = functools.partial(enc_probe_2, base)

    salts = [jnp.uint32(17 * (i + 1)) for i in range(100)]
    _sync(enc_probe(salts[0]))
    dev_us = _timed_chain(enc_probe, salts) * 1e6
    return {
        "host_encode_us": round(host_us, 1),
        "device_encode_us_amortized": round(dev_us, 1),
        "note": "latency-bound single-stripe shape; device wins by batching",
    }


def config4_crc32c(latency: float) -> dict:
    """Config 4: batched crc32c over 64 KiB blobs (BlueStore csum shape).

    1 M x 64 KiB = 64 GiB does not fit; throughput is measured on
    4096-blob (256 MiB) passes — GiB/s is the scale-invariant quantity.
    """
    nblobs, blob = 4096, 65536
    words = blob // 4
    base = jax.random.bits(jax.random.key(3), (nblobs, words),
                           dtype=jnp.uint32)
    seed_part = np.uint32(crc_ops.zeros_shift(0xFFFFFFFF, blob))

    @jax.jit
    def crc_probe_2(b, salt):
        return crc_ops._crc0_words(b ^ salt) ^ seed_part

    crc_probe = functools.partial(crc_probe_2, base)

    # 96 iterations, matching the headline: with a ~107 ms tunnel round
    # trip, 12 iterations left the residual in the noise and produced a
    # 5x r02->r03 swing (round-3 verdict #3 — spread must be <20%)
    salts = [jnp.uint32(0x01000193 * (i + 1) & 0xFFFFFFFF)
             for i in range(96)]
    _sync(crc_probe(salts[0]))
    dt = _timed_chain(crc_probe, salts)
    gibs_dev = nblobs * blob / dt / 2**30

    # guard: salted stream vs the host hw-accelerated CRC
    got0 = np.asarray(crc_probe(salts[0]))
    blobs0 = np.ascontiguousarray(
        np.asarray(base ^ salts[0]).astype("<u4")
    ).view(np.uint8).reshape(nblobs, blob)
    want = native.crc32c_batch(blobs0, threads=THREADS)
    if not (got0 == want).all():
        raise AssertionError("device crc32c differs from host")

    t0 = time.perf_counter()
    native.crc32c_batch(blobs0, threads=THREADS)
    dt_host = time.perf_counter() - t0
    gibs_host = nblobs * blob / dt_host / 2**30
    return {
        "device_gibs": round(gibs_dev, 2),
        "host_gibs": round(gibs_host, 2),
        "vs_host": round(gibs_dev / gibs_host, 2),
    }


def config5_straw2(latency: float) -> dict:
    """Config 5: straw2 bulk placement over a 1 K-OSD bucket, at the
    FULL BASELINE size: 10 M objects x 1 K OSDs.

    Ceiling analysis (measured r3): the kernel is VPU-integer bound —
    the 5x-hashmix Jenkins hash alone runs at ~0.7 Mobj/s/chip, and a
    hand-written Pallas variant of hash+argmax matches XLA's fusion
    (0.435 vs 0.426 Mobj/s), so there is no free kernel-side win; the
    remaining costs are the emulated-int64 divide and the LUT one-hot
    (gather and one-hot paths measure equal). The north-star 10 Mobj/s
    is a v5e-8 figure: per-chip Mobj/s here x 8 shards of the object
    stream (placement is embarrassingly parallel over objects).
    """
    n_osds, chunk, nchunks = 1000, 131072, 76  # ~10.0 M objects
    rng = np.random.default_rng(11)
    items = np.arange(n_osds, dtype=np.int32)
    weights = rng.integers(1, 4 * 0x10000, n_osds, dtype=np.uint32)
    items_d = jnp.asarray(items)
    weights_d = jnp.asarray(weights)
    xs = rng.integers(0, 2**32, chunk * (nchunks + 1), dtype=np.uint32)
    xs_d = jnp.asarray(xs)

    with crush_ops.enable_x64():
        warm = crush_ops._jit_straw2(
            items_d, items_d, weights_d, xs_d[:chunk], jnp.uint32(0)
        )
        _sync(warm[0].astype(jnp.int32) + warm[1].astype(jnp.int32))
        t0 = time.perf_counter()
        outs = [
            crush_ops._jit_straw2(
                items_d, items_d, weights_d,
                xs_d[(i + 1) * chunk : (i + 2) * chunk], jnp.uint32(0),
            )
            for i in range(nchunks)
        ]
        acc = sum(o[0].astype(jnp.int32) for o in outs)
        _sync(acc)
        dt = max(time.perf_counter() - t0 - latency, 1e-9)
    mobj_dev = nchunks * chunk / dt / 1e6

    # guard + host baseline on a subset
    sub = 100_000
    t0 = time.perf_counter()
    want = native.straw2_bulk(items, weights, xs[chunk : chunk + sub],
                              threads=THREADS)
    dt_host = time.perf_counter() - t0
    got = np.concatenate([np.asarray(o) for o in outs[: sub // chunk + 1]])[
        :sub
    ]
    if not (got == want).all():
        raise AssertionError("device straw2 differs from host")
    mobj_host = sub / dt_host / 1e6
    return {
        "device_mobj_s": round(mobj_dev, 3),
        "host_mobj_s": round(mobj_host, 3),
        "vs_host": round(mobj_dev / mobj_host, 2),
        "osds": n_osds,
        "objects": nchunks * chunk,
        "full_run_s": round(dt, 2),
        "projected_v5e8_mobj_s": round(mobj_dev * 8, 2),
    }


def config6_rados_bench(latency: float) -> dict:
    """End-to-end cluster benchmark (rados bench role, round-3 verdict
    #3 — src/common/obj_bencher.h:64-113): client -> OSD -> store ->
    device EC through a live TestCluster on a k=8,m=3 pool, 4 MiB
    objects, fixed-duration write phase then a seq-read phase.

    This measures the SYSTEM, tunnel warts and all: every EC write's
    stripes ride the ECBatcher to the real chip, so the ec_batches /
    stripes-per-batch counters in the output are the direct evidence of
    whether device dispatch amortizes under a real op stream.

    The write phase drives the client's aio op WINDOW (ONE submitter
    task, client_max_inflight = concurrency) instead of N blocking
    writer tasks — same in-flight depth as prior rounds, so the
    trajectory stays comparable, but per-op costs amortize across the
    window. The payload reports the three new occupancy counters next
    to stripes_per_batch: inflight_window_occupancy (client),
    frames_per_drain (messenger cork), txns_per_commit (store group
    commit, from the walstore sub-phase below)."""
    import asyncio

    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    obj_bytes = 4 << 20
    concurrency = 16
    write_secs = 8.0

    # coalescing knobs (cluster/ecbatch.py): hold stripes up to the
    # window/size target so writes from different ops share a device
    # dispatch; op concurrency is what lets stripes meet in the window
    batch_window_s = 0.01
    batch_target_stripes = 48
    op_concurrency = 32

    async def run_bench(objectstore: str = "memstore",
                        data_dir: str | None = None,
                        store_kw: dict | None = None,
                        secs: float = write_secs,
                        with_reads: bool = True) -> dict:
        from ceph_tpu.utils.buffer import STATS as BL_STATS

        c = TestCluster(n_osds=12, osd_conf={
            "osd_ec_batch_window": batch_window_s,
            "osd_ec_batch_target_stripes": batch_target_stripes,
            "osd_op_concurrency": op_concurrency,
        }, objectstore=objectstore, data_dir=data_dir,
            **(store_kw or {}))
        await c.start()
        c.client.op_timeout = 60.0  # first-shape compiles are slow
        c.client.conf.set("client_max_inflight", concurrency)
        # stripe_unit 64 KiB (the reference's is pool-configurable the
        # same way): 4 KiB cells made a 4 MiB object 1,408 tiny python
        # cells; 64 KiB keeps per-cell CRC granularity useful while the
        # per-op bookkeeping stays O(88). backend=auto probes device
        # vs host EC engine economics (ec/engine.py) — over this
        # ~10 MiB/s tunnel the C++ host core wins; on a chip-local
        # link the device batch path wins and is picked instead.
        # pg_num 32: ops serialize per-PG (the reference's ordering
        # contract), so PG count IS the op-level parallelism; 8 PGs
        # under-filled even one reactor core (~20% measured loss).
        # Real deployments run >=128 PGs on 12 OSDs.
        await c.client.create_pool(Pool(
            id=2, name="bench", size=11, min_size=9, pg_num=32,
            crush_rule=1, type="erasure",
            ec_profile={"plugin": "rs_tpu", "k": "8", "m": "3",
                        "stripe_unit": "65536"}))
        await c.wait_active(30)
        payload = np.random.default_rng(5).integers(
            0, 256, obj_bytes, dtype=np.uint8).tobytes()
        # warm: compile the EC batch kernels outside the timed phase
        await c.client.write_full(2, "warm", payload)

        # write phase: ONE submitter drives the aio window at the same
        # in-flight depth the old 16-task shape had — aio_write_full
        # blocks exactly when the window is full, so the pipeline stays
        # at client_max_inflight ops without task-per-op overhead
        comps: list = []
        seq = 0
        # per-op latency samples (this round's trajectory gains
        # percentiles next to MiB/s — config 10's fields)
        lat_w: list = []
        lat_r: list = []
        # buffer-plane ledger: count flattens/zero-copy sends over the
        # measured phases only (warmup/pool-create marshals excluded)
        BL_STATS.reset()
        bus_zc0 = c.bus.zero_copy_sends
        t_end = time.perf_counter() + secs
        t0 = time.perf_counter()
        while time.perf_counter() < t_end:
            name = f"b-{seq}"
            seq += 1
            comp = await c.client.aio_write_full(2, name, payload)
            comp.add_done_callback(
                lambda _c, t1=time.perf_counter():
                    lat_w.append(time.perf_counter() - t1))
            comps.append((name, comp))
        await c.client.writes_wait()
        dt_w = time.perf_counter() - t0
        written = []
        for name, comp in comps:
            comp.result()  # a failed write must fail the bench loudly
            written.append(name)

        dt_r = 0.0
        if with_reads:
            sem = asyncio.Semaphore(concurrency)

            async def reader(name: str) -> None:
                async with sem:
                    t1 = time.perf_counter()
                    got = await c.client.read(2, name)
                    lat_r.append(time.perf_counter() - t1)
                    assert len(got) == obj_bytes

            t0 = time.perf_counter()
            await asyncio.gather(*(reader(n) for n in written))
            dt_r = time.perf_counter() - t0

        batches = stripes = failures = 0
        fail_injected = fail_dispatch = 0
        crc_errs = stale_excl = 0
        ov_calls = ov_exts = ov_cols = 0
        dec_batches = dec_stripes = 0
        qwait_sum = qwait_n = 0.0
        flush: dict[str, int] = {}
        faults: dict[str, int] = {}
        # store group-commit ledger (CommitStats.dump over every OSD
        # store): txns_per_commit / commits_grouped / commit_flush_us
        commits = commits_grouped = store_txns = 0
        flush_us_sum = 0.0
        for s in c.stores:
            d = s.commit_stats.dump()
            commits += d["commits"]
            commits_grouped += d["commits_grouped"]
            store_txns += d["txns"]
            flush_us_sum += s.commit_stats.flush_us_sum
        for osd in c.osds:
            if osd is None:
                continue
            d = osd.perf.dump()
            batches += int(d.get("ec_batches", 0))
            failures += int(d.get("ec_batch_failures", 0))
            fail_injected += int(d.get("ec_batch_failures_injected", 0))
            fail_dispatch += int(d.get("ec_batch_failures_dispatch", 0))
            crc_errs += int(d.get("ec_read_crc_err", 0))
            stale_excl += int(d.get("ec_read_stale_shard", 0))
            ov_calls += int(d.get("ov_apply_calls", 0))
            ov_exts += int(d.get("ov_apply_extents", 0))
            ov_cols += int(d.get("ov_apply_stripes", 0))
            for key, val in d.items():
                if str(key).startswith("faults_injected_"):
                    site = str(key)[len("faults_injected_"):]
                    faults[site] = faults.get(site, 0) + int(val)
            dec_batches += int(d.get("ec_decode_batches", 0))
            h = d.get("ec_batch_stripes", {})
            if isinstance(h, dict):
                stripes += int(h.get("sum", h.get("count", 0) or 0))
            h = d.get("ec_decode_stripes", {})
            if isinstance(h, dict):
                dec_stripes += int(h.get("sum", 0))
            h = d.get("ec_queue_wait_us", {})
            if isinstance(h, dict):
                qwait_sum += float(h.get("sum", 0.0))
                qwait_n += float(h.get("count", 0))
            for key, val in d.items():
                if str(key).startswith("ec_flush_"):
                    reason = str(key)[len("ec_flush_"):]
                    flush[reason] = flush.get(reason, 0) + int(val)
        ws = dict(c.client.window_stats)
        client_retries = c.client.op_retries
        # serving-plane ledger: client resolver + every OSD's resolver
        from ceph_tpu.placement.resolver import PlacementStats
        place = PlacementStats.aggregate(
            [c.client.placement_stats()]
            + [osd.placement.stats.dump() for osd in c.osds
               if osd is not None])
        bus_bursts = c.bus.delivery_bursts
        bus_frames = c.bus.frames_delivered
        bus_fpd = c.bus.frames_per_drain
        # buffer-plane evidence: zero-copy LocalBus deliveries (client-
        # facing bodies NOT re-encoded per hop) and what still flattens
        bl = BL_STATS.dump()
        bl["bl_zero_copy_sends"] = c.bus.zero_copy_sends - bus_zc0
        bl["bus_snapshot_delivery"] = c.bus.snapshot_delivery
        await c.stop()
        from ceph_tpu.ec import engine as ec_engine

        def pct(lat: list, p: float) -> float:
            if not lat:
                return 0.0
            ms = sorted(x * 1e3 for x in lat)
            return round(ms[min(len(ms) - 1, int(p * len(ms)))], 1)

        n = len(written)
        return {
            "object_bytes": obj_bytes,
            "concurrency": concurrency,
            "objectstore": objectstore,
            "ec_engine": ec_engine.data_path_engine(),
            # the device-engine economics recorded NEXT TO the engine
            # actually used (the probe times the fused encode+CRC
            # dispatch both ways): over the tunnel-attached chip the
            # host C++ core wins and stays the data-path default — the
            # device number here is what a chip-local link would get
            "ec_engine_probe": dict(ec_engine.last_probe),
            # r04 ran 4 KiB stripe_units (128 stripes/object); r05 runs
            # 64 KiB (8 stripes/object) — same bytes per batch, so
            # compare stripes_per_batch x stripe_unit across rounds
            "stripe_unit": 65536,
            "write_ops_s": round(n / dt_w, 2),
            "write_mib_s": round(n * obj_bytes / dt_w / 2**20, 1),
            "seqread_ops_s": round(n / dt_r, 2) if dt_r else 0.0,
            "seqread_mib_s": round(n * obj_bytes / dt_r / 2**20, 1)
            if dt_r else 0.0,
            # percentiles join the trajectory this round (same field
            # shape as config 10): tail latency is the claim MiB/s
            # alone cannot carry
            "latency": {
                "write": {"p50_ms": pct(lat_w, 0.50),
                          "p99_ms": pct(lat_w, 0.99),
                          "p999_ms": pct(lat_w, 0.999)},
                "seqread": {"p50_ms": pct(lat_r, 0.50),
                            "p99_ms": pct(lat_r, 0.99),
                            "p999_ms": pct(lat_r, 0.999)},
            },
            # vectorized-overlay evidence: ONE staging materialization
            # per EC write op (ov_apply_calls ~= write ops)
            "ov_apply_calls": ov_calls,
            "ov_apply_extents": ov_exts,
            "ov_apply_stripes": ov_cols,
            # batched placement service (client + OSD resolvers)
            "placement": place,
            "objects": n,
            # ---- write-path pipelining occupancy (this PR's seam
            # evidence): how full the client window ran, how many
            # frames each messenger drain burst carried, how many
            # txns each store commit grouped
            "client_max_inflight": concurrency,
            "inflight_window_occupancy": {
                "mean": round(ws["sum"] / ws["count"], 2)
                if ws["count"] else 0.0,
                "max": ws["max"],
            },
            "frames_per_drain": round(bus_fpd, 2),
            "delivery_bursts": bus_bursts,
            "frames_delivered": bus_frames,
            # ---- buffer plane (this PR's copy-elimination evidence):
            # bl_zero_copy_sends = snapshot-view LocalBus deliveries,
            # bl_flattens / bl_bytes_flattened = copies still paid at
            # sanctioned boundaries during the measured phases
            **bl,
            "store_commits": commits,
            "store_commits_grouped": commits_grouped,
            "store_txns": store_txns,
            "txns_per_commit": round(store_txns / commits, 2)
            if commits else 0.0,
            "commit_flush_us_mean": round(flush_us_sum / commits, 1)
            if commits else 0.0,
            "ec_batches": batches,
            "ec_stripes_batched": stripes,
            "stripes_per_batch": round(stripes / batches, 1)
            if batches else 0.0,
            # WHY batches are the size they are (cluster/ecbatch.py):
            # the flush-reason breakdown plus mean queue wait tells
            # whether occupancy is window-bound, size-bound, or the
            # mClock fast path is draining sparse cohorts
            # robustness ledger (PR 3): a clean bench run must show
            # zero failures/CRC errors/injections — nonzero here means
            # the measured number rode a degraded path
            "ec_batch_failures": failures,
            "ec_batch_failures_injected": fail_injected,
            "ec_batch_failures_dispatch": fail_dispatch,
            "ec_read_crc_err": crc_errs,
            "ec_read_stale_shard": stale_excl,
            "client_op_retries": client_retries,
            "faults_injected": faults,
            "ec_decode_batches": dec_batches,
            "ec_decode_stripes": dec_stripes,
            "flush_reasons": flush,
            "batch_queue_wait_ms_mean": round(
                qwait_sum / qwait_n / 1e3, 3) if qwait_n else 0.0,
            "batch_window_s": batch_window_s,
            "batch_target_stripes": batch_target_stripes,
            "op_concurrency": op_concurrency,
        }

    out = asyncio.run(run_bench())
    # ---- group-commit sub-phase: the SAME pipeline over a durable
    # walstore with the commit window on, so txns_per_commit measures
    # real flush amortization (the main phase stays on memstore to
    # keep the round-over-round write_mib_s trajectory apples-to-
    # apples; a memstore "commit" has no flush to group)
    import shutil
    import tempfile

    tmpd = tempfile.mkdtemp(prefix="ceph_tpu_bench6_gc_")
    try:
        gc = asyncio.run(run_bench(
            objectstore="walstore", data_dir=tmpd,
            store_kw=dict(compression=None, wal_compact_bytes=1 << 30,
                          commit_window_ms=5.0, commit_max_txns=64),
            secs=4.0, with_reads=False))
        out["group_commit_store"] = {
            k: gc[k] for k in (
                "objectstore", "objects", "write_ops_s", "write_mib_s",
                "store_commits", "store_commits_grouped", "store_txns",
                "txns_per_commit", "commit_flush_us_mean",
            )
        }
        out["group_commit_store"]["commit_window_ms"] = 5.0
        out["group_commit_store"]["commit_max_txns"] = 64
    finally:
        shutil.rmtree(tmpd, ignore_errors=True)
    return out


def config7_rbd_cache(_latency: float) -> dict:
    """ObjectCacher under rbd (round-4 verdict #10): 64 KiB sequential
    reads over a 16 MiB image, cache off vs on. One-shot whole-object
    streams (config6 seq-read) cannot benefit from a client cache by
    construction — the win is sub-object access patterns, where the
    whole-object read-ahead turns 64 round trips per object into 1."""
    import asyncio

    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool
    from ceph_tpu.services.rbd import RBD

    img_bytes = 16 << 20
    io_sz = 64 << 10

    async def run_bench() -> dict:
        c = TestCluster(n_osds=4)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rbd", size=3, pg_num=8, crush_rule=0))
        await c.wait_active(30)
        rbd = RBD(c.client, 1)
        await rbd.create("bench", img_bytes)
        img = await rbd.open("bench")
        payload = np.random.default_rng(9).integers(
            0, 256, img_bytes, dtype=np.uint8).tobytes()
        await img.write(0, payload)

        async def sweep(handle) -> float:
            t0 = time.perf_counter()
            for off in range(0, img_bytes, io_sz):
                got = await handle.read(off, io_sz)
                assert len(got) == io_sz
            return time.perf_counter() - t0

        dt_off = await sweep(await rbd.open("bench"))
        cached = await rbd.open("bench", cache=True)
        # steady-state measurement: the one-time exclusive-lock
        # handover (cached reads require ownership) happens before the
        # timed sweep, as it would in any long-lived attachment
        await cached.acquire_lock()
        dt_on = await sweep(cached)
        out = {
            "io_bytes": io_sz,
            "image_bytes": img_bytes,
            "uncached_mib_s": round(img_bytes / dt_off / 2**20, 1),
            "cached_mib_s": round(img_bytes / dt_on / 2**20, 1),
            "speedup": round(dt_off / dt_on, 2),
            "cache_hits": cached._cacher.hits,
            "cache_misses": cached._cacher.misses,
        }
        await c.stop()
        return out

    return asyncio.run(run_bench())


def config8_multichip(_latency: float) -> dict:
    """Multi-chip config 6 (ROADMAP "multi-chip data plane"): the SAME
    client -> OSD -> store -> EC pipeline as config 6, served over the
    parallel/ mesh — batched stripes land device-resident, the fused
    encode+CRC runs sharded so each chip produces the shard rows it
    owns (zero host gathers in the write phase, counter-proven), and
    the payload reports per-chip stripe occupancy plus scaling vs the
    1-chip run of the same workload.

    Runs in a SUBPROCESS: XLA parses the forced-host-device flags once
    per process, so the mesh platform must be pinned before any
    backend init — the parent's chip/tunnel backend stays untouched.
    The payload keeps the MULTICHIP trajectory shape
    (n_devices / rc / ok / skipped / tail) with the measured detail
    alongside."""
    import subprocess

    n = int(os.environ.get("CEPH_TPU_BENCH_MESH_DEVICES", "8"))
    width = int(os.environ.get("CEPH_TPU_BENCH_MESH_WIDTH", "2"))
    cmd = [sys.executable, os.path.abspath(__file__),
           "--multichip-child", str(n), str(width)]
    out = {"n_devices": n, "mesh_width": width, "rc": 0, "ok": False,
           "skipped": False, "tail": ""}
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=900)
    except subprocess.TimeoutExpired as e:
        out["rc"] = -1
        out["tail"] = ((e.stderr or b"").decode("utf-8", "replace")
                       if isinstance(e.stderr, bytes)
                       else (e.stderr or ""))[-400:]
        return out
    out["rc"] = proc.returncode
    err_lines = (proc.stderr or "").strip().splitlines()
    out["tail"] = err_lines[-1][-400:] if err_lines else ""
    if proc.returncode != 0:
        out["tail"] = "\n".join(err_lines[-6:])[-800:]
        return out
    try:
        detail = json.loads((proc.stdout or "").strip().splitlines()[-1])
    except (ValueError, IndexError):
        out["tail"] = f"unparseable child stdout: {proc.stdout[-200:]!r}"
        return out
    # the bar: the mesh actually ENGAGED (a degraded/misconfigured
    # platform would serve single-device with trivially-zero gathers),
    # the write phase gathered nothing, and parity is byte-identical
    write_phase = detail.get("multichip", {}).get("write_phase", {})
    out["ok"] = (bool(detail.get("parity_ok"))
                 and write_phase.get("mesh_encode_dispatches", 0) > 0
                 and write_phase.get("mesh_host_gathers", 1) == 0)
    out.update(detail)
    return out


def _multichip_child(n: int, width: int) -> int:
    """Config 8's measured body (fresh process, forced n-device host
    platform when no real multi-chip backend is available). Prints ONE
    JSON line on stdout."""
    from ceph_tpu import parallel

    parallel.pin_virtual_cpu(n)
    # the mesh IS the engine under test: the auto probe would pick the
    # host C++ core on the virtual-CPU stand-in and measure nothing
    os.environ["CEPH_TPU_EC_ENGINE"] = "device"

    import asyncio

    from ceph_tpu.cluster.ecbatch import ECBatcher
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.ec import load_codec
    from ceph_tpu.parallel import runtime
    from ceph_tpu.placement.osdmap import Pool
    from ceph_tpu.utils import config as cfg

    obj_bytes = 4 << 20
    concurrency = 16
    secs = 4.0
    base_conf = {
        "osd_ec_batch_window": 0.01,
        "osd_ec_batch_target_stripes": 48,
        "osd_op_concurrency": 32,
    }
    mesh_conf = {
        **base_conf,
        "osd_ec_mesh_devices": n,
        "osd_ec_mesh_width": width,
        "parallel_repair_mode": "allgather",
    }

    async def run_pipeline(osd_conf: dict) -> dict:
        c = TestCluster(n_osds=12, osd_conf=osd_conf)
        await c.start()
        c.client.op_timeout = 120.0
        c.client.conf.set("client_max_inflight", concurrency)
        await c.client.create_pool(Pool(
            id=2, name="bench8", size=11, min_size=9, pg_num=16,
            crush_rule=1, type="erasure",
            ec_profile={"plugin": "rs_tpu", "k": "8", "m": "3",
                        "stripe_unit": "65536", "backend": "device"}))
        await c.wait_active(30)
        payload = np.random.default_rng(5).integers(
            0, 256, obj_bytes, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "warm", payload)  # compile outside
        runtime.STATS.reset()
        comps = []
        seq = 0
        t_end = time.perf_counter() + secs
        t0 = time.perf_counter()
        while time.perf_counter() < t_end:
            comps.append(await c.client.aio_write_full(
                2, f"b-{seq}", payload))
            seq += 1
        await c.client.writes_wait()
        dt_w = time.perf_counter() - t0
        for comp in comps:
            comp.result()
        # the write-phase mesh ledger, snapshotted BEFORE reads: the
        # acceptance bar is mesh_host_gathers == 0 here
        write_stats = runtime.STATS.dump()
        got = await c.client.read(2, "b-0")
        assert got == payload
        mesh_dispatches = 0
        for osd in c.osds:
            if osd is None:
                continue
            d = osd.perf.dump()
            mesh_dispatches += int(d.get("ec_mesh_encode_dispatches", 0))
        await c.stop()
        return {
            "objects": seq,
            "write_mib_s": round(seq * obj_bytes / dt_w / 2**20, 1),
            "write_ops_s": round(seq / dt_w, 2),
            "osd_mesh_encode_dispatches": mesh_dispatches,
            "write_phase": write_stats,
        }

    def parity_probe() -> dict:
        """Byte-identical proof: the SAME random stripes through the
        mesh batcher and the single-device batcher must produce
        identical parity, CRCs, and decode output (both combine
        strategies)."""
        rng = np.random.default_rng(11)
        cells = rng.integers(0, 256, (13, 8, 4096), dtype=np.uint8)
        codec = load_codec({"plugin": "rs_tpu", "k": "8", "m": "3",
                            "backend": "device"})

        async def probe(mode: str) -> tuple:
            conf = cfg.proxy()
            conf.apply({**({"osd_ec_mesh_devices": n,
                            "osd_ec_mesh_width": width,
                            "parallel_repair_mode": mode}
                           if mode != "single" else {})})
            b = ECBatcher(conf=conf)
            parity, crcs = await b.encode_cells(codec, cells)
            every = np.concatenate([cells, parity], axis=1)
            present = (0, 2, 3, 4, 5, 6, 8, 9)  # lost 1, 7, 10
            surv = np.ascontiguousarray(every[:, list(present), :])
            dec = await b.decode_cells(codec, present, (1, 7, 10), surv)
            return parity, crcs, dec

        single = asyncio.run(probe("single"))
        ok = True
        for mode in ("allgather", "psum_bits"):
            got = asyncio.run(probe(mode))
            ok = ok and all((a == b).all() for a, b in zip(single, got))
        return {"parity_ok": ok,
                "parity_stripes": int(cells.shape[0]),
                "parity_modes": ["allgather", "psum_bits"]}

    import jax

    mesh = asyncio.run(run_pipeline(mesh_conf))
    runtime.STATS.reset()
    runtime.reset_meshes()
    single = asyncio.run(run_pipeline(base_conf))
    detail = {
        "n_devices": n,
        "mesh": {"stripe": n // width, "width": width},
        "platform": jax.default_backend(),
        "object_bytes": obj_bytes,
        "concurrency": concurrency,
        "stripe_unit": 65536,
        "multichip": mesh,
        "single_device": single,
        "scaling_vs_1chip": round(
            mesh["write_mib_s"] / single["write_mib_s"], 3)
        if single["write_mib_s"] else 0.0,
        **parity_probe(),
    }
    print(json.dumps(detail))
    print(f"config8 ok: mesh={{'stripe': {n // width}, "
          f"'width': {width}}} write {mesh['write_mib_s']} MiB/s "
          f"(1-chip {single['write_mib_s']}), gathers "
          f"{mesh['write_phase']['mesh_host_gathers']}",
          file=sys.stderr)
    return 0


def config9_recovery_storm(_latency: float) -> dict:
    """Recovery storm (ROADMAP "repair-economics codecs"): kill one
    OSD under the config-6 write load and measure what production EC
    actually lives on — DEGRADED performance — per codec family:
    repair MiB/s (shard bytes rebuilt / time to clean), repair-traffic
    amplification (survivor bytes fetched / bytes rebuilt: k for an
    MDS code, d/q for Clay sub-chunk repair, the local group for
    LRC), and degraded-read p50/p99 while the storm runs. Every
    profile must prove its decodes rode the batched device pipeline
    (ec_decode_batches > 0, ec_batch_isolated recorded) — the first
    numbers this repo has for the path the paper's EC math exists for.

    Runs in a SUBPROCESS like config 8 (the EC engine is forced to
    "device" for every codec, which must not leak into the parent's
    probe state) and keeps the same n_devices/rc/ok/skipped/tail
    payload shape."""
    import subprocess

    cmd = [sys.executable, os.path.abspath(__file__),
           "--recovery-storm-child"]
    out = {"n_devices": 1, "rc": 0, "ok": False, "skipped": False,
           "tail": ""}
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1800)
    except subprocess.TimeoutExpired as e:
        out["rc"] = -1
        out["tail"] = ((e.stderr or b"").decode("utf-8", "replace")
                       if isinstance(e.stderr, bytes)
                       else (e.stderr or ""))[-400:]
        return out
    out["rc"] = proc.returncode
    err_lines = (proc.stderr or "").strip().splitlines()
    out["tail"] = err_lines[-1][-400:] if err_lines else ""
    if proc.returncode != 0:
        out["tail"] = "\n".join(err_lines[-6:])[-800:]
        return out
    try:
        detail = json.loads((proc.stdout or "").strip().splitlines()[-1])
    except (ValueError, IndexError):
        out["tail"] = f"unparseable child stdout: {proc.stdout[-200:]!r}"
        return out
    profs = detail.get("profiles", {})
    # the bar: >= 4 codec profiles measured, each with counter-proven
    # batched decode dispatches (not a host per-stripe fallback) and a
    # recorded repair amplification
    out["ok"] = (len(profs) >= 4 and all(
        p.get("ec_decode_batches", 0) > 0
        and p.get("repair_amplification", 0) > 0
        and p.get("oracle_ok") for p in profs.values()))
    out.update(detail)
    return out


#: config 9 codec matrix: rs k8m3 is the config-6 baseline shape; the
#: others are the repair-economics families (theoretical repair reads
#: per rebuilt chunk: rs k=8, lrc local group 6, clay d/q = 11/4 =
#: 2.75 with the default d=k+m-1, blaum_roth k=5)
STORM_PROFILES = {
    "rs_k8m3": {"plugin": "rs_tpu", "k": "8", "m": "3",
                "backend": "device", "stripe_unit": "65536"},
    "lrc_k8m4_l6": {"plugin": "lrc", "k": "8", "m": "4", "l": "6",
                    "backend": "device", "stripe_unit": "65536"},
    "clay_k8m4": {"plugin": "clay", "k": "8", "m": "4",
                  "backend": "device", "stripe_unit": "65536"},
    "blaum_roth_k5m2": {"plugin": "bitmatrix",
                        "technique": "blaum_roth", "k": "5", "m": "2",
                        "backend": "device", "stripe_unit": "65536"},
}


def _recovery_storm_child() -> int:
    """Config 9's measured body (fresh process). One JSON line on
    stdout: per-profile write MiB/s under storm, degraded-read
    p50/p99, repair MiB/s + amplification, batching counters."""
    os.environ["CEPH_TPU_EC_ENGINE"] = "device"

    import asyncio

    from ceph_tpu.ec import load_codec
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    obj_bytes = 4 << 20
    concurrency = 16
    write_secs = 4.0

    async def storm(name: str, prof: dict) -> dict:
        codec = load_codec(dict(prof))
        size = codec.get_chunk_count()
        c = TestCluster(n_osds=size + 2, out_interval=1.0, osd_conf={
            "osd_ec_batch_window": 0.01,
            "osd_ec_batch_target_stripes": 48,
            "osd_op_concurrency": 32,
        })
        await c.start()
        c.client.op_timeout = 120.0
        c.client.conf.set("client_max_inflight", concurrency)
        await c.client.create_pool(Pool(
            id=2, name="storm", size=size, min_size=codec.k,
            pg_num=16, crush_rule=1, type="erasure",
            ec_profile=dict(prof)))
        await c.wait_active(30)
        payload = np.random.default_rng(5).integers(
            0, 256, obj_bytes, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "warm", payload)  # compile
        # ---- write load with a mid-phase kill (the storm trigger)
        comps: list = []
        seq = 0
        t_end = time.perf_counter() + write_secs
        t0 = time.perf_counter()
        killed = None
        t_kill = None
        while time.perf_counter() < t_end:
            if killed is None and time.perf_counter() - t0 > 1.0:
                pgid = c.client.osdmap.object_to_pg(2, b"warm")
                up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
                killed = next(o for o in up if o != primary)
                t_kill = time.perf_counter()
                await c.kill_osd(killed)
            comps.append((f"b-{seq}",
                          await c.client.aio_write_full(
                              2, f"b-{seq}", payload)))
            seq += 1
        if killed is None:
            # the write phase outran the clock before the mid-phase
            # trigger (slow first-shape compiles): kill now, while the
            # window is still draining — the storm must always fire
            pgid = c.client.osdmap.object_to_pg(2, b"warm")
            up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
            killed = next(o for o in up if o != primary)
            t_kill = time.perf_counter()
            await c.kill_osd(killed)
        await c.client.writes_wait()
        dt_w = time.perf_counter() - t0
        written = []
        for nm, comp in comps:
            comp.result()
            written.append(nm)
        # ---- degraded reads while the storm recovers: per-op
        # latencies for p50/p99 (the dead member's shards decode)
        lat: list = []
        oracle_ok = True
        t0 = time.perf_counter()
        for nm in written:
            t1 = time.perf_counter()
            got = await c.client.read(2, nm)
            lat.append(time.perf_counter() - t1)
            oracle_ok = oracle_ok and got == payload
        dt_r = time.perf_counter() - t0
        # ---- repair: wait for the remap + backfill to finish, then
        # read the ledger (repair MiB/s over the kill-to-clean wall)
        await c.wait_clean(240)
        t_clean = time.perf_counter()
        # ---- straggler-tail A/B (ROADMAP "straggler-proof
        # dispatch"): on the now-clean cluster, arm ONE persistently
        # slow survivor (lognormal service-time inflation, median
        # ~250 ms — the order-of-magnitude degradation the SSD-array
        # study calls production stragglers, and well above both the
        # 50 ms hedge floor and the substituted-decode cost) and read
        # the same objects hedged vs CEPH_TPU_HEDGE=0. Running AFTER
        # the heal keeps the arms symmetric — no background backfill
        # draining between them — so the p999 gap is purely the tail
        # the hedged fan-out exists to cut; the hedge ledger shows
        # what it cost.
        slow = max(i for i, o in enumerate(c.osds)
                   if o is not None and i != killed)
        c.faults.slow_osd([slow], scale=0.25, sigma=0.5)
        ab: dict = {"slow_osd": slow}
        sample = written[:24]
        # one unmeasured hedged pass first: seeds the per-peer EWMAs
        # with the straggler's service time (a daemon has these warm)
        # so the measured arms hedge off a converged estimate; the
        # cold-shape shield keeps substituted-pattern decode compiles
        # off the measured reads either way
        for nm in sample:
            oracle_ok = oracle_ok and \
                await c.client.read(2, nm) == payload
        for arm, env in (("unhedged", "0"), ("hedged", "")):
            if env:
                os.environ["CEPH_TPU_HEDGE"] = env
            else:
                os.environ.pop("CEPH_TPU_HEDGE", None)
            arm_lat: list = []
            for _pass in range(3):  # 3 passes: p99 is not max-of-24
                for nm in sample:
                    t1 = time.perf_counter()
                    got = await c.client.read(2, nm)
                    arm_lat.append((time.perf_counter() - t1) * 1e3)
                    oracle_ok = oracle_ok and got == payload
            arm_lat.sort()

            def apct(p: float) -> float:
                return round(arm_lat[min(len(arm_lat) - 1,
                                         int(p * len(arm_lat)))], 1)

            ab[arm] = {"p50_ms": apct(0.50), "p99_ms": apct(0.99),
                       "p999_ms": apct(0.999)}
        os.environ.pop("CEPH_TPU_HEDGE", None)
        c.faults.slow_osd([])
        tot: dict = {}
        for osd in c.osds:
            if osd is None:
                continue
            for key, val in osd.perf.dump().items():
                if isinstance(val, (int, float)):
                    tot[key] = tot.get(key, 0) + val
        for nm in written[:4]:
            oracle_ok = oracle_ok and \
                await c.client.read(2, nm) == payload
        await c.stop()
        fetched = int(tot.get("ec_repair_bytes_fetched", 0))
        rebuilt = int(tot.get("ec_repair_bytes_rebuilt", 0))
        dt_repair = max(1e-9, t_clean - t_kill)
        lat_ms = sorted(x * 1e3 for x in lat)

        def pct(p: float) -> float:
            return round(lat_ms[min(len(lat_ms) - 1,
                                    int(p * len(lat_ms)))], 1)

        return {
            "profile": dict(prof),
            "size": size,
            "objects": len(written),
            "write_mib_s": round(
                len(written) * obj_bytes / dt_w / 2**20, 1),
            "degraded_read_mib_s": round(
                len(written) * obj_bytes / dt_r / 2**20, 1),
            "degraded_read_p50_ms": pct(0.50),
            "degraded_read_p99_ms": pct(0.99),
            "degraded_read_p999_ms": pct(0.999),
            # the straggler A/B arms + the hedge ledger that paid for
            # them (canceled == fired - won is the leak-free invariant)
            "degraded_tail": ab,
            "ec_hedges_fired": int(tot.get("ec_hedges_fired", 0)),
            "ec_hedges_won": int(tot.get("ec_hedges_won", 0)),
            "ec_hedges_canceled": int(tot.get("ec_hedges_canceled", 0)),
            "ec_hedges_wasted_bytes": int(
                tot.get("ec_hedges_wasted_bytes", 0)),
            "repair_mib_s": round(rebuilt / dt_repair / 2**20, 2),
            "repair_bytes_rebuilt": rebuilt,
            "repair_bytes_fetched": fetched,
            "repair_amplification": round(fetched / rebuilt, 2)
            if rebuilt else 0.0,
            "repair_subchunk_rebuilds": int(
                tot.get("ec_repair_subchunk", 0)),
            "kill_to_clean_s": round(dt_repair, 2),
            "oracle_ok": oracle_ok,
            # batching-efficiency ledger (tracked every round like
            # config 6/8): batched decode dispatches must be > 0 —
            # host per-stripe fallback would leave them at zero
            "ec_batches": int(tot.get("ec_batches", 0)),
            "ec_decode_batches": int(tot.get("ec_decode_batches", 0)),
            "ec_batch_isolated": int(tot.get("ec_batch_isolated", 0)),
            "ec_read_crc_err": int(tot.get("ec_read_crc_err", 0)),
        }

    detail: dict = {"object_bytes": obj_bytes,
                    "concurrency": concurrency,
                    "profiles": {}}
    for name, prof in STORM_PROFILES.items():
        print(f"config9 {name} ...", file=sys.stderr, flush=True)
        detail["profiles"][name] = asyncio.run(storm(name, prof))
        p = detail["profiles"][name]
        tail = p["degraded_tail"]
        print(f"config9 {name}: write {p['write_mib_s']} MiB/s, "
              f"degraded p50/p99/p999 {p['degraded_read_p50_ms']}/"
              f"{p['degraded_read_p99_ms']}/"
              f"{p['degraded_read_p999_ms']} ms, straggler p999 "
              f"hedged {tail['hedged']['p999_ms']} vs unhedged "
              f"{tail['unhedged']['p999_ms']} ms (hedges "
              f"{p['ec_hedges_won']}/{p['ec_hedges_fired']} won), "
              f"repair {p['repair_mib_s']} MiB/s amp "
              f"{p['repair_amplification']}", file=sys.stderr,
              flush=True)
    print(json.dumps(detail))
    return 0


def config10_swarm(_latency: float) -> dict:
    """Million-object multi-tenant swarm (ROADMAP "serving harness",
    tools/swarm.py): >= 2,000 simulated clients share four aio windows
    so ONE process sustains O(10^4) in-flight ops against a live
    cluster — Zipf-skewed popularity over a million-name space, mixed
    op shapes (4 KiB PUT/GET, 4 MiB EC stripes, omap index ops) —
    reporting p50/p99/p999 per shape next to MiB/s, the placement-
    resolver counter block (batched device lookups > 0, cache hit
    rate > 90% under the skew is the bar), and two attribution arms:
    the A/B lever off (CEPH_TPU_PLACEMENT_BATCH=0 equivalent) and a
    short seeded thrash DURING the swarm (the combined scenario)."""
    import asyncio
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ceph_tpu_swarm", os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools", "swarm.py"))
    swarm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(swarm)

    out = asyncio.run(swarm.run_swarm(
        clients=2400, duration=8.0, n_osds=10, window=4096,
        n_rados_clients=4, actor_depth=8, seed=10))
    place = out.get("placement", {})
    out["ok"] = (out.get("clients", 0) >= 2000
                 and out.get("inflight_sustained", 0) >= 10_000
                 and place.get("placement_batch_lookups", 0) > 0
                 and place.get("hit_rate", 0.0) > 0.90
                 and all(s.get("ops", 0) > 0
                         and "p999_ms" in s
                         for s in out.get("shapes", {}).values()))
    # A/B arm: same harness, batched resolver OFF — the attribution
    # pair for the placement win (smaller scale: the lever's cost
    # shows in counters and per-op placement work, not wall clock)
    ab = asyncio.run(swarm.run_swarm(
        clients=600, duration=4.0, n_osds=10, window=1024,
        n_rados_clients=2, actor_depth=6, seed=10,
        placement_batch=False, prewarm=False))
    out["ab_no_batch"] = {
        "ops_s": ab["ops_s"],
        "shapes": {s: {"p50_ms": v["p50_ms"], "p99_ms": v["p99_ms"]}
                   for s, v in ab["shapes"].items()},
        "placement": ab["placement"],
    }
    # combined scenario: a seeded kill/revive schedule DURING the
    # swarm; the verdict requires post-heal convergence
    combined = asyncio.run(swarm.run_swarm(
        clients=600, duration=6.0, n_osds=10, window=1024,
        n_rados_clients=2, actor_depth=6, seed=11, thrash_secs=4.0))
    out["thrash_during_swarm"] = {
        "converged": combined.get("thrash", {}).get("converged"),
        "events": combined.get("thrash", {}).get("events"),
        "ops_s": combined["ops_s"],
        "op_errors": combined["op_errors"],
        "placement_epoch_invalidations": combined["placement"].get(
            "placement_epoch_invalidations", 0),
        "placement_batch_lookups": combined["placement"].get(
            "placement_batch_lookups", 0),
    }
    out["ok"] = bool(out["ok"]
                     and out["thrash_during_swarm"]["converged"])
    return out


def config11_fabric_ab(_latency: float) -> dict:
    """Fabric A/B grid (ISSUE 20 tentpole): config-6 + config-10
    shapes (4 MiB EC stripes, 4 KiB PUT/GET) offered by N reactor
    PROCESSES at N in {1,2,4,8}, against three topologies — ``local``
    (each worker owns a private in-process cluster: the sharding
    upper bound), ``tcp`` (shared ProcCluster of real daemon
    processes over TcpMessenger), ``shm`` (same daemons over the
    shared-memory ring messenger).  Total offered clients stay FIXED
    across N so the sweep measures reactor capacity, not admission.
    Per cell: write MiB/s, GET p99 (merged histograms, never averaged
    percentiles), and cpu-seconds-per-MiB with the daemon and worker
    halves ledgered separately.  ``host_cpus`` is recorded because
    scaling curves only mean something relative to the cores the
    host actually has: on a 1-core container every arm is
    time-sliced, so N>1 measures fabric overhead, not speedup."""
    import asyncio
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "ceph_tpu_swarm", os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "tools", "swarm.py"))
    swarm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(swarm)

    mix = {"put4m": 0.25, "put4k": 0.35, "get4k": 0.40}
    total_clients = 240
    sweep = (1, 2, 4, 8)
    backends = ("local", "tcp", "shm")
    cells: dict = {}
    ok = True
    for backend in backends:
        cells[backend] = {}
        for n in sweep:
            _progress(f"fabric {backend} x{n} ...")
            try:
                r = asyncio.run(swarm.run_fabric(
                    backend=backend, n_workers=n,
                    clients_per_worker=max(1, total_clients // n),
                    duration=2.5, seed=20, n_osds=6, window=512,
                    depth=6, n_objects=50_000, mix=mix))
            except Exception as e:  # a dead cell must not kill the grid
                cells[backend][str(n)] = {"error": repr(e)[:300]}
                ok = False
                continue
            cells[backend][str(n)] = {
                "write_mib_s": r["write_mib_s"],
                "mib_s": r["mib_s"],
                "ops_s": r["ops_s"],
                "get_p99_ms": r["get_p99_ms"],
                "cpu_s_per_mib": r["cpu_s_per_mib"],
                "cpu_s_workers": r["cpu_s_workers"],
                "cpu_s_daemons": r["cpu_s_daemons"],
                "op_errors": r["op_errors"],
                "shapes": {s: {k: v[k] for k in
                               ("ops", "mib_s", "p50_ms", "p99_ms",
                                "p999_ms")}
                           for s, v in r["shapes"].items()},
            }
            ok = ok and r["ops"] > 0 and not r["op_errors"]

    def _scale(backend: str, n: int) -> float | None:
        a = cells[backend].get("1", {}).get("write_mib_s")
        b = cells[backend].get(str(n), {}).get("write_mib_s")
        if not a or b is None:
            return None
        return round(b / a, 2)

    scaling = {b: {f"n{n}_vs_n1": _scale(b, n) for n in (2, 4, 8)}
               for b in backends}
    best = max(
        (c.get("write_mib_s", 0.0)
         for by_n in cells.values() for c in by_n.values()), default=0.0)
    meets_scaling_target = any(
        (s := _scale(b, 4)) is not None and s > 2.0 for b in backends)
    return {
        "ok": ok,
        "host_cpus": os.cpu_count(),
        "mix": mix,
        "total_clients": total_clients,
        "duration_per_cell_s": 2.5,
        "single_reactor_baseline_mib_s": 130.6,  # PR 10, config 6
        "best_write_mib_s": best,
        "meets_scaling_target_n4_gt_2x": bool(meets_scaling_target),
        "scaling": scaling,
        "cells": cells,
    }


def main() -> None:
    _progress("measuring tunnel latency ...")
    latency = measure_latency()
    _progress(f"latency {latency*1e3:.1f} ms; headline (configs 2+3) ...")
    result = headline(latency)
    _progress(f"headline done: {result['value']} GiB/s")
    result["configs"] = {}
    for name, fn in (
        ("1_rs_k2m1_4KiB", config1_small_stripe),
        ("4_crc32c_64KiB_blobs", config4_crc32c),
        ("5_straw2_1K_osds", config5_straw2),
        ("6_rados_bench_ec_k8m3_4MiB", config6_rados_bench),
        ("7_rbd_object_cacher_64KiB_reads", config7_rbd_cache),
        ("8_multichip_ec_k8m3_4MiB", config8_multichip),
        ("9_recovery_storm_per_codec", config9_recovery_storm),
        ("10_swarm_million_object", config10_swarm),
        ("11_fabric_ab", config11_fabric_ab),
    ):
        _progress(f"{name} ...")
        result["configs"][name] = fn(latency)
    # snapshot AFTER every config ran, so later chains' modes (e.g. a
    # conservative fallback in config 1/4) are reported too
    result["timing_modes"] = list(_TIMING_MODES)
    _progress("all configs done")
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--multichip-child":
        sys.exit(_multichip_child(int(sys.argv[2]),
                                  int(sys.argv[3])
                                  if len(sys.argv) > 3 else 1))
    if len(sys.argv) >= 2 and sys.argv[1] == "--recovery-storm-child":
        sys.exit(_recovery_storm_child())
    main()
