#!/usr/bin/env python3
"""Headline benchmark: EC encode + 2-erasure decode, k=8, m=3, 4 MiB stripes.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GiB/s", "vs_baseline": N}

value        — aggregate device throughput in data-GiB/s for one encode
               plus one degraded decode pass over the stripe batch (the
               north-star BASELINE.json configs 2+3 shape).
vs_baseline  — speedup over the same math on the host CPU via the C++
               native core (the reference's jerasure/ISA-L role;
               table-driven GF(2^8), multithreaded across all cores).

Run with no JAX_PLATFORMS override so the real TPU chip is used.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402

from ceph_tpu import native  # noqa: E402
from ceph_tpu.models import datapath  # noqa: E402
from ceph_tpu.ops import rs  # noqa: E402

K, M = 8, 3
CHUNK = 512 * 1024  # 4 MiB stripe / k
BATCH = 24  # 96 MiB data per dispatch
ERASED = (1, 6)  # two lost data shards
PRESENT = tuple([i for i in range(K) if i not in ERASED] + [K, K + 1])
ITERS = 10


def device_pass(data: jax.Array):
    params = datapath.ECParams(k=K, m=M, chunk_bytes=CHUNK)
    enc = datapath.jit_write_step(params)
    dec = datapath.jit_repair_step(params, PRESENT)

    parity, crcs = enc(data)
    surviving = jax.numpy.concatenate(
        [data[:, [i for i in PRESENT if i < K], :], parity[:, : len(ERASED), :]],
        axis=1,
    )
    decoded, _ = dec(surviving)
    jax.block_until_ready((parity, crcs, decoded))

    t0 = time.perf_counter()
    for _ in range(ITERS):
        parity, crcs = enc(data)
        decoded, _ = dec(surviving)
    jax.block_until_ready((parity, crcs, decoded))
    dt = (time.perf_counter() - t0) / ITERS
    return dt, np.asarray(parity), np.asarray(decoded)


def host_pass(data_u8: np.ndarray, threads: int) -> float:
    params = datapath.ECParams(k=K, m=M, chunk_bytes=CHUNK)
    n = data_u8.shape[0]
    flat = data_u8.reshape(n, K * CHUNK)  # stripes are independent on host
    # warm + correctness handled by tests; time one encode+decode pass
    t0 = time.perf_counter()
    for s in range(n):
        chunks = flat[s].reshape(K, CHUNK)
        parity = native.rs_encode(params.matrix, chunks, threads=threads)
        surv = np.concatenate(
            [chunks[[i for i in PRESENT if i < K]], parity[: len(ERASED)]], axis=0
        )
        native.rs_decode(params.matrix, list(PRESENT), surv)
    return time.perf_counter() - t0


def main() -> None:
    rng = np.random.default_rng(42)
    data_u8 = rng.integers(0, 256, (BATCH, K, CHUNK), dtype=np.uint8)
    data = jax.device_put(rs.pack_u32(data_u8))

    dt_dev, parity, decoded = device_pass(data)
    # bit-exactness guard on one stripe before publishing a number
    want = native.rs_encode(
        datapath.ECParams(k=K, m=M, chunk_bytes=CHUNK).matrix, data_u8[0]
    )
    assert (rs.unpack_u32(parity[0]) == want).all(), "device parity mismatch"
    assert (rs.unpack_u32(decoded[0]) == data_u8[0]).all(), "repair mismatch"

    data_bytes = BATCH * K * CHUNK
    gibs_dev = 2 * data_bytes / dt_dev / 2**30  # encode + decode passes

    cpu_batch = min(BATCH, 6)
    threads = os.cpu_count() or 1
    dt_host = host_pass(data_u8[:cpu_batch], threads)
    gibs_host = 2 * cpu_batch * K * CHUNK / dt_host / 2**30

    print(
        json.dumps(
            {
                "metric": "ec_encode_plus_2erasure_decode_k8m3_4MiB_stripes",
                "value": round(gibs_dev, 3),
                "unit": "GiB/s",
                "vs_baseline": round(gibs_dev / gibs_host, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
