"""Distributed tracing (utils/trace): span mechanics, cross-daemon
context propagation through real cluster ops (the blkin pg_trace arc:
client -> primary PG -> EC sub-ops), admin-socket dump, and the
standalone exporter's admin-socket scrape."""
import asyncio
import importlib.util
import os

from ceph_tpu.cluster import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.utils import trace


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


def test_span_basics():
    t = trace.get_tracer("svc-a")
    with t.start_span("root") as root:
        root.tag("k", "v")
        child = t.start_span("child", parent=root)
        child.finish()
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert root.parent_id == 0
    dumped = t.dump(trace_id=root.trace_id)
    names = {d["name"] for d in dumped}
    assert names == {"root", "child"}
    by_name = {d["name"]: d for d in dumped}
    assert by_name["child"]["parentId"] == f"{root.span_id:016x}"
    assert by_name["root"]["tags"] == {"k": "v"}


def test_wire_ctx_round_trip():
    t = trace.get_tracer("svc-b")
    parent = t.start_span("parent")
    # NO_CTX parent starts a fresh trace
    fresh = t.start_span("fresh", parent=trace.NO_CTX)
    assert fresh.parent_id == 0 and fresh.trace_id != parent.trace_id
    # a wire ctx tuple parents correctly
    remote = t.start_span("remote", parent=parent.ctx)
    assert remote.trace_id == parent.trace_id
    assert remote.parent_id == parent.span_id
    parent.finish(), fresh.finish(), remote.finish()


def test_trace_propagates_through_ec_write():
    """One client write to an EC pool must produce client, pg.do_op and
    ec_sub_write spans sharing one trace id, parented as a tree."""
    async def t():
        c = TestCluster(n_osds=5)
        await c.start()
        await c.client.create_pool(
            Pool(id=2, name="ec", size=5, min_size=3, pg_num=4,
                 crush_rule=1, type="erasure",
                 ec_profile={"plugin": "rs_tpu", "k": "3", "m": "2"}))
        await c.wait_active(20)
        await c.client.write_full(2, b"traced-obj", b"z" * 20000)
        got = await c.client.read(2, b"traced-obj")
        assert got == b"z" * 20000
        await c.stop()

    run(t())
    client_spans = [s for s in trace.get_tracer("client.0").dump()
                    if s["name"] == "writefull"
                    and s["tags"].get("oid") == "traced-obj"]
    assert client_spans, "client span missing"
    root = client_spans[-1]
    spans = trace.dump_all()
    tree = [s for s in spans if s["traceId"] == root["traceId"]]
    names = {s["name"] for s in tree}
    assert "pg.do_op writefull" in names
    assert "ec_sub_write" in names
    # parenting: do_op under the client span, sub-writes under do_op
    do_op = next(s for s in tree if s["name"] == "pg.do_op writefull")
    assert do_op["parentId"] == root["id"]
    subs = [s for s in tree if s["name"] == "ec_sub_write"]
    assert subs and all(s["parentId"] == do_op["id"] for s in subs)
    # spans come from more than one daemon (distributed, not local)
    services = {s["localEndpoint"]["serviceName"] for s in tree}
    assert len(services) >= 3


def test_admin_socket_dump_tracing_and_exporter(tmp_path):
    async def t():
        c = TestCluster(n_osds=3)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0))
        await c.wait_active(20)
        await c.client.write_full(1, b"obj", b"x" * 500)
        sock_dir = str(tmp_path / "asok")
        os.makedirs(sock_dir)
        for i, osd in enumerate(c.osds):
            await osd.start_admin(os.path.join(sock_dir, f"osd.{i}.sock"))
        from ceph_tpu.utils.admin import admin_command

        dumps = []
        for i in range(3):
            dumps.extend(await admin_command(
                os.path.join(sock_dir, f"osd.{i}.sock"), "dump_tracing"))
        assert any(s["name"].startswith("pg.do_op") for s in dumps)

        # the standalone exporter scrapes the same sockets
        spec = importlib.util.spec_from_file_location(
            "exporter", os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools", "exporter.py"))
        exporter = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(exporter)
        text = await exporter.scrape(sock_dir)
        assert 'ceph_tpu_daemon_up{ceph_daemon="osd.1"} 1' in text
        assert "ceph_tpu_op" in text  # op counters made it through
        await c.stop()

    run(t())
