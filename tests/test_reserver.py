"""Backfill reservations (AsyncReserver role, VERDICT r3 #7): recovery
concurrency is bounded per OSD while client IO keeps flowing."""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.reserver import AsyncReserver
from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool


def run(coro, timeout=180):
    asyncio.run(asyncio.wait_for(coro, timeout))


def test_reserver_bounds_and_priorities():
    async def t():
        r = AsyncReserver(2)
        order = []

        async def worker(key, prio):
            await r.request(key, prio)
            order.append(key)

        await r.request("a")
        await r.request("b")
        assert r.in_use == 2
        # queued beyond the bound; priority picks the next grant
        t_lo = asyncio.ensure_future(worker("lo", 0))
        t_hi = asyncio.ensure_future(worker("hi", 10))
        await asyncio.sleep(0.01)
        assert r.in_use == 2 and not order
        r.release("a")
        await asyncio.sleep(0.01)
        assert order == ["hi"]
        r.release("b")
        await asyncio.sleep(0.01)
        assert order == ["hi", "lo"]
        # idempotent re-request of a granted key returns immediately
        await r.request("hi")
        # releasing a queued (never granted) key cancels it
        r.release("nope")
        r.set_max(3)
        await r.request("c")
        assert r.in_use == 3
        await asyncio.gather(t_lo, t_hi)

    run(t())


def test_mass_remap_bounded_recovery_with_live_io():
    """Kill + out an OSD so many PGs re-place and recover; the local
    reserver bounds concurrent recoveries to osd_max_backfills while a
    client writer keeps making progress the whole time."""
    async def t():
        c = TestCluster(n_osds=6, out_interval=1.0)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="p", size=3, pg_num=32, crush_rule=0))
        await c.wait_active(30)
        rng = np.random.default_rng(21)
        objs = {}
        for i in range(48):
            name = f"o{i}"
            objs[name] = bytes(
                rng.integers(0, 256, 8000, dtype=np.uint8))
            await c.client.write_full(1, name, objs[name])

        # watch concurrency: sample every reserver each tick
        peak = {"local": 0}
        stop = asyncio.Event()

        async def sampler():
            while not stop.is_set():
                for o in c.osds:
                    if o is not None:
                        peak["local"] = max(peak["local"],
                                            o.local_reserver.in_use)
                await asyncio.sleep(0.002)

        wrote = {"n": 0}

        async def writer():
            i = 0
            while not stop.is_set():
                await c.client.write_full(1, f"live{i}", b"x" * 2000)
                wrote["n"] += 1
                i += 1
                await asyncio.sleep(0.01)

        tasks = [asyncio.ensure_future(sampler()),
                 asyncio.ensure_future(writer())]
        # the remap: kill an OSD and let down->out re-place its PGs
        await c.kill_osd(5)
        await c.wait_down(5, 30)
        await asyncio.sleep(1.5)  # out fires; recoveries run
        await c.wait_active(60)
        stop.set()
        await asyncio.gather(*tasks)

        nbf = c.osds[0].conf["osd_max_backfills"]
        assert peak["local"] <= nbf, (
            f"{peak['local']} concurrent recoveries > bound {nbf}")
        assert wrote["n"] > 0, "client IO starved during recovery"
        for name, data in objs.items():
            assert await c.client.read(1, name) == data
        await c.stop()

    run(t())


def test_remote_slots_bound_inbound_backfills():
    """A revived empty-ish OSD is backfilled by many primaries at once;
    its remote reserver keeps inbound backfills at the bound."""
    async def t():
        c = TestCluster(n_osds=4, out_interval=1.0)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="p", size=3, pg_num=32, crush_rule=0))
        await c.wait_active(30)
        rng = np.random.default_rng(5)
        objs = {f"k{i}": bytes(rng.integers(0, 256, 20_000,
                                            dtype=np.uint8))
                for i in range(40)}
        for n, d in objs.items():
            await c.client.write_full(1, n, d)
        await c.kill_osd(2)
        await c.wait_down(2, 30)
        await asyncio.sleep(1.5)  # out: data re-places without it
        await c.wait_active(60)
        for n, d in objs.items():  # churn so osd.2 is far behind
            await c.client.write_full(1, n, d + b"!")

        peak = {"remote": 0}
        stop = asyncio.Event()

        async def sampler():
            while not stop.is_set():
                o = c.osds[2]
                if o is not None:
                    peak["remote"] = max(peak["remote"],
                                         o.remote_reserver.in_use)
                await asyncio.sleep(0.002)

        samp = asyncio.ensure_future(sampler())
        await c.revive_osd(2)
        await c.wait_active(90)
        stop.set()
        await samp
        nbf = c.osds[2].conf["osd_max_backfills"]
        assert peak["remote"] <= nbf
        for n, d in objs.items():
            assert await c.client.read(1, n) == d + b"!"
        await c.stop()

    run(t())
