"""Straggler-proof dispatch (ISSUE 18): hedged EC fan-outs with loser
cancellation, per-peer EWMA hedge delays, rateless over-decomposition
of batched recovery matmuls, the slow-OSD fault arm, and the seeded
straggler thrash.

The contract under test: hedging changes WHEN bytes arrive, never
WHICH bytes — hedged reads are byte-exact vs unhedged under injected
stragglers, cancelled losers leak neither tasks nor reply
expectations (``ec_hedges_canceled == fired - won`` by construction),
and the over-decomposed device dispatch is bit-identical to the
legacy single dispatch for every (k, m, erasure) draw.
"""
import asyncio
import random
import time

import numpy as np
import pytest

from ceph_tpu.cluster import TestCluster
from ceph_tpu.cluster.ecbatch import ECBatcher
from ceph_tpu.cluster.faults import Thrasher, build_schedule
from ceph_tpu.cluster.hedge import (PeerLatencyEWMA, hedge_enabled,
                                    hedged_fanout)
from ceph_tpu.ec import load_codec
from ceph_tpu.placement.osdmap import Pool

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2",
              "backend": "device"}

HEDGE_KEYS = ("ec_hedges_fired", "ec_hedges_won", "ec_hedges_canceled",
              "ec_hedges_wasted_bytes")


def run(coro, timeout=180):
    asyncio.run(asyncio.wait_for(coro, timeout))


async def make_ec_cluster(n=5, seed=0, pg_num=8, profile=None):
    c = TestCluster(n_osds=n, fault_seed=seed)
    await c.start()
    await c.client.create_pool(
        Pool(id=2, name="ec", size=5, min_size=3, pg_num=pg_num,
             crush_rule=1, type="erasure",
             ec_profile=dict(profile or EC_PROFILE)))
    await c.wait_active(20)
    return c


def hedge_totals(c) -> dict:
    tot = {k: 0 for k in HEDGE_KEYS}
    for o in c.osds:
        if o is None:
            continue
        d = o.perf.dump()
        for k in HEDGE_KEYS:
            tot[k] += int(d.get(k, 0))
    return tot


# --------------------------------------------------- EWMA hedge delay


def test_ewma_adapts_and_defaults():
    e = PeerLatencyEWMA(alpha=0.25)
    assert e.latency(3) == 0.0  # never-seen peer
    e.observe(3, 0.1)
    assert e.latency(3) == pytest.approx(0.1)  # first sample seeds
    e.observe(3, 0.2)
    assert e.latency(3) == pytest.approx(0.125)  # prev + a*(x - prev)
    # adaptation converges toward a shifted latency regime
    for _ in range(40):
        e.observe(3, 0.5)
    assert e.latency(3) == pytest.approx(0.5, rel=0.01)


def test_hedge_delay_clamped_to_backoff_bounds():
    e = PeerLatencyEWMA()  # conf-less: base 0.05, cap 2.0, factor 2.0
    # unknown peers: floor at the backoff base (cheap insurance)
    assert e.hedge_delay([1, 2]) == pytest.approx(0.05)
    e.observe(1, 0.001)
    assert e.hedge_delay([1]) == pytest.approx(0.05)  # fast peer: floor
    e.observe(2, 0.2)
    # two-peer plan: upper median == slower peer, 2 x 0.2
    assert e.hedge_delay([1, 2]) == pytest.approx(0.4)
    e.observe(2, 100.0)
    for _ in range(20):
        e.observe(2, 100.0)
    assert e.hedge_delay([1, 2]) == pytest.approx(2.0)  # cap


def test_one_straggler_cannot_postpone_the_hedge():
    """The delay keys on the MEDIAN planned peer: a single known-slow
    peer in a healthy plan must not inflate the deadline — that is
    the exact plan the hedge exists to cut short."""
    e = PeerLatencyEWMA()
    for p in (1, 2, 3, 4):
        e.observe(p, 0.01)
    e.observe(5, 5.0)  # the straggler the fan-out routes around
    assert e.hedge_delay([1, 2, 3, 4, 5]) == pytest.approx(0.05)


def test_hedge_enabled_env_lever(monkeypatch):
    monkeypatch.delenv("CEPH_TPU_HEDGE", raising=False)
    assert hedge_enabled(None)
    monkeypatch.setenv("CEPH_TPU_HEDGE", "0")
    assert not hedge_enabled(None)


# ------------------------------------------------ hedged_fanout unit


class _Perf:
    def __init__(self):
        self.c = {}

    def inc(self, name, v=1):
        self.c[name] = self.c.get(name, 0) + v


class _FakeOsd:
    def __init__(self, delay=0.01):
        self.conf = None
        self.perf = _Perf()
        self._delay = delay

    def hedge_delay(self, peers):
        return self._delay


def _cand(key, peer, result, delay, log):
    async def _one():
        try:
            await asyncio.sleep(delay)
            log.append(("done", key))
            return result
        except asyncio.CancelledError:
            log.append(("cancelled", key))
            raise
    return (key, peer, _one)


def test_hedged_fanout_first_sufficient_cancels_losers():
    """A straggling primary is routed around: the hedge completes,
    the fan-out resolves on the first sufficient subset, the loser is
    cancelled (its CancelledError cleanup RUNS), and the ledger closes
    with canceled == fired - won."""
    async def t():
        osd = _FakeOsd(delay=0.01)
        log = []
        before = len(asyncio.all_tasks())
        out = await hedged_fanout(
            osd,
            [_cand("a", 1, b"A", 0.0, log),
             _cand("slow", 2, b"S", 5.0, log)],
            [_cand("h", 3, b"H", 0.0, log)],
            sufficient=lambda o: len(o) >= 2,
            nbytes=len)
        assert out == {"a": b"A", "h": b"H"}  # loser ABSENT
        assert ("cancelled", "slow") in log
        assert osd.perf.c["ec_hedges_fired"] == 1
        assert osd.perf.c["ec_hedges_won"] == 1
        assert osd.perf.c.get("ec_hedges_canceled", 0) == 0
        # task census returns to baseline: losers were awaited dead
        assert len(asyncio.all_tasks()) == before
    run(t(), timeout=30)


def test_hedged_fanout_cancels_unfinished_hedges():
    """Primaries resolving after the hedge wave fired but before the
    hedges complete: every fired hedge is cancelled and the invariant
    canceled == fired - won holds."""
    async def t():
        osd = _FakeOsd(delay=0.01)
        log = []
        out = await hedged_fanout(
            osd,
            [_cand("a", 1, b"A", 0.05, log)],
            [_cand("h1", 2, b"H", 5.0, log),
             _cand("h2", 3, b"H", 5.0, log)],
            sufficient=lambda o: "a" in o)
        assert out == {"a": b"A"}
        assert osd.perf.c["ec_hedges_fired"] == 2
        assert osd.perf.c.get("ec_hedges_won", 0) == 0
        assert osd.perf.c["ec_hedges_canceled"] == 2
        assert ("cancelled", "h1") in log and ("cancelled", "h2") in log
    run(t(), timeout=30)


def test_hedged_fanout_env_off_is_plan_exact(monkeypatch):
    """CEPH_TPU_HEDGE=0 (the A/B lever): extras never launch, no
    hedge counters move — the legacy plan-exact fan-out."""
    monkeypatch.setenv("CEPH_TPU_HEDGE", "0")

    async def t():
        osd = _FakeOsd(delay=0.0)
        log = []
        out = await hedged_fanout(
            osd,
            [_cand("a", 1, b"A", 0.02, log)],
            [_cand("h", 2, b"H", 0.0, log)],
            sufficient=lambda o: "a" in o)
        assert out == {"a": b"A"}
        assert osd.perf.c == {}
        assert not any(k == "h" for _e, k in log)
    run(t(), timeout=30)


def test_hedged_fanout_records_exceptions_as_outcomes():
    """A raising factory records the exception AS the outcome —
    callers keep their own transient-vs-failed triage."""
    async def t():
        osd = _FakeOsd()

        async def boom():
            raise IOError("transport")

        out = await hedged_fanout(
            osd, [("x", 1, boom)], [],
            sufficient=lambda o: len(o) >= 1)
        assert isinstance(out["x"], IOError)
    run(t(), timeout=30)


# ------------------------------- hedged read vs stragglers (cluster)


def test_hedged_read_byte_exact_and_leak_free(monkeypatch):
    """Under a persistently slow OSD, hedged EC reads return the exact
    written bytes, route around the straggler (hedges fire AND win),
    cancel losers without leaking reply expectations, and the unhedged
    A/B arm (CEPH_TPU_HEDGE=0) reads the same bytes the slow way."""
    monkeypatch.delenv("CEPH_TPU_HEDGE", raising=False)

    async def t():
        c = await make_ec_cluster(seed=7)
        try:
            rng = random.Random(99)
            payloads = {f"hedge-{i}": rng.randbytes(16 << 10)
                        for i in range(6)}
            for name, data in payloads.items():
                await c.client.write_full(2, name, data)
            # one persistently slow daemon: lognormal service-time
            # inflation on its shard-serving path, median well above
            # the 50 ms hedge-delay floor
            c.faults.slow_osd([1], scale=0.3, sigma=0.2)
            for name, data in payloads.items():
                got = await c.client.read(2, name)
                assert got == data, f"hedged read tore {name}"
            tot = hedge_totals(c)
            assert tot["ec_hedges_fired"] > 0
            assert tot["ec_hedges_won"] > 0
            assert tot["ec_hedges_canceled"] == \
                tot["ec_hedges_fired"] - tot["ec_hedges_won"]
            # leak-free: every reply expectation drained (cancelled
            # losers ran their drop_reply cleanup); straggler replies
            # to dropped subtids are no-ops
            deadline = asyncio.get_running_loop().time() + 15.0
            while asyncio.get_running_loop().time() < deadline:
                if all(not o.pending for o in c.osds if o is not None):
                    break
                await asyncio.sleep(0.1)
            assert all(not o.pending for o in c.osds if o is not None)
            # A/B arm: unhedged reads the same bytes, just without
            # firing hedges
            monkeypatch.setenv("CEPH_TPU_HEDGE", "0")
            fired0 = hedge_totals(c)["ec_hedges_fired"]
            for name, data in payloads.items():
                assert await c.client.read(2, name) == data
            assert hedge_totals(c)["ec_hedges_fired"] == fired0
        finally:
            monkeypatch.delenv("CEPH_TPU_HEDGE", raising=False)
            await c.stop()
    run(t(), timeout=240)


# --------------------------- device tier: rateless over-decomposition


def _conf(**kw):
    # plain dict: absent knobs raise KeyError and the batcher falls
    # back to its defaults (window 0, mesh off, repair off)
    return dict(kw)


def _su_for(codec, base=1024):
    """A stripe_unit that is a fixed point of get_chunk_size — what
    osd.sinfo_for would compute for the pool."""
    su = base
    for _ in range(8):
        got = codec.get_chunk_size(codec.k * su)
        if got == su:
            return su
        su = got
    raise AssertionError("stripe unit did not stabilize")


class _BatchPerf:
    def __init__(self):
        self.c = {}

    def add_u64_counter(self, name, *a, **k):
        self.c[name] = 0

    def add_histogram(self, *a, **k):
        pass

    def inc(self, name, v=1):
        self.c[name] = self.c.get(name, 0) + v

    def observe(self, *a, **k):
        pass


def test_overdecompose_decode_parity_random_draws():
    """First-sufficient over-decomposed decode is bit-identical to the
    legacy full-round dispatch across random (k, m, erasure) draws on
    the host engine, and the sub-task ledger balances: every block
    resolves once, its hedge duplicate is shed."""
    async def t():
        rng = np.random.default_rng(20260806)
        for trial in range(5):
            k = int(rng.integers(2, 6))
            m = int(rng.integers(1, 4))
            codec = load_codec({"plugin": "rs_tpu", "k": str(k),
                                "m": str(m), "backend": "host"})
            su = _su_for(codec)
            b = int(rng.integers(9, 48))
            cells = rng.integers(0, 256, (b, k, su), dtype=np.uint8)
            legacy = ECBatcher(perf=None, conf=_conf())
            parity, _ = await legacy.encode_cells(codec, cells)
            every = np.concatenate([cells, parity], axis=1)
            # erase a random data row (plus up to m-1 others), decode
            # the erased data from exactly k survivors
            lost = int(rng.integers(0, k))
            others = [x for x in range(k + m) if x != lost]
            present = tuple(sorted(
                rng.choice(others, size=k, replace=False).tolist()))
            want = tuple(j for j in range(k) if j not in present)
            surv = np.ascontiguousarray(every[:, list(present), :])
            base = await legacy.decode_cells(codec, present, want, surv)
            perf = _BatchPerf()
            ECBatcher.declare_counters(perf)
            od = ECBatcher(perf=perf,
                           conf=_conf(osd_ec_overdecompose=3))
            got = await od.decode_cells(codec, present, want, surv)
            np.testing.assert_array_equal(
                base, got, err_msg=f"trial {trial} k={k} m={m} "
                                   f"present={present}")
            for i, j in enumerate(want):
                np.testing.assert_array_equal(got[:, i, :],
                                              cells[:, j, :])
            d = perf.c
            assert d["ec_overdecompose_rounds"] >= 1
            # ledger: used-once-per-block + shed == submitted copies
            assert d["ec_overdecompose_subtasks"] == \
                2 * d["ec_overdecompose_shed"]
    run(t(), timeout=120)


def test_overdecompose_repair_parity_clay():
    """The sub-chunk repair kind rides the same over-decomposed
    dispatch: bandwidth-optimal Clay repair through row blocks is
    byte-identical to the single dispatch."""
    async def t():
        codec = load_codec({"plugin": "clay", "k": "3", "m": "2",
                            "backend": "host"})
        su = _su_for(codec)
        rng = np.random.default_rng(11)
        cells = rng.integers(0, 256, (13, codec.k, su), dtype=np.uint8)
        parity = np.stack([codec.encode_chunks(c) for c in cells])
        every = np.concatenate([cells, parity], axis=1)
        lost = 0
        avail = sorted(set(range(5)) - {lost})
        assert codec.is_repair({lost}, set(avail))
        legacy = ECBatcher(perf=None, conf=_conf())
        plan = codec.minimum_to_decode([lost], avail)
        sub = su // codec.get_sub_chunk_count()
        order = sorted(plan)
        runs = plan[order[0]]
        surv = np.stack([
            np.concatenate([every[:, ch, o * sub:(o + cnt) * sub]
                            for o, cnt in runs], axis=1)
            for ch in order], axis=1)
        base = await legacy.repair_cells(codec, tuple(order), (lost,),
                                         surv)
        od = ECBatcher(perf=None, conf=_conf(osd_ec_overdecompose=2))
        got = await od.repair_cells(codec, tuple(order), (lost,), surv)
        np.testing.assert_array_equal(base, got)
        np.testing.assert_array_equal(got[:, 0, :], every[:, lost, :])
    run(t(), timeout=120)


class _EngineProbe:
    """Minimal device-engine codec recording which engine each decode
    round ran on — host hook vs device batch."""
    profile = {"plugin": "probe"}
    technique = ""
    k, m = 2, 1
    backend = "device"
    bytewise_linear = False

    def __init__(self):
        self.calls = []

    def resolved_backend(self):
        return "device"

    def decode_cells_host(self, present, want, blk):
        self.calls.append("host")
        return np.ascontiguousarray(blk[:, :len(want), :])

    def decode_batch(self, present, surviving, want=None):
        from ceph_tpu.ops import rs
        self.calls.append("device")
        cells = rs.unpack_u32(np.asarray(surviving))
        return rs.pack_u32(np.ascontiguousarray(
            cells[:, :len(want), :]))


def test_cold_shape_shield_promotes_after_volume():
    """A decode survivor pattern stays on the host engine until its
    cumulative bytes cross osd_ec_cold_shape_bytes; the promotion
    pre-warms the device kernel on a background thread (rounds keep
    landing host meanwhile — the compile never sits on a waiting
    read), and only then does the pattern take the device path. Each
    pattern keeps its own ledger, and 0 disables the shield
    outright."""
    perf = _BatchPerf()
    ECBatcher.declare_counters(perf)
    b = ECBatcher(perf=perf, conf=_conf(osd_ec_cold_shape_bytes=100))
    codec = _EngineProbe()
    cells = np.arange(4 * 2 * 8, dtype=np.uint8).reshape(4, 2, 8)
    key = ("dec", ("probe", "", 2, 1, "device"), 8, (0, 1), (2,))
    for _ in range(2):  # 64 B/round: cold at 0 and at 64 cumulative
        out = b._decode_sync(codec, (0, 1), (2,), cells)
        np.testing.assert_array_equal(out, cells[:, :1, :])
    assert codec.calls == ["host", "host"]
    assert perf.c["ec_decode_cold_host"] == 2
    # crossing the threshold: THIS round still lands host while the
    # background warm runs the device dispatch once off the read path
    out = b._decode_sync(codec, (0, 1), (2,), cells)  # 128 >= 100
    np.testing.assert_array_equal(out, cells[:, :1, :])
    # the counter proves the round itself landed host (the warm
    # thread's device call interleaves into `calls` at its own pace)
    assert perf.c["ec_decode_cold_host"] == 3
    assert codec.calls.count("host") == 3
    for _ in range(200):  # the warm thread flips the promotion flag
        if b._shape_warm.get(key) is True:
            break
        time.sleep(0.01)
    assert b._shape_warm[key] is True
    assert codec.calls.count("device") == 1  # the warm dispatch itself
    out = b._decode_sync(codec, (0, 1), (2,), cells)  # promoted
    np.testing.assert_array_equal(out, cells[:, :1, :])
    assert codec.calls.count("device") == 2
    assert perf.c["ec_decode_cold_host"] == 3
    # a different survivor pattern is its own ledger: cold again
    b._decode_sync(codec, (0, 2), (1,), cells)
    assert codec.calls[-1] == "host"
    # threshold 0 = shield off: straight to the device engine
    off = ECBatcher(perf=None, conf=_conf(osd_ec_cold_shape_bytes=0))
    fresh = _EngineProbe()
    off._decode_sync(fresh, (0, 1), (2,), cells)
    assert fresh.calls == ["device"]


# --------------------------------------------- lint fixtures (+ / -)


def lint(src: str, path: str, only=None):
    import textwrap

    from ceph_tpu import analysis

    return analysis.lint_source(textwrap.dedent(src), path, only)


def test_hedge_fanout_rule_flags_gather_over_reply_waits():
    bad = """
    import asyncio

    async def read_shards(osd, waits):
        return await asyncio.gather(
            *(osd.await_reply(t, f, o) for t, f, o in waits))
    """
    fs = lint(bad, "ceph_tpu/cluster/pg.py",
              only=["hedge-fanout-discipline"])
    assert len(fs) == 1 and "hedged_fanout" in fs[0].message

    bad2 = """
    import asyncio

    async def reconstruct(self, need):
        return await asyncio.gather(
            *(self._fetch_shard_copy(oid, j) for j in need))
    """
    assert lint(bad2, "ceph_tpu/cluster/pg.py",
                only=["hedge-fanout-discipline"])


def test_hedge_fanout_rule_negative_fixtures():
    # all-ack write fan-outs and send bursts legitimately gather
    ok = """
    import asyncio

    async def ship_all(sends):
        await asyncio.gather(*sends)

    async def probe_all(probes):
        return await asyncio.gather(*(p() for p in probes))
    """
    assert lint(ok, "ceph_tpu/cluster/pg.py",
                only=["hedge-fanout-discipline"]) == []
    # out of scope: non-cluster tiers
    bad_elsewhere = """
    import asyncio

    async def f(osd, waits):
        return await asyncio.gather(
            *(osd.await_reply(t, f, o) for t, f, o in waits))
    """
    assert lint(bad_elsewhere, "ceph_tpu/rgw/gateway.py",
                only=["hedge-fanout-discipline"]) == []


def test_hedge_task_rule_flags_orphaned_hedge_tasks():
    bad = """
    import asyncio

    def fire(loop, factory):
        loop.create_task(run_hedge(factory))
    """
    fs = lint(bad, "ceph_tpu/cluster/pg.py",
              only=["hedge-task-discipline"])
    assert len(fs) == 1 and "orphaned hedge task" in fs[0].message

    ok = """
    import asyncio

    def fire(loop, factory, tasks):
        t = loop.create_task(run_hedge(factory))
        tasks.add(t)
        loop.create_task(flush_log())
    """
    assert lint(ok, "ceph_tpu/cluster/pg.py",
                only=["hedge-task-discipline"]) == []


# ------------------------------------------- seeded straggler thrash


def test_straggler_thrash_converges_with_hedges(monkeypatch):
    """Tier-1 straggler thrash: a ~5 s seeded schedule with up to two
    persistently slow OSDs under concurrent oracle writers converges
    byte-exact, the verdict's hedge ledger proves hedges fired AND won
    while the leak-free invariant holds, and the schedule replays
    draw-for-draw (legacy availability draws untouched)."""
    monkeypatch.delenv("CEPH_TPU_HEDGE", raising=False)

    async def t():
        c = await make_ec_cluster(seed=4321)
        c.client.op_timeout = 150.0
        # straggle_scale: median inflation 150 ms — far above the
        # 50 ms hedge floor (hedges fire AND win) yet far below the
        # sub-op timeout, so a cold-cache/loaded run cannot tip slow
        # shards into spurious unreadability mid-recovery
        thr = Thrasher(c, 2, seed=4321, duration=5.0, max_unavail=2,
                       bitrot_p=0.0, partitions=False, n_objects=6,
                       obj_size=16 << 10, writers=3,
                       settle_timeout=120.0, stragglers=2,
                       straggle_scale=0.15, straggle_sigma=0.2)
        assert thr.schedule == build_schedule(
            4321, 5.0, 5, max_unavail=2, partitions=False,
            stragglers=2)
        # the straggler stream must not shift the availability draws
        legacy = build_schedule(4321, 5.0, 5, max_unavail=2,
                                partitions=False)
        assert [e for e in thr.schedule
                if e.kind not in ("straggle", "unstraggle")] == legacy
        assert any(e.kind == "straggle" for e in thr.schedule)
        verdict = await thr.run()
        assert verdict["passed"], verdict
        assert verdict["converged"]
        assert verdict["oracle_mismatches"] == []
        assert verdict["stragglers"]["applied"] > 0
        hedge = verdict["hedge_counters"]
        assert hedge["ec_hedges_fired"] > 0
        assert hedge["ec_hedges_won"] > 0, hedge
        assert hedge["ec_hedges_canceled"] == \
            hedge["ec_hedges_fired"] - hedge["ec_hedges_won"]
        # post-thrash task/reply census back at baseline
        for _ in range(40):
            if all(not o.pending for o in c.osds if o is not None):
                break
            await asyncio.sleep(0.1)
        assert all(not o.pending for o in c.osds if o is not None)
        await c.stop()
    run(t(), timeout=300)
