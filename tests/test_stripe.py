"""Stripe arithmetic + overlay semantics (ECUtil.h:27 stripe_info_t role)."""
import numpy as np
import pytest

from ceph_tpu.cluster import stripe as st
from ceph_tpu.ec.registry import load_codec


def test_stripe_spans_and_sizes():
    si = st.StripeInfo(k=3, m=2, stripe_unit=4096)
    assert si.width == 12288
    assert si.nstripes(0) == 0
    assert si.nstripes(1) == 1
    assert si.nstripes(12288) == 1
    assert si.nstripes(12289) == 2
    assert si.shard_size(12289) == 8192
    assert si.stripe_span(0, 1) == (0, 1)
    assert si.stripe_span(12287, 2) == (0, 2)
    assert si.stripe_span(12288, 1) == (1, 2)
    assert si.stripe_span(0, 0) == (0, 0)


def test_effective_stripe_unit_rs():
    codec = load_codec({"plugin": "rs_tpu", "k": "3", "m": "2"})
    assert st.effective_stripe_unit(codec, 4096) == 4096
    # odd request rounds up to codec alignment
    su = st.effective_stripe_unit(codec, 1000)
    assert su >= 1000 and codec.get_chunk_size(codec.k * su) == su


def test_cells_roundtrip():
    si = st.StripeInfo(k=2, m=1, stripe_unit=8)
    data = np.arange(40, dtype=np.uint8)  # 2.5 stripes
    cells = si.to_cells(data, 0, 3)
    assert cells.shape == (3, 2, 8)
    flat = si.from_cells(cells)
    assert bytes(flat[:40]) == bytes(data)
    assert not flat[40:].any()  # zero padding


def _shadow(ops, old=b""):
    """Reference model: apply the same ops to a plain bytearray."""
    data = bytearray(old)
    for op, *args in ops:
        if op == "write":
            off, payload = args
            end = off + len(payload)
            if len(data) < end:
                data.extend(b"\0" * (end - len(data)))
            data[off:end] = payload
        elif op == "zero":
            off, ln = args
            end = off + ln
            if len(data) < end:
                data.extend(b"\0" * (end - len(data)))
            data[off:end] = b"\0" * ln
        elif op == "truncate":
            (size,) = args
            if size < len(data):
                del data[size:]
            else:
                data.extend(b"\0" * (size - len(data)))
    return data


@pytest.mark.parametrize("seed", range(8))
def test_overlay_matches_shadow_model(seed):
    rng = np.random.default_rng(seed)
    old = bytes(rng.integers(0, 256, 3000, dtype=np.uint8))
    ops = []
    for _ in range(12):
        kind = rng.choice(["write", "zero", "truncate"])
        if kind == "write":
            off = int(rng.integers(0, 4000))
            ln = int(rng.integers(1, 600))
            ops.append(("write", off,
                        bytes(rng.integers(0, 256, ln, dtype=np.uint8))))
        elif kind == "zero":
            ops.append(("zero", int(rng.integers(0, 4000)),
                        int(rng.integers(1, 600))))
        else:
            ops.append(("truncate", int(rng.integers(0, 4500))))
    ov = st.Overlay(len(old))
    for op, *args in ops:
        getattr(ov, op)(*args)
    assert bytes(ov.apply(old)) == bytes(_shadow(ops, old))
    assert ov.size == len(_shadow(ops, old))


def test_overlay_covers_and_slice():
    ov = st.Overlay(100)
    ov.write(10, b"a" * 20)
    ov.write(30, b"b" * 10)
    assert ov.covers(10, 30)
    assert ov.covers(15, 20)
    assert not ov.covers(5, 10)
    assert not ov.covers(35, 10)
    assert ov.slice(25, 10) == b"a" * 5 + b"b" * 5
    ov.zero(40, 5)
    assert ov.slice(38, 5) == b"bb\0\0\0"


def test_overlay_truncate_drops_extents():
    ov = st.Overlay(50)
    ov.write(10, b"x" * 30)  # [10, 40)
    ov.truncate(20)
    assert ov.size == 20
    assert ov.written_ranges() == [(10, 10)]
    assert ov.truncated
    ov.truncate(60)  # extend: explicit zero extent
    assert ov.written_ranges() == [(10, 10), (20, 40)]
    assert bytes(ov.apply(b"o" * 50)) == (
        b"o" * 10 + b"x" * 10 + b"\0" * 40
    )


def test_overlay_empty():
    ov = st.Overlay(77)
    assert ov.empty
    ov.write(0, b"z")
    assert not ov.empty


def test_hinfo_roundtrip_and_zero_cell():
    crcs = np.array([1, 2, 0xDEADBEEF], dtype=np.uint32)
    assert (st.dec_hinfo(st.enc_hinfo(crcs)) == crcs).all()
    su = 512
    assert st.zero_cell_crc(su) == st.StripeInfo(1, 0, su).crc_of_cell(
        np.zeros(su, dtype=np.uint8)
    )


# ------------------------------------------------- vectorized scatter


def _reference_cells(ov, tlist, si, old_parts):
    """The legacy per-stripe apply_range materialization — the oracle
    the one-shot scatter must match byte-for-byte."""
    k, su, width = si.k, si.su, si.width
    ref = np.zeros((k, len(tlist), su), dtype=np.uint8)
    for i, s in enumerate(tlist):
        start = s * width
        end = min(start + width, ov.size)
        buf = ov.apply_range(start, end, old_parts.get(s, b""))
        arr = np.frombuffer(buf, dtype=np.uint8)
        pad = np.zeros(width, np.uint8)
        pad[: len(arr)] = arr
        ref[:, i, :] = pad.reshape(k, su)
    return ref


@pytest.mark.parametrize("seed", range(8))
def test_overlay_scatter_matches_apply_range(seed):
    """Property: Overlay.scatter (one strided materialization per op)
    is byte-identical to the per-stripe apply_range round-trip across
    random write/zero/truncate mixes, misaligned extents, shrinking
    rewrites, and partially-covered stripes."""
    import random

    rng = random.Random(20260803 + seed)
    for _trial in range(60):
        k = rng.choice([2, 3, 8])
        su = rng.choice([4, 16, 64])
        si = st.StripeInfo(k, rng.choice([1, 2]), su)
        width = si.width
        old_size = rng.randrange(0, 6 * width)
        old = bytes(rng.randrange(1, 256) for _ in range(old_size))
        ov = st.Overlay(old_size)
        for _ in range(rng.randrange(0, 6)):
            op = rng.choice(["write", "zero", "truncate"])
            off = rng.randrange(0, 8 * width)
            ln = rng.randrange(1, 3 * width)
            if op == "write":
                ov.write(off, bytes(rng.randrange(1, 256)
                                    for _ in range(ln)))
            elif op == "zero":
                ov.zero(off, ln)
            else:
                ov.truncate(rng.randrange(0, 8 * width))
        new_size = ov.size
        new_nst = si.nstripes(new_size)
        touched = set()
        for off, ln in ov.written_ranges():
            s0, s1 = si.stripe_span(off, ln)
            touched.update(range(s0, min(s1, new_nst)))
        if new_size < old_size and new_size % width and new_nst:
            touched.add(new_nst - 1)
        need_old = sorted(
            s for s in touched
            if s * width < old_size and not ov.covers(
                s * width, min((s + 1) * width, new_size) - s * width))
        runs, rs = [], None
        for s in need_old:
            if rs is None:
                rs, prev = s, s
            elif s == prev + 1:
                prev = s
            else:
                runs.append((rs, prev + 1))
                rs, prev = s, s
        if rs is not None:
            runs.append((rs, prev + 1))
        old_runs, old_parts = [], {}
        for a, b in runs:
            start, end = a * width, min(b * width, old_size)
            data = old[start:end]
            old_runs.append((a, data))
            for s in range(a, b):
                lo = s * width - start
                old_parts[s] = data[lo: lo + width]
        tlist = sorted(touched)
        dst = np.zeros((k, len(tlist), su), dtype=np.uint8)
        n_ext, n_cols = ov.scatter(dst, tlist, si, old_runs)
        assert n_cols == len(tlist)
        ref = _reference_cells(ov, tlist, si, old_parts)
        np.testing.assert_array_equal(dst, ref)


def test_overlay_scatter_writefull_is_one_strided_assign_shape():
    """The aligned fast path: a whole-object write covers every cell
    with one reshape/transpose assign (no fancy indexing) and reports
    exactly one extent."""
    si = st.StripeInfo(4, 2, 64)
    data = bytes(range(256)) * 4  # 4 stripes of 256B width
    ov = st.Overlay(0)
    ov.write(0, data)
    tlist = [0, 1, 2, 3]
    dst = np.zeros((4, 4, 64), dtype=np.uint8)
    n_ext, n_cols = ov.scatter(dst, tlist, si, [])
    assert (n_ext, n_cols) == (1, 4)
    want = np.frombuffer(data, dtype=np.uint8).reshape(4, 4, 64)
    np.testing.assert_array_equal(dst, want.transpose(1, 0, 2))
