"""Stripe arithmetic + overlay semantics (ECUtil.h:27 stripe_info_t role)."""
import numpy as np
import pytest

from ceph_tpu.cluster import stripe as st
from ceph_tpu.ec.registry import load_codec


def test_stripe_spans_and_sizes():
    si = st.StripeInfo(k=3, m=2, stripe_unit=4096)
    assert si.width == 12288
    assert si.nstripes(0) == 0
    assert si.nstripes(1) == 1
    assert si.nstripes(12288) == 1
    assert si.nstripes(12289) == 2
    assert si.shard_size(12289) == 8192
    assert si.stripe_span(0, 1) == (0, 1)
    assert si.stripe_span(12287, 2) == (0, 2)
    assert si.stripe_span(12288, 1) == (1, 2)
    assert si.stripe_span(0, 0) == (0, 0)


def test_effective_stripe_unit_rs():
    codec = load_codec({"plugin": "rs_tpu", "k": "3", "m": "2"})
    assert st.effective_stripe_unit(codec, 4096) == 4096
    # odd request rounds up to codec alignment
    su = st.effective_stripe_unit(codec, 1000)
    assert su >= 1000 and codec.get_chunk_size(codec.k * su) == su


def test_cells_roundtrip():
    si = st.StripeInfo(k=2, m=1, stripe_unit=8)
    data = np.arange(40, dtype=np.uint8)  # 2.5 stripes
    cells = si.to_cells(data, 0, 3)
    assert cells.shape == (3, 2, 8)
    flat = si.from_cells(cells)
    assert bytes(flat[:40]) == bytes(data)
    assert not flat[40:].any()  # zero padding


def _shadow(ops, old=b""):
    """Reference model: apply the same ops to a plain bytearray."""
    data = bytearray(old)
    for op, *args in ops:
        if op == "write":
            off, payload = args
            end = off + len(payload)
            if len(data) < end:
                data.extend(b"\0" * (end - len(data)))
            data[off:end] = payload
        elif op == "zero":
            off, ln = args
            end = off + ln
            if len(data) < end:
                data.extend(b"\0" * (end - len(data)))
            data[off:end] = b"\0" * ln
        elif op == "truncate":
            (size,) = args
            if size < len(data):
                del data[size:]
            else:
                data.extend(b"\0" * (size - len(data)))
    return data


@pytest.mark.parametrize("seed", range(8))
def test_overlay_matches_shadow_model(seed):
    rng = np.random.default_rng(seed)
    old = bytes(rng.integers(0, 256, 3000, dtype=np.uint8))
    ops = []
    for _ in range(12):
        kind = rng.choice(["write", "zero", "truncate"])
        if kind == "write":
            off = int(rng.integers(0, 4000))
            ln = int(rng.integers(1, 600))
            ops.append(("write", off,
                        bytes(rng.integers(0, 256, ln, dtype=np.uint8))))
        elif kind == "zero":
            ops.append(("zero", int(rng.integers(0, 4000)),
                        int(rng.integers(1, 600))))
        else:
            ops.append(("truncate", int(rng.integers(0, 4500))))
    ov = st.Overlay(len(old))
    for op, *args in ops:
        getattr(ov, op)(*args)
    assert bytes(ov.apply(old)) == bytes(_shadow(ops, old))
    assert ov.size == len(_shadow(ops, old))


def test_overlay_covers_and_slice():
    ov = st.Overlay(100)
    ov.write(10, b"a" * 20)
    ov.write(30, b"b" * 10)
    assert ov.covers(10, 30)
    assert ov.covers(15, 20)
    assert not ov.covers(5, 10)
    assert not ov.covers(35, 10)
    assert ov.slice(25, 10) == b"a" * 5 + b"b" * 5
    ov.zero(40, 5)
    assert ov.slice(38, 5) == b"bb\0\0\0"


def test_overlay_truncate_drops_extents():
    ov = st.Overlay(50)
    ov.write(10, b"x" * 30)  # [10, 40)
    ov.truncate(20)
    assert ov.size == 20
    assert ov.written_ranges() == [(10, 10)]
    assert ov.truncated
    ov.truncate(60)  # extend: explicit zero extent
    assert ov.written_ranges() == [(10, 10), (20, 40)]
    assert bytes(ov.apply(b"o" * 50)) == (
        b"o" * 10 + b"x" * 10 + b"\0" * 40
    )


def test_overlay_empty():
    ov = st.Overlay(77)
    assert ov.empty
    ov.write(0, b"z")
    assert not ov.empty


def test_hinfo_roundtrip_and_zero_cell():
    crcs = np.array([1, 2, 0xDEADBEEF], dtype=np.uint32)
    assert (st.dec_hinfo(st.enc_hinfo(crcs)) == crcs).all()
    su = 512
    assert st.zero_cell_crc(su) == st.StripeInfo(1, 0, su).crc_of_cell(
        np.zeros(su, dtype=np.uint8)
    )
