"""Multi-chip data plane: the serving-path mesh (parallel/runtime.py +
ECBatcher mesh mode).

Unit tier pins the acceptance contract directly: mesh-sharded fused
encode+CRC and collective repair are BYTE-IDENTICAL to the
single-device dispatch over random stripes, results cross to the host
only as per-device shard views (host_gathers stays 0), occupancy lands
evenly across chips, and a platform that cannot supply the mesh
degrades gracefully to the 1-device path. Cluster tier proves OSD
traffic actually crosses the mesh: a live TestCluster with the mesh
knobs on serves writes through sharded dispatches and a degraded read
through the collective repair path. Everything runs on the 8-device
virtual CPU platform conftest pins.
"""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.ecbatch import ECBatcher
from ceph_tpu.ec import load_codec
from ceph_tpu.parallel import runtime
from ceph_tpu.utils import config as cfg

DEV_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2",
               "backend": "device"}


def run(coro, timeout=180):
    asyncio.run(asyncio.wait_for(coro, timeout))


def mesh_conf(n=8, width=2, repair="allgather") -> cfg.ConfigProxy:
    conf = cfg.proxy()
    conf.apply({"osd_ec_mesh_devices": n, "osd_ec_mesh_width": width,
                "parallel_repair_mode": repair})
    return conf


def rand_cells(b, k=3, su=256, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (b, k, su), dtype=np.uint8)


# ------------------------------------------------------------ unit tier


@pytest.mark.parametrize("width", [1, 2, 4])
def test_mesh_encode_byte_identical_and_gather_free(width):
    """mesh={stripe, width} fused encode+CRC == the single-device
    dispatch, bit for bit, at every width factoring — and the write
    path never gathers the sharded result through one host buffer."""
    codec = load_codec(dict(DEV_PROFILE))
    cells = rand_cells(11, seed=1)
    runtime.STATS.reset()

    async def t():
        meshed = ECBatcher(conf=mesh_conf(width=width))
        single = ECBatcher()
        pm, cm = await meshed.encode_cells(codec, cells)
        ps, cs = await single.encode_cells(codec, cells)
        assert (pm == ps).all()
        assert (cm == cs).all()
        assert meshed.mesh() is not None

    run(t())
    d = runtime.STATS.dump()
    assert d["mesh_encode_dispatches"] == 1
    assert d["mesh_host_gathers"] == 0
    assert d["mesh_encode_stripes"] == 11
    # occupancy is EVEN: the padded batch splits exactly across the
    # stripe rows, every device owns the same share
    per_dev = set(d["mesh_stripes_per_device"].values())
    assert len(per_dev) == 1


@pytest.mark.parametrize("method", ["allgather", "psum_bits"])
def test_collective_repair_matches_single_device(method):
    """decode_cells under parallel_repair_mode rebuilds data AND
    wanted-parity rows identically to the single-device stacked-matrix
    decode — including the k'=3-over-width=2 shape, where the chunk
    axis zero-pads to the mesh width."""
    codec = load_codec(dict(DEV_PROFILE))
    cells = rand_cells(6, seed=2)
    runtime.STATS.reset()

    async def t():
        meshed = ECBatcher(conf=mesh_conf(width=2, repair=method))
        single = ECBatcher()
        parity, _ = await single.encode_cells(codec, cells)
        every = np.concatenate([cells, parity], axis=1)
        present = (0, 2, 4)  # lost data 1 and parity 3
        surv = np.ascontiguousarray(every[:, list(present), :])
        want = (0, 1, 2, 3)
        got = await meshed.decode_cells(codec, present, want, surv)
        ref = await single.decode_cells(codec, present, want, surv)
        assert (got == ref).all()
        assert (got[:, :3, :] == cells).all()

    run(t())
    d = runtime.STATS.dump()
    assert d["mesh_decode_dispatches"] == 1
    assert d["mesh_host_gathers"] == 0


def test_mesh_single_stripe_pads_to_stripe_row():
    """batch < devices: one stripe still dispatches (padded to a full
    stripe row) and comes back byte-exact."""
    codec = load_codec(dict(DEV_PROFILE))
    cells = rand_cells(1, seed=3)

    async def t():
        meshed = ECBatcher(conf=mesh_conf(width=4))
        single = ECBatcher()
        pm, cm = await meshed.encode_cells(codec, cells)
        ps, cs = await single.encode_cells(codec, cells)
        assert (pm == ps).all() and (cm == cs).all()

    run(t())


def test_mesh_unavailable_degrades_to_single_device():
    """A config asking for more devices than the platform has must NOT
    break serving: the batcher falls back to the 1-device dispatch."""
    codec = load_codec(dict(DEV_PROFILE))
    cells = rand_cells(4, seed=4)

    async def t():
        degraded = ECBatcher(conf=mesh_conf(n=4096))
        single = ECBatcher()
        pd, cd = await degraded.encode_cells(codec, cells)
        ps, cs = await single.encode_cells(codec, cells)
        assert degraded.mesh() is None
        assert (pd == ps).all() and (cd == cs).all()

    run(t())


def test_host_engine_ignores_mesh_knobs():
    """The mesh is a device-engine lever: the host C++ core keeps its
    two-pass shape (no CRCs from the dispatch) regardless of knobs."""
    codec = load_codec({**DEV_PROFILE, "backend": "host"})
    cells = rand_cells(3, seed=5)
    runtime.STATS.reset()

    async def t():
        b = ECBatcher(conf=mesh_conf())
        parity, crcs = await b.encode_cells(codec, cells)
        assert crcs is None
        assert parity.shape == (3, 2, 256)

    run(t())
    assert runtime.STATS.dump()["mesh_encode_dispatches"] == 0


def test_repair_mode_off_keeps_single_device_decode():
    codec = load_codec(dict(DEV_PROFILE))
    cells = rand_cells(4, seed=6)
    runtime.STATS.reset()

    async def t():
        b = ECBatcher(conf=mesh_conf(repair="off"))
        parity, _ = await b.encode_cells(codec, cells)
        every = np.concatenate([cells, parity], axis=1)
        out = await b.decode_cells(codec, (0, 1, 4), (2,),
                                   np.ascontiguousarray(
                                       every[:, [0, 1, 4], :]))
        assert (out[:, 0, :] == cells[:, 2, :]).all()

    run(t())
    d = runtime.STATS.dump()
    assert d["mesh_encode_dispatches"] == 1  # encode still meshes
    assert d["mesh_decode_dispatches"] == 0  # decode stays 1-device


def test_shard_rows_to_host_dedupes_replicas():
    """Width-replicated results (per-stripe CRCs, repair output) are
    read once per unique shard, not once per replica device."""
    import jax

    from ceph_tpu import parallel

    mesh = parallel.make_mesh(parallel.get_devices(8), width=4)
    arr = jax.device_put(np.arange(8, dtype=np.uint32),
                         parallel.per_stripe_sharding(mesh))
    runtime.STATS.reset()
    out = runtime.shard_rows_to_host(arr)
    assert (out == np.arange(8, dtype=np.uint32)).all()
    # 2 stripe rows x 4 width replicas = 8 shards, 2 unique reads
    assert runtime.STATS.shard_reads == 2
    # and the counted escape hatch counts
    runtime.host_gather(arr)
    assert runtime.STATS.host_gathers == 1


# --------------------------------------------------------- cluster tier


def test_cluster_serves_writes_and_degraded_reads_over_mesh():
    """OSD traffic CROSSES the mesh (the whole point of this PR): a
    live cluster with the mesh knobs on serves client writes through
    sharded fused encode+CRC dispatches — zero host gathers — and a
    degraded read (one OSD down) rebuilds its chunk through the
    collective repair path, byte-exact."""
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    runtime.STATS.reset()
    payload = np.random.default_rng(7).integers(
        0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()  # two stripes

    async def t():
        c = TestCluster(n_osds=5, osd_conf={
            "osd_ec_mesh_devices": 8,
            "osd_ec_mesh_width": 2,
            "parallel_repair_mode": "allgather",
        })
        await c.start()
        c.client.op_timeout = 60.0
        await c.client.create_pool(Pool(
            id=2, name="mesh", size=5, min_size=3, pg_num=8,
            crush_rule=1, type="erasure",
            ec_profile={"plugin": "rs_tpu", "k": "3", "m": "2",
                        "backend": "device"}))
        await c.wait_active(30)
        for i in range(4):
            await c.client.write_full(2, f"obj-{i}", payload)
        assert await c.client.read(2, "obj-0") == payload
        gathers_after_writes = runtime.STATS.host_gathers
        # degraded read: kill one OSD, the rebuilt chunk must come
        # through the collective decode and still read byte-exact
        await c.kill_osd(4)
        for i in range(4):
            assert await c.client.read(2, f"obj-{i}") == payload
        await c.stop()
        return gathers_after_writes

    gathers = [None]

    async def outer():
        gathers[0] = await asyncio.wait_for(t(), 150)

    asyncio.run(outer())
    d = runtime.STATS.dump()
    assert d["mesh_encode_dispatches"] > 0, d
    assert gathers[0] == 0, "write path gathered through the host"
    assert d["mesh_decode_dispatches"] > 0, \
        "degraded reads did not use collective repair"
    assert d["mesh_host_gathers"] == 0, d
