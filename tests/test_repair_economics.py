"""Repair-economics device pipeline (ISSUE 9): bitmatrix and Clay
batched cell codecs must be BYTE-IDENTICAL to their per-stripe
reference implementations (property-style random draws, including
Clay's is_repair sub-chunk plans), route through the ECBatcher like
rs_tpu, and serve the cluster's degraded path — with Clay's rebuild
fetching sub-chunks instead of whole chunks (the d/q < k repair-
traffic amplification the codec exists for)."""
import asyncio

import numpy as np
import pytest

from ceph_tpu.ec import load_codec
from ceph_tpu.ops import rs

RNG = np.random.default_rng(20260804)


def run(coro, timeout=120):
    asyncio.run(asyncio.wait_for(coro, timeout))


def _su_for(codec, base=1024):
    """A stripe_unit that is a fixed point of get_chunk_size — what
    osd.sinfo_for would compute for the pool."""
    su = base
    for _ in range(8):
        got = codec.get_chunk_size(codec.k * su)
        if got == su:
            return su
        su = got
    raise AssertionError("stripe unit did not stabilize")


# --------------------------------------- batched-vs-reference parity


BM_DRAWS = [
    ("blaum_roth", 3, 2, 4), ("blaum_roth", 5, 2, 6),
    ("liberation", 4, 2, 5), ("liberation", 6, 2, 7),
    ("liber8tion", 5, 2, 8), ("cauchy_bm", 4, 3, 8),
]


@pytest.mark.parametrize("tech,k,m,w", BM_DRAWS)
def test_bitmatrix_batched_parity(tech, k, m, w):
    """encode_crc_batch/decode_batch == per-stripe encode_chunks/
    decode_chunks, byte for byte, across random erasure draws — and
    the host-engine hooks agree with the device path."""
    from ceph_tpu import native

    codec = load_codec({"plugin": "bitmatrix", "technique": tech,
                        "k": str(k), "m": str(m), "w": str(w)})
    su = _su_for(codec)
    rng = np.random.default_rng(hash((tech, k, m, w)) % 2**32)
    cells = rng.integers(0, 256, (4, k, su), dtype=np.uint8)
    ref = np.stack([codec.encode_chunks(c) for c in cells])
    parity_w, crcs = codec.encode_crc_batch(rs.pack_u32(cells), su)
    parity = rs.unpack_u32(np.asarray(parity_w))
    np.testing.assert_array_equal(parity, ref)
    every = np.concatenate([cells, parity], axis=1)
    want_crc = np.stack([native.crc32c_batch(e) for e in every])
    np.testing.assert_array_equal(np.asarray(crcs), want_crc)
    np.testing.assert_array_equal(codec.encode_cells_host(cells), ref)
    # random erasure sets up to m losses, mixed data/parity wants
    n = k + m
    for _ in range(4):
        r = int(rng.integers(1, m + 1))
        erase = tuple(sorted(rng.choice(n, size=r, replace=False)))
        present = tuple(i for i in range(n) if i not in erase)[:k]
        surv = np.ascontiguousarray(every[:, list(present), :])
        got = rs.unpack_u32(np.asarray(codec.decode_batch(
            present, rs.pack_u32(surv), want=erase)))
        for b in range(len(cells)):
            dec = codec.decode_chunks(list(present), surv[b])
            for wi, g in enumerate(erase):
                np.testing.assert_array_equal(
                    got[b, wi], dec[g],
                    err_msg=f"{tech} erase={erase} chunk {g}")
        np.testing.assert_array_equal(
            codec.decode_cells_host(present, erase, surv), got)


CLAY_DRAWS = [(4, 2, 5), (3, 2, 4), (4, 3, 6), (3, 3, 4)]


@pytest.mark.parametrize("k,m,d", CLAY_DRAWS)
def test_clay_batched_parity(k, m, d):
    """Clay encode_crc_batch/decode_batch == per-stripe reference
    across random erasure draws, shortened (nu > 0) geometries
    included; host hooks agree with the device path."""
    from ceph_tpu import native

    codec = load_codec({"plugin": "clay", "k": str(k), "m": str(m),
                        "d": str(d)})
    su = _su_for(codec)
    rng = np.random.default_rng(k * 1009 + m * 31 + d)
    cells = rng.integers(0, 256, (3, k, su), dtype=np.uint8)
    ref = np.stack([codec.encode_chunks(c) for c in cells])
    parity_w, crcs = codec.encode_crc_batch(rs.pack_u32(cells), su)
    parity = rs.unpack_u32(np.asarray(parity_w))
    np.testing.assert_array_equal(parity, ref)
    every = np.concatenate([cells, parity], axis=1)
    want_crc = np.stack([native.crc32c_batch(e) for e in every])
    np.testing.assert_array_equal(np.asarray(crcs), want_crc)
    np.testing.assert_array_equal(codec.encode_cells_host(cells), ref)
    n = k + m
    for _ in range(3):
        r = int(rng.integers(1, m + 1))
        erase = tuple(sorted(rng.choice(n, size=r, replace=False)))
        present = tuple(i for i in range(n) if i not in erase)
        surv = np.ascontiguousarray(every[:, list(present), :])
        got = rs.unpack_u32(np.asarray(codec.decode_batch(
            present, rs.pack_u32(surv), want=erase)))
        for b in range(len(cells)):
            dec = codec.decode_chunks(list(present), surv[b])
            for wi, g in enumerate(erase):
                np.testing.assert_array_equal(
                    got[b, wi], dec[g],
                    err_msg=f"clay k={k} m={m} d={d} erase={erase}")
        np.testing.assert_array_equal(
            codec.decode_cells_host(present, erase, surv), got)


@pytest.mark.parametrize("k,m,d", CLAY_DRAWS)
def test_clay_repair_batch_parity(k, m, d):
    """repair_batch over is_repair sub-chunk plans == the scalar
    repair() per stripe, for every single-loss chunk the plan covers
    — each helper ships exactly 1/q of its cells."""
    codec = load_codec({"plugin": "clay", "k": str(k), "m": str(m),
                        "d": str(d)})
    su = _su_for(codec)
    rng = np.random.default_rng(k * 7907 + m * 17 + d)
    cells = rng.integers(0, 256, (3, k, su), dtype=np.uint8)
    parity = np.stack([codec.encode_chunks(c) for c in cells])
    every = np.concatenate([cells, parity], axis=1)
    n = k + m
    sub = su // codec.get_sub_chunk_count()
    for lost in range(n):
        avail = sorted(set(range(n)) - {lost})
        if not codec.is_repair({lost}, set(avail)):
            continue
        plan = codec.minimum_to_decode([lost], avail)
        assert lost not in plan and len(plan) == codec.d
        order = sorted(plan)
        runs = plan[order[0]]
        surv = np.stack([
            np.concatenate([every[:, c, o * sub : (o + cnt) * sub]
                            for o, cnt in runs], axis=1)
            for c in order
        ], axis=1)  # (B, d, su/q)
        assert surv.shape[-1] == su // codec.q
        got = rs.unpack_u32(np.asarray(codec.repair_batch(
            tuple(order), rs.pack_u32(surv), (lost,))))
        np.testing.assert_array_equal(got[:, 0, :], every[:, lost, :],
                                      err_msg=f"lost={lost}")
        # host hook agrees
        np.testing.assert_array_equal(
            codec.repair_cells_host(tuple(order), (lost,), surv), got)


def test_lrc_batched_local_and_global_parity():
    """LRC's composite generator rides the rs-style batched hooks:
    local repairs consume FEWER than k rows, global decodes any
    spanning set — both byte-identical to the layered decode()."""
    codec = load_codec({"plugin": "lrc", "k": "4", "m": "2", "l": "3"})
    n = codec.k + codec.m
    su = _su_for(codec)
    rng = np.random.default_rng(4242)
    objs = [rng.integers(0, 256, codec.k * su, dtype=np.uint8)
            for _ in range(3)]
    by_pos: dict[int, list] = {}
    for o in objs:
        enc = codec.encode(list(range(n)), o.tobytes())
        for p, c in enc.items():
            by_pos.setdefault(p, []).append(c)
    for lost_set in ([0], [1], [0, 1], [0, 4]):
        avail = sorted(set(range(n)) - set(lost_set))
        need = sorted(codec.minimum_to_decode(lost_set, avail))
        if len(lost_set) == 1:
            assert len(need) < codec.k  # locality: cheaper than MDS
        pg = tuple(codec._position_to_generator(p) for p in need)
        wg = tuple(codec._position_to_generator(p) for p in lost_set)
        surv = np.stack([np.stack(by_pos[p]) for p in need], axis=1)
        got = rs.unpack_u32(np.asarray(codec.decode_batch(
            pg, rs.pack_u32(surv), want=wg)))
        for i, p in enumerate(lost_set):
            np.testing.assert_array_equal(
                got[:, i, :], np.stack(by_pos[p]),
                err_msg=f"lrc lost={lost_set} pos={p}")


# ------------------------------------------------ ECBatcher routing


def test_batcher_routes_cellwise_codecs():
    """Cellwise codecs dispatch through the SAME bucket machinery as
    rs_tpu on both engines, the repair kind included, and distinct
    geometries (two w's) never share a bucket."""
    from ceph_tpu.cluster.ecbatch import ECBatcher, codec_profile_key
    from ceph_tpu.utils.perf import PerfCounters

    k1 = codec_profile_key(load_codec(
        {"plugin": "bitmatrix", "technique": "liberation",
         "k": "4", "m": "2", "w": "5"}))
    k2 = codec_profile_key(load_codec(
        {"plugin": "bitmatrix", "technique": "liberation",
         "k": "4", "m": "2", "w": "7"}))
    assert k1 != k2
    kc1 = codec_profile_key(load_codec(
        {"plugin": "clay", "k": "3", "m": "2", "d": "3"}))
    kc2 = codec_profile_key(load_codec(
        {"plugin": "clay", "k": "3", "m": "2", "d": "4"}))
    assert kc1 != kc2

    async def t(backend):
        perf = PerfCounters("t")
        ECBatcher.declare_counters(perf)
        b = ECBatcher(perf)
        out = {}
        for plug, prof in (
            ("bm", {"plugin": "bitmatrix", "technique": "blaum_roth",
                    "k": "3", "m": "2", "w": "4",
                    "backend": backend}),
            ("clay", {"plugin": "clay", "k": "3", "m": "2",
                      "backend": backend}),
        ):
            codec = load_codec(prof)
            su = _su_for(codec)
            cells = np.random.default_rng(3).integers(
                0, 256, (2, codec.k, su), dtype=np.uint8)
            parity, crcs = await b.encode_cells(codec, cells)
            ref = np.stack([codec.encode_chunks(c) for c in cells])
            np.testing.assert_array_equal(parity, ref)
            if backend == "device":
                assert crcs is not None and crcs.shape == (2, 5)
            else:
                assert crcs is None  # host engines keep their own pass
            every = np.concatenate([cells, parity], axis=1)
            present = (0, 2, 3)
            dec = await b.decode_cells(
                codec, present, (1,),
                np.ascontiguousarray(every[:, list(present), :]))
            np.testing.assert_array_equal(dec[:, 0, :], cells[:, 1, :])
            out[plug] = codec
        # the sub-chunk repair kind, through the batcher
        codec = out["clay"]
        su = _su_for(codec)
        cells = np.random.default_rng(5).integers(
            0, 256, (2, codec.k, su), dtype=np.uint8)
        parity, _ = await b.encode_cells(codec, cells)
        every = np.concatenate([cells, parity], axis=1)
        lost = 0
        avail = sorted(set(range(5)) - {lost})
        plan = codec.minimum_to_decode([lost], avail)
        sub = su // codec.get_sub_chunk_count()
        order = sorted(plan)
        runs = plan[order[0]]
        surv = np.stack([
            np.concatenate([every[:, c, o * sub : (o + cnt) * sub]
                            for o, cnt in runs], axis=1)
            for c in order], axis=1)
        got = await b.repair_cells(codec, tuple(order), (lost,), surv)
        np.testing.assert_array_equal(got[:, 0, :], every[:, lost, :])
        d = perf.dump()
        assert d["ec_batches"] >= 2
        assert d["ec_decode_batches"] >= 3  # 2 decodes + 1 repair

    run(t("device"))
    run(t("host"))


def test_slice_subruns_selects_per_cell():
    from ceph_tpu.cluster.pg import (_pack_subruns, _slice_subruns,
                                     _unpack_subruns)

    codec = load_codec({"plugin": "clay", "k": "4", "m": "2"})
    subs = codec.get_sub_chunk_count()  # 8
    su = 8 * 16
    chunk = np.arange(2 * su, dtype=np.uint8).tobytes()  # 2 cells
    runs = [(0, 2), (4, 2)]
    raw = _pack_subruns(runs)
    assert _unpack_subruns(raw) == runs
    out = np.frombuffer(_slice_subruns(chunk, su, raw, codec),
                        dtype=np.uint8)
    cells = np.frombuffer(chunk, dtype=np.uint8).reshape(2, subs, 16)
    want = np.concatenate(
        [cells[:, 0:2, :], cells[:, 4:6, :]], axis=1).reshape(-1)
    np.testing.assert_array_equal(out, want)


# -------------------------------------------- cluster serving path


def test_cluster_clay_subchunk_recovery_storm():
    """Kill + out one member of a Clay pool: the backfill rebuild of
    its shards must ride the SUB-CHUNK repair path (counter-proven:
    ec_repair_subchunk > 0 and fetched/rebuilt < k), through batched
    decode dispatches, and every object stays byte-exact."""
    from ceph_tpu.cluster import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    async def t():
        c = TestCluster(n_osds=7, out_interval=1.0)
        await c.start()
        await c.client.create_pool(Pool(
            id=2, name="p", size=5, min_size=3, pg_num=4,
            crush_rule=1, type="erasure",
            ec_profile={"plugin": "clay", "k": "3", "m": "2",
                        "backend": "device", "stripe_unit": "4096"}))
        await c.wait_active(30)
        rng = np.random.default_rng(11)
        datas = {}
        for i in range(4):
            d = rng.integers(0, 256, 40000, dtype=np.uint8).tobytes()
            datas[f"o{i}"] = d
            await c.client.write_full(2, f"o{i}", d)
        pgid = c.client.osdmap.object_to_pg(2, b"o0")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        await c.kill_osd(victim)
        await c.wait_down(victim, 20)
        await asyncio.sleep(1.5)  # past out_interval: remap + backfill
        await c.wait_clean(60)
        for n, d in datas.items():
            assert await c.client.read(2, n) == d, n
        tot = {}
        for o in c.osds:
            if o is None:
                continue
            for key, v in o.perf.dump().items():
                if isinstance(v, (int, float)):
                    tot[key] = tot.get(key, 0) + v
        assert tot.get("ec_repair_subchunk", 0) > 0, tot
        fetched = tot.get("ec_repair_bytes_fetched", 0)
        rebuilt = tot.get("ec_repair_bytes_rebuilt", 0)
        assert rebuilt > 0
        # clay k=3 m=2 d=4 q=2: sub-chunk amp d/q = 2.0 < k = 3; the
        # mixed ledger (some full-path rebuilds ride along) must still
        # beat the MDS bound
        assert fetched / rebuilt < 3.0, (fetched, rebuilt)
        assert tot.get("ec_decode_batches", 0) > 0
        await c.stop()

    run(t(), timeout=180)
