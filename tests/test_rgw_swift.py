"""Swift dialect over the shared RGW core (rgw_rest_swift role):
tempauth tokens, container/object CRUD with metadata, listings,
account stats, and S3<->Swift namespace unification."""
import asyncio
import json

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.rgw import RGWLite, S3Frontend
from ceph_tpu.services.rgw_swift import SwiftFrontend

from test_rgw import http


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make(users=None):
    c = TestCluster(n_osds=3)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rgw", size=2, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    rgw = RGWLite(c.client, 1)
    sw = SwiftFrontend(rgw, users=users)
    host, port = await sw.start()
    return c, rgw, sw, host, port


def test_tempauth_and_container_lifecycle():
    async def t():
        c, rgw, sw, host, port = await make(
            users={"test:tester": "testing"})
        # no token -> 401
        st, _, _ = await http(host, port, "GET", "/v1/AUTH_test")
        assert st == 401
        # wrong key -> 401
        st, _, _ = await http(host, port, "GET", "/auth/v1.0",
                              headers={"x-auth-user": "test:tester",
                                       "x-auth-key": "wrong"})
        assert st == 401
        st, hd, _ = await http(host, port, "GET", "/auth/v1.0",
                               headers={"x-auth-user": "test:tester",
                                        "x-auth-key": "testing"})
        assert st == 200 and hd["x-auth-token"].startswith("AUTH_tk")
        tok = {"x-auth-token": hd["x-auth-token"]}

        st, _, _ = await http(host, port, "PUT", "/v1/AUTH_test/box",
                              headers=tok)
        assert st == 201
        st, _, _ = await http(host, port, "PUT", "/v1/AUTH_test/box",
                              headers=tok)
        assert st == 202  # Swift: existing container accepted
        st, _, body = await http(host, port, "GET", "/v1/AUTH_test",
                                 headers=tok)
        assert st == 200 and body == b"box\n"
        st, _, _ = await http(host, port, "DELETE",
                              "/v1/AUTH_test/box", headers=tok)
        assert st == 204
        await sw.stop()
        await c.stop()

    run(t())


def test_object_crud_metadata_and_listing():
    async def t():
        c, rgw, sw, host, port = await make()
        await http(host, port, "PUT", "/v1/AUTH_test/media")
        st, hd, _ = await http(
            host, port, "PUT", "/v1/AUTH_test/media/pic.jpg",
            body=b"JPEGDATA" * 100,
            headers={"content-type": "image/jpeg",
                     "x-object-meta-camera": "tpu-cam",
                     "x-object-meta-iso": "400"})
        assert st == 201 and hd["etag"]
        st, hd, body = await http(host, port, "GET",
                                  "/v1/AUTH_test/media/pic.jpg")
        assert st == 200 and body == b"JPEGDATA" * 100
        assert hd["content-type"] == "image/jpeg"
        assert hd["x-object-meta-camera"] == "tpu-cam"
        st, hd, body = await http(host, port, "HEAD",
                                  "/v1/AUTH_test/media/pic.jpg")
        assert st == 200 and body == b""
        assert hd["content-length"] == str(800)
        assert hd["x-object-meta-iso"] == "400"

        await http(host, port, "PUT", "/v1/AUTH_test/media/a.txt",
                   body=b"aaa")
        st, _, body = await http(host, port, "GET",
                                 "/v1/AUTH_test/media?format=json")
        rows = json.loads(body)
        assert [r["name"] for r in rows] == ["a.txt", "pic.jpg"]
        assert rows[1]["bytes"] == 800
        assert rows[1]["content_type"] == "image/jpeg"
        st, _, body = await http(host, port, "GET",
                                 "/v1/AUTH_test/media?prefix=pic")
        assert body == b"pic.jpg\n"

        # container + account stats
        st, hd, _ = await http(host, port, "HEAD",
                               "/v1/AUTH_test/media")
        assert st == 204 and hd["x-container-object-count"] == "2"
        assert hd["x-container-bytes-used"] == str(803)
        st, hd, _ = await http(host, port, "HEAD", "/v1/AUTH_test")
        assert st == 204 and hd["x-account-object-count"] == "2"

        # non-empty container cannot be deleted
        st, _, _ = await http(host, port, "DELETE",
                              "/v1/AUTH_test/media")
        assert st == 409
        await sw.stop()
        await c.stop()

    run(t())


def test_copy_verb_and_x_copy_from():
    async def t():
        c, rgw, sw, host, port = await make()
        await http(host, port, "PUT", "/v1/AUTH_test/src")
        await http(host, port, "PUT", "/v1/AUTH_test/dst")
        await http(host, port, "PUT", "/v1/AUTH_test/src/orig",
                   body=b"payload",
                   headers={"x-object-meta-k": "v"})
        st, _, _ = await http(host, port, "COPY",
                              "/v1/AUTH_test/src/orig",
                              headers={"destination": "/dst/copy1"})
        assert st == 201
        st, hd, body = await http(host, port, "GET",
                                  "/v1/AUTH_test/dst/copy1")
        assert body == b"payload"
        assert hd["x-object-meta-k"] == "v"  # attrs carried over
        # PUT + X-Copy-From with replacement metadata
        st, _, _ = await http(host, port, "PUT",
                              "/v1/AUTH_test/dst/copy2",
                              headers={"x-copy-from": "/src/orig",
                                       "x-object-meta-k": "new"})
        assert st == 201
        _, hd, body = await http(host, port, "GET",
                                 "/v1/AUTH_test/dst/copy2")
        assert body == b"payload" and hd["x-object-meta-k"] == "new"
        st, _, _ = await http(host, port, "DELETE",
                              "/v1/AUTH_test/dst/copy1")
        assert st == 204
        st, _, _ = await http(host, port, "GET",
                              "/v1/AUTH_test/dst/copy1")
        assert st == 404
        await sw.stop()
        await c.stop()

    run(t())


def test_s3_and_swift_share_one_namespace():
    """The reference serves both dialects over one bucket index; an
    object PUT via S3 lists and reads through Swift."""
    async def t():
        c, rgw, sw, host, port = await make()
        s3 = S3Frontend(rgw)
        s3host, s3port = await s3.start()
        st, _, _ = await http(s3host, s3port, "PUT", "/shared")
        assert st == 200
        st, _, _ = await http(s3host, s3port, "PUT", "/shared/from-s3",
                              body=b"via s3")
        assert st == 200
        st, _, body = await http(host, port, "GET",
                                 "/v1/AUTH_test/shared")
        assert st == 200 and body == b"from-s3\n"
        st, _, body = await http(host, port, "GET",
                                 "/v1/AUTH_test/shared/from-s3")
        assert st == 200 and body == b"via s3"
        # and the other direction
        await http(host, port, "PUT", "/v1/AUTH_test/shared/from-sw",
                   body=b"via swift")
        st, _, body = await http(s3host, s3port, "GET",
                                 "/shared/from-sw")
        assert st == 200 and body == b"via swift"
        await s3.stop()
        await sw.stop()
        await c.stop()

    run(t())


def test_versioned_delete_preserves_promoted_metadata():
    """Deleting the current version promotes the previous one WITH its
    content-type and user metadata (round-5 review finding)."""
    async def t():
        c, rgw, sw, host, port = await make()
        await rgw.create_bucket("vb")
        await rgw.put_bucket_versioning("vb", "Enabled")
        _, v1 = await rgw.put_object(
            "vb", "doc", b"one", content_type="text/plain",
            meta={"rev": "1"})
        _, v2 = await rgw.put_object(
            "vb", "doc", b"two", content_type="text/html",
            meta={"rev": "2"})
        await rgw.delete_object("vb", "doc", version_id=v2)
        m = await rgw.head_object("vb", "doc")
        assert m["content_type"] == "text/plain"
        assert m["meta"] == {"rev": "1"}
        _, hd, body = await http(host, port, "GET",
                                 "/v1/AUTH_test/vb/doc")
        assert body == b"one" and hd["x-object-meta-rev"] == "1"
        await sw.stop()
        await c.stop()

    run(t())


def test_bad_limit_returns_400():
    async def t():
        c, rgw, sw, host, port = await make()
        await http(host, port, "PUT", "/v1/AUTH_test/c1")
        st, _, body = await http(host, port, "GET",
                                 "/v1/AUTH_test/c1?limit=abc")
        assert st == 400 and body == b"InvalidLimit\n"
        # the keep-alive connection survives for the next request
        st, _, _ = await http(host, port, "GET", "/v1/AUTH_test/c1")
        assert st == 200
        await sw.stop()
        await c.stop()

    run(t())
