"""MDSLite daemon: metadata authority, capabilities with revoke, and
MDLog-role journal recovery.

Acceptance (VERDICT r2 item 7): a two-client coherence test and a
kill-MDS-mid-rename recovery test.
"""
import asyncio

import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.fs import Exists, FSLite, NoEnt
from ceph_tpu.services.mds import FSClient, MDSLite, _MDSCrash


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="fs", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    await FSLite(c.client, 1).mkfs()
    mds = MDSLite(c.bus, c.client, 1)
    await mds.start()
    a = FSClient(c.bus, c.client, 1, name="fsclient.a")
    b = FSClient(c.bus, c.client, 1, name="fsclient.b")
    await a.connect()
    await b.connect()
    return c, mds, a, b


def test_two_client_coherence():
    """mkdir/create/rename/write by one client are immediately visible
    to the other — the single-authority serialization the library
    version of fs.py could not give."""
    async def t():
        c, mds, a, b = await make()
        await a.mkdir("/shared")
        assert await b.listdir("/") == ["shared"]
        await a.create("/shared/f")
        await a.write("/shared/f", b"written-by-A" * 100)
        # B's stat RECALLS A's write cap: A's buffered size flushes to
        # the MDS, so B sees the true size without A closing the file
        st = await b.stat("/shared/f")
        assert st["size"] == 1200
        assert await b.read("/shared/f") == b"written-by-A" * 100
        # B renames while A still has the path; A reopens and writes
        await b.rename("/shared/f", "/shared/g")
        assert await a.listdir("/shared") == ["g"]
        with pytest.raises(NoEnt):
            await b.stat("/shared/f")
        await b.write("/shared/g", b"B!", 0)
        st2 = await a.stat("/shared/g")
        assert st2["size"] == 1200  # B's partial overwrite kept length
        assert (await a.read("/shared/g"))[:2] == b"B!"
        # concurrent mkdir of the same name: exactly one wins
        results = await asyncio.gather(
            a.mkdir("/race"), b.mkdir("/race"), return_exceptions=True)
        assert sum(1 for r in results if r is None) == 1
        assert sum(1 for r in results if isinstance(r, Exists)) == 1
        await a.close()
        await b.close()
        await c.stop()

    run(t())


def test_write_cap_exclusive_and_revoked():
    async def t():
        c, mds, a, b = await make()
        await a.create("/f")
        await a.write("/f", b"x" * 5000)
        ino = a._paths["/f"]
        assert ino in a.wcaps  # A buffers size 5000 under its cap
        assert a.wcaps[ino] == 5000
        # B opening for write revokes A's cap (exclusive)
        await b.open("/f", "w")
        assert ino not in a.wcaps  # revoked + flushed
        st = await mds.fs.stat("/f")
        assert st["size"] == 5000  # A's buffered size landed
        await b.write("/f", b"y" * 100, offset=5000)
        await b.close()
        assert (await a.stat("/f"))["size"] == 5100
        await a.close()
        await c.stop()

    run(t())


def test_mds_crash_mid_rename_recovers():
    """Kill the MDS between the two dirfrag updates of a rename: the
    journal replay on the next MDS completes it — the file exists at
    exactly one path (MDLog crash-recovery role)."""
    async def t():
        c, mds, a, b = await make()
        await a.mkdir("/d1")
        await a.mkdir("/d2")
        await a.create("/d1/f")
        await a.write("/d1/f", b"payload" * 10)
        # flush A's cap so the size is durable before the crash
        await a.close()

        mds._crash_mid_rename = True
        with pytest.raises(Exception):
            await b.rename("/d1/f", "/d2/f")
        # the daemon died mid-op: destination linked, source not yet
        # unlinked — both paths resolve right now (the torn state)
        await mds.stop()

        mds2 = MDSLite(c.bus, c.client, 1)
        await mds2.start()  # journal replay completes the rename
        assert await b.listdir("/d1") == []
        assert await b.listdir("/d2") == ["f"]
        assert await b.read("/d2/f") == b"payload" * 10
        # and the namespace still takes mutations
        await b.rename("/d2/f", "/d1/f")
        assert await b.listdir("/d1") == ["f"]
        await b.close()
        await mds2.stop()
        await c.stop()

    run(t())


def test_mds_restart_idempotent_replay():
    """A completed-but-unexpired journal entry replays as a no-op."""
    async def t():
        c, mds, a, b = await make()
        await a.mkdir("/x")
        await a.create("/x/file")
        # simulate crash AFTER apply but BEFORE expire: rewind pointer
        await c.client.omap_set(1, b"mdslog", {b"expired_upto":
                                               b"\x00" * 8})
        await mds.stop()
        mds2 = MDSLite(c.bus, c.client, 1)
        await mds2.start()  # replays mkdir + create: both exist already
        assert await b.listdir("/x") == ["file"]
        await b.write("/x/file", b"ok")
        assert await b.read("/x/file") == b"ok"
        await a.close()
        await b.close()
        await mds2.stop()
        await c.stop()

    run(t())


def test_trim_then_restart_preserves_crash_recovery():
    """Regression (round-3 advisor, high): after a journal trim and an
    MDS restart, new intents must journal at seqs ABOVE the persisted
    expired_upto — otherwise a later crash replay skips them and a torn
    rename persists, exactly the failure the journal exists to prevent."""
    async def t():
        c, mds, a, b = await make()
        await a.mkdir("/d1")
        await a.mkdir("/d2")
        await a.create("/d1/f")
        await a.close()
        # force a trim: pretend the journal body crossed the threshold
        mds._jbytes = (1 << 20) + 1
        await a.connect()
        await a.mkdir("/junk")  # any journaled mutation triggers _expire
        assert mds._jbytes == 0  # trimmed
        await mds.stop()

        # restart: _seq must resume above the pre-trim high-water
        mds2 = MDSLite(c.bus, c.client, 1)
        await mds2.start()
        assert mds2._seq >= mds._seq

        # now crash mid-rename on the restarted daemon; replay must
        # complete it (would be skipped as "expired" before the fix)
        mds2._crash_mid_rename = True
        with pytest.raises(Exception):
            await b.rename("/d1/f", "/d2/f")
        await mds2.stop()
        mds3 = MDSLite(c.bus, c.client, 1)
        await mds3.start()
        assert await b.listdir("/d1") == []
        assert await b.listdir("/d2") == ["f"]
        await a.close()
        await b.close()
        await mds3.stop()
        await c.stop()

    run(t())


def test_dead_client_evicted():
    """A vanished cap holder cannot wedge the namespace: the revoke
    times out and the MDS evicts the cap (session-eviction role)."""
    async def t():
        c, mds, a, b = await make()
        mds.revoke_timeout = 0.3
        await a.create("/f")
        await a.write("/f", b"z" * 10)
        # A disappears without closing (no unregister -> revoke times out)
        c.bus.unregister("fsclient.a")
        st = await b.stat("/f")  # must not hang; buffered size is lost
        assert st["size"] in (0, 10)  # eviction drops the unflushed size
        await b.write("/f", b"recovered")
        assert await b.read("/f") == b"recovered"
        await b.close()
        await c.stop()

    run(t())


def test_fs_snapshots_read_back_after_mutation():
    """.snap-role read-only snapshots (SnapServer + snaprealm roles,
    VERDICT r4 #8): metadata freezes at mksnap, file DATA is lazy-COW
    through the data pool's SnapContext — overwrite, truncate, delete,
    and new files after the snapshot never leak into it."""
    async def t():
        c, mds, a, b = await make()
        await a.mkdir("/proj")
        await a.mkdir("/proj/sub")
        await a.write("/proj/report", b"version-one")
        await a.write("/proj/sub/data", b"D" * 5000)
        await a._flush(a._paths["/proj/report"])
        await a._flush(a._paths["/proj/sub/data"])

        await a.mksnap("/proj", "s1")
        assert await a.lssnap("/proj") == ["s1"]

        # mutate everything after the snapshot
        await a.write("/proj/report", b"VERSION-TWO-IS-LONGER")
        await a.unlink("/proj/sub/data")
        await a.write("/proj/new-file", b"born later")
        await a._flush(a._paths["/proj/report"])

        # live view reflects the mutations...
        assert await a.read("/proj/report") == b"VERSION-TWO-IS-LONGER"
        assert sorted(await a.listdir("/proj")) == \
            ["new-file", "report", "sub"]
        # ...the snapshot does not — including from ANOTHER client
        assert await b.snap_read("/proj", "s1", "report") \
            == b"version-one"
        assert await b.snap_read("/proj", "s1", "sub/data") \
            == b"D" * 5000
        assert await b.snap_listdir("/proj", "s1") == \
            ["report", "sub"]
        st = await b.snap_stat("/proj", "s1", "report")
        assert st["size"] == len(b"version-one")

        # rmsnap removes the frozen view and the key from lssnap
        await a.rmsnap("/proj", "s1")
        assert await a.lssnap("/proj") == []
        import pytest as _pytest

        from ceph_tpu.services import fs as fslib

        with _pytest.raises(fslib.NoEnt):
            await b.snap_read("/proj", "s1", "report")
        await c.stop()

    run(t())


def test_snapshot_recalls_foreign_write_caps():
    """mksnap recalls write caps under the subtree, so a snapshot taken
    by client A freezes client B's BUFFERED size, and B's next write
    re-opens with the new SnapContext (COW stays correct)."""
    async def t():
        c, mds, a, b = await make()
        await b.write("/doc", b"buffered-by-b")
        # b holds the w cap with a buffered size; a snapshots the root
        await a.mksnap("/", "root-snap")
        # the recall flushed b's size into the dentry the snap froze
        assert await a.snap_read("/", "root-snap", "doc") \
            == b"buffered-by-b"
        # b's next write goes through a fresh open (cap was recalled)
        # and carries the updated SnapContext
        await b.write("/doc", b"after-snap-bbbb")
        await b._flush(b._paths["/doc"])
        assert await a.read("/doc") == b"after-snap-bbbb"
        assert await a.snap_read("/", "root-snap", "doc") \
            == b"buffered-by-b"
        await c.stop()

    run(t())


def test_snapshots_survive_mds_restart():
    """The snap table persists (SnapServer store role): a restarted MDS
    serves existing snapshots."""
    async def t():
        c, mds, a, b = await make()
        await a.write("/f", b"pre-snap")
        await a._flush(a._paths["/f"])
        await a.mksnap("/", "keep")
        await a.write("/f", b"post-snap!")
        await a._flush(a._paths["/f"])

        await mds.stop()
        mds2 = MDSLite(c.bus, c.client, 1)
        await mds2.start()
        assert await a.lssnap("/") == ["keep"]
        assert await a.snap_read("/", "keep", "f") == b"pre-snap"
        assert await a.read("/f") == b"post-snap!"
        await c.stop()

    run(t())


def test_object_cacher_fs_cap_fence():
    """ObjectCacher under the fs client: buffered data flushes when the
    MDS revokes the write cap, so the OTHER client reads it all."""
    async def t():
        c, mds, _a, _b = await make()
        a = FSClient(c.bus, c.client, 1, name="fsclient.ca",
                     cache=True)
        b = FSClient(c.bus, c.client, 1, name="fsclient.cb")
        await a.connect()
        await b.connect()
        await a.write("/doc", b"cached-" * 1000)
        assert a._cacher.dirty_bytes() > 0  # write-back, not landed
        # b's stat triggers the cap revoke -> a flushes data THEN size
        assert await b.read("/doc") == b"cached-" * 1000
        assert a._cacher.dirty_bytes() == 0
        await a.close()
        await b.close()
        await c.stop()

    run(t())


def test_cached_reader_invalidated_by_foreign_write():
    """Reader-side coherence: a cached fs reader registers an r cap, so
    a foreign writer's open revokes it and the cache drops — the next
    read sees the new content (no stale serve)."""
    async def t():
        c, mds, _a, _b = await make()
        rdr = FSClient(c.bus, c.client, 1, name="fsclient.r",
                       cache=True)
        wtr = FSClient(c.bus, c.client, 1, name="fsclient.w")
        await rdr.connect()
        await wtr.connect()
        await wtr.write("/news", b"first edition")
        await wtr._flush(wtr._paths["/news"])
        assert await rdr.read("/news") == b"first edition"  # cached now
        await wtr.write("/news", b"SECOND edition")
        await wtr._flush(wtr._paths["/news"])
        # the writer's open revoked rdr's r cap -> cache invalidated
        assert await rdr.read("/news") == b"SECOND edition"
        await rdr.close()
        await wtr.close()
        await c.stop()

    run(t())


def test_fs_cache_coherent_across_truncate():
    """FSClient.truncate goes through the MDS behind the data cache:
    cached/buffered bytes past the cut must neither be served nor
    re-flushed at a later cap fence (round-5 review finding)."""
    async def t():
        c, mds, _a, _b = await make()
        fsc = FSClient(c.bus, c.client, 1, name="fsclient.tr",
                       cache=True)
        await fsc.connect()
        await fsc.write("/f", b"D" * 50_000)
        assert (await fsc.read("/f"))[:50] == b"D" * 50
        await fsc.truncate("/f", 10)
        await fsc.write("/f", b"x", offset=50_000)  # re-extend
        got = await fsc.read("/f")
        assert got[:10] == b"D" * 10
        assert got[10:50_000] == b"\x00" * (50_000 - 10)
        assert got[50_000:] == b"x"
        await fsc.close()
        await c.stop()

    run(t())


def test_truncate_of_unopened_path_keeps_other_dirty_data():
    """A truncate of a path this client never opened must not discard
    OTHER files' buffered dirty writes in the wholesale invalidate
    (round-5 review finding, confirmed repro)."""
    async def t():
        c, mds, _a, _b = await make()
        w = FSClient(c.bus, c.client, 1, name="fsclient.w2")
        await w.connect()
        await w.write("/other", b"O" * 3000)
        await w.close()
        fsc = FSClient(c.bus, c.client, 1, name="fsclient.k",
                       cache=True)
        await fsc.connect()
        await fsc.write("/doc", b"IMPORTANT" * 1000)
        assert fsc._cacher.dirty_bytes() > 0
        await fsc.truncate("/other", 10)  # never opened here
        await fsc.close()
        rdr = FSClient(c.bus, c.client, 1, name="fsclient.k2")
        await rdr.connect()
        assert await rdr.read("/doc") == b"IMPORTANT" * 1000
        assert await rdr.read("/other") == b"O" * 10
        await rdr.close()
        await c.stop()

    run(t())


def test_foreign_truncate_invalidates_cached_reader():
    """The MDS truncate verb recalls caps: a cached reader must not
    serve pre-truncate bytes after another client cut the file
    (round-5 review finding, confirmed repro)."""
    async def t():
        c, mds, _a, _b = await make()
        w = FSClient(c.bus, c.client, 1, name="fsclient.tw")
        r = FSClient(c.bus, c.client, 1, name="fsclient.trd",
                     cache=True)
        await w.connect()
        await r.connect()
        await w.write("/f", b"D" * 50_000)
        await w._flush(w._paths["/f"])
        assert await r.read("/f") == b"D" * 50_000  # cached now
        await w.truncate("/f", 10)
        await w.write("/f", b"z", offset=49_999)  # re-extend
        await w._flush(w._paths["/f"])
        got = await r.read("/f")
        assert got[:10] == b"D" * 10
        assert got[10:49_999] == b"\x00" * (49_999 - 10)
        assert got[49_999:] == b"z"
        await w.close()
        await r.close()
        await c.stop()

    run(t())


def test_quota_count_cache_deflates_on_unlink():
    """Regression: the realm count cache self-advances on every
    accepted create (and each accept re-extends its TTL), but deletes
    must deflate it too — otherwise a sustained create/delete churn
    under a max_files quota returns EDQUOT while the realm is actually
    under the limit."""
    import ceph_tpu.services.fs as fslib

    async def t():
        c, mds, a, _b = await make()
        await a.mkdir("/q")
        await a.set_quota("/q", max_files=3)
        await a.create("/q/f1")
        await a.create("/q/f2")
        await a.create("/q/f3")
        with pytest.raises(fslib.QuotaExceeded):
            await a.create("/q/f4")
        # churn: delete + create repeatedly WITHIN the cache TTL; the
        # cached count must deflate on each unlink or the self-advance
        # keeps it pinned at the limit and every create EDQUOTs
        for i in range(5):
            await a.unlink("/q/f1")
            await a.create("/q/f1")
        # rmdir deflates too: swap a dir out for a file at the limit
        await a.unlink("/q/f1")
        await a.mkdir("/q/d1")
        with pytest.raises(fslib.QuotaExceeded):
            await a.create("/q/f5")
        await a.rmdir("/q/d1")
        await a.create("/q/f5")
        # rename OUT of the realm deflates it the same way (and the
        # realm-free destination never blocks)
        await a.mkdir("/out")
        await a.rename("/q/f5", "/out/f5")
        await a.create("/q/f6")
        with pytest.raises(fslib.QuotaExceeded):
            await a.create("/q/f7")
        await c.stop()

    run(t())


def test_quotas_files_and_bytes():
    """ceph.quota.max_files (MDS-enforced on create/mkdir) and
    max_bytes (client-enforced on growing writes), realm nesting,
    rstat surface, and clearing."""
    import ceph_tpu.services.fs as fslib

    async def t():
        c, mds, a, b = await make()
        await a.mkdir("/q")
        await a.set_quota("/q", max_files=3)
        await a.create("/q/f1")
        await a.create("/q/f2")
        await a.mkdir("/q/sub")  # 3rd entry hits the limit
        with pytest.raises(fslib.QuotaExceeded):
            await a.create("/q/f3")
        # enforcement is realm-wide: the OTHER client hits it too,
        # and nested dirs count against the same realm
        with pytest.raises(fslib.QuotaExceeded):
            await b.create("/q/sub/nested")
        # outside the realm creation is free
        await a.create("/free")
        # lift the file quota, set a byte quota
        await a.set_quota("/q", max_bytes=4096)
        await a.create("/q/f3")
        await a.write("/q/f3", b"x" * 2048)
        await a._flush(a._paths["/q/f3"])
        b._quota_cache.clear()
        with pytest.raises(fslib.QuotaExceeded):
            await b.write("/q/big", b"y" * 4096)
        # usage surface (getquota + dirstat)
        q = await a.get_quota("/q/sub")
        assert q["realm"] == "/q" and q["max_bytes"] == 4096
        assert q["rbytes"] >= 2048
        st = await a.dir_stat("/q")
        # f1 f2 f3 + the empty "big" left by the rejected write (the
        # create lands before the byte check, POSIX-style)
        assert st["rfiles"] == 4 and st["rsubdirs"] == 1
        assert st["rbytes"] >= 2048
        # clear the quota: writes flow again
        await a.set_quota("/q")
        b._quota_cache.clear()
        await b.write("/q/big", b"y" * 8192)
        await c.stop()

    run(t())


def test_quota_nested_realms():
    """A deeper realm with a tighter limit wins for paths under it;
    the outer realm still governs siblings."""
    import ceph_tpu.services.fs as fslib

    async def t():
        c, mds, a, b = await make()
        await a.mkdir("/outer")
        await a.mkdir("/outer/inner")
        await a.set_quota("/outer", max_files=10)
        await a.set_quota("/outer/inner", max_files=1)
        await a.create("/outer/inner/one")
        with pytest.raises(fslib.QuotaExceeded):
            await a.create("/outer/inner/two")
        # sibling under the outer realm only: fine
        for i in range(3):
            await a.create(f"/outer/s{i}")
        q = await a.get_quota("/outer/inner/one")
        assert q["realm"] == "/outer/inner"
        await c.stop()

    run(t())
