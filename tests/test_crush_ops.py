"""Bit-exactness of the JAX straw2 kernels vs the C++ host reference.

The native core is the oracle (same role as the reference's C
src/crush/mapper.c); every device op must match it exactly — placement
is an interoperability contract, not an approximation.
"""
import numpy as np
import pytest

from ceph_tpu import native
from ceph_tpu.ops import crush


def test_hash32_parity(rng):
    a = rng.integers(0, 2**32, 4096, dtype=np.uint32)
    b = rng.integers(0, 2**32, 4096, dtype=np.uint32)
    c = rng.integers(0, 2**32, 4096, dtype=np.uint32)
    got2 = np.asarray(crush.hash32_2(a, b))
    got3 = np.asarray(crush.hash32_3(a, b, c))
    for i in range(0, 4096, 97):
        assert int(got2[i]) == native.crush_hash32_2(int(a[i]), int(b[i]))
        assert int(got3[i]) == native.crush_hash32_3(
            int(a[i]), int(b[i]), int(c[i])
        )


def test_crush_ln_full_domain():
    """All 2^16 inputs — the whole domain, no sampling."""
    u = np.arange(1 << 16, dtype=np.uint32)
    got = np.asarray(crush.crush_ln(u))
    want = np.array([native.crush_ln(int(v)) for v in u], dtype=np.int64)
    np.testing.assert_array_equal(got, want)


def test_straw2_draw_parity(rng):
    x = rng.integers(0, 2**32, 512, dtype=np.uint32)
    ids = rng.integers(0, 2**32, 512, dtype=np.uint32)
    r = rng.integers(0, 16, 512, dtype=np.uint32)
    w = rng.integers(0, 2**20, 512, dtype=np.uint32)
    w[::17] = 0  # zero-weight items can never win
    got = np.asarray(crush.straw2_draw(x, ids, r, w))
    for i in range(512):
        assert int(got[i]) == native.straw2_draw(
            int(x[i]), int(ids[i]), int(r[i]), int(w[i])
        ), (x[i], ids[i], r[i], w[i])


@pytest.mark.parametrize("n_items", [1, 7, 64, 1000])
def test_straw2_bulk_parity(rng, n_items):
    items = np.arange(n_items, dtype=np.int32)
    weights = rng.integers(1, 0x40000, n_items, dtype=np.uint32)
    if n_items > 3:
        weights[3] = 0
    xs = rng.integers(0, 2**32, 20_000, dtype=np.uint32)
    got = crush.straw2_bulk(items, weights, xs, r=2)
    want = native.straw2_bulk(items, weights, xs, r=2)
    np.testing.assert_array_equal(got, want)


def test_straw2_distribution(rng):
    """Sanity: selections follow weights (straw2's defining property)."""
    items = np.arange(4, dtype=np.int32)
    weights = (np.array([1, 2, 3, 4]) * 0x10000).astype(np.uint32)
    xs = rng.integers(0, 2**32, 100_000, dtype=np.uint32)
    got = crush.straw2_bulk(items, weights, xs)
    counts = np.bincount(got, minlength=4)
    frac = counts / counts.sum()
    np.testing.assert_allclose(frac, np.array([1, 2, 3, 4]) / 10, atol=0.01)


def test_x64_does_not_leak_default_dtypes():
    """crush enables jax x64; other kernels pin dtypes explicitly."""
    import jax.numpy as jnp

    assert jnp.asarray(np.zeros(3, np.uint32)).dtype == jnp.uint32


def test_lut_nogather_bit_exact():
    """The TPU gather-free LUT path equals the gather path (and thus the
    C host core) for every 16-bit input."""
    import jax
    import jax.numpy as jnp

    from ceph_tpu.ops import crush as crush_ops

    u = jnp.asarray(np.arange(65536, dtype=np.uint32))
    try:
        crush_ops.LUT_USE_GATHER = False
        with crush_ops.enable_x64():
            nogather = np.asarray(jax.jit(crush_ops.crush_ln)(u))
        crush_ops.LUT_USE_GATHER = True
        with crush_ops.enable_x64():
            gather = np.asarray(jax.jit(crush_ops.crush_ln)(u))
    finally:
        crush_ops.LUT_USE_GATHER = None
    np.testing.assert_array_equal(nogather, gather)


def test_div_u48_exact_corner_lattice():
    """The float-reciprocal division replacing emulated-int64 `//`
    (round-3 verdict #9) must be EXACT over its whole domain:
    n in [0, 2^48], w in [1, 2^32)."""
    import numpy as np

    from ceph_tpu.ops import crush as crush_ops

    ns = []
    for base in (0, 1, 2, 0xFFFF, 0x10000, 2**24, 2**25, 2**26,
                 2**32 - 1, 2**32, 2**40, 2**47, 2**48):
        for d in (-2, -1, 0, 1, 2):
            v = base + d
            if 0 <= v <= 2**48:
                ns.append(v)
    ws = []
    for base in (1, 2, 3, 5, 7, 0xFFFF, 0x10000, 0x10001, 2**24,
                 2**31 - 1, 2**31, 2**32 - 1):
        for d in (-1, 0, 1):
            v = base + d
            if 1 <= v < 2**32:
                ws.append(v)
    rng = np.random.default_rng(99)
    ns += list(rng.integers(0, 2**48 + 1, 4000, dtype=np.int64))
    ws += list(rng.integers(1, 2**32, 4000, dtype=np.int64))
    n_arr = np.array([n for n in ns for _ in range(len(ws))][:50000],
                     dtype=np.int64)
    w_arr = np.array((ws * (len(n_arr) // len(ws) + 1))[:len(n_arr)],
                     dtype=np.int64)

    import jax
    import jax.numpy as jnp

    with crush_ops.enable_x64():
        got = np.asarray(jax.jit(crush_ops._div_u48)(
            jnp.asarray(n_arr), jnp.asarray(w_arr)))
    want = n_arr // w_arr
    bad = got != want
    assert not bad.any(), (
        f"{bad.sum()} mismatches, first: n={n_arr[bad][0]} "
        f"w={w_arr[bad][0]} got={got[bad][0]} want={want[bad][0]}")
