"""Flagship pipeline: write/repair steps, graft entry, mesh dry run."""
import numpy as np
import pytest

from ceph_tpu import native
from ceph_tpu.models import datapath
from ceph_tpu.ops import rs


@pytest.fixture(scope="module")
def params():
    return datapath.ECParams(k=4, m=2, chunk_bytes=1024)


def test_write_step_matches_host(params, rng):
    data_u8 = rng.integers(0, 256, (3, params.k, params.chunk_bytes), np.uint8)
    parity, crcs = datapath.jit_write_step(params)(rs.pack_u32(data_u8))
    parity = np.asarray(parity)
    crcs = np.asarray(crcs)
    for s in range(3):
        want_parity = native.rs_encode(params.matrix, data_u8[s])
        np.testing.assert_array_equal(rs.unpack_u32(parity[s]), want_parity)
        all_chunks = np.concatenate([data_u8[s], want_parity], axis=0)
        for c in range(params.k + params.m):
            assert int(crcs[s, c]) == native.crc32c(all_chunks[c])


def test_repair_step_roundtrip(params, rng):
    data_u8 = rng.integers(0, 256, (2, params.k, params.chunk_bytes), np.uint8)
    data = rs.pack_u32(data_u8)
    parity, _ = datapath.jit_write_step(params)(data)
    present = (0, 2, 4, 5)  # lost data chunks 1 and 3
    surviving = np.concatenate(
        [np.asarray(data)[:, [0, 2], :], np.asarray(parity)[:, [0, 1], :]], axis=1
    )
    decoded, crcs = datapath.jit_repair_step(params, present)(surviving)
    np.testing.assert_array_equal(
        rs.unpack_u32(np.asarray(decoded)), data_u8
    )
    assert np.asarray(crcs).shape == (2, params.k)


def test_graft_entry_compiles():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    import jax

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)


def test_dryrun_multichip_8():
    import sys

    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g

    g.dryrun_multichip(8)
