"""cls object class tests: lock/refcount/version over a live cluster
(src/cls test roles)."""
import asyncio

import pytest

from ceph_tpu.cluster.client import ObjectOperation
from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.utils import denc


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=3)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="p", size=3, pg_num=4, crush_rule=0)
    )
    await c.wait_active(20)
    return c


def lock_input(name, ltype, owner, cookie):
    return (denc.enc_str(name) + denc.enc_str(ltype)
            + denc.enc_str(owner) + denc.enc_str(cookie))


def unlock_input(name, owner, cookie):
    return denc.enc_str(name) + denc.enc_str(owner) + denc.enc_str(cookie)


def test_cls_lock_exclusive_and_shared():
    async def t():
        c = await make()
        cl = c.client
        await cl.write_full(1, "o", b"guarded")
        await cl.execute(1, "o", "lock", "lock",
                         lock_input("L", "exclusive", "client.a", "c1"))
        # a second exclusive locker bounces with EBUSY (-16)
        with pytest.raises(IOError, match="-16"):
            await cl.execute(1, "o", "lock", "lock",
                             lock_input("L", "exclusive", "client.b",
                                        "c2"))
        # re-entrant grant for the same owner+cookie
        await cl.execute(1, "o", "lock", "lock",
                         lock_input("L", "exclusive", "client.a", "c1"))
        await cl.execute(1, "o", "lock", "unlock",
                         unlock_input("L", "client.a", "c1"))
        # shared locks coexist
        await cl.execute(1, "o", "lock", "lock",
                         lock_input("L", "shared", "client.a", "c1"))
        await cl.execute(1, "o", "lock", "lock",
                         lock_input("L", "shared", "client.b", "c2"))
        with pytest.raises(IOError, match="-16"):
            await cl.execute(1, "o", "lock", "lock",
                             lock_input("L", "exclusive", "client.x",
                                        "c9"))
        # break client.b's locks by owner
        await cl.execute(1, "o", "lock", "break_lock",
                         denc.enc_str("L") + denc.enc_str("client.b"))
        info = await cl.execute(1, "o", "lock", "get_info",
                                denc.enc_str("L"))
        ltype, _off = denc.dec_str(info, 0)
        assert ltype == "shared"
        await c.stop()

    run(t())


def test_cls_refcount_removes_on_last_put():
    async def t():
        c = await make()
        cl = c.client
        await cl.write_full(1, "blob", b"shared-data")
        await cl.execute(1, "blob", "refcount", "get", denc.enc_str("t1"))
        await cl.execute(1, "blob", "refcount", "get", denc.enc_str("t2"))
        raw = await cl.execute(1, "blob", "refcount", "read")
        tags, _ = denc.dec_list(raw, 0, denc.dec_str)
        assert sorted(tags) == ["t1", "t2"]
        await cl.execute(1, "blob", "refcount", "put", denc.enc_str("t1"))
        assert await cl.read(1, "blob") == b"shared-data"  # still alive
        await cl.execute(1, "blob", "refcount", "put", denc.enc_str("t2"))
        with pytest.raises(KeyError):
            await cl.read(1, "blob")  # last ref dropped -> removed
        await c.stop()

    run(t())


def test_cls_version_gate_in_compound_op():
    async def t():
        c = await make()
        cl = c.client
        await cl.write_full(1, "doc", b"v0")
        await cl.execute(1, "doc", "version", "set", denc.enc_u64(7))
        # guarded update: succeeds when the version matches...
        op = (ObjectOperation()
              .call("version", "check_eq", denc.enc_u64(7))
              .write_full(b"v1")
              .call("version", "inc"))
        await cl.operate(1, "doc", op)
        assert await cl.read(1, "doc") == b"v1"
        raw = await cl.execute(1, "doc", "version", "read")
        assert denc.dec_u64(raw, 0)[0] == 8
        # ...and the whole compound aborts when it does not
        bad = (ObjectOperation()
               .call("version", "check_eq", denc.enc_u64(7))
               .write_full(b"SHOULD NOT LAND"))
        with pytest.raises(IOError, match="-125"):
            await cl.operate(1, "doc", bad)
        assert await cl.read(1, "doc") == b"v1"
        await c.stop()

    run(t())


def test_unknown_class_method():
    async def t():
        c = await make()
        await c.client.write_full(1, "o", b"x")
        with pytest.raises(IOError, match="-95"):
            await c.client.execute(1, "o", "nope", "method")
        await c.stop()

    run(t())
