"""GF(2^8) host math: field axioms, matrix construction, inversion."""
import numpy as np
import pytest

from ceph_tpu.ops import gf8


def test_field_axioms_spot():
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(1, 256, 3))
        assert gf8.gf_mul(a, b) == gf8.gf_mul(b, a)
        assert gf8.gf_mul(a, gf8.gf_mul(b, c)) == gf8.gf_mul(gf8.gf_mul(a, b), c)
        # distributivity over XOR (field addition)
        assert gf8.gf_mul(a, b ^ c) == gf8.gf_mul(a, b) ^ gf8.gf_mul(a, c)
        assert gf8.gf_mul(a, gf8.gf_inv(a)) == 1


def test_exp_log_roundtrip():
    exp, log = gf8._tables()
    for v in range(1, 256):
        assert exp[log[v]] == v
    # primitive element generates the full multiplicative group
    assert len(set(exp[:255].tolist())) == 255


def test_mul_table_matches_scalar():
    t = gf8.mul_table()
    rng = np.random.default_rng(1)
    for _ in range(100):
        a, b = (int(x) for x in rng.integers(0, 256, 2))
        assert t[a, b] == gf8.gf_mul(a, b)


@pytest.mark.parametrize("k,m", [(2, 1), (3, 2), (4, 2), (8, 3), (6, 3), (10, 4)])
def test_vandermonde_systematic_mds(k, m):
    gen = gf8.vandermonde_rs_matrix(k, m)
    assert gen.shape == (m, k)
    # MDS: every square submatrix formed by choosing any k rows of
    # [I; gen] must be invertible -> decode matrix exists for every
    # erasure pattern of size <= m.
    import itertools

    for present in itertools.combinations(range(k + m), k):
        r = gf8.decode_matrix(gen, k, list(present))
        # verify R actually inverts the submatrix
        sub = np.zeros((k, k), dtype=np.uint8)
        for row, idx in enumerate(sorted(present)):
            sub[row] = (np.eye(k, dtype=np.uint8)[idx] if idx < k else gen[idx - k])
        assert (gf8.gf_matmul(r, sub) == np.eye(k, dtype=np.uint8)).all()


@pytest.mark.parametrize("k,m", [(4, 2), (8, 3)])
def test_cauchy_mds(k, m):
    import itertools

    gen = gf8.cauchy_rs_matrix(k, m)
    for present in itertools.combinations(range(k + m), k):
        gf8.decode_matrix(gen, k, list(present))  # raises if singular


def test_matrix_inverse_random(rng):
    for n in (1, 2, 5, 8):
        while True:
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = gf8.gf_mat_inv(m)
                break
            except np.linalg.LinAlgError:
                continue
        assert (gf8.gf_matmul(inv, m) == np.eye(n, dtype=np.uint8)).all()
