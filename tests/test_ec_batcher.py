"""ECBatcher: cross-tick coalescing, fused encode+CRC, batched decode.

Unit tier drives the batcher directly (flush policy, failure fan-out,
bucket identity, bit-exactness of the fused CRCs and the stacked-matrix
decode). The cluster tier proves the acceptance shape: under concurrent
writers with the coalescing knobs on and CEPH_TPU_EC_ENGINE=device, the
mean stripes-per-batch beats the single-tick baseline by >= 4x and the
write path performs NO separate host CRC pass over encoded cells.
"""
import asyncio
import time

import numpy as np
import pytest

from ceph_tpu import native
from ceph_tpu.cluster.ecbatch import ECBatcher, codec_profile_key
from ceph_tpu.ec import load_codec
from ceph_tpu.ops import gf8
from ceph_tpu.utils import config as cfg
from ceph_tpu.utils.perf import PerfCounters

DEV_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2", "backend": "device"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


def make_perf() -> PerfCounters:
    perf = PerfCounters("test")
    ECBatcher.declare_counters(perf)
    return perf


def make_conf(**overrides) -> cfg.ConfigProxy:
    conf = cfg.proxy()
    conf.apply(overrides)
    return conf


def rand_cells(b: int, k: int = 3, su: int = 256,
               seed: int = 0) -> np.ndarray:
    return np.random.default_rng(seed).integers(
        0, 256, (b, k, su), dtype=np.uint8)


def host_parity(codec, cells: np.ndarray) -> np.ndarray:
    """(B, k, su) -> (B, m, su) via the numpy GF reference."""
    b, k, su = cells.shape
    flat = np.ascontiguousarray(cells.transpose(1, 0, 2)).reshape(k, -1)
    par = gf8.gf_matmul(codec.matrix, flat)
    return np.ascontiguousarray(
        par.reshape(codec.m, b, su).transpose(1, 0, 2))


# ------------------------------------------------------------ unit tier


def test_fused_crcs_match_native_bit_for_bit():
    """Device-path CRCs come back from the fused dispatch and must
    equal native.crc32c over every data AND parity cell."""
    codec = load_codec(dict(DEV_PROFILE))
    perf = make_perf()

    async def t():
        batcher = ECBatcher(perf)
        cells = rand_cells(5, seed=1)
        parity, crcs = await batcher.encode_cells(codec, cells)
        assert crcs is not None and crcs.shape == (5, 5)
        assert (parity == host_parity(codec, cells)).all()
        every = np.concatenate([cells, parity], axis=1)  # (5, k+m, su)
        for b in range(5):
            for j in range(5):
                want = native.crc32c(np.ascontiguousarray(every[b, j]))
                assert int(crcs[b, j]) == want

    run(t())
    assert perf.dump()["ec_batches"] == 1


def test_host_engine_returns_no_crcs():
    """The host engine keeps its two-pass shape: parity only, CRCs stay
    the caller's separate native pass (engine economics unchanged)."""
    codec = load_codec({**DEV_PROFILE, "backend": "host"})

    async def t():
        batcher = ECBatcher()
        cells = rand_cells(4, seed=2)
        parity, crcs = await batcher.encode_cells(codec, cells)
        assert crcs is None
        assert (parity == host_parity(codec, cells)).all()

    run(t())


@pytest.mark.parametrize("backend", ["device", "host"])
def test_batched_decode_matches_codec_decode(backend):
    """decode_cells must agree with per-object codec.decode for data
    rows AND for a wanted parity row (stacked recovery matrix)."""
    codec = load_codec({**DEV_PROFILE, "backend": backend})

    async def t():
        batcher = ECBatcher()
        cells = rand_cells(6, seed=3)
        parity, _ = await batcher.encode_cells(codec, cells)
        every = np.concatenate([cells, parity], axis=1)
        # lose data shard 1 and parity shard 3: survivors 0, 2, 4
        present = (0, 2, 4)
        surv = np.ascontiguousarray(every[:, list(present), :])
        out = await batcher.decode_cells(codec, present, (0, 1, 2, 3),
                                         surv)
        assert (out[:, :3, :] == cells).all()
        assert (out[:, 3, :] == every[:, 3, :]).all()
        # cross-check one object against the scalar codec.decode
        arrs = {p: every[0, p].copy() for p in present}
        ref = codec.decode([1], arrs)
        assert (out[0, 1, :] == ref[1]).all()

    run(t())


def test_cross_tick_submissions_merge_into_one_batch():
    """With a batch window armed, stripes submitted on DIFFERENT
    reactor ticks coalesce into one dispatch."""
    codec = load_codec(dict(DEV_PROFILE))
    perf = make_perf()
    conf = make_conf(osd_ec_batch_window=0.2,
                     osd_ec_batch_target_stripes=2)

    async def t():
        batcher = ECBatcher(perf, conf=conf, idle_probe=lambda: False)
        t1 = asyncio.ensure_future(
            batcher.encode_cells(codec, rand_cells(1, seed=4)))
        await asyncio.sleep(0.01)  # a later tick, window still open
        t2 = asyncio.ensure_future(
            batcher.encode_cells(codec, rand_cells(1, seed=5)))
        await asyncio.gather(t1, t2)

    run(t())
    d = perf.dump()
    assert d["ec_batches"] == 1
    assert d["ec_batch_stripes"]["sum"] == 2
    assert d["ec_flush_size"] == 1
    assert d["ec_queue_wait_us"]["count"] == 2


def test_deadline_flush_fires_on_sparse_queue():
    """A lone stripe with a busy op queue (idle_probe False) waits out
    the window, then the deadline flushes it."""
    codec = load_codec(dict(DEV_PROFILE))
    perf = make_perf()
    conf = make_conf(osd_ec_batch_window=0.05,
                     osd_ec_batch_target_stripes=1000)

    async def t():
        batcher = ECBatcher(perf, conf=conf, idle_probe=lambda: False)
        t0 = time.perf_counter()
        await batcher.encode_cells(codec, rand_cells(1, seed=6))
        assert time.perf_counter() - t0 >= 0.04

    run(t())
    d = perf.dump()
    assert d["ec_flush_deadline"] == 1
    assert d["ec_batches"] == 1


def test_mclock_idle_fast_flush_skips_the_window():
    """When the op scheduler reports idle, nothing else can contribute
    stripes — the batch must NOT wait out the window."""
    codec = load_codec(dict(DEV_PROFILE))
    perf = make_perf()
    conf = make_conf(osd_ec_batch_window=5.0,
                     osd_ec_batch_target_stripes=1000)

    async def t():
        batcher = ECBatcher(perf, conf=conf, idle_probe=lambda: True)
        t0 = time.perf_counter()
        await batcher.encode_cells(codec, rand_cells(1, seed=7))
        assert time.perf_counter() - t0 < 1.0

    run(t())
    assert perf.dump()["ec_flush_fast"] == 1


def test_double_buffer_accumulates_while_in_flight():
    """Stripes arriving while a batch is on the executor accumulate and
    dispatch as ONE drain batch at completion."""
    codec = load_codec(dict(DEV_PROFILE))
    perf = make_perf()
    real = codec.encode_crc_batch

    def slow(data, cell_bytes):
        time.sleep(0.3)
        return real(data, cell_bytes)

    codec.encode_crc_batch = slow

    async def t():
        batcher = ECBatcher(perf)
        first = asyncio.ensure_future(
            batcher.encode_cells(codec, rand_cells(1, seed=8)))
        for _ in range(400):  # wait until batch 1 is ON the executor
            if batcher._inflight:
                break
            await asyncio.sleep(0.005)
        assert batcher._inflight
        rest = [asyncio.ensure_future(
            batcher.encode_cells(codec, rand_cells(1, seed=9 + i)))
            for i in range(3)]
        await asyncio.gather(first, *rest)

    run(t())
    d = perf.dump()
    assert d["ec_batches"] == 2
    assert d["ec_flush_drain"] == 1
    # the drain batch carried all three accumulated stripes
    assert d["ec_batch_stripes"]["sum"] == 4


def test_failure_rejects_every_waiter_exactly_once():
    """A failed dispatch must reject all waiters, count a failure, and
    contribute NOTHING to the throughput counters."""
    codec = load_codec(dict(DEV_PROFILE))
    perf = make_perf()
    codec.encode_crc_batch = lambda data, cell_bytes: (_ for _ in ()).throw(
        RuntimeError("injected"))

    async def t():
        batcher = ECBatcher(perf)
        waits = [asyncio.ensure_future(
            batcher.encode_cells(codec, rand_cells(1, seed=20 + i)))
            for i in range(3)]
        results = await asyncio.gather(*waits, return_exceptions=True)
        assert all(isinstance(r, RuntimeError) for r in results)
        # the bucket is not wedged: a healthy codec encodes fine after
        healthy = load_codec(dict(DEV_PROFILE))
        parity, _ = await batcher.encode_cells(healthy,
                                               rand_cells(1, seed=30))
        assert parity.shape == (1, 2, 256)

    run(t())
    d = perf.dump()
    assert d["ec_batch_failures"] == 1
    assert d["ec_batches"] == 1  # only the healthy dispatch counted
    assert d["ec_batch_stripes"]["sum"] == 1


def test_bucket_key_is_profile_stable_not_id_based():
    """Two codec instances from the same profile share a bucket (and a
    batch); id()-reuse aliasing cannot happen by construction."""
    c1 = load_codec(dict(DEV_PROFILE))
    c2 = load_codec(dict(DEV_PROFILE))
    assert c1 is not c2
    assert codec_profile_key(c1) == codec_profile_key(c2)
    other = load_codec({**DEV_PROFILE, "k": "4"})
    assert codec_profile_key(other) != codec_profile_key(c1)
    perf = make_perf()

    async def t():
        batcher = ECBatcher(perf)
        a = asyncio.ensure_future(
            batcher.encode_cells(c1, rand_cells(1, seed=40)))
        b = asyncio.ensure_future(
            batcher.encode_cells(c2, rand_cells(1, seed=41)))
        (pa, _), (pb, _) = await asyncio.gather(a, b)
        assert (pa == host_parity(c1, rand_cells(1, seed=40))).all()
        assert (pb == host_parity(c2, rand_cells(1, seed=41))).all()

    run(t())
    assert perf.dump()["ec_batches"] == 1


# --------------------------------------------------------- cluster tier


def test_ec_read_is_atomic_against_concurrent_write():
    """With ops dispatched concurrently (osd_op_concurrency > 1), an EC
    read racing a write's multi-shard fanout must never return a torn
    mix of old and new cells — reads serialize on the PG lock."""
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.ec import rs_plugin
    from ceph_tpu.placement.osdmap import Pool

    old = b"A" * 24576  # two full stripes at k=3, su=4096
    new = b"B" * 24576

    async def t():
        c = TestCluster(n_osds=5)
        await c.start()
        await c.client.create_pool(Pool(
            id=2, name="ec", size=5, min_size=3, pg_num=8, crush_rule=1,
            type="erasure", ec_profile={"plugin": "rs_tpu", "k": "3",
                                        "m": "2", "backend": "device"}))
        await c.wait_active(30)
        await c.client.write_full(2, "obj", old)
        # slow the encode so the overwrite sits mid-fanout while the
        # read races it
        real = rs_plugin.RSCodec.encode_crc_batch

        def slow(self, data, cell_bytes):
            time.sleep(0.15)
            return real(self, data, cell_bytes)

        rs_plugin.RSCodec.encode_crc_batch = slow
        try:
            w = asyncio.ensure_future(c.client.write_full(2, "obj", new))
            await asyncio.sleep(0.05)
            got = await c.client.read(2, "obj")
            await w
        finally:
            rs_plugin.RSCodec.encode_crc_batch = real
        assert got in (old, new), "torn EC read: mixed old/new cells"
        assert await c.client.read(2, "obj") == new
        await c.stop()

    run(t())


def test_cluster_coalescing_beats_single_tick_baseline(monkeypatch):
    """Acceptance: with CEPH_TPU_EC_ENGINE=device, concurrent writers
    and the coalescing knobs on, mean stripes_per_batch >= 4x the
    single-tick baseline — and the write path performs no separate
    host CRC pass over encoded cells (CRCs ride the fused dispatch)."""
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.ec import engine
    from ceph_tpu.placement.osdmap import Pool

    import jax.numpy as jnp

    from ceph_tpu.ops import rs

    monkeypatch.setenv("CEPH_TPU_EC_ENGINE", "device")
    engine.reset_probe()

    # pre-warm the fused kernel at every pow2 batch shape the burst
    # can hit: first-use compiles inside the timed burst otherwise
    # serialize the whole cluster on this box's few cores
    warm = rs.jit_encode_with_crcs(gf8.vandermonde_rs_matrix(3, 2), 4096)
    for b in (1, 2, 4, 8, 16, 32):
        warm(jnp.zeros((b, 3, 1024), jnp.uint32))

    crc_calls = {"n": 0}
    real_crc_batch = native.crc32c_batch

    def counting_crc_batch(*a, **kw):
        crc_calls["n"] += 1
        return real_crc_batch(*a, **kw)

    async def run_one(osd_conf: dict, writers: int,
                      objs: int) -> float:
        c = TestCluster(n_osds=5, osd_conf=osd_conf)
        await c.start()
        c.client.op_timeout = 60.0
        # many PGs: writes serialize per-PG (the reference ordering
        # contract), so the count of concurrently-busy PGs per OSD
        # bounds how many stripes can park at once — the real knob
        # behind coalescing depth
        await c.client.create_pool(Pool(
            id=2, name="ec", size=5, min_size=3, pg_num=128,
            crush_rule=1, type="erasure",
            ec_profile={"plugin": "rs_tpu", "k": "3", "m": "2",
                        "backend": "auto"}))
        await c.wait_active(60)
        # exactly one stripe per object: width = k * stripe_unit
        payload = np.random.default_rng(13).integers(
            0, 256, 3 * 4096, dtype=np.uint8).tobytes()

        async def writer(w: int) -> None:
            for i in range(objs):
                await c.client.write_full(2, f"o{w}-{i}", payload)

        await asyncio.gather(*(writer(w) for w in range(writers)))
        batches = stripes = 0
        for osd in c.osds:
            d = osd.perf.dump()
            batches += int(d["ec_batches"])
            stripes += int(d["ec_batch_stripes"]["sum"])
        await c.stop()
        assert stripes == writers * objs
        return stripes / max(batches, 1)

    async def t():
        base = await run_one(
            {"osd_op_concurrency": 1, "osd_ec_batch_window": 0.0,
             "osd_ec_batch_target_stripes": 0},
            writers=4, objs=3)
        assert base == pytest.approx(1.0), base  # single-tick shape
        monkeypatch.setattr(native, "crc32c_batch", counting_crc_batch)
        try:
            coalesced = await run_one(
                {"osd_op_concurrency": 128,
                 "osd_ec_batch_window": 0.05,
                 "osd_ec_batch_target_stripes": 12},
                writers=96, objs=2)
        finally:
            monkeypatch.setattr(native, "crc32c_batch", real_crc_batch)
        # no separate host CRC pass anywhere in the device write path
        assert crc_calls["n"] == 0
        assert coalesced >= 4 * base, (coalesced, base)

    try:
        run(t())
    finally:
        engine.reset_probe()
