"""Multi-process cluster tier: mon + OSDs as separate OS processes over
real TCP sockets (the vstart.sh + qa/standalone role — VERDICT r3 #1).

What this tier proves that the in-process tier cannot: the wire is real
(kernel sockets, process isolation), kill -9 is a REAL crash (the
process dies mid-whatever, no cooperative cleanup), and revival is a
cold daemon start that must recover from its on-disk store.
"""
import asyncio
import os
import signal

import pytest

from ceph_tpu.cluster.procstart import ProcCluster
from ceph_tpu.placement.osdmap import Pool


def run(coro, timeout=480):
    asyncio.run(asyncio.wait_for(coro, timeout))


async def wait_quorum(client, n_mons: int, deadline_s: float = 120.0,
                      require_rank: int | None = None,
                      strict: bool = False) -> None:
    """Deadline-poll quorum_status until n_mons ranks (optionally a
    specific one) sit in the quorum. Under full-suite load mon
    processes stall behind jax-import compiles, so a paxos commit
    issued on an unformed quorum times out — the long-standing mon
    flake. ``strict`` asserts at the deadline; otherwise the caller's
    own retries get their chance."""
    import json as _json
    import time as _time

    deadline = _time.monotonic() + deadline_s
    while True:
        try:
            _, _, outb = await client.mon_command(["quorum_status"])
            q = _json.loads(outb)["quorum"]
            if len(q) == n_mons and (require_rank is None
                                     or require_rank in q):
                return
        except (IOError, asyncio.TimeoutError):
            pass
        if _time.monotonic() >= deadline:
            assert not strict, \
                f"quorum of {n_mons} (rank {require_rank}) never formed"
            return
        await asyncio.sleep(0.25)


async def make(tmp, n_osds=3, n_mons=1, auth=False, secure=False):
    c = ProcCluster(str(tmp), n_osds=n_osds, n_mons=n_mons,
                    auth=auth, secure=secure)
    await c.start()
    if n_mons > 1:
        # ProcCluster.start's quorum wait is bounded best-effort
        # (30 s): make sure the quorum actually FORMED before the
        # first pool create issues a paxos commit
        await wait_quorum(c.client, n_mons)
    await c.client.create_pool(
        Pool(id=1, name="p", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(120)
    return c


def test_multiprocess_io_roundtrip(tmp_path):
    """Write/read through real sockets: client process -> OSD
    processes, replicated pool."""
    async def t():
        c = await make(tmp_path)
        try:
            payload = {f"obj{i}": os.urandom(2000 + 37 * i)
                       for i in range(12)}
            for name, data in payload.items():
                await c.client.write_full(1, name, data)
            for name, data in payload.items():
                assert await c.client.read(1, name) == data
            listed = await c.client.list_objects(1)
            assert sorted(listed) == sorted(
                n.encode() for n in payload)
        finally:
            await c.stop()

    run(t())


def test_multiprocess_kill9_and_revive(tmp_path):
    """kill -9 an OSD *process*; the mon marks it down, IO keeps
    working degraded; a cold restart mounts the same store and the
    cluster heals with no lost data."""
    async def t():
        c = await make(tmp_path)
        try:
            data = {f"k{i}": os.urandom(4096) for i in range(10)}
            for n, d in data.items():
                await c.client.write_full(1, n, d)
            c.kill_osd(1, signal.SIGKILL)
            await c.wait_down(1, 80)
            # degraded reads AND writes still serve
            for n, d in data.items():
                assert await c.client.read(1, n) == d
            await c.client.write_full(1, "while-down", b"degraded")
            await c.revive_osd(1)
            await c.wait_up(1, 80)
            await c.wait_active(90)
            for n, d in data.items():
                assert await c.client.read(1, n) == d
            assert await c.client.read(1, "while-down") == b"degraded"
        finally:
            await c.stop()

    run(t())


def test_multiprocess_full_restart_durability(tmp_path):
    """Stop EVERY process; restart the whole cluster from disk; the
    pool and its objects survive (the durable-store + mon-store
    cold-boot arc, end to end over processes)."""
    async def t():
        c = await make(tmp_path)
        await c.client.write_full(1, "persist", b"x" * 10_000)
        await c.stop()

        c2 = ProcCluster(str(tmp_path), n_osds=3, n_mons=1)
        await c2.start()
        try:
            await c2.wait_active(120)
            assert await c2.client.read(1, "persist") == b"x" * 10_000
            await c2.client.write_full(1, "again", b"second life")
            assert await c2.client.read(1, "again") == b"second life"
        finally:
            await c2.stop()

    run(t())


def test_multiprocess_cephx_secure(tmp_path):
    """The same tier with cephx auth + AES-GCM secure wire on."""
    pytest.importorskip("cryptography")
    async def t():
        c = await make(tmp_path, auth=True, secure=True)
        try:
            await c.client.write_full(1, "sec", b"over-encrypted-tcp")
            assert await c.client.read(1, "sec") == b"over-encrypted-tcp"
        finally:
            await c.stop()

    run(t())


def test_multiprocess_mon_leader_kill9(tmp_path):
    """Paxos over real sockets (VERDICT r4 #3): kill -9 the LEADER mon
    process mid-write-stream. The quorum re-elects, the public "mon"
    book alias hands over, in-flight IO completes, failure adjudication
    (an OSD kill) still commits new map epochs, and the revived mon
    catches up far enough to carry a later majority."""
    async def t():
        c = ProcCluster(str(tmp_path), n_osds=3, n_mons=3)
        await c.start()
        try:
            # start()'s quorum wait is bounded best-effort (30 s):
            # under full-suite load mon boots stall past it, and the
            # pool create below then issues a paxos commit against an
            # UNFORMED quorum (the diagnosed mon-flake root) — make()
            # carries this guard, direct constructions need it too
            await wait_quorum(c.client, 3)
            await c.client.create_pool(
                Pool(id=1, name="p", size=3, pg_num=8, crush_rule=0))
            await c.wait_active(90)
            for i in range(5):
                await c.client.write_full(1, f"pre{i}", b"x" * 4096)

            leader = c.leader_mon_rank()
            c.kill_mon(leader, signal.SIGKILL)
            # client IO rides OSDs directly: the stream must keep
            # landing while the survivors elect
            for i in range(5):
                await c.client.write_full(1, f"mid{i}", b"y" * 4096)
            # a map MUTATION needs a live quorum: kill an OSD and wait
            # for the down mark (heartbeat adjudication -> Paxos commit
            # by the NEW leader)
            c.kill_osd(2, signal.SIGKILL)
            await c.wait_down(2, 60)
            new_leader = c.leader_mon_rank()
            assert new_leader != leader
            for i in range(5):
                assert await c.client.read(1, f"pre{i}") == b"x" * 4096
                assert await c.client.read(1, f"mid{i}") == b"y" * 4096

            await c.revive_osd(2)
            await c.wait_up(2, 60)
            await c.wait_active(120)

            # revived mon catches up from its durable store + collect
            # round: bring the old leader back, then kill the CURRENT
            # leader — the next majority (2/3) must include the revived
            # rank, so a successful quorum commit proves catch-up.
            # Deadline-poll the revived rank INTO the quorum before the
            # kill (a fixed sleep flaked under suite load: killing the
            # leader while the revived mon was still syncing left no
            # electable majority and the pool create timed out — the
            # long-standing "mon flake")
            await c.revive_mon(leader)
            await wait_quorum(c.client, 3, 90, require_rank=leader,
                              strict=True)
            current = c.leader_mon_rank()
            c.kill_mon(current, signal.SIGKILL)
            await c.client.create_pool(
                Pool(id=2, name="after", size=2, pg_num=4, crush_rule=0))
            await c.client.write_full(2, "obj", b"post-failover")
            assert await c.client.read(2, "obj") == b"post-failover"
        finally:
            await c.stop()

    run(t(), timeout=420)


def test_multiprocess_mon_peon_kill9(tmp_path):
    """kill -9 a PEON mon process: the quorum (leader + survivor)
    keeps committing with no election needed."""
    async def t():
        c = ProcCluster(str(tmp_path), n_osds=3, n_mons=3)
        await c.start()
        try:
            # same unformed-quorum guard as make() / leader_kill9
            await wait_quorum(c.client, 3)
            await c.client.create_pool(
                Pool(id=1, name="p", size=3, pg_num=8, crush_rule=0))
            await c.wait_active(90)
            leader = c.leader_mon_rank()
            peon = next(r for r in range(3) if r != leader)
            c.kill_mon(peon, signal.SIGKILL)
            # both plain IO and quorum commits still work on 2/3
            await c.client.write_full(1, "obj", b"peonless")
            assert await c.client.read(1, "obj") == b"peonless"
            await c.client.create_pool(
                Pool(id=2, name="q", size=2, pg_num=4, crush_rule=0))
            await c.client.write_full(2, "obj2", b"committed")
            assert await c.client.read(2, "obj2") == b"committed"
            assert c.leader_mon_rank() == leader
        finally:
            await c.stop()

    run(t(), timeout=300)


def test_multiprocess_entity_auth_blocks_impersonation(tmp_path):
    """Per-entity wire auth (VERDICT r4 #5): a rogue process that holds
    ONLY the shared node key (so it passes the connection handshake)
    must not be able to speak AS "mon" — neither through the API (no
    signing key) nor by forging an envelope signed with the node key
    (receivers verify against the claimed src entity's own key)."""
    async def t():
        import copy

        from ceph_tpu.cluster import messages as M
        from ceph_tpu.cluster.daemon import load_keyring
        from ceph_tpu.msg.auth import KeyServer
        from ceph_tpu.msg.netbus import NetBus, _env_sig
        from ceph_tpu.placement import encoding as menc

        c = await make(tmp_path, auth=True)
        try:
            await c.client.write_full(1, "legit", b"ok")

            full_keys = load_keyring(c.book)
            rogue_keys = KeyServer()
            rogue_keys.add("node", full_keys.get("node"))
            rogue = NetBus(c.book, keys=rogue_keys)
            await rogue.start()
            try:
                # (a) the honest API cannot even sign as the mon
                with pytest.raises(Exception):
                    await rogue.send("mon", "osd.0",
                                     M.MPing(osd=0, epoch=1))
                # (b) forged envelope: a poisoned full map (huge epoch,
                # osd.1 marked down) signed with the NODE key under
                # src="mon" — the OSD must drop it at the door
                poisoned = copy.deepcopy(c.client.osdmap)
                poisoned.epoch += 50
                poisoned.osds[1].up = False
                msg = M.MOSDMapMsg(
                    full=menc.encode_osdmap(poisoned),
                    incrementals=[], epoch=poisoned.epoch)
                payload = msg.encode()
                env = M.MEnvelope(
                    src="mon", dst="osd.0", mtype=M.MOSDMapMsg.TYPE,
                    payload=payload,
                    sig=_env_sig(full_keys.get("node"), "mon", "osd.0",
                                 M.MOSDMapMsg.TYPE, payload))
                addr = rogue._resolve("osd.0")
                node = f"@{addr[0]}:{addr[1]}"
                rogue._tcp.addrbook[node] = addr
                await rogue._tcp.send(node, env)
                await asyncio.sleep(0.5)
            finally:
                await rogue.close()

            # the cluster never saw the forgery: osd.1 stays up and IO
            # keeps working on sane epochs
            await c.client.write_full(1, "after", b"still-works")
            assert await c.client.read(1, "after") == b"still-works"
            await c._refresh_map()
            assert c.client.osdmap.osds[1].up
            assert c.client.osdmap.epoch < 50
        finally:
            await c.stop()

    run(t())


def test_multiprocess_ec_pool(tmp_path):
    """EC k=2,m=1 pool across OSD processes: encode on the primary's
    process, shard sub-writes over real sockets, degraded read after a
    process kill."""
    async def t():
        c = ProcCluster(str(tmp_path), n_osds=4)
        await c.start()
        try:
            await c.client.create_pool(Pool(
                id=2, name="ec", size=3, min_size=2, pg_num=4,
                crush_rule=1, type="erasure",
                ec_profile={"plugin": "rs_tpu", "k": "2", "m": "1"}))
            await c.wait_active(90)
            blob = os.urandom(40_000)
            await c.client.write_full(2, "ec-obj", blob)
            assert await c.client.read(2, "ec-obj") == blob
            # kill a shard holder; reconstruction serves the read
            pgid = c.client.osdmap.object_to_pg(2, b"ec-obj")
            acting, _ = c.client.osdmap.pg_to_up_acting_osds(pgid)
            c.kill_osd(acting[1], signal.SIGKILL)
            await c.wait_down(acting[1], 40)
            assert await c.client.read(2, "ec-obj") == blob
        finally:
            await c.stop()

    run(t())


def test_multiprocess_mds_kill9_replay(tmp_path):
    """The CephFS metadata daemon as a real OS process: client ops
    over kernel sockets, kill -9 mid-workload, cold restart replays
    the MDLog journal and the namespace survives (the ceph-mds +
    qa fs-recovery role)."""
    async def t():
        from ceph_tpu.services.fs import FSLite
        from ceph_tpu.services.mds import FSClient

        c = await make(tmp_path)
        try:
            await FSLite(c.client, 1).mkfs()
            await c.start_mds(0, pool=1)
            fs = FSClient(c.bus, c.client, 1, name="fsclient.0",
                          timeout=30.0)
            await fs.connect()
            await fs.mkdir("/proj")
            await fs.create("/proj/a")
            await fs.write("/proj/a", b"payload-one")
            assert await fs.read("/proj/a") == b"payload-one"
            # crash-stop the metadata authority mid-stream
            c.kill_mds(0)
            with pytest.raises((OSError, asyncio.TimeoutError)):
                await asyncio.wait_for(fs.mkdir("/proj/lost"), 3)
            # cold restart: journal replay restores the namespace
            await c.revive_mds(0)
            assert sorted(await fs.listdir("/proj")) == ["a"]
            assert await fs.read("/proj/a") == b"payload-one"
            await fs.mkdir("/proj/sub")
            await fs.create("/proj/sub/b")
            await fs.write("/proj/sub/b", b"after-revival")
            assert await fs.read("/proj/sub/b") == b"after-revival"
            # rename spans two dirfrags: the journaled path, over
            # real sockets
            await fs.rename("/proj/sub/b", "/proj/b2")
            assert await fs.read("/proj/b2") == b"after-revival"
            await fs.close()
        finally:
            await c.stop()

    run(t())


def test_multiprocess_multimds_pin_and_cross_rename(tmp_path):
    """TWO MDS ranks as separate OS processes: a client pins a subtree
    to rank 1 (the ceph.dir.pin role), redirects route over real
    sockets, and a cross-subtree rename runs its peer-request link
    half between the two daemon processes."""
    async def t():
        from ceph_tpu.services.fs import FSLite
        from ceph_tpu.services.mds import FSClient

        c = await make(tmp_path)
        try:
            await FSLite(c.client, 1).mkfs()
            await c.start_mds(0, pool=1)
            await c.start_mds(1, pool=1)
            fs = FSClient(c.bus, c.client, 1, name="fsclient.0",
                          timeout=30.0)
            await fs.connect()
            await fs.mkdir("/a")
            await fs.mkdir("/b")
            await fs.set_subtree_pin("/b", 1)
            # ops in both subtrees, including a cold client whose map
            # says rank 0 for everything
            await fs.create("/b/owned-by-1")
            await fs.write("/b/owned-by-1", b"rank1 data")
            fs2 = FSClient(c.bus, c.client, 1, name="fsclient.1",
                           timeout=30.0)
            await fs2.connect()
            assert await fs2.read("/b/owned-by-1") == b"rank1 data"
            # cross-subtree rename: peer_link travels mds.0 -> mds.1
            # over a kernel socket
            await fs.create("/a/f")
            await fs.write("/a/f", b"crossing")
            await fs.rename("/a/f", "/b/f")
            assert await fs2.read("/b/f") == b"crossing"
            assert await fs2.listdir("/a") == []
            # and back the other way (mds.1 -> mds.0)
            await fs2.rename("/b/f", "/a/back")
            assert await fs.read("/a/back") == b"crossing"
            await fs.close()
            await fs2.close()
        finally:
            await c.stop()

    run(t())


def test_multiprocess_mon_command(tmp_path):
    """The `ceph` CLI seam over real sockets: MMonCommand rides
    NetBus to a mon PROCESS (forwarded to the paxos leader when it
    lands on a peon) and mutates the committed map."""
    import json

    import time

    async def t():
        c = await make(tmp_path, n_mons=3)
        try:
            # poll the status digest with a deadline: under full-suite
            # load a mon can answer before every peer joined the
            # quorum / every OSD booted, so a single read races
            # (num_mons came back 2-of-3 in the wild; 90 s: elections
            # among freshly spawned mon processes stall behind suite-
            # load compiles)
            deadline = time.monotonic() + 90
            while True:
                rc, outs, outb = await c.client.mon_command(["status"])
                assert rc == 0
                st = json.loads(outb)
                if (st["osdmap"]["num_up_osds"] == 3
                        and st["monmap"]["num_mons"] == 3):
                    break
                assert time.monotonic() < deadline, st
                await asyncio.sleep(0.25)
            rc, _, outb = await c.client.mon_command(["osd", "tree"])
            assert rc == 0
            rows = [n for n in json.loads(outb) if n["type"] == "osd"]
            assert len(rows) == 3
            # a mutating command commits through paxos quorum
            rc, _, _ = await c.client.mon_command(
                ["osd", "reweight", "2", "0.5"])
            assert rc == 0
            for _ in range(100):
                if (c.client.osdmap is not None
                        and c.client.osdmap.osds[2].weight == 0x8000):
                    break
                await asyncio.sleep(0.1)
            assert c.client.osdmap.osds[2].weight == 0x8000
            # quorum_status names a leader all ranks agree on (same
            # deadline poll: membership may still be converging)
            deadline = time.monotonic() + 90
            while True:
                rc, _, outb = await c.client.mon_command(["quorum_status"])
                q = json.loads(outb)
                if len(q["quorum"]) == 3:
                    break
                assert time.monotonic() < deadline, q
                await asyncio.sleep(0.25)
        finally:
            await c.stop()

    run(t())
