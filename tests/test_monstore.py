"""Durable mon: MonitorDBStore-role persistence on the native kv.

Acceptance (VERDICT r2 item 5): kill all mons+OSDs, restart from disk,
and the cluster converges with its maps, pools, config DB, and epochs
intact — no pool re-creation, no data loss.
"""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.monstore import MonStore
from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2"}


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, 180))
    finally:
        loop.close()


# ------------------------------------------------------------- unit level


def test_monstore_map_roundtrip(tmp_path):
    s = MonStore(str(tmp_path / "mon.kv"))
    s.save_map(b"FULLMAP", 7, b"INC7", 7, next_pool_id=4)
    s.save_map(b"FULLMAP8", 8, b"INC8", 8, next_pool_id=5)
    full, last, history, npool = s.load_map()
    assert full == b"FULLMAP8"
    assert last == 8
    assert history == {7: b"INC7", 8: b"INC8"}
    assert npool == 5
    s.close()
    # reopen: state survives
    s2 = MonStore(str(tmp_path / "mon.kv"))
    assert s2.load_map()[1] == 8
    s2.close()


def test_monstore_paxos_roundtrip(tmp_path):
    s = MonStore(str(tmp_path / "mon.kv"))
    assert s.load_paxos() == (0, 0, 0, None)
    s.save_paxos(103, 105, 105, (105, 9, b"value"))
    assert s.load_paxos() == (103, 105, 105, (105, 9, b"value"))
    s.save_paxos(109, 106, 106, None)
    assert s.load_paxos() == (109, 106, 106, None)
    s.close()


def test_paxos_pn_restore_stays_rank_disjoint(tmp_path):
    """A restarted mon's pn must exceed everything it saw pre-crash AND
    stay on its rank's residue class mod n_mons (global uniqueness)."""
    from ceph_tpu.cluster.paxos_mon import PaxosMon
    from ceph_tpu.msg.messenger import LocalBus

    n_mons = 3
    for rank, promised in ((0, 106), (1, 104), (2, 0)):
        st = MonStore(str(tmp_path / f"m{rank}.kv"))
        st.save_paxos(100 + rank, promised, promised, None)
        st.close()
        m = PaxosMon(LocalBus(), 3, rank=rank, n_mons=n_mons,
                     store=MonStore(str(tmp_path / f"m{rank}.kv")))
        assert m.pn > promised
        assert m.pn % n_mons == (100 + rank) % n_mons
        m.store.close()


def test_monstore_config_roundtrip(tmp_path):
    s = MonStore(str(tmp_path / "mon.kv"))
    s.save_config("osd", "debug_level", "5")
    s.save_config("global", "x", "y")
    assert s.load_config() == {("osd", "debug_level"): "5",
                               ("global", "x"): "y"}
    s.replace_config({("mon", "a"): "b"})
    assert s.load_config() == {("mon", "a"): "b"}
    s.close()


# --------------------------------------------------------- cluster level


def test_full_cluster_restart_keeps_maps(tmp_path):
    data = bytes(np.random.default_rng(0).integers(
        0, 256, 80_000, dtype=np.uint8))
    saved = {}

    async def phase1():
        c = TestCluster(n_osds=5, objectstore="walstore",
                        data_dir=str(tmp_path))
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0))
        await c.client.create_pool(
            Pool(id=2, name="ec", size=5, min_size=3, pg_num=4,
                 crush_rule=1, type="erasure",
                 ec_profile=dict(EC_PROFILE)))
        await c.wait_active(20)
        await c.client.write_full(1, "r", data)
        await c.client.write_full(2, "e", data)
        # a snapshot and a config entry must survive the restart too
        snapid = await c.client.selfmanaged_snap_create(2)
        await c.client.write_full(2, "e", b"after-snap" * 100,
                                  snapc=(snapid, [snapid]))
        saved["snapid"] = snapid
        saved["epoch"] = c.mon.osdmap.epoch
        saved["pools"] = set(c.mon.osdmap.pools)
        await c.stop()

    async def phase2():
        c = TestCluster(n_osds=5, objectstore="walstore",
                        data_dir=str(tmp_path))
        await c.start()
        # the mon recovered its maps: pools exist WITHOUT re-creation,
        # and the epoch continued from where it was
        assert set(c.mon.osdmap.pools) >= saved["pools"]
        assert c.mon.osdmap.epoch >= saved["epoch"]
        assert c.mon.osdmap.pools[2].snap_seq >= saved["snapid"]
        await c.wait_active(30)
        assert await c.client.read(1, "r") == data
        assert await c.client.read(2, "e") == b"after-snap" * 100
        # the pre-snap content still resolves through the clone
        assert await c.client.read(2, "e",
                                   snapid=saved["snapid"]) == data
        await c.stop()

    run(phase1())
    run(phase2())


def test_paxos_mons_restart_with_quorum(tmp_path):
    saved = {}

    async def phase1():
        c = TestCluster(n_osds=4, n_mons=3, objectstore="walstore",
                        data_dir=str(tmp_path))
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0))
        # generous: paxos elections + peering on a loaded single-core
        # box can take far longer than the idle-box 3 s (this test
        # flaked at ~1/3 full-suite runs with tighter budgets)
        await c.wait_active(60)
        await c.client.write_full(1, "obj", b"paxos-durable" * 50)
        saved["epoch"] = c.mon.osdmap.epoch
        await c.stop()

    async def phase2():
        c = TestCluster(n_osds=4, n_mons=3, objectstore="walstore",
                        data_dir=str(tmp_path))
        await c.start()  # waits for quorum
        assert c.mon.osdmap.epoch >= saved["epoch"]
        assert 1 in c.mon.osdmap.pools
        await c.wait_active(60)
        assert await c.client.read(1, "obj") == b"paxos-durable" * 50
        # the recovered cluster still takes writes
        await c.client.write_full(1, "obj2", b"new")
        assert await c.client.read(1, "obj2") == b"new"
        await c.stop()

    run(phase1())
    run(phase2())
