"""S3 ACL tests: the ownership/grant model (rgw_acl.h role) and its
enforcement at the frontend (rgw_op.cc verify_*_permission role)."""
import asyncio

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.rgw import RGWLite, S3Frontend
from ceph_tpu.services.rgw_acl import ALL_USERS, AUTH_USERS, Acl

from test_rgw import _signed_headers, http


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rgw", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c, RGWLite(c.client, 1)


# ------------------------------------------------------------ unit model


def test_acl_model():
    a = Acl("alice", [("bob", "READ")])
    assert a.allows("alice", "WRITE")          # owner: everything
    assert a.allows("alice", "WRITE_ACP")
    assert a.allows("bob", "READ")
    assert not a.allows("bob", "WRITE")
    assert not a.allows("carol", "READ")
    assert not a.allows(None, "READ")          # anonymous
    # groups
    pub = Acl("alice", [(ALL_USERS, "READ")])
    assert pub.allows(None, "READ") and pub.allows("bob", "READ")
    auth = Acl("alice", [(AUTH_USERS, "READ")])
    assert auth.allows("bob", "READ") and not auth.allows(None, "READ")
    # FULL_CONTROL grant implies every permission
    fc = Acl("alice", [("bob", "FULL_CONTROL")])
    for p in ("READ", "WRITE", "READ_ACP", "WRITE_ACP"):
        assert fc.allows("bob", p)
    # unset policy = legacy data: any authenticated principal, never
    # anonymous (the pre-ACL frontend contract)
    unset = Acl("", [])
    assert unset.allows("anyone", "WRITE")
    assert not unset.allows(None, "READ")


def test_acl_coding():
    a = Acl("alice", [("bob", "READ"), (ALL_USERS, "READ"),
                      ("carol", "FULL_CONTROL")])
    assert Acl.parse("alice", a.dump()).grants == a.grants
    b = Acl.from_xml(a.to_xml(), "alice")
    assert b.owner == "alice" and b.grants == a.grants
    # the implicit-owner elision keys on the PERSISTED owner: a body
    # declaring a different owner cannot get its real grant dropped
    spoof = Acl("bob", [("bob", "FULL_CONTROL")])
    parsed = Acl.from_xml(spoof.to_xml(), "alice")
    assert ("bob", "FULL_CONTROL") in parsed.grants
    assert parsed.owner == "alice"
    # canned expansion
    assert Acl.canned("o", "private").grants == []
    assert Acl.canned("o", "public-read").grants == [(ALL_USERS, "READ")]
    assert (ALL_USERS, "WRITE") in Acl.canned(
        "o", "public-read-write").grants
    assert Acl.canned("o", "authenticated-read").grants == \
        [(AUTH_USERS, "READ")]


# ------------------------------------------------------- enforcement


USERS = {"alice": "sk-alice", "bob": "sk-bob"}


async def sreq(host, port, user, method, path, body=b"", extra=None,
               query=""):
    """Signed request through the raw-socket helper."""
    h = _signed_headers(method, path, query, body, host, user,
                        USERS[user])
    h.update(extra or {})
    target = path + (f"?{query}" if query else "")
    return await http(host, port, method, target, body=body, headers=h)


def test_acl_enforcement():
    """Multi-user frontend: ownership gates access; canned ACLs open
    it selectively; per-object ownership holds inside a shared
    bucket; ?acl GET/PUT round-trips grants."""
    async def t():
        c, rgw = await make()
        fe = S3Frontend(rgw, users=dict(USERS))
        host, port = await fe.start()

        # alice creates a private bucket and an object
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/priv")
        assert st == 200
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/priv/k",
                                b"secret")
        assert st == 200
        owner, grants = await rgw.get_bucket_acl("priv")
        assert owner == "alice" and grants == ""

        # bob: no list, no read, no write, no delete-bucket
        st, _h, _b = await sreq(host, port, "bob", "GET", "/priv")
        assert st == 403
        st, _h, _b = await sreq(host, port, "bob", "GET", "/priv/k")
        assert st == 403
        st, _h, _b = await sreq(host, port, "bob", "PUT", "/priv/x",
                                b"nope")
        assert st == 403
        st, _h, _b = await sreq(host, port, "bob", "DELETE", "/priv")
        assert st == 403
        # anonymous: nothing
        st, _h, _b = await http(host, port, "GET", "/priv/k")
        assert st == 403
        st, _h, _b = await http(host, port, "PUT", "/anon-b")
        assert st == 403  # anonymous principals never own buckets

        # canned object ACLs: public-read / authenticated-read
        st, _h, _b = await sreq(host, port, "alice", "PUT",
                                "/priv/pub", b"open",
                                extra={"x-amz-acl": "public-read"})
        assert st == 200
        st, _h, b = await http(host, port, "GET", "/priv/pub")
        assert st == 200 and b == b"open"
        st, _h, _b = await sreq(host, port, "alice", "PUT",
                                "/priv/auth", b"half-open",
                                extra={"x-amz-acl":
                                       "authenticated-read"})
        assert st == 200
        st, _h, b = await sreq(host, port, "bob", "GET", "/priv/auth")
        assert st == 200 and b == b"half-open"
        st, _h, _b = await http(host, port, "GET", "/priv/auth")
        assert st == 403

        # shared bucket: bob may write, but his objects are HIS —
        # the bucket owner holds no implicit read on them (S3)
        st, _h, _b = await sreq(
            host, port, "alice", "PUT", "/shared",
            extra={"x-amz-acl": "public-read-write"})
        assert st == 200
        st, _h, _b = await sreq(host, port, "bob", "PUT", "/shared/b1",
                                b"bobs data")
        assert st == 200
        st, _h, b = await sreq(host, port, "bob", "GET", "/shared/b1")
        assert st == 200 and b == b"bobs data"
        st, _h, _b = await sreq(host, port, "alice", "GET",
                                "/shared/b1")
        assert st == 403
        owner, _g = await rgw.get_object_acl("shared", "b1")
        assert owner == "bob"

        # ?acl round-trip: bob grants alice READ via an XML PUT
        pol = Acl("bob", [("alice", "READ")])
        st, _h, _b = await sreq(host, port, "bob", "PUT", "/shared/b1",
                                pol.to_xml(), query="acl")
        assert st == 200
        st, _h, b = await sreq(host, port, "bob", "GET", "/shared/b1",
                               query="acl")
        assert st == 200 and b"alice" in b
        st, _h, b = await sreq(host, port, "alice", "GET",
                               "/shared/b1")
        assert st == 200 and b == b"bobs data"
        # alice still cannot rewrite bob's ACL (no WRITE_ACP)
        st, _h, _b = await sreq(host, port, "alice", "PUT",
                                "/shared/b1", pol.to_xml(),
                                query="acl")
        assert st == 403

        # deletion: bob CAN delete in the public-read-write bucket
        # (WRITE on bucket governs deletes); only alice may delete the
        # bucket itself
        st, _h, _b = await sreq(host, port, "bob", "DELETE",
                                "/shared/b1")
        assert st == 204
        st, _h, _b = await sreq(host, port, "bob", "DELETE", "/shared")
        assert st == 403
        st, _h, _b = await sreq(host, port, "alice", "DELETE",
                                "/shared")
        assert st == 204

        await fe.stop()
        await c.stop()

    run(t())


def test_acl_namespaced_xml():
    """Real SDK AccessControlPolicy bodies carry the S3 default xmlns;
    parsing must match on local names or a PUT ?acl silently wipes
    every grant (round-5 review finding)."""
    body = (b'<AccessControlPolicy '
            b'xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
            b'<Owner><ID>alice</ID></Owner><AccessControlList>'
            b'<Grant><Grantee><ID>bob</ID></Grantee>'
            b'<Permission>READ</Permission></Grant>'
            b'<Grant><Grantee>'
            b'<URI>http://acs.amazonaws.com/groups/global/AllUsers'
            b'</URI></Grantee><Permission>READ</Permission></Grant>'
            b'</AccessControlList></AccessControlPolicy>')
    a = Acl.from_xml(body)
    assert a.owner == "alice"
    assert a.grants == [("bob", "READ"), (ALL_USERS, "READ")]


def test_acl_listing_and_config_privacy():
    """Anonymous clients cannot enumerate buckets; each principal's
    listing shows only its own buckets; bucket config (versioning)
    is unreadable without READ (round-5 review findings)."""
    async def t():
        c, rgw = await make()
        fe = S3Frontend(rgw, users=dict(USERS))
        host, port = await fe.start()
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/a-b")
        assert st == 200
        st, _h, _b = await sreq(host, port, "bob", "PUT", "/b-b")
        assert st == 200
        # anonymous: no listing, no config reads
        st, _h, _b = await http(host, port, "GET", "/")
        assert st == 403
        st, _h, _b = await http(host, port, "GET", "/a-b?versioning")
        assert st == 403
        st, _h, _b = await http(host, port, "GET", "/a-b?lifecycle")
        assert st == 403
        # per-account listing
        st, _h, b = await sreq(host, port, "alice", "GET", "/")
        assert st == 200 and b"a-b" in b and b"b-b" not in b
        st, _h, b = await sreq(host, port, "bob", "GET", "/")
        assert st == 200 and b"b-b" in b and b"a-b" not in b
        # bob cannot read alice's versioning config either
        st, _h, _b = await sreq(host, port, "bob", "GET", "/a-b",
                                query="versioning")
        assert st == 403
        await fe.stop()
        await c.stop()

    run(t())


def test_object_acl_versioned_no_clobber():
    """PUT ?acl naming a HISTORICAL version must update only that
    version's row — never resurrect its data as the bucket-current
    entry (round-5 review finding)."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b", owner="alice")
        await rgw.put_bucket_versioning("b", "Enabled")
        _e1, v1 = await rgw.put_object("b", "k", b"one",
                                       owner="alice")
        _e2, v2 = await rgw.put_object("b", "k", b"two",
                                       owner="alice")
        await rgw.put_object_acl("b", "k", "alice", "bob:READ",
                                 version_id=v1)
        # current still serves v2's data
        data, meta = await rgw.get_object("b", "k")
        assert data == b"two" and meta["version_id"] == v2
        # v1's row carries the grant; v2's does not
        o1, g1 = await rgw.get_object_acl("b", "k", version_id=v1)
        assert g1 == "bob:READ"
        _o2, g2 = await rgw.get_object_acl("b", "k", version_id=v2)
        assert g2 == ""
        # naming the CURRENT version does update the pointer
        await rgw.put_object_acl("b", "k", "alice", "bob:READ",
                                 version_id=v2)
        _oc, gc = await rgw.get_object_acl("b", "k")
        assert gc == "bob:READ"
        data, _m = await rgw.get_object("b", "k")
        assert data == b"two"
        await c.stop()

    run(t())


def test_object_acl_null_version_keeps_current():
    """PUT ?acl with versionId=null on a still-plain object (the
    standard S3 spelling for pre-versioning objects) must keep the
    current pointer's vid="" — not rewrite it as "null", which would
     404 later null reads and break null preservation (round-5 review
    finding)."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b", owner="alice")
        await rgw.put_object("b", "k", b"plain", owner="alice")
        await rgw.put_object_acl("b", "k", "alice", "bob:READ",
                                 version_id="null")
        # the current entry still reads as the plain object
        data, meta = await rgw.get_object("b", "k")
        assert data == b"plain" and meta["version_id"] == ""
        assert meta["acl"] == "bob:READ"
        # null addressing still resolves
        data, _m = await rgw.get_object("b", "k", version_id="null")
        assert data == b"plain"
        # and a later versioned write still preserves the null version
        await rgw.put_bucket_versioning("b", "Enabled")
        await rgw.put_object("b", "k", b"v2", owner="alice")
        vers = await rgw.list_object_versions("b")
        assert any(v["version_id"] == "null" for v in vers)
        data, _m = await rgw.get_object("b", "k", version_id="null")
        assert data == b"plain"
        await c.stop()

    run(t())


def test_acl_malformed_bodies():
    """Unparseable or invalid ?acl bodies are a 400 MalformedACLError
    — not a dropped connection, not a silently thinned grant list
    (round-5 review findings)."""
    async def t():
        c, rgw = await make()
        fe = S3Frontend(rgw, users=dict(USERS))
        host, port = await fe.start()
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/b")
        assert st == 200
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/b/k",
                                b"data")
        assert st == 200
        # not XML at all
        st, _h, b = await sreq(host, port, "alice", "PUT", "/b",
                               b"not-xml", query="acl")
        assert st == 400 and b"MalformedACLError" in b
        # a typoed permission must not turn the policy private
        bad = (b"<AccessControlPolicy><Owner><ID>alice</ID></Owner>"
               b"<AccessControlList><Grant><Grantee><ID>bob</ID>"
               b"</Grantee><Permission>FULLCONTROL</Permission>"
               b"</Grant></AccessControlList></AccessControlPolicy>")
        st, _h, b = await sreq(host, port, "alice", "PUT", "/b/k",
                               bad, query="acl")
        assert st == 400 and b"MalformedACLError" in b
        await fe.stop()
        await c.stop()

    run(t())


def test_acl_existence_oracle_closed():
    """404-vs-403: a principal without READ (list) on the bucket gets
    AccessDenied for missing AND present keys alike, so absence leaks
    nothing (round-5 review finding)."""
    async def t():
        c, rgw = await make()
        fe = S3Frontend(rgw, users=dict(USERS))
        host, port = await fe.start()
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/priv")
        assert st == 200
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/priv/k",
                                b"x")
        assert st == 200
        # bob and anonymous: same 403 whether the key exists or not
        for who in ("bob", None):
            for path in ("/priv/k", "/priv/nothere"):
                if who:
                    st, _h, _b = await sreq(host, port, who, "GET",
                                            path)
                else:
                    st, _h, _b = await http(host, port, "GET", path)
                assert st == 403, (who, path, st)
        # alice (owner, holds READ): real 404 for the missing key
        st, _h, _b = await sreq(host, port, "alice", "GET",
                                "/priv/nothere")
        assert st == 404
        await fe.stop()
        await c.stop()

    run(t())


def test_acl_bucket_config_gate():
    """Versioning/lifecycle config writes require FULL_CONTROL; reads
    stay open to any authenticated principal on an unset policy but
    respect ownership once set."""
    async def t():
        c, rgw = await make()
        fe = S3Frontend(rgw, users=dict(USERS))
        host, port = await fe.start()
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/b")
        assert st == 200
        body = (b"<VersioningConfiguration><Status>Enabled</Status>"
                b"</VersioningConfiguration>")
        st, _h, _b = await sreq(host, port, "bob", "PUT", "/b", body,
                                query="versioning")
        assert st == 403
        st, _h, _b = await sreq(host, port, "alice", "PUT", "/b", body,
                                query="versioning")
        assert st == 200
        assert await rgw.get_bucket_versioning("b") == "Enabled"
        await fe.stop()
        await c.stop()

    run(t())
