"""Native runtime core (rt_native.cc): embedded KV (src/kv role),
block device (src/blk role), bitmap allocator (BlueStore allocator
role). Durability is exercised the store_test way: reopen-without-close
and torn/corrupt WAL tails."""
import os

import pytest

from ceph_tpu.native import rt


# ------------------------------------------------------------------- kv

def test_kv_basics(tmp_path):
    kv = rt.NativeKV(tmp_path / "kv")
    kv.put(b"a", b"1")
    kv.put(b"b", b"2")
    kv.put(b"c\x00x", b"3")  # embedded NUL is legal
    assert kv.get(b"a") == b"1"
    assert kv.get(b"zz") is None
    kv.delete(b"a")
    assert kv.get(b"a") is None
    kv.batch([("put", b"d", b"4"), ("put", b"e", b"5"), ("del", b"b", None)])
    assert kv.get(b"b") is None and kv.get(b"e") == b"5"
    assert [k for k, _ in kv.scan()] == [b"c\x00x", b"d", b"e"]
    assert kv.scan(b"d", b"e") == [(b"d", b"4")]
    assert kv.scan_prefix(b"c") == [(b"c\x00x", b"3")]
    assert kv.count() == 3
    kv.close()


def test_kv_reopen_replays_wal(tmp_path):
    kv = rt.NativeKV(tmp_path / "kv")
    kv.put(b"k", b"v")
    kv.compact()
    assert kv.wal_size() == 0
    kv.put(b"post", b"snap")  # lives only in the WAL
    kv.close()
    kv = rt.NativeKV(tmp_path / "kv")  # snapshot + WAL replay
    assert kv.get(b"k") == b"v" and kv.get(b"post") == b"snap"
    kv.close()


def test_kv_torn_tail_discarded_then_appendable(tmp_path):
    kv = rt.NativeKV(tmp_path / "kv")
    kv.put(b"good", b"1")
    kv.close()
    with open(tmp_path / "kv" / "kv.wal", "ab") as f:
        f.write(b"\x40\x00\x00\x00GARB")  # torn record header + garbage
    kv = rt.NativeKV(tmp_path / "kv")
    assert kv.count() == 1
    kv.put(b"after", b"2")  # must land where the garbage was truncated
    kv.close()
    kv = rt.NativeKV(tmp_path / "kv")
    assert kv.get(b"after") == b"2" and kv.get(b"good") == b"1"
    kv.close()


def test_kv_corrupt_record_crc(tmp_path):
    kv = rt.NativeKV(tmp_path / "kv")
    kv.put(b"a", b"1")
    kv.put(b"b", b"2")
    kv.close()
    wal = tmp_path / "kv" / "kv.wal"
    blob = bytearray(wal.read_bytes())
    blob[-1] ^= 0xFF  # flip a bit in the last record's body
    wal.write_bytes(bytes(blob))
    kv = rt.NativeKV(tmp_path / "kv")
    assert kv.get(b"a") == b"1"
    assert kv.get(b"b") is None  # corrupt tail record dropped
    kv.close()


def test_kv_corrupt_snapshot_rejected(tmp_path):
    kv = rt.NativeKV(tmp_path / "kv")
    kv.put(b"key", b"value" * 100)
    kv.compact()
    kv.close()
    sst = tmp_path / "kv" / "kv.sst"
    blob = bytearray(sst.read_bytes())
    blob[30] ^= 0x01
    sst.write_bytes(bytes(blob))
    with pytest.raises(rt.KvError):
        rt.NativeKV(tmp_path / "kv")


def test_kv_batch_atomic_on_malformed(tmp_path):
    kv = rt.NativeKV(tmp_path / "kv")
    kv.put(b"x", b"1")
    with pytest.raises(ValueError):
        kv.batch([("put", b"y", b"2"), ("nope", b"z", b"3")])
    assert kv.get(b"y") is None  # nothing half-applied
    kv.close()


def test_kv_prefix_end_edge_cases():
    from ceph_tpu.native.rt import _prefix_end

    assert _prefix_end(b"abc") == b"abd"
    assert _prefix_end(b"a\xff") == b"b"
    assert _prefix_end(b"\xff\xff") == b""  # scan to the end


# ------------------------------------------------------------------ blk

def test_blk_sync_and_async(tmp_path):
    dev = rt.BlockDevice(tmp_path / "block", 1 << 20, n_threads=3)
    assert dev.size == 1 << 20
    dev.submit_write(0, b"hello")
    dev.submit_write(4096, b"world" * 100)
    dev.flush()
    assert dev.pread(0, 5) == b"hello"
    assert dev.pread(4096, 500) == b"world" * 100
    assert dev.pread(1 << 19, 16) == b"\x00" * 16  # sparse reads zeros
    dev.pwrite(8192, b"sync")
    assert dev.pread(8192, 4) == b"sync"
    dev.close()


def test_blk_many_concurrent_writes(tmp_path):
    dev = rt.BlockDevice(tmp_path / "block", 4 << 20, n_threads=4)
    for i in range(256):
        dev.submit_write(i * 4096, bytes([i % 256]) * 4096)
    dev.drain()
    for i in range(0, 256, 37):
        assert dev.pread(i * 4096, 4096) == bytes([i % 256]) * 4096
    dev.close()


def test_blk_sparse_file_is_cheap(tmp_path):
    # capability probe: some container filesystems (overlayfs and
    # friends) materialize every truncated block, so "sparse is cheap"
    # is an env property, not a code property — skip, don't fail
    probe = tmp_path / "sparse-probe"
    with open(probe, "wb") as f:
        f.truncate(4 << 20)
    if os.stat(probe).st_blocks * 512 >= 4 << 20:
        pytest.skip("filesystem does not keep truncated files sparse")
    dev = rt.BlockDevice(tmp_path / "block", 1 << 32, n_threads=1)  # 4 GiB
    dev.pwrite(0, b"x")
    dev.close()
    # apparent size is 4 GiB, real usage a few blocks
    assert os.stat(tmp_path / "block").st_size == 1 << 32
    assert os.stat(tmp_path / "block").st_blocks * 512 < 1 << 20


# ------------------------------------------------------------ allocator

def test_alloc_contiguous_and_release():
    al = rt.BitmapAllocator(256)
    a, b, c = al.alloc(10), al.alloc(100), al.alloc(64)
    assert al.used == 174
    assert len({a, b, c}) == 3
    al.release(b, 100)
    assert al.used == 74
    al.alloc(100)  # must fit back into the released hole
    assert al.used == 174
    al.mark_used(200, 10)
    al.mark_used(205, 10)  # overlapping mark is idempotent
    assert al.used == 174 + 15
    with pytest.raises(MemoryError):
        al.alloc(300)
    al.close()


def test_alloc_word_boundaries():
    al = rt.BitmapAllocator(192)  # 3 words
    runs = [al.alloc(63), al.alloc(65), al.alloc(64)]
    assert al.used == 192
    spans = sorted((s, n) for s, n in zip(runs, (63, 65, 64)))
    end = 0
    for s, n in spans:  # perfectly packed, no overlap
        assert s == end
        end = s + n
    with pytest.raises(MemoryError):
        al.alloc(1)
    al.release(64, 64)
    got = al.alloc(64)
    assert got == 64
    al.close()


def test_alloc_wraps_cursor():
    al = rt.BitmapAllocator(128)
    first = al.alloc(100)
    al.release(first, 100)  # cursor is past the hole; scan must wrap
    again = al.alloc(120)
    assert again == 0
    al.close()
