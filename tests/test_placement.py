"""Placement: host rule engine vs the COMPILED REFERENCE C mapper.

The strongest oracle available: the reference's own mapper.c/hash.c/
builder.c are compiled into a throwaway shared library in /tmp (nothing
enters this repo) and every do_rule result is compared bit-for-bit. If
the reference tree or a C compiler is unavailable the parity tests skip
and the self-consistency tests still run.
"""
from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np
import pytest

from ceph_tpu.placement import crushmap as cm
from ceph_tpu.placement import osdmap as om

REF = Path("/root/reference/src/crush")
SHIM_DIR = Path("/tmp/crushref")

_OPS = {
    cm.OP_TAKE: 1,
    cm.OP_CHOOSE_FIRSTN: 2,
    cm.OP_CHOOSE_INDEP: 3,
    cm.OP_EMIT: 4,
    cm.OP_CHOOSELEAF_FIRSTN: 6,
    cm.OP_CHOOSELEAF_INDEP: 7,
    cm.OP_SET_CHOOSE_TRIES: 8,
    cm.OP_SET_CHOOSELEAF_TRIES: 9,
}
_ALGS = {cm.ALG_UNIFORM: 1, cm.ALG_STRAW2: 5}

_SHIM_SRC = r"""
/* Flat C API over the reference crush core, for ctypes test oracles. */
#include "crush/crush.h"
#include "crush/builder.h"
#include "crush/mapper.h"
#include "crush/hash.h"
#include <stdlib.h>

void* ref_build_map(int n_buckets, const int* bucket_ids,
                    const int* bucket_types, const int* bucket_algs,
                    const int* sizes, const int* items_flat,
                    const int* weights_flat,
                    int choose_local_tries, int choose_local_fallback_tries,
                    int choose_total_tries, int chooseleaf_descend_once,
                    int chooseleaf_vary_r, int chooseleaf_stable) {
  struct crush_map* map = crush_create();
  if (!map) return 0;
  map->choose_local_tries = choose_local_tries;
  map->choose_local_fallback_tries = choose_local_fallback_tries;
  map->choose_total_tries = choose_total_tries;
  map->chooseleaf_descend_once = chooseleaf_descend_once;
  map->chooseleaf_vary_r = chooseleaf_vary_r;
  map->chooseleaf_stable = chooseleaf_stable;
  int off = 0;
  for (int i = 0; i < n_buckets; i++) {
    struct crush_bucket* b = crush_make_bucket(
        map, bucket_algs[i], CRUSH_HASH_RJENKINS1, bucket_types[i],
        sizes[i], (int*)(items_flat + off), (int*)(weights_flat + off));
    if (!b) return 0;
    int id;
    if (crush_add_bucket(map, bucket_ids[i], b, &id) < 0) return 0;
    off += sizes[i];
  }
  crush_finalize(map);
  return map;
}

int ref_add_rule(void* vmap, int ruleno, int n_steps, const int* ops,
                 const int* arg1, const int* arg2) {
  struct crush_map* map = vmap;
  struct crush_rule* rule = crush_make_rule(n_steps, 0);
  if (!rule) return -1;
  for (int i = 0; i < n_steps; i++)
    crush_rule_set_step(rule, i, ops[i], arg1[i], arg2[i]);
  return crush_add_rule(map, rule, ruleno);
}

int ref_do_rule(void* vmap, int ruleno, int x, int* result, int result_max,
                const unsigned* weight, int weight_max) {
  struct crush_map* map = vmap;
  char* cwin = malloc(crush_work_size(map, result_max));
  if (!cwin) return -1;
  crush_init_workspace(map, cwin);
  int n = crush_do_rule(map, ruleno, x, result, result_max, weight,
                        weight_max, cwin, NULL);
  free(cwin);
  return n;
}

void ref_destroy(void* vmap) { crush_destroy((struct crush_map*)vmap); }
"""


def _build_shim() -> Path | None:
    so = SHIM_DIR / "libcrushshim.so"
    if so.exists():
        return so
    if not REF.exists():
        return None
    SHIM_DIR.mkdir(exist_ok=True)
    (SHIM_DIR / "acconfig.h").write_text(
        "#define HAVE_SYS_TYPES_H 1\n#define HAVE_STDINT_H 1\n"
        "#define HAVE_LINUX_TYPES_H 1\n"
    )
    (SHIM_DIR / "shim.c").write_text(_SHIM_SRC)
    srcs = [SHIM_DIR / "shim.c"] + [
        REF / f for f in ("mapper.c", "hash.c", "crush.c", "builder.c")
    ]
    cmd = [
        "gcc", "-shared", "-fPIC", "-O2",
        f"-I{SHIM_DIR}", f"-I{REF.parent}", "-o", str(so),
    ] + [str(s) for s in srcs]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError):
        return None
    return so


class RefCrush:
    """ctypes driver for the compiled reference core."""

    def __init__(self, so: Path, m: cm.CrushMap):
        self.lib = ctypes.CDLL(str(so))
        self.lib.ref_build_map.restype = ctypes.c_void_p
        self.lib.ref_build_map.argtypes = [ctypes.c_int] + [
            ctypes.POINTER(ctypes.c_int)
        ] * 6 + [ctypes.c_int] * 6
        self.lib.ref_add_rule.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        self.lib.ref_do_rule.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint), ctypes.c_int,
        ]
        self.lib.ref_destroy.argtypes = [ctypes.c_void_p]

        # buckets must be added parents-last (items must already exist)
        order = sorted(m.buckets, key=lambda b: -b)
        ids = (ctypes.c_int * len(order))(*order)
        types = (ctypes.c_int * len(order))(*[m.buckets[b].type_id for b in order])
        algs = (ctypes.c_int * len(order))(*[_ALGS[m.buckets[b].alg] for b in order])
        sizes = (ctypes.c_int * len(order))(*[m.buckets[b].size for b in order])
        items_flat: list[int] = []
        weights_flat: list[int] = []
        for b in order:
            items_flat += m.buckets[b].items
            weights_flat += m.buckets[b].weights
        items = (ctypes.c_int * len(items_flat))(*items_flat)
        weights = (ctypes.c_int * len(weights_flat))(*weights_flat)
        t = m.tunables
        self.map = self.lib.ref_build_map(
            len(order), ids, types, algs, sizes, items, weights,
            t.choose_local_tries, t.choose_local_fallback_tries,
            t.choose_total_tries, t.chooseleaf_descend_once,
            t.chooseleaf_vary_r, t.chooseleaf_stable,
        )
        assert self.map, "reference map build failed"
        for rid, rule in m.rules.items():
            ops = (ctypes.c_int * len(rule.steps))(*[_OPS[s.op] for s in rule.steps])
            a1 = (ctypes.c_int * len(rule.steps))(*[s.arg1 for s in rule.steps])
            a2 = (ctypes.c_int * len(rule.steps))(*[s.arg2 for s in rule.steps])
            r = self.lib.ref_add_rule(self.map, rid, len(rule.steps), ops, a1, a2)
            assert r >= 0

    def do_rule(self, ruleno: int, x: int, numrep: int, weights: np.ndarray):
        out = (ctypes.c_int * numrep)()
        w = np.ascontiguousarray(weights, dtype=np.uint32)
        n = self.lib.ref_do_rule(
            self.map, ruleno, x, out, numrep,
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_uint)), len(w),
        )
        return [out[i] for i in range(n)]

    def close(self):
        if self.map:
            self.lib.ref_destroy(self.map)
            self.map = None


@pytest.fixture(scope="module")
def shim():
    so = _build_shim()
    if so is None:
        pytest.skip("reference crush core not available to compile")
    return so


def _compare(shim, m: cm.CrushMap, numrep: int, weights=None, n_x=400):
    if weights is None:
        weights = np.full(m.max_devices, 0x10000, dtype=np.uint32)
    ref = RefCrush(shim, m)
    try:
        for ruleno in m.rules:
            for x in range(n_x):
                got = m.do_rule(ruleno, x, numrep, weights)
                want = ref.do_rule(ruleno, x, numrep, weights)
                assert got == want, (
                    f"rule {ruleno} x={x}: ours {got} != ref {want}"
                )
    finally:
        ref.close()


def test_flat_firstn_parity(shim):
    m = cm.build_flat(12)
    m.add_rule(cm.flat_firstn_rule(0))
    _compare(shim, m, numrep=3)


def test_flat_weighted_and_reweight_parity(shim, rng):
    m = cm.build_flat(10, osd_weights=[1, 2, 3, 4, 0.5, 1, 1, 2, 8, 1])
    m.add_rule(cm.flat_firstn_rule(0))
    w = np.full(10, 0x10000, dtype=np.uint32)
    w[2] = 0          # marked fully out
    w[5] = 0x8000     # half reweighted
    _compare(shim, m, numrep=4, weights=w)


def test_hierarchy_chooseleaf_firstn_parity(shim):
    m = cm.build_hierarchy(osds_per_host=4, n_hosts=6)
    m.add_rule(cm.replicated_rule(0, root=-1, failure_domain_type=1))
    _compare(shim, m, numrep=3)


def test_hierarchy_chooseleaf_indep_parity(shim):
    m = cm.build_hierarchy(osds_per_host=3, n_hosts=8)
    m.add_rule(cm.ec_rule(0, root=-1, failure_domain_type=1))
    _compare(shim, m, numrep=6)


def test_flat_indep_parity(shim):
    m = cm.build_flat(14)
    m.add_rule(cm.ec_rule(0, root=-1, failure_domain_type=0))
    _compare(shim, m, numrep=11)


def test_choose_firstn_host_level_parity(shim):
    """choose (not chooseleaf) of whole hosts."""
    m = cm.build_hierarchy(osds_per_host=2, n_hosts=5)
    m.add_rule(
        cm.Rule(
            0,
            [
                cm.Step(cm.OP_TAKE, -1),
                cm.Step(cm.OP_CHOOSE_FIRSTN, 0, 1),
                cm.Step(cm.OP_EMIT),
            ],
        )
    )
    _compare(shim, m, numrep=3)


def test_legacy_tunables_parity(shim):
    """vary_r/stable off + local retries on (pre-jewel profiles)."""
    m = cm.build_hierarchy(osds_per_host=4, n_hosts=5)
    m.tunables = cm.Tunables(
        choose_local_tries=2,
        choose_local_fallback_tries=5,
        choose_total_tries=19,
        chooseleaf_descend_once=0,
        chooseleaf_vary_r=0,
        chooseleaf_stable=0,
    )
    m.add_rule(cm.replicated_rule(0, root=-1, failure_domain_type=1))
    _compare(shim, m, numrep=3, n_x=200)


def test_uniform_bucket_parity(shim):
    m = cm.CrushMap()
    m.add_type(1, "root")
    m.add_bucket(
        cm.Bucket(
            id=-1, type_id=1, alg=cm.ALG_UNIFORM,
            items=list(range(8)), weights=[0x10000] * 8, name="root",
        )
    )
    m.add_rule(cm.flat_firstn_rule(0))
    _compare(shim, m, numrep=3)


def test_deep_hierarchy_parity(shim, rng):
    """3-level root -> rack -> host -> osd with uneven weights."""
    m = cm.CrushMap()
    m.add_type(1, "host")
    m.add_type(2, "rack")
    m.add_type(3, "root")
    osd = 0
    rack_ids = []
    bid = -2
    for r in range(3):
        host_ids = []
        for h in range(3):
            n = int(rng.integers(2, 5))
            items = list(range(osd, osd + n))
            osd += n
            m.add_bucket(
                cm.Bucket(
                    id=bid, type_id=1, items=items,
                    weights=[int(w) for w in rng.integers(0x8000, 0x30000, n)],
                    name=f"host{r}.{h}",
                )
            )
            host_ids.append(bid)
            bid -= 1
        m.add_bucket(
            cm.Bucket(
                id=bid, type_id=2, items=host_ids,
                weights=[m.buckets[h].weight() for h in host_ids],
                name=f"rack{r}",
            )
        )
        rack_ids.append(bid)
        bid -= 1
    m.add_bucket(
        cm.Bucket(
            id=bid, type_id=3, items=rack_ids,
            weights=[m.buckets[r].weight() for r in rack_ids], name="root",
        )
    )
    root = bid
    m.add_rule(cm.replicated_rule(0, root=root, failure_domain_type=2))
    m.add_rule(cm.ec_rule(1, root=root, failure_domain_type=1))
    _compare(shim, m, numrep=3, n_x=300)


# ---------------------------------------------------------------- OSDMap


def test_object_to_pg_stable_mod():
    crush = cm.build_flat(4)
    crush.add_rule(cm.flat_firstn_rule(0))
    osdm = om.OSDMap(crush, 4)
    osdm.add_pool(om.Pool(id=1, name="p", pg_num=12))  # non-power-of-two
    for name in (b"obj1", b"rbd_data.abc", b"x" * 40):
        _, ps = osdm.object_to_pg(1, name)
        assert 0 <= ps < 12


def test_pg_to_up_acting_replicated_down_filter():
    crush = cm.build_flat(6)
    crush.add_rule(cm.flat_firstn_rule(0))
    osdm = om.OSDMap(crush, 6)
    osdm.add_pool(om.Pool(id=1, name="p", size=3, pg_num=8))
    up0, p0 = osdm.pg_to_up_acting_osds((1, 3))
    assert len(up0) == 3 and p0 == up0[0]
    # take the primary down: it must vanish from the up set
    osdm.apply_incremental(om.Incremental(epoch=2, down=[p0]))
    up1, p1 = osdm.pg_to_up_acting_osds((1, 3))
    assert p0 not in up1 and p1 != p0


def test_pg_to_up_acting_ec_positional_none():
    crush = cm.build_flat(6)
    crush.add_rule(cm.ec_rule(0, failure_domain_type=0))
    osdm = om.OSDMap(crush, 6)
    osdm.add_pool(
        om.Pool(id=2, name="ecp", size=5, pg_num=8, type="erasure", crush_rule=0)
    )
    up0, _ = osdm.pg_to_up_acting_osds((2, 1))
    assert len(up0) == 5
    victim = up0[2]
    osdm.apply_incremental(om.Incremental(epoch=2, down=[victim]))
    up1, _ = osdm.pg_to_up_acting_osds((2, 1))
    assert up1[2] == cm.ITEM_NONE  # positional hole, not shifted
    assert [o for i, o in enumerate(up1) if i != 2] == [
        o for i, o in enumerate(up0) if i != 2
    ]


def test_upmap_overrides():
    crush = cm.build_flat(8)
    crush.add_rule(cm.flat_firstn_rule(0))
    osdm = om.OSDMap(crush, 8)
    osdm.add_pool(om.Pool(id=1, name="p", size=3, pg_num=8))
    pgid = (1, 5)
    up0, _ = osdm.pg_to_up_acting_osds(pgid)
    # full upmap
    target = [o for o in range(8) if o not in up0][:3]
    osdm.pg_upmap[pgid] = target
    up1, _ = osdm.pg_to_up_acting_osds(pgid)
    assert up1 == target
    del osdm.pg_upmap[pgid]
    # item remap
    spare = [o for o in range(8) if o not in up0][0]
    osdm.pg_upmap_items[pgid] = [(up0[1], spare)]
    up2, _ = osdm.pg_to_up_acting_osds(pgid)
    assert up2[1] == spare and up2[0] == up0[0] and up2[2] == up0[2]


def test_reweight_shifts_load():
    crush = cm.build_flat(4)
    crush.add_rule(cm.flat_firstn_rule(0))
    osdm = om.OSDMap(crush, 4)
    osdm.add_pool(om.Pool(id=1, name="p", size=1, pg_num=256))
    count_before = sum(
        osdm.pg_to_up_acting_osds((1, ps))[0] == [3] for ps in range(256)
    )
    osdm.apply_incremental(om.Incremental(epoch=2, weights={3: 0x4000}))
    count_after = sum(
        osdm.pg_to_up_acting_osds((1, ps))[0] == [3] for ps in range(256)
    )
    assert count_after < count_before


def test_str_hash_rjenkins_selfcheck():
    # deterministic + length-sensitive + all tail sizes exercised
    seen = set()
    for n in range(0, 26):
        h = om.ceph_str_hash_rjenkins(bytes(range(n)))
        assert h not in seen
        seen.add(h)
    assert om.ceph_str_hash_rjenkins(b"foo") == om.ceph_str_hash_rjenkins(b"foo")


def test_upmap_full_plus_items_compose():
    """Reference semantics (OSDMap.cc:2682): a valid pg_upmap replaces
    raw AND pg_upmap_items still apply on top; an invalid pg_upmap
    short-circuits, leaving raw untouched and skipping items."""
    crush = cm.build_flat(8)
    crush.add_rule(cm.flat_firstn_rule(0))
    osdm = om.OSDMap(crush, 8)
    osdm.add_pool(om.Pool(id=1, name="p", size=3, pg_num=8))
    pgid = (1, 2)
    up0, _ = osdm.pg_to_up_acting_osds(pgid)
    free = [o for o in range(8) if o not in up0]
    osdm.pg_upmap[pgid] = [free[0], up0[1], up0[2]]
    osdm.pg_upmap_items[pgid] = [(free[0], free[1])]
    up1, _ = osdm.pg_to_up_acting_osds(pgid)
    assert up1 == [free[1], up0[1], up0[2]]  # items applied on top
    # invalidate the full upmap (target marked out): raw wins, items skipped
    osdm.apply_incremental(om.Incremental(epoch=2, weights={free[0]: 0}))
    osdm.pg_upmap_items[pgid] = [(up0[0], free[2])]
    up2, _ = osdm.pg_to_up_acting_osds(pgid)
    assert up2 == up0


def _flat_map_for_upmap(n=6):
    import ceph_tpu.placement.crushmap as cm
    from ceph_tpu.placement.osdmap import OSDMap, Pool

    m = cm.build_flat(n)
    m.add_rule(cm.flat_firstn_rule(0))
    om = OSDMap(m, n)
    om.add_pool(Pool(id=1, name="p", size=3, pg_num=8, crush_rule=0))
    return om


def test_upmap_validity_predicate_matches_reference():
    """OSDMap.cc:2674-2677: reject only in-range weight-0 targets;
    out-of-range targets pass through and get applied."""
    om = _flat_map_for_upmap()
    pgid = (1, 3)
    raw, _ = om.pg_to_raw_osds(pgid)

    # in-range but marked out (weight 0) -> whole pg_upmap rejected
    om.osds[5].weight = 0
    om._out_weights_cache = None
    om.pg_upmap[pgid] = [5, 0, 1]
    assert om._apply_upmap(om.pools[1], pgid, raw) == raw

    # out-of-range target passes the predicate and is applied verbatim
    om.pg_upmap[pgid] = [97, 0, 1]
    assert om._apply_upmap(om.pools[1], pgid, raw) == [97, 0, 1]

    # items: marked-out target skipped, oob target applied
    del om.pg_upmap[pgid]
    om.pg_upmap_items[pgid] = [(raw[0], 5)]  # 5 has weight 0 -> skip
    assert om._apply_upmap(om.pools[1], pgid, raw) == raw
    om.pg_upmap_items[pgid] = [(raw[0], 98)]
    got = om._apply_upmap(om.pools[1], pgid, raw)
    assert got[0] == 98 and got[1:] == raw[1:]
    # target already present anywhere -> pair ignored
    om.pg_upmap_items[pgid] = [(raw[0], raw[1])]
    assert om._apply_upmap(om.pools[1], pgid, raw) == raw


def test_pg_upmap_primaries():
    """OSDMap.cc:2712-2730: valid new primary swaps to front; marked-out
    or absent primaries leave the set untouched."""
    om = _flat_map_for_upmap()
    pgid = (1, 2)
    raw, _ = om.pg_to_raw_osds(pgid)
    assert len(raw) == 3

    om.pg_upmap_primaries[pgid] = raw[2]
    got = om._apply_upmap(om.pools[1], pgid, raw)
    assert got[0] == raw[2] and got[1] == raw[1] and got[2] == raw[0]

    # marked out -> not applied
    om.osds[raw[2]].weight = 0
    om._out_weights_cache = None
    assert om._apply_upmap(om.pools[1], pgid, raw) == raw

    # not in the set -> not applied
    om.osds[raw[2]].weight = 0x10000
    om._out_weights_cache = None
    other = next(o for o in range(6) if o not in raw)
    om.pg_upmap_primaries[pgid] = other
    assert om._apply_upmap(om.pools[1], pgid, raw) == raw
