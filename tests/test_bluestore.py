"""BlueStoreLite: the StoreTest conformance suite against the real
block-device + KV store, plus BlueStore-specific behaviors the
reference tests pin (src/test/objectstore/store_test.cc): crash-reopen
durability, csum detection of device bit rot, COW crash atomicity,
allocator accounting, ENOSPC."""
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from ceph_tpu.store import NotFound, StoreError
from ceph_tpu.store import transaction as tx
from ceph_tpu.store.bluestore import BLOCK, HOLE, BlueStoreLite

from test_store import all_op_txn, check_all_op_state


def make_store(tmp_path, **kw) -> BlueStoreLite:
    kw.setdefault("size", 32 << 20)
    s = BlueStoreLite(str(tmp_path / "bs"), **kw)
    s.mount()
    return s


def test_all_opcodes(tmp_path):
    s = make_store(tmp_path)
    s.apply_transaction(all_op_txn())
    check_all_op_state(s)
    s.umount()


def test_all_opcodes_survive_remount(tmp_path):
    s = make_store(tmp_path)
    s.apply_transaction(all_op_txn())
    s.umount()
    s2 = make_store(tmp_path)
    check_all_op_state(s2)
    s2.umount()


def test_crash_reopen_without_umount(tmp_path):
    """SIGKILL equivalent: no umount/compact; mount replays the kv WAL."""
    s = make_store(tmp_path)
    s.apply_transaction(all_op_txn())
    t = tx.Transaction().create_collection("c2")
    t.write("c2", b"late", 0, b"only in the wal")
    s.apply_transaction(t)
    s2 = make_store(tmp_path)
    check_all_op_state(s2, extra_colls=["c2"])
    assert s2.read("c2", b"late") == b"only in the wal"
    s2.umount()


def test_atomicity_rolls_back_data_and_blocks(tmp_path):
    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"first" * 1000)
    s.apply_transaction(t)
    used0 = s.alloc.used
    bad = tx.Transaction()
    bad.write("c", b"a", 0, b"SECOND" * 2000)
    bad.remove("c", b"ghost")  # fails -> whole txn rolls back
    with pytest.raises(NotFound):
        s.queue_transaction(bad)
    assert s.read("c", b"a") == b"first" * 1000
    assert s.alloc.used == used0  # staged COW blocks were released
    s.umount()


def test_cow_remove_releases_blocks(tmp_path):
    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"big", 0, os.urandom(40 * BLOCK))
    s.apply_transaction(t)
    used = s.alloc.used
    assert used >= 40
    s.apply_transaction(tx.Transaction().remove("c", b"big"))
    assert s.alloc.used == used - 40
    s.umount()


def test_overwrite_is_cow(tmp_path):
    """Overwriting reallocates; the superseded block is freed after
    commit so total usage stays flat."""
    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"x" * BLOCK)
    s.apply_transaction(t)
    used = s.alloc.used
    phys0 = s.colls["c"][b"a"].blocks[0]
    s.apply_transaction(tx.Transaction().write("c", b"a", 0, b"y" * BLOCK))
    assert s.colls["c"][b"a"].blocks[0] != phys0
    assert s.alloc.used == used
    assert s.read("c", b"a") == b"y" * BLOCK
    s.umount()


def test_partial_block_rmw(tmp_path):
    s = make_store(tmp_path)
    data = os.urandom(3 * BLOCK + 777)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, data)
    s.apply_transaction(t)
    patch = os.urandom(100)
    s.apply_transaction(
        tx.Transaction().write("c", b"a", BLOCK + 17, patch))
    want = bytearray(data)
    want[BLOCK + 17:BLOCK + 117] = patch
    assert s.read("c", b"a") == bytes(want)
    # unaligned sub-reads
    assert s.read("c", b"a", 1000, 5000) == bytes(want[1000:6000])
    s.umount()


def test_zero_punches_holes(tmp_path):
    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"q" * (4 * BLOCK))
    s.apply_transaction(t)
    used = s.alloc.used
    s.apply_transaction(tx.Transaction().zero("c", b"a", BLOCK, 2 * BLOCK))
    assert s.alloc.used == used - 2  # full blocks became holes
    o = s.colls["c"][b"a"]
    assert o.blocks[1] == HOLE and o.blocks[2] == HOLE
    assert s.read("c", b"a") == (
        b"q" * BLOCK + b"\x00" * (2 * BLOCK) + b"q" * BLOCK)
    s.umount()


def test_truncate_zeroes_stale_tail(tmp_path):
    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"z" * 3000)
    t.truncate("c", b"a", 1000)
    t.truncate("c", b"a", 2000)  # re-extend within the same block
    s.apply_transaction(t)
    assert s.read("c", b"a") == b"z" * 1000 + b"\x00" * 1000
    s.umount()


def test_csum_detects_device_bit_rot(tmp_path):
    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"R" * (2 * BLOCK))
    s.apply_transaction(t)
    phys = s.colls["c"][b"a"].blocks[1]
    s.umount()
    with open(tmp_path / "bs" / "block", "r+b") as f:
        f.seek(phys * BLOCK + 123)
        f.write(b"\xee")  # cosmic ray
    s2 = make_store(tmp_path)
    with pytest.raises(StoreError, match="csum mismatch"):
        s2.read("c", b"a")
    s2.umount()


def test_split_merge_and_alloc_survive_remount(tmp_path):
    from ceph_tpu.placement.osdmap import ceph_str_hash_rjenkins

    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("1.0")
    oids = [b"obj%d" % i for i in range(16)]
    for oid in oids:
        t.write("1.0", oid, 0, oid * 600)  # >1 block each
    s.apply_transaction(t)
    t2 = tx.Transaction().create_collection("1.1")
    t2.split_collection("1.0", bits=1, rem=1, dest="1.1")
    s.apply_transaction(t2)
    used = s.alloc.used
    s.umount()
    s2 = make_store(tmp_path)
    assert s2.alloc.used == used  # allocator rebuilt from block maps
    left, right = set(s2.list_objects("1.0")), set(s2.list_objects("1.1"))
    assert left | right == set(oids) and not (left & right)
    assert all(ceph_str_hash_rjenkins(o) & 1 == 1 for o in right)
    for oid in right:
        assert s2.read("1.1", oid) == oid * 600
    s2.apply_transaction(
        tx.Transaction().merge_collection("1.1", dest="1.0"))
    assert set(s2.list_objects("1.0")) == set(oids)
    s2.umount()


def test_enospc(tmp_path):
    s = make_store(tmp_path, size=64 * BLOCK)
    t = tx.Transaction().create_collection("c")
    s.apply_transaction(t)
    with pytest.raises(StoreError, match="ENOSPC"):
        s.apply_transaction(
            tx.Transaction().write("c", b"big", 0, b"x" * (100 * BLOCK)))
    # store still healthy after the failed txn
    s.apply_transaction(tx.Transaction().write("c", b"ok", 0, b"fits"))
    assert s.read("c", b"ok") == b"fits"
    s.umount()


def test_sigkill_child_preserves_acked_writes(tmp_path):
    """Real kill -9: a child process writes with fsync=True and reports
    each commit; every acked transaction must be readable after the
    parent reopens the store (the BlueStore durability contract)."""
    script = textwrap.dedent("""
        import sys, os
        sys.path.insert(0, %r)
        from ceph_tpu.store import transaction as tx
        from ceph_tpu.store.bluestore import BlueStoreLite
        s = BlueStoreLite(%r, size=32 << 20, fsync=True)
        s.mount()
        s.apply_transaction(tx.Transaction().create_collection("c"))
        i = 0
        while True:
            t = tx.Transaction().write("c", b"o%%d" %% i, 0, b"v%%d" %% i * 100)
            s.apply_transaction(t)
            print(i, flush=True)
            i += 1
    """) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            str(tmp_path / "bs"))
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE)
    acked = -1
    for _ in range(12):  # let a dozen commits through, then SIGKILL
        acked = int(proc.stdout.readline())
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    s = BlueStoreLite(str(tmp_path / "bs"), size=32 << 20)
    s.mount()
    for i in range(acked + 1):
        assert s.read("c", b"o%d" % i) == b"v%d" % i * 100
    s.umount()


def test_aborted_txn_after_split_does_not_corrupt(tmp_path):
    """Regression: an aborted transaction that wrote to an object MOVED
    by split_collection in the same transaction must not mutate the
    committed onode (the moved Onode is the committed object — the COW
    check must not be fooled by the cid change)."""
    from ceph_tpu.placement.osdmap import ceph_str_hash_rjenkins

    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("1.0")
    oids = [b"o%d" % i for i in range(8)]
    for oid in oids:
        t.write("1.0", oid, 0, oid * 400)
    s.apply_transaction(t)
    moved = next(o for o in oids if ceph_str_hash_rjenkins(o) & 1 == 1)
    bad = tx.Transaction().create_collection("1.1")
    bad.split_collection("1.0", bits=1, rem=1, dest="1.1")
    bad.write("1.1", moved, 0, b"X" * 5000)
    bad.remove("1.1", b"ghost")  # aborts the whole txn
    with pytest.raises(NotFound):
        s.queue_transaction(bad)
    for oid in oids:  # committed state fully intact, csums verify
        assert s.read("1.0", oid) == oid * 400
    assert "1.1" not in s.list_collections()
    s.umount()


def test_rmcoll_then_mkcoll_same_txn(tmp_path):
    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"old")
    s.apply_transaction(t)
    t2 = tx.Transaction()
    t2.remove("c", b"a")
    t2.remove_collection("c")
    t2.create_collection("c")
    t2.write("c", b"b", 0, b"new")
    s.apply_transaction(t2)
    assert s.list_objects("c") == [b"b"]
    s.umount()
    s2 = make_store(tmp_path)
    assert s2.list_objects("c") == [b"b"]
    assert s2.read("c", b"b") == b"new"
    s2.umount()


def test_clone_is_independent(tmp_path):
    s = make_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"src", 0, b"A" * (2 * BLOCK))
    t.clone("c", b"src", b"dup")
    s.apply_transaction(t)
    s.apply_transaction(tx.Transaction().write("c", b"src", 0, b"B" * 10))
    assert s.read("c", b"dup") == b"A" * (2 * BLOCK)  # unaffected
    assert s.read("c", b"src", 0, 10) == b"B" * 10
    s.umount()


def test_cluster_on_bluestore(tmp_path):
    """vstart --bluestore role: a full EC cluster runs on BlueStoreLite,
    survives an OSD kill + revive (remounting the same store), and a
    whole-cluster restart from the same data dirs."""
    import asyncio

    from ceph_tpu.cluster import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    data = os.urandom(100_000)

    async def phase1():
        c = TestCluster(n_osds=5, objectstore="bluestore",
                        data_dir=str(tmp_path), size=32 << 20)
        await c.start()
        await c.client.create_pool(
            Pool(id=2, name="ec", size=5, min_size=3, pg_num=8,
                 crush_rule=1, type="erasure",
                 ec_profile={"plugin": "rs_tpu", "k": "3", "m": "2",
                             "backend": "device"}))
        await c.wait_active(20)
        await c.client.write_full(2, b"obj", data)
        assert await c.client.read(2, b"obj") == data
        await c.kill_osd(1)
        await c.wait_down(1)
        assert await c.client.read(2, b"obj") == data  # degraded
        await c.revive_osd(1)
        await c.wait_active(20)
        await c.stop()

    async def phase2():  # cold restart from the on-disk stores
        c = TestCluster(n_osds=5, objectstore="bluestore",
                        data_dir=str(tmp_path), size=32 << 20)
        await c.start()
        await c.client.create_pool(
            Pool(id=2, name="ec", size=5, min_size=3, pg_num=8,
                 crush_rule=1, type="erasure",
                 ec_profile={"plugin": "rs_tpu", "k": "3", "m": "2",
                             "backend": "device"}))
        await c.wait_active(20)
        assert await c.client.read(2, b"obj") == data
        await c.stop()

    asyncio.run(asyncio.wait_for(phase1(), 60))
    asyncio.run(asyncio.wait_for(phase2(), 60))


def test_kv_auto_compact(tmp_path):
    s = make_store(tmp_path, kv_compact_bytes=4096)
    t = tx.Transaction().create_collection("c")
    s.apply_transaction(t)
    for i in range(50):
        s.apply_transaction(
            tx.Transaction().write("c", b"o%d" % i, 0, b"x" * 200))
    assert s.kv.wal_size() < 4096  # compaction kicked in
    s.umount()
    s2 = make_store(tmp_path)
    for i in range(50):
        assert s2.read("c", b"o%d" % i) == b"x" * 200
    s2.umount()


# ------------------------- deferred small writes (BlueStore.cc:14768)


def test_deferred_small_write_no_cow(tmp_path):
    """A small overwrite of a committed block patches it IN PLACE via
    the kv WAL: the block map keeps the same phys block and no new
    allocation happens (the _do_write_small role) — versus the COW path
    that would burn a fresh 4 KiB block per 100-byte update."""
    s = BlueStoreLite(str(tmp_path / "st"), size=16 << 20)
    s.mount()
    t = tx.Transaction()
    t.create_collection("c")
    t.write("c", b"o", 0, b"A" * 20_000)
    s.queue_transaction(t)
    before_blocks = list(s.colls["c"][b"o"].blocks)

    t = tx.Transaction()
    t.write("c", b"o", 100, b"deferred!")
    s.queue_transaction(t)
    after_blocks = list(s.colls["c"][b"o"].blocks)
    assert after_blocks == before_blocks  # same phys: no COW
    want = b"A" * 100 + b"deferred!" + b"A" * (20_000 - 109)
    assert s.read("c", b"o") == want  # content + csum verify on read

    # durable across a clean reopen
    s.umount()
    s2 = BlueStoreLite(str(tmp_path / "st"), size=16 << 20)
    s2.mount()
    assert s2.read("c", b"o") == want
    s2.umount()


def test_deferred_write_replays_after_crash(tmp_path):
    """Crash between the kv commit (defer record durable) and the
    in-place block write: mount replays the record, so the committed
    csum and the device bytes agree."""
    s = BlueStoreLite(str(tmp_path / "st"), size=16 << 20)
    s.mount()
    t = tx.Transaction()
    t.create_collection("c")
    t.write("c", b"o", 0, b"B" * 8192)
    s.queue_transaction(t)

    s._crash_before_deferred = True  # test hook: die before the patch
    t = tx.Transaction()
    t.write("c", b"o", 4000, b"XYZ")
    s.queue_transaction(t)
    # SIGKILL-style: abandon the instance without umount
    s.dev.close()
    s.kv.close()

    s2 = BlueStoreLite(str(tmp_path / "st"), size=16 << 20)
    s2.mount()  # replays the defer record
    want = b"B" * 4000 + b"XYZ" + b"B" * (8192 - 4003)
    assert s2.read("c", b"o") == want
    # record consumed: a second reopen has nothing to replay
    assert not list(s2.kv.scan_prefix(b"D"))
    s2.umount()


def test_deferred_vs_cow_write_amplification(tmp_path):
    """The before/after bench the r2 verdict asked for: N small
    overwrites allocate ZERO new blocks on the deferred path; the COW
    path would allocate (and free) N. Measured via the allocator."""
    s = BlueStoreLite(str(tmp_path / "st"), size=16 << 20)
    s.mount()
    t = tx.Transaction()
    t.create_collection("c")
    t.write("c", b"o", 0, b"C" * 65536)
    s.queue_transaction(t)

    used_before = sum(1 for b in s.colls["c"][b"o"].blocks if b != HOLE)
    n = 50
    for i in range(n):
        t = tx.Transaction()
        t.write("c", b"o", (i * 1117) % 60_000, b"x" * 64)
        s.queue_transaction(t)
    blocks = s.colls["c"][b"o"].blocks
    assert sum(1 for b in blocks if b != HOLE) == used_before
    # content check over the full object
    data = bytearray(b"C" * 65536)
    for i in range(n):
        off = (i * 1117) % 60_000
        data[off : off + 64] = b"x" * 64
    assert s.read("c", b"o") == bytes(data)
    s.umount()


# ------------------------------------------------------ inline compression


def comp_store(tmp_path, **kw):
    kw.setdefault("compression", "zlib")
    return make_store(tmp_path, **kw)


def test_compressed_write_saves_blocks_and_roundtrips(tmp_path):
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    s.apply_transaction(t)
    used0 = s.alloc.used
    data = b"compress me " * (64 * 1024 // 12 + 1)  # > 64 KiB, squashy
    t = tx.Transaction()
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    nblocks = -(-len(data) // BLOCK)
    assert s.alloc.used - used0 < nblocks  # physically smaller
    assert s.read("c", b"o") == data
    assert s.read("c", b"o", 5000, 9000) == data[5000:14000]
    s.umount()


def test_compressed_survives_remount_without_write_codec(tmp_path):
    data = bytes(range(256)) * 300  # 75 KiB, compressible
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    used = s.alloc.used
    s.umount()
    # reopen with compression OFF: existing blobs must still decode
    # (the blob records its algorithm)
    s2 = make_store(tmp_path)
    assert s2.alloc.used == used  # allocator rebuilt incl. blob blocks
    assert s2.read("c", b"o") == data
    # new writes on the uncompressed store stay plain, old data intact
    t = tx.Transaction()
    t.write("c", b"p", 0, data)
    s2.apply_transaction(t)
    assert s2.read("c", b"p") == data
    s2.umount()


def test_incompressible_falls_through_plain(tmp_path):
    import numpy as np

    s = comp_store(tmp_path)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, 128 * 1024, dtype=np.uint8).tobytes()
    t = tx.Transaction().create_collection("c")
    s.apply_transaction(t)
    used0 = s.alloc.used
    t = tx.Transaction()
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    assert s.alloc.used - used0 == len(data) // BLOCK  # stored raw
    assert not s.colls["c"][b"o"].cblobs
    assert s.read("c", b"o") == data
    s.umount()


def test_alloc_hint_incompressible_skips_compression(tmp_path):
    data = b"Z" * (64 * 1024)
    s = comp_store(tmp_path)  # mode=aggressive honors the hint
    t = tx.Transaction().create_collection("c")
    t.set_alloc_hint("c", b"o", 0, 0, 2)  # FLAG_INCOMPRESSIBLE
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    assert not s.colls["c"][b"o"].cblobs
    assert s.read("c", b"o") == data
    s.umount()


def test_partial_overwrite_dissolves_blob(tmp_path):
    data = b"ab" * (48 * 1024 // 2)
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    assert s.colls["c"][b"o"].cblobs
    patch_off, patch = 10_000, b"PATCHED!"
    t = tx.Transaction()
    t.write("c", b"o", patch_off, patch)
    s.apply_transaction(t)
    want = data[:patch_off] + patch + data[patch_off + len(patch):]
    assert s.read("c", b"o") == want
    # the touched blob is gone; untouched one(s) may remain
    o = s.colls["c"][b"o"]
    for start, cb in o.cblobs.items():
        assert not start <= patch_off // BLOCK < start + cb.nblocks
    s.umount()


def test_full_overwrite_recompresses_and_frees_old(tmp_path):
    d1 = b"first " * (32 * 1024 // 6)
    d2 = b"second" * (32 * 1024 // 6)
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"o", 0, d1)
    s.apply_transaction(t)
    used1 = s.alloc.used
    t = tx.Transaction()
    t.write("c", b"o", 0, d2)
    s.apply_transaction(t)
    assert abs(s.alloc.used - used1) <= 1  # old blob blocks freed
    assert s.read("c", b"o") == d2
    s.umount()


def test_truncate_into_blob(tmp_path):
    data = b"trunc" * (64 * 1024 // 5)
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    cut = 20_000
    t = tx.Transaction()
    t.truncate("c", b"o", cut)
    s.apply_transaction(t)
    assert s.read("c", b"o") == data[:cut]
    t = tx.Transaction()  # re-extend: stale tail must read zero
    t.truncate("c", b"o", len(data))
    s.apply_transaction(t)
    assert s.read("c", b"o") == data[:cut] + b"\x00" * (len(data) - cut)
    s.umount()


def test_clone_copies_compressed_verbatim(tmp_path):
    data = b"clone me " * (48 * 1024 // 9)
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"src", 0, data)
    t.clone("c", b"src", b"dst")
    s.apply_transaction(t)
    src, dst = s.colls["c"][b"src"], s.colls["c"][b"dst"]
    assert set(src.cblobs) == set(dst.cblobs)
    for st in src.cblobs:
        assert src.cblobs[st].phys != dst.cblobs[st].phys  # no sharing
        assert src.cblobs[st].clen == dst.cblobs[st].clen
    t = tx.Transaction()  # mutating the clone leaves the source alone
    t.write("c", b"dst", 0, b"X" * 100)
    s.apply_transaction(t)
    assert s.read("c", b"src") == data
    assert s.read("c", b"dst")[:100] == b"X" * 100
    s.umount()


def test_csum_detects_rot_in_compressed_blob(tmp_path):
    data = b"rot" * (64 * 1024 // 3)
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    o = s.colls["c"][b"o"]
    assert o.cblobs
    cb = next(iter(o.cblobs.values()))
    phys = cb.phys[0]
    buf = bytearray(s.dev.pread(phys * BLOCK, BLOCK))
    buf[17] ^= 0x40
    s.dev.pwrite(phys * BLOCK, bytes(buf))
    with pytest.raises(StoreError, match="csum mismatch"):
        s.read("c", b"o")
    s.umount()


def test_compressed_remove_releases_blob_blocks(tmp_path):
    data = b"gone " * (64 * 1024 // 5)
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    s.apply_transaction(t)
    used0 = s.alloc.used
    t = tx.Transaction()
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    t = tx.Transaction()
    t.remove("c", b"o")
    s.apply_transaction(t)
    assert s.alloc.used == used0
    s.umount()


def test_compressed_crash_reopen(tmp_path):
    """Blob written, no umount: mount rebuilds onode + blob from kv."""
    data = b"durable " * (32 * 1024 // 8)
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"o", 0, data)
    s.apply_transaction(t)
    s2 = comp_store(tmp_path)  # no umount of s: crash-equivalent
    assert s2.read("c", b"o") == data
    assert s2.colls["c"][b"o"].cblobs
    s2.umount()


def test_truncate_blob_at_partial_tail_block(tmp_path):
    """A blob ending exactly at the truncation block with a partial
    tail must dissolve so the tail zeroing patches a plain block (a
    CBLOB sentinel must never reach the allocator free list)."""
    s = comp_store(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"o", 0, b"tail" * (16 * 1024 // 4))  # one 4-block blob
    s.apply_transaction(t)
    assert s.colls["c"][b"o"].cblobs
    cut = 14336  # 3.5 blocks
    t = tx.Transaction()
    t.truncate("c", b"o", cut)
    s.apply_transaction(t)
    o = s.colls["c"][b"o"]
    assert not o.cblobs
    assert all(b != 0xFFFFFFFE for b in o.blocks)
    assert s.read("c", b"o") == (b"tail" * (16 * 1024 // 4))[:cut]
    t = tx.Transaction()  # re-extend: truncated tail reads zero
    t.truncate("c", b"o", 16 * 1024)
    s.apply_transaction(t)
    assert s.read("c", b"o", cut) == b"\x00" * (16 * 1024 - cut)
    # overwrite block 0 afterwards: no stale blob resurrects the tail
    t = tx.Transaction()
    t.write("c", b"o", 0, b"X" * 10)
    s.apply_transaction(t)
    got = s.read("c", b"o")
    assert got[:10] == b"X" * 10
    assert got[cut:] == b"\x00" * (16 * 1024 - cut)
    s.umount()
