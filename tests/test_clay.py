"""CLAY plugin tests: round-trips under every erasure pattern, the
bandwidth-optimal single-loss repair path, sub-chunk accounting
(TestErasureCodeClay role)."""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import ECError, load_codec

RNG = np.random.default_rng(777)


def make(k, m, d=None):
    prof = {"plugin": "clay", "k": str(k), "m": str(m)}
    if d is not None:
        prof["d"] = str(d)
    return load_codec(prof)


def test_parameters():
    c = make(4, 2)  # d = 5, q = 2, t = 3
    assert (c.q, c.t, c.nu) == (2, 3, 0)
    assert c.get_sub_chunk_count() == 8
    c2 = make(8, 4)  # d = 11, q = 4, k+m=12, t = 3
    assert (c2.q, c2.t, c2.nu) == (4, 3, 0)
    assert c2.get_sub_chunk_count() == 64
    c3 = make(3, 3, d=4)  # q = 2, k+m=6, t = 3
    assert (c3.q, c3.t, c3.nu) == (2, 3, 0)
    c4 = make(4, 3)  # q=3, k+m=7, nu=2, t=3
    assert (c4.q, c4.nu, c4.t) == (3, 2, 3)
    assert c4.get_sub_chunk_count() == 27
    with pytest.raises(ECError):
        make(4, 2, d=7)  # d > k+m-1


@pytest.mark.parametrize("k,m,d", [(4, 2, 5), (3, 2, 4), (2, 2, 3)])
def test_roundtrip_all_patterns(k, m, d):
    codec = make(k, m, d)
    n = k + m
    size = codec.get_chunk_size(1) * k  # one aligned object
    obj = RNG.integers(0, 256, size, dtype=np.uint8).tobytes()
    encoded = codec.encode(list(range(n)), obj)
    for r in range(1, m + 1):
        for erase in itertools.combinations(range(n), r):
            avail = {i: encoded[i] for i in range(n) if i not in erase}
            decoded = codec.decode(list(erase), avail)
            for i in erase:
                np.testing.assert_array_equal(
                    decoded[i], encoded[i],
                    err_msg=f"k={k} m={m} erase={erase} chunk {i}",
                )


def test_roundtrip_with_shortening():
    codec = make(4, 3)  # nu = 2
    obj = RNG.integers(
        0, 256, codec.get_chunk_size(1) * 4, dtype=np.uint8
    ).tobytes()
    encoded = codec.encode(list(range(7)), obj)
    for erase in [(0,), (5,), (0, 6), (1, 2, 3)]:
        avail = {i: encoded[i] for i in range(7) if i not in erase}
        decoded = codec.decode(list(erase), avail)
        for i in erase:
            np.testing.assert_array_equal(decoded[i], encoded[i])


def test_decode_concat_roundtrip():
    codec = make(4, 2)
    obj = RNG.integers(0, 256, 100_000, dtype=np.uint8).tobytes()
    encoded = codec.encode(list(range(6)), obj)
    got = codec.decode_concat({i: encoded[i] for i in [0, 2, 3, 4]})
    assert bytes(got[: len(obj)]) == obj


# ------------------------------------------------------------- repair


def test_repair_subchunk_runs():
    codec = make(4, 2)  # q=2, t=3, sub=8
    # lost node (x,y): runs select planes with digit y == x
    # chunk 0 -> node 0 -> (x=0, y=0): planes 0..3 (MSB digit 0)
    assert codec.get_repair_subchunks(0) == [(0, 4)]
    # chunk 1 -> node 1 -> (x=1, y=0): planes 4..7
    assert codec.get_repair_subchunks(1) == [(4, 4)]
    # chunk 2 -> node 2 -> (x=0, y=1): digit1==0 -> 2 runs of 2
    assert codec.get_repair_subchunks(2) == [(0, 2), (4, 2)]
    # chunk 5 -> node 5 -> (x=1, y=2): digit2==1 -> 4 runs of 1
    assert codec.get_repair_subchunks(5) == [(1, 1), (3, 1), (5, 1), (7, 1)]


def test_minimum_to_decode_repair_case():
    codec = make(4, 2)
    need = codec.minimum_to_decode([0], [1, 2, 3, 4, 5])
    assert len(need) == codec.d == 5
    runs = next(iter(need.values()))
    total = sum(c for _, c in runs)
    assert total == codec.get_sub_chunk_count() // codec.q  # 1/q of chunk
    # full-decode fallback when two are missing
    need2 = codec.minimum_to_decode([0, 1], [2, 3, 4, 5])
    assert all(v == [(0, 8)] for v in need2.values())


@pytest.mark.parametrize("lost", [0, 1, 2, 3, 4, 5])
def test_repair_single_loss_bit_exact(lost):
    codec = make(4, 2)
    obj = RNG.integers(
        0, 256, codec.get_chunk_size(1) * 4, dtype=np.uint8
    ).tobytes()
    n = 6
    encoded = codec.encode(list(range(n)), obj)
    avail = sorted(set(range(n)) - {lost})
    plan = codec.minimum_to_decode([lost], avail)
    assert lost not in plan and len(plan) == codec.d
    sub_size = len(encoded[0].tobytes()) // codec.get_sub_chunk_count()
    helper_bytes = {}
    for c, runs in plan.items():
        full = encoded[c].tobytes()
        helper_bytes[c] = b"".join(
            full[off * sub_size : (off + cnt) * sub_size]
            for off, cnt in runs
        )
    # each helper ships 1/q of its chunk
    assert all(
        len(b) == len(encoded[0].tobytes()) // codec.q
        for b in helper_bytes.values()
    )
    repaired = codec.repair([lost], helper_bytes)
    np.testing.assert_array_equal(
        repaired[lost], encoded[lost], err_msg=f"lost={lost}"
    )


def test_repair_with_shortening():
    codec = make(4, 3)  # nu=2, q=3, d=6
    obj = RNG.integers(
        0, 256, codec.get_chunk_size(1) * 4, dtype=np.uint8
    ).tobytes()
    encoded = codec.encode(list(range(7)), obj)
    for lost in (0, 3, 6):
        avail = sorted(set(range(7)) - {lost})
        plan = codec.minimum_to_decode([lost], avail)
        if lost not in plan and len(plan) == codec.d:
            sub = len(encoded[0].tobytes()) // codec.get_sub_chunk_count()
            helper_bytes = {
                c: b"".join(
                    encoded[c].tobytes()[o * sub : (o + n) * sub]
                    for o, n in runs
                )
                for c, runs in plan.items()
            }
            repaired = codec.repair([lost], helper_bytes)
            np.testing.assert_array_equal(repaired[lost], encoded[lost])


def test_repair_bandwidth_beats_mds():
    """The MSR property: repair reads d/q sub-chunk volumes < k chunks."""
    codec = make(8, 4)  # q=4, d=11
    repair_bytes = codec.d / codec.q  # in chunk units
    assert repair_bytes < codec.k
    assert repair_bytes == 2.75  # vs 8 full chunks for plain RS


def test_decode_dispatches_to_repair_via_chunk_size():
    codec = make(4, 2)
    chunk_size = codec.get_chunk_size(4096)
    obj = RNG.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    encoded = codec.encode(list(range(6)), obj)
    lost = 2
    plan = codec.minimum_to_decode([lost], sorted(set(range(6)) - {lost}))
    sub = chunk_size // codec.get_sub_chunk_count()
    partial = {
        c: b"".join(
            encoded[c].tobytes()[o * sub : (o + n) * sub] for o, n in runs
        )
        for c, runs in plan.items()
    }
    out = codec.decode([lost], partial, chunk_size=chunk_size)
    np.testing.assert_array_equal(out[lost], encoded[lost])
