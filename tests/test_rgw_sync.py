"""RGW multisite sync tests: datalog tailing, full-sync bootstrap,
versioned replication, marker persistence (the rgw multisite suite
role, shrunk to two zones on one cluster)."""
import asyncio
import hashlib

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.rgw import RGWError, RGWLite
from ceph_tpu.services.rgw_sync import RGWSyncAgent


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 180))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="zone-a", size=3, pg_num=8, crush_rule=0))
    await c.client.create_pool(
        Pool(id=2, name="zone-b", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    src = RGWLite(c.client, 1, zone="a", datalog=True)
    dst = RGWLite(c.client, 2, zone="b")
    return c, src, dst, RGWSyncAgent(src, dst)


def test_incremental_sync_plain():
    async def t():
        c, src, dst, agent = await make()
        await src.create_bucket("b")
        await src.put_object("b", "k1", b"one",
                             content_type="text/plain",
                             meta={"color": "red"})
        await src.put_object("b", "k2", b"two")
        await agent.sync_once()
        assert await dst.list_buckets() == ["b"]
        got, meta = await dst.get_object("b", "k1")
        assert got == b"one" and meta["content_type"] == "text/plain"
        assert meta["meta"] == {"color": "red"}
        # etag + mtime preserved verbatim across zones
        s = await src.head_object("b", "k1")
        assert (meta["etag"], meta["mtime"]) == (s["etag"], s["mtime"])
        # overwrite + delete propagate
        await src.put_object("b", "k1", b"one-v2")
        await src.delete_object("b", "k2")
        await agent.sync_once()
        got, _ = await dst.get_object("b", "k1")
        assert got == b"one-v2"
        with pytest.raises(RGWError, match="NoSuchKey"):
            await dst.get_object("b", "k2")
        # idempotent: nothing new -> nothing applied
        r = await agent.sync_once()
        assert r["applied"] == 0
        # metadata-only change (same bytes, new content-type/meta)
        # still replicates — replication identity covers the index row
        await src.put_object("b", "k1", b"one-v2",
                             content_type="text/html",
                             meta={"rev": "2"})
        await agent.sync_once()
        got, meta = await dst.get_object("b", "k1")
        assert got == b"one-v2" and meta["content_type"] == "text/html"
        assert meta["meta"] == {"rev": "2"}
        await c.stop()

    run(t())


def test_full_sync_bootstrap_and_striped():
    async def t():
        c, src, dst, agent = await make()
        await src.create_bucket("boot")
        big = np.random.default_rng(7).integers(
            0, 256, (1 << 22) + 4096, dtype=np.uint8).tobytes()
        await src.put_object("boot", "big", big)  # striped form
        await src.put_object("boot", "small", b"s")
        # multipart object: lands assembled on dst, same "-N" etag
        up = await src.initiate_multipart("boot", "mp")
        p1 = b"a" * 1024
        p2 = b"b" * 2048
        await src.upload_part("boot", "mp", up, 1, p1)
        await src.upload_part("boot", "mp", up, 2, p2)
        etag = await src.complete_multipart("boot", "mp", up, [1, 2])
        assert etag.endswith("-2")
        await agent.sync_once()
        got, meta = await dst.get_object("boot", "big")
        assert got == big
        got, meta = await dst.get_object("boot", "mp")
        assert got == p1 + p2 and meta["etag"] == etag
        assert not meta["multipart"]  # assembled on the destination
        # re-sync converges (etag equality, no blind re-copy)
        r = await agent.sync_once()
        assert r["applied"] == 0
        await c.stop()

    run(t())


def test_versioned_sync():
    async def t():
        c, src, dst, agent = await make()
        await src.create_bucket("v")
        await src.put_object("v", "pre", b"null-data")  # pre-versioning
        await src.put_bucket_versioning("v", "Enabled")
        _e1, v1 = await src.put_object("v", "k", b"ver1")
        _e2, v2 = await src.put_object("v", "k", b"ver2")
        marker_vid = await src.delete_object("v", "k")  # delete marker
        _e3, v3 = await src.put_object("v", "k", b"ver3")
        await src.put_object("v", "pre", b"shadows-null")
        await agent.sync_once()
        assert await dst.get_bucket_versioning("v") == "Enabled"
        # full version timeline replicated, newest-first, same vids
        sv = await src.list_object_versions("v", prefix="k")
        dv = await dst.list_object_versions("v", prefix="k")
        assert [(e["version_id"], e["delete_marker"], e["is_latest"])
                for e in sv] == \
               [(e["version_id"], e["delete_marker"], e["is_latest"])
                for e in dv]
        assert {e["version_id"] for e in dv} == \
               {v1, v2, v3, marker_vid}
        for vid, want in ((v1, b"ver1"), (v2, b"ver2"), (v3, b"ver3")):
            got, _ = await dst.get_object("v", "k", version_id=vid)
            assert got == want
        # preserved null version rode along
        got, _ = await dst.get_object("v", "pre", version_id="null")
        assert got == b"null-data"
        # by-vid deletion of the CURRENT version propagates; the
        # promotion lands the delete marker (next-newest) as current on
        # both sides, so the key reads absent
        await src.delete_object("v", "k", version_id=v3)
        await agent.sync_once()
        with pytest.raises(RGWError, match="NoSuchKey"):
            await src.get_object("v", "k")
        with pytest.raises(RGWError, match="NoSuchKey"):
            await dst.get_object("v", "k")
        await c.stop()

    run(t())


def test_versioned_current_after_vid_delete():
    async def t():
        c, src, dst, agent = await make()
        await src.create_bucket("v")
        await src.put_bucket_versioning("v", "Enabled")
        _e1, v1 = await src.put_object("v", "k", b"a")
        _e2, v2 = await src.put_object("v", "k", b"b")
        await src.delete_object("v", "k", version_id=v2)
        await agent.sync_once()
        # v2 gone on both sides; v1 promoted back to current
        got, meta = await dst.get_object("v", "k")
        assert got == b"a" and meta["version_id"] == v1
        with pytest.raises(RGWError, match="NoSuchVersion"):
            await dst.get_object("v", "k", version_id=v2)
        await c.stop()

    run(t())


def test_marker_persistence_and_trim():
    async def t():
        c, src, dst, agent = await make()
        agent.trim = True
        await src.create_bucket("m")
        await src.put_object("m", "k", b"x")
        r1 = await agent.sync_once()
        assert r1["applied"] > 0
        # trimmed: the source log holds nothing before the marker
        head, ents, _tr = await src.datalog.list(0, 100)
        assert not ents and head == r1["marker"]
        # a NEW agent over the same zones resumes from the durable
        # marker: no second full sync, no replays
        agent2 = RGWSyncAgent(src, dst)
        r2 = await agent2.sync_once()
        assert r2["applied"] == 0 and r2["marker"] == r1["marker"]
        await src.put_object("m", "k2", b"y")
        r3 = await agent2.sync_once()
        assert r3["applied"] >= 1
        got, _ = await dst.get_object("m", "k2")
        assert got == b"y"
        await c.stop()

    run(t())


def test_bucket_teardown_and_background_loop():
    async def t():
        c, src, dst, agent = await make()
        await src.create_bucket("gone")
        await src.put_object("gone", "k", b"x")
        await agent.sync_once()
        assert await dst.list_buckets() == ["gone"]
        await src.delete_object("gone", "k")
        await src.delete_bucket("gone")
        await agent.sync_once()
        assert await dst.list_buckets() == []
        # background loop picks up new writes without explicit calls
        await src.create_bucket("live")
        agent.start(interval=0.05)
        await src.put_object("live", "k", b"tail")
        for _ in range(100):
            try:
                got, _ = await dst.get_object("live", "k")
                if got == b"tail":
                    break
            except RGWError:
                pass
            await asyncio.sleep(0.05)
        else:
            raise AssertionError("background sync never converged")
        await agent.stop()
        await c.stop()

    run(t())


def test_sigv4_unaffected_requires_datalog():
    """An agent over a zone without a datalog is a configuration
    error, reported eagerly."""
    async def t():
        c = TestCluster(n_osds=3)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="z", size=2, pg_num=4, crush_rule=0))
        await c.wait_active(20)
        src = RGWLite(c.client, 1)
        with pytest.raises(ValueError, match="datalog"):
            RGWSyncAgent(src, src)
        await c.stop()

    run(t())


def test_acl_replication():
    """ACL changes replicate: on create, on ACL-only rewrite of a
    plain object, and on an in-place version-row rewrite (round-5
    review finding: _ent_sig must cover owner/acl, and matching
    version rows must be re-compared, not just copied-when-missing)."""
    async def t():
        c, src, dst, agent = await make()
        await src.create_bucket("b", owner="alice")
        await src.put_object("b", "k", b"data", owner="alice",
                             acl="*:READ")
        await agent.sync_once()
        assert await dst.get_bucket_acl("b") == \
            await src.get_bucket_acl("b")
        assert await dst.get_object_acl("b", "k") == ("alice", "*:READ")
        # ACL-only rewrite (same bytes) propagates — e.g. revoking
        # public-read must not leave the peer zone serving it publicly
        await src.put_object_acl("b", "k", "alice", "")
        await agent.sync_once()
        assert await dst.get_object_acl("b", "k") == ("alice", "")
        # versioned: in-place ACL rewrite of an EXISTING version row
        await src.put_bucket_versioning("b", "Enabled")
        _e, v1 = await src.put_object("b", "vk", b"v1", owner="alice")
        await agent.sync_once()
        assert (await dst.get_object_acl("b", "vk",
                                         version_id=v1)) == \
            ("alice", "")
        await src.put_object_acl("b", "vk", "alice", "bob:READ",
                                 version_id=v1)
        await agent.sync_once()
        assert (await dst.get_object_acl("b", "vk",
                                         version_id=v1)) == \
            ("alice", "bob:READ")
        await c.stop()

    run(t())
