"""mClock scheduler + Throttle tests (TestMClockScheduler role)."""
import asyncio

import pytest

from ceph_tpu.cluster.scheduler import (
    BEST_EFFORT,
    CLIENT,
    RECOVERY,
    MClockScheduler,
    Throttle,
)


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def drain(s, n):
    out = []
    for _ in range(n):
        item = s.dequeue()
        if item is None:
            break
        out.append(item)
    return out


def test_reservation_served_first():
    clk = FakeClock()
    s = MClockScheduler({
        CLIENT: (10.0, 1.0, 0.0),     # r advances 0.1s/op
        RECOVERY: (1.0, 1.0, 0.0),    # r advances 1s/op
    }, clock=clk)
    for i in range(3):
        s.enqueue(CLIENT, f"c{i}")
    s.enqueue(RECOVERY, "r0")
    clk.t += 10  # everything's reservation tag is due
    got = drain(s, 4)
    assert set(got) == {"c0", "c1", "c2", "r0"}
    # order respects reservation tags: client ops (0.1 spacing) precede
    # the recovery op's 1s tag only where tags are smaller; first out
    # must be a client op
    assert got[0] == "c0"


def test_weight_shares_spare_capacity():
    clk = FakeClock()
    s = MClockScheduler({
        # zero reservation -> everything is weight-phase
        CLIENT: (0.0, 4.0, 0.0),    # p advances 0.25/op
        RECOVERY: (0.0, 1.0, 0.0),  # p advances 1.0/op
    }, clock=clk)
    for i in range(8):
        s.enqueue(CLIENT, f"c{i}")
    for i in range(8):
        s.enqueue(RECOVERY, f"r{i}")
    got = drain(s, 10)
    # 4:1 weights -> in the first 10 decisions client gets ~4x slots
    assert got.count("r0") + got.count("r1") <= 2
    assert sum(1 for g in got if g.startswith("c")) >= 8 - 1


def test_limit_defers_eligibility():
    clk = FakeClock()
    s = MClockScheduler({
        RECOVERY: (0.0, 1.0, 2.0),  # limit 2 ops/s -> l_tag 0.5 apart
    }, clock=clk)
    for i in range(4):
        s.enqueue(RECOVERY, f"r{i}")
    # l_tags clamp to now then advance 0.5 apart: r0 due immediately,
    # the rest gated at now+0.5, now+1.0, now+1.5
    assert drain(s, 10) == ["r0"]
    assert s.dequeue() is None  # limited
    clk.t += 0.5
    assert s.dequeue() == "r1"
    assert s.dequeue() is None
    clk.t += 10
    assert drain(s, 10) == ["r2", "r3"]
    assert len(s) == 0


def test_idle_class_does_not_bank_credit():
    clk = FakeClock()
    s = MClockScheduler({
        BEST_EFFORT: (0.0, 1.0, 1.0),
    }, clock=clk)
    s.enqueue(BEST_EFFORT, "a")
    assert drain(s, 1) == ["a"]
    clk.t += 1000  # long idle must not allow a burst past the limit
    for i in range(5):
        s.enqueue(BEST_EFFORT, f"b{i}")
    assert len(drain(s, 10)) <= 2  # ~1/s: only the clamped head is due


def test_async_get():
    async def t():
        s = MClockScheduler({CLIENT: (100.0, 1.0, 0.0)})
        s.enqueue(CLIENT, "x")
        assert await asyncio.wait_for(s.get(), 5) == "x"
        fut = asyncio.ensure_future(s.get())
        await asyncio.sleep(0.05)
        assert not fut.done()
        s.enqueue(CLIENT, "y")
        assert await asyncio.wait_for(fut, 5) == "y"

    asyncio.run(t())


def test_throttle():
    async def t():
        th = Throttle(100)
        await th.acquire(60)
        await th.acquire(40)
        assert th.past_midpoint()
        blocked = asyncio.ensure_future(th.acquire(10))
        await asyncio.sleep(0.02)
        assert not blocked.done()
        th.release(60)
        await asyncio.wait_for(blocked, 5)
        th.release(50)
        # oversized request admitted alone when empty
        await asyncio.wait_for(th.acquire(1000), 5)
        th.release(1000)

    asyncio.run(t())
