"""RBD encryption tests: LUKS-role format/open, AES-XTS IO with
boundary read-modify-write, passphrase failure, ciphertext-at-rest,
snapshot passthrough (the librbd/crypto test role)."""
import asyncio

import numpy as np
import pytest

# the AES-GCM key wrap and AES-XTS data path both live on the optional
# `cryptography` package (PR 6's test_auth treatment): skip, don't
# error, in minimal containers
pytest.importorskip("cryptography")

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.osdc.striper import FileLayout
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services import RBD
from ceph_tpu.services.rbd_crypto import (
    BLOCK,
    WrongPassphrase,
    encryption_format,
    open_encrypted,
)

LAYOUT = FileLayout(stripe_unit=16384, stripe_count=1,
                    object_size=16384)


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make(size=256 * 1024):
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rbd", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    rbd = RBD(c.client, 1)
    await rbd.create("vault", size, LAYOUT)
    await encryption_format(rbd, "vault", "hunter2")
    return c, rbd


def test_roundtrip_and_at_rest_ciphertext():
    async def t():
        c, rbd = await make()
        img = await open_encrypted(rbd, "vault", "hunter2")
        rng = np.random.default_rng(11)
        data = rng.integers(0, 256, 3 * BLOCK, dtype=np.uint8).tobytes()
        await img.write(0, data)
        assert await img.read(0, len(data)) == data
        # at rest the RADOS object holds CIPHERTEXT, not the plaintext
        plain = await rbd.open("vault")
        raw = await plain.read(0, len(data))
        assert raw != data and len(raw) == len(data)
        await img.release_lock()
        await c.stop()

    run(t())


def test_unaligned_rmw_and_sparse_reads():
    async def t():
        c, rbd = await make()
        img = await open_encrypted(rbd, "vault", "hunter2")
        # never-written regions read as zeros (sparse contract)
        assert await img.read(0, 100) == b"\x00" * 100
        # partial-block writes at odd offsets round-trip, preserving
        # neighbors through the boundary RMW
        await img.write(1000, b"A" * 50)
        await img.write(BLOCK - 7, b"B" * 20)  # spans a block boundary
        assert await img.read(1000, 50) == b"A" * 50
        assert await img.read(BLOCK - 7, 20) == b"B" * 20
        assert await img.read(950, 50) == b"\x00" * 50
        # overwrite inside one block keeps the rest of the block
        await img.write(1010, b"C" * 10)
        assert await img.read(1000, 30) == (
            b"A" * 10 + b"C" * 10 + b"A" * 10)
        await img.release_lock()
        await c.stop()

    run(t())


def test_wrong_passphrase_and_unformatted():
    async def t():
        c, rbd = await make()
        with pytest.raises(WrongPassphrase):
            await open_encrypted(rbd, "vault", "letmein")
        with pytest.raises(IOError, match="already formatted"):
            await encryption_format(rbd, "vault", "again")
        await rbd.create("plain", 64 * 1024, LAYOUT)
        with pytest.raises(IOError, match="not encryption-formatted"):
            await open_encrypted(rbd, "plain", "x")
        # odd-sized images are rejected at format time (XTS blocks)
        await rbd.create("odd", 4096 + 512, LAYOUT)
        with pytest.raises(IOError, match="multiple"):
            await encryption_format(rbd, "odd", "x")
        await c.stop()

    run(t())


def test_concurrent_subblock_writes_and_resize_guard():
    async def t():
        c, rbd = await make()
        img = await open_encrypted(rbd, "vault", "hunter2")
        # two disjoint sub-block writes into the SAME crypto block,
        # issued concurrently: the write lock serializes their RMW so
        # neither erases the other
        await asyncio.gather(img.write(0, b"A" * 100),
                             img.write(200, b"B" * 100))
        assert await img.read(0, 100) == b"A" * 100
        assert await img.read(200, 100) == b"B" * 100
        assert await img.read(100, 100) == b"\x00" * 100
        # resize must hold the crypto-block invariant format enforced
        with pytest.raises(IOError, match="multiple"):
            await img.resize(BLOCK * 3 + 512)
        await img.resize(BLOCK * 4)
        assert img.size == BLOCK * 4
        await img.release_lock()
        await c.stop()

    run(t())


def test_reopen_discard_and_snapshots():
    async def t():
        c, rbd = await make()
        img = await open_encrypted(rbd, "vault", "hunter2")
        payload = bytes(range(256)) * 64  # 16 KiB
        await img.write(2 * BLOCK, payload)
        await img.snap_create("before")
        await img.write(2 * BLOCK, b"\xff" * len(payload))
        # discard: aligned middle becomes a hole, edges re-encrypt
        await img.discard(2 * BLOCK + 100, BLOCK)
        got = await img.read(2 * BLOCK, len(payload))
        assert got[:100] == b"\xff" * 100
        assert got[100:100 + BLOCK] == b"\x00" * BLOCK
        assert got[100 + BLOCK:] == b"\xff" * (len(payload) - BLOCK - 100)
        await img.release_lock()
        # a fresh open with the same passphrase sees the same bytes
        img2 = await open_encrypted(rbd, "vault", "hunter2")
        assert (await img2.read(2 * BLOCK, 100)) == b"\xff" * 100
        # snapshot read-back through an encrypted snap handle
        await img2.release_lock()
        snap = await open_encrypted(rbd, "vault", "hunter2",
                                    snap="before")
        assert await snap.read(2 * BLOCK, len(payload)) == payload
        await c.stop()

    run(t())
