"""JAX batched CRC32C kernel: bit-exact vs native, seeds, padding."""
import numpy as np
import pytest

from ceph_tpu import native as nt
from ceph_tpu.ops import crc32c as cc


def test_scalar_np_matches_native(rng):
    for n in (0, 1, 3, 4, 9, 64, 1000):
        data = rng.integers(0, 256, n, dtype=np.uint8)
        assert cc.crc32c_np(data, seed=0xFFFFFFFF) == nt.crc32c(data)


def test_zeros_shift_matches_native():
    for n in (0, 1, 7, 255, 256, 4096, 10**6):
        assert cc.zeros_shift(0xDEADBEEF, n) == nt.crc32c(None, seed=0xDEADBEEF, length=n)


@pytest.mark.parametrize("blob_len", [4, 16, 64, 100, 4096, 65536, 1000])
def test_batch_matches_native(rng, blob_len):
    blobs = rng.integers(0, 256, (8, blob_len), dtype=np.uint8)
    got = cc.crc32c_batch(blobs)
    want = nt.crc32c_batch(blobs)
    assert (got == want).all()


def test_batch_seed_variants(rng):
    blobs = rng.integers(0, 256, (4, 512), dtype=np.uint8)
    for seed in (0, 1, 0xFFFFFFFF, 0x12345678):
        got = cc.crc32c_batch(blobs, seed=seed)
        want = np.array([nt.crc32c(b, seed=seed) for b in blobs], dtype=np.uint32)
        assert (got == want).all()


def test_batch_multidim(rng):
    blobs = rng.integers(0, 256, (3, 5, 256), dtype=np.uint8)
    got = cc.crc32c_batch(blobs)
    want = nt.crc32c_batch(blobs.reshape(15, 256)).reshape(3, 5)
    assert (got == want).all()


def test_front_pad_is_neutral(rng):
    # pack_blobs front-pads; check non-power-of-two and non-multiple-of-4
    for n in (5, 12, 100, 1023):
        blobs = rng.integers(0, 256, (2, n), dtype=np.uint8)
        assert (cc.crc32c_batch(blobs) == nt.crc32c_batch(blobs)).all()


def test_single_word_blob(rng):
    blobs = rng.integers(0, 256, (4, 4), dtype=np.uint8)
    assert (cc.crc32c_batch(blobs) == nt.crc32c_batch(blobs)).all()
