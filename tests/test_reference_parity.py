"""Optional bit-compat parity vs the reference's own C CRUSH code.

Compiles the reference's src/crush/{mapper.c,hash.c} in a temp dir (read
only; a stub acconfig.h stands in for its cmake config) and checks our
native crush_ln / hash / straw2 draw against it. Skipped when the
reference checkout is absent. This pins the claim that the generated
crush_ln tables (native/gen_tables.py) and the reimplemented fixed-point
pipeline are placement-bit-compatible with the reference
(src/crush/mapper.c:226-363).
"""
import ctypes
import os
import subprocess

import numpy as np
import pytest

REF = "/root/reference/src/crush"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference checkout not available"
)


@pytest.fixture(scope="module")
def refcrush(tmp_path_factory):
    d = tmp_path_factory.mktemp("refcrush")
    (d / "acconfig.h").write_text("/* stub */\n")
    (d / "harness.c").write_text(
        '#include "mapper.c"\n'
        "unsigned long long ref_crush_ln(unsigned x){return crush_ln(x);}\n"
        "long long ref_draw(int x,int id,int r,unsigned w)"
        "{return generate_exponential_distribution(0,x,id,r,w);}\n"
        "unsigned ref_hash3(unsigned a,unsigned b,unsigned c)"
        "{return crush_hash32_3(0,a,b,c);}\n"
    )
    so = d / "refcrush.so"
    subprocess.run(
        ["gcc", "-O2", "-shared", "-fPIC", f"-I{d}", f"-I{REF}",
         "-I/root/reference/src", "-o", str(so), str(d / "harness.c"),
         f"{REF}/hash.c"],
        check=True, capture_output=True, cwd=REF,
    )
    lib = ctypes.CDLL(str(so))
    lib.ref_crush_ln.restype = ctypes.c_uint64
    lib.ref_crush_ln.argtypes = [ctypes.c_uint32]
    lib.ref_draw.restype = ctypes.c_int64
    lib.ref_draw.argtypes = [ctypes.c_int] * 3 + [ctypes.c_uint32]
    lib.ref_hash3.restype = ctypes.c_uint32
    lib.ref_hash3.argtypes = [ctypes.c_uint32] * 3
    return lib


def test_crush_ln_full_domain(refcrush):
    from ceph_tpu import native as nt

    for u in range(0x10000):
        assert refcrush.ref_crush_ln(u) == nt.crush_ln(u), u


def test_hash3_parity(refcrush):
    from ceph_tpu import native as nt

    rng = np.random.default_rng(0)
    for _ in range(5000):
        a, b, c = (int(v) for v in rng.integers(0, 2**32, 3))
        assert refcrush.ref_hash3(a, b, c) == nt.crush_hash32_3(a, b, c)


def test_straw2_draw_parity(refcrush):
    from ceph_tpu import native as nt

    rng = np.random.default_rng(1)
    for _ in range(5000):
        x, idv, r = (int(v) for v in rng.integers(0, 2**31, 3))
        w = int(rng.integers(1, 2**20))
        assert refcrush.ref_draw(x, idv, r, w) == nt.straw2_draw(x, idv, r, w)
