"""Optional bit-compat parity vs the reference's own C CRUSH code.

Compiles the reference's src/crush/{mapper.c,hash.c} in a temp dir (read
only; a stub acconfig.h stands in for its cmake config) and checks our
native crush_ln / hash / straw2 draw against it. Skipped when the
reference checkout is absent. This pins the claim that the generated
crush_ln tables (native/gen_tables.py) and the reimplemented fixed-point
pipeline are placement-bit-compatible with the reference
(src/crush/mapper.c:226-363).
"""
import ctypes
import os
import subprocess

import numpy as np
import pytest

REF = "/root/reference/src/crush"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference checkout not available"
)


@pytest.fixture(scope="module")
def refcrush(tmp_path_factory):
    d = tmp_path_factory.mktemp("refcrush")
    (d / "acconfig.h").write_text("/* stub */\n")
    (d / "harness.c").write_text(
        '#include "mapper.c"\n'
        "unsigned long long ref_crush_ln(unsigned x){return crush_ln(x);}\n"
        "long long ref_draw(int x,int id,int r,unsigned w)"
        "{return generate_exponential_distribution(0,x,id,r,w);}\n"
        "unsigned ref_hash3(unsigned a,unsigned b,unsigned c)"
        "{return crush_hash32_3(0,a,b,c);}\n"
        "unsigned ref_hash4(unsigned a,unsigned b,unsigned c,unsigned d)"
        "{return crush_hash32_4(0,a,b,c,d);}\n"
        "#include <crush/builder.h>\n"
        "int ref_list_choose(int n,int*items,int*weights,int x,int r){\n"
        "  struct crush_bucket_list *b=crush_make_list_bucket("
        "CRUSH_HASH_RJENKINS1,1,n,items,weights); b->h.id=-1;\n"
        "  return bucket_list_choose(b,x,r);}\n"
        "int ref_tree_choose(int n,int*items,int*weights,int x,int r){\n"
        "  struct crush_bucket_tree *b=crush_make_tree_bucket("
        "CRUSH_HASH_RJENKINS1,1,n,items,weights); b->h.id=-1;\n"
        "  return bucket_tree_choose(b,x,r);}\n"
        "int ref_straw_choose(int n,int*items,int*weights,int x,int r){\n"
        "  struct crush_map *m=crush_create(); m->straw_calc_version=1;\n"
        "  struct crush_bucket_straw *b=crush_make_straw_bucket(m,"
        "CRUSH_HASH_RJENKINS1,1,n,items,weights); b->h.id=-1;\n"
        "  int out=bucket_straw_choose(b,x,r); crush_destroy(m); return out;}\n"
        "int ref_straw_scaler(int n,int*items,int*weights,int i){\n"
        "  struct crush_map *m=crush_create(); m->straw_calc_version=1;\n"
        "  struct crush_bucket_straw *b=crush_make_straw_bucket(m,"
        "CRUSH_HASH_RJENKINS1,1,n,items,weights);\n"
        "  int out=b->straws[i]; crush_destroy(m); return out;}\n"
    )
    so = d / "refcrush.so"
    subprocess.run(
        ["gcc", "-O2", "-shared", "-fPIC", f"-I{d}", f"-I{REF}",
         "-I/root/reference/src", "-o", str(so), str(d / "harness.c"),
         f"{REF}/hash.c", f"{REF}/builder.c", f"{REF}/crush.c", "-lm"],
        check=True, capture_output=True, cwd=REF,
    )
    lib = ctypes.CDLL(str(so))
    lib.ref_crush_ln.restype = ctypes.c_uint64
    lib.ref_crush_ln.argtypes = [ctypes.c_uint32]
    lib.ref_draw.restype = ctypes.c_int64
    lib.ref_draw.argtypes = [ctypes.c_int] * 3 + [ctypes.c_uint32]
    lib.ref_hash3.restype = ctypes.c_uint32
    lib.ref_hash3.argtypes = [ctypes.c_uint32] * 3
    lib.ref_hash4.restype = ctypes.c_uint32
    lib.ref_hash4.argtypes = [ctypes.c_uint32] * 4
    iptr = ctypes.POINTER(ctypes.c_int)
    for fn in ("ref_list_choose", "ref_tree_choose", "ref_straw_choose"):
        f = getattr(lib, fn)
        f.restype = ctypes.c_int
        f.argtypes = [ctypes.c_int, iptr, iptr, ctypes.c_int, ctypes.c_int]
    lib.ref_straw_scaler.restype = ctypes.c_int
    lib.ref_straw_scaler.argtypes = [ctypes.c_int, iptr, iptr, ctypes.c_int]
    return lib


def _carr(vals):
    return (ctypes.c_int * len(vals))(*vals)


def test_crush_ln_full_domain(refcrush):
    from ceph_tpu import native as nt

    for u in range(0x10000):
        assert refcrush.ref_crush_ln(u) == nt.crush_ln(u), u


def test_hash3_parity(refcrush):
    from ceph_tpu import native as nt

    rng = np.random.default_rng(0)
    for _ in range(5000):
        a, b, c = (int(v) for v in rng.integers(0, 2**32, 3))
        assert refcrush.ref_hash3(a, b, c) == nt.crush_hash32_3(a, b, c)


def test_straw2_draw_parity(refcrush):
    from ceph_tpu import native as nt

    rng = np.random.default_rng(1)
    for _ in range(5000):
        x, idv, r = (int(v) for v in rng.integers(0, 2**31, 3))
        w = int(rng.integers(1, 2**20))
        assert refcrush.ref_draw(x, idv, r, w) == nt.straw2_draw(x, idv, r, w)


def test_hash4_parity(refcrush):
    from ceph_tpu.placement.crushmap import crush_hash32_4

    rng = np.random.default_rng(2)
    for _ in range(5000):
        a, b, c, d = (int(v) for v in rng.integers(0, 2**32, 4))
        assert refcrush.ref_hash4(a, b, c, d) == crush_hash32_4(a, b, c, d)


def _rand_bucket(rng, alg):
    from ceph_tpu.placement.crushmap import Bucket

    n = int(rng.integers(2, 12))
    items = list(range(n))
    weights = [int(w) for w in rng.integers(1, 0x40000, n)]
    return Bucket(id=-1, type_id=1, alg=alg, items=items,
                  weights=weights), items, weights


@pytest.mark.parametrize("alg,ref_fn", [
    ("list", "ref_list_choose"),
    ("tree", "ref_tree_choose"),
    ("straw", "ref_straw_choose"),
])
def test_legacy_bucket_choose_parity(refcrush, alg, ref_fn):
    """The pre-straw2 bucket algorithms must match the reference's own
    builder + mapper bit-for-bit (mapper.c bucket_*_choose)."""
    from ceph_tpu.placement.crushmap import CrushMap

    rng = np.random.default_rng(hash(alg) % 2**31)
    m = CrushMap()
    ref = getattr(refcrush, ref_fn)
    for _ in range(8):
        b, items, weights = _rand_bucket(rng, alg)
        m.add_bucket(b)
        for _ in range(200):
            x = int(rng.integers(0, 2**31))
            r = int(rng.integers(0, 8))
            want = ref(len(items), _carr(items), _carr(weights), x, r)
            got = m.bucket_choose(b, x, r)
            assert got == want, f"{alg} x={x} r={r} w={weights}"


def test_straw_scaler_parity(refcrush):
    """crush_calc_straw v1 scalers match builder.c exactly."""
    from ceph_tpu.placement.crushmap import calc_straw_scalers

    rng = np.random.default_rng(55)
    for _ in range(30):
        n = int(rng.integers(1, 10))
        items = list(range(n))
        weights = [int(w) for w in rng.integers(0, 0x30000, n)]
        ours = calc_straw_scalers(weights)
        for i in range(n):
            want = refcrush.ref_straw_scaler(
                n, _carr(items), _carr(weights), i
            )
            assert ours[i] == want, f"weights={weights} i={i}"
