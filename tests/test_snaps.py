"""RADOS snapshots end-to-end: SnapSet model, clone-on-write,
read-at-snap, whiteouts, trimming, kill/revive survival.

Reference arcs: PrimaryLogPG::make_writeable (PrimaryLogPG.cc:8526)
lazy clone creation, find_object_context snap resolution, SnapTrimmer
driven by pool removed_snaps, librados selfmanaged snap API.
"""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster import snaps as sn
from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2"}


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, 120))
    finally:
        loop.close()


# ------------------------------------------------------- SnapSet model


def test_resolve_clone_membership():
    ss = sn.SnapSet(seq=5, clones=[sn.Clone(5, [5, 4, 3])])
    assert ss.resolve(4) == 5       # preserved by the clone
    assert ss.resolve(1) is None    # predates the object (ADVICE high)
    assert ss.resolve(6) == sn.NOSNAP
    assert ss.resolve(sn.NOSNAP) == sn.NOSNAP


def test_resolve_trimmed_hole():
    ss = sn.SnapSet(seq=5, clones=[sn.Clone(5, [5, 3])])
    assert ss.resolve(4) is None    # trimmed out of the covering clone
    assert ss.resolve(3) == 5


def test_resolve_all_clones_trimmed():
    # seq stays at 5 but clones are gone: history reads must not leak
    # head data (ADVICE medium)
    ss = sn.SnapSet(seq=5, clones=[])
    assert ss.resolve(3) is None
    assert ss.resolve(6) == sn.NOSNAP


def test_snapset_encode_roundtrip():
    ss = sn.SnapSet(seq=9, clones=[sn.Clone(4, [4, 2], 100),
                                   sn.Clone(9, [9], 5000)])
    dec, _ = sn.SnapSet.decode(ss.encode())
    assert dec == ss


def test_interval_ops():
    iv = sn.interval_insert([], 3, 4)
    iv = sn.interval_insert(iv, 7, 8)
    iv = sn.interval_insert(iv, 4, 7)
    assert iv == [(3, 8)]
    assert sn.interval_contains(iv, 5)
    assert not sn.interval_contains(iv, 8)
    assert sn.interval_diff_ids([(3, 8)], [(4, 6)]) == [3, 6, 7]


def test_clone_oid_roundtrip():
    coid = sn.clone_oid(b"my-object", 77)
    assert sn.is_clone_oid(coid)
    assert not sn.is_clone_oid(b"my-object")
    assert sn.parse_clone_oid(coid) == (b"my-object", 77)


# ------------------------------------------------------------ clusters


async def make_rep(n=4):
    c = TestCluster(n_osds=n)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0))
    await c.wait_active(20)
    return c


async def make_ec(n=5):
    c = TestCluster(n_osds=n)
    await c.start()
    await c.client.create_pool(
        Pool(id=2, name="ec", size=5, min_size=3, pg_num=4, crush_rule=1,
             type="erasure", ec_profile=dict(EC_PROFILE)))
    await c.wait_active(20)
    return c


class SnapCtx:
    """Client-side selfmanaged SnapContext bookkeeping (the librados
    IoCtx snap-write-context role)."""

    def __init__(self, client, pool_id):
        self.client = client
        self.pool_id = pool_id
        self.seq = 0
        self.snaps: list[int] = []  # descending

    async def create(self) -> int:
        snapid = await self.client.selfmanaged_snap_create(self.pool_id)
        self.seq = snapid
        self.snaps.insert(0, snapid)
        return snapid

    async def remove(self, snapid: int) -> None:
        await self.client.selfmanaged_snap_remove(self.pool_id, snapid)
        if snapid in self.snaps:
            self.snaps.remove(snapid)

    @property
    def ctx(self):
        return (self.seq, list(self.snaps))


@pytest.mark.parametrize("pool_id,factory", [(1, make_rep), (2, make_ec)])
def test_snap_write_overwrite_read_at_snap(pool_id, factory):
    async def t():
        c = await factory()
        sc = SnapCtx(c.client, pool_id)
        v1 = b"version-one" * 700
        await c.client.write_full(pool_id, "o", v1, snapc=sc.ctx)
        s1 = await sc.create()
        v2 = b"version-TWO" * 900
        await c.client.write_full(pool_id, "o", v2, snapc=sc.ctx)
        s2 = await sc.create()
        # partial overwrite after second snap
        await c.client.write(pool_id, "o", 5, b"PATCH", snapc=sc.ctx)
        v3 = bytearray(v2)
        v3[5:10] = b"PATCH"

        assert await c.client.read(pool_id, "o") == bytes(v3)
        assert await c.client.read(pool_id, "o", snapid=s1) == v1
        assert await c.client.read(pool_id, "o", snapid=s2) == v2
        assert await c.client.stat(pool_id, "o", snapid=s1) == len(v1)
        # reads at a snap predating the object: ENOENT
        with pytest.raises(KeyError):
            await c.client.read(pool_id, "o2", snapid=s1)
        await c.stop()

    run(t())


@pytest.mark.parametrize("pool_id,factory", [(1, make_rep), (2, make_ec)])
def test_snap_delete_head_keeps_clones(pool_id, factory):
    async def t():
        c = await factory()
        sc = SnapCtx(c.client, pool_id)
        keep = b"keep-me" * 512
        await c.client.write_full(pool_id, "o", keep, snapc=sc.ctx)
        s1 = await sc.create()
        await c.client.delete(pool_id, "o", snapc=sc.ctx)
        # head is gone...
        with pytest.raises(KeyError):
            await c.client.read(pool_id, "o")
        with pytest.raises(KeyError):
            await c.client.stat(pool_id, "o")
        assert b"o" not in await c.client.list_objects(pool_id)
        # ...but the snapshot still serves the data (whiteout role)
        assert await c.client.read(pool_id, "o", snapid=s1) == keep
        # recreating the head works and the snap still resolves
        await c.client.write_full(pool_id, "o", b"new", snapc=sc.ctx)
        assert await c.client.read(pool_id, "o") == b"new"
        assert await c.client.read(pool_id, "o", snapid=s1) == keep
        await c.stop()

    run(t())


@pytest.mark.parametrize("pool_id,factory", [(1, make_rep), (2, make_ec)])
def test_snap_trim_reclaims_clones(pool_id, factory):
    async def t():
        c = await factory()
        sc = SnapCtx(c.client, pool_id)
        v1 = b"A" * 3000
        await c.client.write_full(pool_id, "o", v1, snapc=sc.ctx)
        s1 = await sc.create()
        await c.client.write_full(pool_id, "o", b"B" * 100, snapc=sc.ctx)
        assert await c.client.read(pool_id, "o", snapid=s1) == v1
        await sc.remove(s1)
        # trimming is async: wait for the clone object to disappear
        for _ in range(100):
            try:
                got = await c.client.read(pool_id, "o", snapid=s1)
            except KeyError:
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError(f"snap {s1} still readable: {got!r}")
        assert await c.client.read(pool_id, "o") == b"B" * 100
        await c.stop()

    run(t())


def test_snap_trim_whiteout_head_reclaimed():
    async def t():
        c = await make_rep()
        sc = SnapCtx(c.client, 1)
        await c.client.write_full(1, "o", b"x" * 100, snapc=sc.ctx)
        s1 = await sc.create()
        await c.client.delete(1, "o", snapc=sc.ctx)
        assert await c.client.read(1, "o", snapid=s1) == b"x" * 100
        await sc.remove(s1)
        for _ in range(100):
            try:
                await c.client.read(1, "o", snapid=s1)
            except KeyError:
                break
            await asyncio.sleep(0.05)
        # head shell (whiteout) must be gone from the store too
        pgid = c.client.osdmap.object_to_pg(1, b"o")
        up, _ = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        cid = f"{pgid[0]}.{pgid[1]}"
        for o in up:
            store = c.stores[o]
            if cid in store.list_collections():
                assert b"o" not in store.list_objects(cid)
        await c.stop()

    run(t())


def test_snaps_survive_kill_revive():
    async def t():
        c = await make_ec()
        sc = SnapCtx(c.client, 2)
        rng = np.random.default_rng(5)
        v1 = bytes(rng.integers(0, 256, 50_000, dtype=np.uint8))
        await c.client.write_full(2, "o", v1, snapc=sc.ctx)
        s1 = await sc.create()
        await c.client.write(2, "o", 1000, b"Y" * 20_000, snapc=sc.ctx)
        v2 = bytearray(v1)
        v2[1000:21_000] = b"Y" * 20_000

        pgid = c.client.osdmap.object_to_pg(2, b"o")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        await c.kill_osd(victim)
        await c.wait_down(victim, 20)
        # degraded: both head and snap readable
        assert await c.client.read(2, "o") == bytes(v2)
        assert await c.client.read(2, "o", snapid=s1) == v1
        # write while degraded, then revive: clone must recover too
        await c.client.write(2, "o", 0, b"Z" * 500, snapc=sc.ctx)
        v2[0:500] = b"Z" * 500
        await c.revive_osd(victim)
        await c.wait_active(30)
        up2, primary2 = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        others = [o for o in up2 if o not in (victim, primary2)][:2]
        for o in others:
            await c.kill_osd(o)
            await c.wait_down(o, 20)
        # the revived shard now serves both head and clone reconstruction
        assert await c.client.read(2, "o") == bytes(v2)
        assert await c.client.read(2, "o", snapid=s1) == v1
        await c.stop()

    run(t())


def test_write_to_snap_rejected():
    async def t():
        c = await make_rep()
        sc = SnapCtx(c.client, 1)
        await c.client.write_full(1, "o", b"data", snapc=sc.ctx)
        s1 = await sc.create()
        with pytest.raises(IOError):
            await c.client._submit(
                1, "o",
                [__import__("ceph_tpu.cluster.messages",
                            fromlist=["osd_op"]).osd_op(
                                "writefull", data=b"nope")],
                snapid=s1)
        await c.stop()

    run(t())
