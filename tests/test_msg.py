"""Wire layer: frames, typed messages, TCP messenger, map encodings.

The direct_messenger / msgr test role (SURVEY §4.2, src/test/msgr/).
"""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster import messages as M
from ceph_tpu.msg import frames
from ceph_tpu.msg.messenger import TcpMessenger
from ceph_tpu.placement import crushmap as cm
from ceph_tpu.placement import encoding as menc
from ceph_tpu.placement.osdmap import Incremental, OSDMap, Pool


def test_frame_roundtrip():
    f = frames.Frame(type=7, payload=b"hello world" * 100)
    wire = frames.encode_frame(f)
    got, used = frames.decode_frame(wire)
    assert used == len(wire)
    assert got.type == 7 and got.payload == f.payload


def test_frame_crc_detects_corruption():
    wire = bytearray(frames.encode_frame(frames.Frame(1, b"payload")))
    wire[14] ^= 0x40
    with pytest.raises(frames.FrameError):
        frames.decode_frame(bytes(wire))


def test_frame_incomplete():
    wire = frames.encode_frame(frames.Frame(1, b"x" * 64))
    with pytest.raises(frames.IncompleteFrame):
        frames.decode_frame(wire[:10])
    with pytest.raises(frames.IncompleteFrame):
        frames.decode_frame(wire[:-1])


def test_message_roundtrips():
    samples = [
        M.MOSDBoot(osd=3),
        M.MOSDMapMsg(full=b"mapbytes", incrementals=[b"a", b"bb"], epoch=9),
        M.MOSDOp(tid=5, pgid=(1, 7), oid=b"obj",
                 ops=[M.osd_op("writefull", data=b"\x00\x01" * 50),
                      M.osd_op("setxattr", key=b"k", data=b"v"),
                      M.osd_op("omap_setkeys", kv={b"a": b"1"}),
                      M.osd_op("omap_rmkeys", keys=[b"z"])],
                 epoch=4),
        M.MOSDOpReply(tid=5, result=0, data=b"x", size=1,
                      outs=[(0, b"x"), (-2, b"")], epoch=4),
        M.MECSubWrite(tid=1, pgid=(2, 3), shard=4, txn=b"t", entry=b"e",
                      epoch=2),
        M.MECSubReadReply(tid=1, pgid=(2, 3), shard=4, result=0,
                          data=b"chunk", digest=0xDEADBEEF, size=123,
                          attrs={"u:meta": b"m"}),
        M.MPushOp(pgid=(1, 2), shard=-1, oid=b"o", version=(3, 9),
                  data=b"d", attrs={"v": b"\x01", "hinfo": b"\x02"},
                  epoch=3, last_update=(3, 11)),
        M.MPGScanReply(pgid=(1, 2), shard=0,
                       objects={b"a": (1, 2), b"b": (3, 4)}),
    ]
    from ceph_tpu.msg.messages import decode_message

    for msg in samples:
        got = decode_message(msg.TYPE, msg.encode())
        assert got == msg, msg


def test_tcp_messenger_roundtrip():
    async def run():
        got = []
        done = asyncio.Event()

        async def dispatch_a(src, msg):
            got.append(("a", src, msg))
            done.set()

        async def dispatch_b(src, msg):
            got.append(("b", src, msg))
            await b.send(src, M.MOSDBoot(osd=99))

        a = TcpMessenger("client.1", dispatch_a)
        b = TcpMessenger("osd.0", dispatch_b)
        host, port_b = await b.listen()
        host_a, port_a = await a.listen()
        a.addrbook["osd.0"] = (host, port_b)
        b.addrbook["client.1"] = (host_a, port_a)
        await a.send("osd.0", M.MOSDOp(tid=1, pgid=(1, 0), oid=b"x",
                                       ops=[M.osd_op("read")], epoch=1))
        await asyncio.wait_for(done.wait(), 5)
        await a.close()
        await b.close()
        assert got[0][0] == "b" and got[0][1] == "client.1"
        assert isinstance(got[0][2], M.MOSDOp)
        assert got[1] == ("a", "osd.0", M.MOSDBoot(osd=99))

    asyncio.run(run())


def test_crushmap_encoding_roundtrip():
    m = cm.build_hierarchy(osds_per_host=3, n_hosts=4)
    m.add_rule(cm.replicated_rule(0, root=-1, failure_domain_type=1))
    m.add_rule(cm.ec_rule(1, root=-1, failure_domain_type=1))
    m2, used = menc.decode_crushmap(menc.encode_crushmap(m))
    assert used == len(menc.encode_crushmap(m))
    # placement-equivalent: identical do_rule results
    w = np.full(m.max_devices, 0x10000, dtype=np.uint32)
    for x in range(50):
        assert m.do_rule(0, x, 3, w) == m2.do_rule(0, x, 3, w)
        assert m.do_rule(1, x, 5, w) == m2.do_rule(1, x, 5, w)


def test_osdmap_encoding_roundtrip():
    crush = cm.build_flat(6)
    crush.add_rule(cm.flat_firstn_rule(0))
    m = OSDMap(crush, 6)
    m.add_pool(Pool(id=1, name="p", size=3, pg_num=16, crush_rule=0))
    m.add_pool(Pool(id=2, name="e", size=5, pg_num=8, crush_rule=0,
                    type="erasure", ec_profile={"k": "3", "m": "2"}))
    m.osds[2].up = False
    m.osds[4].weight = 0x8000
    m.pg_upmap[(1, 3)] = [5, 0, 1]
    m.pg_upmap_items[(1, 4)] = [(0, 5)]
    m.pg_upmap_primaries[(1, 5)] = 2
    m2, _ = menc.decode_osdmap(menc.encode_osdmap(m))
    assert m2.epoch == m.epoch and len(m2.osds) == 6
    assert m2.pools[2].ec_profile == {"k": "3", "m": "2"}
    for pool in (1, 2):
        for ps in range(m.pools[pool].pg_num):
            assert m.pg_to_up_acting_osds((pool, ps)) == \
                m2.pg_to_up_acting_osds((pool, ps))


def test_incremental_encoding_roundtrip():
    inc = Incremental(epoch=4, up=[1], down=[2, 3],
                      weights={0: 0, 5: 0x10000},
                      new_pools=[Pool(id=9, name="x", size=2, pg_num=4)],
                      new_pg_upmap={(1, 2): [3, 4]},
                      new_pg_upmap_items={(1, 3): [(0, 1)]},
                      new_pg_upmap_primaries={(1, 4): 2, (1, 5): None})
    inc2, _ = menc.decode_incremental(menc.encode_incremental(inc))
    assert inc2 == inc


def test_lazy_subop_fields_wire_roundtrip():
    """MECSubWrite/MOSDRepOp accept LIVE Transaction/entry-list objects
    (LocalBus ships them by reference); the WIRE encode must marshal
    them identically to pre-encoded bytes, or the process tier would
    corrupt shard sub-ops (round-5 zero-copy change)."""
    from ceph_tpu.cluster import messages as M
    from ceph_tpu.cluster.pglog import Entry
    from ceph_tpu.cluster.pg import enc_entries
    from ceph_tpu.store import transaction as tx

    t = tx.Transaction()
    t.touch("1.0s0", b"obj")
    t.write("1.0s0", b"obj", 0, b"payload-bytes" * 100)
    t.setattr("1.0s0", b"obj", "k", b"v")
    entries = [Entry("modify", b"obj", (3, 7), (3, 6),
                     reqid=("client.0", 42))]

    live = M.MECSubWrite(tid=1, pgid=(1, 0), shard=0, txn=t,
                         entry=entries, epoch=3, hpatch=b"hp",
                         ncells=1, size=1300, prev_head=(3, 6))
    pre = M.MECSubWrite(tid=1, pgid=(1, 0), shard=0, txn=t.encode(),
                        entry=enc_entries(entries), epoch=3,
                        hpatch=b"hp", ncells=1, size=1300,
                        prev_head=(3, 6))
    assert live.encode() == pre.encode()
    dec = M.MECSubWrite.decode(live.encode())
    t2, _ = tx.Transaction.decode(dec.txn)
    assert [op.code for op in t2.ops] == [op.code for op in t.ops]

    live_r = M.MOSDRepOp(tid=2, pgid=(1, 1), txn=t, entry=entries,
                         epoch=3, prev_head=(3, 6))
    pre_r = M.MOSDRepOp(tid=2, pgid=(1, 1), txn=t.encode(),
                        entry=enc_entries(entries), epoch=3,
                        prev_head=(3, 6))
    assert live_r.encode() == pre_r.encode()
