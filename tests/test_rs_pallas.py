"""Pallas GF(2^8) matmul kernel: bit-exactness vs the host byte oracle.

The real kernel runs on TPU; under the CPU test mesh it runs in Pallas
interpreter mode — same jaxpr, same semantics, so a pass here plus the
TPU-side bench guard (bench.py checks device parity vs the C++ core on
the real chip) covers both halves.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from ceph_tpu import native
from ceph_tpu.ops import gf8, rs


@pytest.mark.parametrize(
    "r,c,w",
    [(3, 8, 1024), (1, 2, 128), (8, 8, 512), (4, 6, 384), (2, 5, 256)],
)
def test_pallas_matches_host_oracle(r, c, w):
    rng = np.random.default_rng(r * 100 + c)
    mat = rng.integers(0, 256, (r, c), dtype=np.uint8)
    data = rng.integers(0, 256, (3, c, w * 4), dtype=np.uint8)
    want = np.stack([gf8.gf_matmul(mat, d) for d in data])
    got = rs.gf_matmul_pallas(mat, jnp.asarray(rs.pack_u32(data)),
                              interpret=True)
    assert (rs.unpack_u32(np.asarray(got)) == want).all()


def test_pallas_2d_no_batch():
    rng = np.random.default_rng(9)
    mat = native.rs_matrix_vandermonde(4, 2)
    data = rng.integers(0, 256, (4, 2048), dtype=np.uint8)
    want = gf8.gf_matmul(mat, data)
    got = rs.gf_matmul_pallas(mat, jnp.asarray(rs.pack_u32(data)),
                              interpret=True)
    assert (rs.unpack_u32(np.asarray(got)) == want).all()


def test_pallas_unaligned_width_falls_back():
    # W=100 words has no 128-multiple tile; must still be correct (einsum).
    rng = np.random.default_rng(3)
    mat = native.rs_matrix_vandermonde(3, 2)
    data = rng.integers(0, 256, (3, 400), dtype=np.uint8)
    want = gf8.gf_matmul(mat, data)
    got = rs.gf_matmul_pallas(mat, jnp.asarray(rs.pack_u32(data)))
    assert (rs.unpack_u32(np.asarray(got)) == want).all()


def test_lift_bitmatrix_planar_permutation():
    rng = np.random.default_rng(5)
    mat = rng.integers(0, 256, (3, 4), dtype=np.uint8)
    bm = rs._lift_bitmatrix(mat)
    bmp = rs._lift_bitmatrix_planar(mat)
    r, c = mat.shape
    for rr in range(r):
        for i in range(8):
            for cc in range(c):
                for j in range(8):
                    assert bmp[i * r + rr, j * c + cc] == bm[rr * 8 + i, cc * 8 + j]


def test_pallas_tile_selection():
    assert rs._pallas_tile(1024) == 1024
    assert rs._pallas_tile(131072) == 8192
    assert rs._pallas_tile(100) is None
    assert rs._pallas_tile(384) == 384
    t = rs._pallas_tile(1280)
    assert t is not None and 1280 % t == 0 and t % 128 == 0


def test_crc_pallas_matches_tree():
    """The MXU matmul CRC (kept as a documented alternative; the VPU
    tree measured faster and stays default) is bit-exact vs the host."""
    import jax.numpy as jnp

    from ceph_tpu.ops import crc32c as crc_ops

    rng = np.random.default_rng(7)
    for nb, blob in [(5, 1024), (130, 4096), (8, 65536)]:
        blobs = rng.integers(0, 256, (nb, blob), dtype=np.uint8)
        words = jnp.asarray(crc_ops.pack_blobs(blobs))
        got = np.asarray(
            crc_ops.crc32c_words_pallas(words, interpret=True))
        want = native.crc32c_batch(blobs) ^ np.uint32(
            crc_ops.zeros_shift(0xFFFFFFFF, blob))
        assert (got == want).all(), (nb, blob)
