"""RGW-lite tests: bucket/object API, listings, multipart, and the
HTTP frontend driven over a real socket (the s3-tests role, shrunk)."""
import asyncio
import hashlib

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.rgw import RGWError, RGWLite, S3Frontend


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rgw", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c, RGWLite(c.client, 1)


def test_bucket_lifecycle():
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("alpha")
        await rgw.create_bucket("beta")
        with pytest.raises(RGWError, match="BucketAlreadyExists"):
            await rgw.create_bucket("alpha")
        with pytest.raises(RGWError, match="InvalidBucketName"):
            await rgw.create_bucket("bad/name")
        assert await rgw.list_buckets() == ["alpha", "beta"]
        await rgw.put_object("alpha", "k", b"v")
        with pytest.raises(RGWError, match="BucketNotEmpty"):
            await rgw.delete_bucket("alpha")
        await rgw.delete_object("alpha", "k")
        await rgw.delete_bucket("alpha")
        assert await rgw.list_buckets() == ["beta"]
        with pytest.raises(RGWError, match="NoSuchBucket"):
            await rgw.put_object("gone", "k", b"v")
        await c.stop()

    run(t())


def test_object_roundtrip_and_listing():
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b")
        data = b"hello s3 world"
        etag = await rgw.put_object("b", "docs/readme.txt", data)
        assert etag == hashlib.md5(data).hexdigest()
        got, meta = await rgw.get_object("b", "docs/readme.txt")
        assert got == data and meta["etag"] == etag
        for k in ("docs/a", "docs/b", "logs/1", "logs/2", "zzz"):
            await rgw.put_object("b", k, k.encode())
        entries, trunc = await rgw.list_objects("b")
        keys = [e["key"] for e in entries]
        assert keys == sorted(keys) and not trunc
        docs, _ = await rgw.list_objects("b", prefix="docs/")
        assert [e["key"] for e in docs] == ["docs/a", "docs/b",
                                           "docs/readme.txt"]
        page1, trunc = await rgw.list_objects("b", max_keys=2)
        assert len(page1) == 2 and trunc
        page2, _ = await rgw.list_objects("b", marker=page1[-1]["key"])
        assert page2[0]["key"] > page1[-1]["key"]
        # overwrite changes etag; copy preserves content
        await rgw.put_object("b", "zzz", b"new")
        await rgw.copy_object("b", "zzz", "b", "zzz-copy")
        got2, _ = await rgw.get_object("b", "zzz-copy")
        assert got2 == b"new"
        await rgw.delete_object("b", "zzz")
        with pytest.raises(RGWError, match="NoSuchKey"):
            await rgw.get_object("b", "zzz")
        await c.stop()

    run(t())


def test_multipart_upload():
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("mp")
        upload = await rgw.initiate_multipart("mp", "big")
        rng = np.random.default_rng(5)
        parts = [rng.integers(0, 256, 50_000, dtype=np.uint8).tobytes()
                 for _ in range(3)]
        for i, p in enumerate(parts, start=1):
            await rgw.upload_part("mp", "big", upload, i, p)
        etag = await rgw.complete_multipart("mp", "big", upload,
                                            [1, 2, 3])
        assert etag.endswith("-3")
        got, meta = await rgw.get_object("mp", "big")
        assert got == b"".join(parts)
        assert meta["size"] == 150_000 and meta["multipart"]
        await rgw.delete_object("mp", "big")
        entries, _ = await rgw.list_objects("mp")
        assert entries == []
        await c.stop()

    run(t())


async def http(host, port, method, path, body=b"", headers=None):
    reader, writer = await asyncio.open_connection(host, port)
    head = [f"{method} {path} HTTP/1.1", f"host: {host}",
            f"content-length: {len(body)}"]
    for k, v in (headers or {}).items():
        head.append(f"{k}: {v}")
    writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
    await writer.drain()
    status_line = await reader.readline()
    status = int(status_line.split()[1])
    rheaders = {}
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n"):
            break
        k, v = h.decode().split(":", 1)
        rheaders[k.strip().lower()] = v.strip()
    n = int(rheaders.get("content-length", "0"))
    # HEAD advertises the entity length but carries no body
    rbody = await reader.readexactly(n) if n and method != "HEAD" else b""
    writer.close()
    return status, rheaders, rbody


def test_http_frontend():
    async def t():
        c, rgw = await make()
        fe = S3Frontend(rgw)
        host, port = await fe.start()
        assert (await http(host, port, "PUT", "/photos"))[0] == 200
        st, hd, _ = await http(host, port, "PUT", "/photos/cat.jpg",
                               b"MEOW" * 100)
        assert st == 200 and hd["etag"].strip('"') == hashlib.md5(
            b"MEOW" * 100
        ).hexdigest()
        st, hd, body = await http(host, port, "GET", "/photos/cat.jpg")
        assert st == 200 and body == b"MEOW" * 100
        st, hd, _ = await http(host, port, "HEAD", "/photos/cat.jpg")
        assert st == 200 and hd["content-length"] == "400"
        # copy via x-amz-copy-source
        st, _, _ = await http(host, port, "PUT", "/photos/cat2.jpg",
                              headers={"x-amz-copy-source":
                                       "/photos/cat.jpg"})
        assert st == 200
        st, _, body = await http(host, port, "GET",
                                 "/photos?prefix=cat")
        assert st == 200 and b"<Key>cat.jpg</Key>" in body \
            and b"<Key>cat2.jpg</Key>" in body
        st, _, body = await http(host, port, "GET", "/")
        assert b"<Name>photos</Name>" in body
        assert (await http(host, port, "DELETE",
                           "/photos/cat.jpg"))[0] == 204
        st, _, body = await http(host, port, "GET", "/photos/cat.jpg")
        assert st == 404 and b"NoSuchKey" in body
        await fe.stop()
        await c.stop()

    run(t())


def test_cls_bucket_index_stats():
    """The bucket index is cls-served: every update maintains count and
    byte totals atomically server-side (cls_rgw stats role)."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("s")
        st = await rgw.bucket_stats("s")
        assert (st["count"], st["bytes"]) == (0, 0)
        await rgw.put_object("s", "a", b"x" * 100)
        await rgw.put_object("s", "b", b"y" * 250)
        st = await rgw.bucket_stats("s")
        assert (st["count"], st["bytes"]) == (2, 350)
        await rgw.put_object("s", "a", b"z" * 10)  # overwrite re-accounts
        st = await rgw.bucket_stats("s")
        assert (st["count"], st["bytes"]) == (2, 260)
        await rgw.delete_object("s", "b")
        st = await rgw.bucket_stats("s")
        assert (st["count"], st["bytes"]) == (1, 10)
        assert st["generation"] == 4  # one bump per index mutation
        await c.stop()

    run(t())


def _signed_headers(method, path, query, body, host, access, secret,
                    amz_date=None):
    import time as _time

    from ceph_tpu.services.rgw import _sha256, sigv4_sign

    if amz_date is None:  # fresh: inside the frontend's skew window
        amz_date = _time.strftime("%Y%m%dT%H%M%SZ", _time.gmtime())
    headers = {
        "host": host,
        "x-amz-content-sha256": _sha256(body),
        "x-amz-date": amz_date,
    }
    headers["authorization"] = sigv4_sign(
        method, path, query, headers, body, access, secret, amz_date)
    return headers


def test_sigv4_auth():
    """Frontend with a user table: correctly signed requests pass,
    bad signatures / unknown keys / tampered bodies get 403."""
    async def t():
        import urllib.request

        c, rgw = await make()
        fe = S3Frontend(rgw, users={"AKIDEXAMPLE": "s3cr3t"})
        host, port = await fe.start()
        base = f"http://{host}:{port}"
        hosthdr = f"{host}:{port}"

        def req(method, path, body=b"", headers=None, query=""):
            url = base + path + (f"?{query}" if query else "")
            r = urllib.request.Request(url, data=body or None,
                                       method=method)
            for k, v in (headers or {}).items():
                r.add_header(k, v)
            try:
                with urllib.request.urlopen(r) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()

        loop = asyncio.get_running_loop()

        async def areq(*a, **kw):
            return await loop.run_in_executor(None,
                                              lambda: req(*a, **kw))

        # unauthenticated: rejected
        status, body = await areq("PUT", "/b1")
        assert status == 403 and b"AccessDenied" in body
        # signed bucket create + object put + get round-trip
        h = _signed_headers("PUT", "/b1", "", b"", hosthdr,
                            "AKIDEXAMPLE", "s3cr3t")
        status, _ = await areq("PUT", "/b1", headers=h)
        assert status == 200
        payload = b"signed payload"
        h = _signed_headers("PUT", "/b1/k", "", payload, hosthdr,
                            "AKIDEXAMPLE", "s3cr3t")
        status, _ = await areq("PUT", "/b1/k", body=payload, headers=h)
        assert status == 200
        h = _signed_headers("GET", "/b1/k", "", b"", hosthdr,
                            "AKIDEXAMPLE", "s3cr3t")
        status, body = await areq("GET", "/b1/k", headers=h)
        assert status == 200 and body == payload
        # wrong secret -> 403
        h = _signed_headers("GET", "/b1/k", "", b"", hosthdr,
                            "AKIDEXAMPLE", "WRONG")
        status, body = await areq("GET", "/b1/k", headers=h)
        assert status == 403 and b"SignatureDoesNotMatch" in body
        # unknown access key -> 403
        h = _signed_headers("GET", "/b1/k", "", b"", hosthdr,
                            "NOBODY", "s3cr3t")
        status, body = await areq("GET", "/b1/k", headers=h)
        assert status == 403 and b"InvalidAccessKeyId" in body
        # tampered body (hash mismatch) -> 403
        h = _signed_headers("PUT", "/b1/k2", "", b"original", hosthdr,
                            "AKIDEXAMPLE", "s3cr3t")
        status, body = await areq("PUT", "/b1/k2", body=b"tampered",
                                  headers=h)
        assert status == 403
        # stale replay: a validly signed request whose x-amz-date is
        # outside the 15-min skew window is rejected (round-3 advisor:
        # without this a captured request replays forever)
        import time as _time

        old = _time.strftime("%Y%m%dT%H%M%SZ",
                             _time.gmtime(_time.time() - 3600))
        h = _signed_headers("GET", "/b1/k", "", b"", hosthdr,
                            "AKIDEXAMPLE", "s3cr3t", amz_date=old)
        status, body = await areq("GET", "/b1/k", headers=h)
        assert status == 403 and b"RequestTimeTooSkewed" in body
        await fe.stop()
        await c.stop()

    run(t())
