"""RGW-lite tests: bucket/object API, listings, multipart, and the
HTTP frontend driven over a real socket (the s3-tests role, shrunk)."""
import asyncio
import hashlib

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.rgw import RGWError, RGWLite, S3Frontend


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rgw", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c, RGWLite(c.client, 1)


def test_bucket_lifecycle():
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("alpha")
        await rgw.create_bucket("beta")
        with pytest.raises(RGWError, match="BucketAlreadyExists"):
            await rgw.create_bucket("alpha")
        with pytest.raises(RGWError, match="InvalidBucketName"):
            await rgw.create_bucket("bad/name")
        assert await rgw.list_buckets() == ["alpha", "beta"]
        await rgw.put_object("alpha", "k", b"v")
        with pytest.raises(RGWError, match="BucketNotEmpty"):
            await rgw.delete_bucket("alpha")
        await rgw.delete_object("alpha", "k")
        await rgw.delete_bucket("alpha")
        assert await rgw.list_buckets() == ["beta"]
        with pytest.raises(RGWError, match="NoSuchBucket"):
            await rgw.put_object("gone", "k", b"v")
        await c.stop()

    run(t())


def test_object_roundtrip_and_listing():
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b")
        data = b"hello s3 world"
        etag = await rgw.put_object("b", "docs/readme.txt", data)
        assert etag == hashlib.md5(data).hexdigest()
        got, meta = await rgw.get_object("b", "docs/readme.txt")
        assert got == data and meta["etag"] == etag
        for k in ("docs/a", "docs/b", "logs/1", "logs/2", "zzz"):
            await rgw.put_object("b", k, k.encode())
        entries, trunc = await rgw.list_objects("b")
        keys = [e["key"] for e in entries]
        assert keys == sorted(keys) and not trunc
        docs, _ = await rgw.list_objects("b", prefix="docs/")
        assert [e["key"] for e in docs] == ["docs/a", "docs/b",
                                           "docs/readme.txt"]
        page1, trunc = await rgw.list_objects("b", max_keys=2)
        assert len(page1) == 2 and trunc
        page2, _ = await rgw.list_objects("b", marker=page1[-1]["key"])
        assert page2[0]["key"] > page1[-1]["key"]
        # overwrite changes etag; copy preserves content
        await rgw.put_object("b", "zzz", b"new")
        await rgw.copy_object("b", "zzz", "b", "zzz-copy")
        got2, _ = await rgw.get_object("b", "zzz-copy")
        assert got2 == b"new"
        await rgw.delete_object("b", "zzz")
        with pytest.raises(RGWError, match="NoSuchKey"):
            await rgw.get_object("b", "zzz")
        await c.stop()

    run(t())


def test_multipart_upload():
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("mp")
        upload = await rgw.initiate_multipart("mp", "big")
        rng = np.random.default_rng(5)
        parts = [rng.integers(0, 256, 50_000, dtype=np.uint8).tobytes()
                 for _ in range(3)]
        for i, p in enumerate(parts, start=1):
            await rgw.upload_part("mp", "big", upload, i, p)
        etag = await rgw.complete_multipart("mp", "big", upload,
                                            [1, 2, 3])
        assert etag.endswith("-3")
        got, meta = await rgw.get_object("mp", "big")
        assert got == b"".join(parts)
        assert meta["size"] == 150_000 and meta["multipart"]
        await rgw.delete_object("mp", "big")
        entries, _ = await rgw.list_objects("mp")
        assert entries == []
        await c.stop()

    run(t())


async def http(host, port, method, path, body=b"", headers=None):
    reader, writer = await asyncio.open_connection(host, port)
    head = [f"{method} {path} HTTP/1.1", f"host: {host}",
            f"content-length: {len(body)}"]
    for k, v in (headers or {}).items():
        head.append(f"{k}: {v}")
    writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
    await writer.drain()
    status_line = await reader.readline()
    status = int(status_line.split()[1])
    rheaders = {}
    while True:
        h = await reader.readline()
        if h in (b"\r\n", b"\n"):
            break
        k, v = h.decode().split(":", 1)
        rheaders[k.strip().lower()] = v.strip()
    n = int(rheaders.get("content-length", "0"))
    # HEAD advertises the entity length but carries no body
    rbody = await reader.readexactly(n) if n and method != "HEAD" else b""
    writer.close()
    return status, rheaders, rbody


def test_http_frontend():
    async def t():
        c, rgw = await make()
        fe = S3Frontend(rgw)
        host, port = await fe.start()
        assert (await http(host, port, "PUT", "/photos"))[0] == 200
        st, hd, _ = await http(host, port, "PUT", "/photos/cat.jpg",
                               b"MEOW" * 100)
        assert st == 200 and hd["etag"].strip('"') == hashlib.md5(
            b"MEOW" * 100
        ).hexdigest()
        st, hd, body = await http(host, port, "GET", "/photos/cat.jpg")
        assert st == 200 and body == b"MEOW" * 100
        st, hd, _ = await http(host, port, "HEAD", "/photos/cat.jpg")
        assert st == 200 and hd["content-length"] == "400"
        # copy via x-amz-copy-source
        st, _, _ = await http(host, port, "PUT", "/photos/cat2.jpg",
                              headers={"x-amz-copy-source":
                                       "/photos/cat.jpg"})
        assert st == 200
        st, _, body = await http(host, port, "GET",
                                 "/photos?prefix=cat")
        assert st == 200 and b"<Key>cat.jpg</Key>" in body \
            and b"<Key>cat2.jpg</Key>" in body
        st, _, body = await http(host, port, "GET", "/")
        assert b"<Name>photos</Name>" in body
        assert (await http(host, port, "DELETE",
                           "/photos/cat.jpg"))[0] == 204
        st, _, body = await http(host, port, "GET", "/photos/cat.jpg")
        assert st == 404 and b"NoSuchKey" in body
        await fe.stop()
        await c.stop()

    run(t())


def test_cls_bucket_index_stats():
    """The bucket index is cls-served: every update maintains count and
    byte totals atomically server-side (cls_rgw stats role)."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("s")
        st = await rgw.bucket_stats("s")
        assert (st["count"], st["bytes"]) == (0, 0)
        await rgw.put_object("s", "a", b"x" * 100)
        await rgw.put_object("s", "b", b"y" * 250)
        st = await rgw.bucket_stats("s")
        assert (st["count"], st["bytes"]) == (2, 350)
        await rgw.put_object("s", "a", b"z" * 10)  # overwrite re-accounts
        st = await rgw.bucket_stats("s")
        assert (st["count"], st["bytes"]) == (2, 260)
        await rgw.delete_object("s", "b")
        st = await rgw.bucket_stats("s")
        assert (st["count"], st["bytes"]) == (1, 10)
        assert st["generation"] == 4  # one bump per index mutation
        await c.stop()

    run(t())


def _signed_headers(method, path, query, body, host, access, secret,
                    amz_date=None):
    import time as _time

    from ceph_tpu.services.rgw import _sha256, sigv4_sign

    if amz_date is None:  # fresh: inside the frontend's skew window
        amz_date = _time.strftime("%Y%m%dT%H%M%SZ", _time.gmtime())
    headers = {
        "host": host,
        "x-amz-content-sha256": _sha256(body),
        "x-amz-date": amz_date,
    }
    headers["authorization"] = sigv4_sign(
        method, path, query, headers, body, access, secret, amz_date)
    return headers


def test_sigv4_auth():
    """Frontend with a user table: correctly signed requests pass,
    bad signatures / unknown keys / tampered bodies get 403."""
    async def t():
        import urllib.request

        c, rgw = await make()
        fe = S3Frontend(rgw, users={"AKIDEXAMPLE": "s3cr3t"})
        host, port = await fe.start()
        base = f"http://{host}:{port}"
        hosthdr = f"{host}:{port}"

        def req(method, path, body=b"", headers=None, query=""):
            url = base + path + (f"?{query}" if query else "")
            r = urllib.request.Request(url, data=body or None,
                                       method=method)
            for k, v in (headers or {}).items():
                r.add_header(k, v)
            try:
                with urllib.request.urlopen(r) as resp:
                    return resp.status, resp.read()
            except urllib.error.HTTPError as e:
                return e.code, e.read()

        loop = asyncio.get_running_loop()

        async def areq(*a, **kw):
            return await loop.run_in_executor(None,
                                              lambda: req(*a, **kw))

        # unauthenticated: rejected
        status, body = await areq("PUT", "/b1")
        assert status == 403 and b"AccessDenied" in body
        # signed bucket create + object put + get round-trip
        h = _signed_headers("PUT", "/b1", "", b"", hosthdr,
                            "AKIDEXAMPLE", "s3cr3t")
        status, _ = await areq("PUT", "/b1", headers=h)
        assert status == 200
        payload = b"signed payload"
        h = _signed_headers("PUT", "/b1/k", "", payload, hosthdr,
                            "AKIDEXAMPLE", "s3cr3t")
        status, _ = await areq("PUT", "/b1/k", body=payload, headers=h)
        assert status == 200
        h = _signed_headers("GET", "/b1/k", "", b"", hosthdr,
                            "AKIDEXAMPLE", "s3cr3t")
        status, body = await areq("GET", "/b1/k", headers=h)
        assert status == 200 and body == payload
        # wrong secret -> 403
        h = _signed_headers("GET", "/b1/k", "", b"", hosthdr,
                            "AKIDEXAMPLE", "WRONG")
        status, body = await areq("GET", "/b1/k", headers=h)
        assert status == 403 and b"SignatureDoesNotMatch" in body
        # unknown access key -> 403
        h = _signed_headers("GET", "/b1/k", "", b"", hosthdr,
                            "NOBODY", "s3cr3t")
        status, body = await areq("GET", "/b1/k", headers=h)
        assert status == 403 and b"InvalidAccessKeyId" in body
        # tampered body (hash mismatch) -> 403
        h = _signed_headers("PUT", "/b1/k2", "", b"original", hosthdr,
                            "AKIDEXAMPLE", "s3cr3t")
        status, body = await areq("PUT", "/b1/k2", body=b"tampered",
                                  headers=h)
        assert status == 403
        # stale replay: a validly signed request whose x-amz-date is
        # outside the 15-min skew window is rejected (round-3 advisor:
        # without this a captured request replays forever)
        import time as _time

        old = _time.strftime("%Y%m%dT%H%M%SZ",
                             _time.gmtime(_time.time() - 3600))
        h = _signed_headers("GET", "/b1/k", "", b"", hosthdr,
                            "AKIDEXAMPLE", "s3cr3t", amz_date=old)
        status, body = await areq("GET", "/b1/k", headers=h)
        assert status == 403 and b"RequestTimeTooSkewed" in body
        await fe.stop()
        await c.stop()

    run(t())


def test_object_versioning():
    """Versioned buckets (rgw_op.cc versioned paths): PUT stacks
    versions, GET serves current or a named version, DELETE without a
    version inserts a delete marker, deleting the marker restores, and
    deleting a specific version promotes the next-newest."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b")
        assert await rgw.get_bucket_versioning("b") == ""
        await rgw.put_bucket_versioning("b", "Enabled")
        assert await rgw.get_bucket_versioning("b") == "Enabled"

        _, v1 = await rgw.put_object("b", "k", b"one")
        await asyncio.sleep(0.002)
        _, v2 = await rgw.put_object("b", "k", b"two")
        assert v1 != v2
        data, meta = await rgw.get_object("b", "k")
        assert data == b"two" and meta["version_id"] == v2
        data, _ = await rgw.get_object("b", "k", version_id=v1)
        assert data == b"one"

        vers = await rgw.list_object_versions("b")
        assert [e["version_id"] for e in vers] == [v2, v1]
        assert [e["is_latest"] for e in vers] == [True, False]

        # delete -> marker; key vanishes from plain listings but all
        # versions remain readable by id
        marker_vid = await rgw.delete_object("b", "k")
        with pytest.raises(RGWError, match="NoSuchKey"):
            await rgw.get_object("b", "k")
        ents, _tr = await rgw.list_objects("b")
        assert ents == []
        assert (await rgw.get_object("b", "k", version_id=v2))[0] \
            == b"two"
        vers = await rgw.list_object_versions("b")
        assert vers[0]["delete_marker"] and vers[0]["is_latest"]

        # deleting the MARKER undeletes (S3 semantics)
        await rgw.delete_object("b", "k", version_id=marker_vid)
        data, _ = await rgw.get_object("b", "k")
        assert data == b"two"
        ents, _tr = await rgw.list_objects("b")
        assert [e["key"] for e in ents] == ["k"]

        # deleting the CURRENT version promotes the previous one
        await rgw.delete_object("b", "k", version_id=v2)
        data, meta = await rgw.get_object("b", "k")
        assert data == b"one" and meta["version_id"] == v1
        # and deleting the last version removes the key entirely
        await rgw.delete_object("b", "k", version_id=v1)
        with pytest.raises(RGWError):
            await rgw.get_object("b", "k")
        assert await rgw.list_object_versions("b") == []
        await c.stop()

    run(t())


def test_lifecycle_expiration():
    """LC rules (rgw_lc.cc role): ``days`` expires current objects
    (marker on versioned buckets), ``noncurrent_days`` reaps old
    versions for good; driven one pass at a time via lc_process (the
    rgw_lc mgr module's tick calls exactly this)."""
    async def t():
        import time as _time

        c, rgw = await make()
        await rgw.create_bucket("b")
        await rgw.put_bucket_versioning("b", "Enabled")
        _, v1 = await rgw.put_object("b", "old", b"x" * 100)
        await asyncio.sleep(0.002)
        _, v2 = await rgw.put_object("b", "old", b"y" * 100)
        await rgw.put_object("b", "tmp/scratch", b"z")

        await rgw.put_lifecycle("b", [
            {"id": "expire-tmp", "prefix": "tmp/", "days": 1},
            {"id": "reap-old-versions", "prefix": "old",
             "noncurrent_days": 2},
        ])
        got = await rgw.get_lifecycle("b")
        assert [r["id"] for r in got] == ["expire-tmp",
                                         "reap-old-versions"]

        # nothing is old enough yet: a pass is a no-op
        rep = await rgw.lc_process()
        assert rep["b"] == {"expired_current": 0,
                            "expired_noncurrent": 0}
        ents, _ = await rgw.list_objects("b")
        assert [e["key"] for e in ents] == ["old", "tmp/scratch"]

        # jump 1.5 days: tmp/ current expires (delete marker), old's
        # noncurrent v1 survives (needs 2 days)
        rep = await rgw.lc_process(now=_time.time() + 1.5 * 86400)
        assert rep["b"]["expired_current"] == 1
        ents, _ = await rgw.list_objects("b")
        assert [e["key"] for e in ents] == ["old"]
        assert (await rgw.get_object("b", "old", version_id=v1))[0] \
            == b"x" * 100

        # jump 3 days: noncurrent v1 reaped; current v2 still there
        # (the "old" rule has no current-expiration days)
        rep = await rgw.lc_process(now=_time.time() + 3 * 86400)
        assert rep["b"]["expired_noncurrent"] >= 1
        with pytest.raises(RGWError, match="NoSuchVersion"):
            await rgw.get_object("b", "old", version_id=v1)
        assert (await rgw.get_object("b", "old"))[0] == b"y" * 100
        await c.stop()

    run(t())


def test_versioning_rest_surface():
    """The REST dialect: ?versioning, ?versions, ?lifecycle and
    versionId= routing."""
    async def t():
        import urllib.parse

        c, rgw = await make()
        fe = S3Frontend(rgw)
        host, port = await fe.start()

        async def req(method, target, body=b""):
            r, w = await asyncio.open_connection(host, port)
            w.write(
                f"{method} {target} HTTP/1.1\r\n"
                f"host: {host}\r\ncontent-length: {len(body)}\r\n"
                "\r\n".encode() + body)
            await w.drain()
            status = int((await r.readline()).split()[1])
            hdrs = {}
            while True:
                line = await r.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, v = line.decode().split(":", 1)
                hdrs[k.strip().lower()] = v.strip()
            data = await r.readexactly(int(hdrs.get("content-length",
                                                    "0")))
            w.close()
            return status, hdrs, data

        assert (await req("PUT", "/vb"))[0] == 200
        assert (await req(
            "PUT", "/vb?versioning",
            b"<VersioningConfiguration><Status>Enabled</Status>"
            b"</VersioningConfiguration>"))[0] == 200
        st, _, body = await req("GET", "/vb?versioning")
        assert st == 200 and b"Enabled" in body

        st, h1, _ = await req("PUT", "/vb/k", b"one")
        v1 = h1["x-amz-version-id"]
        st, h2, _ = await req("PUT", "/vb/k", b"two")
        v2 = h2["x-amz-version-id"]
        st, _, data = await req(
            "GET", f"/vb/k?versionId={urllib.parse.quote(v1)}")
        assert st == 200 and data == b"one"
        st, _, body = await req("GET", "/vb?versions")
        assert body.count(b"<Version>") == 2
        assert v2.encode() in body

        st, h, _ = await req("DELETE", "/vb/k")
        assert h.get("x-amz-delete-marker") == "true"
        assert (await req("GET", "/vb/k"))[0] == 404
        st, _, body = await req("GET", "/vb?versions")
        assert b"<DeleteMarker>" in body

        assert (await req(
            "PUT", "/vb?lifecycle",
            b"<LifecycleConfiguration><Rule><ID>r1</ID>"
            b"<Prefix>tmp/</Prefix><Expiration><Days>7</Days>"
            b"</Expiration></Rule></LifecycleConfiguration>"))[0] == 200
        st, _, body = await req("GET", "/vb?lifecycle")
        assert st == 200 and b"<Days>7.0</Days>" in body
        await fe.stop()
        await c.stop()

    run(t())


def test_rgw_lc_mgr_module_drives_expiration(tmp_path):
    """The rgw_lc mgr module (background LC on the mgr tick) runs the
    same pass via its admin command."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b")
        await rgw.put_object("b", "tmp/x", b"data")
        await rgw.put_lifecycle("b", [
            {"id": "r", "prefix": "tmp/", "days": 0}])
        await asyncio.sleep(0.002)  # make mtime strictly < cutoff

        from ceph_tpu.utils.admin import admin_command

        await c.mgr.start_admin(str(tmp_path / "mgr.sock"))
        rep = await admin_command(c.mgr.admin.path, "lc process",
                                  pool=1)
        assert rep["b"]["expired_current"] == 1
        ents, _ = await rgw.list_objects("b")
        assert ents == []
        await c.stop()

    run(t())


def test_null_version_preserved_and_addressable():
    """S3 null-version semantics: an object written BEFORE versioning
    was enabled stays addressable as versionId=null, survives versioned
    overwrites and delete markers, and its data/row clean up when
    deleted by id."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b")
        await rgw.put_object("b", "k", b"pre-versioning")
        await rgw.put_bucket_versioning("b", "Enabled")

        # addressable as null while still current
        data, meta = await rgw.get_object("b", "k", version_id="null")
        assert data == b"pre-versioning"

        # a versioned overwrite preserves it as the null version
        _, v1 = await rgw.put_object("b", "k", b"v1-data")
        assert (await rgw.get_object("b", "k"))[0] == b"v1-data"
        data, _ = await rgw.get_object("b", "k", version_id="null")
        assert data == b"pre-versioning"
        vers = await rgw.list_object_versions("b")
        assert [e["version_id"] for e in vers] == [v1, "null"]

        # HEAD on a marker-current key 404s like GET
        await rgw.delete_object("b", "k")
        with pytest.raises(RGWError, match="NoSuchKey"):
            await rgw.head_object("b", "k")

        # deleting the versioned v1 and the marker promotes null back
        marker_vid = next(
            e["version_id"]
            for e in await rgw.list_object_versions("b")
            if e["delete_marker"])
        await rgw.delete_object("b", "k", version_id=v1)
        await rgw.delete_object("b", "k", version_id=marker_vid)
        data, meta = await rgw.get_object("b", "k")
        assert data == b"pre-versioning"

        # deleting the null version by id removes it for good
        await rgw.delete_object("b", "k", version_id="null")
        with pytest.raises(RGWError):
            await rgw.get_object("b", "k")
        assert await rgw.list_object_versions("b") == []
        # keys with NUL are rejected (version-row namespace guard)
        with pytest.raises(RGWError, match="InvalidObjectName"):
            await rgw.put_object("b", "k\x00v123", b"x")
        await c.stop()

    run(t())


def test_versioned_multipart_complete():
    """Multipart complete on a versioning-enabled bucket produces a
    real version (with id) and reclaims the part objects."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b")
        await rgw.put_bucket_versioning("b", "Enabled")
        up = await rgw.initiate_multipart("b", "big")
        await rgw.upload_part("b", "big", up, 1, b"A" * 1000)
        await rgw.upload_part("b", "big", up, 2, b"B" * 1000)
        etag, vid = await rgw.complete_multipart("b", "big", up, [1, 2])
        assert etag.endswith("-2") and vid
        data, meta = await rgw.get_object("b", "big")
        assert data == b"A" * 1000 + b"B" * 1000
        assert meta["version_id"] == vid
        await c.stop()

    run(t())


def test_presigned_urls():
    """Query-string sigv4 (presigned URL role): GET/PUT with no auth
    headers, expiry enforcement, tamper rejection."""
    async def t():
        import urllib.parse as up

        from ceph_tpu.services.rgw import presign_url

        c, rgw = await make()
        await rgw.create_bucket("pub")
        await rgw.put_object("pub", "doc.txt", b"shared content")
        fe = S3Frontend(rgw, users={"AK": "s3cr3t"})
        host, port = await fe.start()

        def target(url):
            p = up.urlsplit(url)
            return p.path + "?" + p.query

        # un-authenticated requests are still refused
        st, _h, _b = await http(host, port, "GET", "/pub/doc.txt")
        assert st == 403
        # presigned GET: no headers beyond host
        url = presign_url("GET", "/pub/doc.txt", host, "AK", "s3cr3t")
        st, _h, body = await http(host, port, "GET", target(url))
        assert st == 200 and body == b"shared content"
        # presigned PUT uploads without credentials in the request
        url = presign_url("PUT", "/pub/up.bin", host, "AK", "s3cr3t")
        st, _h, _b = await http(host, port, "PUT", target(url),
                                body=b"uploaded")
        assert st == 200
        got, _m = await rgw.get_object("pub", "up.bin")
        assert got == b"uploaded"
        # expired link: signed long ago with a short window
        import time as _t

        old = _t.strftime("%Y%m%dT%H%M%SZ", _t.gmtime(_t.time() - 600))
        url = presign_url("GET", "/pub/doc.txt", host, "AK", "s3cr3t",
                          expires=60, amz_date=old)
        st, _h, _b = await http(host, port, "GET", target(url))
        assert st == 403
        # tampering with the signed expiry breaks the signature
        url = presign_url("GET", "/pub/doc.txt", host, "AK", "s3cr3t",
                          expires=60, amz_date=old)
        st, _h, _b = await http(host, port, "GET",
                                target(url).replace(
                                    "X-Amz-Expires=60",
                                    "X-Amz-Expires=6000"))
        assert st == 403
        # a presigned GET cannot be replayed as a DELETE
        url = presign_url("GET", "/pub/doc.txt", host, "AK", "s3cr3t")
        st, _h, _b = await http(host, port, "DELETE", target(url))
        assert st == 403
        await fe.stop()
        await c.stop()

    run(t())


def test_object_and_bucket_tagging():
    """S3 tag sets (rgw_tag_s3 role): per-object tags ride the index
    entry, survive copies and version promotion, and bucket tags live
    on the bucket attr."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b")
        await rgw.put_object("b", "k", b"v",
                             tags={"env": "prod", "team": "storage"})
        assert await rgw.get_object_tagging("b", "k") == {
            "env": "prod", "team": "storage"}
        # replace + delete
        await rgw.put_object_tagging("b", "k", {"env": "dev"})
        assert await rgw.get_object_tagging("b", "k") == {"env": "dev"}
        await rgw.delete_object_tagging("b", "k")
        assert await rgw.get_object_tagging("b", "k") == {}
        # limits
        with pytest.raises(RGWError) as ei:
            await rgw.put_object_tagging(
                "b", "k", {f"t{i}": "x" for i in range(11)})
        assert ei.value.code == "InvalidTag"
        with pytest.raises(RGWError) as ei:
            await rgw.put_object_tagging("b", "k", {"k" * 129: "v"})
        assert ei.value.code == "InvalidTag"
        # copy carries the tag set (S3 default COPY directive)
        await rgw.put_object_tagging("b", "k", {"a": "1"})
        await rgw.copy_object("b", "k", "b", "k2")
        assert await rgw.get_object_tagging("b", "k2") == {"a": "1"}
        # bucket tags
        await rgw.put_bucket_tagging("b", {"owner": "me"})
        assert await rgw.get_bucket_tagging("b") == {"owner": "me"}
        await rgw.delete_bucket_tagging("b")
        assert await rgw.get_bucket_tagging("b") == {}
        await c.stop()

    run(t())


def test_tagging_versioned_rows():
    """Tagging a NAMED version updates that row; the current pointer
    follows only when the named version is current."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("b")
        await rgw.put_bucket_versioning("b", "Enabled")
        _, v1 = await rgw.put_object("b", "k", b"one")
        _, v2 = await rgw.put_object("b", "k", b"two")
        await rgw.put_object_tagging("b", "k", {"gen": "1"},
                                     version_id=v1)
        await rgw.put_object_tagging("b", "k", {"gen": "2"},
                                     version_id=v2)
        assert await rgw.get_object_tagging("b", "k",
                                            version_id=v1) == {"gen": "1"}
        # current (= v2) reflects v2's tags, not v1's
        assert await rgw.get_object_tagging("b", "k") == {"gen": "2"}
        # deleting current promotes v1 WITH its tags intact
        await rgw.delete_object("b", "k", version_id=v2)
        assert await rgw.get_object_tagging("b", "k") == {"gen": "1"}
        await c.stop()

    run(t())


def test_tagging_and_cors_http_routes():
    """?tagging / ?cors subresources + OPTIONS preflight over a real
    socket (s3-tests CORS cases, shrunk)."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("web")
        fe = S3Frontend(rgw)
        host, port = await fe.start()
        try:
            # object tagging via x-amz-tagging header on PUT
            st, rh, _ = await http(
                host, port, "PUT", "/web/o", b"data",
                headers={"x-amz-tagging": "k1=v1&k2=v2"})
            assert st == 200
            st, _, body = await http(host, port, "GET",
                                     "/web/o?tagging")
            assert st == 200 and b"<Key>k1</Key>" in body \
                and b"<Value>v2</Value>" in body
            # PUT ?tagging replaces; DELETE clears
            xml = (b"<Tagging><TagSet><Tag><Key>env</Key>"
                   b"<Value>prod</Value></Tag></TagSet></Tagging>")
            st, _, _ = await http(host, port, "PUT", "/web/o?tagging",
                                  xml)
            assert st == 200
            st, _, body = await http(host, port, "GET",
                                     "/web/o?tagging")
            assert b"env" in body and b"k1" not in body
            st, _, _ = await http(host, port, "DELETE",
                                  "/web/o?tagging")
            assert st == 204
            # GET object advertises the tag count
            st, rh, _ = await http(host, port, "PUT", "/web/o2", b"x",
                                   headers={"x-amz-tagging": "a=1"})
            st, rh, _ = await http(host, port, "GET", "/web/o2")
            assert rh.get("x-amz-tagging-count") == "1"
            # bucket tagging
            st, _, _ = await http(
                host, port, "PUT", "/web?tagging",
                b"<Tagging><TagSet><Tag><Key>t</Key><Value>b</Value>"
                b"</Tag></TagSet></Tagging>")
            assert st == 204
            st, _, body = await http(host, port, "GET",
                                     "/web?tagging")
            assert st == 200 and b"<Key>t</Key>" in body
            # CORS config
            cors = (b"<CORSConfiguration><CORSRule>"
                    b"<AllowedOrigin>https://*.example.com"
                    b"</AllowedOrigin>"
                    b"<AllowedMethod>GET</AllowedMethod>"
                    b"<AllowedHeader>*</AllowedHeader>"
                    b"<ExposeHeader>etag</ExposeHeader>"
                    b"<MaxAgeSeconds>300</MaxAgeSeconds>"
                    b"</CORSRule></CORSConfiguration>")
            st, _, _ = await http(host, port, "PUT", "/web?cors", cors)
            assert st == 200
            st, _, body = await http(host, port, "GET", "/web?cors")
            assert st == 200 and b"AllowedOrigin" in body
            # preflight: matching origin+method allowed
            st, rh, _ = await http(
                host, port, "OPTIONS", "/web/o",
                headers={"origin": "https://app.example.com",
                         "access-control-request-method": "GET",
                         "access-control-request-headers":
                             "x-custom"})
            assert st == 200
            assert rh["access-control-allow-origin"] \
                == "https://app.example.com"
            assert rh["access-control-max-age"] == "300"
            # preflight: method not allowed -> 403
            st, _, _ = await http(
                host, port, "OPTIONS", "/web/o",
                headers={"origin": "https://app.example.com",
                         "access-control-request-method": "DELETE"})
            assert st == 403
            # preflight: origin not allowed -> 403
            st, _, _ = await http(
                host, port, "OPTIONS", "/web/o",
                headers={"origin": "https://evil.com",
                         "access-control-request-method": "GET"})
            assert st == 403
            # simple cross-origin GET gets the allow + expose headers
            st, rh, _ = await http(
                host, port, "GET", "/web/o2",
                headers={"origin": "https://app.example.com"})
            assert rh.get("access-control-allow-origin") \
                == "https://app.example.com"
            assert rh.get("access-control-expose-headers") == "etag"
            # DELETE ?cors; preflight then refuses
            st, _, _ = await http(host, port, "DELETE", "/web?cors")
            assert st == 204
            st, _, body = await http(host, port, "GET", "/web?cors")
            assert st == 404
            st, _, _ = await http(
                host, port, "OPTIONS", "/web/o",
                headers={"origin": "https://app.example.com",
                         "access-control-request-method": "GET"})
            assert st == 403
        finally:
            await fe.stop()
            await c.stop()

    run(t())


def test_cors_cache_invalidated_after_store_write():
    """Regression (race): a preflight that re-reads the OLD rules
    while a cors PUT is mid-write must not leave them cached past the
    write — invalidation happens AFTER the store write completes, so
    the racing entry is popped and the next preflight re-reads."""
    async def t():
        c, rgw = await make()
        await rgw.create_bucket("web")
        fe = S3Frontend(rgw)
        host, port = await fe.start()
        try:
            cors = (b"<CORSConfiguration><CORSRule>"
                    b"<AllowedOrigin>https://a.example</AllowedOrigin>"
                    b"<AllowedMethod>GET</AllowedMethod>"
                    b"</CORSRule></CORSConfiguration>")
            st, _, _ = await http(host, port, "PUT", "/web?cors", cors)
            assert st == 200
            # the racing preflight: re-caches the CURRENT (soon stale)
            # rules exactly between the route's cache handling and the
            # store write finishing
            real_put = rgw.put_bucket_cors
            stale = await rgw.get_bucket_cors("web")

            async def racing_put(bucket, rules):
                fe._cors_cache[bucket] = (1e18, stale)
                await real_put(bucket, rules)

            rgw.put_bucket_cors = racing_put
            cors2 = cors.replace(b"https://a.example",
                                 b"https://b.example")
            st, _, _ = await http(host, port, "PUT", "/web?cors",
                                  cors2)
            assert st == 200
            rgw.put_bucket_cors = real_put
            # the post-write pop evicted the racing entry: preflight
            # serves the NEW origin, not the stale cache
            st, rh, _ = await http(
                host, port, "OPTIONS", "/web/o",
                headers={"origin": "https://b.example",
                         "access-control-request-method": "GET"})
            assert st == 200
            assert rh.get("access-control-allow-origin") \
                == "https://b.example"
            # mirrored interleaving: a preflight READS the old rules,
            # suspends, the PUT completes (pop + generation bump), the
            # preflight resumes — it must NOT cache its stale copy
            real_get = rgw.get_bucket_cors
            hold = asyncio.Event()

            async def slow_get(bucket):
                rules = await real_get(bucket)
                await hold.wait()
                return rules

            rgw.get_bucket_cors = slow_get
            fe._cors_cache.pop("web", None)
            reader = asyncio.create_task(fe._cors_rules("web"))
            await asyncio.sleep(0.05)  # reader holds the OLD rules
            rgw.get_bucket_cors = real_get
            cors3 = cors.replace(b"https://a.example",
                                 b"https://c.example")
            st, _, _ = await http(host, port, "PUT", "/web?cors",
                                  cors3)
            assert st == 200
            hold.set()
            await reader  # returns stale rules to ITS caller only
            st, rh, _ = await http(
                host, port, "OPTIONS", "/web/o",
                headers={"origin": "https://c.example",
                         "access-control-request-method": "GET"})
            assert st == 200
            assert rh.get("access-control-allow-origin") \
                == "https://c.example"
        finally:
            await fe.stop()
            await c.stop()

    run(t())
