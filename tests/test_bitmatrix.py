"""Bitmatrix code family tests: MDS property verified exhaustively for
every supported erasure pattern per technique (the
TestErasureCodeJerasure bit-matrix roles)."""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import ECError, load_codec
from ceph_tpu.ec.bitmatrix_plugin import _bitmatrix, _recovery_plan

RNG = np.random.default_rng(99)


def roundtrip_all_patterns(codec, max_erasures=None):
    n = codec.get_chunk_count()
    m = max_erasures or codec.m
    size = codec.get_chunk_size(1) * codec.k
    obj = RNG.integers(0, 256, size, dtype=np.uint8).tobytes()
    encoded = codec.encode(list(range(n)), obj)
    for r in range(1, m + 1):
        for erase in itertools.combinations(range(n), r):
            avail = {i: encoded[i] for i in range(n) if i not in erase}
            decoded = codec.decode(list(erase), avail)
            for i in erase:
                np.testing.assert_array_equal(
                    decoded[i], encoded[i],
                    err_msg=f"erase={erase} chunk={i}",
                )
    return encoded


@pytest.mark.parametrize("k,w", [(3, 4), (4, 4), (4, 6), (6, 6)])
def test_blaum_roth_mds(k, w):
    codec = load_codec({
        "plugin": "bitmatrix", "technique": "blaum_roth",
        "k": str(k), "m": "2", "w": str(w),
    })
    roundtrip_all_patterns(codec)


@pytest.mark.parametrize("k,w", [(3, 3), (4, 5), (5, 5), (7, 7)])
def test_liberation_mds(k, w):
    codec = load_codec({
        "plugin": "bitmatrix", "technique": "liberation",
        "k": str(k), "m": "2", "w": str(w),
    })
    roundtrip_all_patterns(codec)


@pytest.mark.parametrize("k", [3, 5, 8])
def test_liber8tion_mds(k):
    codec = load_codec({
        "plugin": "bitmatrix", "technique": "liber8tion",
        "k": str(k), "m": "2",
    })
    roundtrip_all_patterns(codec)


@pytest.mark.parametrize("k,m", [(4, 2), (5, 3)])
def test_cauchy_bitmatrix_mds(k, m):
    codec = load_codec({
        "plugin": "bitmatrix", "technique": "cauchy_bm",
        "k": str(k), "m": str(m),
    })
    roundtrip_all_patterns(codec)


def test_every_pattern_invertible_exhaustive():
    """MDS certification at the matrix level: every k-subset of the
    generator's row blocks is invertible (no data needed)."""
    for technique, k, m, w in [
        ("blaum_roth", 6, 2, 6),
        ("liberation", 7, 2, 7),
        ("liber8tion", 8, 2, 8),
        ("cauchy_bm", 6, 3, 8),
    ]:
        n = k + m
        for present in itertools.combinations(range(n), k):
            _recovery_plan(technique, k, m, w, present)  # raises if not


def test_jerasure_technique_dispatch():
    codec = load_codec({
        "plugin": "jerasure", "technique": "liberation",
        "k": "4", "m": "2", "w": "5",
    })
    from ceph_tpu.ec.bitmatrix_plugin import BitmatrixCodec

    assert isinstance(codec, BitmatrixCodec)
    roundtrip_all_patterns(codec)
    codec2 = load_codec({
        "plugin": "jerasure", "technique": "blaum_roth",
        "k": "4", "m": "2", "w": "4",
    })
    assert isinstance(codec2, BitmatrixCodec)


def test_parameter_validation():
    with pytest.raises(ECError):
        _bitmatrix("liberation", 4, 2, 6)  # w not prime
    with pytest.raises(ECError):
        _bitmatrix("blaum_roth", 4, 2, 5)  # w+1 not prime
    with pytest.raises(ECError):
        _bitmatrix("liberation", 8, 2, 7)  # k > w
    with pytest.raises(ECError):
        _bitmatrix("blaum_roth", 4, 3, 6)  # m != 2
    with pytest.raises(ECError):
        _bitmatrix("liber8tion", 4, 2, 7)  # w != 8


def test_xor_only_parity_row():
    """Row block 0 (the P parity) is plain XOR of the data chunks —
    the RAID6 P invariant."""
    codec = load_codec({
        "plugin": "bitmatrix", "technique": "liberation",
        "k": "4", "m": "2", "w": "5",
    })
    size = codec.get_chunk_size(1) * 4
    obj = RNG.integers(0, 256, size, dtype=np.uint8).tobytes()
    enc = codec.encode(list(range(6)), obj)
    p = np.bitwise_xor.reduce([enc[i] for i in range(4)])
    np.testing.assert_array_equal(enc[4], p)
