"""Cluster integration: the SURVEY §7 minimum end-to-end slice and the
thrash scenarios (kill/revive/blackhole) of the qa tier, in-process.

Every test assembles mon + OSDs + client on a LocalBus; the EC pool path
runs striped writes through the batched device encode (on the virtual
CPU mesh under pytest) and repairs through minimum_to_decode + decode —
the ECBackend.cc:1539/2405 arc end to end.
"""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster import TestCluster
from ceph_tpu.cluster.pg import NONE
from ceph_tpu.placement.osdmap import Pool

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2", "backend": "device"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make_cluster(n=5):
    c = TestCluster(n_osds=n)
    await c.start()
    return c


async def make_ec_cluster(n=5):
    c = await make_cluster(n)
    await c.client.create_pool(
        Pool(id=2, name="ec", size=5, min_size=3, pg_num=8, crush_rule=1,
             type="erasure", ec_profile=dict(EC_PROFILE))
    )
    await c.wait_active(20)
    return c


def test_boot_and_health():
    async def t():
        c = await make_cluster(4)
        assert all(st.up for st in c.mon.osdmap.osds)
        await c.stop()

    run(t())


def test_replicated_write_read_delete():
    async def t():
        c = await make_cluster(4)
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0)
        )
        await c.wait_active(20)
        payload = b"the quick brown fox" * 123
        await c.client.write_full(1, "obj", payload)
        assert await c.client.read(1, "obj") == payload
        assert await c.client.stat(1, "obj") == len(payload)
        # overwrite bumps the version and replaces content everywhere
        await c.client.write_full(1, "obj", b"short")
        assert await c.client.read(1, "obj") == b"short"
        await c.client.delete(1, "obj")
        with pytest.raises(KeyError):
            await c.client.read(1, "obj")
        await c.stop()

    run(t())


def test_replicated_survives_replica_loss():
    async def t():
        c = await make_cluster(4)
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0)
        )
        await c.wait_active(20)
        await c.client.write_full(1, "obj", b"D" * 4096)
        pgid = c.client.osdmap.object_to_pg(1, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        replica = next(o for o in up if o != primary)
        await c.kill_osd(replica)
        await c.wait_down(replica, 20)
        assert await c.client.read(1, "obj") == b"D" * 4096
        # failure detection produced a new epoch marking it down
        assert not c.mon.osdmap.osds[replica].up
        await c.stop()

    run(t())


def test_replicated_primary_loss_client_resends():
    async def t():
        c = await make_cluster(5)
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0)
        )
        await c.wait_active(20)
        await c.client.write_full(1, "obj", b"P" * 1000)
        pgid = c.client.osdmap.object_to_pg(1, b"obj")
        _, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        await c.kill_osd(primary)
        await c.wait_down(primary, 20)
        await c.wait_active(20)
        # Objecter recalculates the target from the new map and resends
        assert await c.client.read(1, "obj") == b"P" * 1000
        await c.stop()

    run(t())


def test_pool_create_spec_conflict_rejected():
    """A retried create with the SAME spec is idempotent; a same-name
    create with a DIFFERENT spec must fail EEXIST, not silently ack the
    existing pool's id (round-4 advisor finding)."""
    async def t():
        c = await make_cluster(4)
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0)
        )
        # identical spec: idempotent OK, same id
        pid = await c.client.create_pool(
            Pool(id=-1, name="rep", size=3, pg_num=8, crush_rule=0)
        )
        assert pid == 1
        with pytest.raises(FileExistsError):
            await c.client.create_pool(
                Pool(id=-1, name="rep", size=2, pg_num=8, crush_rule=0)
            )
        await c.stop()

    run(t())


def test_duplicate_op_not_reexecuted():
    """The client tick-resends in-flight ops; a duplicate (src, tid)
    reaching the primary must NOT re-execute a non-idempotent verb
    (reqid reply-cache role). Drive the PG directly with two identical
    MOSDOp append messages and check the append applied once."""
    async def t():
        from ceph_tpu.cluster import messages as M

        c = await make_cluster(4)
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0)
        )
        await c.wait_active(20)
        await c.client.write_full(1, "obj", b"base-")
        pgid = c.client.osdmap.object_to_pg(1, b"obj")
        _, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        osd = c.osds[primary]
        msg = M.MOSDOp(tid=990_001, pgid=pgid, oid=b"obj",
                       ops=[M.osd_op("append", data=b"tail")],
                       epoch=c.client.osdmap.epoch)
        pg = osd._pg_for_primary(pgid)
        await pg.do_op("client.0", msg)
        # network duplicate: same src, same tid — answered from the
        # reply cache, not re-applied
        await pg.do_op("client.0", msg)
        assert await c.client.read(1, "obj") == b"base-tail"
        # a FRESH tid is a genuinely new op and does apply
        msg2 = M.MOSDOp(tid=990_002, pgid=pgid, oid=b"obj",
                        ops=[M.osd_op("append", data=b"!")],
                        epoch=c.client.osdmap.epoch)
        await pg.do_op("client.0", msg2)
        assert await c.client.read(1, "obj") == b"base-tail!"
        await c.stop()

    run(t())


def test_ec_write_read_unaligned():
    async def t():
        c = await make_ec_cluster()
        data = bytes(range(256)) * 37  # 9472 B: pads within the stripe
        await c.client.write_full(2, "obj", data)
        assert await c.client.read(2, "obj") == data
        assert await c.client.stat(2, "obj") == len(data)
        # every live shard holds a chunk with a valid hinfo CRC
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, _ = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        held = 0
        for shard, osd_id in enumerate(up):
            if osd_id == NONE:
                continue
            store = c.stores[osd_id]
            cid = f"{pgid[0]}.{pgid[1]}s{shard}"
            if store.exists(cid, b"obj"):
                held += 1
        assert held == 5
        await c.stop()

    run(t())


def test_ec_degraded_read_two_losses():
    async def t():
        c = await make_ec_cluster()
        data = np.random.default_rng(3).integers(
            0, 256, 3 * 4096, dtype=np.uint8
        ).tobytes()
        await c.client.write_full(2, "obj", data)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victims = [o for o in up if o != primary][:2]
        for v in victims:
            await c.kill_osd(v)
            await c.wait_down(v, 20)
        # k=3 of 5 shards remain: reconstruct on read, bit-exact
        assert await c.client.read(2, "obj") == data
        await c.stop()

    run(t())


def test_ec_recovery_on_revive():
    async def t():
        c = await make_ec_cluster()
        datas = {f"o{i}": bytes([i]) * (1024 * (i + 1)) for i in range(4)}
        for name, d in datas.items():
            await c.client.write_full(2, name, d)
        # find an OSD holding shards of pg of o0; kill it, write more,
        # revive: the PGLog delta drives chunk reconstruction pushes
        pgid = c.client.osdmap.object_to_pg(2, b"o0")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        shard = up.index(victim)
        await c.kill_osd(victim)
        await c.wait_down(victim, 20)
        await c.client.write_full(2, "o0", b"NEW" * 2048)  # degraded write
        await c.revive_osd(victim)
        await c.wait_active(30)
        # revived shard must converge: its chunk decodes with the rest
        assert await c.client.read(2, "o0") == b"NEW" * 2048

        # the revived OSD's own shard was re-reconstructed bit-exact:
        # kill two OTHER members and force a read that needs it
        up2, primary2 = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        others = [o for o in up2
                  if o not in (victim, primary2) and o != NONE][:2]
        for o in others:
            await c.kill_osd(o)
            await c.wait_down(o, 20)
        assert await c.client.read(2, "o0") == b"NEW" * 2048
        await c.stop()

    run(t())


def test_replicated_delta_recovery_and_delete():
    async def t():
        c = await make_cluster(4)
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0)
        )
        await c.wait_active(20)
        for i in range(6):
            await c.client.write_full(1, f"k{i}", b"x" * 512 + bytes([i]))
        pgid = c.client.osdmap.object_to_pg(1, b"k0")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        await c.kill_osd(victim)
        await c.wait_down(victim, 20)
        await c.client.write_full(1, "k0", b"fresh")
        await c.client.delete(1, "k1")
        await c.revive_osd(victim)
        await c.wait_active(30)
        store = c.stores[victim]
        cid = f"{pgid[0]}.{pgid[1]}"
        # recovered write visible, recovered delete applied
        if store.exists(cid, b"k0"):
            assert bytes(store.read(cid, b"k0")) == b"fresh"
            assert not store.exists(cid, b"k1")
        assert await c.client.read(1, "k0") == b"fresh"
        with pytest.raises(KeyError):
            await c.client.read(1, "k1")
        await c.stop()

    run(t())


def test_backfill_after_log_trim():
    async def t():
        c = TestCluster(n_osds=4)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=1, crush_rule=0)
        )
        await c.wait_active(20)
        for o in c.osds:
            if o is not None:
                o.log_keep = 4  # tiny logs force the backfill path
        await c.client.write_full(1, "base", b"B")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds((1, 0))
        victim = next(o for o in up if o != primary)
        await c.kill_osd(victim)
        await c.wait_down(victim, 20)
        # push far more writes than the log keeps -> delta impossible
        for i in range(12):
            await c.client.write_full(1, f"n{i}", bytes([i]) * 128)
        o = await c.revive_osd(victim)
        o.log_keep = 4
        await c.wait_active(30)
        store = c.stores[victim]
        have = set(store.list_objects("1.0")) - {b"_pgmeta"}
        assert {f"n{i}".encode() for i in range(12)} <= have
        await c.stop()

    run(t())


def test_mark_out_replaces_member():
    async def t():
        c = TestCluster(n_osds=5, out_interval=1.0)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0)
        )
        await c.wait_active(20)
        await c.client.write_full(1, "obj", b"keepme" * 100)
        pgid = c.client.osdmap.object_to_pg(1, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        await c.kill_osd(victim)
        await c.wait_down(victim, 20)

        async def wait_out():
            while c.mon.osdmap.osds[victim].weight != 0:
                await asyncio.sleep(0.05)
        await asyncio.wait_for(wait_out(), 30)
        await c.wait_active(30)
        up2, _ = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        assert victim not in up2 and len([o for o in up2 if o != NONE]) == 3
        # the replacement member was backfilled
        assert await c.client.read(1, "obj") == b"keepme" * 100
        newcomer = next(o for o in up2 if o not in up)
        assert c.stores[newcomer].exists(f"{pgid[0]}.{pgid[1]}", b"obj")
        await c.stop()

    run(t())


def test_primary_crash_mid_fanout_survivors_converge():
    """VERDICT r3 #6: kill the primary after SOME (not all) replicas
    committed a rep-op. The unacked entry lives on one survivor only;
    the new interval must converge both survivors to one authoritative
    state, the client's resend must land exactly once, and a scrub must
    come back clean — acks were never lied about."""
    async def t():
        c = await make_cluster(5)
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0)
        )
        await c.wait_active(20)
        base = b"stable" * 500
        await c.client.write_full(1, "torn", base)
        pgid = c.mon.osdmap.object_to_pg(1, b"torn")
        acting, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        r1, r2 = [o for o in acting if o != primary]

        # blackhole r2: the primary's fan-out commits on r1 only
        c.bus.blackholes.add(f"osd.{r2}")
        newdata = b"half-committed" * 400
        wtask = asyncio.ensure_future(
            c.client.write_full(1, "torn", newdata))
        # let the rep-op land on r1 (but never on r2), then crash the
        # primary before it can gather all-ack or answer the client
        for _ in range(200):
            await asyncio.sleep(0.005)
            osd1 = c.osds[r1]
            pgs = [pg for pg in osd1.pgs.values()
                   if (pg.pgid[0], pg.pgid[1]) == pgid]
            if pgs and any(e.oid == b"torn" and e.version[1] >= 2
                           for e in pgs[0].log.entries):
                break
        await c.kill_osd(primary)
        c.bus.blackholes.discard(f"osd.{r2}")
        await c.wait_down(primary, 30)

        # the client's pending write must complete via the new interval
        await asyncio.wait_for(wtask, 60)
        assert await c.client.read(1, "torn") == newdata

        # survivors converged: same log head, same object bytes
        await c.wait_active(40)
        heads, versions = set(), set()
        for o in (r1, r2):
            for pg in c.osds[o].pgs.values():
                if (pg.pgid[0], pg.pgid[1]) == pgid:
                    heads.add(pg.log.head)
                    versions.add(
                        bytes(c.osds[o].store.read(pg.cid, b"torn")))
        assert len(heads) == 1, f"divergent survivor logs: {heads}"
        assert versions == {newdata}

        # the revived old primary (which applied locally pre-crash)
        # must also converge, not resurrect its unacked ordering
        await c.revive_osd(primary)
        await c.wait_active(40)
        assert await c.client.read(1, "torn") == newdata
        report = await c.scrub_pg(pgid)
        assert report["inconsistent"] == [], report
        await c.stop()

    run(t())


def test_primary_crash_no_replica_committed():
    """Same crash, but NO replica saw the rep-op (both blackholed):
    the entry exists only on the dead primary. The new interval serves
    the PRIOR state until the client's resend re-applies the write."""
    async def t():
        c = await make_cluster(5)
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0)
        )
        await c.wait_active(20)
        base = b"old-state" * 300
        await c.client.write_full(1, "obj", base)
        pgid = c.mon.osdmap.object_to_pg(1, b"obj")
        acting, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        replicas = [o for o in acting if o != primary]
        for r in replicas:
            c.bus.blackholes.add(f"osd.{r}")
        newdata = b"never-acked" * 350
        wtask = asyncio.ensure_future(
            c.client.write_full(1, "obj", newdata))
        await asyncio.sleep(0.05)  # primary applied locally, fanout dark
        await c.kill_osd(primary)
        for r in replicas:
            c.bus.blackholes.discard(f"osd.{r}")
        await c.wait_down(primary, 30)
        await asyncio.wait_for(wtask, 60)  # resend lands on new primary
        assert await c.client.read(1, "obj") == newdata
        await c.wait_active(40)
        report = await c.scrub_pg(pgid)
        assert report["inconsistent"] == [], report
        await c.stop()

    run(t())
