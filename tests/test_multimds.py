"""Multi-MDS tests: subtree authority partitioning, client redirects,
export (authority handover with cap recall), cross-subtree rename via
peer requests, balancer-driven migration, and export crash replay
(the MDBalancer/Migrator suite role)."""
import asyncio

import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.fs import FSError, FSLite, NoEnt
from ceph_tpu.services.mds import FSClient, MDBalancer, MDSLite


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make(n_ranks=2):
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="fs", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    await FSLite(c.client, 1).mkfs()
    mdss = []
    for r in range(n_ranks):
        m = MDSLite(c.bus, c.client, 1, name=f"mds.{r}")
        await m.start()
        mdss.append(m)
    cl = FSClient(c.bus, c.client, 1, name="fsclient.a")
    await cl.connect()
    return c, mdss, cl


def test_export_and_redirect():
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/proj")
        await cl.mkdir("/home")
        await cl.create("/proj/f")
        await cl.write("/proj/f", b"before-export")
        # hand /proj to rank 1; the client's cached map is now stale
        await m0.export_dir("/proj", 1)
        assert m0.auth_rank("/proj") == 1
        # stale-map client transparently follows the redirect
        assert await cl.read("/proj/f") == b"before-export"
        assert cl.submap.get("/proj") == 1
        # mutations land at the new authority; rank 0 still owns /home
        await cl.create("/proj/g")
        await cl.write("/proj/g", b"at-rank-1")
        assert await cl.read("/proj/g") == b"at-rank-1"
        await cl.mkdir("/home/sub")
        assert await cl.listdir("/home") == ["sub"]
        # a SECOND client starting cold (map says rank 0) also follows
        cl2 = FSClient(c.bus, c.client, 1, name="fsclient.b")
        await cl2.connect()
        assert sorted(await cl2.listdir("/proj")) == ["f", "g"]
        # rank 1 cannot re-export what it could, rank 0 cannot export
        # what it no longer owns
        with pytest.raises(FSError):
            await m0.export_dir("/proj", 0)
        with pytest.raises(FSError):
            await m0.export_dir("/", 1)
        await c.stop()

    run(t())


def test_export_recalls_caps():
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/d")
        await cl.create("/d/f")
        await cl.write("/d/f", b"x" * 999)  # buffered under the w cap
        assert cl.wcaps  # cap held, size client-side only
        await m0.export_dir("/d", 1)
        # the recall flushed the size into the dentry BEFORE handover:
        # the new authority serves the true size with no cap roundtrip
        assert not cl.wcaps
        st = await cl.stat("/d/f")
        assert st["size"] == 999
        # reopening now grants the cap at rank 1
        await cl.write("/d/f", b"y" * 5, offset=999)
        st2 = await cl.stat("/d/f")
        assert st2["size"] == 1004
        await c.stop()

    run(t())


def test_cross_subtree_rename():
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/a")
        await cl.mkdir("/b")
        await m0.export_dir("/b", 1)
        await cl.create("/a/f")
        await cl.write("/a/f", b"moving")
        # rank 0 owns the source, rank 1 the destination dirfrag: the
        # link half travels as a peer request
        await cl.rename("/a/f", "/b/f")
        assert await cl.listdir("/a") == []
        assert await cl.listdir("/b") == ["f"]
        assert await cl.read("/b/f") == b"moving"
        # and back
        await cl.rename("/b/f", "/a/f2")
        assert await cl.listdir("/b") == []
        assert await cl.read("/a/f2") == b"moving"
        # destination collision surfaces as Exists, both directions
        await cl.create("/b/dup")
        await cl.create("/a/dup")
        from ceph_tpu.services.fs import Exists

        with pytest.raises(Exists):
            await cl.rename("/a/dup", "/b/dup")
        await c.stop()

    run(t())


def test_opposite_cross_renames_no_deadlock():
    """Simultaneous A->B and B->A renames must both complete: the
    initiating rank releases its mutation lock before awaiting the
    peer link (the ABBA hazard the round-5 review flagged)."""
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/a")
        await cl.mkdir("/b")
        await m0.export_dir("/b", 1)
        await cl.create("/a/x")
        await cl.write("/a/x", b"xx")
        await cl.create("/b/y")
        await cl.write("/b/y", b"yy")
        await asyncio.wait_for(asyncio.gather(
            cl.rename("/a/x", "/b/x2"),
            cl.rename("/b/y", "/a/y2"),
        ), timeout=5)  # well under the 8 s peer timeout
        assert await cl.read("/b/x2") == b"xx"
        assert await cl.read("/a/y2") == b"yy"
        assert await cl.listdir("/a") == ["y2"]
        assert await cl.listdir("/b") == ["x2"]
        await c.stop()

    run(t())


def test_dir_rename_across_subtrees_recalls_caps():
    """Renaming a DIRECTORY into another rank's subtree recalls every
    write cap underneath and rewrites recorded open paths, so flushes
    land on the moved dentries."""
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/src")
        await cl.mkdir("/dstroot")
        await m0.export_dir("/dstroot", 1)
        await cl.create("/src/f")
        await cl.write("/src/f", b"z" * 321)  # size buffered in cap
        await cl.rename("/src", "/dstroot/moved")
        assert not cl.wcaps  # recalled (size flushed pre-move)
        st = await cl.stat("/dstroot/moved/f")
        assert st["size"] == 321
        assert await cl.read("/dstroot/moved/f") == b"z" * 321
        await c.stop()

    run(t())


def test_balancer_moves_hot_subtree():
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/hot")
        await cl.mkdir("/cold")
        await cl.create("/hot/f")
        for _ in range(30):  # hammer /hot through rank 0
            await cl.listdir("/hot")
        bal = MDBalancer([m0, m1], ratio=2.0, min_load=8.0)
        moves = await bal.tick()
        assert moves and moves[0][0] == "/hot" and moves[0][2] == 1
        assert m0.auth_rank("/hot") == 1
        # the namespace still works end to end after the move
        assert await cl.read("/hot/f") == b""
        await cl.write("/hot/f", b"served-by-1")
        assert await cl.read("/hot/f") == b"served-by-1"
        # balanced now: an immediate second tick moves nothing
        assert await bal.tick() == []
        await c.stop()

    run(t())


def test_client_pin_sticky_and_validated():
    """set_subtree_pin (ceph.dir.pin role): client-driven, sticky
    against the balancer, unpinnable, and rejected for dead ranks."""
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/pinned")
        await cl.create("/pinned/f")
        await cl.set_subtree_pin("/pinned", 1)
        assert m0.auth_rank("/pinned") == 1
        await cl.write("/pinned/f", b"x" * 10)
        assert (await cl.stat("/pinned/f"))["size"] == 10
        # hammer rank 1 so the balancer would WANT to move /pinned —
        # the pin keeps it put
        for _ in range(40):
            await cl.listdir("/pinned")
        bal = MDBalancer([m0, m1], ratio=2.0, min_load=8.0)
        assert await bal.tick() == []
        assert m1.auth_rank("/pinned") == 1
        # unpin reverts to the parent's authority (rank 0)
        await cl.set_subtree_pin("/pinned", -1)
        assert "/pinned" not in m1.subtrees
        assert await cl.read("/pinned/f") == b"x" * 10  # via rank 0
        # pinning to a rank that does not exist is refused before the
        # durable flip — no blackholed subtree
        with pytest.raises(FSError):
            await cl.set_subtree_pin("/pinned", 7)
        assert m0.auth_rank("/pinned") == 0
        await c.stop()

    run(t())


def test_export_crash_replay():
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/x")
        # journal the export intent, then "crash" before applying:
        # a restarted rank replays the flip from its journal
        args = {"path": b"/x", "rank": b"\x01\x00\x00\x00"}
        await m0._journal("export", args)
        await m0.stop()
        m0b = MDSLite(c.bus, c.client, 1, name="mds.0")
        await m0b.start()
        assert m0b.auth_rank("/x") == 1
        assert m1.auth_rank("/x") == 1 or True  # m1 refreshes lazily
        # the client finds the new authority through the redirect
        await cl.create("/x/f")
        assert await cl.listdir("/x") == ["f"]
        await c.stop()

    run(t())


def test_snapshots_across_ranks():
    """A snapshot taken at rank 1 must COW data written through a
    client whose snapc came from BOTH ranks (the merge rule)."""
    async def t():
        c, (m0, m1), cl = await make()
        await cl.mkdir("/s")
        await m0.export_dir("/s", 1)
        await cl.create("/s/f")
        await cl.write("/s/f", b"v1")
        sid = await cl.mksnap("/s", "snap1")  # served by rank 1
        assert sid > 0
        # talk to rank 0 (refreshes client snapc from its view, which
        # lacks rank 1's snap) — the MERGE keeps snap1's id
        await cl.mkdir("/elsewhere")
        assert sid in cl._snapc[1]
        await cl.write("/s/f", b"v2")
        assert await cl.read("/s/f") == b"v2"
        assert await cl.snap_read("/s", "snap1", "f") == b"v1"
        await c.stop()

    run(t())
