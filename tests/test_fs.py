"""FS-lite tests: hierarchy, file IO through the striper, rename,
errors (the libcephfs/client test role, shrunk)."""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.fs import Exists, FSLite, NoEnt, NotEmpty


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="fs", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    fs = FSLite(c.client, 1)
    await fs.mkfs()
    return c, fs


def test_hierarchy():
    async def t():
        c, fs = await make()
        await fs.mkdir("/home")
        await fs.mkdir("/home/alice")
        await fs.mkdir("/home/bob")
        await fs.mkdir("/tmp")
        assert await fs.listdir("/") == ["home", "tmp"]
        assert await fs.listdir("/home") == ["alice", "bob"]
        with pytest.raises(Exists):
            await fs.mkdir("/home")
        with pytest.raises(NoEnt):
            await fs.listdir("/nonexistent")
        with pytest.raises(NotEmpty):
            await fs.rmdir("/home")
        await fs.rmdir("/home/bob")
        assert await fs.listdir("/home") == ["alice"]
        st = await fs.stat("/home")
        assert st["type"] == 1
        await c.stop()

    run(t())


def test_file_io():
    async def t():
        c, fs = await make()
        await fs.mkdir("/data")
        rng = np.random.default_rng(11)
        blob = rng.integers(0, 256, 3_000_000, dtype=np.uint8).tobytes()
        await fs.write("/data/big.bin", blob)  # create-on-write
        st = await fs.stat("/data/big.bin")
        assert st["type"] == 2 and st["size"] == len(blob)
        assert await fs.read("/data/big.bin") == blob
        # ranged read + overwrite inside the file
        assert await fs.read("/data/big.bin", 100, 50) == blob[100:150]
        await fs.write("/data/big.bin", b"PATCH", offset=1_000_000)
        got = await fs.read("/data/big.bin", 999_998, 10)
        assert got[2:7] == b"PATCH"
        # append past the end grows it
        await fs.write("/data/big.bin", b"TAIL", offset=len(blob))
        assert (await fs.stat("/data/big.bin"))["size"] == len(blob) + 4
        await fs.truncate("/data/big.bin", 10)
        assert await fs.read("/data/big.bin") == blob[:10]
        await fs.unlink("/data/big.bin")
        with pytest.raises(NoEnt):
            await fs.stat("/data/big.bin")
        await c.stop()

    run(t())


def test_rename():
    async def t():
        c, fs = await make()
        await fs.mkdir("/a")
        await fs.mkdir("/b")
        await fs.write("/a/f.txt", b"content")
        await fs.rename("/a/f.txt", "/b/g.txt")
        assert await fs.listdir("/a") == []
        assert await fs.listdir("/b") == ["g.txt"]
        assert await fs.read("/b/g.txt") == b"content"
        # rename a whole directory: children follow the inode
        await fs.mkdir("/a/sub")
        await fs.write("/a/sub/x", b"x")
        await fs.rename("/a/sub", "/b/sub2")
        assert await fs.read("/b/sub2/x") == b"x"
        with pytest.raises(Exists):
            await fs.rename("/b/g.txt", "/b/sub2")
        await c.stop()

    run(t())
