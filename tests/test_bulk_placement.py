"""Device bulk rule engine vs the host oracle (itself reference-verified).

Every configuration compares the vectorized engine's whole output matrix
against per-x host do_rule results — the firstn rows compacted, indep
rows positional, exactly as the C produces them.
"""
import numpy as np
import pytest

from ceph_tpu.placement import bulk
from ceph_tpu.placement import crushmap as cm

N_X = 512


def _host_rows(m, ruleno, xs, numrep, weights):
    rows = []
    for x in xs:
        got = m.do_rule(ruleno, int(x), numrep, weights)
        rows.append(got + [cm.ITEM_NONE] * (numrep - len(got)))
    return np.asarray(rows, dtype=np.int32)


def _check(m, ruleno, numrep, weights=None, n_x=N_X):
    comp = bulk.CompiledMap(m)
    xs = (np.arange(n_x, dtype=np.uint64) * 2654435761 % (1 << 32)).astype(
        np.uint32
    )
    got = bulk.do_rule_bulk(comp, ruleno, xs, numrep, weights)
    want = _host_rows(m, ruleno, xs, numrep, weights)
    np.testing.assert_array_equal(got, want)


def test_flat_firstn():
    m = cm.build_flat(12)
    m.add_rule(cm.flat_firstn_rule(0))
    _check(m, 0, 3)


def test_flat_firstn_weighted_reweight():
    m = cm.build_flat(10, osd_weights=[1, 2, 3, 4, 0.5, 1, 1, 2, 8, 1])
    m.add_rule(cm.flat_firstn_rule(0))
    w = np.full(10, 0x10000, dtype=np.uint32)
    w[2] = 0
    w[5] = 0x8000
    _check(m, 0, 4, weights=w)


def test_hierarchy_chooseleaf_firstn():
    m = cm.build_hierarchy(osds_per_host=4, n_hosts=6)
    m.add_rule(cm.replicated_rule(0, root=-1, failure_domain_type=1))
    _check(m, 0, 3)


def test_hierarchy_chooseleaf_firstn_with_outs():
    m = cm.build_hierarchy(osds_per_host=3, n_hosts=5)
    m.add_rule(cm.replicated_rule(0, root=-1, failure_domain_type=1))
    w = np.full(15, 0x10000, dtype=np.uint32)
    w[[0, 1, 2]] = 0  # host0 fully out: forces retries
    w[7] = 0x2000
    _check(m, 0, 3, weights=w)


def test_hierarchy_chooseleaf_indep():
    m = cm.build_hierarchy(osds_per_host=3, n_hosts=8)
    m.add_rule(cm.ec_rule(0, root=-1, failure_domain_type=1))
    _check(m, 0, 6)


def test_flat_indep():
    m = cm.build_flat(14)
    m.add_rule(cm.ec_rule(0, root=-1, failure_domain_type=0))
    _check(m, 0, 11)


def test_flat_indep_with_outs():
    m = cm.build_flat(8)
    m.add_rule(cm.ec_rule(0, root=-1, failure_domain_type=0))
    w = np.full(8, 0x10000, dtype=np.uint32)
    w[[1, 4]] = 0  # k+m > up devices: NONE holes must match the C's
    _check(m, 0, 7, weights=w)


def test_choose_firstn_host_level():
    m = cm.build_hierarchy(osds_per_host=2, n_hosts=5)
    m.add_rule(
        cm.Rule(
            0,
            [
                cm.Step(cm.OP_TAKE, -1),
                cm.Step(cm.OP_CHOOSE_FIRSTN, 0, 1),
                cm.Step(cm.OP_EMIT),
            ],
        )
    )
    _check(m, 0, 3)


def test_deep_hierarchy_rack_rule(rng):
    m = cm.CrushMap()
    m.add_type(1, "host")
    m.add_type(2, "rack")
    m.add_type(3, "root")
    osd, bid, rack_ids = 0, -2, []
    for r in range(3):
        host_ids = []
        for h in range(3):
            n = int(rng.integers(2, 5))
            items = list(range(osd, osd + n))
            osd += n
            m.add_bucket(
                cm.Bucket(
                    id=bid, type_id=1, items=items,
                    weights=[int(w) for w in rng.integers(0x8000, 0x30000, n)],
                    name=f"h{r}{h}",
                )
            )
            host_ids.append(bid)
            bid -= 1
        m.add_bucket(
            cm.Bucket(
                id=bid, type_id=2, items=host_ids,
                weights=[m.buckets[h].weight() for h in host_ids],
                name=f"rack{r}",
            )
        )
        rack_ids.append(bid)
        bid -= 1
    m.add_bucket(
        cm.Bucket(
            id=bid, type_id=3, items=rack_ids,
            weights=[m.buckets[r].weight() for r in rack_ids], name="root",
        )
    )
    m.add_rule(cm.replicated_rule(0, root=bid, failure_domain_type=2))
    m.add_rule(cm.ec_rule(1, root=bid, failure_domain_type=1))
    _check(m, 0, 3, n_x=256)
    _check(m, 1, 5, n_x=256)


def test_nonstable_tunables():
    m = cm.build_hierarchy(osds_per_host=4, n_hosts=5)
    m.tunables = cm.Tunables(chooseleaf_stable=0, chooseleaf_vary_r=0)
    m.add_rule(cm.replicated_rule(0, root=-1, failure_domain_type=1))
    _check(m, 0, 3, n_x=256)


def test_unsupported_rejected():
    m = cm.build_flat(4, alg=cm.ALG_UNIFORM)
    with pytest.raises(ValueError):
        bulk.CompiledMap(m)
    m2 = cm.build_flat(4)
    m2.add_rule(
        cm.Rule(0, [cm.Step(cm.OP_TAKE, -1), cm.Step(cm.OP_EMIT)])
    )
    with pytest.raises(ValueError):
        bulk.CompiledMap(m2).compile_rule(0, 3)


def test_chunked_dispatch_consistency():
    m = cm.build_flat(9)
    m.add_rule(cm.flat_firstn_rule(0))
    comp = bulk.CompiledMap(m)
    xs = np.arange(1000, dtype=np.uint32)
    a = bulk.do_rule_bulk(comp, 0, xs, 3, chunk=128)
    b = bulk.do_rule_bulk(comp, 0, xs, 3, chunk=1 << 18)
    np.testing.assert_array_equal(a, b)


def test_device_above_choose_type_rejected():
    """A root holding both hosts and bare OSDs diverges from the C's
    skip_rep/ITEM_NONE semantics (mapper.c:497-516), so compile_rule must
    reject it rather than silently produce different placements."""
    m = cm.build_hierarchy(osds_per_host=2, n_hosts=2)
    root = next(b for b in m.buckets.values() if b.type_id == 2)
    root.items.append(99)  # bare OSD directly under the root
    root.weights.append(0x10000)
    m.max_devices = max(m.max_devices, 100)
    m.add_rule(cm.replicated_rule(0, root=root.id, failure_domain_type=1))
    comp = bulk.CompiledMap(m)
    with pytest.raises(ValueError, match="above choose type"):
        comp.compile_rule(0, 3)


def test_take_device_rejected():
    m = cm.build_flat(4)
    m.add_rule(cm.Rule(0, [
        cm.Step(cm.OP_TAKE, 2),  # a device, not a bucket
        cm.Step(cm.OP_CHOOSELEAF_FIRSTN, 0, 1),
        cm.Step(cm.OP_EMIT),
    ]))
    with pytest.raises(ValueError, match="not a bucket"):
        bulk.CompiledMap(m).compile_rule(0, 3)
