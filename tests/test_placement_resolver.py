"""PlacementResolver: batched device lookups vs the host pipeline.

The batched path must be bit-identical to pg_to_up_acting_full by
construction (device raw rows feed the SAME raw_to_up_acting host
code), the epoch-keyed memo must invalidate the instant the map moves,
and placement must never become a liveness dependency (host fallback
on every wrinkle). The cluster-tier test proves the serving-plane
contract: a map-epoch bump mid-flight re-targets resends onto the
post-remap primary with the batched resolver armed.
"""
import asyncio

import pytest

from ceph_tpu.placement import bulk
from ceph_tpu.placement import crushmap as cm
from ceph_tpu.placement import resolver as rmod
from ceph_tpu.placement.osdmap import Incremental, OSDMap, Pool
from ceph_tpu.placement.resolver import PlacementResolver
from ceph_tpu.utils import config as cfg


def _map(n=8):
    crush = cm.build_flat(n)
    crush.add_rule(cm.flat_firstn_rule(0))
    crush.add_rule(cm.ec_rule(1, root=-1, failure_domain_type=0))
    om = OSDMap(crush, n)
    om.add_pool(Pool(id=1, name="r", size=3, pg_num=32, crush_rule=0))
    om.add_pool(Pool(id=2, name="e", size=5, pg_num=16, crush_rule=1,
                     type="erasure"))
    return om


def _conf(min_batch=4):
    c = cfg.proxy()
    c.set("client_placement_batch_min", min_batch)
    return c


def _full_tuple(got):
    up, upp, acting, ap = got
    return tuple(up), upp, tuple(acting), ap


async def _sweep(r, om, pools=((1, 32), (2, 16))):
    """One concurrent miss sweep; asserts bit-identity vs host."""
    for pool_id, n_pg in pools:
        got = await asyncio.gather(*(
            r.afull(om, (pool_id, ps)) for ps in range(n_pg)))
        for ps, g in enumerate(got):
            want = om.pg_to_up_acting_full((pool_id, ps))
            assert _full_tuple(g) == _full_tuple(want), (pool_id, ps)


def test_batched_resolve_bit_identical_to_host():
    """The cold→warm→device arc: the first two miss storms host-serve
    (a jit compile never stalls parked ops; the second storm kicks the
    background warm), and once warm, storms dispatch through the
    device bulk engine — every stage bit-identical to the host
    pipeline."""
    async def run():
        om = _map()
        r = PlacementResolver(conf=_conf(), batch=True)
        await _sweep(r, om)                    # storm 1: host, no warm
        assert r.stats.placement_batch_lookups == 0
        om.apply_incremental(Incremental(epoch=2))
        await _sweep(r, om)                    # storm 2: host + warm
        for _ in range(200):                   # compile finishes async
            if r.stats.placement_bg_warms >= 2:
                break
            await asyncio.sleep(0.05)
        assert r.stats.placement_bg_warms >= 2
        om.apply_incremental(Incremental(epoch=3))
        await _sweep(r, om)                    # storm 3: device
        assert r.stats.placement_batch_lookups >= 2
        assert r.stats.placement_batched_pgids >= 48
        # steady state: pure cache hits, no further dispatches
        n = r.stats.placement_batch_lookups
        await _sweep(r, om, pools=((1, 32),))
        assert r.stats.placement_batch_lookups == n
        assert r.stats.placement_cache_hits >= 32

    asyncio.run(run())


def test_batched_resolve_with_overrides_and_weights():
    """upmap / pg_temp / primary-temp / reweight all ride the shared
    post-CRUSH host pipeline — batched results must carry them."""
    async def run():
        om = _map()
        om.osds[2].weight = 0          # out: CRUSH reroutes
        om.osds[5].up = False          # down: filtered from up
        om.pg_upmap_items[(1, 3)] = [(0, 7)]
        om.pg_temp[(2, 1)] = [1, 3, 4, 6, 7]
        om.primary_temp[(2, 1)] = 4
        om._out_weights_cache = None
        r = PlacementResolver(conf=_conf(), batch=True)
        # prewarm compiles AND marks the op-path shapes warm; the
        # epoch bump then invalidates the memo so the sweep below is
        # a genuine device-dispatched miss storm
        assert await r.prewarm(om, [1, 2]) == 48
        n0 = r.stats.placement_batch_lookups
        om.apply_incremental(Incremental(epoch=2))
        await _sweep(r, om)
        assert r.stats.placement_batch_lookups > n0

    asyncio.run(run())


def test_epoch_bump_invalidates_cache():
    async def run():
        om = _map()
        r = PlacementResolver(conf=_conf(), batch=True)
        await asyncio.gather(*(r.afull(om, (1, ps))
                               for ps in range(32)))
        before = _full_tuple(await r.afull(om, (1, 0)))
        om.apply_incremental(Incremental(epoch=2, down=[before[1]],
                                         weights={before[1]: 0}))
        # sync surface sees the new epoch immediately
        got = r.full(om, (1, 0))
        want = om.pg_to_up_acting_full((1, 0))
        assert _full_tuple(got) == _full_tuple(want)
        assert r.stats.placement_epoch_invalidations >= 1
        # async surface re-resolves under the new epoch too
        got = await r.afull(om, (1, 0))
        assert _full_tuple(got) == _full_tuple(want)

    asyncio.run(run())


def test_epoch_bump_mid_window_resolves_on_current_map():
    """Misses parked on the window when the epoch bumps must not be
    served from rows computed on the dead epoch."""
    async def run():
        om = _map()
        conf = _conf()
        conf.set("client_placement_batch_window", 0.02)
        r = PlacementResolver(conf=conf, batch=True)
        futs = [asyncio.ensure_future(r.afull(om, (1, ps)))
                for ps in range(32)]
        # bump while the window is still open
        om.apply_incremental(Incremental(epoch=2, down=[0],
                                         weights={0: 0}))
        got = await asyncio.gather(*futs)
        for ps, g in enumerate(got):
            want = om.pg_to_up_acting_full((1, ps))
            assert _full_tuple(g) == _full_tuple(want)

    asyncio.run(run())


def test_device_failure_falls_back_to_host(monkeypatch):
    async def run():
        om = _map()
        r = PlacementResolver(conf=_conf(), batch=True)

        def boom(*a, **kw):
            raise RuntimeError("no accelerator")

        monkeypatch.setattr(bulk, "do_rule_bulk", boom)
        got = await asyncio.gather(*(r.afull(om, (1, ps))
                                     for ps in range(32)))
        for ps, g in enumerate(got):
            want = om.pg_to_up_acting_full((1, ps))
            assert _full_tuple(g) == _full_tuple(want)
        assert r.stats.placement_batch_lookups == 0
        assert r.stats.placement_host_resolves >= 32

    saved = rmod._DEVICE_BROKEN
    try:
        asyncio.run(run())
    finally:
        # the sticky process latch must not poison later tests
        rmod._DEVICE_BROKEN = saved


def test_unsupported_map_rejected_once_host_serves():
    async def run():
        crush = cm.build_flat(6)
        crush.add_rule(cm.flat_firstn_rule(0))
        crush.tunables.choose_local_tries = 2  # device engine rejects
        om = OSDMap(crush, 6)
        om.add_pool(Pool(id=1, name="r", size=3, pg_num=32,
                         crush_rule=0))
        r = PlacementResolver(conf=_conf(), batch=True)
        got = await asyncio.gather(*(r.afull(om, (1, ps))
                                     for ps in range(32)))
        for ps, g in enumerate(got):
            want = om.pg_to_up_acting_full((1, ps))
            assert _full_tuple(g) == _full_tuple(want)
        assert r.stats.placement_batch_lookups == 0
        entry = r._compiles[id(om.crush)]
        assert entry.rejected

    asyncio.run(run())


def test_ab_lever_disables_batching(monkeypatch):
    monkeypatch.setenv("CEPH_TPU_PLACEMENT_BATCH", "0")

    async def run():
        om = _map()
        r = PlacementResolver(conf=_conf())  # reads the env lever
        got = await asyncio.gather(*(r.afull(om, (1, ps))
                                     for ps in range(32)))
        for ps, g in enumerate(got):
            want = om.pg_to_up_acting_full((1, ps))
            assert _full_tuple(g) == _full_tuple(want)
        assert r.stats.placement_batch_lookups == 0

    asyncio.run(run())


def test_below_min_batch_resolves_host():
    async def run():
        om = _map()
        r = PlacementResolver(conf=_conf(min_batch=64), batch=True)
        got = await asyncio.gather(*(r.afull(om, (1, ps))
                                     for ps in range(8)))
        for ps, g in enumerate(got):
            want = om.pg_to_up_acting_full((1, ps))
            assert _full_tuple(g) == _full_tuple(want)
        assert r.stats.placement_batch_lookups == 0

    asyncio.run(run())


def test_prewarm_fills_whole_pool_tables():
    async def run():
        om = _map()
        r = PlacementResolver(conf=_conf(), batch=True)
        warmed = await r.prewarm(om, [1, 2])
        assert warmed == 48
        assert r.stats.placement_batch_lookups >= 2
        # every subsequent lookup is a hit
        m0 = r.stats.placement_cache_misses
        for ps in range(32):
            r.up_acting(om, (1, ps))
        assert r.stats.placement_cache_misses == m0

    asyncio.run(run())


def test_resend_lands_on_post_remap_primary():
    """Cluster tier: with the batched resolver armed on the op path,
    a primary dying mid-workload must re-target the resend onto the
    post-remap primary (the swarm-shaped epoch-correctness contract).
    """
    from ceph_tpu.cluster.vstart import TestCluster

    async def run():
        c = TestCluster(n_osds=5, out_interval=1.0)
        await c.start()
        c.client.conf.set("client_placement_batch_min", 1)
        pool_id = await c.client.create_pool(
            Pool(id=7, name="remap", size=3, min_size=2, pg_num=8,
                 crush_rule=0))
        await c.wait_active(30)
        await c.client._placement.prewarm(c.client.osdmap, [pool_id])
        payload = b"x" * 4096
        await c.client.write_full(pool_id, "obj", payload)
        pgid = c.client.osdmap.object_to_pg(pool_id, b"obj")
        _up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        await c.kill_osd(primary)
        # the next write's tick-resend must land on the NEW primary
        # once the map moves (down -> out reroutes the PG)
        c.client.op_timeout = 30.0
        await c.client.write_full(pool_id, "obj", payload * 2)
        got = await c.client.read(pool_id, "obj")
        assert got == payload * 2
        stats = c.client.placement_stats()
        assert stats["placement_epoch_invalidations"] >= 1
        new_primary = c.client._calc_target(
            c.client.osdmap.object_to_pg(pool_id, b"obj"))
        assert new_primary != primary
        await c.stop()

    asyncio.run(run())
