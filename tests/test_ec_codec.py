"""Codec layer: interface contracts, techniques, registry, backends.

Models the reference's per-plugin round-trip tests
(src/test/erasure-code/TestErasureCodeJerasure.cc etc., SURVEY.md §4.1).
"""
import numpy as np
import pytest

from ceph_tpu import ec

TECHS = ["reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good"]


def make(plugin="rs_tpu", backend="host", **kw):
    profile = {"plugin": plugin, "backend": backend}
    profile.update({k: str(v) for k, v in kw.items()})
    return ec.load_codec(profile)


@pytest.mark.parametrize("technique", TECHS)
@pytest.mark.parametrize("backend", ["host", "device"])
def test_roundtrip_all_erasure_pairs(technique, backend, rng):
    c = make(backend=backend, k=4, m=2, technique=technique)
    data = rng.integers(0, 256, 4 * 128, dtype=np.uint8).tobytes()
    n = c.get_chunk_count()
    encoded = c.encode(range(n), data)
    assert len(encoded) == n
    # every 2-erasure pattern must be recoverable
    for a in range(n):
        for b in range(a + 1, n):
            chunks = {i: encoded[i] for i in range(n) if i not in (a, b)}
            dec = c.decode([a, b], chunks)
            np.testing.assert_array_equal(dec[a], encoded[a])
            np.testing.assert_array_equal(dec[b], encoded[b])


@pytest.mark.parametrize("backend", ["host", "device"])
def test_padding_object_not_multiple_of_k(backend, rng):
    c = make(backend=backend, k=5, m=2)
    data = rng.integers(0, 256, 1003, dtype=np.uint8).tobytes()
    cs = c.get_chunk_size(len(data))
    assert cs * 5 >= 1003 and cs % 4 == 0
    encoded = c.encode(range(7), data)
    got = c.decode_concat({i: encoded[i] for i in [0, 2, 3, 4, 6]})
    np.testing.assert_array_equal(
        got[:1003], np.frombuffer(data, dtype=np.uint8)
    )
    assert (got[1003:] == 0).all()  # zero padding (ErasureCode.cc:169)


def test_device_host_parity(rng):
    data = rng.integers(0, 256, 8 * 4096, dtype=np.uint8).tobytes()
    for technique in TECHS:
        h = make(backend="host", k=8, technique=technique)
        d = make(backend="device", k=8, technique=technique)
        eh = h.encode(range(h.get_chunk_count()), data)
        ed = d.encode(range(d.get_chunk_count()), data)
        for i in eh:
            np.testing.assert_array_equal(eh[i], ed[i], err_msg=technique)


def test_minimum_to_decode():
    c = make(k=4, m=2)
    # all wanted available: wanted only
    assert set(c.minimum_to_decode([0, 1], {0, 1, 2, 3})) == {0, 1}
    # one missing: need k chunks
    got = c.minimum_to_decode([0], {1, 2, 3, 4, 5})
    assert len(got) == 4
    assert all(v == [(0, 1)] for v in got.values())
    with pytest.raises(ec.ECError):
        c.minimum_to_decode([0], {1, 2, 3})
    # cost-aware: prefer cheap chunks
    got = c.minimum_to_decode_with_cost([0], {1: 10, 2: 1, 3: 1, 4: 1, 5: 1})
    assert 1 not in got and len(got) == 4


def test_decode_passthrough_and_want_filter(rng):
    c = make(k=3, m=2)
    data = rng.integers(0, 256, 300, dtype=np.uint8).tobytes()
    enc = c.encode([0, 3], data)
    assert set(enc) == {0, 3}
    full = c.encode(range(5), data)
    # passthrough: wanted chunks all present, no decode needed
    out = c.decode([1, 2], {1: full[1], 2: full[2]})
    np.testing.assert_array_equal(out[1], full[1])


def test_raid6_forces_m2():
    c = make(technique="reed_sol_r6_op", k=4, m=7)
    assert c.get_coding_chunk_count() == 2
    assert c.get_profile()["m"] == "2"


def test_chunk_mapping_dd_d():
    # "DD_D": data chunks land at positions 0,1,3; coding chunk at 2
    # (ErasureCode::to_mapping, ErasureCode.cc:260-283)
    c = make(k=3, m=1, mapping="DD_D")
    assert [c.chunk_index(i) for i in range(4)] == [0, 1, 3, 2]


def test_registry():
    assert "rs_tpu" in ec.instance().names()
    assert "isa_tpu" in ec.instance().names()
    with pytest.raises(KeyError):
        ec.load_codec({"plugin": "nope"})
    with pytest.raises(ec.ECError):
        ec.load_codec({"plugin": "rs_tpu", "w": "16"})


def test_isa_plugin_technique_names(rng):
    c = make(plugin="isa_tpu", technique="cauchy", k=4, m=2)
    assert c.get_profile()["technique"] == "cauchy"
    data = rng.integers(0, 256, 512, dtype=np.uint8).tobytes()
    enc = c.encode(range(6), data)
    dec = c.decode([0, 1], {i: enc[i] for i in [2, 3, 4, 5]})
    np.testing.assert_array_equal(dec[0], enc[0])
    with pytest.raises(ec.ECError):
        make(plugin="isa_tpu", technique="liberation")


def test_batched_device_api(rng):
    from ceph_tpu.ops import rs

    c = make(backend="device", k=4, m=2)
    data_u8 = rng.integers(0, 256, (16, 4, 256), dtype=np.uint8)
    packed = rs.pack_u32(data_u8)
    parity = np.asarray(c.encode_batch(packed))
    present = (0, 2, 4, 5)
    surv = np.concatenate([packed[:, [0, 2]], parity], axis=1)
    dec = np.asarray(c.decode_batch(present, surv))
    np.testing.assert_array_equal(rs.unpack_u32(dec), data_u8)


def test_decode_under_nontrivial_mapping(rng):
    """Regression: decode must invert chunk_mapping, not treat stored
    positions as generator indices (review-confirmed corruption bug)."""
    c = make(k=2, m=2, mapping="D_D_")  # data at 0,2; coding at 1,3
    data = rng.integers(0, 256, 64, dtype=np.uint8).tobytes()
    enc = c.encode(range(4), data)
    half = np.frombuffer(data, np.uint8).reshape(2, -1)
    np.testing.assert_array_equal(enc[0], half[0])
    np.testing.assert_array_equal(enc[2], half[1])
    # lose the second data chunk (position 2): recover from d0 + one parity
    dec = c.decode([2], {0: enc[0], 1: enc[1]})
    np.testing.assert_array_equal(dec[2], half[1])
    # minimum_to_decode answers in position space too
    need = c.minimum_to_decode([2], {0, 1, 3})
    assert set(need) <= {0, 1, 3} and len(need) == 2


def test_mapping_validation():
    with pytest.raises(ec.ECError):
        make(k=3, m=1, mapping="DD")  # too short
    with pytest.raises(ec.ECError):
        make(k=3, m=1, mapping="DDDD_")  # wrong length
    with pytest.raises(ec.ECError):
        make(k=3, m=1, mapping="DD__")  # wrong D count


def test_alignment_reference_semantics():
    """get_alignment/get_chunk_size match the reference formulas
    (ErasureCodeJerasure.cc:174-184, ErasureCodeIsa.cc:66-79)."""
    c = make(k=8, m=3)
    assert c.get_alignment() == 8 * 8 * 4  # k*w*sizeof(int), w=8
    # object padded to alignment, chunk = padded/k
    assert c.get_chunk_size(1) == 256 // 8
    assert c.get_chunk_size(8 * 32) == 32
    assert c.get_chunk_size(8 * 32 + 1) == 64

    pc = make(k=8, m=3, **{"jerasure-per-chunk-alignment": "true"})
    assert pc.get_alignment() == 8 * 16  # w * LARGEST_VECTOR_WORDSIZE
    assert pc.get_chunk_size(1) == 128  # ceil(1/8) -> pad to 128
    assert pc.get_chunk_size(8 * 128 + 1) == 256

    isa = make(plugin="isa_tpu", k=7, m=3)
    assert isa.get_alignment() == 32  # EC_ISA_ADDRESS_ALIGNMENT
    assert isa.get_chunk_size(7 * 32) == 32
    assert isa.get_chunk_size(7 * 32 + 1) == 64  # ceil(225/7)=33 -> 64


def test_minimum_to_decode_raw_position_space():
    """With a non-trivial mapping the fetch set is chosen among stored
    positions directly (ErasureCode::_minimum_to_decode semantics), not
    translated through generator space first."""
    # k=3, m=2: mapping puts coding chunks at positions 0,2 and data at
    # 1,3,4 (mapping chars: non-D = coding).
    c = make(k=3, m=2, mapping="_D_DD")
    # data (generator 0,1,2) live at positions 1,3,4; coding at 0,2
    assert c.get_chunk_mapping() == [1, 3, 4, 0, 2]
    # want position 1 but it is missing; available positions 0,2,3,4:
    # reference picks the first k=3 of sorted available -> {0, 2, 3}
    got = c.minimum_to_decode([1], [0, 2, 3, 4])
    assert set(got) == {0, 2, 3}
    # decode using exactly that set must reproduce the missing chunk
    data = np.arange(3 * 64, dtype=np.uint8).tobytes()
    enc = c.encode(range(5), data)
    dec = c.decode([1], {p: enc[p] for p in got})
    np.testing.assert_array_equal(dec[1], enc[1])
    # consistency: with_cost picks in the same space
    got_cost = c.minimum_to_decode_with_cost(
        [1], {p: 1 for p in [0, 2, 3, 4]}
    )
    assert set(got_cost) == {0, 2, 3}
