"""MgrLite + OpTracker tests (DaemonServer/ClusterState, prometheus
exporter, OpRequest dump_historic_ops roles)."""
import asyncio

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.utils.admin import admin_command


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="p", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c


def test_mgr_status_and_health():
    async def t():
        c = await make()
        for i in range(5):
            await c.client.write_full(1, f"o{i}", b"x" * 100)
        await asyncio.sleep(c.hb_interval * 3)  # reports flow on hb
        st = c.mgr.status()
        assert st["osds"] == {"total": 4, "up": 4, "in": 4}
        assert st["pools"] == 1
        assert st["pgs"].get("active", 0) > 0
        assert st["client_ops_total"] >= 5
        assert st["health"] == "HEALTH_OK"
        # kill an OSD: health degrades to WARN with OSD_DOWN
        await c.kill_osd(3)
        await c.wait_down(3, 20)
        h = c.mgr.health()
        assert h["status"] == "HEALTH_WARN"
        assert "OSD_DOWN" in h["checks"]
        await c.stop()

    run(t())


def test_mgr_prometheus_exposition(tmp_path):
    async def t():
        c = await make()
        await c.client.write_full(1, "obj", b"data")
        await asyncio.sleep(c.hb_interval * 3)
        await c.mgr.start_admin(str(tmp_path / "mgr.sock"))
        text = await admin_command(c.mgr.admin.path, "prometheus")
        assert 'ceph_osd_up{osd="0"} 1' in text
        assert "ceph_osd_op_total" in text
        assert 'ceph_pg_states{state="active"}' in text
        status = await admin_command(c.mgr.admin.path, "status")
        assert status["osds"]["up"] == 4
        health = await admin_command(c.mgr.admin.path, "health")
        assert health["status"] == "HEALTH_OK"
        await c.stop()

    run(t())


def test_optracker_timelines(tmp_path):
    async def t():
        c = await make()
        for i in range(3):
            await c.client.write_full(1, f"t{i}", b"payload")
            await c.client.read(1, f"t{i}")
        # find the OSD(s) that served ops and check their history
        total_hist = 0
        for osd in c.osds:
            hist = osd.optracker.dump_historic_ops()
            total_hist += hist["num_ops"]
            for op in hist["ops"]:
                events = [e["event"] for e in op["events"]]
                assert events[0] == "queued"
                assert "dequeued" in events
                assert events[-1] == "done"
                assert op["duration"] is not None
                assert "osd_op" in op["description"]
            assert osd.optracker.dump_ops_in_flight()["num_ops"] == 0
        assert total_hist >= 6
        # admin socket surface
        osd = next(o for o in c.osds
                   if o.optracker.dump_historic_ops()["num_ops"])
        await osd.start_admin(str(tmp_path / "osd.sock"))
        dump = await admin_command(osd.admin.path, "dump_historic_ops")
        assert dump["num_ops"] >= 1
        await c.stop()

    run(t())
