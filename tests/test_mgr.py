"""MgrLite + OpTracker tests (DaemonServer/ClusterState, prometheus
exporter, OpRequest dump_historic_ops roles)."""
import asyncio

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.utils.admin import admin_command


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="p", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c


def test_mgr_status_and_health():
    async def t():
        c = await make()
        for i in range(5):
            await c.client.write_full(1, f"o{i}", b"x" * 100)
        await asyncio.sleep(c.hb_interval * 3)  # reports flow on hb
        st = c.mgr.status()
        assert st["osds"] == {"total": 4, "up": 4, "in": 4}
        assert st["pools"] == 1
        assert st["pgs"].get("active", 0) > 0
        assert st["client_ops_total"] >= 5
        assert st["health"] == "HEALTH_OK"
        # kill an OSD: health degrades to WARN with OSD_DOWN
        await c.kill_osd(3)
        await c.wait_down(3, 20)
        h = c.mgr.health()
        assert h["status"] == "HEALTH_WARN"
        assert "OSD_DOWN" in h["checks"]
        await c.stop()

    run(t())


def test_mgr_prometheus_exposition(tmp_path):
    async def t():
        c = await make()
        await c.client.write_full(1, "obj", b"data")
        await asyncio.sleep(c.hb_interval * 3)
        await c.mgr.start_admin(str(tmp_path / "mgr.sock"))
        text = await admin_command(c.mgr.admin.path, "prometheus")
        assert 'ceph_osd_up{osd="0"} 1' in text
        assert "ceph_osd_op_total" in text
        assert 'ceph_pg_states{state="active"}' in text
        status = await admin_command(c.mgr.admin.path, "status")
        assert status["osds"]["up"] == 4
        health = await admin_command(c.mgr.admin.path, "health")
        assert health["status"] == "HEALTH_OK"
        await c.stop()

    run(t())


def test_optracker_timelines(tmp_path):
    async def t():
        c = await make()
        for i in range(3):
            await c.client.write_full(1, f"t{i}", b"payload")
            await c.client.read(1, f"t{i}")
        # find the OSD(s) that served ops and check their history
        total_hist = 0
        for osd in c.osds:
            hist = osd.optracker.dump_historic_ops()
            total_hist += hist["num_ops"]
            for op in hist["ops"]:
                events = [e["event"] for e in op["events"]]
                assert events[0] == "queued"
                assert "dequeued" in events
                assert events[-1] == "done"
                assert op["duration"] is not None
                assert "osd_op" in op["description"]
            assert osd.optracker.dump_ops_in_flight()["num_ops"] == 0
        assert total_hist >= 6
        # admin socket surface
        osd = next(o for o in c.osds
                   if o.optracker.dump_historic_ops()["num_ops"])
        await osd.start_admin(str(tmp_path / "osd.sock"))
        dump = await admin_command(osd.admin.path, "dump_historic_ops")
        assert dump["num_ops"] >= 1
        await c.stop()

    run(t())


THIRD_PARTY_MODULE = '''
"""A third-party mgr module (drop-in file format)."""
from ceph_tpu.cluster.mgr_module import MgrModule


class Module(MgrModule):
    COMMANDS = [{"cmd": "hello world", "desc": "demo command"}]

    def __init__(self, name, host):
        super().__init__(name, host)
        self.notifies = []

    def notify(self, what, ident):
        self.notifies.append((what, ident))

    async def serve(self):
        await self.set_store("served", "yes")

    async def handle_command(self, cmd, args):
        osdmap = self.get("osd_map")
        return {"greeting": args.get("name", "world"),
                "osds": osdmap.n_osds,
                "served": self.get_store("served"),
                "notified": bool(self.notifies)}
'''


def test_mgr_module_host_drop_in(tmp_path):
    """A third-party module FILE drops into a directory and runs
    (ActivePyModules role): its command registers on the admin socket,
    serve() runs, notify() fires on reports, and set_store/get_store
    persist through the mon's config DB."""
    async def t():
        mod_dir = tmp_path / "modules"
        mod_dir.mkdir()
        (mod_dir / "hello.py").write_text(THIRD_PARTY_MODULE)

        c = await make()
        loaded = c.mgr.load_modules_from(mod_dir)
        assert loaded == ["hello"]
        # builtins run as modules too — the substrate, not hardcoded
        assert {"balancer", "pg_autoscaler", "prometheus"} \
            <= set(c.mgr.modules)
        await asyncio.sleep(0.6)  # serve() ran; a report tick arrived
        await c.mgr.start_admin(str(tmp_path / "mgr.sock"))
        out = await admin_command(c.mgr.admin.path, "hello world",
                                  name="ceph")
        assert out["greeting"] == "ceph"
        assert out["osds"] == 4
        assert out["served"] == "yes"  # set_store -> config DB -> back
        assert out["notified"]  # notify() delivered
        mods = await admin_command(c.mgr.admin.path, "mgr modules")
        assert "hello" in mods
        await c.stop()

    run(t())


def test_mgr_module_store_survives_mgr_restart(tmp_path):
    """Module KV (set_store/get_store) lives in the mon's central
    config DB, so a fresh mgr instance sees it (MonKVStore role)."""
    async def t():
        c = await make()
        await c.mgr.modules["pg_autoscaler"].set_store("marker", "42")
        await asyncio.sleep(0.3)

        from ceph_tpu.cluster.mgr import MgrLite

        await c.mgr.stop()
        mgr2 = MgrLite(c.bus, c.mgr.mon)
        await mgr2.start()
        await asyncio.sleep(1.2)  # subscribe -> MConfig push lands
        assert mgr2.modules["pg_autoscaler"].get_store("marker") == "42"
        c.mgr = mgr2  # let cluster teardown stop the new instance
        await c.stop()

    run(t())


def test_dashboard_module():
    """The dashboard mgr module serves the read-only web UI + JSON API
    (src/pybind/mgr/dashboard monitoring-slice role)."""
    async def t():
        c = await make()
        await c.client.write_full(1, "obj", b"data")
        await asyncio.sleep(c.hb_interval * 3)  # reports flow
        dash = c.mgr.modules["dashboard"]
        # opt-in like the reference: no socket until `dashboard start`
        assert dash.addr is None
        out = await c.mgr.dispatch_command("dashboard start", {})
        assert dash.addr is not None
        assert out["url"] == f"http://{dash.addr[0]}:{dash.addr[1]}/"

        async def get(path):
            r, w = await asyncio.open_connection(*dash.addr)
            w.write(f"GET {path} HTTP/1.1\r\nhost: x\r\n\r\n".encode())
            await w.drain()
            status = int((await r.readline()).split()[1])
            hdrs = {}
            while True:
                line = await r.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, v = line.decode().split(":", 1)
                hdrs[k.strip().lower()] = v.strip()
            body = await r.readexactly(int(hdrs.get("content-length",
                                                    "0")))
            w.close()
            return status, body

        code, body = await get("/")
        page = body.decode()
        assert code == 200 and "HEALTH_OK" in page
        assert "osd.3" in page and "active" in page
        import json as _json

        code, body = await get("/api/status")
        st = _json.loads(body)
        assert code == 200 and st["osds"]["up"] == 4
        code, body = await get("/api/osds")
        osds = _json.loads(body)
        assert len(osds) == 4 and all(o["up"] for o in osds)
        code, _ = await get("/nope")
        assert code == 404
        # degraded cluster renders the warning banner
        await c.kill_osd(3)
        await c.wait_down(3, 20)
        code, body = await get("/")
        assert b"HEALTH_WARN" in body and b"OSD_DOWN" in body
        # the `dashboard url` command answers with the bound address
        out = await c.mgr.dispatch_command("dashboard url", {})
        assert out["url"].startswith("http://127.0.0.1:")
        await c.stop()

    run(t())


def test_osd_bench_admin_command(tmp_path):
    """`ceph tell osd.N bench` role: raw store write throughput via
    the admin socket, scratch state cleaned up."""
    async def t():
        c = await make()
        osd = c.osds[0]
        await osd.start_admin(str(tmp_path / "osd.sock"))
        out = await admin_command(osd.admin.path, "bench",
                                  count=8, size=65536)
        assert out["bytes_written"] == 8 * 65536
        assert out["bytes_per_sec"] > 0 and out["iops"] > 0
        # scratch collection removed (unique per-invocation cid)
        assert not [cid for cid in osd.store.list_collections()
                    if str(cid).startswith(f"bench.{osd.id}")]
        # size clamp: an absurd request is bounded, not fatal
        out = await admin_command(osd.admin.path, "bench",
                                  count=2, size=1 << 30)
        assert out["blocksize"] == 4 << 20
        await c.stop()

    run(t())


def test_crash_module():
    """crash mgr module: post/ls/info/rm/prune + recent summary,
    persisted in the mon-backed module store."""
    async def t():
        c = await make()
        out = await c.mgr.dispatch_command(
            "crash post", {"entity": "osd.2",
                           "backtrace": "0x1 raise\n0x2 abort"})
        cid = out["crash_id"]
        ls = await c.mgr.dispatch_command("crash ls", {})
        assert [e["crash_id"] for e in ls] == [cid]
        info = await c.mgr.dispatch_command("crash info", {"id": cid})
        assert info["entity_name"] == "osd.2" \
            and "abort" in info["backtrace"]
        stat = await c.mgr.dispatch_command("crash stat", {})
        assert stat == {"total": 1, "recent": 1,
                        "health": "RECENT_CRASH"}
        # an ancient crash prunes; the fresh one survives
        import time as _t
        old = await c.mgr.dispatch_command(
            "crash post", {"entity": "osd.0",
                           "ts": _t.time() - 30 * 86400})
        out = await c.mgr.dispatch_command("crash prune",
                                           {"keep_days": 14})
        assert out == {"removed": 1}
        ls = await c.mgr.dispatch_command("crash ls", {})
        assert [e["crash_id"] for e in ls] == [cid]
        await c.mgr.dispatch_command("crash rm", {"id": cid})
        assert await c.mgr.dispatch_command("crash ls", {}) == []
        assert old["crash_id"]  # only shape-used above
        await c.stop()

    run(t())


def test_telemetry_module():
    """telemetry mgr module: opt-in state machine + anonymized report
    (shapes and counts, no pool names)."""
    async def t():
        c = await make()
        st = await c.mgr.dispatch_command("telemetry status", {})
        assert st == {"enabled": False, "last_report_at": None}
        rep = await c.mgr.dispatch_command("telemetry show", {})
        assert rep["osd"]["count"] == 4
        assert rep["pools"] and rep["pools"][0]["size"] == 3
        # anonymized: no pool names anywhere in the report
        import json as _json
        assert "'p'" not in str(rep) and '"p"' not in _json.dumps(rep)
        await c.mgr.dispatch_command("telemetry on", {})
        out = await c.mgr.dispatch_command("telemetry send", {})
        assert out["sent"]
        st = await c.mgr.dispatch_command("telemetry status", {})
        assert st["enabled"] and st["last_report_at"] is not None
        await c.mgr.dispatch_command("telemetry off", {})
        st = await c.mgr.dispatch_command("telemetry status", {})
        assert not st["enabled"]
        await c.stop()

    run(t())
