"""rbd-mirror-lite: journaled images + cross-cluster async replication
(the src/journal + rbd_mirror roles). Two independent in-process
clusters; the daemon replays the primary's image journals onto the
secondary and survives trims, incremental syncs, and the promote
split-brain guard."""
import asyncio
import os

import pytest

from ceph_tpu.cluster import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services import mirror as mir
from ceph_tpu.services.rbd import RBD


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make_site(pool_id=1):
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=pool_id, name="rbd", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    return c


def test_journal_append_read_trim():
    async def t():
        a = await make_site()
        rbd = RBD(a.client, 1)
        await rbd.create("img", 1 << 22)
        img = await mir.journaled(a.client, 1, "img")
        await img.write(0, b"abc" * 1000)
        await img.write(8192, b"xyz")
        entries = await img.journal_read(0)
        assert [e[1][0] for e in entries] == [mir.E_WRITE, mir.E_WRITE]
        assert entries[0][1][3] == b"abc" * 1000
        # trim the first entry; positions stay logical
        first_end = entries[0][0]
        await img.journal_trim(first_end)
        tail = await img.journal_read(first_end)
        assert len(tail) == 1 and tail[0][1][3] == b"xyz"
        assert await img.journal_tail() == entries[1][0]
        await a.stop()

    run(t())


def test_mirror_replicates_and_stays_incremental():
    async def t():
        a = await make_site()
        b = await make_site()
        rbd_a = RBD(a.client, 1)
        await rbd_a.create("vol", 1 << 22)
        img = await mir.journaled(a.client, 1, "vol")
        data1 = os.urandom(10000)
        await img.write(5000, data1)

        d = mir.MirrorDaemon(a.client, 1, b.client, 1)
        # bootstrap copies the head as of the journal tail: the pre-sync
        # write arrives via the copy, not replay
        assert await d.sync_image("vol") == 0
        dst = await RBD(b.client, 1).open("vol")
        assert await dst.read(5000, 10000) == data1
        assert dst.size == 1 << 22

        # incremental: only NEW entries replay (journal was trimmed)
        data2 = os.urandom(3000)
        await img.write(0, data2)
        await img.discard(5000, 8192)
        await img.resize(1 << 21)
        assert await d.sync_image("vol") == 3
        dst = await RBD(b.client, 1).open("vol")
        assert await dst.read(0, 3000) == data2
        assert await dst.read(5000, 100) == b"\x00" * 100
        assert dst.size == 1 << 21
        assert await d.sync_image("vol") == 0  # caught up
        await a.stop()
        await b.stop()

    run(t())


def test_mirror_snapshots_and_daemon_loop():
    async def t():
        a = await make_site()
        b = await make_site()
        rbd_a = RBD(a.client, 1)
        await rbd_a.create("snapvol", 1 << 20)
        img = await mir.journaled(a.client, 1, "snapvol")
        await img.write(0, b"v1" * 500)
        await img.snap_create("s1")
        await img.write(0, b"v2" * 500)

        d = mir.MirrorDaemon(a.client, 1, b.client, 1,
                             poll_interval=0.05)
        await d.start()
        for _ in range(100):  # wait until the loop catches up
            try:
                dst = await RBD(b.client, 1).open("snapvol")
                if (await dst.read(0, 1000) == b"v2" * 500
                        and "s1" in await dst.snap_list()):
                    break
            except Exception:
                pass
            await asyncio.sleep(0.05)
        await d.stop()
        dst = await RBD(b.client, 1).open("snapvol")
        assert await dst.read(0, 1000) == b"v2" * 500
        snap_view = await RBD(b.client, 1).open("snapvol", snap="s1")
        assert await snap_view.read(0, 1000) == b"v1" * 500
        await a.stop()
        await b.stop()

    run(t())


def test_promote_guard_blocks_split_brain():
    async def t():
        a = await make_site()
        b = await make_site()
        await RBD(a.client, 1).create("guard", 1 << 20)
        img = await mir.journaled(a.client, 1, "guard")
        await img.write(0, b"x" * 100)
        d = mir.MirrorDaemon(a.client, 1, b.client, 1)
        await d.sync_image("guard")
        # failover: promote the secondary; further replay must refuse
        await mir.promote(b.client, 1, "guard")
        await img.write(200, b"y" * 100)
        with pytest.raises(IOError, match="promoted"):
            await d.sync_image("guard")
        # demote re-enables replication
        await mir.demote(b.client, 1, "guard")
        assert await d.sync_image("guard") == 1
        dst = await RBD(b.client, 1).open("guard")
        assert await dst.read(200, 100) == b"y" * 100
        await a.stop()
        await b.stop()

    run(t())


def test_rejected_write_leaves_no_journal_entry():
    """A past-end write must fail BEFORE journaling, or the secondary
    would replay a phantom mutation the primary never applied."""
    async def t():
        a = await make_site()
        await RBD(a.client, 1).create("small", 4096)
        img = await mir.journaled(a.client, 1, "small")
        with pytest.raises(IOError, match="past end"):
            await img.write(4096, b"x" * 100)
        assert await img.journal_read(0) == []
        await a.stop()

    run(t())


def test_bootstrap_replicates_snapshot_history():
    """Bootstrap of an absent secondary must reproduce each snapshot's
    OWN content (oldest-first), not stamp snapshots onto the current
    head — and must not replay pre-bootstrap journal entries."""
    async def t():
        a = await make_site()
        b = await make_site()
        await RBD(a.client, 1).create("hist", 1 << 20)
        img = await mir.journaled(a.client, 1, "hist")
        await img.write(0, b"A" * 4096)
        await img.snap_create("s1")
        await img.write(8192, b"B" * 4096)  # post-s1 data
        await img.write(0, b"\x00" * 4096)  # zeroed since s1
        d = mir.MirrorDaemon(a.client, 1, b.client, 1)
        await d.sync_image("hist")
        sview = await RBD(b.client, 1).open("hist", snap="s1")
        assert await sview.read(0, 4096) == b"A" * 4096
        assert await sview.read(8192, 4096) == b"\x00" * 4096  # no B!
        head = await RBD(b.client, 1).open("hist")
        assert await head.read(8192, 4096) == b"B" * 4096
        assert await head.read(0, 4096) == b"\x00" * 4096
        await a.stop()
        await b.stop()

    run(t())


def test_cls_journal_trim_atomicity_semantics():
    """journal.trim runs server-side (atomic with appends): trimming to
    a mid-journal offset keeps later records; past-tail trim errors."""
    async def t():
        a = await make_site()
        await RBD(a.client, 1).create("jt", 1 << 20)
        img = await mir.journaled(a.client, 1, "jt")
        await img.write(0, b"one")
        await img.write(100, b"two")
        entries = await img.journal_read(0)
        await img.journal_trim(entries[0][0])
        left = await img.journal_read(entries[0][0])
        assert [e[1][3] for e in left] == [b"two"]
        with pytest.raises(IOError):
            await img.journal_trim(entries[1][0] + 999)
        # records appended AFTER a trim parse cleanly from the position
        await img.write(200, b"three")
        tail = await img.journal_read(entries[0][0])
        assert [e[1][3] for e in tail] == [b"two", b"three"]
        await a.stop()

    run(t())


def test_bootstrap_existing_image():
    """An image with pre-journal history bootstraps via full copy, then
    journal entries replay on top."""
    async def t():
        a = await make_site()
        b = await make_site()
        rbd_a = RBD(a.client, 1)
        await rbd_a.create("boot", 1 << 21)
        plain = await rbd_a.open("boot")
        old = os.urandom(7000)
        await plain.write(100_000, old)  # unjournaled history
        img = await mir.journaled(a.client, 1, "boot")
        new = os.urandom(500)
        await img.write(0, new)
        d = mir.MirrorDaemon(a.client, 1, b.client, 1)
        await d.sync_image("boot")
        dst = await RBD(b.client, 1).open("boot")
        assert await dst.read(100_000, 7000) == old
        assert await dst.read(0, 500) == new
        await a.stop()
        await b.stop()

    run(t())
