"""C++ native core: GF/RS parity with Python+JAX, CRC vectors, straw2."""
import numpy as np
import pytest

from ceph_tpu import native as nt
from ceph_tpu.ops import gf8, rs


def test_gf_mul_parity():
    rng = np.random.default_rng(3)
    for _ in range(300):
        a, b = (int(v) for v in rng.integers(0, 256, 2))
        assert nt.gf_mul(a, b) == gf8.gf_mul(a, b)


@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 3), (10, 4)])
def test_matrix_parity(k, m):
    assert (nt.rs_matrix_vandermonde(k, m) == gf8.vandermonde_rs_matrix(k, m)).all()
    assert (nt.rs_matrix_cauchy(k, m) == gf8.cauchy_rs_matrix(k, m)).all()


def test_matinv_parity(rng):
    m = rng.integers(0, 256, (6, 6)).astype(np.uint8)
    try:
        want = gf8.gf_mat_inv(m)
    except np.linalg.LinAlgError:
        with pytest.raises(np.linalg.LinAlgError):
            nt.gf_matinv(m)
        return
    assert (nt.gf_matinv(m) == want).all()


def test_rs_encode_native_vs_jax(rng):
    k, m, L = 8, 3, 4096
    gen = nt.rs_matrix_vandermonde(k, m)
    data = rng.integers(0, 256, (k, L), dtype=np.uint8)
    native = nt.rs_encode(gen, data)
    jaxed = rs.unpack_u32(np.asarray(rs.encode(gen, rs.pack_u32(data))))
    assert (native == jaxed).all()
    # multithreaded path identical
    assert (nt.rs_encode(gen, data, threads=4) == native).all()


def test_rs_decode_native(rng):
    k, m, L = 8, 3, 1024
    gen = nt.rs_matrix_vandermonde(k, m)
    data = rng.integers(0, 256, (k, L), dtype=np.uint8)
    parity = nt.rs_encode(gen, data)
    allc = np.concatenate([data, parity])
    present = [0, 2, 3, 4, 5, 6, 8, 10]
    rec = nt.rs_decode(gen, present, allc[present])
    assert (rec == data).all()


def test_crc32c_known_vectors():
    # standard CRC-32C check value: crc32c("123456789") = 0xE3069283
    assert nt.crc32c(b"123456789", seed=0xFFFFFFFF) ^ 0xFFFFFFFF == 0xE3069283
    # incremental == one-shot
    a = nt.crc32c(b"hello ", seed=0xFFFFFFFF)
    assert nt.crc32c(b"world", seed=a) == nt.crc32c(b"hello world", seed=0xFFFFFFFF)


def test_crc32c_zeros_combine():
    for n in (0, 1, 7, 8, 9, 63, 4096, 100000):
        direct = nt.crc32c(np.zeros(n, np.uint8), seed=0xDEADBEEF)
        fast = nt.crc32c(None, seed=0xDEADBEEF, length=n)
        assert direct == fast, n


def test_crc32c_batch(rng):
    blobs = rng.integers(0, 256, (64, 4096), dtype=np.uint8)
    got = nt.crc32c_batch(blobs)
    for i in range(64):
        assert got[i] == nt.crc32c(blobs[i])
    assert (nt.crc32c_batch(blobs, threads=4) == got).all()


def test_crc32c_hw_sw_agree(rng):
    data = rng.integers(0, 256, 100001, dtype=np.uint8)
    assert nt.crc32c(data, seed=123) == nt.lib().ct_crc32c_sw(123, data, data.size)


def test_xxhash_vectors():
    assert nt.xxhash32(b"") == 0x02CC5D05
    assert nt.xxhash32(b"abc") == 0x32D153FF
    assert nt.xxhash64(b"") == 0xEF46DB3751D8E999
    assert nt.xxhash64(b"abc") == 0x44BC2CF5AD770999


def test_straw2_weight_proportionality():
    # straw2's contract: selection probability proportional to weight
    # (mapper.c:339 straw2 exponential-minimum argument)
    items = np.arange(4, dtype=np.int32)
    w = np.array([1, 2, 3, 2], dtype=np.uint32) * 0x10000  # 16.16 fixed point
    xs = np.arange(200000, dtype=np.uint32)
    out = nt.straw2_bulk(items, w, xs, r=0)
    counts = np.bincount(out, minlength=4).astype(float)
    frac = counts / counts.sum()
    want = w / w.sum()
    assert np.abs(frac - want).max() < 0.01


def test_straw2_zero_weight_never_chosen():
    items = np.arange(3, dtype=np.int32)
    w = np.array([0x10000, 0, 0x10000], dtype=np.uint32)
    out = nt.straw2_bulk(items, w, np.arange(5000, dtype=np.uint32))
    assert 1 not in set(out.tolist())


def test_straw2_stability_under_weight_change():
    # straw2's headline property vs straw: changing one item's weight only
    # moves inputs to/from that item, never between unchanged items.
    items = np.arange(5, dtype=np.int32)
    w1 = np.array([3, 3, 3, 3, 3], dtype=np.uint32) * 0x10000
    w2 = w1.copy()
    w2[2] = 1 * 0x10000  # shrink item 2
    xs = np.arange(50000, dtype=np.uint32)
    a = nt.straw2_bulk(items, w1, xs)
    b = nt.straw2_bulk(items, w2, xs)
    moved = a != b
    # every change must involve item 2 (losing an input it used to win)
    assert ((a[moved] == 2) | (b[moved] == 2)).all()
    assert (a[moved] == 2).sum() > 0 and (b[moved] == 2).sum() == 0
