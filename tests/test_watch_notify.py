"""watch/notify tests (the librados watch_notify test role)."""
import asyncio

import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 60))


async def make():
    c = TestCluster(n_osds=3)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="p", size=3, pg_num=4, crush_rule=0)
    )
    await c.wait_active(20)
    return c


def test_watch_notify_roundtrip():
    async def t():
        c = await make()
        cl = c.client
        await cl.write_full(1, "bell", b"x")
        events = []
        got = asyncio.Event()

        def on_notify(oid, notify_id, payload):
            events.append((oid, notify_id, payload))
            got.set()

        cookie = await cl.watch(1, "bell", on_notify)
        nid = await cl.notify(1, "bell", b"ding")
        await asyncio.wait_for(got.wait(), 5)
        assert events == [(b"bell", nid, b"ding")]
        # second notify; ids increase
        got.clear()
        nid2 = await cl.notify(1, "bell", b"dong")
        await asyncio.wait_for(got.wait(), 5)
        assert nid2 > nid and events[-1][2] == b"dong"
        # unwatch: no more deliveries
        await cl.unwatch(1, "bell", cookie)
        await cl.notify(1, "bell", b"silent")
        await asyncio.sleep(0.2)
        assert len(events) == 2
        # watching a nonexistent object is ENOENT
        with pytest.raises(KeyError):
            await cl.watch(1, "ghost", on_notify)
        await c.stop()

    run(t())


def test_multiple_watchers():
    async def t():
        c = await make()
        cl = c.client
        await cl.write_full(1, "topic", b"x")
        hits = []
        c1 = await cl.watch(1, "topic",
                            lambda o, n, p: hits.append(("w1", p)))
        c2 = await cl.watch(1, "topic",
                            lambda o, n, p: hits.append(("w2", p)))
        await cl.notify(1, "topic", b"fanout")
        await asyncio.sleep(0.2)
        assert sorted(hits) == [("w1", b"fanout"), ("w2", b"fanout")]
        await cl.unwatch(1, "topic", c1)
        await cl.unwatch(1, "topic", c2)
        await c.stop()

    run(t())
