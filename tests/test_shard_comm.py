"""Distributed EC over the device mesh (parallel/shard_comm): shards
resident one-per-device on the width axis, repair/encode as mesh
collectives — bit-exact vs the host oracle for both combine
strategies, on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ceph_tpu import native
from ceph_tpu import parallel
from ceph_tpu.ops import gf8, rs
from ceph_tpu.parallel import shard_comm

K, M = 8, 3
W = 256  # words per chunk
BATCH = 8


@pytest.fixture(scope="module")
def mesh4():
    devs = parallel.get_devices(8)
    return parallel.make_mesh(devs, width=4)


def _setup(rng):
    mat = native.rs_matrix_vandermonde(K, M)
    data_b = rng.integers(0, 256, (BATCH, K, W * 4), dtype=np.uint8)
    parity_b = np.stack([gf8.gf_matmul(mat, d) for d in data_b])
    return mat, data_b, parity_b


@pytest.mark.parametrize("method", ["allgather", "psum_bits"])
def test_distributed_repair_bit_exact(mesh4, method):
    rng = np.random.default_rng(1)
    mat, data_b, parity_b = _setup(rng)
    erased = (1, 6)
    present = [i for i in range(K) if i not in erased] + [K, K + 1]
    surv = np.concatenate(
        [rs.pack_u32(data_b)[:, [i for i in range(K) if i not in erased]],
         rs.pack_u32(parity_b)[:, :2]], axis=1)  # (B, 8, W)
    xs = jax.device_put(jnp.asarray(surv),
                        shard_comm.shard_placement_sharding(mesh4))
    out = shard_comm.distributed_repair(mesh4, mat, K, present, xs,
                                        method=method)
    assert (rs.unpack_u32(np.asarray(out)) == data_b).all()
    # result is batch-sharded, chunk axis whole
    spec = out.sharding.spec
    assert spec[0] == parallel.STRIPE_AXIS


@pytest.mark.parametrize("method", ["allgather", "psum_bits"])
def test_distributed_encode_bit_exact(mesh4, method):
    rng = np.random.default_rng(2)
    mat, data_b, parity_b = _setup(rng)
    xs = jax.device_put(jnp.asarray(rs.pack_u32(data_b)),
                        shard_comm.shard_placement_sharding(mesh4))
    out = shard_comm.distributed_encode(mesh4, mat, xs, method=method)
    assert (rs.unpack_u32(np.asarray(out)) == parity_b).all()


def test_methods_agree_under_jit(mesh4):
    rng = np.random.default_rng(3)
    mat, data_b, _ = _setup(rng)
    xs = jax.device_put(jnp.asarray(rs.pack_u32(data_b)),
                        shard_comm.shard_placement_sharding(mesh4))

    @jax.jit
    def both(x):
        a = shard_comm.distributed_encode(mesh4, mat, x, "allgather")
        b = shard_comm.distributed_encode(mesh4, mat, x, "psum_bits")
        return a, b

    a, b = both(xs)
    assert (np.asarray(a) == np.asarray(b)).all()


def test_uneven_split_rejected(mesh4):
    mat = native.rs_matrix_vandermonde(6, 2)  # 6 chunks over 4 devices
    xs = jnp.zeros((BATCH, 6, W), jnp.uint32)
    with pytest.raises(ValueError, match="do not split"):
        shard_comm.distributed_encode(mesh4, mat, xs)
