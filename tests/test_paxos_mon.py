"""Multi-mon consensus tests: quorum formation, replicated commits,
leader failover, quorum loss (the Paxos.cc + Elector roles)."""
import asyncio

import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool


def run(coro, timeout=120):
    asyncio.run(asyncio.wait_for(coro, timeout))


async def make(n_mons=3, n_osds=4):
    c = TestCluster(n_osds=n_osds, n_mons=n_mons)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="p", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c


def test_quorum_forms_and_cluster_works():
    async def t():
        c = await make()
        # lowest rank leads (classic elector)
        assert c.mon.rank == 0
        assert len(c.mon.quorum) >= 2
        await c.client.write_full(1, "obj", b"replicated-map-data")
        assert await c.client.read(1, "obj") == b"replicated-map-data"
        await c.stop()

    run(t())


def test_commits_replicate_to_all_mons():
    async def t():
        c = await make()
        await c.client.write_full(1, "x", b"data")
        # drive a few epochs: kill an OSD (mark-down commits a map)
        await c.kill_osd(3)
        await c.wait_down(3, 20)
        await asyncio.sleep(0.5)  # let commits fan out
        epochs = [m.osdmap.epoch for m in c.mons if m is not None]
        assert len(set(epochs)) == 1, f"divergent epochs {epochs}"
        downs = [m.osdmap.osds[3].up for m in c.mons if m is not None]
        assert not any(downs)
        await c.stop()

    run(t())


def test_leader_failover():
    async def t():
        c = await make()
        assert c.mon.rank == 0
        epoch_before = c.mon.osdmap.epoch
        await c.kill_mon(0)
        # a new leader takes over and keeps serving the cluster
        await c.wait_quorum(15)
        assert c.mon.rank == 1
        assert c.mon.osdmap.epoch >= epoch_before
        # map mutations still commit: kill an OSD, map must advance
        await c.kill_osd(2)
        await c.wait_down(2, 25)
        # IO keeps working under the new mon
        await c.client.write_full(1, "after-failover", b"ok")
        assert await c.client.read(1, "after-failover") == b"ok"
        await c.stop()

    run(t())


def test_quorum_loss_stalls_map_mutations():
    async def t():
        c = await make()
        await c.kill_mon(1)
        await c.kill_mon(2)
        await asyncio.sleep(0.3)
        # 1 of 3 alive: no majority -> map mutation must fail
        from ceph_tpu.cluster.paxos_mon import QuorumLost
        from ceph_tpu.placement.osdmap import Incremental

        leader = c.mons[0]
        inc = Incremental(epoch=leader.osdmap.epoch + 1, down=[3])
        with pytest.raises(QuorumLost):
            await leader.commit(inc)
        await c.stop()

    run(t())
