"""Compressor plugin layer + SloppyCRCMap tests."""
import numpy as np
import pytest

from ceph_tpu.utils import compress as C
from ceph_tpu.utils.sloppy_crc import SloppyCRCMap


@pytest.mark.parametrize("name", ["zlib", "bz2", "lzma"])
def test_compressor_roundtrip(name):
    comp = C.create(name)
    data = b"the quick brown fox " * 500
    packed = comp.compress(data)
    assert len(packed) < len(data)
    assert comp.decompress(packed) == data


def test_compressor_corrupt_stream():
    comp = C.create("zlib")
    packed = bytearray(comp.compress(b"x" * 10000))
    packed[5] ^= 0xFF
    with pytest.raises(C.CompressError):
        comp.decompress(bytes(packed))


def test_unknown_compressor():
    with pytest.raises(C.CompressError):
        C.create("snappy9000")
    assert "zlib" in C.names()


def test_compression_modes():
    assert not C.should_compress(C.MODE_NONE, C.HINT_COMPRESSIBLE)
    assert C.should_compress(C.MODE_FORCE, C.HINT_INCOMPRESSIBLE)
    assert C.should_compress(C.MODE_PASSIVE, C.HINT_COMPRESSIBLE)
    assert not C.should_compress(C.MODE_PASSIVE, C.HINT_NONE)
    assert C.should_compress(C.MODE_AGGRESSIVE, C.HINT_NONE)
    assert not C.should_compress(C.MODE_AGGRESSIVE, C.HINT_INCOMPRESSIBLE)


def test_compress_blob_ratio_gate():
    comp = C.create("zlib")
    assert C.compress_blob(comp, b"A" * 8192) is not None
    incompressible = np.random.default_rng(1).integers(
        0, 256, 8192, dtype=np.uint8
    ).tobytes()
    assert C.compress_blob(comp, incompressible) is None


def test_walstore_compressed_snapshot(tmp_path):
    from ceph_tpu.store import Transaction
    from ceph_tpu.store.walstore import WalStore

    s = WalStore(str(tmp_path / "s"), compression="zlib")
    s.mount()
    t = Transaction().create_collection("c")
    t.write("c", b"big", 0, b"Z" * 100_000)  # compressible
    t.write("c", b"small", 0, b"tiny")
    s.apply_transaction(t)
    s.umount()
    import os

    snap_size = os.path.getsize(str(tmp_path / "s" / "snap"))
    assert snap_size < 10_000  # 100 KB of Zs squashed
    s2 = WalStore(str(tmp_path / "s"), compression="zlib")
    s2.mount()
    assert s2.read("c", b"big") == b"Z" * 100_000
    assert s2.read("c", b"small") == b"tiny"
    s2.umount()


# ------------------------------------------------------- SloppyCRCMap


def test_sloppy_full_block_writes_tracked():
    m = SloppyCRCMap(block_size=16)
    data = bytes(range(64))
    m.write(0, data)
    assert len(m.crc) == 4
    assert m.read_check(0, data) == []
    bad = bytearray(data)
    bad[20] ^= 1
    assert m.read_check(0, bytes(bad)) == [16]


def test_sloppy_partial_write_invalidates():
    m = SloppyCRCMap(block_size=16)
    m.write(0, bytes(64))
    m.write(8, b"xy")  # partial: block 0 forgotten
    assert 0 not in m.crc and 1 in m.crc
    # a check over a forgotten block reports nothing (sloppy contract)
    junk = b"j" * 16 + bytes(48)
    assert m.read_check(0, junk) == []


def test_sloppy_zero_truncate():
    m = SloppyCRCMap(block_size=16)
    m.write(0, bytes(range(16)) * 4)
    m.zero(16, 16)
    assert m.read_check(16, bytes(16)) == []
    m.truncate(40)  # cuts block 2 partially, drops block 3
    assert 3 not in m.crc and 2 not in m.crc
    assert 0 in m.crc and 1 in m.crc


def test_sloppy_encode_decode():
    m = SloppyCRCMap(block_size=32)
    m.write(0, bytes(range(128)))
    m2, used = SloppyCRCMap.decode(m.encode())
    assert used == len(m.encode())
    assert m2.block_size == 32 and m2.crc == m.crc
