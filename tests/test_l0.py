"""L0 infrastructure tests: config schema/proxy/observers, perf
counters, logging ring, admin socket, and their wiring into a live
OSD daemon."""
import asyncio
import io

import pytest

from ceph_tpu.utils import config as cfg
from ceph_tpu.utils.admin import AdminSocket, admin_command
from ceph_tpu.utils.log import Log
from ceph_tpu.utils.perf import PerfCounters, PerfCountersCollection


# ------------------------------------------------------------- config


def test_config_defaults_and_types():
    c = cfg.proxy()
    assert c["osd_heartbeat_interval"] == 0.25
    assert c["osd_pg_log_keep"] == 128
    assert c["store_kind"] == "memstore"
    with pytest.raises(cfg.ConfigError):
        c.get("no_such_option")


def test_config_set_validate():
    c = cfg.proxy()
    c.set("osd_pg_log_keep", "256")
    assert c["osd_pg_log_keep"] == 256
    with pytest.raises(cfg.ConfigError):
        c.set("osd_pg_log_keep", 0)  # min 1
    with pytest.raises(cfg.ConfigError):
        c.set("store_kind", "rocks")  # enum
    c.set("walstore_compact_bytes", "8K")
    assert c["walstore_compact_bytes"] == 8192
    c.set("walstore_fsync", "yes")
    assert c["walstore_fsync"] is True
    c.reset("osd_pg_log_keep")
    assert c["osd_pg_log_keep"] == 128
    assert not c.is_set("osd_pg_log_keep")


def test_config_observers_fire_on_change():
    c = cfg.proxy()
    seen = []
    c.observe("osd_heartbeat_grace", lambda n, v: seen.append((n, v)))
    c.set("osd_heartbeat_grace", 5.0)
    c.set("osd_heartbeat_grace", 5.0)  # no change -> no fire
    c.set("osd_heartbeat_grace", 6.0)
    assert seen == [("osd_heartbeat_grace", 5.0),
                    ("osd_heartbeat_grace", 6.0)]


def test_config_freeze_blocks_non_runtime():
    c = cfg.proxy()
    c.set("store_kind", "walstore")  # fine before freeze
    c.freeze()
    with pytest.raises(cfg.ConfigError):
        c.set("store_kind", "memstore")
    c.set("osd_heartbeat_grace", 9.0)  # runtime ok
    assert c.diff()["store_kind"] == "walstore"


# --------------------------------------------------------------- perf


def test_perf_counters():
    p = PerfCounters("osd.0")
    p.add_u64_counter("ops")
    p.add_gauge("load")
    p.add_time_avg("lat")
    p.add_histogram("batch")
    p.inc("ops")
    p.inc("ops", 4)
    p.set("load", 0.7)
    p.tinc("lat", 0.5)
    p.tinc("lat", 1.5)
    p.observe("batch", 3)
    p.observe("batch", 100)
    d = p.dump()
    assert d["ops"] == 5
    assert d["load"] == 0.7
    assert d["lat"] == {"avgcount": 2, "sum": 2.0}
    assert d["batch"]["count"] == 2 and d["batch"]["sum"] == 103
    with p.time("lat"):
        pass
    assert p.dump()["lat"]["avgcount"] == 3


def test_perf_collection():
    coll = PerfCountersCollection()
    a = coll.create("osd.0")
    a.add_u64_counter("x")
    a.inc("x")
    b = coll.create("mon")
    b.add_gauge("y")
    d = coll.dump()
    assert d == {"mon": {"y": 0}, "osd.0": {"x": 1}}
    coll.remove("mon")
    assert "mon" not in coll.dump()


# ---------------------------------------------------------------- log


def test_log_levels_and_ring():
    buf = io.StringIO()
    log = Log(default_level=1, gather_level=10, ring_size=100,
              stream=buf)
    log.dout("osd", 1, "printed")
    log.dout("osd", 5, "gathered only")
    log.dout("osd", 15, "dropped entirely")
    out = buf.getvalue()
    assert "printed" in out and "gathered" not in out
    recent = log.dump_recent()
    assert len(recent) == 2  # printed + gathered, not the dropped one
    assert "gathered only" in recent[-1]
    log.set_level("osd", 5)
    log.dout("osd", 5, "now visible")
    assert "now visible" in buf.getvalue()


# ------------------------------------------------------- admin socket


def test_admin_socket_roundtrip(tmp_path):
    async def t():
        sock = AdminSocket(str(tmp_path / "a.sock"))
        sock.register("echo", lambda a: {"you said": a.get("msg")})
        await sock.start()
        got = await admin_command(sock.path, "echo", msg="hi")
        assert got == {"you said": "hi"}
        helped = await admin_command(sock.path, "help")
        assert "echo" in helped
        with pytest.raises(RuntimeError):
            await admin_command(sock.path, "nope")
        await sock.stop()

    asyncio.run(asyncio.wait_for(t(), 30))


def test_osd_admin_socket_live_cluster(tmp_path):
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    async def t():
        c = TestCluster(n_osds=3)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="p", size=3, pg_num=4, crush_rule=0)
        )
        await c.wait_active(20)
        await c.client.write_full(1, "x", b"payload")
        assert await c.client.read(1, "x") == b"payload"
        osd = c.osds[0]
        await osd.start_admin(str(tmp_path / "osd0.sock"))
        perf = await admin_command(osd.admin.path, "perf dump")
        assert perf["map_epochs"] >= 1
        status = await admin_command(osd.admin.path, "status")
        assert status["osd"] == 0 and status["pgs"] > 0
        pgs = await admin_command(osd.admin.path, "dump_pgs")
        assert all(v["state"] == "active" for v in pgs.values())
        conf = await admin_command(osd.admin.path, "config show")
        assert conf["osd_pg_log_keep"] == 128
        await admin_command(osd.admin.path, "config set",
                            key="osd_subop_timeout", value=7)
        assert osd.subop_timeout == 7.0
        # ops were counted on whichever OSD is the primary
        total_ops = 0
        for o in c.osds:
            total_ops += o.perf.dump()["op"]
        assert total_ops >= 2
        await c.stop()

    asyncio.run(asyncio.wait_for(t(), 60))
