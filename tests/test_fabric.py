"""Serving-fabric tier: the shared-memory ring messenger, mergeable
latency histograms, and ProcCluster as the measured topology.

What this file proves (ISSUE 20):
- ShmRing unit semantics: wrap-around slot reuse, full-ring
  backpressure, and epoch-tagged descriptor reclamation after peer
  death (a zombie's late release must be a no-op).
- ShmMessenger honors NetFaultPolicy identically to LocalBus/TCP —
  drop/delay/dup consult the SAME seeded plan() stream, so thrash
  schedules stay deterministic per backend.
- Histogram merging is exact where averaging per-worker percentiles
  is wrong (the satellite-1 fix).
- A seeded thrash over a ProcCluster of real daemon processes
  converges byte-exact on BOTH messenger backends, and one EC
  write/read cycle is byte-identical across localbus, tcp, and shm.
"""
import asyncio
import importlib.util
import os
from pathlib import Path

import pytest

from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.faults import NetFaultPolicy
from ceph_tpu.msg.shmring import ShmMessenger, ShmRing
from ceph_tpu.utils.lathist import LatHist

_REPO = Path(__file__).resolve().parents[1]


def run(coro, timeout=240):
    return asyncio.run(asyncio.wait_for(coro, timeout))


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        f"ceph_tpu_{name}", _REPO / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- ring units


def _ring_pair(tmp_path, slots=4, arena=1 << 16):
    path = str(tmp_path / "ring")
    prod = ShmRing(path, slots=slots, arena_bytes=arena, create=True)
    cons = ShmRing(path, slots=slots, arena_bytes=arena, create=False)
    return prod, cons


def test_ring_wraparound_reuses_slots_and_extents(tmp_path):
    prod, cons = _ring_pair(tmp_path, slots=4)
    # 10x the slot count forces both index wrap-around and arena
    # extent reuse; contents must survive the recycling byte-exact
    for i in range(40):
        payload = bytes([i & 0xFF]) * (100 + i)
        assert prod.try_send([payload], mtype=7)
        msgs = cons.recv_all()
        assert len(msgs) == 1
        assert bytes(msgs[0].view) == payload
        assert msgs[0].mtype == 7
        msgs[0].release()
    assert prod.sends == 40
    assert prod.backpressure_hits == 0
    prod.close(unlink=True)
    cons.close()


def test_ring_full_backpressure_then_release_unblocks(tmp_path):
    prod, cons = _ring_pair(tmp_path, slots=4)
    for _ in range(4):
        assert prod.try_send([b"x" * 64], mtype=1)
    # ring full (nothing consumed): the producer must refuse, not
    # overwrite
    assert not prod.try_send([b"y" * 64], mtype=1)
    assert prod.backpressure_hits == 1
    msgs = cons.recv_all()
    assert len(msgs) == 4
    # consumed but NOT released: slots are still pinned
    assert not prod.try_send([b"y" * 64], mtype=1)
    for m_ in msgs:
        m_.release()
    assert prod.try_send([b"y" * 64], mtype=1)
    got = cons.recv_all()
    assert len(got) == 1 and bytes(got[0].view) == b"y" * 64
    got[0].release()
    prod.close(unlink=True)
    cons.close()


def test_ring_arena_exhaustion_is_backpressure(tmp_path):
    prod, cons = _ring_pair(tmp_path, slots=64, arena=4096)
    assert prod.try_send([b"a" * 3000], mtype=1)
    # slots remain, arena does not: still backpressure, not a tear
    assert not prod.try_send([b"b" * 3000], mtype=1)
    msgs = cons.recv_all()
    for m_ in msgs:
        m_.release()
    assert prod.try_send([b"b" * 3000], mtype=1)
    for m_ in cons.recv_all():
        m_.release()
    prod.close(unlink=True)
    cons.close()


def test_ring_reclaim_after_peer_death_zombie_release_noop(tmp_path):
    prod, cons = _ring_pair(tmp_path, slots=8)
    for i in range(5):
        assert prod.try_send([b"z" * 200], mtype=i)
    zombies = cons.recv_all()
    assert len(zombies) == 5
    # consumer "dies" holding all 5 descriptors: reclaim force-frees
    # them and bumps epochs
    assert prod.reclaim_dead() == 5
    assert prod.reclaimed_dead == 5
    # the arena and every slot must be whole again
    for _ in range(8):
        assert prod.try_send([b"w" * 200], mtype=9)
    # a zombie's late release lands on a bumped epoch: no-op (the
    # slots it would flip are live again with NEW data)
    for z in zombies:
        z.release()
    fresh = cons.recv_all()
    assert len(fresh) == 8
    assert all(bytes(m_.view) == b"w" * 200 for m_ in fresh)
    for m_ in fresh:
        m_.release()
    # and the ring keeps working end-to-end after the whole episode
    assert prod.try_send([b"ok"], mtype=1)
    last = cons.recv_all()
    assert len(last) == 1 and bytes(last[0].view) == b"ok"
    last[0].release()
    prod.close(unlink=True)
    cons.close()


# ------------------------------------------------------- messenger pair


async def _mk_pair(tmp_path, faults_a=None):
    inbox_a, inbox_b = [], []

    async def da(src, msg):
        inbox_a.append((src, msg))

    async def db(src, msg):
        inbox_b.append((src, msg))

    a = ShmMessenger("a", da, faults=faults_a)
    b = ShmMessenger("b", db)
    # short /tmp paths: AF_UNIX socket paths cap at ~108 bytes
    sa = await a.listen(f"/tmp/ctpu-t{os.getpid()}-a.sock")
    sb = await b.listen(f"/tmp/ctpu-t{os.getpid()}-b.sock")
    return a, b, sa, sb, inbox_a, inbox_b


async def _drain(predicate, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while not predicate():
        if asyncio.get_running_loop().time() > deadline:
            return False
        await asyncio.sleep(0.01)
    return True


def test_shm_messenger_roundtrip_delivery(tmp_path):
    async def body():
        a, b, sa, sb, inbox_a, inbox_b = await _mk_pair(tmp_path)
        try:
            for i in range(50):
                await a.send(sb, M.MPing(osd=i, epoch=i * 2))
            assert await _drain(lambda: len(inbox_b) == 50)
            src, last = inbox_b[-1]
            assert src == "a"
            assert last.osd == 49 and last.epoch == 98
            await b.send(sa, M.MPing(osd=7, epoch=1))
            assert await _drain(lambda: len(inbox_a) == 1)
        finally:
            await a.close()
            await b.close()
    run(body())


def test_shm_messenger_fault_parity_with_policy_plan():
    """drop/delay/dup inject through the SAME NetFaultPolicy.plan()
    stream the LocalBus/TCP backends consult: a fresh policy with the
    same seed replays plan() and predicts the shm delivery count
    exactly (seed => schedule => verdict, per backend)."""
    import random

    async def body():
        pol = NetFaultPolicy(random.Random(42))
        pol.set_link("a", "*", drop=0.4, dup=0.3)
        a, b, sa, sb, _ia, inbox_b = await _mk_pair(None, faults_a=pol)
        try:
            n = 60
            for i in range(n):
                await a.send(sb, M.MPing(osd=i, epoch=0))
            # replay the identical plan stream to predict deliveries
            ref = NetFaultPolicy(random.Random(42))
            ref.set_link("a", "*", drop=0.4, dup=0.3)
            expect = sum(len(p) for i in range(n)
                         if (p := ref.plan("a", sb)) is not None)
            assert await _drain(lambda: len(inbox_b) >= expect, 10)
            await asyncio.sleep(0.05)  # no EXTRA copies either
            assert len(inbox_b) == expect
            assert 0 < expect < 2 * n  # faults actually engaged
        finally:
            await a.close()
            await b.close()
    run(body())


def test_shm_messenger_delay_and_partition_parity():
    import random

    async def body():
        pol = NetFaultPolicy(random.Random(3))
        a, b, sa, sb, _ia, inbox_b = await _mk_pair(None, faults_a=pol)
        try:
            # partition: silent drop, counted like every backend
            pol.partition({"a"}, {"*"})
            await a.send(sb, M.MPing(osd=1, epoch=1))
            await asyncio.sleep(0.1)
            assert inbox_b == []
            assert pol.counters.get("partition_drop", 0) == 1
            pol.heal()
            # delay: delivered, but not before the link delay elapses
            pol.set_link("a", "*", delay=0.2)
            loop = asyncio.get_running_loop()
            t0 = loop.time()
            await a.send(sb, M.MPing(osd=2, epoch=2))
            assert await _drain(lambda: len(inbox_b) == 1, 5)
            assert loop.time() - t0 >= 0.19
        finally:
            await a.close()
            await b.close()
    run(body())


# -------------------------------------------------- histogram semantics


def test_lathist_merge_exact_where_averaging_is_wrong():
    # two reactor shards with very different tails: worker A all-fast,
    # worker B all-slow. Pooled p99 is 100 ms; the old mean-of-
    # per-worker-percentiles path reports ~50 ms. The merged histogram
    # must land on the pooled answer.
    a, b = LatHist(), LatHist()
    for _ in range(1000):
        a.note_ms(1.0)
    for _ in range(100):
        b.note_ms(100.0)
    pooled = sorted([1.0] * 1000 + [100.0] * 100)
    exact_p99 = pooled[int(0.99 * len(pooled))]
    merged = LatHist.merged([a, b])
    assert merged.count == 1100
    assert abs(merged.percentile(0.99) - exact_p99) / exact_p99 < 0.02
    averaged = (a.percentile(0.99) + b.percentile(0.99)) / 2
    assert abs(averaged - exact_p99) / exact_p99 > 0.4  # the old bug


def test_lathist_json_roundtrip_and_merge_associativity():
    import json

    hs = [LatHist() for _ in range(3)]
    for i, h in enumerate(hs):
        for j in range(50):
            h.note_ms(0.5 * (i + 1) * (j + 1))
    wire = [json.loads(json.dumps(h.to_json())) for h in hs]
    back = [LatHist.from_json(d) for d in wire]
    m1 = LatHist.merged(back)
    m2 = LatHist.merged([back[2], back[0], back[1]])
    assert m1.count == m2.count == 150
    for p in (0.5, 0.99, 0.999):
        assert m1.percentile(p) == m2.percentile(p)
    assert m1.total_ms == pytest.approx(sum(h.total_ms for h in hs))


# ------------------------------------------- process-tier acceptance


@pytest.mark.parametrize("backend", ["tcp", "shm"])
def test_proccluster_seeded_thrash_converges(backend, tmp_path):
    """~5 s seeded thrash over REAL daemon processes on each messenger
    backend: post-heal active+clean, byte-exact oracle, a clean
    deep-scrub round, leak-free hedge ledger."""
    thrash = _load_tool("thrash")
    import argparse

    args = argparse.Namespace(
        seed=20260803, duration=4.0, osds=5, mons=1, k=3, m=2,
        profile="rs", pg_num=8, objects=6, obj_size=24 << 10,
        writers=2, settle=90.0, backend=backend,
        objectstore="walstore", proc=True)
    verdict = run(thrash._run_proc(args, max_unavail=2), timeout=300)
    assert verdict["converged"], verdict
    assert verdict["byte_exact"], verdict
    assert verdict["scrub_inconsistent"] == 0, verdict
    assert verdict["hedge_leak_free"], verdict["hedges"]
    assert verdict["passed"], verdict


def test_ec_write_read_byte_exact_across_backends(tmp_path):
    """One EC write/read cycle, three messenger backends, one source
    buffer: every byte identical (the A/B the zero-copy plane must
    not break)."""
    import numpy as np

    from ceph_tpu.cluster.procstart import ProcCluster
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    data = np.random.default_rng(20).integers(
        0, 256, 1 << 20, dtype=np.uint8).tobytes()
    pool = dict(id=2, name="ab", size=6, min_size=4, pg_num=8,
                crush_rule=1, type="erasure",
                ec_profile={"plugin": "rs_tpu", "k": "4", "m": "2",
                            "stripe_unit": "65536"})
    results = {}

    async def localbus():
        c = TestCluster(n_osds=6)
        await c.start()
        try:
            await c.client.create_pool(Pool(**pool))
            await c.wait_active(30)
            await c.client.write_full(2, "obj", data)
            results["localbus"] = bytes(await c.client.read(2, "obj"))
        finally:
            await c.stop()

    async def proc(backend):
        d = tmp_path / backend
        d.mkdir()
        c = ProcCluster(str(d), n_osds=6, objectstore="memstore",
                        backend=backend)
        await c.start()
        try:
            await c.client.create_pool(Pool(**pool))
            await c.wait_active(60)
            await c.client.write_full(2, "obj", data)
            results[backend] = bytes(await c.client.read(2, "obj"))
        finally:
            await c.stop()

    run(localbus())
    run(proc("tcp"))
    run(proc("shm"))
    assert results["localbus"] == data
    assert results["tcp"] == data
    assert results["shm"] == data
