"""LRC + SHEC plugin tests (the TestErasureCodeLrc/TestErasureCodeShec
roles): round-trips under every erasure pattern the codes tolerate,
locality of repair reads, shingle windows, and kml generation."""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import ECError, load_codec
from ceph_tpu.ec.shec_plugin import _shec_matrix, _window

RNG = np.random.default_rng(123)


def roundtrip(codec, obj: bytes, erase: set[int]) -> None:
    n = codec.get_chunk_count()
    encoded = codec.encode(list(range(n)), obj)
    assert set(encoded) == set(range(n))
    avail = {i: encoded[i] for i in range(n) if i not in erase}
    want = sorted(erase) or list(range(n))
    need = codec.minimum_to_decode(want, sorted(avail))
    assert set(need) <= set(avail), "plan demands an erased chunk"
    decoded = codec.decode(want, {i: avail[i] for i in need})
    for i in want:
        np.testing.assert_array_equal(
            decoded[i], encoded[i], err_msg=f"chunk {i}, erase {erase}"
        )


# ------------------------------------------------------------------ LRC


def lrc_docs_codec():
    return load_codec({
        "plugin": "lrc",
        "mapping": "__DD__DD",
        "layers": '[["_cDD_cDD", ""], ["cDDD____", ""], ["____cDDD", ""]]',
    })


def test_lrc_docs_example_roundtrip():
    codec = lrc_docs_codec()
    assert codec.k == 4
    assert codec.get_chunk_count() == 8
    obj = RNG.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    for erase in ([set()] + [{i} for i in range(8)]
                  + [{2, 7}, {0, 4}, {1, 5}, {3, 6}]):
        roundtrip(codec, obj, erase)


def test_lrc_local_repair_reads_fewer():
    """Losing chunk 7 must be repairable from the last-four group (the
    doc's 'loss of chunk 7 can be recovered with the last four
    chunks')."""
    codec = lrc_docs_codec()
    need = codec.minimum_to_decode([7], [0, 1, 2, 3, 4, 5, 6])
    assert set(need) <= {4, 5, 6}
    need2 = codec.minimum_to_decode([2], [0, 1, 3, 4, 5, 6, 7])
    assert set(need2) <= {0, 1, 3}


def test_lrc_kml_generation():
    codec = load_codec({"plugin": "lrc", "k": "4", "m": "2", "l": "3"})
    # (k+m)/l = 2 groups: 4 data + 2 global + 2 local = 8 chunks
    assert codec.k == 4
    assert codec.get_chunk_count() == 8
    assert codec.profile["mapping"] == "DD__DD__"
    obj = RNG.integers(0, 256, 8192, dtype=np.uint8).tobytes()
    for erase in [set(), {0}, {3}, {6}, {0, 4}, {2, 5}]:
        roundtrip(codec, obj, erase)
    # single data loss repairs within its group of l+1 chunks
    need = codec.minimum_to_decode([0], list(range(1, 8)))
    assert len(need) == 3
    assert set(need) <= {1, 2, 3}  # group 0 = positions 0..3


def test_lrc_kml_validation():
    with pytest.raises(ECError):
        load_codec({"plugin": "lrc", "k": "4", "m": "2", "l": "5"})
    with pytest.raises(ECError):
        load_codec({"plugin": "lrc", "k": "4", "m": "2"})
    with pytest.raises(ECError):
        load_codec({
            "plugin": "lrc", "k": "2", "m": "1", "l": "3",
            "mapping": "DD_",
        })


def test_lrc_unrecoverable():
    codec = lrc_docs_codec()
    # global layer has k=4: losing 5 chunks incl. all of one group's
    # data beats every layer
    with pytest.raises(ECError):
        codec.minimum_to_decode([2], [0, 4, 5])


def test_lrc_layered_chain_repair():
    """A coding chunk consumed by a later layer (step 1's c at position
    1 feeds step 2) must be reconstructible through multi-step plans."""
    codec = lrc_docs_codec()
    # erase chunk 0 (layer-2 coding) and chunk 2 (its input): repair
    # needs chunk2 first (layer 1), then chunk 0 (layer 2)
    obj = RNG.integers(0, 256, 2048, dtype=np.uint8).tobytes()
    roundtrip(codec, obj, {0, 2})


# ----------------------------------------------------------------- SHEC


def test_shec_window_semantics():
    # single group m=3, c=2, k=6: parity r covers [r*k/m, (r+c)*k/m)
    assert _window(0, 6, 3, 2) == {0, 1, 2, 3}
    assert _window(1, 6, 3, 2) == {2, 3, 4, 5}
    assert _window(2, 6, 3, 2) == {4, 5, 0, 1}


def test_shec_matrix_windows_zeroed():
    mat = _shec_matrix(6, 3, 2, True)
    for r in range(3):
        cover = _window(r, 6, 3, 2)
        for j in range(6):
            if j in cover:
                assert mat[r, j] != 0
            else:
                assert mat[r, j] == 0


@pytest.mark.parametrize("technique", ["single", "multiple"])
def test_shec_roundtrip_single_erasures(technique):
    codec = load_codec({
        "plugin": "shec", "k": "6", "m": "3", "c": "2",
        "technique": technique,
    })
    obj = RNG.integers(0, 256, 6 * 512, dtype=np.uint8).tobytes()
    for i in range(9):
        roundtrip(codec, obj, {i})


def test_shec_roundtrip_c_erasures():
    """c=2 guarantees any 2 losses are recoverable."""
    codec = load_codec({"plugin": "shec", "k": "4", "m": "3", "c": "2"})
    obj = RNG.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    for erase in itertools.combinations(range(7), 2):
        roundtrip(codec, obj, set(erase))


def test_shec_local_repair_reads_fewer_than_k():
    """The point of shingling: one lost data chunk reads < k+1 chunks
    (a covering parity + its window, minus the lost chunk)."""
    codec = load_codec({
        "plugin": "shec", "k": "6", "m": "3", "c": "2",
        "technique": "single",
    })
    need = codec.minimum_to_decode([0], [1, 2, 3, 4, 5, 6, 7, 8])
    # parity 0 covers {0,1,2,3}: read parity 6 + data {1,2,3} = 4 reads
    assert len(need) <= 4
    assert 0 not in need


def test_shec_defaults():
    codec = load_codec({"plugin": "shec"})
    assert (codec.k, codec.m, codec.c) == (4, 3, 2)
    obj = b"shec-default" * 300
    roundtrip(codec, obj, {1})
    roundtrip(codec, obj, {5})
