"""JAX RS encode/decode kernels: bit-exact vs numpy reference."""
import numpy as np
import pytest

from ceph_tpu.ops import gf8, rs


def _rand_chunks(rng, k, chunk_len):
    return rng.integers(0, 256, (k, chunk_len), dtype=np.uint8).astype(np.uint8)


@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 3), (10, 4)])
def test_encode_bit_exact(rng, k, m):
    gen = gf8.vandermonde_rs_matrix(k, m)
    data = _rand_chunks(rng, k, 256)
    want = rs.encode_np(gen, data)
    got = np.asarray(rs.encode(gen, rs.pack_u32(data)))
    assert (rs.unpack_u32(got) == want).all()


def test_encode_batched(rng):
    k, m, batch, chunk = 8, 3, 7, 128
    gen = gf8.vandermonde_rs_matrix(k, m)
    data = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
    got = rs.unpack_u32(np.asarray(rs.encode(gen, rs.pack_u32(data))))
    for b in range(batch):
        assert (got[b] == rs.encode_np(gen, data[b])).all()


@pytest.mark.parametrize("erased", [[0], [7], [8], [10], [0, 10], [3, 8], [9, 10], [0, 1, 2]])
def test_decode_recovers(rng, erased):
    k, m, chunk = 8, 3, 256
    gen = gf8.vandermonde_rs_matrix(k, m)
    data = _rand_chunks(rng, k, chunk)
    parity = rs.encode_np(gen, data)
    allc = np.concatenate([data, parity], axis=0)
    present = [i for i in range(k + m) if i not in erased][:k]
    surviving = allc[sorted(present)]
    rec = rs.decode(gen, k, sorted(present), rs.pack_u32(surviving))
    assert (rs.unpack_u32(np.asarray(rec)) == data).all()


def test_decode_batched_two_missing(rng):
    k, m, batch, chunk = 8, 3, 5, 64
    gen = gf8.vandermonde_rs_matrix(k, m)
    data = rng.integers(0, 256, (batch, k, chunk), dtype=np.uint8)
    parity = np.stack([rs.encode_np(gen, d) for d in data])
    allc = np.concatenate([data, parity], axis=1)
    present = sorted(set(range(k + m)) - {2, 9})[:k]
    rec = rs.decode(gen, k, present, rs.pack_u32(allc[:, present]))
    assert (rs.unpack_u32(np.asarray(rec)) == data).all()


def test_decode_unsorted_present_order(rng):
    # surviving chunks stacked parity-first: decode must honor caller order
    k, m, chunk = 4, 2, 64
    gen = gf8.vandermonde_rs_matrix(k, m)
    data = _rand_chunks(rng, k, chunk)
    parity = rs.encode_np(gen, data)
    allc = np.concatenate([data, parity], axis=0)
    present = [4, 1, 2, 3]
    rec = rs.decode(gen, k, present, rs.pack_u32(allc[present]))
    assert (rs.unpack_u32(np.asarray(rec)) == data).all()


def test_decode_duplicate_present_rejected(rng):
    gen = gf8.vandermonde_rs_matrix(4, 2)
    with pytest.raises(ValueError, match="duplicate"):
        rs.decode(gen, 4, [0, 0, 1, 2], np.zeros((4, 4), np.uint32))


def test_cauchy_roundtrip(rng):
    k, m, chunk = 6, 3, 128
    gen = gf8.cauchy_rs_matrix(k, m)
    data = _rand_chunks(rng, k, chunk)
    parity = rs.unpack_u32(np.asarray(rs.encode(gen, rs.pack_u32(data))))
    assert (parity == rs.encode_np(gen, data)).all()
    allc = np.concatenate([data, parity], axis=0)
    present = [0, 2, 3, 5, 6, 8]
    rec = rs.decode(gen, k, present, rs.pack_u32(allc[present]))
    assert (rs.unpack_u32(np.asarray(rec)) == data).all()
