"""libcephsqlite role: an unmodified SQLite engine on RADOS via the
ctypes-registered VFS (src/libcephsqlite.cc + SimpleRADOSStriper
behavior: striped db file, exclusive-lock single writer, journal as a
second striped file)."""
import sqlite3

import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.sqlite_vfs import CephVFS, ClusterLoopThread

POOL = 1


@pytest.fixture
def vfs():
    bridge = ClusterLoopThread()

    async def mk():
        c = TestCluster(n_osds=3)
        await c.start()
        await c.client.create_pool(
            Pool(id=POOL, name="db", size=2, pg_num=8, crush_rule=0))
        await c.wait_active(20)
        return c

    cluster = bridge.call(mk())
    v = CephVFS(bridge, cluster.client, POOL)
    v.register()
    yield v
    v.unregister()
    bridge.call(cluster.stop())
    bridge.stop()


def connect(v: CephVFS, name: str = "testdb") -> sqlite3.Connection:
    return sqlite3.connect(f"file:{name}?vfs={v.name}", uri=True,
                           timeout=2)


def test_crud_and_durability(vfs):
    db = connect(vfs)
    db.execute("create table kv (k text primary key, v int)")
    with db:
        db.executemany("insert into kv values (?, ?)",
                       [(f"key-{i}", i) for i in range(200)])
    assert db.execute(
        "select count(*), sum(v) from kv").fetchone() == (200, 19900)
    with db:
        db.execute("delete from kv where v % 2 = 0")
    assert db.execute("select count(*) from kv").fetchone() == (100,)
    db.close()

    # a NEW connection sees the committed state (pages read back out
    # of RADOS, not an OS page cache)
    db2 = connect(vfs)
    assert db2.execute(
        "select count(*), max(v) from kv").fetchone() == (100, 199)
    db2.close()


def test_pages_live_in_rados_objects(vfs):
    db = connect(vfs, "objcheck")
    with db:
        db.execute("create table t (x)")
        db.execute("insert into t values (zeroblob(100000))")
    db.close()
    objs = vfs.bridge.call(vfs.client.list_objects(POOL))
    names = {o.decode() if isinstance(o, bytes) else o for o in objs}
    assert any(n.startswith("objcheck.0") for n in names), names
    assert "objcheck.size" in names


def test_rollback_via_striped_journal(vfs):
    db = connect(vfs)
    with db:
        db.execute("create table t (x int)")
        db.execute("insert into t values (1)")
    try:
        with db:
            db.execute("insert into t values (2)")
            db.execute("this is not sql")
    except sqlite3.OperationalError:
        pass
    assert db.execute("select count(*) from t").fetchone() == (1,)
    db.close()


def test_single_writer_lock(vfs):
    db = connect(vfs)
    db.execute("create table t (x)")
    # second writer: the RADOS exclusive lock is held -> cannot open
    with pytest.raises(sqlite3.OperationalError):
        db2 = connect(vfs)
        db2.execute("insert into t values (1)")
    db.close()
    # lock released at close: a new writer proceeds
    db3 = connect(vfs)
    with db3:
        db3.execute("insert into t values (1)")
    assert db3.execute("select count(*) from t").fetchone() == (1,)
    db3.close()


def test_two_databases_coexist(vfs):
    a, b = connect(vfs, "dba"), connect(vfs, "dbb")
    with a:
        a.execute("create table t (x)")
        a.execute("insert into t values ('a')")
    with b:
        b.execute("create table t (x)")
        b.execute("insert into t values ('b')")
    assert a.execute("select x from t").fetchone() == ("a",)
    assert b.execute("select x from t").fetchone() == ("b",)
    a.close()
    b.close()


def test_crashed_holder_lock_expires(vfs):
    """A SIGKILLed lock holder must not wedge the database forever:
    the cls lock carries a duration, so an unrenewed grant expires and
    the next opener proceeds (SimpleRADOSStriper timed-lock role;
    round-5 review finding)."""
    import time as _time

    vfs.lock_duration_s = 0.5
    db = connect(vfs, "crashdb")
    with db:
        db.execute("create table t (x)")
        db.execute("insert into t values (1)")
    # simulate a crash: kill renewal and drop the handle registry so
    # xClose finds nothing to unlock (the lock is left held, exactly
    # as after a SIGKILL); then close the sqlite side so no dangling
    # connection outlives the VFS (its GC would call freed callbacks)
    h = next(iter(vfs._files.values()))
    if h.renew_task is not None:
        h.renew_task.cancel()
    vfs._files.clear()
    db.close()
    # immediately: still held (renewals stopped but not yet expired)
    with pytest.raises(sqlite3.OperationalError):
        connect(vfs, "crashdb").execute("select * from t")
    _time.sleep(0.8)  # > duration: the grant lapses on its own
    db2 = connect(vfs, "crashdb")
    assert db2.execute("select x from t").fetchone() == (1,)
    db2.close()
