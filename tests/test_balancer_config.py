"""Balancer (upmap optimizer, mgr balancer-module role) and the
central config DB (ConfigMonitor / MConfig push role)."""
import asyncio
import os

import pytest

from ceph_tpu.cluster import TestCluster, balancer
from ceph_tpu.placement import crushmap as cm
from ceph_tpu.placement.osdmap import OSDMap, Pool
from ceph_tpu.utils.admin import admin_command


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def _until(pred, timeout=10.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while not pred():
        if asyncio.get_running_loop().time() > deadline:
            raise AssertionError("condition never became true")
        await asyncio.sleep(0.02)


# ----------------------------------------------------------- balancer


def _map_with_pool(n_osds=6, pg_num=64) -> OSDMap:
    crush = cm.build_flat(n_osds)
    crush.add_rule(cm.flat_firstn_rule(0))
    m = OSDMap(crush, n_osds)
    m.pools[1] = Pool(id=1, name="p", size=3, pg_num=pg_num,
                      crush_rule=0)
    return m


def test_compute_moves_improves_spread():
    m = _map_with_pool()
    before = balancer.spread(m, 1)
    moves = balancer.compute_moves(m, 1, max_moves=50)
    if before["spread"] <= 1:
        assert moves == []
        return
    for pgid, pairs in moves:
        m.pg_upmap_items[pgid] = pairs
    after = balancer.spread(m, 1)
    assert after["spread"] < before["spread"]
    # every PG still maps to `size` distinct up OSDs
    for ps in range(m.pools[1].pg_num):
        up, _ = m.pg_to_up_acting_osds((1, ps))
        ups = [o for o in up if o is not None and o >= 0]
        assert len(ups) == len(set(ups)) == 3


def test_compute_moves_respects_failure_domains():
    crush = cm.build_hierarchy(osds_per_host=2, n_hosts=4)
    crush.add_rule(cm.replicated_rule(0))
    m = OSDMap(crush, 8)
    m.pools[1] = Pool(id=1, name="p", size=3, pg_num=64, crush_rule=0)
    parents = balancer._parents(m)
    assert parents is not None
    moves = balancer.compute_moves(m, 1, max_moves=50)
    for pgid, pairs in moves:
        m.pg_upmap_items[pgid] = pairs
    for ps in range(64):
        up, _ = m.pg_to_up_acting_osds((1, ps))
        ups = [o for o in up if o is not None and o >= 0]
        doms = [parents[o] for o in ups]
        assert len(set(doms)) == len(doms), (ps, ups, doms)


def test_balancer_via_mgr_and_data_survives(tmp_path):
    async def t():
        c = TestCluster(n_osds=6)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="p", size=3, pg_num=64, crush_rule=0))
        await c.wait_active(30)
        data = {f"o{i}".encode(): os.urandom(2000) for i in range(12)}
        for k, v in data.items():
            await c.client.write_full(1, k, v)
        sock = str(tmp_path / "mgr.sock")
        await c.mgr.start_admin(sock)
        before = await admin_command(sock, "balancer status", pool=1)
        res = await admin_command(sock, "balancer run", pool=1,
                                  max_moves=50)
        if before["spread"] > 1:
            assert res["moves"], "skewed pool but no moves proposed"
            await c.wait_epoch(c.mon.osdmap.epoch, 10)
            after = await admin_command(sock, "balancer status", pool=1)
            assert after["spread"] < before["spread"]
        await c.wait_active(30)  # PGs re-peer onto the new mapping
        for k, v in data.items():
            assert await c.client.read(1, k) == v
        await c.stop()

    run(t())


# ------------------------------------------------------- central config


def test_config_push_reaches_all_osds(tmp_path):
    async def t():
        c = TestCluster(n_osds=4)
        await c.start()
        sock = str(tmp_path / "mgr.sock")
        await c.mgr.start_admin(sock)
        assert await admin_command(
            sock, "config set", who="osd", key="osd_subop_timeout",
            value="7.5") == "ok"
        await _until(lambda: all(
            o.conf.get("osd_subop_timeout") == 7.5 for o in c.osds))
        # per-instance beats class for that instance only
        await admin_command(sock, "config set", who="osd.2",
                            key="osd_subop_timeout", value="2.0")
        await _until(
            lambda: c.osds[2].conf.get("osd_subop_timeout") == 2.0)
        assert c.osds[0].conf.get("osd_subop_timeout") == 7.5
        # mirror serves config dump
        dump = await admin_command(sock, "config dump")
        assert dump["osd/osd_subop_timeout"] == "7.5"
        # a REVIVED osd gets the DB on subscribe (late joiner)
        await c.kill_osd(1)
        await c.wait_down(1)
        await c.revive_osd(1)
        await _until(
            lambda: c.osds[1].conf.get("osd_subop_timeout") == 7.5)
        await c.stop()

    run(t())


def test_config_survives_mon_failover():
    """The config DB mirrors to peer mons, so a new leader after
    failover still serves it to (re)booting daemons."""
    async def t():
        c = TestCluster(n_osds=3, n_mons=3)
        await c.start()
        await c.wait_quorum(10)
        leader = c.mon.rank
        await c.mon.handle("client.x", __import__(
            "ceph_tpu.cluster.messages", fromlist=["M"]).MConfigSet(
                who="osd", key="osd_subop_timeout", value="9.0"))
        await _until(lambda: all(
            o.conf.get("osd_subop_timeout") == 9.0 for o in c.osds))
        await c.kill_mon(leader)
        await c.wait_quorum(10)
        assert c.mon.config_db[("osd", "osd_subop_timeout")] == "9.0"
        # a rebooting OSD gets the DB from the NEW leader
        await c.kill_osd(0)
        await c.wait_down(0)
        await c.revive_osd(0)
        await _until(
            lambda: c.osds[0].conf.get("osd_subop_timeout") == 9.0)
        await c.stop()

    run(t())


def test_bad_config_value_rejected_quietly():
    async def t():
        c = TestCluster(n_osds=2)
        await c.start()
        await c.mon.handle("client.x", __import__(
            "ceph_tpu.cluster.messages", fromlist=["M"]).MConfigSet(
                who="osd", key="osd_subop_timeout", value="not-a-float"))
        await asyncio.sleep(0.1)
        # daemons keep running with their old value
        assert c.osds[0].conf.get("osd_subop_timeout") > 0
        await c.stop()

    run(t())
