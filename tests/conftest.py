"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §7).

Multi-chip hardware is not available in CI; all sharding/collective tests
run on 8 virtual CPU devices, mirroring how the reference tests cluster
logic without a cluster (MemStore / vstart tiers, SURVEY.md §4). Bench
(`bench.py`) runs separately on the real TPU chip.

This must run before jax is imported anywhere in the test process.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
