"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §7).

Multi-chip hardware is not available in CI; all sharding/collective tests
run on 8 virtual CPU devices, mirroring how the reference tests cluster
logic without a cluster (MemStore / vstart tiers, SURVEY.md §4). Bench
(`bench.py`) runs separately on the real TPU chip.

This must run before jax is imported anywhere in the test process.
"""
import os

# Force, not setdefault: the shell env pre-sets JAX_PLATFORMS=axon (the
# real chip tunnel), which would pin tests to 1 TPU device and slow
# compiles. Tests always use the virtual 8-CPU mesh; bench.py uses the chip.
# The axon PJRT plugin ignores the JAX_PLATFORMS env var, so the config
# update below (which it does respect) is what actually filters it out.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
