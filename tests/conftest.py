"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §7).

Multi-chip hardware is not available in CI; all sharding/collective tests
run on 8 virtual CPU devices, mirroring how the reference tests cluster
logic without a cluster (MemStore / vstart tiers, SURVEY.md §4). Bench
(`bench.py`) runs separately on the real TPU chip.

pin_virtual_cpu must run before the first jax backend init (importing jax
is fine; creating devices is not).
"""
# Force, not setdefault: the shell env pre-sets JAX_PLATFORMS=axon (the
# real chip tunnel), which would pin tests to 1 TPU device and slow
# compiles. Tests always use the virtual 8-CPU mesh; bench.py uses the chip.
from ceph_tpu import parallel

parallel.pin_virtual_cpu(8)

import signal  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'` (ROADMAP): long thrashes and other
    # minute-scale scenarios carry @pytest.mark.slow
    config.addinivalue_line(
        "markers",
        "slow: long-running scenario excluded from tier-1 (-m 'not slow')")


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture(autouse=True)
def _sigpipe_ignored():
    """Keep CPython's SIGPIPE ignore in force for every test.

    A stray signal.signal(SIGPIPE, SIG_DFL) anywhere in the suite (e.g.
    a CLI module imported by a test) would make the NEXT write to a dead
    daemon socket kill the whole pytest process with exit 141, mid-run,
    with no summary — exactly the round-4 full-suite failure. Restore
    the disposition before each test and verify nothing left it reset."""
    prev = signal.getsignal(signal.SIGPIPE)
    signal.signal(signal.SIGPIPE, signal.SIG_IGN)
    yield
    now = signal.getsignal(signal.SIGPIPE)
    signal.signal(signal.SIGPIPE, signal.SIG_IGN)
    assert now is signal.SIG_IGN, (
        f"test left SIGPIPE disposition as {now!r}; writes to dead "
        "sockets would kill the test runner"
    )
    if prev is not signal.SIG_IGN:
        # first test after the offending import: disposition was already
        # broken on entry; it is fixed now, but flag the origin loudly
        import warnings

        warnings.warn("SIGPIPE was not SIG_IGN on test entry")
