"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §7).

Multi-chip hardware is not available in CI; all sharding/collective tests
run on 8 virtual CPU devices, mirroring how the reference tests cluster
logic without a cluster (MemStore / vstart tiers, SURVEY.md §4). Bench
(`bench.py`) runs separately on the real TPU chip.

pin_virtual_cpu must run before the first jax backend init (importing jax
is fine; creating devices is not).
"""
# Force, not setdefault: the shell env pre-sets JAX_PLATFORMS=axon (the
# real chip tunnel), which would pin tests to 1 TPU device and slow
# compiles. Tests always use the virtual 8-CPU mesh; bench.py uses the chip.
from ceph_tpu import parallel

parallel.pin_virtual_cpu(8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
