"""Model-based random-op stress client with OSD thrashing.

The ceph_test_rados role (reference src/test/osd/RadosModel.h +
TestRados.cc, driven under thrashing by qa/tasks/ceph_manager.py
OSDThrasher): a random op stream — writes, partial overwrites, zeros,
truncates, appends, deletes, snapshots, snap reads, xattrs — runs
against a live cluster while OSDs are killed and revived, with a
shadow model tracking the expected state of every object; reads are
verified against the model continuously and after the cluster heals.
"""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2"}


def run(coro, timeout=300):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, timeout))
    finally:
        loop.close()


class Model:
    """Shadow state: object bytes + per-snap frozen copies."""

    def __init__(self):
        self.objects: dict[str, bytearray] = {}
        #: snapid -> {name: bytes} frozen at snap time
        self.snaps: dict[int, dict[str, bytes]] = {}
        self.snap_ids: list[int] = []  # live snaps, ascending

    @property
    def snapc(self):
        if not self.snap_ids:
            return None
        return (self.snap_ids[-1], list(reversed(self.snap_ids)))

    def take_snap(self, snapid: int) -> None:
        self.snaps[snapid] = {n: bytes(d)
                              for n, d in self.objects.items()}
        self.snap_ids.append(snapid)

    def drop_snap(self, snapid: int) -> None:
        self.snaps.pop(snapid, None)
        self.snap_ids.remove(snapid)


class Thrasher:
    """OSDThrasher role (ceph_manager.py:202): kill a random non-mon
    OSD, let the cluster run degraded, revive, wait, repeat."""

    def __init__(self, cluster, rng, min_up: int):
        self.c = cluster
        self.rng = rng
        self.min_up = min_up
        self.down: list[int] = []
        self.kills = 0

    async def maybe_thrash(self) -> None:
        up = [i for i, o in enumerate(self.c.osds) if o is not None]
        if self.down and (len(up) <= self.min_up
                          or self.rng.random() < 0.5):
            victim = self.down.pop(0)
            await self.c.revive_osd(victim)
            await self.c.wait_active(60)
        elif len(up) > self.min_up:
            victim = int(self.rng.choice(up))
            await self.c.kill_osd(victim)
            await self.c.wait_down(victim, 30)
            self.down.append(victim)
            self.kills += 1

    async def heal(self) -> None:
        while self.down:
            await self.c.revive_osd(self.down.pop(0))
        await self.c.wait_active(60)


async def _model_run(pool: Pool, n_osds: int, min_up: int, seed: int,
                     rounds: int, with_snaps: bool) -> None:
    c = TestCluster(n_osds=n_osds)
    await c.start()
    await c.client.create_pool(pool)
    await c.wait_active(20)
    pid = pool.id
    rng = np.random.default_rng(seed)
    model = Model()
    thrasher = Thrasher(c, rng, min_up)
    names = [f"obj{i}" for i in range(8)]

    async def verify(name: str) -> None:
        want = model.objects.get(name)
        if want is None:
            with pytest.raises(KeyError):
                await c.client.read(pid, name)
        else:
            got = await c.client.read(pid, name)
            assert got == bytes(want), (
                f"{name}: got {len(got)}B want {len(want)}B")

    async def verify_snap(snapid: int, name: str) -> None:
        frozen = model.snaps[snapid].get(name)
        if frozen is None:
            with pytest.raises(KeyError):
                await c.client.read(pid, name, snapid=snapid)
        else:
            got = await c.client.read(pid, name, snapid=snapid)
            assert got == frozen, f"{name}@{snapid}"

    for step in range(rounds):
        name = str(rng.choice(names))
        cur = model.objects.get(name)
        ops = ["write_full", "write", "append", "zero", "truncate",
               "delete", "read"]
        if with_snaps:
            ops += ["snap_create", "snap_read", "snap_remove"]
        op = str(rng.choice(ops))
        snapc = model.snapc
        if op == "write_full":
            data = bytes(rng.integers(0, 256, int(rng.integers(1, 40_000)),
                                      dtype=np.uint8))
            await c.client.write_full(pid, name, data, snapc=snapc)
            model.objects[name] = bytearray(data)
        elif op == "write" and cur is not None:
            off = int(rng.integers(0, 50_000))
            data = bytes(rng.integers(0, 256, int(rng.integers(1, 9000)),
                                      dtype=np.uint8))
            await c.client.write(pid, name, off, data, snapc=snapc)
            if len(cur) < off + len(data):
                cur.extend(b"\0" * (off + len(data) - len(cur)))
            cur[off : off + len(data)] = data
        elif op == "append" and cur is not None:
            data = bytes(rng.integers(0, 256, int(rng.integers(1, 5000)),
                                      dtype=np.uint8))
            await c.client.append(pid, name, data, snapc=snapc)
            cur.extend(data)
        elif op == "zero" and cur is not None:
            off = int(rng.integers(0, 40_000))
            ln = int(rng.integers(1, 8000))
            await c.client.zero(pid, name, off, ln, snapc=snapc)
            if len(cur) < off + ln:
                cur.extend(b"\0" * (off + ln - len(cur)))
            cur[off : off + ln] = b"\0" * ln
        elif op == "truncate" and cur is not None:
            size = int(rng.integers(0, 45_000))
            await c.client.truncate(pid, name, size, snapc=snapc)
            if size < len(cur):
                del cur[size:]
            else:
                cur.extend(b"\0" * (size - len(cur)))
        elif op == "delete" and cur is not None:
            await c.client.delete(pid, name, snapc=snapc)
            del model.objects[name]
        elif op == "read":
            await verify(name)
        elif op == "snap_create" and len(model.snap_ids) < 3:
            snapid = await c.client.selfmanaged_snap_create(pid)
            model.take_snap(snapid)
        elif op == "snap_read" and model.snap_ids:
            snapid = int(rng.choice(model.snap_ids))
            await verify_snap(snapid, name)
        elif op == "snap_remove" and model.snap_ids:
            snapid = int(rng.choice(model.snap_ids))
            await c.client.selfmanaged_snap_remove(pid, snapid)
            model.drop_snap(snapid)
        if step % 12 == 11:
            await thrasher.maybe_thrash()

    await thrasher.heal()
    assert thrasher.kills > 0, "the thrasher never thrashed"
    for name in names:
        await verify(name)
    for snapid in model.snap_ids:
        for name in names:
            await verify_snap(snapid, name)
    # scrub every PG of the pool: a model run must end CLEAN
    for ps in range(pool.pg_num):
        pgid = (pid, ps)
        _up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        osd = c.osds[primary]
        for key, pg in osd.pgs.items():
            if (key[0], key[1]) == pgid and pg.is_primary():
                report = await pg.scrub()
                assert report["inconsistent"] == [], (pgid, report)
                break
    await c.stop()


def test_rados_model_replicated_thrash():
    run(_model_run(
        Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0),
        n_osds=5, min_up=3, seed=1234, rounds=120, with_snaps=True))


def test_rados_model_ec_thrash():
    run(_model_run(
        Pool(id=2, name="ec", size=5, min_size=3, pg_num=4, crush_rule=1,
             type="erasure", ec_profile=dict(EC_PROFILE)),
        n_osds=6, min_up=5, seed=77, rounds=100, with_snaps=True))
