"""RGW bucket-notification tests: topics, rule filters, reliable
event queues, pull/ack consumption (the rgw_notify + pubsub suite
role)."""
import asyncio

import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services.rgw import RGWError, RGWLite
from ceph_tpu.services.rgw_notify import (
    TopicQueue,
    create_topic,
    delete_topic,
    get_bucket_notification,
    list_topics,
    put_bucket_notification,
)


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rgw", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    rgw = RGWLite(c.client, 1)
    await rgw.create_bucket("b")
    return c, rgw


def test_topics_and_event_flow():
    async def t():
        c, rgw = await make()
        await create_topic(rgw, "events")
        assert await list_topics(rgw) == ["events"]
        # a rule referencing a missing topic is rejected
        with pytest.raises(RGWError, match="no such topic"):
            await put_bucket_notification(
                rgw, "b", [{"id": "r", "topic": "nope"}])
        await put_bucket_notification(rgw, "b", [
            {"id": "all", "topic": "events",
             "events": ["s3:ObjectCreated:*",
                        "s3:ObjectRemoved:*"]}])
        assert (await get_bucket_notification(rgw, "b"))[0]["id"] \
            == "all"
        await rgw.put_object("b", "k1", b"hello")
        await rgw.delete_object("b", "k1")
        up = await rgw.initiate_multipart("b", "big")
        await rgw.upload_part("b", "big", up, 1, b"x" * 100)
        await rgw.complete_multipart("b", "big", up, [1])
        q = TopicQueue(rgw.client, 1, "events")
        events, marker, _tr = await q.pull()
        names = [e["eventName"] for e in events]
        assert names == ["s3:ObjectCreated:Put",
                         "s3:ObjectRemoved:Delete",
                         "s3:ObjectCreated:CompleteMultipartUpload"]
        assert events[0]["s3"]["object"]["key"] == "k1"
        assert events[0]["s3"]["object"]["size"] == 5
        assert events[2]["s3"]["object"]["eTag"].endswith("-1")
        # ack drops processed history; new events keep flowing
        await q.ack(marker)
        events, marker2, _tr = await q.pull(marker)
        assert events == []
        await rgw.put_object("b", "k2", b"again")
        events, _m, _tr = await q.pull(marker)
        assert [e["eventName"] for e in events] == \
            ["s3:ObjectCreated:Put"]
        await c.stop()

    run(t())


def test_filters_and_versioned_markers():
    async def t():
        c, rgw = await make()
        await create_topic(rgw, "creates")
        await put_bucket_notification(rgw, "b", [
            {"id": "c", "topic": "creates",
             "events": ["s3:ObjectCreated:*"], "prefix": "logs/"}])
        await rgw.put_object("b", "logs/a", b"1")   # matches
        await rgw.put_object("b", "data/a", b"2")   # prefix miss
        await rgw.delete_object("b", "logs/a")      # event-type miss
        q = TopicQueue(rgw.client, 1, "creates")
        events, _m, _tr = await q.pull()
        assert [e["s3"]["object"]["key"] for e in events] == ["logs/a"]
        # versioned bucket: marker creation emits its own event name
        await create_topic(rgw, "rm")
        rgw._notif_cache.clear()
        await put_bucket_notification(rgw, "b", [
            {"id": "rm", "topic": "rm",
             "events": ["s3:ObjectRemoved:*"]}])
        await rgw.put_bucket_versioning("b", "Enabled")
        _e, vid = await rgw.put_object("b", "v", b"x")
        marker_vid = await rgw.delete_object("b", "v")
        await rgw.delete_object("b", "v", version_id=vid)
        qrm = TopicQueue(rgw.client, 1, "rm")
        events, _m, _tr = await qrm.pull()
        assert [(e["eventName"], e["s3"]["object"]["versionId"])
                for e in events] == [
            ("s3:ObjectRemoved:DeleteMarkerCreated", marker_vid),
            ("s3:ObjectRemoved:Delete", vid)]
        # unconfigured buckets stay silent and cheap
        await rgw.create_bucket("quiet")
        await rgw.put_object("quiet", "k", b"x")
        events, _m, _tr = await qrm.pull()
        assert len(events) == 2
        # a topic still referenced by live rules refuses deletion —
        # its queue would keep filling with no consumer
        with pytest.raises(RGWError, match="still referenced"):
            await delete_topic(rgw, "rm")
        await delete_topic(rgw, "creates")  # unreferenced: fine
        assert await list_topics(rgw) == ["rm"]
        await c.stop()

    run(t())


def test_copy_emits_copy_event():
    async def t():
        c, rgw = await make()
        await create_topic(rgw, "t")
        await put_bucket_notification(rgw, "b", [
            {"id": "c", "topic": "t",
             "events": ["s3:ObjectCreated:Copy"]}])
        await rgw.put_object("b", "src", b"data")  # Put: filtered out
        await rgw.copy_object("b", "src", "b", "dst")
        q = TopicQueue(rgw.client, 1, "t")
        events, _m, _tr = await q.pull()
        assert [(e["eventName"], e["s3"]["object"]["key"])
                for e in events] == [("s3:ObjectCreated:Copy", "dst")]
        await c.stop()

    run(t())
