"""Golden-bytes non-regression: every codec must reproduce the pinned
corpus encodings exactly (ceph_erasure_code_non_regression.cc +
ceph-erasure-code-corpus role). A failure here means the wire/disk
format changed — that is NEVER a test to update casually; stored data
depends on it."""
import hashlib
import json
import os

import numpy as np
import pytest

from ceph_tpu.ec import load_codec

CORPUS_PATH = os.path.join(os.path.dirname(__file__), "corpus",
                           "ec_corpus.json")

with open(CORPUS_PATH) as f:
    CORPUS = json.load(f)


def payload(size: int) -> bytes:
    return np.random.default_rng(0xEC0DE + size).integers(
        0, 256, size, dtype=np.uint8
    ).tobytes()


def check_entry(entry: dict, profile: dict) -> None:
    codec = load_codec(profile)
    n = entry["n"]
    assert codec.get_chunk_count() == n
    for size_s, pinned in entry["sizes"].items():
        size = int(size_s)
        assert codec.get_chunk_size(size) == pinned["chunk_size"], (
            f"chunk_size drift at object size {size}"
        )
        encoded = codec.encode(list(range(n)), payload(size))
        got = [
            hashlib.sha256(encoded[i].tobytes()).hexdigest()[:24]
            for i in range(n)
        ]
        assert got == pinned["chunks"], (
            f"ENCODING DRIFT: profile={profile} size={size}"
        )


@pytest.mark.parametrize("key", sorted(CORPUS))
def test_corpus_host(key):
    entry = CORPUS[key]
    check_entry(entry, dict(entry["profile"]))


@pytest.mark.parametrize(
    "key",
    [k for k in sorted(CORPUS)
     if CORPUS[k]["profile"].get("plugin") == "rs_tpu"],
)
def test_corpus_device_backend(key):
    """The batched device kernels must match the host corpus bytes —
    the bit-exactness gate for every kernel change."""
    entry = CORPUS[key]
    profile = dict(entry["profile"])
    profile["backend"] = "device"
    check_entry(entry, profile)
