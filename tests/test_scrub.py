"""Scrub + fault-injection tests (the qa/standalone/scrub and
test-erasure-eio.sh roles): digest batching, corrupt-shard detection and
repair, EIO-resilient reconstruct-on-read."""
import asyncio

import numpy as np
import pytest

from ceph_tpu import native
from ceph_tpu.cluster.scrub import digest_map, pick_authoritative
from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.store import Transaction
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.utils.fault import FaultInjector

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


# ------------------------------------------------------------ units


def test_digest_map_batches_by_size():
    s = MemStore()
    t = Transaction().create_collection("c")
    rng = np.random.default_rng(0)
    blobs = {}
    for i, size in enumerate([100, 100, 100, 256, 0, 256]):
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        oid = b"o%d" % i
        blobs[oid] = data
        t.write("c", oid, 0, data)
    s.apply_transaction(t)
    got = digest_map(s, "c")
    assert set(got) == set(blobs)
    for oid, data in blobs.items():
        want = native.crc32c(np.frombuffer(data, np.uint8)) if data \
            else native.crc32c(None)
        assert got[oid] == (len(data), want), oid


def test_pick_authoritative():
    v1, v2 = (1, 1), (1, 2)
    # newest version wins regardless of count
    key, auth = pick_authoritative({
        (0, -1): (v2, (10, 0xAA)),
        (1, -1): (v1, (10, 0xBB)),
        (2, -1): (v1, (10, 0xBB)),
    })
    assert key == (0, -1) and auth == (v2, (10, 0xAA))
    # same version: majority digest wins
    key, auth = pick_authoritative({
        (0, -1): (v2, (10, 0xAA)),
        (1, -1): (v2, (10, 0xBB)),
        (2, -1): (v2, (10, 0xBB)),
    })
    assert key == (1, -1) and auth == (v2, (10, 0xBB))


def test_fault_injector():
    f = FaultInjector()
    assert not f.hit("x")
    f.arm("x", count=2, oid=b"a")
    assert f.hit("x", oid=b"a")
    assert not f.hit("x", oid=b"b")  # filter mismatch
    assert f.hit("x", oid=b"a")
    assert not f.hit("x", oid=b"a")  # budget exhausted
    assert f.fired("x") == 2
    f.arm("y")
    for _ in range(5):
        assert f.hit("y")
    f.clear()
    assert not f.hit("y")


# ---------------------------------------------------------- clusters


async def make_rep_cluster(n=4):
    c = TestCluster(n_osds=n)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c


async def make_ec_cluster(n=5):
    c = TestCluster(n_osds=n)
    await c.start()
    await c.client.create_pool(
        Pool(id=2, name="ec", size=5, min_size=3, pg_num=8, crush_rule=1,
             type="erasure", ec_profile=dict(EC_PROFILE))
    )
    await c.wait_active(20)
    return c


def corrupt_object(store, cid: bytes | str, oid: bytes, flip: int = 0):
    """Flip one bit in an object's data behind the store's back (the
    bit-rot simulation of test-erasure-eio.sh corrupt verbs)."""
    obj = store.colls[cid].objects[oid]
    obj.data[flip] ^= 0x01


def test_scrub_clean_replicated():
    async def t():
        c = await make_rep_cluster()
        await c.client.write_full(1, "a", b"A" * 5000)
        await c.client.write_full(1, "b", b"B" * 100)
        pgid = c.client.osdmap.object_to_pg(1, b"a")
        report = await c.scrub_pg(pgid)
        assert report["inconsistent"] == []
        assert report["clean"] >= 1
        await c.stop()

    run(t())


def test_scrub_detects_and_repairs_replica_bitrot():
    async def t():
        c = await make_rep_cluster()
        payload = b"precious" * 1000
        await c.client.write_full(1, "obj", payload)
        pgid = c.client.osdmap.object_to_pg(1, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        cid = f"{pgid[0]}.{pgid[1]}"
        corrupt_object(c.stores[victim], cid, b"obj", flip=17)
        report = await c.scrub_pg(pgid)
        assert b"obj" in report["inconsistent"]
        assert (victim, -1) in report["repaired"]
        # re-scrub: clean now, and the replica's bytes match
        report2 = await c.scrub_pg(pgid)
        assert report2["inconsistent"] == []
        assert bytes(
            c.stores[victim].colls[cid].objects[b"obj"].data
        ) == payload
        await c.stop()

    run(t())


def test_scrub_detects_and_repairs_ec_shard_bitrot():
    async def t():
        c = await make_ec_cluster()
        payload = bytes(range(256)) * 200
        await c.client.write_full(2, "obj", payload)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        shard = up.index(victim)
        cid = f"{pgid[0]}.{pgid[1]}s{shard}"
        corrupt_object(c.stores[victim], cid, b"obj", flip=3)
        report = await c.scrub_pg(pgid)
        assert b"obj" in report["inconsistent"]
        assert (victim, shard) in report["repaired"]
        report2 = await c.scrub_pg(pgid)
        assert report2["inconsistent"] == []
        # the repaired shard decodes with the rest
        assert await c.client.read(2, "obj") == payload
        await c.stop()

    run(t())


def test_ec_read_survives_injected_eio():
    """test-erasure-eio.sh role: EIO on a shard sub-read must not fail
    the client read — the primary reconstructs from survivors."""
    async def t():
        c = await make_ec_cluster()
        payload = b"resilient" * 3000
        await c.client.write_full(2, "obj", payload)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        c.osds[victim].fault.arm("ec_sub_read", oid=b"obj")
        got = await c.client.read(2, "obj")
        assert got == payload
        assert c.osds[victim].fault.fired("ec_sub_read") >= 0
        await c.stop()

    run(t())


def test_ec_read_survives_primary_local_corruption():
    """The primary's own shard fails its hinfo check: the read must
    reconstruct around it instead of erroring."""
    async def t():
        c = await make_ec_cluster()
        payload = b"local-rot" * 2500
        await c.client.write_full(2, "obj", payload)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        shard = up.index(primary)
        cid = f"{pgid[0]}.{pgid[1]}s{shard}"
        corrupt_object(c.stores[primary], cid, b"obj", flip=0)
        assert await c.client.read(2, "obj") == payload
        await c.stop()

    run(t())


def test_ec_read_fails_only_beyond_m_erasures():
    async def t():
        c = await make_ec_cluster()
        payload = b"limit" * 4000
        await c.client.write_full(2, "obj", payload)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        others = [o for o in up if o != primary]
        # m=2: two injected EIOs still decode…
        for v in others[:2]:
            c.osds[v].fault.arm("ec_sub_read", oid=b"obj")
        assert await c.client.read(2, "obj") == payload
        # …a third makes the object unreadable (IOError -> EAGAIN-> give
        # up) but must not wedge the PG
        c.osds[others[2]].fault.arm("ec_sub_read", oid=b"obj")
        with pytest.raises(Exception):
            await asyncio.wait_for(c.client.read(2, "obj"), 30)
        for o in others:
            c.osds[o].fault.clear()
        assert await c.client.read(2, "obj") == payload
        await c.stop()

    run(t())
