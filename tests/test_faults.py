"""Fault plane + thrasher: deterministic injection, read-path
version/CRC hardening, fail-closed batching, and thrash convergence.

The robustness tier of ISSUE 4: the messenger policy (drop/delay/dup/
partition), the store fault sites (EIO/bitrot/torn writes), the ATTR_V
stale-shard exclusion (the ROADMAP wrong-bytes gap), osd_ec_verify_on_
read + read-triggered repair, the ECBatcher's per-op failure isolation,
and the seeded Thrasher demanding active+clean / scrub-clean / oracle-
byte-equal convergence. The 60 s acceptance thrash is @slow; a short
seeded thrash stays in tier-1.
"""
import asyncio
import random

import numpy as np
import pytest

from ceph_tpu.cluster import TestCluster
from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.faults import (FaultPlane, NetFaultPolicy,
                                     Thrasher, build_schedule, flip_bit)
from ceph_tpu.cluster.pg import ATTR_V, PG, UNFOUND_GRACE
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.store import transaction as tx

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2", "backend": "device"}

#: repair-economics codec arms: the same seeded thrash (bitrot on
#: reads + flaps) exercises each codec family's CRC verify-on-read +
#: async repair path through the batched decode pipeline
THRASH_PROFILES = {
    "rs": EC_PROFILE,
    "clay": {"plugin": "clay", "k": "3", "m": "2",
             "backend": "device", "stripe_unit": "4096"},
    "blaum_roth": {"plugin": "bitmatrix", "technique": "blaum_roth",
                   "k": "3", "m": "2", "backend": "device",
                   "stripe_unit": "4096"},
}


def run(coro, timeout=180):
    asyncio.run(asyncio.wait_for(coro, timeout))


async def make_ec_cluster(n=5, seed=0, pg_num=8, profile=None):
    c = TestCluster(n_osds=n, fault_seed=seed)
    await c.start()
    await c.client.create_pool(
        Pool(id=2, name="ec", size=5, min_size=3, pg_num=pg_num,
             crush_rule=1, type="erasure",
             ec_profile=dict(profile or EC_PROFILE))
    )
    await c.wait_active(20)
    return c


# ------------------------------------------------------ determinism


def test_net_policy_same_seed_same_decisions():
    """The replay contract at the policy level: two policies with the
    same seed make the identical drop/dup/delay sequence for the same
    call sequence."""
    def decide(seed):
        p = NetFaultPolicy(rng=random.Random(seed))
        p.set_link("client.0", "*", drop=0.3, dup=0.3, delay=0.002,
                   jitter=0.004, reorder=0.2)
        return [p.plan("client.0", f"osd.{i % 3}") for i in range(64)]

    a, b = decide(11), decide(11)
    assert a == b
    assert decide(12) != a  # and the seed actually matters
    # mix sanity: some drops, some dups, some delays
    assert any(x is None for x in a)
    assert any(x is not None and len(x) == 2 for x in a)
    assert any(x is not None and x[0] > 0 for x in a)


def test_schedule_deterministic_and_bounded():
    s1 = build_schedule(42, 60.0, 5, max_unavail=2)
    s2 = build_schedule(42, 60.0, 5, max_unavail=2)
    assert s1 == s2 and len(s1) > 10
    assert build_schedule(43, 60.0, 5, max_unavail=2) != s1
    # replay the schedule: never more than max_unavail OSDs down/cut
    dead, cut = set(), set()
    for ev in s1:
        if ev.kind == "kill":
            assert ev.target not in dead
            dead.add(ev.target)
        elif ev.kind == "revive":
            dead.discard(ev.target)
        elif ev.kind == "partition":
            assert not cut
            cut = {ev.target}
        elif ev.kind == "heal":
            cut = set()
        assert len(dead) + len(cut - dead) <= 2


def test_partition_blocks_and_heals():
    p = NetFaultPolicy()
    p.partition({"osd.3"}, {"*"})
    assert p.plan("osd.3", "mon") is None
    assert p.plan("client.0", "osd.3") is None
    assert p.plan("client.0", "osd.1") == [0.0]
    assert p.plan("osd.1", "osd.2") == [0.0]
    p.heal()
    assert p.plan("osd.3", "mon") == [0.0]


def test_blackhole_compat_view():
    """LocalBus.blackholes is now a view over the policy — the
    historical test verb keeps working verbatim."""
    c = TestCluster(n_osds=3)
    c.bus.blackholes.add("osd.1")
    assert c.faults.net.plan("osd.0", "osd.1") is None
    c.bus.blackholes.discard("osd.1")
    assert c.faults.net.plan("osd.0", "osd.1") == [0.0]


# --------------------------------------------- cluster-level faults


def test_partition_heal_cluster_converges():
    """Isolate a PG's primary from everyone mid-workload: the mon
    marks it down, the interval moves on, ops complete; heal + revive
    and the cluster returns to clean with byte-exact reads."""
    async def t():
        c = await make_ec_cluster(seed=3)
        c.client.op_timeout = 60.0
        data = b"partition-me" * 512
        await c.client.write_full(2, "obj", data)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        _, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        c.faults.net.partition({f"osd.{primary}"}, {"*"})
        await c.wait_down(primary, 20)
        data2 = b"post-partition" * 500
        await c.client.write_full(2, "obj", data2)  # re-peered interval
        assert await c.client.read(2, "obj") == data2
        c.faults.net.heal()
        await c.wait_active(40)
        assert await c.client.read(2, "obj") == data2
        await c.stop()

    run(t())


def test_duplicate_delivery_idempotent():
    """Duplicate EVERY client->OSD message: the PG's reqid dedup must
    keep non-idempotent verbs exactly-once."""
    async def t():
        c = TestCluster(n_osds=4, fault_seed=1)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0))
        await c.wait_active(20)
        c.faults.net.set_link("client.0", "*", dup=1.0)
        await c.client.write_full(1, "obj", b"base-")
        for i in range(6):
            await c.client.append(1, "obj", b"x%d" % i)
        await c.bus.drain()
        got = await c.client.read(1, "obj")
        assert got == b"base-" + b"".join(b"x%d" % i for i in range(6))
        assert c.faults.net.counters.get("dup", 0) >= 7
        await c.stop()

    run(t())


def test_injected_eio_excludes_shard_and_read_succeeds():
    """The original fault sites still compose with the plane: injected
    sub-read EIO on one member leaves the read bit-exact (reconstructed
    from survivors) and shows up in faults_injected_*."""
    async def t():
        c = await make_ec_cluster(seed=5)
        data = np.random.default_rng(9).integers(
            0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", data)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        c.osds[victim].fault.arm("ec_sub_read", oid=b"obj")
        assert await c.client.read(2, "obj") == data
        assert c.osds[victim].fault.fired("ec_sub_read") >= 1
        assert c.faults.injected().get("ec_sub_read", 0) >= 1
        d = c.osds[victim].perf.dump()
        assert d.get("faults_injected_ec_sub_read", 0) >= 1
        await c.stop()

    run(t())


# ------------------------------------------- stale-shard regression


def _doctor_stale(store, cid, oid, saved):
    """Reinstall a saved (data, attrs) shard state — the on-disk shape
    of a revived stale member whose recovery was missed."""
    data, attrs = saved
    t = tx.Transaction()
    t.truncate(cid, oid, 0)
    t.write(cid, oid, 0, data)
    t.rmattrs(cid, oid)
    t.setattrs(cid, oid, dict(attrs))
    store.queue_transaction(t)


def test_stale_shard_read_version_crosscheck():
    """THE ROADMAP wrong-bytes gap, reproduced deterministically: two
    data shards carry a self-consistent STALE generation (valid against
    their own stale hinfo). On the seed read path (version check off)
    the read mixes generations and returns wrong bytes; with the
    ATTR_V cross-check the laggards are excluded like hinfo failures
    and the read decodes correct bytes from the surviving quorum."""
    async def t():
        c = await make_ec_cluster(seed=2)
        rng = np.random.default_rng(17)
        v1 = rng.integers(0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v1)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        # two non-primary DATA shards (positions < k): the ones a
        # default fetch plan actually reads
        victims = [(s, o) for s, o in enumerate(up[:3]) if o != primary]
        assert len(victims) >= 2
        victims = victims[:2]
        saved = {}
        for s, o in victims:
            cid = f"{pgid[0]}.{pgid[1]}s{s}"
            saved[s] = (bytes(c.stores[o].read(cid, b"obj")),
                        dict(c.stores[o].getattrs(cid, b"obj")))
        # shrinking rewrite, all members healthy
        v2 = rng.integers(0, 256, 3 * 4096, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v2)
        # re-plant the stale generation on the two victims
        for s, o in victims:
            cid = f"{pgid[0]}.{pgid[1]}s{s}"
            _doctor_stale(c.stores[o], cid, b"obj", saved[s])

        # seed read path: trusts per-shard hinfo only -> mixes stale
        # and new cells -> wrong bytes (or a reconstruct error)
        PG._ec_version_check = False
        try:
            try:
                got = await c.client.read(2, "obj")
                assert got != v2, "seed read path should serve rot here"
            except (IOError, KeyError):
                pass  # "cannot reconstruct" is the other seed symptom
        finally:
            PG._ec_version_check = True

        # hardened path: version-lagging shards excluded, bytes exact
        assert await c.client.read(2, "obj") == v2
        prim = c.osds[primary]
        assert prim.perf.dump().get("ec_read_stale_shard", 0) >= 1
        await c.stop()

    run(t())


def test_stale_primary_size_ranged_read_probes():
    """The primary itself can be the revived stale shard: a ranged read
    planned past its stale (smaller) ATTR_SIZE must not short-circuit
    to empty — it probes a cell, learns the authoritative size from the
    fresh quorum, and re-plans."""
    async def t():
        c = await make_ec_cluster(seed=15)
        rng = np.random.default_rng(77)
        v1 = rng.integers(0, 256, 3 * 4096, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v1)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        ppos = up.index(primary)
        cid = f"{pgid[0]}.{pgid[1]}s{ppos}"
        saved = (bytes(c.stores[primary].read(cid, b"obj")),
                 dict(c.stores[primary].getattrs(cid, b"obj")))
        v2 = rng.integers(0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v2)  # GREW the object
        _doctor_stale(c.stores[primary], cid, b"obj", saved)
        # offset beyond the stale size, inside the real object
        off = len(v1) + 512
        got = await c.client.read(2, "obj", offset=off, length=1000)
        assert got == v2[off:off + 1000]
        assert await c.client.read(2, "obj") == v2
        await c.stop()

    run(t())


def test_past_eof_probe_cached_on_healthy_path():
    """The past-EOF quorum probe runs ONCE per (oid, local version):
    after a probe confirms the primary's size attr against the quorum,
    later past-EOF reads short-circuit locally — proven by cutting the
    primary off from every other OSD and reading past EOF again."""
    async def t():
        c = await make_ec_cluster(seed=17)
        data = b"z" * (3 * 4096)
        await c.client.write_full(2, "obj", data)
        # first past-EOF read: probes the quorum, caches the verdict
        assert await c.client.read(2, "obj", offset=len(data) + 100,
                                   length=50) == b""
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        _, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        # cut the primary off from all OTHER OSDs (client + mon still
        # reach it, so the op arrives and the osdmap holds still): a
        # re-probe would stall on dead sub-reads — the cache must not
        others = {f"osd.{o}" for o in range(5) if o != primary}
        c.faults.net.partition({f"osd.{primary}"}, others)
        got = await asyncio.wait_for(
            c.client.read(2, "obj", offset=len(data) + 100, length=50),
            timeout=5)
        assert got == b""
        c.faults.net.heal()
        await c.stop()

    run(t())


def test_interrupted_fanout_falls_back_to_decodable_generation():
    """A write fan-out that died mid-flight leaves a MINORITY of shards
    one generation ahead (< k members — never ack-able). The version
    cross-check must not brick the read: it falls back to the newest
    generation with >= k members and serves IT consistently (never a
    mix, never 'cannot reconstruct')."""
    async def t():
        c = await make_ec_cluster(seed=14)
        rng = np.random.default_rng(55)
        v1 = rng.integers(0, 256, 3 * 4096, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v1)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, _primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        # snapshot gen-N state of a MAJORITY (3 shards)
        saved = {}
        for s in range(3):
            cid = f"{pgid[0]}.{pgid[1]}s{s}"
            saved[s] = (bytes(c.stores[up[s]].read(cid, b"obj")),
                        dict(c.stores[up[s]].getattrs(cid, b"obj")))
        v2 = rng.integers(0, 256, 3 * 4096, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v2)
        # re-plant gen N on the majority: now only 2 shards carry the
        # newer generation — exactly the dead-mid-fanout shape
        for s in range(3):
            cid = f"{pgid[0]}.{pgid[1]}s{s}"
            _doctor_stale(c.stores[up[s]], cid, b"obj", saved[s])
        got = await c.client.read(2, "obj")
        assert got == v1, "fallback must serve the decodable gen whole"
        await c.stop()

    run(t())


def test_interrupted_shrinking_fanout_refetches_wider():
    """An interrupted SHRINKING fan-out: the < k ahead generation is
    smaller than the decodable gen-N fallback, so the read is planned
    on the small size, version-demotes the gen-N majority, falls back
    to it, learns the larger authoritative size, and must refetch
    WIDER — the demoted shards must rejoin that replan (leaving them
    in the failed set would strand the only decodable generation and
    brick the read with 'cannot reconstruct')."""
    async def t():
        c = await make_ec_cluster(seed=16)
        rng = np.random.default_rng(91)
        # gen N: two stripes; gen N+1 (interrupted): one stripe
        v1 = rng.integers(0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v1)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        ppos = up.index(primary)
        # keep the primary's shard AND one shard from the other class
        # (data if the primary holds parity, parity otherwise) on the
        # ahead generation, so the first fetch plan sees a version mix
        other = 0 if ppos >= 3 else 3
        doctored = [s for s in range(5) if s not in (ppos, other)][:3]
        saved = {}
        for s in doctored:
            cid = f"{pgid[0]}.{pgid[1]}s{s}"
            saved[s] = (bytes(c.stores[up[s]].read(cid, b"obj")),
                        dict(c.stores[up[s]].getattrs(cid, b"obj")))
        v2 = rng.integers(0, 256, 3 * 4096, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v2)  # shrinks the object
        for s in doctored:
            cid = f"{pgid[0]}.{pgid[1]}s{s}"
            _doctor_stale(c.stores[up[s]], cid, b"obj", saved[s])
        got = await c.client.read(2, "obj")
        assert got == v1, "wider replan must serve gen N byte-exact"
        await c.stop()

    run(t())


def test_kill_two_degraded_write_revive_both():
    """The integration shape of the same gap (ROADMAP open item): kill
    TWO members of a k=3,m=2 PG, do a shrinking degraded write, revive
    both — every subsequent read must return the new bytes, including
    reads forced through the revived shards."""
    async def t():
        c = await make_ec_cluster(seed=4)
        rng = np.random.default_rng(21)
        v1 = rng.integers(0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v1)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victims = [o for o in up if o != primary][:2]
        for v in victims:
            await c.kill_osd(v)
            await c.wait_down(v, 20)
        v2 = rng.integers(0, 256, 3 * 4096, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", v2)  # k shards exactly
        for v in victims:
            await c.revive_osd(v)
        await c.wait_active(40)
        assert await c.client.read(2, "obj") == v2
        # force the revived shards into the decode set: kill two OTHERS
        up2, primary2 = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        others = [o for o in up2 if o not in victims][:2]
        for o in others:
            await c.kill_osd(o)
            await c.wait_down(o, 20)
        assert await c.client.read(2, "obj") == v2
        await c.stop()

    run(t())


def test_converged_head_never_fabricates_ack():
    """Acked-write-loss regression (thrash-found): a write whose cells
    reached < k shards bounces; peering then skips it as unfound and
    CONVERGES every member's log head over the gap. Heads now claim a
    generation no quorum can decode — and after a primary flap wipes
    the in-memory phantom blacklist, the seed's reply-cache rebuild
    read those converged heads as content-coverage and fabricated an
    OK for the still-resending client: the write "succeeded" yet reads
    serve the OLD generation forever. The persistent missing-set must
    keep the gap on record across the flap, so the resend re-executes
    for real and the new bytes land on all shards."""
    async def t():
        c = await make_ec_cluster(seed=11)
        c.client.op_timeout = 120.0
        rng = np.random.default_rng(77)
        p1 = rng.integers(0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", p1)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        others = [o for o in up if o != primary]
        cut, dead = others[0], others[1:3]
        # cut one member at the wire (still "up" in the map), kill two:
        # the gen-2 fanout applies on at most primary + one peer (< k),
        # gathers no full ack, and bounces EAGAIN to the client
        c.faults.net.set_link(f"osd.{cut}", "*", drop=1.0)
        c.faults.net.set_link("*", f"osd.{cut}", drop=1.0)
        for o in dead:
            await c.kill_osd(o)
        p2 = rng.integers(0, 256, 3 * 4096, dtype=np.uint8).tobytes()
        wtask = asyncio.create_task(c.client.write_full(2, "obj", p2))
        await asyncio.sleep(2.0)
        assert not wtask.done()  # still bouncing: no quorum for gen-2
        # silence the client so its resend cannot land before the flap
        c.faults.net.set_link("client.0", "*", drop=1.0)
        c.faults.net.set_link("*", "client.0", drop=1.0)
        # heal the member cut and revive the dead: peering pushes the
        # orphan gen-2 (2 members < k), fails, waits out UNFOUND_GRACE,
        # then converges every head over the recorded gap
        c.faults.net.clear_link(f"osd.{cut}", "*")
        c.faults.net.clear_link("*", f"osd.{cut}")
        for o in dead:
            await c.revive_osd(o)
        # generous: peering must wait out UNFOUND_GRACE retry rounds
        # before it converges, and full-suite load stretches each round
        await c.wait_active(150)
        await asyncio.sleep(UNFOUND_GRACE + 4.0)
        # flap the primary: its in-memory phantom blacklist dies; only
        # the PERSISTENT missing set still marks the gap
        await c.kill_osd(primary)
        await c.wait_down(primary, 20)
        await c.revive_osd(primary)
        await c.wait_active(150)
        # un-silence the client: the pending resend must RE-EXECUTE
        # (not be acked from a fabricated cache entry) and land gen-2
        # on every live shard
        c.faults.net.clear_link("client.0", "*")
        c.faults.net.clear_link("*", "client.0")
        await asyncio.wait_for(wtask, 90)
        assert await c.client.read(2, "obj") == p2
        report = await c.scrub_pg(pgid)
        report = await c.scrub_pg(pgid)
        assert report["inconsistent"] == [], report
        assert await c.client.read(2, "obj") == p2
        await c.stop()

    run(t(), timeout=600)


# -------------------------------------- verify-on-read + bitrot


def test_bitrot_caught_counted_and_repaired():
    """osd_ec_verify_on_read (default on): a flipped bit fails hinfo,
    the shard is excluded (read still byte-exact), ec_read_crc_err
    counts it, and a read-triggered repair reinstalls the shard so a
    later scrub finds nothing."""
    async def t():
        c = await make_ec_cluster(seed=6)
        data = np.random.default_rng(33).integers(
            0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", data)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        c.osds[victim].fault.arm("ec_read_bitflip", count=1, oid=b"obj")
        assert await c.client.read(2, "obj") == data
        crc = sum(o.perf.dump().get("ec_read_crc_err", 0)
                  for o in c.osds if o is not None)
        assert crc >= 1

        async def repaired():
            while not any(o.perf.dump().get("ec_read_repairs", 0)
                          for o in c.osds if o is not None):
                await asyncio.sleep(0.02)
        await asyncio.wait_for(repaired(), 20)
        report = await c.scrub_pg(pgid)
        assert report["inconsistent"] == [], report
        await c.stop()

    run(t())


def test_verify_on_read_off_serves_rot():
    """The knob's contrapositive: with osd_ec_verify_on_read=false a
    flipped bit sails through the normal read path — which is exactly
    why the verification defaults on."""
    async def t():
        c = TestCluster(n_osds=5, fault_seed=8,
                        osd_conf={"osd_ec_verify_on_read": False})
        await c.start()
        await c.client.create_pool(
            Pool(id=2, name="ec", size=5, min_size=3, pg_num=8,
                 crush_rule=1, type="erasure",
                 ec_profile=dict(EC_PROFILE)))
        await c.wait_active(20)
        data = np.random.default_rng(3).integers(
            0, 256, 3 * 4096, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", data)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        # rot a DATA shard (position < k) so the flip lands in the
        # returned logical bytes, not a parity cell
        s, o = next((s, o) for s, o in enumerate(up[:3])
                    if o != primary)
        c.osds[o].fault.arm("ec_read_bitflip", count=1, oid=b"obj")
        got = await c.client.read(2, "obj")
        assert got != data and len(got) == len(data)
        await c.stop()

    run(t())


def test_torn_write_detected_by_scrub():
    """A torn shard write (prefix of the transaction persisted) leaves
    the shard divergent; scrub detects and repairs it, and reads stay
    correct throughout (the write itself still all-acked because the
    tear is on-disk state, not the ack path)."""
    async def t():
        c = await make_ec_cluster(seed=9)
        data = np.random.default_rng(41).integers(
            0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "seed-obj", data)  # PG exists now
        pgid = c.client.osdmap.object_to_pg(2, b"torn")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victim = next(o for o in up if o != primary)
        c.osds[victim].fault.arm("torn_write", count=1, oid=b"torn")
        await c.client.write_full(2, "torn", data)
        assert await c.client.read(2, "torn") == data
        report = await c.scrub_pg(pgid)
        if c.osds[victim].fault.fired("torn_write"):
            assert b"torn" in report["inconsistent"], report
        report2 = await c.scrub_pg(pgid)
        assert report2["inconsistent"] == [], report2
        assert await c.client.read(2, "torn") == data
        await c.stop()

    run(t())


# ----------------------------------------- batcher fail-closed


def test_ec_batcher_fails_closed_per_op():
    """An injected dispatch error fails ONLY the op whose stripes still
    fail alone: batch-mates recover via isolation, the queue keeps
    flowing, and the failure counters split by cause."""
    from ceph_tpu.cluster.ecbatch import ECBatcher
    from ceph_tpu.ec import load_codec
    from ceph_tpu.utils.fault import FaultInjector
    from ceph_tpu.utils.perf import PerfCounters

    codec = load_codec({"plugin": "rs_tpu", "k": "3", "m": "2",
                        "backend": "host"})
    perf = PerfCounters("t")
    ECBatcher.declare_counters(perf)
    fault = FaultInjector()
    fault.arm("ec_batch", count=2)  # batch dispatch + first retry

    def cells(seed):
        return np.random.default_rng(seed).integers(
            0, 256, (1, 3, 256), dtype=np.uint8)

    async def t():
        b = ECBatcher(perf, fault=fault)
        waits = [asyncio.ensure_future(b.encode_cells(codec, cells(i)))
                 for i in range(3)]
        results = await asyncio.gather(*waits, return_exceptions=True)
        failures = [r for r in results if isinstance(r, RuntimeError)]
        ok = [r for r in results if not isinstance(r, BaseException)]
        assert len(failures) == 1 and len(ok) == 2
        for parity, _crcs in ok:
            assert parity.shape == (1, 2, 256)
        # the bucket is not wedged: later work flows
        parity, _ = await b.encode_cells(codec, cells(99))
        assert parity.shape == (1, 2, 256)

    run(t())
    d = perf.dump()
    assert d["ec_batch_failures"] == 1
    assert d["ec_batch_failures_injected"] == 1
    assert d["ec_batch_failures_dispatch"] == 0
    assert d["ec_batch_isolated"] == 2


def test_ec_batcher_failure_release_is_single_shot():
    """The failure path must release the bucket exactly once: a fresh
    batch that starts while the failed batch's isolation retries are
    still grinding owns the in-flight marker — a second (finally-path)
    discard after the retries would let a third concurrent dispatch
    launch for the same bucket and break the double-buffer invariant."""
    from ceph_tpu.cluster.ecbatch import ECBatcher
    from ceph_tpu.ec import load_codec
    from ceph_tpu.utils.fault import InjectedError
    from ceph_tpu.utils.perf import PerfCounters

    codec = load_codec({"plugin": "rs_tpu", "k": "3", "m": "2",
                        "backend": "host"})
    perf = PerfCounters("t")
    ECBatcher.declare_counters(perf)

    def cells(seed):
        return np.random.default_rng(seed).integers(
            0, 256, (1, 3, 256), dtype=np.uint8)

    async def t():
        b = ECBatcher(perf)
        seen = {}
        fail_gate = asyncio.Event()   # holds B1's failure path open
        b2_entered = asyncio.Event()
        b2_gate = asyncio.Event()     # holds B2 mid-dispatch
        state = {"calls": 0}
        real_disp = b._dispatch_once
        real_fail = b._fail_closed

        async def disp(loop, key, codec_, cells_):
            seen.setdefault("key", key)
            state["calls"] += 1
            if state["calls"] == 1:
                raise InjectedError("injected batch failure")
            if state["calls"] == 2:
                b2_entered.set()
                await b2_gate.wait()
            return await real_disp(loop, key, codec_, cells_)

        async def held_fail(loop, key, items, exc):
            await fail_gate.wait()
            await real_fail(loop, key, items, exc)

        b._dispatch_once = disp
        b._fail_closed = held_fail

        fut1 = asyncio.ensure_future(b.encode_cells(codec, cells(1)))
        while state["calls"] < 1:       # B1 dispatched and failed
            await asyncio.sleep(0.001)
        await asyncio.sleep(0.01)       # except path released + parked
        fut2 = asyncio.ensure_future(b.encode_cells(codec, cells(2)))
        await asyncio.wait_for(b2_entered.wait(), 5)
        key = seen["key"]
        assert key in b._inflight       # B2 owns the bucket
        fail_gate.set()                 # B1's _run finishes now
        await asyncio.sleep(0.05)
        assert key in b._inflight, \
            "failure path released the bucket twice"
        b2_gate.set()
        parity, _ = await asyncio.wait_for(fut2, 10)
        assert parity.shape == (1, 2, 256)
        with pytest.raises(RuntimeError):
            await fut1

    run(t())
    d = perf.dump()
    assert d["ec_batch_failures"] == 1
    assert d["ec_batch_failures_injected"] == 1


def test_injected_batch_failure_only_fails_affected_op_end_to_end():
    """Cluster shape of fail-closed: arm one injected dispatch failure
    mid-workload — the affected op EAGAINs, the client's bounded-
    backoff retry lands it, no op is lost and nothing wedges."""
    async def t():
        c = await make_ec_cluster(seed=10)
        pgid = c.client.osdmap.object_to_pg(2, b"o0")
        _, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        c.osds[primary].fault.arm("ec_batch", count=1, kind="enc")
        datas = {f"o{i}": bytes([i + 1]) * 8192 for i in range(6)}
        await asyncio.gather(*(c.client.write_full(2, n, d)
                               for n, d in datas.items()))
        for n, d in datas.items():
            assert await c.client.read(2, n) == d
        assert c.client.op_retries >= 0  # counter exists and is sane
        await c.stop()

    run(t())


# ----------------------------------------------- client backoff


def test_client_backoff_bounded_exponential_with_jitter():
    from ceph_tpu.cluster.client import RadosClient

    client = RadosClient(bus=None)
    base = client.conf["client_backoff_base"]
    cap = client.conf["client_backoff_max"]
    for attempt in range(24):
        raw = min(cap, base * (1 << min(attempt, 16)))
        for _ in range(8):
            d = client._backoff(attempt)
            assert raw * 0.5 <= d <= raw  # jittered, never above cap
    assert client._backoff(50) <= cap


# --------------------------------------------------- the thrasher


@pytest.mark.parametrize("profile", list(THRASH_PROFILES))
def test_short_thrash_converges_and_replays(profile):
    """Tier-1 thrash per codec family: a seeded short schedule (flaps
    [+ a partition on the rs arm] + 1-2% bitrot) under concurrent
    oracle writers must converge to active+clean, scrub-clean,
    byte-exact — the same seed reproduces the same schedule, and the
    non-RS arms prove each codec's verify-on-read + async repair path
    through the batched decode pipeline (clay, blaum_roth)."""
    # rs keeps the historical 5 s shape; the codec arms run a leaner
    # 3 s schedule (bitrot is the point there, not partitions)
    dur, n_obj, writers, partitions, bitrot = {
        "rs": (5.0, 6, 3, True, 0.01),
        "clay": (3.0, 4, 2, False, 0.02),
        "blaum_roth": (3.0, 4, 2, False, 0.02),
    }[profile]

    async def t():
        c = await make_ec_cluster(seed=1234, pg_num=8,
                                  profile=THRASH_PROFILES[profile])
        c.client.op_timeout = 150.0
        thr = Thrasher(c, 2, seed=1234, duration=dur, max_unavail=2,
                       bitrot_p=bitrot, partitions=partitions,
                       n_objects=n_obj, obj_size=16 << 10,
                       writers=writers, settle_timeout=90.0)
        assert thr.schedule == build_schedule(1234, dur, 5,
                                              max_unavail=2,
                                              partitions=partitions)
        verdict = await thr.run()
        assert verdict["passed"], verdict
        assert verdict["converged"]
        assert verdict["scrub_inconsistent"] == []
        assert verdict["oracle_mismatches"] == []
        assert verdict["writes_acked"] > 0
        assert [[e.t, e.kind, e.target] for e in thr.schedule] == \
            verdict["events"]
        if profile != "rs":
            # the arm's writes rode the batched cell pipeline (the
            # degraded-dispatch counter-proof lives in
            # test_repair_economics — here kills/reads race the heal)
            enc = sum(o.perf.dump().get("ec_batches", 0)
                      for o in c.osds if o is not None)
            assert enc > 0
        await c.stop()

    run(t(), timeout=300)


@pytest.mark.slow
def test_thrash_60s_acceptance():
    """The ISSUE 4 acceptance thrash: 60 seconds of OSD flaps + one
    rolling partition + bitrot on 1% of reads against a k=3,m=2 pool
    with concurrent writers; converges to active+clean with zero
    deep-scrub inconsistencies and byte-exact oracle reads, and the
    seed reproduces the schedule."""
    async def t():
        seed = 20260803
        c = await make_ec_cluster(seed=seed, pg_num=8)
        c.client.op_timeout = 300.0
        thr = Thrasher(c, 2, seed=seed, duration=60.0, max_unavail=2,
                       bitrot_p=0.01, partitions=True, n_objects=10,
                       obj_size=24 << 10, writers=4,
                       settle_timeout=120.0)
        assert thr.schedule == build_schedule(seed, 60.0, 5,
                                              max_unavail=2,
                                              partitions=True)
        verdict = await thr.run()
        assert verdict["passed"], verdict
        await c.stop()

    run(t(), timeout=600)


def test_unfound_grace_anchors_on_recovery_progress():
    """The orphan-rollback gate (ROADMAP item d): UNFOUND_GRACE alone
    is a wall clock, and a merely SLOW recovery (delayed reconstructs)
    exhausts it while acked objects are still recoverable — the skip
    then converges heads over the gap and scrub rolls the generation
    back. The gate must re-anchor whenever recovery progressed since
    the mark, and only classify unfound after a full grace with ZERO
    progress."""
    async def t():
        pg = PG.__new__(PG)  # pure gate logic: no cluster needed
        pg._unfound_since = {}
        pg._recovery_progress = 0
        oid = b"debris"
        # first failure only marks
        assert not pg._unfound_grace_spent(oid)
        t0, p0 = pg._unfound_since[oid]
        assert p0 == 0
        # wall clock spent but recovery progressed since the mark:
        # NOT unfound — the mark re-anchors at the new reading
        pg._unfound_since[oid] = (t0 - UNFOUND_GRACE - 1.0, p0)
        pg._note_recovery_progress()
        assert not pg._unfound_grace_spent(oid)
        t1, p1 = pg._unfound_since[oid]
        assert p1 == pg._recovery_progress and t1 > t0 - 1.0
        # grace not yet spent at the new anchor: still not unfound
        assert not pg._unfound_grace_spent(oid)
        # a full grace with no progress at all: unfound
        pg._unfound_since[oid] = (t1 - UNFOUND_GRACE - 1.0, p1)
        assert pg._unfound_grace_spent(oid)

    run(t(), timeout=10)


@pytest.mark.slow
def test_slow_recovery_keeps_acked_writes(monkeypatch):
    """ROADMAP item (d) regression: delaying _reconstruct_chunk by
    ~80 ms per call (a saturated device link / cold-compile shape)
    made the 20 s seeded thrash lose an acked generation ~1-in-3 on
    plain rs at seed 20260803 — UNFOUND_GRACE expired while recovery
    was still grinding, the skip converged heads over the gap, and
    scrub rolled the orphan back. With the grace anchored on recovery
    progress the same run stays byte-exact."""
    orig = PG._reconstruct_chunk

    async def slow_reconstruct(self, oid, shard):
        await asyncio.sleep(0.08)
        return await orig(self, oid, shard)

    monkeypatch.setattr(PG, "_reconstruct_chunk", slow_reconstruct)

    async def t():
        seed = 20260803
        c = await make_ec_cluster(seed=seed, pg_num=8)
        c.client.op_timeout = 300.0
        thr = Thrasher(c, 2, seed=seed, duration=20.0, max_unavail=2,
                       bitrot_p=0.01, partitions=True, n_objects=8,
                       obj_size=24 << 10, writers=4,
                       settle_timeout=150.0)
        verdict = await thr.run()
        assert verdict["passed"], verdict
        await c.stop()

    run(t(), timeout=600)


def test_flip_bit_breaks_and_is_deterministic():
    buf = bytes(range(64))
    assert flip_bit(buf) != buf
    assert flip_bit(buf) == flip_bit(buf)
    assert flip_bit(b"") == b""


def test_late_subop_pg_shell_never_wedges_wait_clean():
    """Thrash-found convergence wedge: a late/duplicated sub-op (or a
    prior-interval push) addressed to a shard position this OSD no
    longer holds creates a fresh PG instance via _ensure_pg. With the
    map epoch stable afterwards, on_map never runs again — the shell
    kept the constructor's 'peering' forever and wait_clean never
    returned. _ensure_pg must classify the newborn instance against
    the CURRENT map immediately (stray/replica -> active, genuine
    primary -> peering task)."""
    async def t():
        c = await make_ec_cluster(seed=17)
        await c.client.write_full(2, "obj", b"x" * (3 * 4096))
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        osd = c.osds[up[1]]
        # a shard position some OTHER OSD holds under the current map:
        # exactly what a delayed MECSubWrite from a prior pg_temp
        # interval addresses
        stray_shard = next(s for s in range(len(up))
                           if up[s] != osd.id)
        shell = osd._ensure_pg(pgid, stray_shard)
        assert shell.state == "active"  # stray: serve, never drive
        # and the cluster still converges with the shell registered
        await c.wait_clean(30)
        await c.stop()

    run(t())


def test_primary_delta_write_over_missing_base_bounces():
    """Review-found sibling of the handle_ec_write missing-base bounce:
    the PRIMARY's own shard used to apply a delta write even when its
    base content was on the missing record (head converged over a
    skipped unfound push), stamping the new ATTR_V + copied hinfo over
    absent cells — zeros that hash as zero cells, corruption neither
    CRC nor the version cross-check can convict. The fan-out must
    bounce (EAGAIN -> client retry) and re-peer so recovery restores
    the base first; the retried write then lands byte-exact."""
    async def t():
        c = await make_ec_cluster(seed=19)
        rng = np.random.default_rng(55)
        data = rng.integers(0, 256, 3 * 4096 * 2, dtype=np.uint8).tobytes()
        await c.client.write_full(2, "obj", data)
        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        posd = c.osds[primary]
        key = (pgid[0], pgid[1], up.index(primary))
        pg = posd.pgs[key]
        # simulate the converged-over gap: the primary's own shard
        # base is gone and the gap is on record
        from ceph_tpu.cluster.pg import ATTR_V as AV
        import ceph_tpu.utils.denc as denc
        raw = posd.store.getattr(pg.cid, b"obj", AV)
        ver = (denc.dec_u32(raw, 0)[0], denc.dec_u64(raw, 4)[0])
        t0 = tx.Transaction()
        t0.remove(pg.cid, b"obj")
        posd.store.queue_transaction(t0)
        pg.missing[b"obj"] = ver
        # a partial (delta) overwrite: must NOT serve from the absent
        # base; the bounce re-peers, recovery reinstalls the shard,
        # the client's retry lands
        patch = rng.integers(0, 256, 512, dtype=np.uint8).tobytes()
        await c.client.write(2, "obj", 1024, patch)
        want = data[:1024] + patch + data[1024 + 512:]
        assert await c.client.read(2, "obj") == want
        assert pg.missing.get(b"obj") is None  # recovered, gap cleared
        await c.stop()

    run(t())


def test_revived_peon_rediscovers_leader_without_election():
    """Mon-failover rejoin: a revived peon boots leaderless and
    campaigns; the healthy leader must answer with a victory
    re-announce (fold-in) rather than silence — quorum-membership
    tests alone miss this, because the leader's quorum list never
    shrank while the peon was down, yet the peon's own `leader` stays
    None and every client op forwarded through it would fail."""
    async def t():
        c = TestCluster(n_osds=3, n_mons=3)
        await c.start()
        peon = next(r for r, m in enumerate(c.mons)
                    if m is not None and not m.is_leader())
        await c.kill_mon(peon)
        m = await c.revive_mon(peon)
        for _ in range(200):
            if m.leader is not None and m.rank in m.quorum:
                break
            await asyncio.sleep(0.05)
        assert m.leader is not None, "revived peon never found the leader"
        assert m.rank in m.quorum, "revived peon never rejoined quorum"
        await c.stop()

    run(t())


def test_chip_loss_schedule_deterministic_and_bounded():
    """chip_loss events join the schedule deterministically, one dark
    chip at a time, with the dark chip's OWNING OSDs counted against
    the availability budget like kills."""
    from ceph_tpu.cluster.faults import chip_owners

    kw = dict(max_unavail=2, chip_loss=True, n_chips=4)
    s1 = build_schedule(77, 120.0, 5, **kw)
    assert s1 == build_schedule(77, 120.0, 5, **kw)
    kinds = {e.kind for e in s1}
    assert "chip_loss" in kinds and "chip_heal" in kinds
    # without the flag the schedule is exactly the legacy one (no
    # extra rng draws: replayability across the flag)
    legacy = build_schedule(77, 120.0, 5, max_unavail=2)
    assert all(e.kind not in ("chip_loss", "chip_heal")
               for e in legacy)
    # replay: unavailability (dead + cut + dark-chip owners) bounded
    dead, cut, dark = set(), set(), set()
    for ev in s1:
        if ev.kind == "kill":
            dead.add(ev.target)
        elif ev.kind == "revive":
            dead.discard(ev.target)
        elif ev.kind == "partition":
            cut = {ev.target}
        elif ev.kind == "heal":
            cut = set()
        elif ev.kind == "chip_loss":
            assert not dark
            dark = set(chip_owners(5, 4, ev.target))
            assert dark  # only owner-ful chips get scheduled
        elif ev.kind == "chip_heal":
            dark = set()
        assert len(dead | (cut - dead) | (dark - dead - cut)) <= 2


def test_chip_loss_fault_scopes_to_owning_osds():
    """The chip-loss arm fires EC device dispatches only on the dark
    chip's owners, re-arms on revive (a revived OSD whose chip is
    still dark comes back dark), and chip_heal disarms everywhere
    without touching other armed sites."""
    async def t():
        c = await make_ec_cluster(seed=17)
        c.faults.store_fault("ec_read_bitflip", p=0.01)  # another arm
        # chip 1 of 4 owns osd.1 (1 % 4) — and nobody else at n=5
        c.faults.store_fault("ec_batch", p=1.0, osd_ids=[1])
        assert c.osds[1].fault._arms.get("ec_batch")
        assert not c.osds[0].fault._arms.get("ec_batch")
        assert not c.osds[4].fault._arms.get("ec_batch")
        await c.kill_osd(1)
        await c.revive_osd(1)
        assert c.osds[1].fault._arms.get("ec_batch")
        c.faults.clear_store_fault("ec_batch")
        assert not c.osds[1].fault._arms.get("ec_batch")
        # the unrelated site survives the single-site heal
        assert c.osds[2].fault._arms.get("ec_read_bitflip")
        await c.stop()

    run(t())


def test_short_chip_loss_thrash_converges_over_mesh():
    """Tier-1 chip-loss thrash: the serving mesh on (device engine,
    collective repair), a seeded ~4 s schedule that includes mesh-chip
    losses, byte-exact convergence — the small sibling of the 20 s
    CLI acceptance run (tools/thrash.py --chip-loss)."""
    from ceph_tpu.parallel import runtime

    async def t():
        c = TestCluster(n_osds=5, fault_seed=4242, osd_conf={
            "osd_ec_mesh_devices": 8,
            "osd_ec_mesh_width": 2,
            "parallel_repair_mode": "allgather",
        })
        await c.start()
        await c.client.create_pool(
            Pool(id=2, name="ec", size=5, min_size=3, pg_num=8,
                 crush_rule=1, type="erasure",
                 ec_profile=dict(EC_PROFILE)))
        await c.wait_active(20)
        c.client.op_timeout = 150.0
        runtime.STATS.reset()
        thr = Thrasher(c, 2, seed=4242, duration=4.0, max_unavail=2,
                       bitrot_p=0.0, partitions=False, n_objects=6,
                       obj_size=16 << 10, writers=3,
                       settle_timeout=90.0, chip_loss=True, n_chips=8)
        assert thr.schedule == build_schedule(
            4242, 4.0, 5, max_unavail=2, partitions=False,
            chip_loss=True, n_chips=8)
        assert any(e.kind == "chip_loss" for e in thr.schedule)
        verdict = await thr.run()
        assert verdict["passed"], verdict
        assert verdict["writes_acked"] > 0
        assert any(k == "chip_loss" for _, k, _ in verdict["events"])
        await c.stop()

    run(t(), timeout=300)
    # the thrash actually rode the mesh
    assert runtime.STATS.dump()["mesh_encode_dispatches"] > 0
    assert runtime.STATS.dump()["mesh_host_gathers"] == 0


def test_plane_store_fault_rearms_on_revive():
    """A plane-registered store fault survives kill/revive: the spec
    re-arms on the fresh injector (specs outlive incarnations)."""
    async def t():
        c = await make_ec_cluster(seed=13)
        c.faults.store_fault("ec_sub_read", p=1.0, oid=b"nope")
        victim = 1
        assert c.osds[victim].fault._arms.get("ec_sub_read")
        await c.kill_osd(victim)
        await c.revive_osd(victim)
        assert c.osds[victim].fault._arms.get("ec_sub_read")
        # re-arming REPLACES on live injectors (no stacked arms — live
        # and revived OSDs must fire at the same rate)
        c.faults.store_fault("ec_sub_read", p=0.5, oid=b"nope")
        assert len(c.osds[victim].fault._arms["ec_sub_read"]) == 1
        c.faults.clear_store_faults()
        assert not c.osds[victim].fault._arms.get("ec_sub_read")
        await c.stop()

    run(t())
