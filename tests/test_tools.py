"""CLI tools: rados (put/get/ls/df/bench — src/tools/rados +
obj_bencher roles) and objectstore_tool (offline PG surgery —
ceph_objectstore_tool role). Each invocation is a fresh process-style
main() call against durable BlueStoreLite state, so the tools also
exercise cold cluster restart."""
import importlib.util
import json
import os
import sys

import pytest

_TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


rados = _load("rados")
ost = _load("objectstore_tool")


def test_rados_put_get_ls_df_roundtrip(tmp_path, capsys):
    d = str(tmp_path / "cluster")
    base = ["--data-dir", d, "--osds", "5", "--dev-size", "64"]
    assert rados.main(base + ["mkpool", "ecp", "--ec-k", "3",
                              "--ec-m", "2"]) == 0
    payload = os.urandom(50_000)
    src = tmp_path / "in.bin"
    src.write_bytes(payload)
    assert rados.main(base + ["put", "ecp", "obj1", str(src)]) == 0
    assert rados.main(base + ["put", "ecp", "obj2", str(src)]) == 0
    out = tmp_path / "out.bin"
    capsys.readouterr()
    assert rados.main(base + ["get", "ecp", "obj1", str(out)]) == 0
    assert out.read_bytes() == payload
    assert rados.main(base + ["ls", "ecp"]) == 0
    lines = capsys.readouterr().out.splitlines()
    assert lines == ["obj1", "obj2"]
    assert rados.main(base + ["stat", "ecp", "obj1"]) == 0
    assert "size 50000" in capsys.readouterr().out
    assert rados.main(base + ["df"]) == 0
    df = capsys.readouterr().out
    assert "ecp" in df and "100000" in df
    assert rados.main(base + ["rm", "ecp", "obj2"]) == 0
    assert rados.main(base + ["ls", "ecp"]) == 0
    assert capsys.readouterr().out.splitlines() == ["obj1"]


def test_rados_bench_write_then_read(tmp_path, capsys):
    base = ["--osds", "4"]  # MemStore throwaway cluster
    assert rados.main(base + ["bench", "bp", "1", "write",
                              "-b", "65536", "-t", "4"]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["mode"] == "write" and res["ops"] > 0
    assert res["mb_per_sec"] > 0 and res["avg_lat_ms"] > 0
    # seq needs the written objects -> durable dir variant
    d = str(tmp_path / "bcluster")
    base = ["--data-dir", d, "--osds", "4", "--dev-size", "64"]
    assert rados.main(base + ["bench", "bp", "1", "write",
                              "-b", "16384", "-t", "4"]) == 0
    capsys.readouterr()
    assert rados.main(base + ["bench", "bp", "1", "seq", "-t", "4"]) == 0
    res = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert res["mode"] == "seq" and res["ops"] > 0


def test_objectstore_tool_surgery(tmp_path, capsys):
    """Export a PG from one (downed) OSD store, wipe it, re-import —
    the disaster-recovery arc the reference tool exists for."""
    d = str(tmp_path / "cluster")
    base = ["--data-dir", d, "--osds", "4", "--dev-size", "64"]
    assert rados.main(base + ["mkpool", "rp", "3"]) == 0
    payload = os.urandom(9000)
    src = tmp_path / "in.bin"
    src.write_bytes(payload)
    assert rados.main(base + ["put", "rp", "victim", str(src)]) == 0
    capsys.readouterr()

    pgid = None
    for i in range(4):  # find an OSD holding a replica
        tb = ["--data-path", os.path.join(d, f"osd.{i}"),
              "--type", "bluestore"]
        assert ost.main(tb + ["--op", "list"]) == 0
        rows = [json.loads(ln) for ln in
                capsys.readouterr().out.splitlines()]
        pgids = {cid for cid, oid in rows if oid == "victim"}
        if pgids:
            pgid = pgids.pop()
            break
    assert pgid is not None, "no OSD holds the object?"

    assert ost.main(tb + ["--op", "info", "--pgid", pgid]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["objects"] >= 1

    exp = str(tmp_path / "pg.export")
    assert ost.main(tb + ["--op", "export", "--pgid", pgid,
                          "--file", exp]) == 0
    assert ost.main(tb + ["--op", "remove", "--pgid", pgid]) == 0
    capsys.readouterr()
    assert ost.main(tb + ["--op", "list"]) == 0
    rows = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()]
    assert pgid not in {cid for cid, _ in rows}

    assert ost.main(tb + ["--op", "import", "--file", exp]) == 0
    out = str(tmp_path / "got.bin")
    assert ost.main(tb + ["--op", "get-bytes", "--pgid", pgid,
                          "--obj", "victim", "--file", out]) == 0
    assert open(out, "rb").read() == payload

    # importing over an existing PG is refused (log would go stale)
    with pytest.raises(SystemExit, match="already exists"):
        ost.main(tb + ["--op", "import", "--file", exp])

    # corrupt export is rejected
    blob = bytearray(open(exp, "rb").read())
    blob[10] ^= 1
    bad = str(tmp_path / "bad.export")
    open(bad, "wb").write(bytes(blob))
    with pytest.raises(SystemExit, match="corrupt"):
        ost.main(tb + ["--op", "import", "--file", bad])


rbd_cli = _load("rbd")


def test_rbd_cli_lifecycle(tmp_path, capsys):
    """rbd CLI (src/tools/rbd role): create/import/export/snap/clone/
    encryption over durable state, each call a cold cluster restart."""
    # the `encryption format`/`--encryption-passphrase-file` legs ride
    # the optional `cryptography` package — skip in minimal containers
    pytest.importorskip("cryptography")
    d = str(tmp_path / "cluster")
    base = ["--data-dir", d, "--osds", "4"]
    img = os.urandom(200_000)
    src = tmp_path / "disk.img"
    src.write_bytes(img)
    out = tmp_path / "out.img"
    assert rbd_cli.main(base + ["mkpool", "rbd"]) == 0
    assert rbd_cli.main(base + ["create", "rbd/disk",
                                "--size", "1M"]) == 0
    assert rbd_cli.main(base + ["ls", "rbd"]) == 0
    assert "disk" in capsys.readouterr().out
    assert rbd_cli.main(base + ["import", "rbd/disk", str(src)]) == 0
    assert rbd_cli.main(base + ["export", "rbd/disk", str(out)]) == 0
    assert out.read_bytes()[:len(img)] == img
    # snapshot, mutate, clone from the snap: clone sees the snap state
    assert rbd_cli.main(base + ["snap", "create", "rbd/disk@s1"]) == 0
    mut = tmp_path / "mut.img"
    mut.write_bytes(b"\xaa" * 1000)
    assert rbd_cli.main(base + ["import", "rbd/disk", str(mut)]) == 0
    assert rbd_cli.main(base + ["clone", "rbd/disk@s1",
                                "rbd/child"]) == 0
    assert rbd_cli.main(base + ["flatten", "rbd/child"]) == 0
    assert rbd_cli.main(base + ["export", "rbd/child", str(out)]) == 0
    assert out.read_bytes()[:len(img)] == img  # pre-mutation content
    assert rbd_cli.main(base + ["info", "rbd/disk"]) == 0
    assert "size" in capsys.readouterr().out
    # encrypted image: format once, encrypted import/export round-trips
    pf = tmp_path / "pass.txt"
    pf.write_text("s3kr1t\n")
    assert rbd_cli.main(base + ["create", "rbd/vault",
                                "--size", "1M"]) == 0
    assert rbd_cli.main(base + ["encryption", "format", "rbd/vault",
                                str(pf)]) == 0
    assert rbd_cli.main(base + ["import", "rbd/vault", str(src),
                                "--passphrase-file", str(pf)]) == 0
    assert rbd_cli.main(base + ["export", "rbd/vault", str(out),
                                "--passphrase-file", str(pf)]) == 0
    assert out.read_bytes()[:len(img)] == img
    # without the passphrase the export is ciphertext
    assert rbd_cli.main(base + ["export", "rbd/vault", str(out)]) == 0
    assert out.read_bytes()[:len(img)] != img
    assert rbd_cli.main(base + ["rm", "rbd/child"]) == 0
    capsys.readouterr()  # drop the rm confirmation
    assert rbd_cli.main(base + ["ls", "rbd"]) == 0
    outtxt = capsys.readouterr().out
    assert "child" not in outtxt and "vault" in outtxt
