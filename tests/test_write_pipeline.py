"""End-to-end write-path pipelining (client op windows, corked wire
batching, store group commit).

Three layers, one contract each:
- the client aio window honors ``client_max_inflight`` as real
  backpressure, completes ops on one object in submission order, and
  keeps the tick-resend machinery working per-op inside the window;
- the corked TcpMessenger writer coalesces N queued frames into one
  write + one drain, preserves per-pair ordering (secure mode's
  counter nonces included), and surfaces SendError to exactly the
  caller whose message rode the failed burst; LocalBus's in-process
  cork keeps FIFO and counts burst occupancy;
- store group commit flushes/fsyncs ONCE per window of transactions,
  fires on_commit only after the group's barrier, and a crash between
  append and flush replays to a clean prefix.
"""
import asyncio
import os
import shutil

import pytest

from ceph_tpu.cluster import TestCluster
from ceph_tpu.cluster import messages as M
from ceph_tpu.msg.messenger import LocalBus, SendError, TcpMessenger
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.store import transaction as tx
from ceph_tpu.store.walstore import WalStore


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make_rep_cluster(n=4, **kw):
    c = TestCluster(n_osds=n, **kw)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    return c


# ------------------------------------------------------ client op window


def test_aio_window_backpressure_and_occupancy():
    """client_max_inflight is a hard budget: a submitter pushing 12 ops
    through a 4-slot window never observes more than 4 in flight, and
    the occupancy stats prove the window actually ran full."""
    async def t():
        c = await make_rep_cluster()
        c.client.conf.set("client_max_inflight", 4)
        comps = []
        for i in range(12):
            comps.append(await c.client.aio_write_full(
                1, f"w{i}", b"x" * 1024))
            assert c.client._aio_inflight <= 4
        await c.client.writes_wait()
        for comp in comps:
            comp.result()  # raises if any write failed
        ws = c.client.window_stats
        assert ws["max"] <= 4
        assert ws["count"] == 12
        assert ws["sum"] / ws["count"] > 1.0  # pipelined, not serial
        for i in range(12):
            assert await c.client.read(1, f"w{i}") == b"x" * 1024
        await c.stop()

    run(t())


def test_aio_per_object_completion_order():
    """Ops on ONE object execute and complete in submission order;
    the object ends with the last submission's bytes."""
    async def t():
        c = await make_rep_cluster()
        c.client.conf.set("client_max_inflight", 8)
        order = []
        comps = []
        for i in range(6):
            comp = await c.client.aio_write_full(
                1, "same", f"payload-{i}".encode())
            comp._fut.add_done_callback(
                lambda _f, i=i: order.append(i))
            comps.append(comp)
        await c.client.writes_wait()
        for comp in comps:
            comp.result()
        assert order == sorted(order), order
        assert await c.client.read(1, "same") == b"payload-5"
        await c.stop()

    run(t())


def test_aio_resend_inside_window():
    """The tick-resend machinery keeps working per-op INSIDE the
    window: ops submitted into a partition complete after heal, via
    resends, with no outside intervention."""
    async def t():
        c = await make_rep_cluster()
        c.client.conf.set("client_max_inflight", 8)
        c.client.conf.set("client_backoff_max", 0.5)
        c.client.op_timeout = 30.0
        before = c.client.op_retries
        c.faults.net.partition({"client.0"}, {"*"})
        comps = [await c.client.aio_write_full(1, f"p{i}", b"y" * 512)
                 for i in range(4)]
        await asyncio.sleep(1.0)
        assert not any(comp.done() for comp in comps)
        c.faults.net.heal()
        await c.client.writes_wait()
        for comp in comps:
            comp.result()
        assert c.client.op_retries > before
        for i in range(4):
            assert await c.client.read(1, f"p{i}") == b"y" * 512
        await c.stop()

    run(t())


# -------------------------------------------------- corked wire batching


async def _tcp_pair(got, done_at, keys=None, secure=False):
    async def dispatch(src, msg):
        got.append(msg)
        if len(got) >= done_at[0]:
            done_at[1].set()

    async def drop(src, msg):
        pass

    a = TcpMessenger("client.1", drop, keys=keys, secure=secure)
    b = TcpMessenger("osd.0", dispatch, keys=keys, secure=secure)
    host, port = await b.listen()
    a.addrbook["osd.0"] = (host, port)
    return a, b


def test_corked_writer_coalesces_frames():
    """N concurrently queued frames reach the peer in order through
    FEWER than N drain barriers (frames_per_drain > 1)."""
    async def t():
        got, done = [], (20, asyncio.Event())
        a, b = await _tcp_pair(got, done)
        await asyncio.gather(*(
            a.send("osd.0", M.MOSDBoot(osd=i)) for i in range(20)))
        await asyncio.wait_for(done[1].wait(), 5)
        assert [m.osd for m in got] == list(range(20))  # per-pair FIFO
        assert a.frames_sent == 20
        assert a.drains < 20, (a.drains, a.frames_sent)
        assert a.frames_per_drain > 1.0
        await a.close()
        await b.close()

    run(t())


def _have_aesgcm() -> bool:
    try:
        from cryptography.hazmat.primitives.ciphers.aead import (  # noqa
            AESGCM)
        return True
    except Exception:
        return False


@pytest.mark.parametrize("secure", [
    False,
    pytest.param(True, marks=pytest.mark.skipif(
        not _have_aesgcm(),
        reason="secure mode needs the cryptography package")),
])
def test_corked_writer_authed_ordering(secure):
    """Signing/encryption happen in the writer task in queue order:
    per-frame HMACs (and, with AES-GCM available, secure mode's
    counter nonces) survive corking — an out-of-order encrypt would be
    rejected as a replay by the peer."""
    async def t():
        from ceph_tpu.msg.auth import KeyServer

        keys = KeyServer()
        keys.add("client.1", b"k" * 16)
        keys.add("osd.0", b"o" * 16)
        got, done = [], (16, asyncio.Event())
        a, b = await _tcp_pair(got, done, keys=keys, secure=secure)
        await asyncio.gather(*(
            a.send("osd.0", M.MOSDBoot(osd=i)) for i in range(16)))
        await asyncio.wait_for(done[1].wait(), 5)
        assert [m.osd for m in got] == list(range(16))
        assert a.drains < 16
        await a.close()
        await b.close()

    run(t())


def test_corked_writer_senderror_reaches_caller():
    """Every message riding a burst that cannot connect fails ITS
    caller with SendError — no silent drops, no hung futures."""
    async def t():
        async def drop(src, msg):
            pass

        a = TcpMessenger("client.1", drop)
        a.addrbook["osd.9"] = ("127.0.0.1", 1)  # nothing listens
        results = await asyncio.gather(
            *(a.send("osd.9", M.MOSDBoot(osd=i)) for i in range(5)),
            return_exceptions=True)
        assert all(isinstance(r, SendError) for r in results), results
        await a.close()

    run(t())


def test_localbus_cork_fifo_and_burst_counters():
    """Same-tick LocalBus sends to one destination ride one delivery
    burst, in order."""
    async def t():
        got = []

        async def handler(src, msg):
            got.append(msg.osd)

        bus = LocalBus()
        bus.register("osd.0", handler)
        bus.register("client.0", handler)
        for i in range(10):
            await bus.send("client.0", "osd.0", M.MOSDBoot(osd=i))
        await bus.drain()
        assert got == list(range(10))
        assert bus.delivery_bursts == 1
        assert bus.frames_delivered == 10
        assert bus.frames_per_drain == 10.0

    run(t())


# ------------------------------------------------------ store group commit


def _txn(i: int, cid="c") -> tx.Transaction:
    t = tx.Transaction()
    t.write(cid, b"o%d" % i, 0, b"v" * 512)
    return t


def test_walstore_group_commit_fsyncs_once_per_group(tmp_path,
                                                     monkeypatch):
    """20 transactions inside one commit window pay ~1 fsync, not 20;
    the per-txn store pays 20. Counters prove the grouping."""
    import ceph_tpu.store.walstore as ws_mod

    fsyncs = []
    real_fsync = os.fsync
    monkeypatch.setattr(ws_mod.os, "fsync",
                        lambda fd: (fsyncs.append(fd),
                                    real_fsync(fd))[1])

    s = WalStore(str(tmp_path / "grouped"), fsync=True,
                 commit_window_ms=2000.0, commit_max_txns=64)
    s.mount()
    t0 = tx.Transaction()
    t0.create_collection("c")
    s.queue_transaction(t0)
    for i in range(20):
        s.queue_transaction(_txn(i))
    s._committer.flush_now()
    grouped_fsyncs = len(fsyncs)
    st = s.commit_stats
    assert st.txns == 21
    assert st.commits < 21
    assert st.txns / st.commits > 1.0
    assert st.commits_grouped >= 1
    s.umount()
    assert grouped_fsyncs <= 3  # mount-side + the group barriers

    fsyncs.clear()
    s2 = WalStore(str(tmp_path / "perTxn"), fsync=True)
    s2.mount()
    t0 = tx.Transaction()
    t0.create_collection("c")
    s2.queue_transaction(t0)
    for i in range(20):
        s2.queue_transaction(_txn(i))
    assert len(fsyncs) >= 21  # one barrier per transaction
    assert s2.commit_stats.txns / s2.commit_stats.commits == 1.0
    s2.umount()


def test_walstore_group_commit_on_commit_after_flush(tmp_path):
    """on_commit fires only at the group boundary — never before the
    flush that makes the transaction durable."""
    s = WalStore(str(tmp_path / "s"), commit_window_ms=60000.0,
                 commit_max_txns=1000)
    s.mount()
    fired = []
    t0 = tx.Transaction()
    t0.create_collection("c")
    s.queue_transaction(t0, lambda: fired.append(0))
    s._committer.flush_now()
    assert fired == [0]
    s.queue_transaction(_txn(1), lambda: fired.append(1))
    s.queue_transaction(_txn(2), lambda: fired.append(2))
    assert fired == [0]  # pending: window far away, no flush yet
    # reads see the committed-to-memory state before the barrier
    assert s.read("c", b"o1") == b"v" * 512
    s._committer.flush_now()
    assert fired == [0, 1, 2]
    s.umount()


def test_walstore_group_commit_crash_replays_flushed_prefix(tmp_path):
    """Crash between append and flush: the copy-at-crash image mounts
    clean and serves exactly the flushed prefix (unflushed tail
    discarded, its on_commit never fired — the acked/unacked line)."""
    src = tmp_path / "src"
    s = WalStore(str(src), commit_window_ms=60000.0,
                 commit_max_txns=1000)
    s.mount()
    acked = []
    t0 = tx.Transaction()
    t0.create_collection("c")
    s.queue_transaction(t0)
    s.queue_transaction(_txn(1), lambda: acked.append(1))
    s._committer.flush_now()  # txn 1 durable + acked
    s.queue_transaction(_txn(2), lambda: acked.append(2))  # buffered
    assert acked == [1]
    crash = tmp_path / "crash"
    shutil.copytree(src, crash)  # the disk at power-cut time
    s._committer.flush_now()
    s.umount()

    s2 = WalStore(str(crash))
    s2.mount()
    assert s2.read("c", b"o1") == b"v" * 512  # acked write survived
    # the unacked tail either replayed whole or vanished whole — a
    # torn record must never half-apply
    try:
        data = s2.read("c", b"o2")
        assert data == b"v" * 512
    except Exception:
        pass  # discarded with the torn tail: fine, it was never acked
    s2.umount()


def test_bluestore_group_commit_read_your_write_and_batching(tmp_path):
    """BlueStoreLite grouped mode: deferred small overwrites stay
    readable through the pending-patch overlay before the group
    flushes, kv batches drop below one-per-txn, and a clean remount
    serves the grouped writes."""
    from ceph_tpu.store.bluestore import BlueStoreLite

    s = BlueStoreLite(str(tmp_path / "bs"), size=16 << 20,
                      commit_window_ms=2000.0, commit_max_txns=64)
    s.mount()
    batches = []
    real_batch = s.kv.batch
    s.kv.batch = lambda ops: (batches.append(len(ops)),
                              real_batch(ops))[1]
    t0 = tx.Transaction()
    t0.create_collection("c")
    s.queue_transaction(t0)
    base = bytes(range(256)) * 32  # 8 KiB
    t1 = tx.Transaction()
    t1.write("c", b"obj", 0, base)
    s.queue_transaction(t1)
    # small partial overwrite of a committed block -> deferred patch
    s._committer.flush_now()
    t2 = tx.Transaction()
    t2.write("c", b"obj", 100, b"PATCH")
    s.queue_transaction(t2)
    want = base[:100] + b"PATCH" + base[105:]
    assert s.read("c", b"obj") == want  # overlay serves the patch
    kv_batches_before_flush = len(batches)
    s._committer.flush_now()
    assert s.read("c", b"obj") == want  # device serves it after
    assert kv_batches_before_flush < 3
    st = s.commit_stats
    assert st.txns == 3
    assert st.commits <= st.txns
    s.umount()

    s2 = BlueStoreLite(str(tmp_path / "bs"), size=16 << 20)
    s2.mount()
    assert s2.read("c", b"obj") == want
    s2.umount()


def test_cluster_acks_wait_for_group_flush(tmp_path):
    """With a commit window armed, a client write is acked only after
    every shard's group flushed — an ack outrunning the flush would
    let a crash lose acked bytes (the acked-write-loss class the
    thrasher exists to catch)."""
    async def t():
        c = TestCluster(n_osds=4, objectstore="walstore",
                        data_dir=str(tmp_path), compression=None,
                        commit_window_ms=60000.0,
                        commit_max_txns=10_000)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0))
        await c.wait_active(20)
        comp = await c.client.aio_write_full(1, "durable", b"d" * 2048)
        await asyncio.sleep(0.8)
        # the window is an hour away and nothing forced a flush: the
        # ack must still be pending
        assert not comp.done()
        for _ in range(200):
            for s in c.stores:
                s._committer.flush_now()
            if comp.done():
                break
            await asyncio.sleep(0.05)
        await comp.wait()
        assert await c.client.read(1, "durable") == b"d" * 2048
        await c.stop()

    run(t())


# --------------------------------------------------- cluster-level smoke


def test_cluster_over_walstore_group_commit(tmp_path):
    """The whole write path — aio window, corked LocalBus, EC fan-out,
    group-commit walstore — serves byte-exact reads."""
    async def t():
        c = TestCluster(n_osds=5, objectstore="walstore",
                        data_dir=str(tmp_path), compression=None,
                        commit_window_ms=5.0, commit_max_txns=32)
        await c.start()
        await c.client.create_pool(
            Pool(id=2, name="ec", size=5, min_size=3, pg_num=8,
                 crush_rule=1, type="erasure",
                 ec_profile={"plugin": "rs_tpu", "k": "3", "m": "2"}))
        await c.wait_active(20)
        c.client.conf.set("client_max_inflight", 8)
        payload = os.urandom(1 << 16)
        comps = [await c.client.aio_write_full(2, f"g{i}", payload)
                 for i in range(16)]
        await c.client.writes_wait()
        for comp in comps:
            comp.result()
        for i in range(16):
            assert await c.client.read(2, f"g{i}") == payload
        grouped = sum(s.commit_stats.commits_grouped for s in c.stores)
        txns = sum(s.commit_stats.txns for s in c.stores)
        commits = sum(s.commit_stats.commits for s in c.stores)
        assert txns > 0 and commits > 0
        assert grouped >= 1 or txns == commits  # grouping is load-dependent
        await c.stop()

    run(t())
