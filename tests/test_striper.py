"""Striper tests: layout math vs a brute-force per-byte oracle, reverse
mapping, and the striped client over a live TestCluster (the
libradosstriper round-trip role)."""
import asyncio

import numpy as np
import pytest

from ceph_tpu.osdc import (
    FileLayout,
    RadosStriper,
    StripedReadResult,
    extent_to_file,
    file_to_extents,
    get_num_objects,
)


def byte_oracle(layout: FileLayout, fileoff: int):
    """Where does file byte `fileoff` live? (objectno, object offset) —
    straight from the layout definition, one byte at a time."""
    su, sc, spo = (layout.stripe_unit, layout.stripe_count,
                   layout.stripes_per_object)
    blockno = fileoff // su
    stripeno = blockno // sc
    stripepos = blockno % sc
    objectsetno = stripeno // spo
    objectno = objectsetno * sc + stripepos
    objoff = (stripeno % spo) * su + fileoff % su
    return objectno, objoff


LAYOUTS = [
    FileLayout(stripe_unit=4, stripe_count=3, object_size=8),
    FileLayout(stripe_unit=16, stripe_count=1, object_size=64),
    FileLayout(stripe_unit=8, stripe_count=4, object_size=8),
    FileLayout(stripe_unit=1 << 20, stripe_count=4, object_size=1 << 22),
]


@pytest.mark.parametrize("layout", LAYOUTS[:3])
@pytest.mark.parametrize("offset,length", [
    (0, 1), (0, 100), (3, 29), (7, 64), (25, 3), (0, 0), (128, 256),
])
def test_file_to_extents_matches_byte_oracle(layout, offset, length):
    extents = file_to_extents(layout, offset, length)
    placed = {}
    for ex in extents:
        pos = 0
        for bo, ln in ex.buffer_extents:
            for i in range(ln):
                placed[bo + i] = (ex.objectno, ex.offset + pos + i)
            pos += ln
    assert len(placed) == length
    for b in range(length):
        assert placed[b] == byte_oracle(layout, offset + b), f"byte {b}"


@pytest.mark.parametrize("layout", LAYOUTS[:3])
def test_extent_to_file_inverts(layout):
    rng = np.random.default_rng(42)
    for _ in range(20):
        off = int(rng.integers(0, 200))
        ln = int(rng.integers(1, 120))
        for ex in file_to_extents(layout, off, ln):
            runs = extent_to_file(layout, ex.objectno, ex.offset, ex.length)
            covered = sorted(
                b for fo, fl in runs for b in range(fo, fo + fl)
            )
            want = sorted(
                off + bo + i
                for bo, bln in ex.buffer_extents
                for i in range(bln)
            )
            assert covered == want


def test_get_num_objects():
    lay = FileLayout(stripe_unit=4, stripe_count=3, object_size=8)
    # stripe width 12, object set spans 24 bytes across 3 objects
    assert get_num_objects(lay, 0) == 0
    assert get_num_objects(lay, 1) == 1
    assert get_num_objects(lay, 4) == 1
    assert get_num_objects(lay, 5) == 2
    assert get_num_objects(lay, 12) == 3
    assert get_num_objects(lay, 24) == 3
    assert get_num_objects(lay, 25) == 4
    assert get_num_objects(lay, 48) == 6


def test_striped_read_result_holes():
    r = StripedReadResult(10)
    r.add_partial_result(b"abc", [(0, 3)])
    r.add_partial_result(b"", [(5, 2)])  # short read -> zero hole
    r.add_partial_result(b"XY", [(8, 2)])
    assert r.assemble() == b"abc\0\0\0\0\0XY"


def test_bulk_matches_scalar_big():
    lay = FileLayout(stripe_unit=1 << 16, stripe_count=4,
                     object_size=1 << 18)
    extents = file_to_extents(lay, (1 << 16) * 3 + 17, 5 << 16)
    total = sum(ex.length for ex in extents)
    assert total == 5 << 16
    # spot-check first byte of each extent against the oracle
    for ex in extents:
        bo = ex.buffer_extents[0][0]
        assert byte_oracle(lay, (1 << 16) * 3 + 17 + bo) == \
            (ex.objectno, ex.offset)


# ------------------------------------------------- cluster round-trip


def test_striper_over_cluster():
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    async def t():
        c = TestCluster(n_osds=4)
        await c.start()
        await c.client.create_pool(
            Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0)
        )
        await c.wait_active(20)
        lay = FileLayout(stripe_unit=4096, stripe_count=3,
                         object_size=16384)
        st = RadosStriper(c.client, 1, lay)
        rng = np.random.default_rng(7)
        data = rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes()
        await st.write("f", data)
        assert await st.stat("f") == len(data)
        assert await st.read("f") == data
        # partial overwrite crossing object boundaries
        patch = b"P" * 20000
        await st.write("f", patch, offset=30000)
        want = bytearray(data)
        want[30000:50000] = patch
        assert await st.read("f") == bytes(want)
        # ranged read
        assert await st.read("f", 29990, 40) == bytes(want[29990:30030])
        # grow via sparse write past EOF: hole reads back as zeros
        await st.write("f", b"END", offset=150_000)
        got = await st.read("f")
        assert len(got) == 150_003
        assert got[: len(want)] == bytes(want)
        assert got[len(want):150_000] == b"\0" * (150_000 - len(want))
        assert got[150_000:] == b"END"
        await st.remove("f")
        assert await st.stat("f") == 0
        await c.stop()

    asyncio.run(asyncio.wait_for(t(), 120))
