"""Swarm harness (tools/swarm.py): scaled-down tier-1 proofs of the
serving-plane claims — percentile reporting per op shape, batched
placement engagement under Zipf skew, mClock tenant isolation at
saturation, and the combined thrash-during-swarm scenario. Bench
config 10 runs the same engine at production shape (2,400 clients /
O(10^4) in-flight); these keep the contracts honest per-commit.
"""
import asyncio
import importlib.util
import os

import pytest

_SWARM_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools", "swarm.py")
spec = importlib.util.spec_from_file_location("ceph_tpu_swarm",
                                              _SWARM_PATH)
swarm = importlib.util.module_from_spec(spec)
spec.loader.exec_module(swarm)

#: 4 KiB-only mix: tier-1 runs skip the 4 MiB EC shape (it exists to
#: load the batcher, which test_ec_batcher already proves; here it
#: would just burn suite seconds)
MIX_4K = {"put4k": 0.5, "get4k": 0.4, "omap": 0.1}


def test_swarm_smoke_reports_percentiles_per_shape():
    out = asyncio.run(swarm.run_swarm(
        clients=100, duration=0.9, seed=3, n_osds=4,
        n_rados_clients=2, window=128, actor_depth=4,
        mix=MIX_4K, prewarm=False))
    assert out["ops"] > 0
    assert out["op_errors"] == {}
    for shape in MIX_4K:
        rep = out["shapes"][shape]
        assert rep["ops"] > 0
        assert rep["p50_ms"] <= rep["p99_ms"] <= rep["p999_ms"]
    # the window machinery actually pipelined (not serial awaits)
    assert out["inflight_peak"] > 20
    assert out["distinct_objects_touched"] > 10


def test_swarm_batched_placement_engages_under_zipf():
    out = asyncio.run(swarm.run_swarm(
        clients=120, duration=1.2, seed=4, n_osds=4,
        n_rados_clients=2, window=192, actor_depth=4,
        mix=MIX_4K, prewarm=True, placement_batch=True))
    place = out["placement"]
    assert place["placement_batch_lookups"] > 0
    # Zipf-skewed traffic over stable pg tables: overwhelmingly hits
    assert place["hit_rate"] > 0.90
    # A/B arm: lever off => zero batched lookups, same service
    ab = asyncio.run(swarm.run_swarm(
        clients=60, duration=0.8, seed=4, n_osds=4,
        n_rados_clients=1, window=96, actor_depth=4,
        mix=MIX_4K, prewarm=False, placement_batch=False))
    assert ab["placement"]["placement_batch_lookups"] == 0
    assert ab["ops"] > 0


@pytest.mark.slow
def test_swarm_mclock_tenant_isolation():
    """The satellite proof: a reservation-backed latency tenant keeps
    bounded tails and its reservation throughput while a bulk tenant
    saturates the same daemons (cluster/scheduler.py knobs under
    load, finally counter-proven). @slow: the isolation margin is a
    CONTENTION measurement — under a full parallel tier-1 suite the
    host itself starves both tenants and the ratio flakes; tier-2
    runs it on a quiet box where the scheduler, not the CI load, is
    what's measured."""
    out = asyncio.run(swarm.run_swarm(
        clients=220, duration=3.0, seed=5, n_osds=4,
        n_rados_clients=2, window=512, actor_depth=6, mix=MIX_4K,
        prewarm=False,
        qos={"reservation_ops_s": 20.0, "lat_actors": 6,
             "pace_s": 0.01}))
    q = out["qos"]
    # saturation really happened: the bulk tenant queued deeply
    assert out["inflight_sustained"] > 200
    assert q["bulk_p99_ms"] > 0
    # isolation: the latency tenant's p99 is decisively below the
    # bulk tenant's (reservation-phase dequeue jumps the queue)
    assert q["lat_p99_ms"] < q["bulk_p99_ms"] / 2, q
    # and its achieved rate is real service, not starvation (floor is
    # deliberately generous for 2-core CI: the reservation admits it
    # to a worker per service slot; shared-CPU service time bounds
    # the absolute rate, starvation would read ~0)
    assert q["lat_achieved_ops_s"] >= 5.0, q


def test_swarm_thrash_arm_converges():
    """Combined scenario: a seeded kill/revive schedule DURING the
    swarm; post-heal the cluster must converge and the epoch bumps
    must show up in the resolver's invalidation counter."""
    out = asyncio.run(swarm.run_swarm(
        clients=40, duration=2.0, seed=6, n_osds=5,
        n_rados_clients=2, window=128, actor_depth=4, mix=MIX_4K,
        prewarm=True, thrash_secs=1.5))
    assert out["thrash"]["converged"]
    assert out["thrash"]["events"], "schedule must have fired"
    assert out["ops"] > 0
    # epoch-bump -> invalidation -> re-resolve correctness is pinned
    # deterministically in test_placement_resolver; here the map churn
    # may land after the short swarm window, so only the serving
    # verdict (convergence + service) is asserted
