"""pg_temp / primary_temp / primary affinity tests (OSDMap.cc
_get_temp_osds + _apply_primary_affinity roles)."""
import numpy as np

from ceph_tpu.placement import crushmap as cm
from ceph_tpu.placement import encoding as menc
from ceph_tpu.placement.osdmap import Incremental, OSDMap, Pool

NONE = 0x7FFFFFFF


def make_map(n=6, pool_type="replicated"):
    crush = cm.build_flat(n)
    crush.add_rule(cm.flat_firstn_rule(0))
    crush.add_rule(cm.ec_rule(1))
    m = OSDMap(crush, n)
    if pool_type == "replicated":
        m.add_pool(Pool(id=1, name="p", size=3, pg_num=16, crush_rule=0))
    else:
        m.add_pool(Pool(id=1, name="p", size=3, min_size=2, pg_num=16,
                        crush_rule=1, type="erasure",
                        ec_profile={"k": "2", "m": "1"}))
    return m


def test_pg_temp_overrides_acting_not_up():
    m = make_map()
    pgid = (1, 3)
    up, upp, acting, actp = m.pg_to_up_acting_full(pgid)
    assert acting == up and actp == upp
    temp = [o for o in range(m.n_osds) if o not in up][:2]
    m.pg_temp[pgid] = temp
    up2, upp2, acting2, actp2 = m.pg_to_up_acting_full(pgid)
    assert up2 == up and upp2 == upp  # up side untouched
    assert acting2 == temp
    assert actp2 == temp[0]
    # the 2-tuple surface serves acting (what IO targets)
    a, p = m.pg_to_up_acting_osds(pgid)
    assert a == temp and p == temp[0]
    # removing the temp restores crush placement
    del m.pg_temp[pgid]
    assert m.pg_to_up_acting_osds(pgid) == (up, upp)


def test_pg_temp_drops_down_members():
    m = make_map()
    pgid = (1, 0)
    m.pg_temp[pgid] = [0, 1, 2]
    m.osds[1].up = False
    acting, primary = m.pg_to_up_acting_osds(pgid)
    assert acting == [0, 2]  # replicated: compacted
    m2 = make_map(pool_type="erasure")
    m2.pg_temp[pgid] = [0, 1, 2]
    m2.osds[1].up = False
    acting2, _ = m2.pg_to_up_acting_osds(pgid)
    assert acting2 == [0, NONE, 2]  # EC: positional hole


def test_primary_temp():
    m = make_map()
    pgid = (1, 5)
    up, _ = m.pg_to_up_acting_osds(pgid)
    m.primary_temp[pgid] = up[-1]
    _, _, acting, primary = m.pg_to_up_acting_full(pgid)
    assert primary == up[-1]
    assert acting == up  # membership unchanged, only who leads


def test_primary_affinity_shifts_leadership():
    m = make_map(n=4)
    # osd 0 never primary: every pg it would lead picks someone else
    m.primary_affinity[0] = 0
    led_by_0 = 0
    for ps in range(16):
        acting, primary = m.pg_to_up_acting_osds((1, ps))
        if primary == 0:
            led_by_0 += 1
        # replicated pools shift the chosen primary to the front
        assert acting[0] == primary
        assert 0 in acting or 0 not in acting  # membership intact
    assert led_by_0 == 0
    # partial affinity: 0 leads a reduced share, not zero forever
    m.primary_affinity[0] = 0x8000
    led = sum(
        1 for ps in range(16)
        if m.pg_to_up_acting_osds((1, ps))[1] == 0
    )
    assert 0 <= led <= 8  # roughly halved from its fair share


def test_affinity_fallback_when_all_decline():
    m = make_map(n=3)
    for o in range(3):
        m.primary_affinity[o] = 0
    for ps in range(8):
        acting, primary = m.pg_to_up_acting_osds((1, ps))
        assert primary in acting  # someone still leads


def test_temp_and_affinity_ride_incrementals_and_encoding():
    m = make_map()
    inc = Incremental(
        epoch=2,
        new_pg_temp={(1, 2): [3, 4, 5]},
        new_primary_temp={(1, 2): 4},
        new_primary_affinity={0: 0x4000},
    )
    blob = menc.encode_incremental(inc)
    inc2, used = menc.decode_incremental(blob)
    assert used == len(blob)
    assert inc2.new_pg_temp == inc.new_pg_temp
    assert inc2.new_primary_temp == inc.new_primary_temp
    assert inc2.new_primary_affinity == inc.new_primary_affinity
    m.apply_incremental(inc2)
    assert m.pg_to_up_acting_osds((1, 2)) == ([3, 4, 5], 4)
    assert m.primary_affinity == {0: 0x4000}
    # removal semantics
    m.apply_incremental(Incremental(
        epoch=3, new_pg_temp={(1, 2): []},
        new_primary_temp={(1, 2): -1},
        new_primary_affinity={0: 0x10000},
    ))
    assert not m.pg_temp and not m.primary_temp
    assert not m.primary_affinity
    # full-map round trip carries the fields
    m.pg_temp[(1, 9)] = [1, 2, 0]
    m.primary_affinity[2] = 0x2000
    m2, _ = menc.decode_osdmap(menc.encode_osdmap(m))
    assert m2.pg_temp == {(1, 9): [1, 2, 0]}
    assert m2.primary_affinity == {2: 0x2000}
