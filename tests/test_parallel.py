"""parallel/ sharding helpers: mesh factoring, placement specs, the
unified pad — the direct coverage test_shard_comm only gave these
transitively. Runs on the 8-device virtual CPU platform conftest pins.
"""
import jax
import numpy as np
import pytest

from ceph_tpu import parallel

K, SU_WORDS = 3, 16


@pytest.fixture(scope="module")
def devs():
    return parallel.get_devices(8)


# ------------------------------------------------------------ make_mesh


def test_make_mesh_width_factoring(devs):
    m1 = parallel.make_mesh(devs, width=1)
    assert dict(m1.shape) == {"stripe": 8, "width": 1}
    m4 = parallel.make_mesh(devs, width=4)
    assert dict(m4.shape) == {"stripe": 2, "width": 4}
    m8 = parallel.make_mesh(devs, width=8)
    assert dict(m8.shape) == {"stripe": 1, "width": 8}
    # a mesh over a device subset factors that subset
    m6 = parallel.make_mesh(devs[:6], width=3)
    assert dict(m6.shape) == {"stripe": 2, "width": 3}


def test_make_mesh_rejects_nondividing_width(devs):
    with pytest.raises(ValueError, match="does not divide"):
        parallel.make_mesh(devs, width=3)


# ----------------------------------------------------- placement specs


def _shard_shapes(arr):
    """{device id -> local shard shape} with replica dedup by index."""
    seen = {}
    for s in arr.addressable_shards:
        seen.setdefault(tuple((sl.start, sl.stop) for sl in s.index),
                        np.asarray(s.data).shape)
    return list(seen.values())


def test_chunk_batch_vs_per_stripe_vs_replicated_placement(devs):
    mesh = parallel.make_mesh(devs, width=4)  # stripe 2, width 4
    batch = np.arange(8 * K * SU_WORDS, dtype=np.uint32).reshape(
        8, K, SU_WORDS)

    cb = jax.device_put(batch, parallel.chunk_batch_sharding(mesh))
    # batch split over stripe (8/2), words over width (16/4), the
    # chunk axis REPLICATED — the "EC shard axis stays local" layout
    assert _shard_shapes(cb) == [(4, K, 4)] * 8
    spec = cb.sharding.spec
    assert spec[0] == parallel.STRIPE_AXIS and spec[2] == \
        parallel.WIDTH_AXIS

    ps = jax.device_put(np.arange(8, dtype=np.uint32),
                        parallel.per_stripe_sharding(mesh))
    # per-stripe scalars: one batch block per stripe row, width
    # replicates (2 unique blocks across the 8 devices)
    assert sorted(s[0] for s in _shard_shapes(ps)) == [4, 4]

    rp = jax.device_put(np.arange(8, dtype=np.uint32),
                        parallel.replicated(mesh))
    # fully replicated: ONE unique (whole) block
    assert _shard_shapes(rp) == [(8,)]

    # round-trips preserve content
    assert (np.asarray(cb) == batch).all()
    assert (np.asarray(ps) == np.arange(8, dtype=np.uint32)).all()


def test_shard_placement_puts_chunks_on_width_devices(devs):
    from ceph_tpu.parallel import shard_comm

    mesh = parallel.make_mesh(devs, width=4)
    batch = np.zeros((4, 8, SU_WORDS), dtype=np.uint32)
    xs = jax.device_put(batch, shard_comm.shard_placement_sharding(mesh))
    # chunk axis over width: 8 chunks / 4 width devices = 2 resident
    # chunk rows per device, batch over stripe
    assert _shard_shapes(xs) == [(2, 2, SU_WORDS)] * 8


# ------------------------------------------------------------- padding


def test_pad_batch_pow2_is_single_pad(devs):
    # no mesh: plain next power of two
    assert [parallel.pad_batch_pow2(n) for n in (1, 2, 3, 5, 8, 9)] \
        == [1, 2, 4, 8, 8, 16]
    m6 = parallel.make_mesh(devs[:6], width=1)  # stripe axis 6
    # the old sequential shape double-padded: pow2(5)=8, then mesh
    # pad 8 -> 12; the folded pad lands on 6 (>=5, divisible by 6,
    # pow2 per-device share)
    assert parallel.pad_batch_pow2(5, m6) == 6
    assert parallel.pad_batch_pow2(7, m6) == 12
    assert parallel.pad_batch_pow2(13, m6) == 24
    m8 = parallel.make_mesh(devs, width=2)  # stripe axis 4
    # batch < devices: one stripe still pads to a full stripe row
    assert parallel.pad_batch_pow2(1, m8) == 4
    assert parallel.pad_batch_pow2(5, m8) == 8
    # every result divides the stripe axis and covers n
    for n in range(1, 40):
        for mesh in (m6, m8):
            p = parallel.pad_batch_pow2(n, mesh)
            assert p >= n and p % mesh.shape["stripe"] == 0
            # per-device share is a power of two (shape-bucketing cap)
            share = p // mesh.shape["stripe"]
            assert share & (share - 1) == 0


def test_pow2_pad_uses_mesh_aware_target(devs):
    from ceph_tpu.cluster.ecbatch import ECBatcher

    m6 = parallel.make_mesh(devs[:6], width=1)
    batch = np.zeros((5, K, SU_WORDS), dtype=np.uint32)
    assert len(ECBatcher._pow2_pad(batch)) == 8
    assert len(ECBatcher._pow2_pad(batch, m6)) == 6


def test_pad_chunk_axis_zero_extends_matrix_and_chunks():
    from ceph_tpu.parallel import shard_comm

    mat = np.arange(6, dtype=np.uint8).reshape(2, 3)
    chunks = np.ones((4, 3, SU_WORDS), dtype=np.uint32)
    m2, c2 = shard_comm.pad_chunk_axis(mat, chunks, 2)
    assert m2.shape == (2, 4) and (m2[:, 3] == 0).all()
    assert c2.shape == (4, 4, SU_WORDS) and (c2[:, 3] == 0).all()
    # already divisible: untouched objects pass through
    m1, c1 = shard_comm.pad_chunk_axis(mat, chunks, 3)
    assert m1 is mat and c1 is chunks
