"""Store layer tests: Transaction wire form, MemStore op conformance,
WalStore durability (the store_test.cc role, src/test/objectstore/
store_test.cc, run against every backend the same way the reference's
StoreTest is parameterized over memstore/bluestore)."""
import os

import numpy as np
import pytest

from ceph_tpu import native
from ceph_tpu.store import NotFound, StoreError
from ceph_tpu.store import transaction as tx
from ceph_tpu.store.memstore import MemStore
from ceph_tpu.store.walstore import WalStore
from ceph_tpu.utils import denc


def all_op_txn() -> tx.Transaction:
    """One transaction touching every opcode (order matters)."""
    t = tx.Transaction()
    t.create_collection("c")
    t.touch("c", b"a")
    t.write("c", b"a", 0, b"hello world")
    t.zero("c", b"a", 5, 3)
    t.truncate("c", b"a", 8)
    t.setattr("c", b"a", "k1", b"v1")
    t.setattrs("c", b"a", {"k2": b"v2", "k3": b"v3"})
    t.rmattr("c", b"a", "k3")
    t.clone("c", b"a", b"b")
    t.clone_range("c", b"a", b"r", 2, 4, 1)
    t.omap_setheader("c", b"a", b"HDR")
    t.omap_setkeys("c", b"a", {b"x": b"1", b"y": b"2", b"z": b"3"})
    t.omap_rmkeys("c", b"a", [b"z"])
    t.omap_rmkeyrange("c", b"a", b"y", b"yz")
    t.touch("c", b"tmp")
    t.remove("c", b"tmp")
    t.create_collection("dead")
    t.remove_collection("dead")
    return t


def check_all_op_state(s, extra_colls=()):
    # write "hello world" -> zero [5,8) -> truncate 8 = "hello\0\0\0"
    assert s.read("c", b"a") == b"hello\0\0\0"
    assert s.stat("c", b"a") == 8
    assert s.getattr("c", b"a", "k1") == b"v1"
    assert s.getattrs("c", b"a") == {"k1": b"v1", "k2": b"v2"}
    # clone happened after attrs/truncate but before omap writes
    assert s.read("c", b"b") == b"hello\0\0\0"
    assert s.getattrs("c", b"b") == {"k1": b"v1", "k2": b"v2"}
    # clone_range: src[2:6] = "llo\0" written at dst_off 1
    assert s.read("c", b"r") == b"\0llo\0"
    assert s.omap_get_header("c", b"a") == b"HDR"
    assert s.omap_get("c", b"a") == {b"x": b"1"}
    assert not s.exists("c", b"tmp")
    assert s.list_collections() == sorted(["c", *extra_colls])
    assert s.list_objects("c") == [b"a", b"b", b"r"]


def test_transaction_encode_roundtrip():
    t = all_op_txn()
    blob = t.encode()
    t2, off = tx.Transaction.decode(blob)
    assert off == len(blob)
    assert len(t2) == len(t)
    for a, b in zip(t.ops, t2.ops):
        assert (a.code, a.cid, a.oid, a.args) == (b.code, b.cid, b.oid, b.args)


def test_memstore_all_opcodes():
    s = MemStore()
    s.apply_transaction(all_op_txn())
    check_all_op_state(s)


def test_memstore_atomicity():
    """A failing op rolls back the whole transaction (all-or-nothing,
    the do_transaction contract)."""
    s = MemStore()
    t = tx.Transaction()
    t.create_collection("c")
    t.write("c", b"a", 0, b"first")
    s.apply_transaction(t)

    bad = tx.Transaction()
    bad.write("c", b"a", 0, b"SECOND")
    bad.remove("c", b"nonexistent")  # raises NotFound
    with pytest.raises(NotFound):
        s.queue_transaction(bad)
    assert s.read("c", b"a") == b"first"  # first op rolled back too

    bad2 = tx.Transaction()
    bad2.write("c", b"a", 0, b"X")
    bad2.remove_collection("c")  # not empty -> StoreError
    with pytest.raises(StoreError):
        s.queue_transaction(bad2)
    assert s.read("c", b"a") == b"first"


def test_memstore_errors():
    s = MemStore()
    with pytest.raises(NotFound):
        s.read("nope", b"x")
    t = tx.Transaction().create_collection("c")
    s.apply_transaction(t)
    with pytest.raises(NotFound):
        s.read("c", b"x")
    with pytest.raises(NotFound):
        s.getattr("c", b"x", "a")
    t2 = tx.Transaction().create_collection("c")
    with pytest.raises(StoreError):
        s.queue_transaction(t2)  # duplicate collection


def test_denc_roundtrips():
    assert denc.dec_u8(denc.enc_u8(0xAB), 0) == (0xAB, 1)
    assert denc.dec_u16(denc.enc_u16(0xABCD), 0) == (0xABCD, 2)
    assert denc.dec_u32(denc.enc_u32(0xDEADBEEF), 0) == (0xDEADBEEF, 4)
    assert denc.dec_u64(denc.enc_u64(2**61 + 5), 0) == (2**61 + 5, 8)
    assert denc.dec_i32(denc.enc_i32(-7), 0) == (-7, 4)
    assert denc.dec_i64(denc.enc_i64(-(2**40)), 0) == (-(2**40), 8)
    assert denc.dec_bytes(denc.enc_bytes(b"abc"), 0) == (b"abc", 7)
    assert denc.dec_str(denc.enc_str("héllo"), 0)[0] == "héllo"
    xs = [b"a", b"bb", b""]
    assert denc.dec_list(denc.enc_list(xs, denc.enc_bytes), 0,
                         denc.dec_bytes)[0] == xs
    d = {b"k": b"v", b"": b"x"}
    assert denc.dec_map(denc.enc_map(d, denc.enc_bytes, denc.enc_bytes),
                        0, denc.dec_bytes, denc.dec_bytes)[0] == d
    with pytest.raises(denc.DecodeError):
        denc.dec_u32(b"\x01\x02", 0)  # truncated


def test_split_merge_collections():
    """PG split/merge (Transaction split_collection/merge_collection
    roles): objects partition by hash bits and reunite on merge."""
    from ceph_tpu.placement.osdmap import ceph_str_hash_rjenkins

    s = MemStore()
    t = tx.Transaction().create_collection("1.0")
    oids = [b"obj%d" % i for i in range(32)]
    for oid in oids:
        t.write("1.0", oid, 0, oid)
    s.apply_transaction(t)
    t2 = tx.Transaction().create_collection("1.1")
    t2.split_collection("1.0", bits=1, rem=1, dest="1.1")
    s.apply_transaction(t2)
    left = set(s.list_objects("1.0"))
    right = set(s.list_objects("1.1"))
    assert left | right == set(oids) and not (left & right)
    assert all(ceph_str_hash_rjenkins(o) & 1 == 0 for o in left)
    assert all(ceph_str_hash_rjenkins(o) & 1 == 1 for o in right)
    for oid in right:
        assert s.read("1.1", oid) == oid  # data moved intact
    # merge back reunites and removes the source
    t3 = tx.Transaction().merge_collection("1.1", dest="1.0")
    s.apply_transaction(t3)
    assert set(s.list_objects("1.0")) == set(oids)
    assert "1.1" not in s.list_collections()
    # wire round-trip of the new opcodes
    t4 = tx.Transaction()
    t4.split_collection("1.0", 2, 3, "1.3")
    t4.merge_collection("1.3", "1.0", bits=2)
    t4.set_alloc_hint("1.0", b"obj0", 1 << 22, 4096, flags=3)
    t5, used = tx.Transaction.decode(t4.encode())
    assert used == len(t4.encode())
    assert [op.code for op in t5.ops] == [
        tx.OP_SPLIT_COLL, tx.OP_MERGE_COLL, tx.OP_SETALLOCHINT
    ]


def test_split_merge_atomicity_with_preexisting_dest():
    """A rejected transaction must not leak objects into a PRE-EXISTING
    destination collection (the shadow must clone dest_cid too)."""
    s = MemStore()
    t = tx.Transaction()
    t.create_collection("1.0")
    t.create_collection("1.1")
    for i in range(8):
        t.write("1.1", b"o%d" % i, 0, b"x")
    s.apply_transaction(t)
    bad = tx.Transaction().merge_collection("1.1", dest="1.0")
    bad.remove("1.0", b"nope")  # fails -> whole txn rolls back
    with pytest.raises(NotFound):
        s.queue_transaction(bad)
    assert s.list_objects("1.0") == []  # nothing leaked into live dest
    assert len(s.list_objects("1.1")) == 8
    bad2 = tx.Transaction().split_collection("1.1", 1, 1, "1.0")
    bad2.remove("1.0", b"nope")
    with pytest.raises(NotFound):
        s.queue_transaction(bad2)
    assert s.list_objects("1.0") == []
    assert len(s.list_objects("1.1")) == 8


def test_set_alloc_hint_recorded():
    s = MemStore()
    t = tx.Transaction().create_collection("c")
    t.set_alloc_hint("c", b"new", 4 << 20, 64 << 10)
    t.write("c", b"new", 0, b"data")
    s.apply_transaction(t)
    hint = s.getattr("c", b"new", "_alloc_hint")
    assert int.from_bytes(hint[:8], "little") == 4 << 20
    assert int.from_bytes(hint[8:16], "little") == 64 << 10


# ------------------------------------------------------------- WalStore


def make_walstore(tmp_path, **kw) -> WalStore:
    s = WalStore(str(tmp_path / "store"), **kw)
    s.mount()
    return s


def test_walstore_all_opcodes(tmp_path):
    s = make_walstore(tmp_path)
    s.apply_transaction(all_op_txn())
    check_all_op_state(s)
    s.umount()


def test_walstore_replay_after_crash(tmp_path):
    """kill -9 mid-life: reopen WITHOUT umount; WAL replay must restore
    everything (the BlueStore deferred-replay contract)."""
    s = make_walstore(tmp_path)
    s.apply_transaction(all_op_txn())
    t = tx.Transaction().create_collection("c2")
    t.write("c2", b"late", 0, b"not checkpointed")
    s.apply_transaction(t)
    # no umount: simulates SIGKILL (state only in WAL, no snapshot)
    s2 = make_walstore(tmp_path)
    check_all_op_state(s2, extra_colls=["c2"])
    assert s2.read("c2", b"late") == b"not checkpointed"
    s2.umount()


def test_walstore_snapshot_plus_wal(tmp_path):
    s = make_walstore(tmp_path)
    s.apply_transaction(all_op_txn())
    s.compact()  # snapshot; WAL truncated
    t = tx.Transaction().create_collection("c2")
    t.write("c2", b"post", 0, b"after snap")
    s.apply_transaction(t)
    s2 = make_walstore(tmp_path)  # crash-reopen: snapshot + 1 WAL record
    check_all_op_state(s2, extra_colls=["c2"])
    assert s2.read("c2", b"post") == b"after snap"
    s2.umount()


def test_walstore_torn_tail(tmp_path):
    """A record cut mid-append (torn write) is discarded; every record
    before it survives."""
    s = make_walstore(tmp_path)
    t1 = tx.Transaction().create_collection("c")
    t1.write("c", b"a", 0, b"durable")
    s.apply_transaction(t1)
    t2 = tx.Transaction().write("c", b"a", 0, b"torn away")
    s.apply_transaction(t2)
    wal = os.path.join(s.path, "wal.log")
    size = os.path.getsize(wal)
    with open(wal, "r+b") as f:
        f.truncate(size - 3)  # cut into the last record
    s2 = make_walstore(tmp_path)
    assert s2.read("c", b"a") == b"durable"
    s2.umount()


def test_walstore_corrupt_tail_crc(tmp_path):
    s = make_walstore(tmp_path)
    t1 = tx.Transaction().create_collection("c")
    t1.write("c", b"a", 0, b"good")
    s.apply_transaction(t1)
    t2 = tx.Transaction().write("c", b"b", 0, b"flipped")
    s.apply_transaction(t2)
    wal = os.path.join(s.path, "wal.log")
    with open(wal, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        last = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([last[0] ^ 0xFF]))
    s2 = make_walstore(tmp_path)
    assert s2.read("c", b"a") == b"good"
    assert not s2.exists("c", b"b")  # corrupt record dropped
    s2.umount()


def test_walstore_torn_tail_then_more_writes(tmp_path):
    """Mount must truncate a torn tail before appending: records written
    after the first crash stay reachable across a second crash."""
    s = make_walstore(tmp_path)
    t1 = tx.Transaction().create_collection("c")
    t1.write("c", b"a", 0, b"one")
    s.apply_transaction(t1)
    s.apply_transaction(tx.Transaction().write("c", b"a", 0, b"gone"))
    wal = os.path.join(s.path, "wal.log")
    with open(wal, "r+b") as f:
        f.truncate(os.path.getsize(wal) - 2)  # tear the second record
    s2 = make_walstore(tmp_path)  # crash-reopen #1
    assert s2.read("c", b"a") == b"one"
    t2 = tx.Transaction().write("c", b"b", 0, b"two")
    s2.apply_transaction(t2)
    s3 = make_walstore(tmp_path)  # crash-reopen #2
    assert s3.read("c", b"a") == b"one"
    assert s3.read("c", b"b") == b"two"
    s3.umount()


def test_walstore_crash_inside_compact(tmp_path):
    """Crash between snapshot publish and WAL truncate: replay must skip
    the pre-snapshot records (seq watermark), not double-apply them."""
    s = make_walstore(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"v1")
    s.apply_transaction(t)
    s.apply_transaction(tx.Transaction().write("c", b"a", 0, b"v2"))
    # simulate the torn compact: publish the snapshot but leave the WAL
    snap_blob = s._encode_snapshot()
    with open(os.path.join(s.path, "snap"), "wb") as f:
        f.write(snap_blob)
    s2 = make_walstore(tmp_path)  # crash-reopen
    assert s2.read("c", b"a") == b"v2"
    # and it keeps working: new writes land after the stale records
    s2.apply_transaction(tx.Transaction().write("c", b"a", 0, b"v3"))
    s3 = make_walstore(tmp_path)
    assert s3.read("c", b"a") == b"v3"
    s3.umount()


def test_walstore_snapshot_csum_detects_corruption(tmp_path):
    """Blob checksums (calc_csum/verify_csum role) catch bit rot in the
    checkpoint file."""
    # compression off so raw data bytes are findable in the snapshot
    s = WalStore(str(tmp_path / "store"), compression=None)
    s.mount()
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"Z" * 10000)
    s.apply_transaction(t)
    s.umount()  # compacts -> snapshot holds the data
    snap = os.path.join(str(tmp_path / "store"), "snap")
    blob = bytearray(open(snap, "rb").read())
    idx = blob.find(b"Z" * 100)
    assert idx > 0
    blob[idx + 50] ^= 0x01
    open(snap, "wb").write(bytes(blob))
    s2 = WalStore(str(tmp_path / "store"), compression=None)
    with pytest.raises(StoreError, match="csum mismatch"):
        s2.mount()


def test_walstore_rejected_txn_not_logged(tmp_path):
    """A transaction that fails validation must not pollute the WAL."""
    s = make_walstore(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.write("c", b"a", 0, b"ok")
    s.apply_transaction(t)
    bad = tx.Transaction().remove("c", b"ghost")
    with pytest.raises(NotFound):
        s.queue_transaction(bad)
    s2 = make_walstore(tmp_path)  # crash-reopen replays the log
    assert s2.read("c", b"a") == b"ok"
    s2.umount()


def test_walstore_auto_compact(tmp_path):
    s = WalStore(str(tmp_path / "store"), wal_compact_bytes=256)
    s.mount()
    t = tx.Transaction().create_collection("c")
    s.apply_transaction(t)
    for i in range(20):
        t = tx.Transaction().write("c", b"o%d" % i, 0, b"x" * 64)
        s.apply_transaction(t)
    if s._compactor is not None:
        s._compactor.join()  # compaction runs off the commit thread
    assert os.path.getsize(os.path.join(s.path, "wal.log")) < 4096
    assert os.path.exists(os.path.join(s.path, "snap"))
    s2 = make_walstore(tmp_path)
    for i in range(20):
        assert s2.read("c", b"o%d" % i) == b"x" * 64
    s2.umount()


def test_walstore_empty_object_and_omap_snapshot(tmp_path):
    s = make_walstore(tmp_path)
    t = tx.Transaction().create_collection("c")
    t.touch("c", b"empty")
    t.omap_setkeys("c", b"empty", {b"k": b"v"})
    t.omap_setheader("c", b"empty", b"H")
    s.apply_transaction(t)
    s.umount()
    s2 = make_walstore(tmp_path)
    assert s2.stat("c", b"empty") == 0
    assert s2.omap_get("c", b"empty") == {b"k": b"v"}
    assert s2.omap_get_header("c", b"empty") == b"H"
    s2.umount()
