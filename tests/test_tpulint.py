"""tpulint: per-rule fixtures + the whole-repo tier-1 gate.

Each rule family gets positive fixtures (the hazard MUST fire) and
negative fixtures (the idiomatic form MUST stay clean — false-positive
regression guards). The gate at the bottom runs the full analyzer over
ceph_tpu/ and tools/ against the committed baseline: any NEW finding
fails tier-1, which is the whole point of the pass.
"""
import textwrap
from pathlib import Path

import pytest

from ceph_tpu import analysis

REPO = Path(__file__).resolve().parents[1]
BASELINE = REPO / "tools" / "tpulint_baseline.json"


def lint(src: str, path: str, only=None):
    return analysis.lint_source(textwrap.dedent(src), path, only)


def msgs(findings):
    return [f.message for f in findings]


# ---------------------------------------------------------- trace-safety


def test_trace_decorated_jit_host_sync_fires():
    out = lint(
        """
        import jax

        @jax.jit
        def kernel(x):
            n = x.sum().item()
            print(n)
            return x * n
        """,
        "ceph_tpu/ops/fixture.py", only=["trace-safety"])
    assert any(".item()" in m for m in msgs(out))
    assert any("print" in m for m in msgs(out))


def test_trace_assigned_jit_and_partial_binding():
    # jax.jit(partial(f, host_const)): the bound arg is static, so
    # int() on it is fine; int() on the traced arg fires.
    out = lint(
        """
        import functools, jax

        def kernel(matrix, chunks):
            c = int(matrix[0, 0])   # static: partial-bound
            k = int(chunks[0])      # traced: must fire
            return chunks * c + k

        _jit = jax.jit(functools.partial(kernel, M))
        """,
        "ceph_tpu/ops/fixture.py", only=["trace-safety"])
    assert len(out) == 1
    assert "`int()` on a traced value" in out[0].message


def test_trace_static_argnames_suppresses():
    out = lint(
        """
        import jax

        def run(xs, static):
            return xs * int(static)

        run_jit = jax.jit(run, static_argnames=("static",))
        """,
        "ceph_tpu/placement/fixture.py", only=["trace-safety"])
    assert out == []


def test_trace_self_mutation_and_np_asarray_fire():
    out = lint(
        """
        import jax
        import numpy as np

        class Engine:
            @jax.jit
            def step(self, x):
                self.count = self.count + 1
                return np.asarray(x)
        """,
        "ceph_tpu/ops/fixture.py", only=["trace-safety"])
    assert any("mutation of `self.count`" in m for m in msgs(out))
    assert any("np.asarray" in m for m in msgs(out))


def test_trace_unhashable_static_argnums():
    out = lint(
        """
        import jax

        def f(x, n):
            return x

        g = jax.jit(f, static_argnums=[1])
        """,
        "ceph_tpu/ops/fixture.py", only=["trace-safety"])
    assert any("unhashable" in m for m in msgs(out))


def test_trace_shape_metadata_access_is_clean():
    # int(x.shape[0]) is static metadata, not a concretization
    out = lint(
        """
        import jax

        @jax.jit
        def f(x):
            n = int(x.shape[0]) * int(x.ndim)
            return x.reshape(n)
        """,
        "ceph_tpu/ops/fixture.py", only=["trace-safety"])
    assert out == []


def test_trace_reactor_readback_fires():
    """The regression the fused EC pipeline must never reintroduce: a
    blocking np.asarray readback of a batched device dispatch placed on
    the reactor thread (an async def) — it stalls the whole daemon for
    the transfer+execution round trip."""
    out = lint(
        """
        import numpy as np

        class PG:
            async def write_stripes(self, codec, batch):
                return np.asarray(codec.encode_batch(batch))

            async def rebuild(self, codec, present, surv):
                return np.asarray(codec.decode_batch(present, surv))
        """,
        "ceph_tpu/cluster/fixture.py", only=["trace-safety"])
    assert len(out) == 2
    assert all("reactor thread" in m for m in msgs(out))
    assert {f.symbol for f in out} == {"PG.write_stripes", "PG.rebuild"}


def test_trace_reactor_readback_in_executor_is_clean():
    # the idiomatic shape (cluster/ecbatch.py): dispatch + readback in
    # a SYNC helper that the async side runs on an executor worker
    out = lint(
        """
        import numpy as np

        class Batcher:
            @staticmethod
            def _encode_sync(codec, batch):
                return np.asarray(codec.encode_batch(batch))

            async def encode(self, loop, codec, batch):
                return await loop.run_in_executor(
                    None, self._encode_sync, codec, batch)
        """,
        "ceph_tpu/cluster/fixture.py", only=["trace-safety"])
    assert out == []


def test_trace_reactor_readback_skips_nested_sync_def():
    # a sync closure defined inside an async fn runs wherever it is
    # called (e.g. handed to an executor) — not on the reactor per se
    out = lint(
        """
        import numpy as np

        class Batcher:
            async def encode(self, loop, codec, batch):
                def work():
                    return np.asarray(codec.encode_batch(batch))
                return await loop.run_in_executor(None, work)
        """,
        "ceph_tpu/cluster/fixture.py", only=["trace-safety"])
    assert out == []


def test_trace_clean_kernel_is_clean():
    # the idiom of ops/crc32c.py: shape access, astype, while loop
    out = lint(
        """
        import jax
        import jax.numpy as jnp

        def _crc0(words):
            w = words.shape[-1]
            c = words.astype(jnp.uint32)
            return c[..., 0]

        _jit = jax.jit(_crc0)
        """,
        "ceph_tpu/ops/fixture.py", only=["trace-safety"])
    assert out == []


def test_trace_real_kernels_are_clean():
    for rel in ("ceph_tpu/ops/crc32c.py", "ceph_tpu/ops/rs.py",
                "ceph_tpu/ops/crush.py"):
        src = (REPO / rel).read_text(encoding="utf-8")
        assert lint(src, rel, only=["trace-safety"]) == []


# ----------------------------------------------------------------- dtype


def test_dtype_missing_dtype_fires_only_in_scope():
    src = """
        import numpy as np

        def make():
            return np.zeros(16)
        """
    assert msgs(lint(src, "ceph_tpu/ec/fixture.py", only=["dtype"]))
    assert msgs(lint(src, "ceph_tpu/checksum/fixture.py",
                     only=["dtype"]))
    # out of scope: the RGW frontend may allocate floats freely
    assert lint(src, "ceph_tpu/services/fixture.py",
                only=["dtype"]) == []


def test_dtype_positional_and_kw_dtype_are_clean():
    out = lint(
        """
        import numpy as np
        import jax.numpy as jnp

        def make():
            a = np.zeros(16, np.uint8)
            b = jnp.zeros((), jnp.uint32)
            c = np.frombuffer(b"xy", dtype=np.uint8)
            return a, b, c
        """,
        "ceph_tpu/ec/fixture.py", only=["dtype"])
    assert out == []


def test_dtype_float_dtype_fires():
    out = lint(
        """
        import numpy as np

        def make(x):
            a = np.zeros(4, dtype=np.float32)
            b = x.astype(float)
            return a, b
        """,
        "ceph_tpu/placement/fixture.py", only=["dtype"])
    assert any("float dtype" in m for m in msgs(out))
    assert any("astype" in m for m in msgs(out))


def test_dtype_gf2_kernel_wide_int_fires():
    """GF(2) bit-plane kernels (ops/gf2.py scope): a 64-bit lane
    promotion inside the jitted kernel fires — XOR/popcount lanes must
    stay uint8/uint32 with int32 gather indices."""
    src = """
        import jax.numpy as jnp
        import numpy as np

        def gf2_apply(plan, rows):
            acc = rows.astype(jnp.int64)
            idx = np.zeros((4, 4), dtype=np.uint64)
            return acc, idx
        """
    out = lint(src, "ceph_tpu/ops/gf2.py", only=["dtype"])
    assert any("64 bits" in m or "64-bit" in m for m in msgs(out))
    assert sum(("64" in m) for m in msgs(out)) == 2
    # positional dtype is checked too (np.zeros(n, np.int64))
    out_pos = lint(
        """
        import numpy as np

        def xor_plan(m):
            return np.zeros(8, np.int64)
        """,
        "ceph_tpu/ops/gf2.py", only=["dtype"])
    assert any("64-bit" in m for m in msgs(out_pos))
    # ctor-without-dtype applies in the gf2 scope too
    out2 = lint(
        """
        import numpy as np

        def xor_plan(m):
            return np.zeros(8)
        """,
        "ceph_tpu/ops/gf2.py", only=["dtype"])
    assert any("explicit dtype" in m for m in msgs(out2))


def test_dtype_gf2_kernel_clean_and_arith_exempt():
    """The idiomatic uint32 gather+XOR shape stays clean — including
    the index/shape arithmetic the GF(2^8) operator check would flag
    (GF(2) work is XOR by construction; `*` there is indexing math,
    not a missing table lookup). The real kernel module must lint
    clean end to end."""
    out = lint(
        """
        import jax.numpy as jnp
        import numpy as np

        def gf2_encode_cells(plan, w, data):
            c, words = data.shape[-2], data.shape[-1]
            rows = data.reshape(c * w, words // w)
            acc = rows.astype(jnp.uint32)
            idx = np.zeros((4, 4), dtype=np.int32)
            return acc, idx
        """,
        "ceph_tpu/ops/gf2.py", only=["dtype"])
    assert out == []
    rel = "ceph_tpu/ops/gf2.py"
    src = (REPO / rel).read_text(encoding="utf-8")
    assert lint(src, rel, only=["dtype"]) == []


def test_dtype_gf_arithmetic_fires():
    out = lint(
        """
        def gf_mul_table(a, b):
            return a * b
        """,
        "ceph_tpu/ec/fixture.py", only=["dtype"])
    assert any("XOR / table lookups" in m for m in msgs(out))
    # same code outside a GF-named context is arithmetic, not a field op
    out2 = lint(
        """
        def scale(a, b):
            return a * b
        """,
        "ceph_tpu/ec/fixture.py", only=["dtype"])
    assert out2 == []


# ----------------------------------------------------------- wire-parity


def test_wire_parity_symmetric_pair_is_clean():
    out = lint(
        """
        from ..utils import denc

        def encode_thing(t):
            return denc.enc_u32(t.a) + denc.enc_str(t.b)

        def decode_thing(buf, off=0):
            a, off = denc.dec_u32(buf, off)
            b, off = denc.dec_str(buf, off)
            return (a, b), off
        """,
        "ceph_tpu/placement/encoding.py", only=["wire-parity"])
    assert out == []


def test_wire_parity_missing_field_fires():
    out = lint(
        """
        from ..utils import denc

        def encode_thing(t):
            return (denc.enc_u32(t.a) + denc.enc_str(t.b)
                    + denc.enc_u64(t.c))

        def decode_thing(buf, off=0):
            a, off = denc.dec_u32(buf, off)
            b, off = denc.dec_str(buf, off)
            return (a, b), off
        """,
        "ceph_tpu/placement/encoding.py", only=["wire-parity"])
    assert len(out) == 1
    assert "encoder-only kinds: u64x1" in out[0].message


def test_wire_parity_struct_arity_mismatch_fires():
    out = lint(
        """
        import struct

        _HDR = struct.Struct("<IHHI")

        def encode_frame(f):
            return _HDR.pack(1, f.type, f.flags, len(f.payload))

        def decode_frame(buf):
            magic, ftype, flags = _HDR.unpack_from(buf, 0)
            return ftype, flags
        """,
        "ceph_tpu/msg/frames.py", only=["wire-parity"])
    assert any("wire skew" in m for m in msgs(out))


def test_wire_parity_unrelated_struct_formats_do_not_collide():
    # two independent module-level struct codecs with different
    # formats must not be compared against each other
    out = lint(
        """
        import struct

        def enc_a(x):
            return struct.pack("<I", x)

        def dec_a(buf):
            (x,) = struct.unpack("<I", buf)
            return x

        def dec_b(buf):
            a, b = struct.unpack("<HH", buf)
            return a, b
        """,
        "ceph_tpu/msg/frames.py", only=["wire-parity"])
    assert out == []


def test_wire_parity_real_wire_layer_is_clean():
    for rel in ("ceph_tpu/placement/encoding.py",
                "ceph_tpu/msg/frames.py", "ceph_tpu/msg/messages.py"):
        src = (REPO / rel).read_text(encoding="utf-8")
        assert lint(src, rel, only=["wire-parity"]) == []


# ------------------------------------------------------- lock-discipline


def test_lock_unguarded_shared_write_fires():
    out = lint(
        """
        import asyncio

        class Daemon:
            def __init__(self):
                self._lock = asyncio.Lock()
                self.epoch = 0

            async def commit(self, e):
                async with self._lock:
                    self.epoch = e

            async def sneaky(self, e):
                self.epoch = e
        """,
        "ceph_tpu/cluster/fixture.py", only=["lock-discipline"])
    assert len(out) == 1
    assert out[0].symbol == "Daemon.sneaky"
    assert "outside the lock" in out[0].message


def test_lock_init_writes_are_exempt():
    out = lint(
        """
        import asyncio

        class Daemon:
            def __init__(self):
                self._lock = asyncio.Lock()
                self.epoch = 0

            async def commit(self, e):
                async with self._lock:
                    self.epoch = e
        """,
        "ceph_tpu/cluster/fixture.py", only=["lock-discipline"])
    assert out == []


def test_lock_blocking_call_under_lock_fires():
    out = lint(
        """
        import asyncio, time

        class Daemon:
            def __init__(self):
                self._lock = asyncio.Lock()
                self.n = 0

            async def tick(self):
                async with self._lock:
                    time.sleep(1)
                    self.n += 1
        """,
        "ceph_tpu/cluster/fixture.py", only=["lock-discipline"])
    assert any("time.sleep" in m for m in msgs(out))


def test_lock_mu_hint_is_suffix_only():
    # `xattr_muts` is a data dict, not a lock; treating it as one
    # would EXEMPT unlocked writes to it from the shared-state check
    out = lint(
        """
        import asyncio

        class PG:
            def __init__(self):
                self.lock = asyncio.Lock()
                self.xattr_muts = {}

            async def record(self, k, v):
                async with self.lock:
                    self.xattr_muts = {k: v}

            async def sneaky(self, k, v):
                self.xattr_muts = {k: v}
        """,
        "ceph_tpu/cluster/fixture.py", only=["lock-discipline"])
    assert len(out) == 1 and out[0].symbol == "PG.sneaky"


def test_lock_fault_hook_awaited_under_lock_fires():
    """The fault-plane extension: an AWAITED fault hook while holding
    a PG lock turns an injected one-op pause into a whole-PG stall
    with the lock pinned — must fire."""
    out = lint(
        """
        import asyncio

        class PG:
            def __init__(self):
                self.lock = asyncio.Lock()

            async def do_op(self, osd):
                async with self.lock:
                    await osd.fault.pause("op_delay")
        """,
        "ceph_tpu/cluster/fixture.py", only=["lock-discipline"])
    assert len(out) == 1
    assert "fault-injection hook" in out[0].message
    assert out[0].symbol == "PG.do_op"


def test_lock_fault_hook_sync_or_outside_lock_is_clean():
    # sync hit() under a lock is one dict lookup (fine); awaiting the
    # hook OUTSIDE the lock is the idiomatic placement (osd._client_op)
    out = lint(
        """
        import asyncio

        class PG:
            def __init__(self):
                self.lock = asyncio.Lock()

            async def do_op(self, osd):
                await osd.fault.pause("op_delay")
                async with self.lock:
                    if osd.fault.hit("eio"):
                        raise IOError("injected")
        """,
        "ceph_tpu/cluster/fixture.py", only=["lock-discipline"])
    assert out == []


def test_lock_out_of_scope_dir_is_ignored():
    out = lint(
        """
        import asyncio, time

        class Frontend:
            def __init__(self):
                self._lock = asyncio.Lock()
                self.n = 0

            async def tick(self):
                self.n += 1
        """,
        "ceph_tpu/services/fixture.py", only=["lock-discipline"])
    assert out == []


# ------------------------------------------------------------- registry


def test_registry_rejects_duplicates_and_lists_rules():
    analysis.preload()
    reg = analysis.instance()
    assert set(reg.names()) >= {
        "trace-safety", "dtype", "wire-parity", "lock-discipline"}
    with pytest.raises(KeyError):
        reg.add("dtype", analysis.Rule)
    with pytest.raises(KeyError):
        reg.get("no-such-rule")


def test_unknown_rule_id_raises():
    with pytest.raises(KeyError):
        analysis.lint_source("x = 1", "ceph_tpu/ec/f.py",
                             only=["bogus"])


# ------------------------------------------------------------- baseline


def test_baseline_roundtrip_and_ratchet(tmp_path):
    f1 = analysis.Finding("dtype", "a.py", 3, "f", "m1")
    f2 = analysis.Finding("dtype", "a.py", 9, "f", "m1")  # same key
    f3 = analysis.Finding("dtype", "a.py", 5, "g", "m2")
    p = tmp_path / "b.json"
    analysis.save_baseline(p, [f1, f2])
    base = analysis.load_baseline(p)
    # both grandfathered occurrences pass; a third same-key finding
    # and any new key fail
    assert analysis.unbaselined([f1, f2], base) == []
    assert analysis.unbaselined([f1, f2, f2, f3], base) == [f2, f3]
    # missing baseline file == empty baseline
    assert analysis.load_baseline(tmp_path / "nope.json") == {}


def test_update_baseline_ignores_filters(tmp_path):
    """A filtered run (`--rules dtype ceph_tpu/ec --update-baseline`)
    must still write the FULL baseline — honoring the filters would
    silently erase every other grandfathered entry."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "tpulint_cli", REPO / "tools" / "tpulint.py")
    cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cli)
    out = tmp_path / "b.json"
    rc = cli.main(["--rules", "dtype", "ceph_tpu/ec",
                   "--baseline", str(out), "--update-baseline"])
    assert rc == 0
    written = analysis.load_baseline(out)
    committed = analysis.load_baseline(BASELINE)
    assert written == committed


# ------------------------------------------------------- send-discipline


def test_send_discipline_per_frame_drain_fires():
    out = lint(
        """
        async def _send_all(self, writer, frames):
            for f in frames:
                writer.write(f)
                await writer.drain()
        """,
        "ceph_tpu/msg/fixture.py", only=["send-discipline"])
    assert len(out) == 1
    assert "per-frame" in out[0].message


def test_send_discipline_corked_writer_allowlisted():
    # the corked writer's drain-per-BURST loop is the one legal shape
    out = lint(
        """
        async def _writer_bursts(self, dst, evt, items):
            while True:
                writer.write(b"".join(take_all(self)))
                await writer.drain()
        """,
        "ceph_tpu/msg/fixture.py", only=["send-discipline"])
    assert out == []


def test_send_discipline_handshake_single_drain_clean():
    # one frame, one drain, no loop (the auth handshake shape)
    out = lint(
        """
        async def _connect(self, dst):
            writer.write(hello)
            await writer.drain()
        """,
        "ceph_tpu/msg/fixture.py", only=["send-discipline"])
    assert out == []


def test_send_discipline_scoped_to_msg_layer():
    # a drain loop outside ceph_tpu/msg/ is not this rule's business
    out = lint(
        """
        async def pump(writer, frames):
            for f in frames:
                writer.write(f)
                await writer.drain()
        """,
        "ceph_tpu/cluster/fixture.py", only=["send-discipline"])
    assert out == []


def test_send_discipline_wal_flush_fires():
    out = lint(
        """
        import os

        class S:
            def queue_transaction(self, rec):
                self._wal.write(rec)
                self._wal.flush()
                os.fsync(self._wal.fileno())
        """,
        "ceph_tpu/store/fixture.py", only=["send-discipline"])
    assert len(out) == 2
    assert all("group-commit" in m for m in msgs(out))


def test_send_discipline_committer_hook_clean():
    out = lint(
        """
        import os

        class S:
            def _flush_wal(self):
                self._wal.flush()
                os.fsync(self._wal.fileno())

            def compact(self):
                self._wal.truncate(0)
                os.fsync(self._wal.fileno())
        """,
        "ceph_tpu/store/fixture.py", only=["send-discipline"])
    assert out == []


# ----------------------------------------------------- buffer-discipline


def test_buffer_discipline_payload_coercion_fires():
    out = lint(
        """
        def ship(conn, payload):
            wire = bytes(payload)
            conn.send(wire)
        """,
        "ceph_tpu/msg/fixture.py", only=["buffer-discipline"])
    assert len(out) == 1
    assert "payload coercion" in out[0].message


def test_buffer_discipline_tobytes_fires_in_cluster_hot_path():
    out = lint(
        """
        def stage(t, cid, oid, rows):
            t.write(cid, oid, 0, rows.tobytes())
        """,
        "ceph_tpu/cluster/fixture.py", only=["buffer-discipline"])
    assert len(out) == 1
    assert ".tobytes()" in out[0].message


def test_buffer_discipline_identity_and_alloc_clean():
    # oid/name coercions and size allocations are not payload copies
    out = lint(
        """
        def route(name, n):
            oid = bytes(name)
            pad = bytes(16)
            return oid, pad
        """,
        "ceph_tpu/msg/fixture.py", only=["buffer-discipline"])
    assert out == []


def test_buffer_discipline_flatten_boundary_clean():
    # the buffer plane's own flatten entry points may materialize
    out = lint(
        """
        class BL:
            def flatten(self, payload):
                return bytes(payload)

        def _send_now(self, payload):
            return bytes(payload)
        """,
        "ceph_tpu/msg/fixture.py", only=["buffer-discipline"])
    assert out == []


def test_buffer_discipline_scoped_to_hot_paths():
    # control-plane / services code is out of scope
    out = lint(
        """
        def archive(payload):
            return bytes(payload)
        """,
        "ceph_tpu/services/fixture.py", only=["buffer-discipline"])
    assert out == []


# ------------------------------------------------------ mesh-discipline


def test_mesh_discipline_device_get_fires():
    out = lint(
        """
        import jax

        def collect(parity):
            return jax.device_get(parity)
        """,
        "ceph_tpu/parallel/fixture.py", only=["mesh-discipline"])
    assert len(out) == 1
    assert "jax.device_get" in out[0].message


def test_mesh_discipline_whole_array_asarray_fires_in_batcher():
    out = lint(
        """
        import numpy as np

        class ECBatcher:
            def _mesh_encode_sync(self, codec, cells, mesh):
                parity, crcs = codec.encode_crc_batch_mesh(cells, 1, mesh)
                return np.asarray(parity), np.asarray(crcs)
        """,
        "ceph_tpu/cluster/ecbatch.py", only=["mesh-discipline"])
    assert len(out) == 2
    assert all("per-device shard views" in m for m in msgs(out))


def test_mesh_discipline_sanctioned_boundaries_clean():
    # the per-device view reader, the counted gather, and the single-
    # device engine boundary may materialize; device-list helpers too
    out = lint(
        """
        import numpy as np

        def shard_rows_to_host(arr, out=None):
            for shard in arr.addressable_shards:
                out[shard.index] = np.asarray(shard.data)
            return out

        def host_gather(arr):
            return np.asarray(arr)

        def make_mesh(devices, width):
            return np.array(devices).reshape(-1, width)

        class ECBatcher:
            def _encode_sync(self, codec, cells):
                return np.asarray(codec.encode_batch(cells))
        """,
        "ceph_tpu/parallel/fixture.py", only=["mesh-discipline"])
    assert out == []


def test_mesh_discipline_scoped_to_mesh_path():
    # np.asarray outside parallel/ and the batcher is other rules'
    # business (e.g. trace-safety's reactor-readback check)
    out = lint(
        """
        import numpy as np

        def collect(parity):
            return np.asarray(parity)
        """,
        "ceph_tpu/cluster/pg.py", only=["mesh-discipline"])
    assert out == []


# ------------------------------------------------- dispatch-discipline


def test_dispatch_discipline_host_placement_on_client_fires():
    out = lint(
        """
        class Client:
            def _calc(self, pgid):
                up, p = self.osdmap.pg_to_up_acting_osds(pgid)
                return p
        """,
        "ceph_tpu/cluster/client.py", only=["dispatch-discipline"])
    assert len(out) == 1
    assert "batched PlacementResolver" in out[0].message


def test_dispatch_discipline_memo_ctor_and_do_rule_fire_in_osdc():
    out = lint(
        """
        from ceph_tpu.placement.osdmap import PlacementMemo

        class Striper:
            def __init__(self):
                self._memo = PlacementMemo()

            def place(self, crush, rule, pps, size, w):
                return crush.do_rule(rule, pps, size, w)
        """,
        "ceph_tpu/osdc/striper.py", only=["dispatch-discipline"])
    msgs_ = msgs(out)
    assert any("PlacementMemo" in m for m in msgs_)
    assert any("do_rule" in m for m in msgs_)


def test_dispatch_discipline_resolver_path_clean():
    out = lint(
        """
        class Client:
            async def _acalc(self, pgid):
                up, p = await self._placement.aup_acting(self.osdmap,
                                                         pgid)
                return p

            def _calc(self, pgid):
                up, p = self._placement.up_acting(self.osdmap, pgid)
                return p
        """,
        "ceph_tpu/cluster/client.py", only=["dispatch-discipline"])
    assert out == []


def test_dispatch_discipline_scoped_to_client_tier():
    # daemons/mon/tools legitimately call the map directly
    out = lint(
        """
        def scan(self, pgid):
            return self.osdmap.pg_to_up_acting_osds(pgid)
        """,
        "ceph_tpu/cluster/osd.py", only=["dispatch-discipline"])
    assert out == []


def test_trace_bulk_crush_readback_on_reactor_fires():
    # the serving-path extension: materializing a bulk-CRUSH dispatch
    # on the reactor thread is the same hazard as a codec readback
    out = lint(
        """
        import numpy as np

        class Resolver:
            async def _run_batch(self, compiled, rule, xs, n, w):
                return np.asarray(
                    bulk.do_rule_bulk(compiled, rule, xs, n, w))
        """,
        "ceph_tpu/placement/fixture.py", only=["trace-safety"])
    assert any("do_rule_bulk" in m for m in msgs(out))


def test_trace_bulk_crush_executor_shape_clean():
    # the resolver's real shape: sync worker fn, run_in_executor
    out = lint(
        """
        import numpy as np

        class Resolver:
            @staticmethod
            def _bulk_sync(compiled, rule, xs, n, w):
                out = bulk.do_rule_bulk(compiled, rule, xs, n, w)
                return np.asarray(out)

            async def _run_batch(self, loop, *a):
                return await loop.run_in_executor(
                    None, self._bulk_sync, *a)
        """,
        "ceph_tpu/placement/fixture.py", only=["trace-safety"])
    assert out == []


# ------------------------------------------------------- fabric-discipline


def test_fabric_spawn_fork_fires():
    out = lint(
        """
        import multiprocessing
        import os

        def shard_out():
            pid = os.fork()
            ctx = multiprocessing.get_context("fork")
            return pid, ctx
        """,
        "tools/fixture.py", only=["fabric-spawn-discipline"])
    assert any("os.fork" in m for m in msgs(out))
    assert any("spawn-only" in m for m in msgs(out))


def test_fabric_spawn_bare_mp_process_fires_popen_clean():
    out = lint(
        """
        import multiprocessing
        import subprocess
        import sys

        def workers(n):
            bad = multiprocessing.Process(target=print)
            good = subprocess.Popen([sys.executable, "-m", "x"])
            ctx = multiprocessing.get_context("spawn")
            return bad, good, ctx
        """,
        "ceph_tpu/cluster/fixture.py",
        only=["fabric-spawn-discipline"])
    assert len(out) == 1 and "fork start" in out[0].message


def test_fabric_pipe_pickle_fires_on_pipe_surface():
    out = lint(
        """
        import pickle

        def ship(result, pipe):
            pipe.write(pickle.dumps(result))

        def recv(pipe):
            return pickle.loads(pipe.read())
        """,
        "tools/swarm.py", only=["fabric-pipe-pickle"])
    assert len(out) == 2
    assert all("JSON histogram" in m for m in msgs(out))


def test_fabric_pipe_pickle_scoped_and_json_clean():
    # same calls OFF the pipe surfaces stay clean (store layers
    # legitimately serialize); json on the surface is the idiom
    out = lint(
        """
        import pickle

        def snapshot(x):
            return pickle.dumps(x)
        """,
        "ceph_tpu/store/fixture.py", only=["fabric-pipe-pickle"])
    assert out == []
    out = lint(
        """
        import json

        def ship(result, pipe):
            pipe.write(json.dumps(result).encode())
        """,
        "tools/swarm.py", only=["fabric-pipe-pickle"])
    assert out == []


def test_fabric_shm_release_missing_fires():
    out = lint(
        """
        def drain(ring, sink):
            for m in ring.recv_all():
                sink.append(bytes(m.view))
        """,
        "ceph_tpu/msg/fixture.py", only=["fabric-shm-release"])
    assert len(out) == 1
    assert "release()" in out[0].message


def test_fabric_shm_release_in_finally_clean():
    out = lint(
        """
        def drain(ring, sink):
            for m in ring.recv_all():
                try:
                    sink.append(bytes(m.view))
                finally:
                    m.release()

        def reap(ring):
            ring.recv_all()
            return ring.reclaim_dead()
        """,
        "ceph_tpu/msg/fixture.py", only=["fabric-shm-release"])
    assert out == []


# ------------------------------------------------------------ repo gate


def test_repo_gate_no_new_findings():
    """Tier-1 gate: `python tools/tpulint.py ceph_tpu tools` must be
    clean at HEAD modulo the committed baseline."""
    findings = analysis.run_paths(["ceph_tpu", "tools"], REPO)
    new = analysis.unbaselined(findings,
                               analysis.load_baseline(BASELINE))
    assert new == [], (
        "new tpulint findings (fix them or deliberately run "
        "`python tools/tpulint.py --update-baseline`):\n"
        + "\n".join(f.render() for f in new))


def test_repo_gate_baseline_not_stale():
    """The baseline may not carry entries for findings that no longer
    exist — shrink it when you fix one (ratchet, not blanket)."""
    findings = analysis.run_paths(["ceph_tpu", "tools"], REPO)
    base = analysis.load_baseline(BASELINE)
    live = {f.key for f in findings}
    stale = sorted(k for k in base if k not in live)
    assert stale == [], (
        "baseline entries with no matching finding — regenerate with "
        "`python tools/tpulint.py --update-baseline`:\n"
        + "\n".join(stale))
