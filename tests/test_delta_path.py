"""Delta write path: op-granular replication + EC partial-stripe RMW.

The acceptance bar from the reference's data-path shape
(ReplicatedBackend.cc:465 ships the op transaction; ECBackend.cc:1898
start_rmw reads/encodes only touched stripes): a 4 KiB write into a
4 MiB object must move O(stripe) bytes end-to-end, independent of the
object size — asserted here by counting actual encoded wire bytes on
the bus.
"""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2"}
MIB = 1024 * 1024


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, 120))
    finally:
        loop.close()


class WireCounter:
    """Wraps LocalBus.send, counting encoded bytes per message type."""

    def __init__(self, bus):
        self.bus = bus
        self.orig = bus.send
        self.by_type: dict[str, int] = {}
        bus.send = self.send

    async def send(self, src, dst, msg):
        name = type(msg).__name__
        self.by_type[name] = self.by_type.get(name, 0) + len(msg.encode())
        await self.orig(src, dst, msg)

    def reset(self):
        self.by_type = {}

    def total(self, *names):
        if not names:
            return sum(self.by_type.values())
        return sum(self.by_type.get(n, 0) for n in names)


async def make_rep(n=4):
    c = TestCluster(n_osds=n)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rep", size=3, pg_num=4, crush_rule=0)
    )
    await c.wait_active(20)
    return c


async def make_ec(n=5):
    c = TestCluster(n_osds=n)
    await c.start()
    await c.client.create_pool(
        Pool(id=2, name="ec", size=5, min_size=3, pg_num=4, crush_rule=1,
             type="erasure", ec_profile=dict(EC_PROFILE))
    )
    await c.wait_active(20)
    return c


def test_replicated_small_write_ships_delta_not_object():
    async def t():
        c = await make_rep()
        big = bytes(np.random.default_rng(0).integers(
            0, 256, 4 * MIB, dtype=np.uint8))
        await c.client.write_full(1, "obj", big)
        wc = WireCounter(c.bus)
        await c.client.write(1, "obj", 1 * MIB + 123, b"\xAA" * 4096)
        # 2 replicas x (4 KiB payload + txn/log framing) << object size
        rep_bytes = wc.total("MOSDRepOp")
        assert rep_bytes < 64 * 1024, f"RepOp shipped {rep_bytes} B"
        want = bytearray(big)
        want[1 * MIB + 123 : 1 * MIB + 123 + 4096] = b"\xAA" * 4096
        assert await c.client.read(1, "obj") == bytes(want)
        await c.stop()

    run(t())


def test_ec_small_write_moves_o_stripe_bytes():
    async def t():
        c = await make_ec()
        rng = np.random.default_rng(1)
        big = bytes(rng.integers(0, 256, 4 * MIB, dtype=np.uint8))
        await c.client.write_full(2, "obj", big)
        wc = WireCounter(c.bus)
        off = 1 * MIB + 5000  # straddles cells, not stripe-aligned
        await c.client.write(2, "obj", off, b"\xBB" * 4096)
        moved = wc.total("MECSubWrite", "MECSubRead", "MECSubReadReply",
                        "MECSubWriteReply")
        # touched stripes ~2 of 342: old-stripe reads + per-shard cell
        # deltas + CRC patches; full-object would be >5.6 MiB encoded
        assert moved < 300 * 1024, f"EC RMW moved {moved} B"
        want = bytearray(big)
        want[off : off + 4096] = b"\xBB" * 4096
        assert await c.client.read(2, "obj") == bytes(want)
        await c.stop()

    run(t())


def test_ec_rmw_parity_consistent_under_two_losses():
    """Partial overwrites must leave every stripe a consistent codeword:
    kill two shards and reconstruct-read the whole object."""
    async def t():
        c = await make_ec()
        rng = np.random.default_rng(2)
        data = bytearray(rng.integers(0, 256, 200_000, dtype=np.uint8))
        await c.client.write_full(2, "obj", bytes(data))
        # a burst of partial mutations: overwrites, append, zero, truncate
        for _ in range(10):
            off = int(rng.integers(0, 190_000))
            ln = int(rng.integers(1, 9000))
            payload = bytes(rng.integers(0, 256, ln, dtype=np.uint8))
            await c.client.write(2, "obj", off, payload)
            data[off : off + ln] = payload
        await c.client.zero(2, "obj", 50_000, 7000)
        data[50_000:57_000] = b"\0" * 7000
        tail = bytes(rng.integers(0, 256, 5000, dtype=np.uint8))
        await c.client.append(2, "obj", tail)
        data.extend(tail)
        await c.client.truncate(2, "obj", 150_000)
        del data[150_000:]
        assert await c.client.read(2, "obj") == bytes(data)

        pgid = c.client.osdmap.object_to_pg(2, b"obj")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        victims = [o for o in up if o != primary][:2]
        for v in victims:
            await c.kill_osd(v)
            await c.wait_down(v, 20)
        assert await c.client.read(2, "obj") == bytes(data)
        await c.stop()

    run(t())


@pytest.mark.parametrize("pool_id,factory", [(1, make_rep), (2, make_ec)])
def test_random_mutations_match_shadow(pool_id, factory):
    async def t():
        c = await factory()
        rng = np.random.default_rng(42 + pool_id)
        shadow = bytearray()
        await c.client.write_full(pool_id, "o", b"")
        for i in range(18):
            kind = rng.choice(["write", "zero", "truncate", "append",
                               "read"])
            if kind == "write":
                off = int(rng.integers(0, 60_000))
                ln = int(rng.integers(1, 20_000))
                p = bytes(rng.integers(0, 256, ln, dtype=np.uint8))
                await c.client.write(pool_id, "o", off, p)
                if len(shadow) < off + ln:
                    shadow.extend(b"\0" * (off + ln - len(shadow)))
                shadow[off : off + ln] = p
            elif kind == "zero":
                off = int(rng.integers(0, 60_000))
                ln = int(rng.integers(1, 20_000))
                await c.client.zero(pool_id, "o", off, ln)
                if len(shadow) < off + ln:
                    shadow.extend(b"\0" * (off + ln - len(shadow)))
                shadow[off : off + ln] = b"\0" * ln
            elif kind == "truncate":
                size = int(rng.integers(0, 80_000))
                await c.client.truncate(pool_id, "o", size)
                if size < len(shadow):
                    del shadow[size:]
                else:
                    shadow.extend(b"\0" * (size - len(shadow)))
            elif kind == "append":
                ln = int(rng.integers(1, 10_000))
                p = bytes(rng.integers(0, 256, ln, dtype=np.uint8))
                await c.client.append(pool_id, "o", p)
                shadow.extend(p)
            else:
                assert await c.client.read(pool_id, "o") == bytes(shadow)
                assert await c.client.stat(pool_id, "o") == len(shadow)
        assert await c.client.read(pool_id, "o") == bytes(shadow)
        await c.stop()

    run(t())


def test_ec_xattr_update_touches_no_data(  ):
    async def t():
        c = await make_ec()
        await c.client.write_full(2, "obj", b"Z" * MIB)
        wc = WireCounter(c.bus)
        await c.client.setxattr(2, "obj", "color", b"blue")
        assert wc.total("MECSubRead") == 0  # no old stripes fetched
        assert wc.total("MECSubWrite") < 8 * 1024
        assert await c.client.getxattr(2, "obj", "color") == b"blue"
        await c.stop()

    run(t())
