"""Checksummer contract (reference src/common/Checksummer.h)."""
import numpy as np
import pytest

from ceph_tpu import checksum as ck
from ceph_tpu import native as nt


@pytest.mark.parametrize("alg,size", [
    ("none", 0), ("xxhash32", 4), ("xxhash64", 8),
    ("crc32c", 4), ("crc32c_16", 2), ("crc32c_8", 1),
])
def test_value_sizes(alg, size):
    assert ck.csum_value_size(alg) == size


@pytest.mark.parametrize("alg", ["crc32c", "crc32c_16", "crc32c_8", "xxhash32", "xxhash64"])
def test_calculate_and_verify_clean(rng, alg):
    cs = ck.Checksummer(alg=alg, csum_block_size=4096)
    data = rng.integers(0, 256, 4096 * 8, dtype=np.uint8)
    vals = cs.calculate(data)
    assert vals.shape == (8,)
    assert cs.verify(data, vals) == (-1, None)


def test_verify_detects_bad_block(rng):
    cs = ck.Checksummer(alg="crc32c", csum_block_size=4096)
    data = rng.integers(0, 256, 4096 * 8, dtype=np.uint8)
    vals = cs.calculate(data)
    corrupted = data.copy()
    corrupted[4096 * 3 + 17] ^= 0xFF
    off, bad = cs.verify(corrupted, vals)
    assert off == 4096 * 3
    assert bad == cs.calculate(corrupted)[3]


def test_device_path_matches_host(rng):
    data = rng.integers(0, 256, 4096 * 16, dtype=np.uint8)
    for alg in ("crc32c", "crc32c_16", "crc32c_8"):
        cs = ck.Checksummer(alg=alg, csum_block_size=4096)
        assert (cs.calculate(data, device=True) == cs.calculate(data)).all()


def test_crc32c_matches_raw_native(rng):
    cs = ck.Checksummer(alg="crc32c", csum_block_size=512)
    data = rng.integers(0, 256, 512 * 4, dtype=np.uint8)
    vals = cs.calculate(data)
    for i in range(4):
        assert vals[i] == nt.crc32c(data[512 * i : 512 * (i + 1)])


def test_unaligned_length_rejected():
    cs = ck.Checksummer(alg="crc32c", csum_block_size=4096)
    with pytest.raises(ValueError, match="not a multiple"):
        cs.calculate(np.zeros(1000, np.uint8))


def test_bad_block_size_rejected():
    with pytest.raises(ValueError, match="power of two"):
        ck.Checksummer(alg="crc32c", csum_block_size=3000)


def test_unknown_alg_rejected():
    with pytest.raises(ValueError, match="unknown csum"):
        ck.Checksummer(alg="md5")


def test_xxhash64_default_init_is_64bit(rng):
    """Reference seeds xxhash64 with -1 as uint64 (Checksummer.h:203):
    init_value_t is uint64_t, so the default must be 2^64-1, not 2^32-1."""
    from ceph_tpu import native
    from ceph_tpu.checksum import Checksummer

    block = rng.integers(0, 256, 4096, dtype=np.uint8)
    cs = Checksummer(alg="xxhash64", csum_block_size=4096)
    got = cs.calculate(block)
    assert int(got[0]) == native.xxhash64(block, seed=(1 << 64) - 1)
    assert int(got[0]) != native.xxhash64(block, seed=0xFFFFFFFF)
