"""Buffer plane: BufferList semantics + the zero-copy message seams.

Covers the contracts the write path now leans on: slice aliasing vs
mutation isolation, splice across segment boundaries, lazy-flatten
idempotence (and its counters), encode/decode round-trip equivalence
with the legacy bytes path (property-style over random segmentations),
and LocalBus snapshot-view delivery under the resend-mutation safety
contract (PR 5's corked-writer stance: a retained, re-stamped message
must never leak post-send state into a delivery)."""
import asyncio
import random

import numpy as np
import pytest

from ceph_tpu.cluster import messages as M
from ceph_tpu.msg.frames import Frame, decode_frame, encode_frame, encode_frame_bl
from ceph_tpu.msg.messenger import LocalBus
from ceph_tpu.utils.buffer import STATS, BufferList

# ------------------------------------------------------------ BufferList


def test_append_is_zero_copy_and_len_tracks():
    payload = b"x" * 1024
    bl = BufferList()
    bl.append(payload)
    bl.append(memoryview(payload)[10:20])
    bl.append(np.arange(16, dtype=np.uint8))
    assert len(bl) == 1024 + 10 + 16
    assert bl.num_segments == 3
    # the first segment aliases the original bytes object
    assert next(iter(bl.segments())).obj is payload


def test_bytearray_append_snapshots():
    buf = bytearray(b"abcd")
    bl = BufferList(buf)
    buf[0] = ord("z")
    assert bytes(bl) == b"abcd"  # mutable storage was snapshotted


def test_substr_aliases_without_copy():
    a, b = b"hello", b"world!"
    bl = BufferList()
    bl.append(a)
    bl.append(b)
    sub = bl.substr(3, 5)  # crosses the segment boundary
    assert bytes(sub) == b"lowor"
    # aliasing: the substr's segments point into the same objects
    segs = list(sub.segments())
    assert segs[0].obj is a and segs[1].obj is b


def test_substr_bounds_checked():
    bl = BufferList(b"abc")
    with pytest.raises(ValueError):
        bl.substr(1, 3)
    with pytest.raises(ValueError):
        bl.substr(-1, 1)


def test_splice_across_segment_boundaries():
    bl = BufferList()
    for part in (b"aaaa", b"bbbb", b"cccc"):
        bl.append(part)
    removed = bl.splice(2, 8)  # a|aabb bbcc|cc
    assert bytes(removed) == b"aabbbbcc"
    assert bytes(bl) == b"aacc"
    assert len(bl) == 4
    # payload bytes never moved: still views over the originals
    assert all(type(s.obj) is bytes for s in bl.segments())


def test_mutation_isolation_snapshot_vs_append():
    bl = BufferList(b"base")
    snap = bl.snapshot()
    bl.append(b"-more")
    assert bytes(snap) == b"base"
    assert bytes(bl) == b"base-more"


def test_flatten_idempotent_and_counted():
    STATS.reset()
    bl = BufferList()
    bl.append(b"12")
    bl.append(b"34")
    first = bl.flatten()
    assert first == b"1234"
    assert STATS.flattens == 1
    assert STATS.bytes_flattened == 4
    # second flatten is cached: same object, no new copy counted
    assert bl.flatten() is first
    assert bytes(bl) is first
    assert STATS.flattens == 1


def test_flatten_whole_bytes_segment_is_free():
    STATS.reset()
    payload = b"z" * 64
    bl = BufferList(payload)
    assert bl.flatten() is payload  # no copy at all
    assert STATS.flattens == 0


def test_equality_with_bytes():
    bl = BufferList()
    bl.append(b"ab")
    bl.append(b"cd")
    assert bl == b"abcd"
    assert bl != b"abce"
    other = BufferList(b"abcd")
    assert bl == other


def test_strided_storage_rejected():
    arr = np.arange(64, dtype=np.uint8).reshape(8, 8)
    with pytest.raises(ValueError):
        BufferList(arr[:, ::2])  # non-contiguous view has no byte form
    with pytest.raises(ValueError):
        # 1-D step-sliced memoryview: must be rejected at append, not
        # blow up at a distant flatten/join boundary
        BufferList(memoryview(b"abcdef")[::2])


# ------------------------------------------------- frames over BufferList


def test_frame_bl_encode_matches_legacy_and_decodes_as_view():
    payload = b"p" * 300
    bl_form = bytes(encode_frame_bl(Frame(7, BufferList(payload))))
    flat_form = encode_frame(Frame(7, payload))
    assert bl_form == flat_form
    frame, used = decode_frame(flat_form)
    assert used == len(flat_form)
    assert isinstance(frame.payload, memoryview)  # zero-copy decode
    assert frame.payload == payload


# ------------------------------------- round-trip equivalence (property)


def _random_message(rng: random.Random) -> M.Message:
    body = rng.randbytes(rng.randrange(1, 4096))
    return M.MOSDOp(
        tid=rng.randrange(1 << 40), pgid=(2, rng.randrange(32)),
        oid=rng.randbytes(rng.randrange(1, 24)),
        ops=[M.osd_op("writefull", data=body),
             M.osd_op("setxattr", key=b"k", data=rng.randbytes(8))],
        epoch=rng.randrange(1 << 20),
        snap_seq=rng.randrange(1 << 10),
        snaps=[rng.randrange(1 << 16) for _ in range(rng.randrange(3))],
    )


def test_encode_bl_equals_legacy_encode_property():
    """Property-style: over random messages and random payload
    segmentations, the BufferList encoding is byte-identical to the
    legacy join encoding, and decode inverts both."""
    rng = random.Random(20260804)
    for _ in range(40):
        msg = _random_message(rng)
        legacy = msg.encode()
        assert bytes(msg.encode_bl()) == legacy
        # segmented body: the op data arrives as a multi-segment
        # BufferList and must encode identically
        ops = []
        for (op, off, ln, key, data, kv, keys) in msg.ops:
            if data:
                data = bytes(data)
                seg = BufferList()
                pos = 0
                while pos < len(data):
                    step = rng.randrange(1, len(data) - pos + 1)
                    seg.append(data[pos : pos + step])
                    pos += step
                data = seg
            ops.append((op, off, ln, key, data, kv, keys))
        msg.ops = ops
        assert bytes(msg.encode_bl()) == legacy
        dec = M.MOSDOp.decode(legacy)
        assert dec.encode() == legacy


def test_decode_bodies_are_views():
    body = b"B" * 512
    msg = M.MOSDOpReply(tid=1, result=0, data=body, size=len(body),
                        outs=[(0, body)], epoch=3)
    enc = msg.encode()
    dec = M.MOSDOpReply.decode(enc)
    assert isinstance(dec.data, memoryview)
    assert isinstance(dec.outs[0][1], memoryview)
    assert dec.data == body and dec.outs[0][1] == body
    assert dec == msg  # view/bytes equality is structural


# ----------------------------------------- LocalBus snapshot deliveries


def _run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_localbus_snapshot_delivery_resend_mutation_safety():
    """The client retains and re-stamps its MOSDOp for resends (epoch
    bump, PR 5 window machinery): the delivered snapshot must carry
    SEND-TIME state, share the payload storage (zero-copy), and two
    deliveries must never share one mutable message object."""

    async def scenario():
        bus = LocalBus()
        got: list[M.MOSDOp] = []

        async def handler(_src, m):
            got.append(m)

        bus.register("osd.0", handler)
        payload = b"D" * (1 << 16)
        msg = M.MOSDOp(tid=9, pgid=(2, 1), oid=b"o",
                       ops=[M.osd_op("writefull", data=payload)],
                       epoch=5)
        await bus.send("client.0", "osd.0", msg)
        # the resend path mutates the retained message BEFORE delivery
        # ran (delivery is corked onto the next loop tick)
        msg.epoch = 6
        msg.ops = [M.osd_op("writefull", data=b"replaced")]
        await bus.drain()
        assert len(got) == 1
        snap = got[0]
        assert snap is not msg
        assert snap.epoch == 5  # send-time state
        assert snap.ops[0][4] is payload  # zero-copy shared body
        assert bus.zero_copy_sends == 1

    _run(scenario())


def test_localbus_duplicate_deliveries_are_isolated():
    async def scenario():
        from ceph_tpu.cluster.faults import NetFaultPolicy

        pol = NetFaultPolicy(random.Random(1))
        pol.set_link("client.0", "osd.0", dup=1.0)
        bus = LocalBus(faults=pol)
        got = []

        async def handler(_src, m):
            got.append(m)

        bus.register("osd.0", handler)
        msg = M.MOSDOpReply(tid=1, result=0, data=b"x", size=1,
                            outs=[(0, b"x")], epoch=1)
        await bus.send("osd.0", "client.0", msg) \
            if False else await bus.send("client.0", "osd.0", msg)
        await bus.drain()
        assert len(got) == 2
        assert got[0] is not got[1]  # two deliveries, two objects
        got[0].outs.append((1, b"y"))  # a receiver-side mutation...
        assert len(got[1].outs) == 1  # ...never leaks to the twin

    _run(scenario())


def test_localbus_codec_symmetry_check_passes_when_armed():
    async def scenario():
        bus = LocalBus()
        bus.verify_codec_symmetry = True
        got = []

        async def handler(_src, m):
            got.append(m)

        bus.register("osd.0", handler)
        msg = M.MOSDOp(tid=1, pgid=(2, 0), oid=b"o",
                       ops=[M.osd_op("writefull", data=b"abc" * 100)],
                       epoch=1)
        await bus.send("client.0", "osd.0", msg)
        await bus.drain()
        assert got and bus.codec_symmetry_checks == 1

    _run(scenario())


def test_localbus_codec_symmetry_check_catches_asymmetry():
    """The armed check must actually discriminate: a message whose
    field value does not survive its own wire codec (here: a snap id
    too big for the u64 the codec writes... use a type that encodes
    lossily) fails the send loudly."""

    async def scenario():
        from ceph_tpu.msg.frames import FrameError
        from ceph_tpu.msg.messages import Message, register_message

        class MLossy(Message):
            TYPE = 0x7F01
            # encoder drops the payload tail: decode can never agree
            FIELDS = (("blob", (
                lambda v: __import__(
                    "ceph_tpu.utils.denc", fromlist=["denc"]
                ).enc_bytes(v[:1]),
                lambda b, o: __import__(
                    "ceph_tpu.utils.denc", fromlist=["denc"]
                ).dec_bytes(b, o),
            )),)

        register_message(MLossy)
        bus = LocalBus()
        bus.verify_codec_symmetry = True

        async def handler(_src, m):
            pass

        bus.register("osd.0", handler)
        with pytest.raises(FrameError):
            await bus.send("client.0", "osd.0", MLossy(blob=b"lossy"))

    _run(scenario())


def test_localbus_legacy_marshal_lever():
    """CEPH_TPU_BUS_SNAPSHOT=0 (surfaced as snapshot_delivery=False)
    restores the encode+decode-per-hop path — the bench A/B lever."""

    async def scenario():
        bus = LocalBus()
        bus.snapshot_delivery = False
        got = []

        async def handler(_src, m):
            got.append(m)

        bus.register("osd.0", handler)
        payload = b"P" * 1024
        msg = M.MOSDOp(tid=2, pgid=(2, 0), oid=b"o",
                       ops=[M.osd_op("writefull", data=payload)],
                       epoch=1)
        await bus.send("client.0", "osd.0", msg)
        await bus.drain()
        assert got and bus.zero_copy_sends == 0
        # marshalled delivery: the body was re-materialized, not shared
        assert got[0].ops[0][4] is not payload
        assert bytes(got[0].ops[0][4]) == payload

    _run(scenario())
