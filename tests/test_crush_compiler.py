"""Crushmap text compiler, CrushTester, legacy bucket algs in full
rules, and choose_args tests (CrushCompiler.cc / CrushTester.cc roles).
"""
import numpy as np
import pytest

from ceph_tpu.placement import compiler, crushmap as cm
from ceph_tpu.placement.tester import test_rule as run_rule_test

SAMPLE = """
# sample map
tunable choose_total_tries 50
device 0 osd.0
device 1 osd.1
device 2 osd.2 class ssd
device 3 osd.3
device 4 osd.4
device 5 osd.5

type 0 osd
type 1 host
type 2 root

host host0 {
    id -2
    alg straw2
    hash 0
    item osd.0 weight 1.000
    item osd.1 weight 1.000
}
host host1 {
    id -3
    alg straw2
    hash 0
    item osd.2 weight 2.000
    item osd.3 weight 1.000
}
host host2 {
    id -4
    alg straw2
    hash 0
    item osd.4 weight 1.000
    item osd.5 weight 1.000
}
root default {
    id -1
    alg straw2
    hash 0
    item host0 weight 2.000
    item host1 weight 3.000
    item host2 weight 2.000
}

rule replicated_rule {
    id 0
    type replicated
    step take default
    step chooseleaf firstn 0 type host
    step emit
}
rule ec_rule {
    id 1
    type replicated
    step set_chooseleaf_tries 5
    step take default
    step chooseleaf indep 0 type host
    step emit
}
"""


def test_compile_sample():
    m = compiler.compile(SAMPLE)
    assert m.max_devices == 6
    assert set(m.buckets) == {-1, -2, -3, -4}
    assert m.buckets[-1].items == [-2, -3, -4]
    assert m.buckets[-3].weights == [0x20000, 0x10000]
    assert m.tunables.choose_total_tries == 50
    assert m.device_classes == {2: "ssd"}
    assert len(m.rules[0].steps) == 3
    assert m.rules[1].steps[0].op == cm.OP_SET_CHOOSELEAF_TRIES
    # the compiled map actually places
    out = m.do_rule(0, 1234, 3)
    assert len({d for d in out if d >= 0}) == 3


def test_compile_decompile_roundtrip():
    m1 = compiler.compile(SAMPLE)
    text = compiler.decompile(m1)
    m2 = compiler.compile(text)
    # placement-equivalent: identical mappings across rules and inputs
    for rule in (0, 1):
        for x in range(200):
            assert m1.do_rule(rule, x, 3) == m2.do_rule(rule, x, 3), \
                (rule, x)


def test_compile_errors():
    with pytest.raises(compiler.CompileError):
        compiler.compile("garbage line here")
    with pytest.raises(compiler.CompileError):
        compiler.compile("tunable nonexistent 5")
    with pytest.raises(compiler.CompileError):
        compiler.compile(
            "type 1 host\nhost h {\n id -1\n alg warp\n}\n"
        )
    with pytest.raises(compiler.CompileError):
        compiler.compile("type 1 host\nhost h {\n alg straw2\n}\n")


def test_legacy_algs_in_full_rule():
    """list/tree/straw buckets work through do_rule end-to-end."""
    for alg in (cm.ALG_LIST, cm.ALG_TREE, cm.ALG_STRAW):
        m = cm.CrushMap()
        m.add_type(1, "root")
        m.add_bucket(cm.Bucket(
            id=-1, type_id=1, alg=alg, items=list(range(6)),
            weights=[0x10000] * 6, name="root",
        ))
        m.add_rule(cm.flat_firstn_rule(0))
        seen = set()
        for x in range(300):
            out = m.do_rule(0, x, 3)
            picked = [d for d in out if d >= 0]
            assert len(set(picked)) == len(picked), (alg, x)
            seen.update(picked)
        assert seen == set(range(6)), alg


def test_choose_args_reweights_placement():
    """A choose_args weight set shifts straw2 placement away from a
    zero-weighted item without touching the base map (upmap-balancer
    mechanics, crush_choose_arg role)."""
    m = cm.build_flat(4)
    m.add_rule(cm.flat_firstn_rule(0))
    base = [m.do_rule(0, x, 2) for x in range(400)]
    m.choose_args["balancer"] = {-1: ([0, 0x10000, 0x10000, 0x10000],
                                      None)}
    shifted = [m.do_rule(0, x, 2, choose_args="balancer")
               for x in range(400)]
    assert any(0 in row for row in base)
    assert not any(0 in row for row in shifted)
    # base behavior untouched afterwards
    assert [m.do_rule(0, x, 2) for x in range(400)] == base


def test_choose_args_substitute_ids():
    m = cm.build_flat(4)
    m.add_rule(cm.flat_firstn_rule(0))
    base = [m.do_rule(0, x, 2) for x in range(100)]
    # same weights but different hash ids -> different placements
    m.choose_args[0] = {-1: ([0x10000] * 4, [100, 101, 102, 103])}
    swapped = [m.do_rule(0, x, 2, choose_args=0) for x in range(100)]
    assert base != swapped


# -------------------------------------------------------------- tester


def test_tester_uniform_distribution():
    m = cm.build_flat(8)
    m.add_rule(cm.flat_firstn_rule(0))
    rep = run_rule_test(m, 0, 3, n_inputs=3000)
    assert rep.placed == 3000 * 3
    assert not rep.bad_mappings
    assert rep.max_deviation(m) < 0.02  # uniform weights -> ~1/8 each


def test_tester_weighted_distribution():
    m = cm.build_flat(4, osd_weights=[4.0, 1.0, 1.0, 1.0])
    m.add_rule(cm.flat_firstn_rule(0))
    rep = run_rule_test(m, 0, 1, n_inputs=6000)
    util = rep.utilization()
    exp = rep.expected_utilization(m)
    assert abs(exp[0] - 4 / 7) < 1e-9
    assert abs(util[0] - exp[0]) < 0.03


def test_tester_detects_bad_mappings():
    # ask for more replicas than devices exist
    m = cm.build_flat(2)
    m.add_rule(cm.flat_firstn_rule(0))
    rep = run_rule_test(m, 0, 3, n_inputs=50)
    assert len(rep.bad_mappings) == 50


def test_tester_device_engine_matches_host():
    m = cm.build_hierarchy(osds_per_host=2, n_hosts=4)
    m.add_rule(cm.replicated_rule(0, failure_domain_type=1))
    host = run_rule_test(m, 0, 3, n_inputs=256, device=False)
    dev = run_rule_test(m, 0, 3, n_inputs=256, device=True)
    assert host.device_counts == dev.device_counts
    assert host.bad_mappings == dev.bad_mappings
