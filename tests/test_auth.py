"""Auth tests: cephx-role handshake, frame signing, rejection paths
(src/auth test role)."""
import asyncio

import pytest

from ceph_tpu.cluster import messages as M
from ceph_tpu.msg.auth import (
    AuthError,
    Authenticator,
    KeyServer,
    handshake_accept,
)
from ceph_tpu.msg.messenger import TcpMessenger


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 30))


def test_handshake_unit():
    keys = KeyServer()
    secret = keys.add("osd.1")
    a = Authenticator("osd.1", secret)
    hello, nonce = a.make_hello()
    challenge = Authenticator.make_challenge()
    proof = a.prove(challenge, nonce)
    session = handshake_accept(keys, hello, challenge, proof)
    a.derive_session(secret, challenge, nonce)
    assert session == a.session_key
    # wrong secret -> rejected
    mallory = Authenticator("osd.1", b"guessed-wrong")
    bad = mallory.prove(challenge, nonce)
    with pytest.raises(AuthError):
        handshake_accept(keys, hello, challenge, bad)
    # unknown entity -> rejected
    ghost = Authenticator("osd.99", secret)
    h2, n2 = ghost.make_hello()
    with pytest.raises(AuthError):
        handshake_accept(keys, h2, challenge, ghost.prove(challenge, n2))


def test_frame_signing_unit():
    keys = KeyServer()
    secret = keys.add("x")
    a = Authenticator("x", secret)
    a.session_key = b"k" * 32
    tag = a.sign(b"frame-bytes")
    a.check(b"frame-bytes", tag)
    with pytest.raises(AuthError):
        a.check(b"frame-bytEs", tag)


def test_authenticated_messenger_roundtrip():
    async def t():
        keys = KeyServer()
        keys.add("client.1")
        keys.add("osd.0")
        got = []
        done = asyncio.Event()

        async def da(src, msg):
            got.append((src, msg))
            done.set()

        async def db(src, msg):
            await b.send(src, M.MOSDBoot(osd=7))

        a = TcpMessenger("client.1", da, keys=keys)
        b = TcpMessenger("osd.0", db, keys=keys)
        hb, pb = await b.listen()
        ha, pa = await a.listen()
        a.addrbook["osd.0"] = (hb, pb)
        b.addrbook["client.1"] = (ha, pa)
        await a.send("osd.0", M.MMonGetMap(have=0))
        await asyncio.wait_for(done.wait(), 5)
        assert got[0] == ("osd.0", M.MOSDBoot(osd=7))
        await a.close()
        await b.close()

    run(t())


def test_wrong_key_rejected_on_wire():
    async def t():
        server_keys = KeyServer()
        server_keys.add("osd.0")
        server_keys.add("client.1", b"the-real-secret")
        rogue_keys = KeyServer()
        rogue_keys.add("client.1", b"WRONG")
        received = []

        async def db(src, msg):
            received.append(msg)

        b = TcpMessenger("osd.0", db, keys=server_keys)
        hb, pb = await b.listen()
        a = TcpMessenger("client.1", lambda s, m: None, keys=rogue_keys)
        a.addrbook["osd.0"] = (hb, pb)
        from ceph_tpu.msg.messenger import SendError

        with pytest.raises(SendError):
            await a.send("osd.0", M.MMonGetMap(have=0))
        await asyncio.sleep(0.1)
        assert received == []
        await a.close()
        await b.close()

    run(t())


def test_unauthenticated_peer_rejected():
    async def t():
        keys = KeyServer()
        keys.add("osd.0")
        received = []

        async def db(src, msg):
            received.append(msg)

        b = TcpMessenger("osd.0", db, keys=keys)
        hb, pb = await b.listen()
        # a plaintext messenger (no keys) talks to an authed acceptor:
        # its first frame is not AUTH_HELLO -> connection dropped
        a = TcpMessenger("client.1", lambda s, m: None)
        a.addrbook["osd.0"] = (hb, pb)
        await a.send("osd.0", M.MMonGetMap(have=0))
        await asyncio.sleep(0.2)
        assert received == []
        await a.close()
        await b.close()

    run(t())


def test_secure_mode_roundtrip():
    """msgr2 secure mode: AES-GCM frames end to end, both directions."""
    pytest.importorskip("cryptography")
    async def t():
        keys = KeyServer()
        keys.add("client.1")
        keys.add("osd.0")
        got = []
        done = asyncio.Event()

        async def da(src, msg):
            got.append((src, msg))
            done.set()

        async def db(src, msg):
            await b.send(src, M.MOSDBoot(osd=9))

        a = TcpMessenger("client.1", da, keys=keys, secure=True)
        b = TcpMessenger("osd.0", db, keys=keys, secure=True)
        hb, pb = await b.listen()
        ha, pa = await a.listen()
        a.addrbook["osd.0"] = (hb, pb)
        b.addrbook["client.1"] = (ha, pa)
        await a.send("osd.0", M.MMonGetMap(have=0))
        await asyncio.wait_for(done.wait(), 5)
        assert got[0] == ("osd.0", M.MOSDBoot(osd=9))
        await a.close()
        await b.close()

    run(t())


def test_secure_acceptor_rejects_signed_peer():
    """A secure acceptor must refuse a peer that only offers signed
    mode (downgrade refusal)."""
    async def t():
        keys = KeyServer()
        keys.add("client.1")
        keys.add("osd.0")
        got = []
        b = TcpMessenger("osd.0", lambda s, m: got.append(m), keys=keys,
                         secure=True)
        hb, pb = await b.listen()
        a = TcpMessenger("client.1", lambda s, m: None, keys=keys)
        a.addrbook["osd.0"] = (hb, pb)
        # the acceptor sends AUTH_OK only after checking the proof, and
        # drops the connection when the mode is refused — the signed
        # sender's frames never reach the dispatcher
        try:
            await a.send("osd.0", M.MMonGetMap(have=0))
        except Exception:
            pass
        await asyncio.sleep(0.2)
        assert got == []
        await a.close()
        await b.close()

    run(t())


def test_secure_frame_tamper_detected():
    """Flipping one ciphertext byte must kill the connection before
    dispatch (GCM authentication)."""
    pytest.importorskip("cryptography")
    import struct

    from ceph_tpu.msg.auth import SecureSession

    sess_a = SecureSession(b"k" * 32, "connector")
    sess_b = SecureSession(b"k" * 32, "acceptor")
    rec = b"hello frame bytes"
    wire = sess_a.encrypt(rec)
    (ln,) = struct.unpack("<I", wire[:4])
    ct = bytearray(wire[4:4 + ln])
    assert sess_b.decrypt(bytes(ct)) == rec  # clean copy decrypts
    sess_b2 = SecureSession(b"k" * 32, "acceptor")
    ct[5] ^= 0x40
    with pytest.raises(AuthError, match="authentication"):
        sess_b2.decrypt(bytes(ct))


def test_secure_replay_rejected():
    """A replayed record fails: the receive counter has moved on."""
    pytest.importorskip("cryptography")
    from ceph_tpu.msg.auth import SecureSession

    tx = SecureSession(b"s" * 32, "connector")
    rx = SecureSession(b"s" * 32, "acceptor")
    w1 = tx.encrypt(b"first")
    w2 = tx.encrypt(b"second")
    assert rx.decrypt(w1[4:]) == b"first"
    assert rx.decrypt(w2[4:]) == b"second"
    with pytest.raises(AuthError):
        rx.decrypt(w1[4:])  # replay of record 0 at position 2


def test_onwire_compression_roundtrip():
    """compression_onwire role: large payloads ride deflated (flagged
    per frame) and inflate transparently at dispatch."""
    async def t():
        got = []
        done = asyncio.Event()

        async def da(src, msg):
            got.append(msg)
            done.set()

        a = TcpMessenger("client.1", lambda s, m: None,
                         compress_threshold=64)
        b = TcpMessenger("osd.0", da, compress_threshold=64)
        hb, pb = await b.listen()
        a.addrbook["osd.0"] = (hb, pb)
        big = M.MOSDMapMsg(full=b"z" * 50_000, incrementals=[], epoch=3)
        await a.send("osd.0", big)
        await asyncio.wait_for(done.wait(), 5)
        assert got[0] == big
        await a.close()
        await b.close()

    run(t())


def test_secure_no_reflection():
    """A peer's own transmitted record must not decrypt as a received
    one (per-direction nonce salts — GCM nonce-reuse guard)."""
    pytest.importorskip("cryptography")
    from ceph_tpu.msg.auth import SecureSession

    a = SecureSession(b"q" * 32, "connector")
    wire = a.encrypt(b"mine")
    with pytest.raises(AuthError):
        a.decrypt(wire[4:])  # reflected back at the sender


def test_decompression_bomb_capped():
    """A frame inflating past MAX_INFLATE kills the connection instead
    of the process's memory."""
    import zlib

    async def t():
        crashed = asyncio.Event()
        b = TcpMessenger("osd.0", lambda s, m: None)
        hb, pb = await b.listen()
        bomb = zlib.compress(b"\x00" * (TcpMessenger.MAX_INFLATE + 100), 9)
        from ceph_tpu.msg.frames import Frame, encode_frame

        r, w = await asyncio.open_connection(hb, pb)
        w.write(encode_frame(Frame(11, bomb, TcpMessenger.FLAG_COMPRESSED)))
        await w.drain()
        # connection must be dropped by the receiver
        got = await asyncio.wait_for(r.read(1), 5)
        assert got == b""  # EOF: handler tore the connection down
        w.close()
        await b.close()

    run(t())
