"""Auth tests: cephx-role handshake, frame signing, rejection paths
(src/auth test role)."""
import asyncio

import pytest

from ceph_tpu.cluster import messages as M
from ceph_tpu.msg.auth import (
    AuthError,
    Authenticator,
    KeyServer,
    handshake_accept,
)
from ceph_tpu.msg.messenger import TcpMessenger


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 30))


def test_handshake_unit():
    keys = KeyServer()
    secret = keys.add("osd.1")
    a = Authenticator("osd.1", secret)
    hello, nonce = a.make_hello()
    challenge = Authenticator.make_challenge()
    proof = a.prove(challenge, nonce)
    session = handshake_accept(keys, hello, challenge, proof)
    a.derive_session(secret, challenge, nonce)
    assert session == a.session_key
    # wrong secret -> rejected
    mallory = Authenticator("osd.1", b"guessed-wrong")
    bad = mallory.prove(challenge, nonce)
    with pytest.raises(AuthError):
        handshake_accept(keys, hello, challenge, bad)
    # unknown entity -> rejected
    ghost = Authenticator("osd.99", secret)
    h2, n2 = ghost.make_hello()
    with pytest.raises(AuthError):
        handshake_accept(keys, h2, challenge, ghost.prove(challenge, n2))


def test_frame_signing_unit():
    keys = KeyServer()
    secret = keys.add("x")
    a = Authenticator("x", secret)
    a.session_key = b"k" * 32
    tag = a.sign(b"frame-bytes")
    a.check(b"frame-bytes", tag)
    with pytest.raises(AuthError):
        a.check(b"frame-bytEs", tag)


def test_authenticated_messenger_roundtrip():
    async def t():
        keys = KeyServer()
        keys.add("client.1")
        keys.add("osd.0")
        got = []
        done = asyncio.Event()

        async def da(src, msg):
            got.append((src, msg))
            done.set()

        async def db(src, msg):
            await b.send(src, M.MOSDBoot(osd=7))

        a = TcpMessenger("client.1", da, keys=keys)
        b = TcpMessenger("osd.0", db, keys=keys)
        hb, pb = await b.listen()
        ha, pa = await a.listen()
        a.addrbook["osd.0"] = (hb, pb)
        b.addrbook["client.1"] = (ha, pa)
        await a.send("osd.0", M.MMonGetMap(have=0))
        await asyncio.wait_for(done.wait(), 5)
        assert got[0] == ("osd.0", M.MOSDBoot(osd=7))
        await a.close()
        await b.close()

    run(t())


def test_wrong_key_rejected_on_wire():
    async def t():
        server_keys = KeyServer()
        server_keys.add("osd.0")
        server_keys.add("client.1", b"the-real-secret")
        rogue_keys = KeyServer()
        rogue_keys.add("client.1", b"WRONG")
        received = []

        async def db(src, msg):
            received.append(msg)

        b = TcpMessenger("osd.0", db, keys=server_keys)
        hb, pb = await b.listen()
        a = TcpMessenger("client.1", lambda s, m: None, keys=rogue_keys)
        a.addrbook["osd.0"] = (hb, pb)
        from ceph_tpu.msg.messenger import SendError

        with pytest.raises(SendError):
            await a.send("osd.0", M.MMonGetMap(have=0))
        await asyncio.sleep(0.1)
        assert received == []
        await a.close()
        await b.close()

    run(t())


def test_unauthenticated_peer_rejected():
    async def t():
        keys = KeyServer()
        keys.add("osd.0")
        received = []

        async def db(src, msg):
            received.append(msg)

        b = TcpMessenger("osd.0", db, keys=keys)
        hb, pb = await b.listen()
        # a plaintext messenger (no keys) talks to an authed acceptor:
        # its first frame is not AUTH_HELLO -> connection dropped
        a = TcpMessenger("client.1", lambda s, m: None)
        a.addrbook["osd.0"] = (hb, pb)
        await a.send("osd.0", M.MMonGetMap(have=0))
        await asyncio.sleep(0.2)
        assert received == []
        await a.close()
        await b.close()

    run(t())
