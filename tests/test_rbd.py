"""RBD-lite tests: image lifecycle, striped IO, snapshots, layering
(the librbd test role)."""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.osdc.striper import FileLayout
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services import RBD, ImageNotFound
from ceph_tpu.services.rbd import ImageExists

LAYOUT = FileLayout(stripe_unit=8192, stripe_count=1, object_size=8192)


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rbd", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c, RBD(c.client, 1)


def test_image_lifecycle_and_io():
    async def t():
        c, rbd = await make()
        await rbd.create("disk", 64 * 1024, LAYOUT)
        with pytest.raises(ImageExists):
            await rbd.create("disk", 1024)
        img = await rbd.open("disk")
        assert img.size == 64 * 1024
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, 30000, dtype=np.uint8).tobytes()
        await img.write(1000, data)
        assert await img.read(1000, 30000) == data
        # holes read as zeros
        assert await img.read(40000, 100) == b"\0" * 100
        # cross-object overwrite
        await img.write(8000, b"B" * 400)
        got = await img.read(7990, 420)
        assert got[10:410] == b"B" * 400
        # discard zeroes a range
        await img.discard(1000, 500)
        assert await img.read(1000, 500) == b"\0" * 500
        with pytest.raises(IOError):
            await img.write(64 * 1024 - 10, b"x" * 20)  # past end
        await c.stop()

    run(t())


def test_resize_and_remove():
    async def t():
        c, rbd = await make()
        await rbd.create("vol", 40960, LAYOUT)  # 5 objects
        img = await rbd.open("vol")
        await img.write(0, b"A" * 40960)
        await img.resize(12000)  # shrink into object 1
        assert img.size == 12000
        assert await img.read(0, 12000) == b"A" * 12000
        await img.resize(20000)  # grow: new bytes read as zeros
        got = await img.read(0, 20000)
        assert got[:12000] == b"A" * 12000
        assert got[12000:] == b"\0" * 8000
        await rbd.remove("vol")
        with pytest.raises(ImageNotFound):
            await rbd.open("vol")
        await c.stop()

    run(t())


def test_snapshots_and_rollback():
    async def t():
        c, rbd = await make()
        await rbd.create("img", 32768, LAYOUT)
        img = await rbd.open("img")
        await img.write(0, b"v1" * 8000)
        await img.snap_create("s1")
        await img.write(0, b"v2" * 8000)
        assert await img.read(0, 16000) == b"v2" * 8000
        # read-at-snap sees the old data
        at_s1 = await rbd.open("img", snap="s1")
        assert await at_s1.read(0, 16000) == b"v1" * 8000
        with pytest.raises(IOError):
            await at_s1.write(0, b"nope")
        assert await img.snap_list() == ["s1"]
        await img.snap_rollback("s1")
        assert await img.read(0, 16000) == b"v1" * 8000
        await img.snap_remove("s1")
        assert await img.snap_list() == []
        # removing an image with snapshots is refused
        await img.snap_create("s2")
        with pytest.raises(RuntimeError):
            await rbd.remove("img")
        await c.stop()

    run(t())


def test_clone_cow_and_flatten():
    async def t():
        c, rbd = await make()
        await rbd.create("base", 32768, LAYOUT)
        base = await rbd.open("base")
        await base.write(0, b"GOLD" * 4096)  # 16384 bytes, 2 objects
        await base.snap_create("gold")
        await rbd.clone("base", "gold", "child")
        child = await rbd.open("child")
        assert child.parent == ("base", "gold")
        # unwritten child extents read through to the parent snapshot
        assert await child.read(0, 16384) == b"GOLD" * 4096
        # COW: writing the child leaves the parent untouched
        await child.write(0, b"EDIT")
        assert (await child.read(0, 8))[:4] == b"EDIT"
        assert await base.read(0, 8) == b"GOLDGOLD"
        # the copied-up object carries the rest of the parent bytes
        assert await child.read(4, 100) == (b"GOLD" * 30)[4 - 4:100]
        # parent changes after the snap are invisible to the child
        await base.write(8192, b"NEWBASE!")
        assert await child.read(8192, 8) == b"GOLD" * 2
        await child.flatten()
        assert child.parent is None
        # flatten made the child self-contained: removing base works
        await base.snap_remove("gold")
        await rbd.remove("base")
        assert await child.read(0, 4) == b"EDIT"
        await c.stop()

    run(t())


def test_clone_child_snapshot_preserves_parent_backed_data():
    """A snapshot of a clone child must serve parent-backed extents the
    child never copied up — at the child's snap the object's logical
    content was the parent's clone-time data (librbd layered-snap
    semantics)."""
    async def t():
        c, rbd = await make()
        await rbd.create("base", 32768, LAYOUT)
        base = await rbd.open("base")
        await base.write(0, b"GOLD" * 4096)
        await base.snap_create("gold")
        await rbd.clone("base", "gold", "child")
        child = await rbd.open("child")
        await child.snap_create("cs")  # O(1): no data copied
        # write AFTER the snap: triggers copy-up + overwrite
        await child.write(0, b"EDIT")
        # the snap still shows parent content, not zeros and not EDIT
        snapv = await rbd.open("child", snap="cs")
        assert await snapv.read(0, 8) == b"GOLDGOLD"
        # an object never touched in the child also resolves via parent
        assert await snapv.read(8192, 8) == b"GOLD" * 2
        # head shows the edit
        assert (await child.read(0, 8))[:4] == b"EDIT"
        await c.stop()

    run(t())
