"""RBD-lite tests: image lifecycle, striped IO, snapshots, layering
(the librbd test role)."""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.osdc.striper import FileLayout
from ceph_tpu.placement.osdmap import Pool
from ceph_tpu.services import RBD, ImageNotFound
from ceph_tpu.services.rbd import ImageExists

LAYOUT = FileLayout(stripe_unit=8192, stripe_count=1, object_size=8192)


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make():
    c = TestCluster(n_osds=4)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rbd", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c, RBD(c.client, 1)


def test_image_lifecycle_and_io():
    async def t():
        c, rbd = await make()
        await rbd.create("disk", 64 * 1024, LAYOUT)
        with pytest.raises(ImageExists):
            await rbd.create("disk", 1024)
        img = await rbd.open("disk")
        assert img.size == 64 * 1024
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, 30000, dtype=np.uint8).tobytes()
        await img.write(1000, data)
        assert await img.read(1000, 30000) == data
        # holes read as zeros
        assert await img.read(40000, 100) == b"\0" * 100
        # cross-object overwrite
        await img.write(8000, b"B" * 400)
        got = await img.read(7990, 420)
        assert got[10:410] == b"B" * 400
        # discard zeroes a range
        await img.discard(1000, 500)
        assert await img.read(1000, 500) == b"\0" * 500
        with pytest.raises(IOError):
            await img.write(64 * 1024 - 10, b"x" * 20)  # past end
        await c.stop()

    run(t())


def test_resize_and_remove():
    async def t():
        c, rbd = await make()
        await rbd.create("vol", 40960, LAYOUT)  # 5 objects
        img = await rbd.open("vol")
        await img.write(0, b"A" * 40960)
        await img.resize(12000)  # shrink into object 1
        assert img.size == 12000
        assert await img.read(0, 12000) == b"A" * 12000
        await img.resize(20000)  # grow: new bytes read as zeros
        got = await img.read(0, 20000)
        assert got[:12000] == b"A" * 12000
        assert got[12000:] == b"\0" * 8000
        await rbd.remove("vol")
        with pytest.raises(ImageNotFound):
            await rbd.open("vol")
        await c.stop()

    run(t())


def test_snapshots_and_rollback():
    async def t():
        c, rbd = await make()
        await rbd.create("img", 32768, LAYOUT)
        img = await rbd.open("img")
        await img.write(0, b"v1" * 8000)
        await img.snap_create("s1")
        await img.write(0, b"v2" * 8000)
        assert await img.read(0, 16000) == b"v2" * 8000
        # read-at-snap sees the old data
        at_s1 = await rbd.open("img", snap="s1")
        assert await at_s1.read(0, 16000) == b"v1" * 8000
        with pytest.raises(IOError):
            await at_s1.write(0, b"nope")
        assert await img.snap_list() == ["s1"]
        await img.snap_rollback("s1")
        assert await img.read(0, 16000) == b"v1" * 8000
        await img.snap_remove("s1")
        assert await img.snap_list() == []
        # removing an image with snapshots is refused
        await img.snap_create("s2")
        with pytest.raises(RuntimeError):
            await rbd.remove("img")
        await c.stop()

    run(t())


def test_clone_cow_and_flatten():
    async def t():
        c, rbd = await make()
        await rbd.create("base", 32768, LAYOUT)
        base = await rbd.open("base")
        await base.write(0, b"GOLD" * 4096)  # 16384 bytes, 2 objects
        await base.snap_create("gold")
        await rbd.clone("base", "gold", "child")
        child = await rbd.open("child")
        assert child.parent == ("base", "gold")
        # unwritten child extents read through to the parent snapshot
        assert await child.read(0, 16384) == b"GOLD" * 4096
        # COW: writing the child leaves the parent untouched
        await child.write(0, b"EDIT")
        assert (await child.read(0, 8))[:4] == b"EDIT"
        assert await base.read(0, 8) == b"GOLDGOLD"
        # the copied-up object carries the rest of the parent bytes
        assert await child.read(4, 100) == (b"GOLD" * 30)[4 - 4:100]
        # parent changes after the snap are invisible to the child
        await base.write(8192, b"NEWBASE!")
        assert await child.read(8192, 8) == b"GOLD" * 2
        await child.flatten()
        assert child.parent is None
        # flatten made the child self-contained: removing base works
        await base.snap_remove("gold")
        await rbd.remove("base")
        assert await child.read(0, 4) == b"EDIT"
        await c.stop()

    run(t())


def test_clone_child_snapshot_preserves_parent_backed_data():
    """A snapshot of a clone child must serve parent-backed extents the
    child never copied up — at the child's snap the object's logical
    content was the parent's clone-time data (librbd layered-snap
    semantics)."""
    async def t():
        c, rbd = await make()
        await rbd.create("base", 32768, LAYOUT)
        base = await rbd.open("base")
        await base.write(0, b"GOLD" * 4096)
        await base.snap_create("gold")
        await rbd.clone("base", "gold", "child")
        child = await rbd.open("child")
        await child.snap_create("cs")  # O(1): no data copied
        # write AFTER the snap: triggers copy-up + overwrite
        await child.write(0, b"EDIT")
        # the snap still shows parent content, not zeros and not EDIT
        snapv = await rbd.open("child", snap="cs")
        assert await snapv.read(0, 8) == b"GOLDGOLD"
        # an object never touched in the child also resolves via parent
        assert await snapv.read(8192, 8) == b"GOLD" * 2
        # head shows the edit
        assert (await child.read(0, 8))[:4] == b"EDIT"
        await c.stop()

    run(t())


def test_exclusive_lock_two_clients_cooperative():
    """Two live image handles serialize through the exclusive lock
    (ExclusiveLock.h:20 role): the second handle's acquire notifies the
    holder, which releases cooperatively, and ownership transfers."""
    async def t():
        from ceph_tpu.cluster.client import RadosClient

        c, rbd = await make()
        await rbd.create("disk", 64 * 1024, LAYOUT)
        img_a = await rbd.open("disk")
        await img_a.write(0, b"A" * 8192)  # lazy acquire
        assert img_a.lock_owned

        c2 = RadosClient(c.bus, name="client.1")
        await c2.connect()
        rbd_b = RBD(c2, 1)
        img_b = await rbd_b.open("disk")
        assert not img_b.lock_owned
        await img_b.write(8192, b"B" * 8192)  # cooperative handover
        assert img_b.lock_owned
        assert not img_a.lock_owned  # holder released on request
        # data from both writers is intact
        assert await img_a.read(0, 8192) == b"A" * 8192
        assert await img_b.read(8192, 8192) == b"B" * 8192
        # and A can take it back the same way
        await img_a.write(0, b"C" * 100)
        assert img_a.lock_owned and not img_b.lock_owned
        await c2.close()
        await c.stop()

    run(t())


def test_exclusive_lock_steal_fences_dead_holder():
    """A holder that never answers the cooperative request is stolen
    from: break_lock + osdmap blocklist. The stale holder's later
    writes bounce EBLOCKLISTED at the OSD (the fence that makes the
    steal safe)."""
    async def t():
        from ceph_tpu.cluster.client import RadosClient

        c, rbd = await make()
        await rbd.create("disk", 64 * 1024, LAYOUT)
        img_a = await rbd.open("disk")
        # "dead" holder: ignores request_lock notifies
        img_a._header_notify = lambda *a: None
        await img_a.write(0, b"A" * 8192)
        assert img_a.lock_owned

        c2 = RadosClient(c.bus, name="client.1")
        await c2.connect()
        img_b = await RBD(c2, 1).open("disk")
        await img_b.acquire_lock(timeout=0.8)
        assert img_b.lock_owned
        assert "client.0" in c2.osdmap.blocklist
        await img_b.write(8192, b"B" * 8192)

        # the fenced holder cannot write anymore — not via rbd, not raw
        with pytest.raises(ConnectionAbortedError):
            await img_a.client.write_full(1, "fenced-probe", b"x")
        # B's view of the image is authoritative
        assert await img_b.read(8192, 8192) == b"B" * 8192
        await c2.close()
        await c.stop()

    run(t())


def test_object_map_fast_diff_and_flatten():
    """The object map tracks which data objects exist under the lock
    (ObjectMap.h role) and prunes flatten/remove sweeps."""
    async def t():
        c, rbd = await make()
        await rbd.create("disk", 10 * 8192, LAYOUT)
        img = await rbd.open("disk")
        await img.write(0, b"x" * 8192)          # object 0
        await img.write(3 * 8192, b"y" * 8192)   # object 3
        m = img.object_map()
        assert m is not None and list(m) == [1, 0, 0, 1] + [0] * 6

        # map survives a release/re-acquire (persisted bitmap)
        await img.release_lock()
        assert img.object_map() is None  # not authoritative unlocked
        await img.write(5 * 8192, b"z" * 100)    # re-acquires
        assert list(img.object_map()) == [1, 0, 0, 1, 0, 1] + [0] * 4

        # clone + flatten: the child's map prunes copy-up stats
        await img.snap_create("s1")
        await rbd.clone("disk", "s1", "child")
        child = await rbd.open("child")
        await child.write(0, b"c" * 100)   # child owns object 0
        await child.flatten()
        assert child.parent is None
        got = await child.read(3 * 8192, 8192)
        assert got == b"y" * 8192  # copied up from parent at flatten
        assert list(child.object_map())[:4] == [1, 0, 0, 1]
        await c.stop()

    run(t())


def test_object_cacher_rbd_write_back_and_fence():
    """ObjectCacher under rbd (ObjectCacher.h role): reads serve from
    cache after one fetch, writes buffer (write-back — nothing lands
    until a flush boundary), and the exclusive-lock release fence
    flushes so the next owner sees everything."""
    async def t():
        c, rbd = await make()
        await rbd.create("disk", 8 * 8192, LAYOUT)
        img = await rbd.open("disk", cache=True)
        await img.write(0, b"A" * 8192)
        # write-back: buffered, not yet on the OSDs
        assert img._cacher.dirty_bytes() == 8192
        assert await img.read(0, 8192) == b"A" * 8192  # served hot
        hits0 = img._cacher.hits
        await img.read(0, 100)
        await img.read(4000, 100)
        assert img._cacher.hits >= hits0 + 2  # no server round trips

        # the lock-release fence flushes; an UNCACHED second handle
        # (fresh client view) reads everything back
        await img.release_lock()
        assert img._cacher.dirty_bytes() == 0
        img2 = await rbd.open("disk")
        assert await img2.read(0, 8192) == b"A" * 8192

        # snapshot boundary flushes buffered writes into the snap
        await img.write(8192, b"B" * 8192)
        await img.snap_create("s")
        await img.write(8192, b"C" * 8192)
        await img.flush()
        snap_view = await rbd.open("disk", snap="s")
        assert await snap_view.read(8192, 8192) == b"B" * 8192
        assert await img.read(8192, 8192) == b"C" * 8192
        await c.stop()

    run(t())


def test_cache_coherent_across_rollback_and_shrink():
    """snap_rollback and shrink mutate objects server-side with the
    RAW client; a cached image must not serve (or later re-flush)
    pre-rollback / past-the-cut bytes (round-5 review finding)."""
    async def t():
        c, rbd = await make()
        await rbd.create("disk", 8 * 8192, LAYOUT)
        img = await rbd.open("disk", cache=True)
        await img.write(0, b"A" * 8192)
        await img.snap_create("s")          # fence: A is in the snap
        assert await img.read(0, 8192) == b"A" * 8192  # cached clean
        await img.write(0, b"B" * 8192)     # buffered dirty
        await img.snap_rollback("s")
        # rollback wins over both the cached clean A-copy and the
        # buffered B write (flushed before the rollback rewrote it)
        assert await img.read(0, 8192) == b"A" * 8192
        img2 = await rbd.open("disk")
        assert await img2.read(0, 8192) == b"A" * 8192

        # shrink: cached bytes past the cut must die with the resize
        await img.write(8192, b"C" * 8192)
        assert await img.read(8192, 8192) == b"C" * 8192
        await img.resize(8192 + 100)
        await img.resize(2 * 8192)
        tail = await img.read(8192, 8192)
        assert tail == b"C" * 100 + b"\x00" * (8192 - 100)
        await c.stop()

    run(t())


def test_deep_copy_with_snapshot_history():
    """deep_copy replays every snapshot level: dst@s == src@s for all
    s, head matches, and a new layout is honored (DeepCopyRequest
    role)."""
    async def t():
        c, rbd = await make()
        await rbd.create("src", 4 * 8192, LAYOUT)
        img = await rbd.open("src")
        await img.write(0, b"v1" * 4096)
        await img.snap_create("s1")
        await img.write(8192, b"v2" * 4096)
        await img.snap_create("s2")
        await img.write(0, b"v3" * 4096)
        await img.release_lock()

        new_layout = FileLayout(stripe_unit=4096, stripe_count=2,
                                object_size=16384)
        await rbd.deep_copy("src", "dst", layout=new_layout)
        dst = await rbd.open("dst")
        assert dst.snaps == ["s1", "s2"]
        assert dst.layout.object_size == 16384
        assert await dst.read(0, 8192) == b"v3" * 4096
        assert await dst.read(8192, 8192) == b"v2" * 4096
        for s, want0, want1 in [("s1", b"v1" * 4096, b"\x00" * 8192),
                                ("s2", b"v1" * 4096, b"v2" * 4096)]:
            view = await rbd.open("dst", snap=s)
            assert await view.read(0, 8192) == want0
            assert await view.read(8192, 8192) == want1
        # the copy is independent of the source
        img2 = await rbd.open("src")
        await img2.write(0, b"XX")
        assert (await dst.read(0, 2)) == b"v3"
        await c.stop()

    run(t())


def test_migration_lifecycle():
    """prepare -> target serves reads/writes with source fallback ->
    execute moves data+snaps -> commit retires the source
    (librbd api/Migration.cc role)."""
    async def t():
        c, rbd = await make()
        await rbd.create("old", 4 * 8192, LAYOUT)
        img = await rbd.open("old")
        await img.write(0, b"A" * 8192)
        await img.snap_create("s")
        await img.write(8192, b"B" * 8192)
        await img.release_lock()

        new_layout = FileLayout(stripe_unit=8192, stripe_count=1,
                                object_size=16384)
        await rbd.migration_prepare("old", "new", layout=new_layout)
        # the source refuses normal opens now
        with pytest.raises(RuntimeError, match="mid-migration"):
            await rbd.open("old")
        # the target serves the source's data before any copy happened
        dst = await rbd.open("new")
        assert await dst.read(0, 8192) == b"A" * 8192
        assert await dst.read(8192, 8192) == b"B" * 8192
        # a write to the target copies up and sticks (into dst object
        # 1 = bytes [16384, 32768), a source hole — so dst object 0
        # stays unowned and its snapshot history replays properly)
        await dst.write(16384 + 100, b"LIVE")
        assert (await dst.read(16384 + 96, 12)
                ) == b"\x00" * 4 + b"LIVE" + b"\x00" * 4
        # commit before execute is refused
        with pytest.raises(RuntimeError, match="not executed"):
            await rbd.migration_commit("new")
        await rbd.migration_execute("new")
        await rbd.migration_commit("new")
        # source is gone; target stands alone with the snap history
        with pytest.raises(ImageNotFound):
            await rbd.open("old")
        dst2 = await rbd.open("new")
        assert dst2.snaps == ["s"]
        assert await dst2.read(0, 8192) == b"A" * 8192
        assert await dst2.read(8192, 8192) == b"B" * 8192
        got = await dst2.read(16384 + 96, 12)
        assert got == b"\x00" * 4 + b"LIVE" + b"\x00" * 4
        snap_view = await rbd.open("new", snap="s")
        # object 0 was never client-written: its history replayed
        # properly — at snap s the B range did not exist yet
        assert await snap_view.read(0, 8192) == b"A" * 8192
        assert await snap_view.read(8192, 8192) == b"\x00" * 8192
        # object 1 was client-written post-prepare: its history
        # collapses onto the written content (documented lite
        # semantics)
        assert (await snap_view.read(16384 + 100, 4)) == b"LIVE"
        await c.stop()

    run(t())


def test_migration_abort_restores_source():
    async def t():
        c, rbd = await make()
        await rbd.create("keep", 2 * 8192, LAYOUT)
        img = await rbd.open("keep")
        await img.write(0, b"K" * 100)
        await img.release_lock()
        await rbd.migration_prepare("keep", "scrapped")
        dst = await rbd.open("scrapped")
        await dst.write(0, b"doomed")
        await rbd.migration_abort("scrapped")
        with pytest.raises(ImageNotFound):
            await rbd.open("scrapped")
        img2 = await rbd.open("keep")  # source serves again, untouched
        assert await img2.read(0, 100) == b"K" * 100
        await c.stop()

    run(t())


def test_wide_striping_flatten_rollback_remove():
    """Regression: _object_count assumed sequential layout; with
    stripe_count > 1 a small image spreads over the whole object SET,
    and flatten/rollback/remove must sweep every object of the set
    (Striper::get_num_objects role)."""
    async def t():
        c, rbd = await make()
        wide = FileLayout(stripe_unit=4096, stripe_count=4,
                          object_size=16384)
        await rbd.create("w", 64 * 1024, wide)
        img = await rbd.open("w")
        rng = np.random.default_rng(9)
        data = rng.integers(0, 256, 50_000, dtype=np.uint8).tobytes()
        await img.write(0, data)
        await img.snap_create("s")
        await img.write(0, b"\x55" * len(data))
        await rbd.clone("w", "s", "wc")
        child = await rbd.open("wc")
        await child.flatten()
        # every object of the set was copied up, not just object 0
        assert await child.read(0, len(data)) == data
        await child.release_lock()
        # rollback sweeps the whole set too
        await img.snap_rollback("s")
        assert await img.read(0, len(data)) == data
        await img.release_lock()
        # remove leaves no stray data objects behind
        await rbd.remove("wc")
        with pytest.raises(ImageNotFound):
            await rbd.open("wc")
        await c.stop()

    run(t())


def test_wide_striping_shrink_keeps_live_data():
    """Regression: shrink used sequential object math and deleted
    mid-set objects holding live striped data."""
    async def t():
        c, rbd = await make()
        wide = FileLayout(stripe_unit=4096, stripe_count=4,
                          object_size=16384)
        await rbd.create("w", 64 * 1024, wide)
        img = await rbd.open("w")
        rng = np.random.default_rng(13)
        data = rng.integers(0, 256, 64 * 1024,
                            dtype=np.uint8).tobytes()
        await img.write(0, data)
        # shrink to 16384: stripe units 0-3 live at offset 0 of
        # objects 0-3 — the old math deleted objects 1..3 outright
        await img.resize(16384)
        assert await img.read(0, 16384) == data[:16384]
        # grow back: the cut range reads as zeros, the kept prefix
        # stays intact
        await img.resize(64 * 1024)
        assert await img.read(0, 16384) == data[:16384]
        assert await img.read(16384, 4096) == b"\x00" * 4096
        await img.release_lock()
        await c.stop()

    run(t())


def test_retained_bytes_matches_extent_enumeration():
    """Property check: the closed-form shrink math equals brute-force
    extent enumeration across randomized layouts."""
    import random

    from ceph_tpu.osdc.striper import file_to_extents
    from ceph_tpu.services.rbd import retained_bytes

    random.seed(7)
    for _ in range(500):
        su = random.choice([512, 4096, 65536])
        sc = random.choice([1, 2, 4, 7])
        upo = random.choice([1, 2, 4, 8])
        lo = FileLayout(stripe_unit=su, stripe_count=sc,
                        object_size=su * upo)
        upto = random.randrange(0, su * upo * sc * 3 + 3)
        want = {}
        if upto:
            for ex in file_to_extents(lo, 0, upto, "o{objectno}"):
                want[ex.objectno] = max(want.get(ex.objectno, 0),
                                        ex.offset + ex.length)
        hi = max(want.keys(), default=-1) + 3
        for objno in range(hi):
            assert retained_bytes(lo, upto, objno) == \
                want.get(objno, 0), (lo, upto, objno)


def test_trash_lifecycle():
    """rbd trash mv/ls/restore/rm/purge: deferred delete with the
    name reserved while trashed (data objects are name-keyed here)."""
    async def t():
        c, rbd = await make()
        await rbd.create("disk", 32 * 1024, LAYOUT)
        img = await rbd.open("disk")
        await img.write(0, b"precious" * 512)
        await img.release_lock()
        tid = await rbd.trash_move("disk", delay_s=3600)
        assert await rbd.list() == []
        ents = await rbd.trash_list()
        assert len(ents) == 1 and ents[0]["name"] == "disk" \
            and ents[0]["id"] == tid
        # the name is reserved while trashed
        with pytest.raises(ImageExists):
            await rbd.create("disk", 1024)
        # inside the deferment window rm refuses without force
        with pytest.raises(RuntimeError):
            await rbd.trash_remove(tid)
        # restore brings the image back intact
        assert await rbd.trash_restore(tid) == "disk"
        img = await rbd.open("disk")
        assert (await img.read(0, 8))[:8] == b"precious"
        await img.release_lock()
        assert await rbd.trash_list() == []
        # trash again and force-remove: data really gone
        tid = await rbd.trash_move("disk")
        await rbd.trash_remove(tid, force=True)
        assert await rbd.list() == []
        assert await rbd.trash_list() == []
        await rbd.create("disk", 1024)  # name free again
        # purge honors deferment
        await rbd.create("short", 4096, LAYOUT)
        await rbd.create("long", 4096, LAYOUT)
        await rbd.trash_move("short")
        await rbd.trash_move("long", delay_s=3600)
        assert await rbd.trash_purge() == ["short"]
        assert [e["name"] for e in await rbd.trash_list()] == ["long"]
        await c.stop()

    run(t())


def test_groups_and_group_snapshots():
    """Consistency groups: membership, the all-member lock barrier on
    group snapshots, and group rollback."""
    async def t():
        c, rbd = await make()
        await rbd.create("a", 16 * 1024, LAYOUT)
        await rbd.create("b", 16 * 1024, LAYOUT)
        await rbd.group_create("g")
        with pytest.raises(ImageExists):
            await rbd.group_create("g")
        assert await rbd.group_list() == ["g"]
        await rbd.group_image_add("g", "a")
        await rbd.group_image_add("g", "b")
        with pytest.raises(ImageExists):  # already in a group
            await rbd.group_image_add("g", "a")
        assert await rbd.group_image_list("g") == ["a", "b"]
        # a grouped image cannot be removed or trashed
        with pytest.raises(RuntimeError):
            await rbd.remove("a")
        with pytest.raises(RuntimeError):
            await rbd.trash_move("a")
        # write state, snap the group, overwrite, roll back
        ia = await rbd.open("a")
        ib = await rbd.open("b")
        await ia.write(0, b"A1" * 100)
        await ib.write(0, b"B1" * 100)
        await ia.release_lock()
        await ib.release_lock()
        await rbd.group_snap_create("g", "s1")
        snaps = await rbd.group_snap_list("g")
        assert snaps[0]["name"] == "s1" \
            and len(snaps[0]["members"]) == 2
        # member images carry the per-image group snap
        ia = await rbd.open("a")
        assert any(s.startswith(".group.g.") for s in ia.snaps)
        await ia.write(0, b"A2" * 100)
        await ia.release_lock()
        await rbd.group_snap_rollback("g", "s1")
        ia = await rbd.open("a")
        assert (await ia.read(0, 4)) == b"A1A1"
        await ia.release_lock()
        # snap removal then group teardown
        await rbd.group_snap_remove("g", "s1")
        assert await rbd.group_snap_list("g") == []
        await rbd.group_remove("g")
        assert await rbd.group_list() == []
        await rbd.remove("a")  # detached: removable again
        await c.stop()

    run(t())
