"""Compound object operations: op vectors, xattrs, omap, partial
writes/append/zero/truncate, atomicity (the librados ObjectOperation +
do_osd_ops surface)."""
import asyncio

import pytest

from ceph_tpu.cluster.client import ObjectOperation
from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2"}


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make_rep(n=4):
    c = TestCluster(n_osds=n)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="rep", size=3, pg_num=8, crush_rule=0)
    )
    await c.wait_active(20)
    return c


async def make_ec(n=5):
    c = TestCluster(n_osds=n)
    await c.start()
    await c.client.create_pool(
        Pool(id=2, name="ec", size=5, min_size=3, pg_num=8, crush_rule=1,
             type="erasure", ec_profile=dict(EC_PROFILE))
    )
    await c.wait_active(20)
    return c


def test_partial_writes_append_zero_truncate():
    async def t():
        c = await make_rep()
        cl = c.client
        await cl.write_full(1, "o", b"0123456789")
        await cl.write(1, "o", 3, b"XYZ")
        assert await cl.read(1, "o") == b"012XYZ6789"
        await cl.append(1, "o", b"++")
        assert await cl.read(1, "o") == b"012XYZ6789++"
        await cl.zero(1, "o", 1, 2)
        assert await cl.read(1, "o") == b"0\0\0XYZ6789++"
        await cl.truncate(1, "o", 4)
        assert await cl.read(1, "o") == b"0\0\0X"
        await cl.truncate(1, "o", 8)  # grow zero-fills
        assert await cl.read(1, "o") == b"0\0\0X\0\0\0\0"
        # sparse write past the end
        await cl.write(1, "o", 12, b"end")
        assert await cl.stat(1, "o") == 15
        await c.stop()

    run(t())


def test_xattrs_roundtrip_replicated():
    async def t():
        c = await make_rep()
        cl = c.client
        await cl.write_full(1, "o", b"data")
        await cl.setxattr(1, "o", "owner", b"alice")
        await cl.setxattr(1, "o", "mode", b"0644")
        assert await cl.getxattr(1, "o", "owner") == b"alice"
        assert await cl.getxattrs(1, "o") == {
            "owner": b"alice", "mode": b"0644"
        }
        await cl.rmxattr(1, "o", "mode")
        assert await cl.getxattrs(1, "o") == {"owner": b"alice"}
        with pytest.raises(IOError):
            await cl.getxattr(1, "o", "mode")
        # xattrs survive a data overwrite
        await cl.write_full(1, "o", b"newdata")
        assert await cl.getxattr(1, "o", "owner") == b"alice"
        await c.stop()

    run(t())


def test_omap_roundtrip_replicated():
    async def t():
        c = await make_rep()
        cl = c.client
        await cl.write_full(1, "idx", b"")
        await cl.omap_set(1, "idx", {b"k1": b"v1", b"k2": b"v2"})
        assert await cl.omap_get(1, "idx") == {b"k1": b"v1", b"k2": b"v2"}
        await cl.omap_rm(1, "idx", [b"k1"])
        assert await cl.omap_get(1, "idx") == {b"k2": b"v2"}
        await c.stop()

    run(t())


def test_omap_rejected_on_ec_pool():
    async def t():
        c = await make_ec()
        await c.client.write_full(2, "o", b"x" * 1000)
        with pytest.raises(IOError, match="-95"):
            await c.client.omap_set(2, "o", {b"k": b"v"})
        await c.stop()

    run(t())


def test_xattrs_on_ec_pool_survive_recovery():
    async def t():
        c = await make_ec()
        cl = c.client
        await cl.write_full(2, "o", b"payload" * 500)
        await cl.setxattr(2, "o", "tag", b"gold")
        assert await cl.getxattr(2, "o", "tag") == b"gold"
        # kill the primary: new primary must still serve the xattr
        pgid = cl.osdmap.object_to_pg(2, b"o")
        up, primary = c.mon.osdmap.pg_to_up_acting_osds(pgid)
        await c.kill_osd(primary)
        await c.wait_down(primary, 20)
        await c.wait_active(30)
        assert await cl.getxattr(2, "o", "tag") == b"gold"
        assert await cl.read(2, "o") == b"payload" * 500
        await c.stop()

    run(t())


def test_compound_atomic_and_read_your_writes():
    async def t():
        c = await make_rep()
        cl = c.client
        op = (ObjectOperation()
              .create()
              .write_full(b"hello world")
              .setxattr("lang", b"en")
              .omap_set({b"seq": b"1"})
              .read()
              .stat())
        outs = await cl.operate(1, "doc", op)
        assert outs[4] == b"hello world"  # read sees earlier write
        # failing op aborts the WHOLE vector: the write must not land
        bad = (ObjectOperation()
               .write_full(b"SHOULD NOT PERSIST")
               .getxattr("nonexistent"))
        with pytest.raises(IOError):
            await cl.operate(1, "doc", bad)
        assert await cl.read(1, "doc") == b"hello world"
        assert await cl.getxattr(1, "doc", "lang") == b"en"
        # exclusive create on an existing object fails
        with pytest.raises(IOError, match="-17"):
            await cl.operate(1, "doc", ObjectOperation().create())
        await c.stop()

    run(t())


def test_read_nonexistent_still_enoent():
    async def t():
        c = await make_rep()
        with pytest.raises(KeyError):
            await c.client.read(1, "ghost")
        with pytest.raises(KeyError):
            await c.client.stat(1, "ghost")
        await c.stop()

    run(t())
