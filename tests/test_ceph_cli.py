"""MonCommand surface + `ceph` CLI (src/ceph.in + MonCommands.h roles):
argv matching against the served descriptor table, map/status/pool
commands, pool quotas (FLAG_FULL_QUOTA), and pool deletion."""
import asyncio
import json

import pytest

from ceph_tpu.cluster import moncommands
from ceph_tpu.cluster import messages as M
from ceph_tpu.cluster.client import RadosError
from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool


def run(coro):
    asyncio.run(asyncio.wait_for(coro, 120))


async def make(n_osds=4):
    c = TestCluster(n_osds=n_osds)
    await c.start()
    await c.client.create_pool(
        Pool(id=1, name="p", size=3, pg_num=8, crush_rule=0))
    await c.wait_active(20)
    return c


# ------------------------------------------------------- argv matching


def test_match_argv():
    assert moncommands.match_argv(["status"]) == {"prefix": "status"}
    assert moncommands.match_argv(["osd", "tree"]) == {
        "prefix": "osd tree"}
    # literal-prefix beats shorter commands; params coerce types
    got = moncommands.match_argv(["osd", "pool", "set", "p",
                                  "pg_num", "16"])
    assert got == {"prefix": "osd pool set", "pool": "p",
                   "var": "pg_num", "val": "16"}
    got = moncommands.match_argv(["osd", "out", "1", "3"])
    assert got == {"prefix": "osd out", "ids": [1, 3]}
    got = moncommands.match_argv(["osd", "reweight", "2", "0.5"])
    assert got["id"] == 2 and got["weight"] == 0.5
    # optional arg omitted / present
    assert moncommands.match_argv(["health"]) == {"prefix": "health"}
    assert moncommands.match_argv(["health", "detail"])["detail"] \
        == "detail"
    # junk does not match
    assert moncommands.match_argv(["osd", "frobnicate"]) is None
    assert moncommands.match_argv(["osd", "reweight", "2", "x"]) is None


def test_descriptions_served():
    async def t():
        c = await make(n_osds=3)
        try:
            rc, _outs, outb = await c.client.mon_command(
                ["get_command_descriptions"])
            assert rc == 0
            descs = json.loads(outb)
            prefixes = {d["prefix"] for d in descs}
            assert {"status", "osd tree", "osd pool create",
                    "config dump"} <= prefixes
        finally:
            await c.stop()

    run(t())


# -------------------------------------------------------- map commands


def test_status_health_tree_and_df():
    async def t():
        c = await make()
        try:
            for i in range(6):
                await c.client.write_full(1, f"o{i}", b"z" * 500)
            # poll until OSD reports -> mgr digest -> mon land (the
            # stats path is throttled at ~2 s + 1 s digest tick)
            for _ in range(60):
                rc, outs, outb = await c.client.mon_command(["status"])
                assert rc == 0
                st = json.loads(outb)
                if st["pgmap"]["objects"] == 6:
                    break
                await asyncio.sleep(0.25)
            assert st["osdmap"]["num_up_osds"] == 4
            assert st["pgmap"]["num_pools"] == 1
            assert st["pgmap"]["pgs_by_state"].get("active", 0) > 0
            assert st["pgmap"]["objects"] == 6
            assert "HEALTH_OK" in outs

            rc, outs, _ = await c.client.mon_command(["health"])
            assert rc == 0 and outs.startswith("HEALTH_OK")

            rc, outs, outb = await c.client.mon_command(["osd", "tree"])
            assert rc == 0
            nodes = json.loads(outb)
            osd_rows = [n for n in nodes if n["type"] == "osd"]
            assert len(osd_rows) == 4
            assert all(n["status"] == "up" for n in osd_rows)

            rc, _, outb = await c.client.mon_command(["df"])
            pools = json.loads(outb)["pools"]
            assert pools[0]["name"] == "p"
            assert pools[0]["objects"] == 6
            # size-3 replication: raw stored bytes ~ 3 * 6 * 500
            assert pools[0]["stored_bytes"] >= 3 * 6 * 500

            rc, outs, outb = await c.client.mon_command(["pg", "stat"])
            assert rc == 0 and json.loads(outb)["num_pgs"] > 0

            rc, _, outb = await c.client.mon_command(["osd", "ls"])
            assert json.loads(outb) == [0, 1, 2, 3]
        finally:
            await c.stop()

    run(t())


def test_osd_out_in_and_reweight():
    async def t():
        c = await make()
        try:
            rc, outs, _ = await c.client.mon_command(["osd", "out", "3"])
            assert rc == 0
            assert c.mon.osdmap.osds[3].weight == 0
            rc, _, _ = await c.client.mon_command(["osd", "in", "3"])
            assert c.mon.osdmap.osds[3].weight == 0x10000
            rc, _, _ = await c.client.mon_command(
                ["osd", "reweight", "3", "0.25"])
            assert c.mon.osdmap.osds[3].weight == 0x4000
            rc, outs, _ = await c.client.mon_command(
                ["osd", "reweight", "9", "0.5"])
            assert rc == M.ENOENT
        finally:
            await c.stop()

    run(t())


def test_pool_create_get_set_and_config():
    async def t():
        c = await make(n_osds=3)
        try:
            rc, outs, outb = await c.client.mon_command(
                ["osd", "pool", "create", "rep2", "8", "replicated",
                 "2"])
            assert rc == 0
            pid = json.loads(outb)["pool_id"]
            assert c.mon.osdmap.pools[pid].size == 2

            rc, _, outb = await c.client.mon_command(
                ["osd", "pool", "ls"])
            assert set(json.loads(outb)) == {"p", "rep2"}

            rc, _, outb = await c.client.mon_command(
                ["osd", "pool", "get", "rep2", "size"])
            assert json.loads(outb) == {"size": 2}

            rc, _, _ = await c.client.mon_command(
                ["osd", "pool", "set", "rep2", "pg_num", "16"])
            assert rc == 0
            assert c.mon.osdmap.pools[pid].pg_num == 16

            rc, _, _ = await c.client.mon_command(
                ["config", "set", "osd", "debug_level", "3"])
            assert rc == 0
            rc, outs, _ = await c.client.mon_command(
                ["config", "get", "osd", "debug_level"])
            assert outs == "3"
            rc, _, outb = await c.client.mon_command(["config", "dump"])
            assert any(e["key"] == "debug_level"
                       for e in json.loads(outb))
        finally:
            await c.stop()

    run(t())


def test_blocklist_commands():
    async def t():
        c = await make(n_osds=3)
        try:
            rc, _, _ = await c.client.mon_command(
                ["osd", "blocklist", "add", "client.evil"])
            assert rc == 0
            await c.client._await_epoch(c.mon.osdmap.epoch)
            rc, _, outb = await c.client.mon_command(
                ["osd", "blocklist", "ls"])
            assert json.loads(outb) == ["client.evil"]
            rc, _, _ = await c.client.mon_command(
                ["osd", "blocklist", "rm", "client.evil"])
            assert rc == 0
            rc, _, outb = await c.client.mon_command(
                ["osd", "blocklist", "ls"])
            assert json.loads(outb) == []
        finally:
            await c.stop()

    run(t())


# ------------------------------------------------------------- quotas


def test_pool_quota_blocks_writes_and_clears():
    async def t():
        c = await make()
        try:
            rc, _, _ = await c.client.mon_command(
                ["osd", "pool", "set", "p", "quota_max_objects", "3"])
            assert rc == 0
            for i in range(4):
                await c.client.write_full(1, f"q{i}", b"d" * 64)
            # wait for stats to flow and the mon to flag the pool full
            for _ in range(80):
                if c.client.osdmap.pools[1].full:
                    break
                await asyncio.sleep(0.25)
            assert c.client.osdmap.pools[1].full
            with pytest.raises(RadosError) as ei:
                await c.client.write_full(1, "overflow", b"x")
            assert ei.value.code == M.EDQUOT
            # reads still work on a full pool
            assert await c.client.read(1, "q0") == b"d" * 64
            h = moncommands._health(c.mon)
            assert "POOL_FULL" in h["checks"]
            # lift the quota: the flag clears and writes resume
            rc, _, _ = await c.client.mon_command(
                ["osd", "pool", "set", "p", "quota_max_objects", "0"])
            for _ in range(80):
                if not c.client.osdmap.pools[1].full:
                    break
                await asyncio.sleep(0.25)
            assert not c.client.osdmap.pools[1].full
            await c.client.write_full(1, "overflow", b"x")
        finally:
            await c.stop()

    run(t())


def test_full_pool_allows_delete_and_self_clears():
    """FULL_TRY stance: a quota-FULL pool must accept deletes so space
    can be reclaimed and the FULL flag can clear WITHOUT raising the
    quota — otherwise the pool is wedged forever."""
    async def t():
        c = await make()
        try:
            rc, _, _ = await c.client.mon_command(
                ["osd", "pool", "set", "p", "quota_max_objects", "3"])
            assert rc == 0
            # exactly 3 writes: the flag trips at objs >= quota, so a
            # 4th write would race the stats digest and flake
            for i in range(3):
                await c.client.write_full(1, f"q{i}", b"d" * 64)
            for _ in range(80):
                if c.client.osdmap.pools[1].full:
                    break
                await asyncio.sleep(0.25)
            assert c.client.osdmap.pools[1].full
            with pytest.raises(RadosError):
                await c.client.write_full(1, "overflow", b"x")
            # deletes ride through the FULL flag
            await c.client.delete(1, "q0")
            await c.client.delete(1, "q1")
            # with usage back under quota the mon clears FULL and
            # writes resume — the flag self-clears via reclamation
            for _ in range(80):
                if not c.client.osdmap.pools[1].full:
                    break
                await asyncio.sleep(0.25)
            assert not c.client.osdmap.pools[1].full
            await c.client.write_full(1, "after", b"x")
        finally:
            await c.stop()

    run(t())


# ----------------------------------------------------------- pool rm


def test_pool_rm_requires_triple_interlock():
    """Pool deletion is gated like the reference: the
    mon_allow_pool_delete config flag, the name twice, and the
    --yes-i-really-really-mean-it literal — each missing piece is
    EPERM and the pool survives."""
    async def t():
        c = await make()
        try:
            # config flag off: refused regardless of confirmations
            rc, outs, _ = await c.client.mon_command(
                ["osd", "pool", "rm", "p", "p",
                 "--yes-i-really-really-mean-it"])
            assert rc == M.EPERM
            assert "mon_allow_pool_delete" in outs
            rc, _, _ = await c.client.mon_command(
                ["config", "set", "mon", "mon_allow_pool_delete",
                 "true"])
            assert rc == 0
            # flag on, but no / wrong confirmation: still refused
            rc, outs, _ = await c.client.mon_command(
                ["osd", "pool", "rm", "p"])
            assert rc == M.EPERM
            rc, _, _ = await c.client.mon_command(
                ["osd", "pool", "rm", "p", "q",
                 "--yes-i-really-really-mean-it"])
            assert rc == M.EPERM
            rc, _, _ = await c.client.mon_command(
                ["osd", "pool", "rm", "p", "p"])
            assert rc == M.EPERM
            assert 1 in c.mon.osdmap.pools
        finally:
            await c.stop()

    run(t())


def test_pool_rm_drops_pgs_and_objects():
    async def t():
        c = await make()
        try:
            for i in range(5):
                await c.client.write_full(1, f"del{i}", b"y" * 128)
            rc, _, _ = await c.client.mon_command(
                ["config", "set", "mon", "mon_allow_pool_delete",
                 "true"])
            assert rc == 0
            rc, _, _ = await c.client.mon_command(
                ["osd", "pool", "rm", "p", "p",
                 "--yes-i-really-really-mean-it"])
            assert rc == 0
            assert 1 not in c.mon.osdmap.pools
            # OSDs drop the pool's PGs + collections on the new epoch
            for _ in range(40):
                left = [k for o in c.osds if o is not None
                        for k in o.pgs if k[0] == 1]
                if not left:
                    break
                await asyncio.sleep(0.1)
            assert not left
            rc, _, _ = await c.client.mon_command(
                ["osd", "pool", "rm", "p", "p",
                 "--yes-i-really-really-mean-it"])
            assert rc == M.ENOENT
        finally:
            await c.stop()

    run(t())


def test_osd_df_and_upmap_commands():
    async def t():
        c = await make()
        try:
            for i in range(4):
                await c.client.write_full(1, f"d{i}", b"w" * 1000)
            rc, outs, outb = await c.client.mon_command(
                ["osd", "df"])
            assert rc == 0
            rows = json.loads(outb)
            assert len(rows) == 4
            # stats flow on the digest tick: poll until EVERY replica's
            # usage landed (a lone early heartbeat reports a partial
            # sum that would flake the assertion below)
            for _ in range(60):
                rc, _, outb = await c.client.mon_command(["osd", "df"])
                rows = json.loads(outb)
                if sum(r["used_bytes"] for r in rows) >= 4 * 1000:
                    break
                await asyncio.sleep(0.25)
            assert sum(r["used_bytes"] for r in rows) >= 4 * 1000
            assert all(r["pgs"] > 0 for r in rows)
            # upmap: swap one PG's replica, then clear it
            up, _ = c.mon.osdmap.pg_to_up_acting_osds((1, 0))
            absent = next(i for i in range(4) if i not in up)
            rc, outs, _ = await c.client.mon_command(
                ["osd", "pg-upmap-items", "1.0",
                 str(up[0]), str(absent)])
            assert rc == 0
            up2, _ = c.mon.osdmap.pg_to_up_acting_osds((1, 0))
            assert absent in up2 and up[0] not in up2
            rc, _, _ = await c.client.mon_command(
                ["osd", "rm-pg-upmap-items", "1.0"])
            assert rc == 0
            up3, _ = c.mon.osdmap.pg_to_up_acting_osds((1, 0))
            assert up3 == up
            # bad pgid -> -22, not a crash
            rc, _, _ = await c.client.mon_command(
                ["osd", "pg-upmap-items", "junk", "0", "1"])
            assert rc == -22
        finally:
            await c.stop()

    run(t())


def test_rados_namespaces_ioctx():
    """IoCtx namespace scoping (rados_ioctx_set_namespace role): same
    object names coexist per namespace; listings are scoped; the
    default namespace rejects the reserved lead byte."""
    async def t():
        c = await make(n_osds=3)
        try:
            blue = c.client.ioctx(1, "blue")
            green = c.client.ioctx(1, "green")
            await c.client.write_full(1, "obj", b"default")
            await blue.write_full(1, "obj", b"blue")
            await green.write_full(1, "obj", b"green")
            assert await c.client.read(1, "obj") == b"default"
            assert await blue.read(1, "obj") == b"blue"
            assert await green.read(1, "obj") == b"green"
            assert await blue.list_objects(1) == [b"obj"]
            assert sorted(await blue.ioctx(1).list_namespaces(1)) == [
                "", "blue", "green"]
            # xattrs/omap ride the same scoping
            await blue.setxattr(1, "obj", "k", b"v")
            assert await blue.getxattr(1, "obj", "k") == b"v"
            import pytest as _pt
            # missing xattr on an EXISTING object is ENODATA, not
            # KeyError (KeyError maps only from ENOENT; other callers
            # rely on the ENODATA distinction — see absent_attr)
            with _pt.raises(RadosError) as ei:
                await green.getxattr(1, "obj", "k")
            assert ei.value.code == RadosError.ENODATA
            # delete is scoped
            await blue.delete(1, "obj")
            with _pt.raises(KeyError):
                await blue.read(1, "obj")
            assert await green.read(1, "obj") == b"green"
            # default namespace: reserved byte refused
            with _pt.raises(ValueError):
                await c.client.ioctx(1).write_full(1, b"\x1ex", b"d")
        finally:
            await c.stop()

    run(t())


def test_rbd_pool_namespaces():
    """rbd pool namespaces: registry create/ls/rm, per-namespace image
    directories, and non-empty protection."""
    from ceph_tpu.osdc.striper import FileLayout
    from ceph_tpu.services import RBD

    lo = FileLayout(stripe_unit=8192, stripe_count=1,
                    object_size=8192)

    async def t():
        c = await make(n_osds=3)
        try:
            rbd = RBD(c.client, 1)
            await rbd.namespace_create("tenant-a")
            await rbd.namespace_create("tenant-b")
            assert await rbd.namespace_list() == ["tenant-a",
                                                  "tenant-b"]
            ra = RBD(c.client, 1, namespace="tenant-a")
            rb = RBD(c.client, 1, namespace="tenant-b")
            await rbd.create("disk", 16 * 1024, lo)
            await ra.create("disk", 16 * 1024, lo)  # same name, own ns
            await rb.create("other", 16 * 1024, lo)
            assert await rbd.list() == ["disk"]
            assert await ra.list() == ["disk"]
            assert await rb.list() == ["other"]
            ia = await ra.open("disk")
            await ia.write(0, b"tenant-a data")
            await ia.release_lock()
            i0 = await rbd.open("disk")
            assert await i0.read(0, 13) == b"\0" * 13  # isolated
            await i0.release_lock()
            # trash is per-namespace too
            tid = await ra.trash_move("disk")
            assert await ra.list() == [] and await rbd.list() == ["disk"]
            import pytest as _pt
            with _pt.raises(RuntimeError):  # trash entry keeps it busy
                await rbd.namespace_remove("tenant-a")
            await ra.trash_restore(tid)
            await ra.remove("disk")
            await rbd.namespace_remove("tenant-a")
            assert await rbd.namespace_list() == ["tenant-b"]
            with _pt.raises(RuntimeError):
                await rbd.namespace_remove("tenant-b")
        finally:
            await c.stop()

    run(t())
