"""PG split + pg_autoscaler: live pg_num growth (PG.cc:546 split_into
role) with IO continuing, pgp_num re-placement, and the mgr loop.

Acceptance (VERDICT r2 item 6): a pool goes 8 -> 32 PGs under load
with no lost or misplaced-forever objects.
"""
import asyncio

import numpy as np
import pytest

from ceph_tpu.cluster import autoscaler
from ceph_tpu.cluster.vstart import TestCluster
from ceph_tpu.placement.osdmap import Pool

EC_PROFILE = {"plugin": "rs_tpu", "k": "3", "m": "2"}


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(asyncio.wait_for(coro, 180))
    finally:
        loop.close()


async def make(pool_type="replicated", n=5, pg_num=8):
    c = TestCluster(n_osds=n)
    await c.start()
    if pool_type == "replicated":
        await c.client.create_pool(
            Pool(id=1, name="p", size=3, pg_num=pg_num, crush_rule=0))
        pid = 1
    else:
        await c.client.create_pool(
            Pool(id=2, name="p", size=5, min_size=3, pg_num=pg_num,
                 crush_rule=1, type="erasure",
                 ec_profile=dict(EC_PROFILE)))
        pid = 2
    await c.wait_active(20)
    return c, pid


@pytest.mark.parametrize("pool_type", ["replicated", "erasure"])
def test_split_8_to_32_under_load(pool_type):
    async def t():
        c, pid = await make(pool_type)
        rng = np.random.default_rng(3)
        objs = {}
        for i in range(40):
            name = f"pre{i}"
            objs[name] = bytes(rng.integers(0, 256, 2000 + 17 * i,
                                            dtype=np.uint8))
            await c.client.write_full(pid, name, objs[name])

        stop = asyncio.Event()
        written_during: dict[str, bytes] = {}

        async def writer(wid):
            i = 0
            while not stop.is_set():
                name = f"live{wid}-{i}"
                data = bytes(rng.integers(0, 256, 1500,
                                          dtype=np.uint8))
                await c.client.write_full(pid, name, data)
                written_during[name] = data
                i += 1
                await asyncio.sleep(0)

        writers = [asyncio.ensure_future(writer(w)) for w in range(3)]
        await asyncio.sleep(0.1)
        # the live split: 8 -> 32 while writes keep flowing
        await c.client.set_pool_param(pid, "pg_num", 32)
        await c.wait_active(30)
        await asyncio.sleep(0.2)
        stop.set()
        await asyncio.gather(*writers)
        assert c.mon.osdmap.pools[pid].pg_num == 32

        objs.update(written_during)
        assert len(written_during) > 0
        # every object readable, nothing lost or duplicated
        for name, data in objs.items():
            assert await c.client.read(pid, name) == data, name
        listed = await c.client.list_objects(pid)
        assert sorted(listed) == sorted(n.encode() for n in objs)

        # phase 2: re-place the children and verify again
        await c.client.set_pool_param(pid, "pgp_num", 32)
        await c.wait_active(40)
        for name, data in objs.items():
            assert await c.client.read(pid, name) == data, name
        await c.stop()

    run(t())


def test_split_preserves_snapshots():
    async def t():
        c, pid = await make("replicated")
        v1 = b"epoch-one" * 300
        await c.client.write_full(pid, "o", v1)
        snapid = await c.client.selfmanaged_snap_create(pid)
        await c.client.write_full(pid, "o", b"epoch-two" * 100,
                                  snapc=(snapid, [snapid]))
        await c.client.set_pool_param(pid, "pg_num", 32)
        await c.client.set_pool_param(pid, "pgp_num", 32)
        await c.wait_active(40)
        # the clone migrated WITH its head (head-oid hashing)
        assert await c.client.read(pid, "o") == b"epoch-two" * 100
        assert await c.client.read(pid, "o", snapid=snapid) == v1
        await c.stop()

    run(t())


def test_split_survives_member_failure():
    async def t():
        c, pid = await make("replicated")
        rng = np.random.default_rng(9)
        objs = {f"k{i}": bytes(rng.integers(0, 256, 3000, dtype=np.uint8))
                for i in range(24)}
        for n_, d in objs.items():
            await c.client.write_full(pid, n_, d)
        await c.client.set_pool_param(pid, "pg_num", 16)
        await c.client.set_pool_param(pid, "pgp_num", 16)
        await c.wait_active(40)
        victim = 1
        await c.kill_osd(victim)
        await c.wait_down(victim, 20)
        for n_, d in objs.items():
            assert await c.client.read(pid, n_) == d
        await c.revive_osd(victim)
        await c.wait_active(40)
        for n_, d in objs.items():
            assert await c.client.read(pid, n_) == d
        await c.stop()

    run(t())


def test_pg_num_validation():
    async def t():
        c, pid = await make("replicated")
        with pytest.raises(IOError):
            await c.client.set_pool_param(pid, "pg_num", 4)  # shrink
        with pytest.raises(IOError):
            await c.client.set_pool_param(pid, "pg_num", 24)  # not pow2
        with pytest.raises(IOError):
            await c.client.set_pool_param(pid, "pgp_num", 64)  # > pg_num
        await c.stop()

    run(t())


# --------------------------------------------------------- autoscaler


class _FakePool:
    def __init__(self, pid, pg_num, pgp_num, size):
        self.id, self.pg_num, self.pgp_num, self.size = \
            pid, pg_num, pgp_num, size


class _FakeOSDState:
    def __init__(self):
        self.up, self.weight = True, 0x10000


class _FakeMap:
    def __init__(self, pools, n_osds):
        self.pools = {p.id: p for p in pools}
        self.osds = [_FakeOSDState() for _ in range(n_osds)]


def test_autoscaler_plan():
    # 32 OSDs, one size-3 pool at pg_num 8: budget 32*100/1 / 3 ~ 1066
    # -> pow2 1024 >= 3*8: grow
    m = _FakeMap([_FakePool(1, 8, 8, 3)], 32)
    assert autoscaler.plan(m, 100) == [(1, "pg_num", 1024)]
    # pgp lag: catch-up action, no further growth this round
    m = _FakeMap([_FakePool(1, 32, 8, 3)], 32)
    assert autoscaler.plan(m, 100) == [(1, "pgp_num", 32)]
    # close to ideal: no flapping
    m = _FakeMap([_FakePool(1, 512, 512, 3)], 32)
    assert autoscaler.plan(m, 100) == []


def test_autoscaler_end_to_end():
    async def t():
        c, pid = await make("replicated", pg_num=4)
        for i in range(10):
            await c.client.write_full(pid, f"o{i}", b"x" * 500)
        # round 1 grows pg_num; round 2 catches pgp_num up
        r1 = await c.mgr.autoscale_once(target_per_osd=64)
        assert any(a[1] == "pg_num" for a in r1["actions"])
        await c.wait_active(40)
        r2 = await c.mgr.autoscale_once(target_per_osd=64)
        assert any(a[1] == "pgp_num" for a in r2["actions"])
        await c.wait_active(40)
        pool = c.mon.osdmap.pools[pid]
        assert pool.pg_num > 4 and pool.pgp_num == pool.pg_num
        for i in range(10):
            assert await c.client.read(pid, f"o{i}") == b"x" * 500
        await c.stop()

    run(t())


@pytest.mark.parametrize("pool_type", ["replicated", "erasure"])
def test_merge_32_to_8_round_trip_under_load(pool_type):
    """VERDICT r3 #5 (PG.cc:571 merge_from role): 8 -> 32 -> 8 round
    trip with writers flowing; pgp_num collapses first (co-location),
    then pg_num halves fold collections. No object lost, listing
    exact."""
    async def t():
        c, pid = await make(pool_type)
        rng = np.random.default_rng(13)
        objs = {}
        for i in range(40):
            name = f"pre{i}"
            objs[name] = bytes(rng.integers(0, 256, 2500 + 11 * i,
                                            dtype=np.uint8))
            await c.client.write_full(pid, name, objs[name])
        # grow 8 -> 32 (split + re-place)
        await c.client.set_pool_param(pid, "pg_num", 32)
        await c.client.set_pool_param(pid, "pgp_num", 32)
        await c.wait_active(40)

        stop = asyncio.Event()
        written_during: dict[str, bytes] = {}

        async def writer(wid):
            i = 0
            while not stop.is_set():
                name = f"live{wid}-{i}"
                data = bytes(rng.integers(0, 256, 1200, dtype=np.uint8))
                await c.client.write_full(pid, name, data)
                written_during[name] = data
                i += 1
                await asyncio.sleep(0)

        writers = [asyncio.ensure_future(writer(w)) for w in range(3)]
        await asyncio.sleep(0.1)
        # the shrink: placement collapses, data migrates off the
        # pins, THEN collections fold (the mon refuses earlier)
        await c.client.set_pool_param(pid, "pgp_num", 8)
        await c.wait_clean(60)
        await c.client.set_pool_param(pid, "pg_num", 8)
        await c.wait_active(40)
        await asyncio.sleep(0.2)
        stop.set()
        await asyncio.gather(*writers)
        assert c.mon.osdmap.pools[pid].pg_num == 8
        assert c.mon.osdmap.pools[pid].pgp_num == 8

        objs.update(written_during)
        assert len(written_during) > 0
        for name, data in objs.items():
            assert await c.client.read(pid, name) == data, name
        listed = await c.client.list_objects(pid)
        assert sorted(listed) == sorted(n.encode() for n in objs)
        # and the pool still takes IO on the merged PGs
        await c.client.write_full(pid, "post-merge", b"alive")
        assert await c.client.read(pid, "post-merge") == b"alive"
        await c.stop()

    run(t())


def test_merge_preserves_snapshots():
    """Clones ride the merge with their heads and snap reads still
    resolve afterwards."""
    async def t():
        c, pid = await make("replicated", pg_num=16)
        v1 = b"first-era" * 400
        await c.client.write_full(pid, "o", v1)
        snapid = await c.client.selfmanaged_snap_create(pid)
        await c.client.write_full(pid, "o", b"second-era" * 150,
                                  snapc=(snapid, [snapid]))
        await c.client.set_pool_param(pid, "pgp_num", 4)
        await c.wait_clean(60)
        await c.client.set_pool_param(pid, "pg_num", 4)
        await c.wait_active(40)
        assert await c.client.read(pid, "o") == b"second-era" * 150
        assert await c.client.read(pid, "o", snapid=snapid) == v1
        await c.stop()

    run(t())


def test_autoscaler_plans_shrink_sequence():
    """The planner emits pgp_num-then-pg_num for oversized pools."""
    from ceph_tpu.cluster import autoscaler
    from ceph_tpu.placement import crushmap as cm
    from ceph_tpu.placement.osdmap import OSDMap

    crush = cm.build_flat(3)
    crush.add_rule(cm.flat_firstn_rule(0))
    m = OSDMap(crush, 3)
    m.add_pool(Pool(id=1, name="fat", size=3, pg_num=512, pgp_num=512,
                    crush_rule=0))
    # 3 osds * 100 target / 1 pool / size 3 = 100 -> ideal 64 << 512/3
    acts = autoscaler.plan(m, target_per_osd=100)
    assert acts == [(1, "pgp_num", 64)]
    m.pools[1].pgp_num = 64
    acts = autoscaler.plan(m, target_per_osd=100)
    assert acts == [(1, "pg_num", 64)]
    m.pools[1].pg_num = 64
    assert autoscaler.plan(m, target_per_osd=100) == []
