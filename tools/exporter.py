#!/usr/bin/env python3
"""exporter: standalone Prometheus exporter scraping daemon admin
sockets (the src/exporter DaemonMetricCollector role — distinct from
the mgr's cluster-level /prometheus, which renders map state).

  exporter.py --sock-dir /tmp/c1/asok --once          # print and exit
  exporter.py --sock-dir /tmp/c1/asok --port 9926     # serve /metrics

Every *.sock in --sock-dir is scraped with `perf dump`; counters become
`ceph_tpu_<counter>{ceph_daemon="<name>"}` exactly the way the
reference labels per-daemon series.
"""
from __future__ import annotations

import argparse
import asyncio
import glob
import os
import signal
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.utils.admin import admin_command  # noqa: E402


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


async def scrape(sock_dir: str) -> str:
    lines: list[str] = []
    seen_help: set[str] = set()
    for sock in sorted(glob.glob(os.path.join(sock_dir, "*.sock"))):
        daemon = os.path.splitext(os.path.basename(sock))[0]
        try:
            perf = await admin_command(sock, "perf dump")
        except (OSError, ConnectionError):
            lines.append(f'ceph_tpu_daemon_up{{ceph_daemon="{daemon}"}} 0')
            continue
        lines.append(f'ceph_tpu_daemon_up{{ceph_daemon="{daemon}"}} 1')
        for counter, value in sorted(_flatten(perf)):
            metric = f"ceph_tpu_{_sanitize(counter)}"
            if metric not in seen_help:
                lines.append(f"# TYPE {metric} gauge")
                seen_help.add(metric)
            lines.append(
                f'{metric}{{ceph_daemon="{daemon}"}} {value}')
    return "\n".join(lines) + "\n"


def _flatten(obj, prefix: str = ""):
    if isinstance(obj, dict):
        for k, v in obj.items():
            yield from _flatten(v, f"{prefix}_{k}" if prefix else str(k))
    elif isinstance(obj, bool):
        yield prefix, int(obj)
    elif isinstance(obj, (int, float)):
        yield prefix, obj


async def serve(sock_dir: str, port: int) -> None:
    async def handle(reader, writer):
        try:
            # drain request line + headers; responding with unread bytes
            # in the kernel buffer risks an RST eating the response
            while (await reader.readline()).strip():
                pass
            body = (await scrape(sock_dir)).encode()
            writer.write(
                b"HTTP/1.0 200 OK\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"\r\n" + body)
            await writer.drain()
        finally:
            writer.close()

    server = await asyncio.start_server(handle, "127.0.0.1", port)
    print(f"serving /metrics on 127.0.0.1:{port}", file=sys.stderr)
    async with server:
        await server.serve_forever()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sock-dir", required=True)
    ap.add_argument("--port", type=int, default=9926)
    ap.add_argument("--once", action="store_true",
                    help="scrape once to stdout and exit")
    args = ap.parse_args(argv)
    if args.once:
        print(asyncio.run(scrape(args.sock_dir)), end="")
        return 0
    asyncio.run(serve(args.sock_dir, args.port))
    return 0


if __name__ == "__main__":
    # head-friendly CLI: a closed stdout pipe is a normal exit. Set
    # only when run as a program — at import time this would strip
    # the hosting process (e.g. pytest) of CPython's SIGPIPE ignore
    # and a later write to any dead socket would kill it (exit 141).
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
