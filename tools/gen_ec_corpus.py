#!/usr/bin/env python3
"""Generate the EC golden-bytes corpus (the ceph-erasure-code-corpus +
ceph_erasure_code_non_regression role, src/test/erasure-code/
ceph_erasure_code_non_regression.cc).

For every (plugin, technique/config, k, m, object size) in the matrix,
encode a deterministic seeded payload with the HOST reference path and
pin the SHA-256 of every chunk. tests/test_corpus.py re-encodes with
both host and device backends and fails on any byte drift — encodings
are an on-disk format: once written, future kernels must reproduce
them forever.

Run: python tools/gen_ec_corpus.py [--check]
Corpus lives at tests/corpus/ec_corpus.json (checked in).
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.ec import load_codec  # noqa: E402

SIZES = (31, 4096, 65537)

MATRIX: list[dict] = []
for technique in ("reed_sol_van", "reed_sol_r6_op", "cauchy_orig",
                  "cauchy_good"):
    for k, m in ((2, 1), (4, 2), (8, 3), (8, 4)):
        if technique == "reed_sol_r6_op" and m != 2:
            continue
        MATRIX.append({
            "plugin": "rs_tpu", "technique": technique,
            "k": str(k), "m": str(m), "backend": "host",
        })
MATRIX += [
    {"plugin": "lrc", "mapping": "__DD__DD",
     "layers": '[["_cDD_cDD", ""], ["cDDD____", ""], ["____cDDD", ""]]'},
    {"plugin": "lrc", "k": "4", "m": "2", "l": "3"},
    {"plugin": "shec", "k": "4", "m": "3", "c": "2"},
    {"plugin": "shec", "k": "6", "m": "3", "c": "2",
     "technique": "single"},
    {"plugin": "clay", "k": "4", "m": "2"},
    {"plugin": "clay", "k": "3", "m": "2", "d": "4"},
    {"plugin": "clay", "k": "4", "m": "3"},
]


def profile_key(profile: dict) -> str:
    return "&".join(f"{k}={v}" for k, v in sorted(profile.items()))


def payload(size: int) -> bytes:
    return np.random.default_rng(0xEC0DE + size).integers(
        0, 256, size, dtype=np.uint8
    ).tobytes()


def encode_entry(profile: dict) -> dict:
    codec = load_codec(dict(profile))
    n = codec.get_chunk_count()
    sizes = {}
    for size in SIZES:
        encoded = codec.encode(list(range(n)), payload(size))
        sizes[str(size)] = {
            "chunk_size": codec.get_chunk_size(size),
            "chunks": [
                hashlib.sha256(encoded[i].tobytes()).hexdigest()[:24]
                for i in range(n)
            ],
        }
    return {"profile": profile, "n": n, "sizes": sizes}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="verify against the existing corpus, no write")
    args = ap.parse_args()
    path = os.path.join(os.path.dirname(__file__), "..", "tests",
                        "corpus", "ec_corpus.json")
    corpus = {profile_key(p): encode_entry(p) for p in MATRIX}
    if args.check:
        with open(path) as f:
            want = json.load(f)
        if want != corpus:
            print("CORPUS DRIFT DETECTED", file=sys.stderr)
            return 1
        print(f"corpus clean: {len(corpus)} configs x {len(SIZES)} sizes")
        return 0
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(corpus, f, indent=1, sort_keys=True)
    print(f"wrote {len(corpus)} configs to {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
