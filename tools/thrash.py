#!/usr/bin/env python
"""thrash: seeded EC thrash runner with a JSON verdict.

The teuthology thrasher verb for this repo (qa/tasks/thrashosds role):
assemble an in-process TestCluster, create a k/m EC pool, run a
deterministic fault schedule (OSD kill/revive/flap, one rolling
partition, bitrot on a fraction of reads, optional mon failover when
--mons > 1) under concurrent oracle-checked writers, then demand
convergence — active+clean, a deep-scrub round finding nothing after
one repair pass, and byte-exact oracle reads.

Usage:
    python tools/thrash.py --seed 7 --duration 20
    python tools/thrash.py --seed 7 --osds 5 --k 3 --m 2 \
        --bitrot 0.01 --max-unavail 2 --duration 60

Exit codes: 0 the verdict passed, 1 it failed, 2 usage error.
Same seed => same schedule => same verdict (the replayability
contract the fault plane exists for).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_REPO))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="thrash", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--seed", type=int, default=0,
                    help="fault-plane seed (default %(default)s)")
    ap.add_argument("--duration", type=float, default=20.0,
                    help="thrash phase seconds (default %(default)s)")
    ap.add_argument("--osds", type=int, default=5)
    ap.add_argument("--mons", type=int, default=1,
                    help=">1 runs a Paxos quorum and enables mon "
                         "failover events")
    ap.add_argument("--k", type=int, default=3)
    ap.add_argument("--m", type=int, default=2)
    ap.add_argument("--profile", default="rs",
                    choices=("rs", "clay", "blaum_roth", "liberation",
                             "lrc"),
                    help="EC codec family for the thrashed pool "
                         "(default %(default)s). Non-RS arms exercise "
                         "each repair-economics codec's verify-on-read"
                         " + batched decode/repair path; blaum_roth/"
                         "liberation force m=2 (RAID6 codes); lrc "
                         "splits into two locality groups "
                         "(l=(k+m)/2) when k and m are even, else "
                         "one (l=k+m) — stored chunks grow by one "
                         "local parity per group, so --osds must "
                         "cover the codec's chunk_count")
    ap.add_argument("--pg-num", type=int, default=8)
    ap.add_argument("--max-unavail", type=int, default=None,
                    help="max simultaneously killed/partitioned OSDs "
                         "(default: m)")
    ap.add_argument("--bitrot", type=float, default=0.01,
                    help="P(bit-flip) per shard read (default 1%%)")
    ap.add_argument("--chip-loss", action="store_true",
                    help="thrash the multi-chip data plane: run the "
                         "pool on a forced host-device mesh (device "
                         "engine, collective repair) and schedule "
                         "mesh-chip losses — a dark chip fails EC "
                         "device dispatches on its owning OSDs")
    ap.add_argument("--chips", type=int, default=8,
                    help="mesh device count for --chip-loss "
                         "(default %(default)s)")
    ap.add_argument("--mesh-width", type=int, default=2,
                    help="mesh width axis for --chip-loss (must "
                         "divide --chips; default %(default)s)")
    ap.add_argument("--stragglers", type=int, default=0,
                    help="straggle/unstraggle events keeping up to N "
                         "OSDs persistently slow (seeded lognormal "
                         "service-time inflation; the hedged-read "
                         "arm of the fault mix — default off)")
    ap.add_argument("--proc", action="store_true",
                    help="thrash a ProcCluster: REAL daemon processes "
                         "(kill -9 means kill -9), durable stores, "
                         "the asok deep-scrub verdict. Partitions "
                         "and bitrot are in-process fault-plane "
                         "verbs and are disabled in this mode")
    ap.add_argument("--backend", default="tcp",
                    choices=("tcp", "shm"),
                    help="--proc messenger backend "
                         "(default %(default)s)")
    ap.add_argument("--objectstore", default="walstore",
                    help="--proc daemon store kind "
                         "(default %(default)s)")
    ap.add_argument("--no-partitions", action="store_true")
    ap.add_argument("--objects", type=int, default=8)
    ap.add_argument("--obj-size", type=int, default=24 << 10)
    ap.add_argument("--writers", type=int, default=4)
    ap.add_argument("--settle", type=float, default=90.0,
                    help="post-heal convergence deadline seconds")
    ap.add_argument("--schedule-only", action="store_true",
                    help="print the deterministic schedule and exit "
                         "(no cluster)")
    args = ap.parse_args(argv)
    if args.profile in ("blaum_roth", "liberation"):
        args.m = 2  # RAID6 code families
    if args.profile == "lrc":
        # k/m/l generation adds one local parity per group; size grows
        args.m = max(args.m, 2)
    if args.k < 2 or args.m < 1 or args.osds < args.k + args.m:
        ap.error("need osds >= k + m, k >= 2, m >= 1")
    if args.chip_loss and args.chips % args.mesh_width:
        ap.error(f"--mesh-width {args.mesh_width} does not divide "
                 f"--chips {args.chips}")
    max_unavail = args.max_unavail if args.max_unavail is not None \
        else args.m

    from ceph_tpu.cluster.faults import build_schedule

    if args.schedule_only:
        sched = build_schedule(args.seed, args.duration, args.osds,
                               max_unavail=max_unavail,
                               partitions=not args.no_partitions,
                               mon_flaps=args.mons > 1,
                               chip_loss=args.chip_loss,
                               n_chips=args.chips,
                               stragglers=args.stragglers)
        print(json.dumps({"seed": args.seed,
                          "events": [[e.t, e.kind, e.target]
                                     for e in sched]}, indent=1))
        return 0

    if args.chip_loss:
        # the mesh must exist BEFORE any jax backend init: force the
        # virtual host platform to the chip count (the CPU recipe the
        # mesh tests use; on a real multi-chip host get_devices picks
        # the healthy accelerator platform instead)
        from ceph_tpu import parallel

        parallel.pin_virtual_cpu(args.chips)

    if args.proc:
        verdict = asyncio.run(_run_proc(args, max_unavail))
    else:
        verdict = asyncio.run(_run(args, max_unavail))
    print(json.dumps(verdict, indent=1, sort_keys=True))
    return 0 if verdict["passed"] else 1


def _ec_profile(args, backend: str) -> dict:
    """ec_profile for the thrashed pool per --profile (the codec arm
    of the repair-economics pipeline; rs stays the legacy default)."""
    if args.profile == "rs":
        return {"plugin": "rs_tpu", "k": str(args.k),
                "m": str(args.m), "backend": backend}
    if args.profile in ("blaum_roth", "liberation"):
        return {"plugin": "bitmatrix", "technique": args.profile,
                "k": str(args.k), "m": "2", "backend": backend}
    if args.profile == "clay":
        return {"plugin": "clay", "k": str(args.k), "m": str(args.m),
                "backend": backend}
    # lrc: two locality groups when k and m split evenly, else one
    groups = 2 if args.k % 2 == 0 and args.m % 2 == 0 else 1
    l = (args.k + args.m) // groups  # noqa: E741 (reference name)
    return {"plugin": "lrc", "k": str(args.k), "m": str(args.m),
            "l": str(l), "backend": backend}


async def _run(args, max_unavail: int) -> dict:
    from ceph_tpu.cluster.faults import Thrasher
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool

    osd_conf = None
    backend = "auto"
    if args.chip_loss:
        # the multi-chip serving path under thrash: device engine,
        # mesh-sharded encode staging, collective repair — the arm
        # that proves a chip loss degrades and repairs through the
        # mesh, not just through messenger fan-in
        osd_conf = {
            "osd_ec_mesh_devices": args.chips,
            "osd_ec_mesh_width": args.mesh_width,
            "parallel_repair_mode": "allgather",
        }
        backend = "device"
    profile = _ec_profile(args, backend)
    from ceph_tpu.ec import load_codec

    size = load_codec(dict(profile)).get_chunk_count()
    if args.osds < size:
        raise SystemExit(
            f"--profile {args.profile} stores {size} chunks: need "
            f"--osds >= {size}")
    c = TestCluster(n_osds=args.osds, n_mons=args.mons,
                    fault_seed=args.seed, osd_conf=osd_conf)
    await c.start()
    # the oracle's ordering contract: one tid per op for the whole
    # thrash — the op must outlive any partition, so the deadline
    # has to exceed the thrash+settle horizon
    c.client.op_timeout = args.duration + args.settle + 60.0
    pool_id = await c.client.create_pool(Pool(
        id=2, name="thrash", size=size, min_size=args.k,
        pg_num=args.pg_num, crush_rule=1, type="erasure",
        ec_profile=profile))
    await c.wait_active(30)
    thrasher = Thrasher(
        c, pool_id, seed=args.seed, duration=args.duration,
        max_unavail=max_unavail, bitrot_p=args.bitrot,
        partitions=not args.no_partitions, mon_flaps=args.mons > 1,
        n_objects=args.objects, obj_size=args.obj_size,
        writers=args.writers, settle_timeout=args.settle,
        chip_loss=args.chip_loss, n_chips=args.chips,
        stragglers=args.stragglers)
    try:
        verdict = await thrasher.run()
        verdict["health"] = c.mon.health()
        verdict["ec_profile"] = args.profile
        econ: dict = {}
        for o in c.osds:
            if o is None:
                continue
            d = o.perf.dump()
            for key in ("ec_batches", "ec_decode_batches",
                        "ec_batch_isolated", "ec_read_crc_err",
                        "ec_read_repairs", "ec_repair_subchunk",
                        "ec_repair_bytes_fetched",
                        "ec_repair_bytes_rebuilt"):
                econ[key] = econ.get(key, 0) + int(d.get(key, 0))
        verdict["ec_counters"] = econ
        if args.chip_loss:
            from ceph_tpu.parallel import runtime

            # the mesh ledger proves the serving path actually ran
            # sharded (encode dispatches > 0) and repaired through
            # collectives (decode dispatches) with zero host gathers
            verdict["mesh"] = runtime.STATS.dump()
    finally:
        await c.stop()
    return verdict


async def _run_proc(args, max_unavail: int) -> dict:
    """Process-tier thrash: the same seeded schedule applied to a
    ProcCluster of REAL daemon processes over the chosen messenger
    backend (tcp or shm).  kill means SIGKILL of an OS process;
    revive means a cold daemon restart against its durable store.
    Partition/bitrot/straggle events are in-process fault-plane verbs
    with no cross-process equivalent, so the schedule is built with
    partitions off and any residual non-kill event is skipped (and
    counted, so seed⇒schedule determinism stays auditable).

    Verdict demands: post-heal active+clean, byte-exact oracle reads,
    a zero-inconsistency asok deep-scrub round, and a leak-free hedge
    ledger (canceled == fired - won) summed across daemons."""
    import shutil
    import tempfile

    import numpy as np

    from ceph_tpu.cluster.faults import build_schedule
    from ceph_tpu.cluster.procstart import ProcCluster
    from ceph_tpu.ec import load_codec
    from ceph_tpu.placement.osdmap import Pool

    profile = _ec_profile(args, "auto")
    size = load_codec(dict(profile)).get_chunk_count()
    if args.osds < size:
        raise SystemExit(
            f"--profile {args.profile} stores {size} chunks: need "
            f"--osds >= {size}")
    sched = build_schedule(args.seed, args.duration, args.osds,
                           max_unavail=max_unavail, partitions=False)

    data_dir = tempfile.mkdtemp(prefix="ctpu-thrash-proc-")
    c = ProcCluster(data_dir, n_osds=args.osds, n_mons=args.mons,
                    objectstore=args.objectstore,
                    backend=args.backend)
    applied: list[list] = []
    skipped = 0
    writes = {"ok": 0, "err": 0}
    oracle: dict[str, bytes] = {}
    try:
        await c.start()
        c.client.op_timeout = args.duration + args.settle + 60.0
        pool_id = await c.client.create_pool(Pool(
            id=2, name="thrash", size=size, min_size=args.k,
            pg_num=args.pg_num, crush_rule=1, type="erasure",
            ec_profile=profile))
        await c.wait_active(60)

        stop_ev = asyncio.Event()

        async def writer(wid: int) -> None:
            r = np.random.default_rng((args.seed << 8) ^ wid)
            while not stop_ev.is_set():
                name = f"obj-{int(r.integers(args.objects))}"
                data = r.integers(0, 256, args.obj_size,
                                  dtype=np.uint8).tobytes()
                try:
                    await c.client.write_full(pool_id, name, data)
                except Exception:
                    writes["err"] += 1
                else:
                    # full-object writes through ONE client serialize
                    # per name, so last-acked == authoritative
                    oracle[name] = data
                    writes["ok"] += 1
                await asyncio.sleep(0.05)

        writers = [asyncio.get_running_loop().create_task(writer(i))
                   for i in range(args.writers)]

        loop = asyncio.get_running_loop()
        t0 = loop.time()
        for ev in sched:
            delay = t0 + ev.t - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            proc = c.procs.get(f"osd.{ev.target}")
            if ev.kind == "kill" and proc is not None:
                c.kill_osd(ev.target)
                applied.append([round(ev.t, 2), "kill", ev.target])
            elif ev.kind == "revive" and proc is None:
                await c.revive_osd(ev.target)
                applied.append([round(ev.t, 2), "revive", ev.target])
            else:
                skipped += 1
        for i in range(args.osds):
            if c.procs.get(f"osd.{i}") is None:
                await c.revive_osd(i)

        stop_ev.set()
        await asyncio.gather(*writers, return_exceptions=True)

        converged = True
        try:
            await c.wait_active(args.settle)
        except asyncio.TimeoutError:
            converged = False

        byte_exact = converged
        mismatches = 0
        if converged:
            for name, want in sorted(oracle.items()):
                try:
                    got = await c.client.read(pool_id, name)
                except Exception:
                    got = None
                if got is None or bytes(got) != want:
                    mismatches += 1
            byte_exact = mismatches == 0

        scrub_pgs = 0
        scrub_inconsistent = 0
        hedges = {"ec_hedges_fired": 0, "ec_hedges_won": 0,
                  "ec_hedges_canceled": 0}
        scrub_repaired = 0
        if converged:
            # one repair pass, then a round that must find NOTHING
            # (the in-process thrasher's deep-scrub contract)
            rep1 = await c.scrub_all()
            scrub_repaired = sum(v["repaired"] for v in rep1.values())
            rep = await c.scrub_all()
            scrub_pgs = len(rep)
            scrub_inconsistent = sum(len(v["inconsistent"])
                                     for v in rep.values())
            for i in range(args.osds):
                if c.procs.get(f"osd.{i}") is None:
                    continue
                d = await c.asok(f"osd.{i}", "perf dump")
                for key in hedges:
                    hedges[key] += int(d.get(key, 0))
        hedge_leak_free = (hedges["ec_hedges_canceled"]
                           == hedges["ec_hedges_fired"]
                           - hedges["ec_hedges_won"])

        passed = (converged and byte_exact
                  and scrub_inconsistent == 0 and hedge_leak_free
                  and writes["ok"] > 0)
        return {
            "passed": passed,
            "mode": "proc",
            "backend": args.backend,
            "objectstore": args.objectstore,
            "seed": args.seed,
            "duration_s": args.duration,
            "n_osds": args.osds,
            "ec_profile": args.profile,
            "events": applied,
            "events_scheduled": len(sched),
            "events_skipped": skipped,
            "writes": writes,
            "oracle_objects": len(oracle),
            "converged": converged,
            "byte_exact": byte_exact,
            "oracle_mismatches": mismatches,
            "scrub_pgs": scrub_pgs,
            "scrub_repaired_first_pass": scrub_repaired,
            "scrub_inconsistent": scrub_inconsistent,
            "hedges": hedges,
            "hedge_leak_free": hedge_leak_free,
            "daemon_cpu_s": round(c.cpu_seconds(), 2),
        }
    finally:
        await c.stop()
        shutil.rmtree(data_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
