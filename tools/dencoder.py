#!/usr/bin/env python3
"""dencoder: encode/decode framework wire types (the
src/tools/ceph-dencoder role): list types, round-trip check, hex dump.

  dencoder.py list
  dencoder.py dump <TypeName> <hexfile|->       # decode + pretty-print
  dencoder.py selftest                          # round-trip every type
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.cluster import messages as M  # noqa: E402
from ceph_tpu.msg.messages import _REGISTRY  # noqa: E402


def _samples() -> dict[str, object]:
    """One representative instance per message type (the corpus role)."""
    pg = (1, 3)
    ver = (2, 7)
    return {
        "MOSDBoot": M.MOSDBoot(osd=3),
        "MMonGetMap": M.MMonGetMap(have=5),
        "MOSDMapMsg": M.MOSDMapMsg(full=b"F" * 8, incrementals=[b"i1"],
                                   epoch=9),
        "MPing": M.MPing(osd=1, epoch=4),
        "MMonSubscribe": M.MMonSubscribe(what="osdmap"),
        "MFailure": M.MFailure(target=2, reporter="osd.1"),
        "MPoolCreate": M.MPoolCreate(pool=b"P" * 16),
        "MPoolCreateReply": M.MPoolCreateReply(pool_id=1, epoch=2),
        "MOSDOp": M.MOSDOp(tid=1, pgid=pg, oid=b"obj",
                           ops=[M.osd_op("read"),
                                M.osd_op("setxattr", key=b"k",
                                         data=b"v")],
                           epoch=3),
        "MOSDOpReply": M.MOSDOpReply(tid=1, result=0, data=b"d", size=1,
                                     outs=[(0, b"d")], epoch=3),
        "MOSDRepOp": M.MOSDRepOp(tid=2, pgid=pg, txn=b"T", entry=b"E",
                                 epoch=3),
        "MOSDRepOpReply": M.MOSDRepOpReply(tid=2, pgid=pg, result=0,
                                           osd=1),
        "MECSubWrite": M.MECSubWrite(tid=3, pgid=pg, shard=2, txn=b"T",
                                     entry=b"E", epoch=3),
        "MECSubWriteReply": M.MECSubWriteReply(tid=3, pgid=pg, shard=2,
                                               result=0),
        "MECSubRead": M.MECSubRead(tid=4, pgid=pg, shard=1, oid=b"o",
                                   offset=0, length=-1),
        "MECSubReadReply": M.MECSubReadReply(tid=4, pgid=pg, shard=1,
                                             result=0, data=b"c",
                                             digest=7, size=1,
                                             attrs={"u:k": b"v"}),
        "MPGInfoReq": M.MPGInfoReq(pgid=pg, epoch=3, shard=0),
        "MPGInfoReply": M.MPGInfoReply(pgid=pg, epoch=3, shard=0,
                                       info=b"I"),
        "MPushOp": M.MPushOp(pgid=pg, shard=0, oid=b"o", version=ver,
                             data=b"D", attrs={"v": b"x"}, epoch=3,
                             last_update=ver),
        "MPushReply": M.MPushReply(pgid=pg, shard=0, oid=b"o", result=0),
        "MPull": M.MPull(pgid=pg, shard=0, oid=b"o", epoch=3),
        "MPGScan": M.MPGScan(pgid=pg, shard=0, epoch=3),
        "MPGScanReply": M.MPGScanReply(pgid=pg, shard=0,
                                       objects={b"o": ver}),
        "MScrub": M.MScrub(pgid=pg, shard=0, epoch=3, tid=9),
        "MScrubReply": M.MScrubReply(pgid=pg, shard=0, tid=9,
                                     objects={b"o": (ver, (10, 0xAB))},
                                     errors=[b"bad"]),
    }


def cmd_list() -> int:
    for t, cls in sorted(_REGISTRY.items()):
        print(f"{t}\t{cls.__name__}")
    # non-message denc types
    print("-\tTransaction (store)")
    print("-\tPGLog / PGInfo / Entry (cluster)")
    print("-\tCrushMap / OSDMap / Incremental (placement)")
    return 0


def cmd_selftest() -> int:
    samples = _samples()
    missing = [cls.__name__ for cls in _REGISTRY.values()
               if cls.__name__ not in samples]
    if missing:
        print(f"NO SAMPLE for {missing}", file=sys.stderr)
        return 1
    bad = 0
    for name, msg in samples.items():
        blob = msg.encode()
        back = type(msg).decode(blob)
        if back != msg:
            print(f"ROUNDTRIP FAILED: {name}", file=sys.stderr)
            bad += 1
        else:
            print(f"ok {name} ({len(blob)}B)")
    # the non-message families
    from ceph_tpu.cluster.pglog import OP_MODIFY, Entry, PGLog
    from ceph_tpu.store.transaction import Transaction

    t = Transaction().create_collection("c")
    t.write("c", b"o", 0, b"data")
    t2, _ = Transaction.decode(t.encode())
    print("ok Transaction" if t2.encode() == t.encode()
          else "ROUNDTRIP FAILED: Transaction")
    log = PGLog()
    log.append(Entry(OP_MODIFY, b"o", (1, 1)))
    log2, _ = PGLog.decode(log.encode())
    print("ok PGLog" if log2.encode() == log.encode()
          else "ROUNDTRIP FAILED: PGLog")
    return 1 if bad else 0


def cmd_dump(type_name: str, path: str) -> int:
    cls = next(
        (c for c in _REGISTRY.values() if c.__name__ == type_name), None
    )
    if cls is None:
        print(f"unknown type {type_name!r}", file=sys.stderr)
        return 1
    raw = sys.stdin.buffer.read() if path == "-" else \
        open(path, "rb").read()
    try:
        blob = bytes.fromhex(raw.decode().strip())
    except (UnicodeDecodeError, ValueError):
        blob = raw  # already binary
    msg = cls.decode(blob)
    print(repr(msg))
    return 0


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 2
    if argv[0] == "list":
        return cmd_list()
    if argv[0] == "selftest":
        return cmd_selftest()
    if argv[0] == "dump" and len(argv) == 3:
        return cmd_dump(argv[1], argv[2])
    print(__doc__)
    return 2


if __name__ == "__main__":
    sys.exit(main())
