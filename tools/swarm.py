#!/usr/bin/env python
"""swarm: the million-object multi-tenant serving harness.

The bench drove one client at 16-deep for nine rounds; production
serves millions of users.  This harness closes that gap in ONE process:
thousands of simulated clients (lightweight actors sharing a few
RadosClient aio windows — the PR 5 machinery is what lets one reactor
sustain O(10^4) in-flight ops) issue Zipf-skewed traffic across
multiple pools/namespaces with mixed op shapes:

- ``put4k`` / ``get4k`` — 4 KiB RGW-ish PUT/GET on a replicated pool,
  object popularity Zipf-drawn from a million-name space (hot-key
  contention and per-object ordering chains are the p999 story);
- ``put4m`` — 4 MiB RBD-ish full-stripe writes on an EC pool (the
  config-6 shape under swarm interference);
- ``omap`` — omap-heavy bucket-index ops (setkeys + get on shared
  index shards).

Reported per shape: p50/p99/p999 latency AND MiB/s — arXiv:1804.10331's
point that load balancing is a tail-latency problem, not a bandwidth
one, is only visible in percentiles.  Alongside: aggregate in-flight
occupancy (sampled; the >= 10^4 sustained claim is measured, not
asserted), the placement-resolver counter block (cache hits/misses,
batched device lookups — the serving plane's evidence), and dispatch
counters from every OSD.

Modes:

- ``qos=...`` — mClock isolation proof: a bulk tenant (weight-only,
  64 KiB hammering) and a latency tenant (reservation-backed, paced
  4 KiB) on the SAME daemons; the verdict carries each tenant's
  achieved ops/s and percentiles so "the reservation held" is a number
  (cluster/scheduler.py knobs finally proven under saturation).
- ``thrash_secs > 0`` — a seeded kill/revive schedule runs DURING the
  swarm (the combined scenario the ROADMAP asked for); the verdict
  demands post-heal convergence.
- ``placement_batch=False`` — the A/B arm (CEPH_TPU_PLACEMENT_BATCH=0
  equivalent): pure memo+host placement, so the batched resolver's win
  is attributable.

Sharded fabric mode (``run_fabric`` / ``--fabric``): offered load
comes from N REACTOR PROCESSES, each owning a disjoint client slice
with its own event loop — the GIL stops bounding offered load at one
process's ceiling.  Workers report per-shape latency HISTOGRAMS
(utils/lathist.py) over a JSON-line results pipe; the parent merges
histograms and reads exact p50/p99/p999 off the merged counts.
Percentiles are NEVER averaged across workers, and nothing pickled
crosses the pipe.  Backends: ``local`` (each worker boots its own
in-process cluster — the sharded-everything upper bound), ``tcp`` /
``shm`` (workers dial a shared ProcCluster of real daemon processes
over the chosen messenger backend).

CLI:
    python tools/swarm.py --clients 2000 --duration 8
    python tools/swarm.py --qos --duration 6
    python tools/swarm.py --thrash-secs 5 --clients 500
    python tools/swarm.py --fabric --backend shm --workers 4
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np

_REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_REPO))

from ceph_tpu.utils.lathist import LatHist  # noqa: E402

#: pool ids (outside the test-suite's habitual 1/2)
POOL_SMALL = 21   # replicated: put4k/get4k/omap
POOL_BIG = 22     # erasure: put4m
POOL_LAT = 23     # replicated: the latency tenant's private pool

#: default op mix (actor weights)
DEFAULT_MIX = {"put4k": 0.45, "get4k": 0.40, "omap": 0.10,
               "put4m": 0.05}

#: fabric results-pipe line marker (one JSON line per worker; the
#: parent takes the LAST marked line so stray daemon chatter on the
#: same fd never corrupts the protocol)
_FABRIC_TAG = "CTPU_FABRIC1 "


def _shape_report(hist: LatHist, data_bytes: int, dt: float) -> dict:
    return {
        "ops": hist.count,
        "ops_s": round(hist.count / dt, 1) if dt else 0.0,
        "mib_s": round(data_bytes / dt / 2**20, 2) if dt else 0.0,
        "p50_ms": round(hist.percentile(0.50), 2),
        "p99_ms": round(hist.percentile(0.99), 2),
        "p999_ms": round(hist.percentile(0.999), 2),
    }


class _Recorder:
    """Per-shape latency/byte/miss ledger, fed by completion
    callbacks on the loop.  Latencies land in mergeable log-bucket
    histograms (utils/lathist.py), never raw sample lists: one
    recorder per REACTOR PROCESS, and the fabric parent merges
    bucket counts — merging percentiles would be wrong the moment
    there is a second source of load."""

    def __init__(self) -> None:
        self.hist: dict[str, LatHist] = {}
        self.bytes: dict[str, int] = {}
        self.errors: dict[str, int] = {}
        self.get_misses = 0
        self.objects: set = set()

    def note(self, shape: str, dt: float, nbytes: int,
             exc: BaseException | None) -> None:
        if exc is not None:
            if shape == "get4k" and isinstance(exc, KeyError):
                self.get_misses += 1  # Zipf tail read-before-write
            else:
                self.errors[shape] = self.errors.get(shape, 0) + 1
                return
        h = self.hist.get(shape)
        if h is None:
            h = self.hist[shape] = LatHist()
        h.note_s(dt)
        self.bytes[shape] = self.bytes.get(shape, 0) + nbytes


async def _actor(aid: int, rec: _Recorder, clients: list,
                 big_sem: asyncio.Semaphore, mix: dict, seed: int,
                 n_objects: int, zipf_s: float, payload4k: bytes,
                 payload4m: bytes, t_end: float, depth: int) -> None:
    """One simulated client: submit through a shared aio window,
    record completion latency per op shape. ``depth`` bounds the
    actor's own in-flight ops (the window bounds the process);
    ``big_sem`` additionally bounds 4 MiB ops process-wide — each one
    stages (k+m, T, su) server-side, so an unbounded swarm of them
    would measure the allocator, not the serving plane."""
    from ceph_tpu.cluster import messages as M
    from ceph_tpu.cluster.client import ObjectOperation

    rng = np.random.default_rng((seed << 20) ^ aid)
    cl = clients[aid % len(clients)]
    shapes = list(mix)
    weights = np.array([mix[s] for s in shapes], dtype=np.float64)
    weights /= weights.sum()
    ns = f"t{aid % 4}"   # namespace by actor cohort
    sem = asyncio.Semaphore(depth)
    loop = asyncio.get_running_loop()

    def draw_name(space: int) -> str:
        rank = int(rng.zipf(zipf_s))
        return f"o-{min(rank, space)}"

    while loop.time() < t_end:
        shape = shapes[int(rng.choice(len(shapes), p=weights))]
        await sem.acquire()
        is_big = shape == "put4m"
        if is_big:
            await big_sem.acquire()
        t0 = time.perf_counter()
        try:
            if shape == "put4k":
                name = f"{ns}-{draw_name(n_objects)}"
                comp = await cl.aio_write_full(POOL_SMALL, name,
                                               payload4k)
                nbytes = len(payload4k)
            elif shape == "get4k":
                name = f"{ns}-{draw_name(n_objects)}"
                comp = await cl.aio_submit(
                    POOL_SMALL, name,
                    [M.osd_op("read", offset=0, length=-1)])
                nbytes = len(payload4k)
            elif is_big:
                name = f"big-{int(rng.integers(64))}"
                comp = await cl.aio_write_full(POOL_BIG, name,
                                               payload4m)
                nbytes = len(payload4m)
            else:  # omap index op
                op = ObjectOperation()
                key = f"k{int(rng.integers(4096))}".encode()
                op.omap_set({key: payload4k[:64]})
                op.omap_get_keys()
                name = f"idx-{ns}-{int(rng.integers(64))}"
                comp = await cl.aio_operate(POOL_SMALL, name, op)
                nbytes = 128
        except Exception:
            sem.release()
            if is_big:
                big_sem.release()
            continue
        rec.objects.add(name)

        def done(c, shape=shape, t0=t0, nbytes=nbytes, is_big=is_big):
            sem.release()
            if is_big:
                big_sem.release()
            try:
                r = c.result()
            except BaseException as e:
                rec.note(shape, time.perf_counter() - t0, 0, e)
            else:
                if shape == "get4k" and getattr(r, "outs", None):
                    nbytes = len(r.outs[0][1])
                rec.note(shape, time.perf_counter() - t0, nbytes, None)

        comp.add_done_callback(done)
    # drain this actor's own in-flight before returning
    for _ in range(depth):
        await sem.acquire()


async def _sample_inflight(clients: list, samples: list,
                           stop: asyncio.Event) -> None:
    """Timestamped aggregate in-flight samples: the sustained claim is
    computed over the OFFERED-load phase (samples before t_end) — the
    post-deadline drain of 10^4-deep queues runs for as long as the
    tail latency says and would dilute the mean with the decay."""
    loop = asyncio.get_running_loop()
    while not stop.is_set():
        samples.append((loop.time(),
                        sum(cl._aio_inflight for cl in clients)))
        try:
            await asyncio.wait_for(stop.wait(), 0.05)
        except asyncio.TimeoutError:
            pass


async def _run_thrash_arm(cluster, seed: int, secs: float) -> dict:
    """A seeded kill/revive schedule DURING the swarm (no partitions:
    the swarm clients would be cut too, measuring the partition, not
    the serving plane). Heals everything afterwards; convergence is
    awaited by the caller."""
    from ceph_tpu.cluster.faults import build_schedule

    sched = build_schedule(seed, secs, cluster.n_osds, max_unavail=1,
                           partitions=False)
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    applied = []
    for ev in sched:
        delay = t0 + ev.t - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        if ev.kind == "kill" and cluster.osds[ev.target] is not None:
            await cluster.kill_osd(ev.target)
            applied.append([ev.t, "kill", ev.target])
        elif ev.kind == "revive" and cluster.osds[ev.target] is None:
            await cluster.revive_osd(ev.target)
            applied.append([ev.t, "revive", ev.target])
    for i, osd in enumerate(cluster.osds):
        if osd is None:
            await cluster.revive_osd(i)
    return {"events": applied, "scheduled": len(sched)}


async def run_swarm(*, clients: int = 2000, duration: float = 8.0,
                    seed: int = 1, n_osds: int = 10,
                    n_rados_clients: int = 4, window: int = 4096,
                    actor_depth: int = 8, n_objects: int = 1_000_000,
                    zipf_s: float = 1.1, mix: dict | None = None,
                    placement_batch: bool = True, prewarm: bool = True,
                    thrash_secs: float = 0.0,
                    qos: dict | None = None) -> dict:
    """Drive the swarm against a fresh in-process cluster and return
    the measured payload (bench config 10's body and the tier-1
    swarm tests' engine)."""
    from ceph_tpu.cluster.vstart import TestCluster
    from ceph_tpu.placement.osdmap import Pool
    from ceph_tpu.utils import config as cfg

    mix = dict(mix or DEFAULT_MIX)
    c = TestCluster(n_osds=n_osds, osd_conf={
        "osd_ec_batch_window": 0.01,
        "osd_ec_batch_target_stripes": 48,
        "osd_op_concurrency": 32,
        "osd_client_message_size_cap": 256 << 20,
    })
    await c.start()

    def make_client(name: str):
        conf = cfg.proxy()
        conf.set("client_max_inflight", window)
        # 10^4-deep pipelines run at seconds of queueing latency by
        # design; the default 2 s resend cap would duplicate-storm
        conf.set("client_backoff_max", 30.0)
        conf.set("client_placement_batch_min", 8)
        from ceph_tpu.cluster.client import RadosClient

        return RadosClient(c.bus, name=name, op_timeout=300.0,
                           conf=conf, placement_batch=placement_batch)

    swarm_clients = [make_client(f"swarm.{i}")
                     for i in range(n_rados_clients)]
    for cl in swarm_clients:
        await cl.connect()
    ec_size = 6
    await c.client.create_pool(Pool(
        id=POOL_SMALL, name="swarm-small", size=3, min_size=2,
        pg_num=64, crush_rule=0))
    await c.client.create_pool(Pool(
        id=POOL_BIG, name="swarm-big", size=ec_size, min_size=4,
        pg_num=16, crush_rule=1, type="erasure",
        ec_profile={"plugin": "rs_tpu", "k": "4", "m": "2",
                    "stripe_unit": "65536"}))
    if qos:
        await c.client.create_pool(Pool(
            id=POOL_LAT, name="swarm-lat", size=3, min_size=2,
            pg_num=32, crush_rule=0))
    await c.wait_active(60)

    rng = np.random.default_rng(seed)
    payload4k = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    payload4m = rng.integers(0, 256, 4 << 20, dtype=np.uint8).tobytes()

    # warm the pipeline (compiles, pool maps) outside the measured run
    await swarm_clients[0].write_full(POOL_SMALL, "warm", payload4k)
    if mix.get("put4m"):
        await swarm_clients[0].write_full(POOL_BIG, "warm", payload4m)
    warmed = 0
    if prewarm and placement_batch:
        # serving-process startup warm: compile the bulk-CRUSH engine
        # and device-resolve every pool's pg table so cold jit never
        # rides a client op (counted in placement_batch_lookups)
        for cl in swarm_clients:
            pools = [POOL_SMALL, POOL_BIG] + ([POOL_LAT] if qos else [])
            warmed += await cl._placement.prewarm(cl.osdmap, pools)

    rec = _Recorder()
    samples: list[int] = []
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    sampler = loop.create_task(_sample_inflight(swarm_clients,
                                                samples, stop))
    big_sem = asyncio.Semaphore(16)
    t_end = loop.time() + duration
    t0 = time.perf_counter()

    tasks = [loop.create_task(_actor(
        a, rec, swarm_clients, big_sem, mix, seed, n_objects, zipf_s,
        payload4k, payload4m, t_end, actor_depth))
        for a in range(clients)]

    qos_out: dict = {}
    qos_tasks: list = []
    lat_rec = _Recorder()
    if qos:
        # tenants: bulk rides the swarm clients above (they are the
        # saturating load); the latency tenant gets its OWN clients,
        # pool, and a reservation-backed mClock class on every OSD
        res = float(qos.get("reservation_ops_s", 50.0))
        lat_actors = int(qos.get("lat_actors", 8))
        pace = float(qos.get("pace_s", 0.02))
        for osd in c.osds:
            if osd is None:
                continue
            osd.set_qos_tenant("swarm-lat", "tenant_lat",
                               reservation=max(1.0, res / n_osds) * 2,
                               weight=1.0)
            osd.set_qos_tenant("swarm.", "tenant_blk",
                               reservation=0.0, weight=4.0)
        lat_clients = [make_client(f"swarm-lat.{i}") for i in range(2)]
        for cl in lat_clients:
            await cl.connect()
        await lat_clients[0].write_full(POOL_LAT, "warm", payload4k)
        lat_mix = {"put4k": 0.5, "get4k": 0.5}

        async def lat_actor(aid: int) -> None:
            # private pool: redirect by overriding the pool constant
            # via a tiny shim actor (depth 1, paced = offered rate)
            cl = lat_clients[aid % len(lat_clients)]
            rng = np.random.default_rng((seed << 16) ^ (aid + 7))
            while loop.time() < t_end:
                name = f"lat-{int(rng.integers(256))}"
                t1 = time.perf_counter()
                try:
                    if rng.random() < 0.5:
                        await cl.write_full(POOL_LAT, name, payload4k)
                    else:
                        try:
                            await cl.read(POOL_LAT, name)
                        except KeyError:
                            pass
                except (IOError, asyncio.TimeoutError) as e:
                    lat_rec.note("lat4k", time.perf_counter() - t1,
                                 0, e)
                else:
                    lat_rec.note("lat4k", time.perf_counter() - t1,
                                 len(payload4k), None)
                await asyncio.sleep(pace)

        qos_tasks = [loop.create_task(lat_actor(a))
                     for a in range(lat_actors)]
        qos_out = {"reservation_ops_s": res, "lat_actors": lat_actors,
                   "offered_ops_s": round(lat_actors / pace
                                          if pace else 0.0, 1),
                   "mix": lat_mix}

    thrash_out: dict = {}
    if thrash_secs > 0:
        thrash_out = await _run_thrash_arm(c, seed, min(thrash_secs,
                                                        duration))

    await asyncio.gather(*tasks)
    for cl in swarm_clients:
        await cl.writes_wait()
    dt = time.perf_counter() - t0
    if qos_tasks:
        await asyncio.gather(*qos_tasks)
    stop.set()
    await sampler

    converged = True
    if thrash_secs > 0:
        try:
            await c.wait_clean(120)
        except asyncio.TimeoutError:
            converged = False

    # ---- ledgers
    from ceph_tpu.placement.resolver import PlacementStats
    place = PlacementStats.aggregate(
        [cl.placement_stats() for cl in swarm_clients])
    osd_tot: dict = {}
    for osd in c.osds:
        if osd is None:
            continue
        d = osd.perf.dump()
        for key in ("op", "op_w", "op_r", "ec_batches",
                    "ov_apply_calls", "ov_apply_extents",
                    "ec_batch_failures", "client_op_retries"):
            if key in d:
                osd_tot[key] = osd_tot.get(key, 0) + int(d[key])
    window_stats = [dict(cl.window_stats) for cl in swarm_clients]
    occ_mean = [round(w["sum"] / w["count"], 1) if w["count"] else 0.0
                for w in window_stats]

    shapes_out = {
        s: _shape_report(rec.hist.get(s) or LatHist(),
                         rec.bytes.get(s, 0), dt)
        for s in mix
    }
    active = [v for t, v in samples if t <= t_end]
    # drop the leading ramp (actors spinning up): sustained is the
    # steady back 80% of the offered-load phase
    mid = active[len(active) // 5:] or active
    sustained = round(float(np.mean(mid)), 1) if mid else 0.0
    peak = max((v for _t, v in samples), default=0)
    total_bytes = sum(rec.bytes.values())
    total_ops = sum(h.count for h in rec.hist.values())

    out = {
        "clients": clients,
        "rados_clients": n_rados_clients,
        "window_per_client": window,
        "duration_s": round(dt, 2),
        "seed": seed,
        "n_osds": n_osds,
        "zipf_s": zipf_s,
        "namespace_objects": n_objects,
        "distinct_objects_touched": len(rec.objects),
        "ops": total_ops,
        "ops_s": round(total_ops / dt, 1) if dt else 0.0,
        "mib_s": round(total_bytes / dt / 2**20, 2) if dt else 0.0,
        "inflight_sustained": sustained,
        "inflight_peak": peak,
        "window_occupancy_mean": occ_mean,
        "get_misses": rec.get_misses,
        "op_errors": rec.errors,
        "shapes": shapes_out,
        "placement": place,
        "placement_batch": placement_batch,
        "placement_prewarmed_pgids": warmed,
        "osd_counters": osd_tot,
    }
    if qos:
        lat_ms = _shape_report(lat_rec.hist.get("lat4k") or LatHist(),
                               lat_rec.bytes.get("lat4k", 0), dt)
        bulk_ref = shapes_out.get("put4k", {})
        qos_out.update({
            "lat_tenant": lat_ms,
            "lat_achieved_ops_s": lat_ms.get("ops_s", 0.0),
            "bulk_p99_ms": bulk_ref.get("p99_ms", 0.0),
            "lat_p99_ms": lat_ms.get("p99_ms", 0.0),
        })
        out["qos"] = qos_out
    if thrash_secs > 0:
        out["thrash"] = {**thrash_out, "converged": converged}
    for cl in swarm_clients:
        await cl.close()
    await c.stop()
    return out


# --------------------------------------------------------------- fabric
#
# Sharded reactors: the parent never drives load itself — it spawns N
# worker PROCESSES (fresh interpreters via Popen: spawn semantics, so
# no fork ever follows a JAX runtime init), coordinates a file-based
# start barrier, and merges the per-shape histograms each worker ships
# back as one JSON line on stdout.


def _fabric_client_conf(window: int):
    from ceph_tpu.utils import config as cfg

    conf = cfg.proxy()
    conf.set("client_max_inflight", window)
    conf.set("client_backoff_max", 30.0)
    conf.set("client_placement_batch_min", 8)
    return conf


async def _fabric_worker(cfg_d: dict) -> dict:
    """One reactor shard: own event loop, disjoint client slice,
    private recorder.  Returns the JSON-safe result payload (histogram
    bucket dicts — never pickles, never raw sample lists)."""
    import resource

    w = int(cfg_d["worker"])
    seed = int(cfg_d["seed"])
    mix = dict(cfg_d["mix"])
    duration = float(cfg_d["duration"])
    depth = int(cfg_d.get("depth", 8))
    window = int(cfg_d.get("window", 1024))
    actors = int(cfg_d["clients"])
    n_objects = int(cfg_d.get("n_objects", 100_000))
    zipf_s = float(cfg_d.get("zipf_s", 1.1))
    barrier = Path(cfg_d["barrier"])

    from ceph_tpu.cluster.client import RadosClient
    from ceph_tpu.placement.osdmap import Pool

    cluster = None
    bus = None
    if cfg_d["mode"] == "local":
        # sharded-everything arm: this worker owns a PRIVATE
        # in-process cluster — the upper bound where nothing is shared
        from ceph_tpu.cluster.vstart import TestCluster

        cluster = TestCluster(n_osds=int(cfg_d.get("n_osds", 6)),
                              osd_conf=dict(cfg_d.get("osd_conf", {})))
        await cluster.start()
        swarm_clients = [RadosClient(
            cluster.bus, name=f"fw{w}.{i}", op_timeout=300.0,
            conf=_fabric_client_conf(window))
            for i in range(int(cfg_d.get("n_rados_clients", 2)))]
        for cl in swarm_clients:
            await cl.connect()
        await cluster.client.create_pool(Pool(
            id=POOL_SMALL, name="fab-small", size=3, min_size=2,
            pg_num=32, crush_rule=0))
        await cluster.client.create_pool(Pool(
            id=POOL_BIG, name="fab-big", size=6, min_size=4,
            pg_num=16, crush_rule=1, type="erasure",
            ec_profile={"plugin": "rs_tpu", "k": "4", "m": "2",
                        "stripe_unit": "65536"}))
        await cluster.wait_active(60)
    else:
        # shared ProcCluster: dial the daemons' book over the chosen
        # messenger backend (tcp or shm)
        from ceph_tpu.msg.netbus import NetBus

        bus = NetBus(cfg_d["book"], backend=cfg_d["backend"])
        await bus.start()
        swarm_clients = [RadosClient(
            bus, name=f"fw{w}.{i}", op_timeout=300.0,
            conf=_fabric_client_conf(window))
            for i in range(int(cfg_d.get("n_rados_clients", 2)))]
        for cl in swarm_clients:
            await cl.connect()

    rng = np.random.default_rng(seed)
    payload4k = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    payload4m = rng.integers(0, 256, 4 << 20, dtype=np.uint8).tobytes()
    # warm outside the measured window (compiles, maps, pool waits)
    await swarm_clients[0].write_full(POOL_SMALL, f"warm-{w}",
                                      payload4k)
    if mix.get("put4m"):
        await swarm_clients[0].write_full(POOL_BIG, f"warm-{w}",
                                          payload4m)

    # barrier: ready -> wait for go (simultaneous offered load across
    # every shard; a shard that starts early would measure an idle
    # cluster)
    (barrier / f"w{w}.ready").write_text(str(os.getpid()))
    go = barrier / "go"
    deadline = time.monotonic() + 120
    while not go.exists():
        if time.monotonic() > deadline:
            raise TimeoutError("fabric start barrier never opened")
        await asyncio.sleep(0.02)

    rec = _Recorder()
    loop = asyncio.get_running_loop()
    big_sem = asyncio.Semaphore(8)
    t_end = loop.time() + duration
    ru0 = resource.getrusage(resource.RUSAGE_SELF)
    t0 = time.perf_counter()
    tasks = [loop.create_task(_actor(
        (w << 16) | a, rec, swarm_clients, big_sem, mix,
        seed + w, n_objects, zipf_s, payload4k, payload4m, t_end,
        depth)) for a in range(actors)]
    await asyncio.gather(*tasks)
    for cl in swarm_clients:
        await cl.writes_wait()
    dt = time.perf_counter() - t0
    ru1 = resource.getrusage(resource.RUSAGE_SELF)
    cpu_s = (ru1.ru_utime - ru0.ru_utime) + (ru1.ru_stime
                                             - ru0.ru_stime)

    out = {
        "worker": w,
        "dt": round(dt, 3),
        "cpu_s": round(cpu_s, 3),
        "ops": sum(h.count for h in rec.hist.values()),
        "objects": len(rec.objects),
        "get_misses": rec.get_misses,
        "errors": rec.errors,
        "shapes": {
            s: {"hist": h.to_json(), "bytes": rec.bytes.get(s, 0)}
            for s, h in rec.hist.items()
        },
    }
    for cl in swarm_clients:
        await cl.close()
    if cluster is not None:
        await cluster.stop()
    if bus is not None:
        await bus.close()
    return out


def _fabric_worker_main(cfg_json: str) -> int:
    out = asyncio.run(_fabric_worker(json.loads(cfg_json)))
    sys.stdout.write(_FABRIC_TAG + json.dumps(out) + "\n")
    sys.stdout.flush()
    return 0


async def run_fabric(*, backend: str = "tcp", n_workers: int = 2,
                     clients_per_worker: int = 200,
                     duration: float = 4.0, seed: int = 1,
                     n_osds: int = 6, mix: dict | None = None,
                     data_dir: str | None = None, window: int = 1024,
                     depth: int = 8, n_objects: int = 100_000,
                     zipf_s: float = 1.1,
                     osd_conf: dict | None = None) -> dict:
    """Sharded fabric run: N reactor processes against one topology.

    ``backend="local"``: every worker boots a private in-process
    cluster (nothing shared — the pure sharding upper bound).
    ``"tcp"`` / ``"shm"``: ONE shared ProcCluster of real daemon
    processes; workers dial its book over the chosen messenger.
    Returns the merged verdict: per-shape histograms merged bucket-
    wise (exact percentiles), plus the cpu-seconds ledger split into
    worker and daemon halves.
    """
    import shutil
    import tempfile

    if backend not in ("local", "tcp", "shm"):
        raise ValueError(f"unknown fabric backend {backend!r}")
    mix = dict(mix or DEFAULT_MIX)
    osd_conf = dict(osd_conf or {
        "osd_ec_batch_window": 0.01,
        "osd_ec_batch_target_stripes": 48,
        "osd_op_concurrency": 32,
        "osd_client_message_size_cap": 256 << 20,
    })
    own_dir = data_dir is None
    data_dir = data_dir or tempfile.mkdtemp(prefix="ctpu-fabric-")
    barrier = Path(data_dir) / "barrier"
    shutil.rmtree(barrier, ignore_errors=True)
    barrier.mkdir(parents=True)

    cluster = None
    cpu_daemons0 = 0.0
    if backend != "local":
        from ceph_tpu.cluster.procstart import ProcCluster
        from ceph_tpu.placement.osdmap import Pool

        cluster = ProcCluster(data_dir, n_osds=n_osds,
                              objectstore="memstore", backend=backend,
                              osd_conf=osd_conf)
        await cluster.start()
        await cluster.client.create_pool(Pool(
            id=POOL_SMALL, name="fab-small", size=3, min_size=2,
            pg_num=32, crush_rule=0))
        await cluster.client.create_pool(Pool(
            id=POOL_BIG, name="fab-big", size=6, min_size=4,
            pg_num=16, crush_rule=1, type="erasure",
            ec_profile={"plugin": "rs_tpu", "k": "4", "m": "2",
                        "stripe_unit": "65536"}))
        await cluster.wait_active(60)

    env = dict(os.environ)
    env["PYTHONPATH"] = str(_REPO) + os.pathsep + env.get(
        "PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    procs: list[subprocess.Popen] = []
    logs = []
    try:
        for w_i in range(n_workers):
            cfg_d = {
                "mode": "local" if backend == "local" else "proc",
                "backend": backend,
                "book": (cluster.book if cluster is not None
                         else ""),
                "barrier": str(barrier),
                "worker": w_i,
                "n_workers": n_workers,
                "clients": clients_per_worker,
                "duration": duration,
                "seed": seed,
                "mix": mix,
                "window": window,
                "depth": depth,
                "n_objects": n_objects,
                "zipf_s": zipf_s,
                "n_osds": n_osds,
                "osd_conf": osd_conf,
            }
            log = open(Path(data_dir) / f"worker.{w_i}.err", "wb")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, str(_REPO / "tools" / "swarm.py"),
                 "--fabric-worker", json.dumps(cfg_d)],
                stdout=subprocess.PIPE, stderr=log, env=env))

        # barrier: all shards ready -> open the gate together
        deadline = time.monotonic() + 120
        while True:
            ready = sum((barrier / f"w{i}.ready").exists()
                        for i in range(n_workers))
            if ready == n_workers:
                break
            for i, p in enumerate(procs):
                if p.poll() is not None:
                    raise RuntimeError(
                        f"fabric worker {i} died before the barrier "
                        f"(rc={p.returncode}, see "
                        f"{data_dir}/worker.{i}.err)")
            if time.monotonic() > deadline:
                raise TimeoutError("fabric workers never all readied")
            await asyncio.sleep(0.05)
        if cluster is not None:
            cpu_daemons0 = cluster.cpu_seconds()
        (barrier / "go").write_text("go")

        # results pipe: one tagged JSON line per worker
        loop = asyncio.get_running_loop()
        outs = []
        for i, p in enumerate(procs):
            raw = await asyncio.wait_for(
                loop.run_in_executor(None, p.communicate),
                duration + 600)
            lines = [ln for ln in raw[0].decode().splitlines()
                     if ln.startswith(_FABRIC_TAG)]
            if p.returncode != 0 or not lines:
                raise RuntimeError(
                    f"fabric worker {i} failed (rc={p.returncode}, "
                    f"see {data_dir}/worker.{i}.err)")
            outs.append(json.loads(lines[-1][len(_FABRIC_TAG):]))
        cpu_daemons = (cluster.cpu_seconds() - cpu_daemons0
                       if cluster is not None else 0.0)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for log in logs:
            log.close()
        if cluster is not None:
            await cluster.stop()

    # merge: histograms bucket-wise, byte/err counters by sum; the
    # wall clock of the run is the SLOWEST shard's window (offered
    # load overlapped for at least that long)
    dt = max(o["dt"] for o in outs)
    hists: dict[str, LatHist] = {}
    bytes_: dict[str, int] = {}
    errors: dict[str, int] = {}
    for o in outs:
        for s, d in o["shapes"].items():
            hists.setdefault(s, LatHist()).merge(
                LatHist.from_json(d["hist"]))
            bytes_[s] = bytes_.get(s, 0) + int(d["bytes"])
        for s, n in o.get("errors", {}).items():
            errors[s] = errors.get(s, 0) + int(n)
    shapes_out = {s: _shape_report(hists[s], bytes_.get(s, 0), dt)
                  for s in hists}
    cpu_workers = sum(o["cpu_s"] for o in outs)
    write_bytes = sum(bytes_.get(s, 0) for s in bytes_
                      if s.startswith("put"))
    total_bytes = sum(bytes_.values())
    write_mib = write_bytes / 2**20
    cpu_total = cpu_workers + cpu_daemons
    out = {
        "backend": backend,
        "workers": n_workers,
        "clients_per_worker": clients_per_worker,
        "host_cpus": os.cpu_count(),
        "duration_s": round(dt, 2),
        "seed": seed,
        "n_osds": n_osds,
        "ops": sum(o["ops"] for o in outs),
        "ops_s": round(sum(o["ops"] for o in outs) / dt, 1)
        if dt else 0.0,
        "mib_s": round(total_bytes / dt / 2**20, 2) if dt else 0.0,
        "write_mib_s": round(write_mib / dt, 2) if dt else 0.0,
        "get_p99_ms": shapes_out.get("get4k", {}).get("p99_ms", 0.0),
        "cpu_s_workers": round(cpu_workers, 2),
        "cpu_s_daemons": round(cpu_daemons, 2),
        "cpu_s_per_mib": (round(cpu_total / write_mib, 4)
                          if write_mib else 0.0),
        "get_misses": sum(o.get("get_misses", 0) for o in outs),
        "op_errors": errors,
        "distinct_objects_touched": sum(o["objects"] for o in outs),
        "shapes": shapes_out,
    }
    if own_dir:
        shutil.rmtree(data_dir, ignore_errors=True)
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="swarm", description=__doc__,
                                 formatter_class=argparse
                                 .RawDescriptionHelpFormatter)
    ap.add_argument("--clients", type=int, default=2000)
    ap.add_argument("--duration", type=float, default=8.0)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--osds", type=int, default=10)
    ap.add_argument("--window", type=int, default=4096)
    ap.add_argument("--rados-clients", type=int, default=4)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--objects", type=int, default=1_000_000)
    ap.add_argument("--zipf", type=float, default=1.1)
    ap.add_argument("--thrash-secs", type=float, default=0.0)
    ap.add_argument("--qos", action="store_true",
                    help="mClock tenant-isolation mode")
    ap.add_argument("--no-placement-batch", action="store_true",
                    help="A/B arm: disable the batched resolver")
    ap.add_argument("--fabric", action="store_true",
                    help="sharded fabric mode: N reactor processes")
    ap.add_argument("--workers", type=int, default=2,
                    help="fabric: reactor process count")
    ap.add_argument("--backend", default="tcp",
                    choices=["local", "tcp", "shm"],
                    help="fabric: topology/messenger backend")
    ap.add_argument("--fabric-worker", metavar="CFGJSON",
                    help=argparse.SUPPRESS)  # internal child entry
    args = ap.parse_args(argv)
    if args.fabric_worker:
        return _fabric_worker_main(args.fabric_worker)
    if args.fabric:
        out = asyncio.run(run_fabric(
            backend=args.backend, n_workers=args.workers,
            clients_per_worker=max(1, args.clients // args.workers),
            duration=args.duration, seed=args.seed, n_osds=args.osds,
            window=args.window, depth=args.depth,
            n_objects=args.objects, zipf_s=args.zipf))
        print(json.dumps(out, indent=1, sort_keys=True))
        return 0
    out = asyncio.run(run_swarm(
        clients=args.clients, duration=args.duration, seed=args.seed,
        n_osds=args.osds, window=args.window,
        n_rados_clients=args.rados_clients, actor_depth=args.depth,
        n_objects=args.objects, zipf_s=args.zipf,
        thrash_secs=args.thrash_secs,
        qos={"reservation_ops_s": 50.0} if args.qos else None,
        placement_batch=not args.no_placement_batch))
    print(json.dumps(out, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
