#!/usr/bin/env python3
"""ceph-objectstore-tool: offline store surgery (src/tools/
ceph_objectstore_tool.cc role). Operates directly on an OSD's store
directory while the OSD is down.

  objectstore_tool.py --data-path /tmp/c1/osd.0 --op list
  objectstore_tool.py --data-path /tmp/c1/osd.0 --op list --pgid 2.3
  objectstore_tool.py --data-path /tmp/c1/osd.0 --op info  --pgid 2.3
  objectstore_tool.py --data-path /tmp/c1/osd.0 --op export --pgid 2.3 \
                      --file pg.export
  objectstore_tool.py --data-path /tmp/c1/osd.1 --op import --file pg.export
  objectstore_tool.py --data-path /tmp/c1/osd.0 --op remove --pgid 2.3
  objectstore_tool.py --data-path /tmp/c1/osd.0 --op get-bytes \
                      --pgid 2.3 --obj myobj --file out.bin

The export format is a denc blob (magic, pgid, objects with data,
xattrs, omap) with a trailing CRC32C; import replays it as one
transaction. Works on both store flavors (BlueStoreLite: pass
--type bluestore, default; WalStore: --type walstore).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

from ceph_tpu import native  # noqa: E402
from ceph_tpu import store as store_mod  # noqa: E402
from ceph_tpu.store import transaction as tx  # noqa: E402
from ceph_tpu.utils import denc  # noqa: E402

EXPORT_MAGIC = 0x43455850  # "CEXP"


def open_store(args):
    return store_mod.create(args.type, args.data_path)


def coll_for(pgid: str) -> str:
    return pgid


def cmd_list(args, s) -> int:
    cols = [args.pgid] if args.pgid else s.list_collections()
    for cid in cols:
        for oid in s.list_objects(cid):
            print(json.dumps([cid, oid.decode(errors="replace")]))
    return 0


def cmd_info(args, s) -> int:
    cid = coll_for(args.pgid)
    oids = s.list_objects(cid)
    total = sum(s.stat(cid, o) for o in oids)
    print(json.dumps({"pgid": args.pgid, "objects": len(oids),
                      "bytes": total}))
    return 0


def cmd_export(args, s) -> int:
    cid = coll_for(args.pgid)
    parts = [denc.enc_u32(EXPORT_MAGIC), denc.enc_str(cid)]
    oids = s.list_objects(cid)
    parts.append(denc.enc_u32(len(oids)))
    for oid in oids:
        parts.append(denc.enc_bytes(oid))
        parts.append(denc.enc_bytes(bytes(s.read(cid, oid))))
        parts.append(denc.enc_map(s.getattrs(cid, oid),
                                  denc.enc_str, denc.enc_bytes))
        parts.append(denc.enc_map(s.omap_get(cid, oid),
                                  denc.enc_bytes, denc.enc_bytes))
        parts.append(denc.enc_bytes(s.omap_get_header(cid, oid)))
    blob = b"".join(parts)
    blob += denc.enc_u32(native.crc32c(np.frombuffer(blob, np.uint8)))
    with open(args.file, "wb") as f:
        f.write(blob)
    print(f"exported {len(oids)} objects from {cid} "
          f"({len(blob)} bytes)")
    return 0


def cmd_import(args, s) -> int:
    blob = open(args.file, "rb").read()
    body, want = blob[:-4], denc.dec_u32(blob, len(blob) - 4)[0]
    got = native.crc32c(np.frombuffer(body, np.uint8))
    if got != want:
        raise SystemExit(f"export file corrupt: crc {got:#x} != {want:#x}")
    magic, off = denc.dec_u32(body, 0)
    if magic != EXPORT_MAGIC:
        raise SystemExit("not an export file")
    cid, off = denc.dec_str(body, off)
    n, off = denc.dec_u32(body, off)
    if cid in s.list_collections():
        # merging under an existing PG would leave its log (_pgmeta)
        # inconsistent with the union of contents; the reference tool
        # refuses the same way
        raise SystemExit(
            f"collection {cid} already exists; --op remove it first")
    t = tx.Transaction()
    t.create_collection(cid)
    for _ in range(n):
        oid, off = denc.dec_bytes(body, off)
        data, off = denc.dec_bytes(body, off)
        xattrs, off = denc.dec_map(body, off, denc.dec_str, denc.dec_bytes)
        omap, off = denc.dec_map(body, off, denc.dec_bytes, denc.dec_bytes)
        hdr, off = denc.dec_bytes(body, off)
        t.touch(cid, oid)
        t.truncate(cid, oid, 0)
        if data:
            t.write(cid, oid, 0, data)
        if xattrs:
            t.setattrs(cid, oid, xattrs)
        if omap:
            t.omap_setkeys(cid, oid, omap)
        if hdr:
            t.omap_setheader(cid, oid, hdr)
    s.apply_transaction(t)
    print(f"imported {n} objects into {cid}")
    return 0


def cmd_remove(args, s) -> int:
    cid = coll_for(args.pgid)
    t = tx.Transaction()
    for oid in s.list_objects(cid):
        t.remove(cid, oid)
    t.remove_collection(cid)
    s.apply_transaction(t)
    print(f"removed {cid}")
    return 0


def cmd_get_bytes(args, s) -> int:
    data = bytes(s.read(coll_for(args.pgid), args.obj.encode()))
    if args.file == "-":
        sys.stdout.buffer.write(data)
    else:
        with open(args.file, "wb") as f:
            f.write(data)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--type", default="bluestore",
                    choices=["bluestore", "walstore", "filestore"])
    ap.add_argument("--op", required=True,
                    choices=["list", "info", "export", "import",
                             "remove", "get-bytes"])
    ap.add_argument("--pgid")
    ap.add_argument("--obj")
    ap.add_argument("--file")
    args = ap.parse_args(argv)
    if args.op in ("info", "export", "remove", "get-bytes") \
            and not args.pgid:
        ap.error(f"--op {args.op} requires --pgid")
    if args.op in ("export", "import", "get-bytes") and not args.file:
        ap.error(f"--op {args.op} requires --file")
    s = open_store(args)
    try:
        fn = {
            "list": cmd_list, "info": cmd_info, "export": cmd_export,
            "import": cmd_import, "remove": cmd_remove,
            "get-bytes": cmd_get_bytes,
        }[args.op]
        return fn(args, s)
    finally:
        s.umount()


if __name__ == "__main__":
    # head-friendly CLI: a closed stdout pipe is a normal exit. Set
    # only when run as a program — at import time this would strip
    # the hosting process (e.g. pytest) of CPython's SIGPIPE ignore
    # and a later write to any dead socket would kill it (exit 141).
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
