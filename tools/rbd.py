#!/usr/bin/env python3
"""rbd: block-image CLI against a dev cluster (the src/tools/rbd
role). Runs vstart-style in-process; with --data-dir images persist on
durable BlueStoreLite stores across invocations:

  rbd.py --data-dir /tmp/c1 mkpool rbd 3
  rbd.py --data-dir /tmp/c1 create rbd/disk --size 64M
  rbd.py --data-dir /tmp/c1 ls rbd
  rbd.py --data-dir /tmp/c1 info rbd/disk
  rbd.py --data-dir /tmp/c1 import rbd/disk ./disk.img
  rbd.py --data-dir /tmp/c1 export rbd/disk ./out.img
  rbd.py --data-dir /tmp/c1 snap create rbd/disk@s1
  rbd.py --data-dir /tmp/c1 clone rbd/disk@s1 rbd/child
  rbd.py --data-dir /tmp/c1 flatten rbd/child
  rbd.py --data-dir /tmp/c1 cp rbd/disk rbd/copy        # deep copy
  rbd.py --data-dir /tmp/c1 resize rbd/disk --size 128M
  rbd.py --data-dir /tmp/c1 encryption format rbd/disk pass.txt
  rbd.py --data-dir /tmp/c1 export rbd/disk out.img --passphrase-file pass.txt
  rbd.py --data-dir /tmp/c1 migration prepare rbd/disk rbd/disk2
  rbd.py --data-dir /tmp/c1 migration execute rbd/disk2
  rbd.py --data-dir /tmp/c1 migration commit rbd/disk2
  rbd.py --data-dir /tmp/c1 rm rbd/disk
"""
from __future__ import annotations

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import importlib.util  # noqa: E402

from ceph_tpu.osdc.striper import FileLayout  # noqa: E402
from ceph_tpu.services.rbd import RBD  # noqa: E402
from ceph_tpu.services import rbd_crypto  # noqa: E402

_spec = importlib.util.spec_from_file_location(
    "ceph_tpu_tools_rados",
    os.path.join(os.path.dirname(__file__), "rados.py"))
_rados = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_rados)  # shared cluster_up/pool registry


def _size(s: str) -> int:
    mult = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30}
    if s and s[-1].upper() in mult:
        return int(float(s[:-1]) * mult[s[-1].upper()])
    return int(s)


def _split(spec: str) -> tuple[str, str, str | None]:
    """pool/image[@snap] -> (pool, image, snap)."""
    if "/" not in spec:
        raise SystemExit(f"image spec {spec!r} must be pool/name")
    pool, _, rest = spec.partition("/")
    name, _, snap = rest.partition("@")
    return pool, name, snap or None


async def _open_ctx(args, spec: str):
    c, pools = await _rados.cluster_up(args)
    pool, name, snap = _split(spec)
    return c, RBD(c.client, _rados._pool_id(pools, pool)), name, snap


def _passphrase(args) -> str | None:
    pf = getattr(args, "passphrase_file", None)
    if not pf:
        return None
    with open(pf) as f:
        return f.read().strip()


async def _image_handle(rbd: RBD, name: str, snap, args):
    """Plain or decrypting handle, by --passphrase-file."""
    pw = _passphrase(args)
    if pw is None:
        return await rbd.open(name, snap=snap)
    return await rbd_crypto.open_encrypted(rbd, name, pw, snap=snap)


async def cmd_create(args) -> int:
    c, rbd, name, _ = await _open_ctx(args, args.image)
    try:
        layout = FileLayout(stripe_unit=args.stripe_unit,
                            stripe_count=args.stripe_count,
                            object_size=args.object_size)
        await rbd.create(name, _size(args.size), layout)
        print(f"image '{name}' created ({_size(args.size)} bytes)")
    finally:
        await c.stop()
    return 0


async def cmd_ls(args) -> int:
    c, pools = await _rados.cluster_up(args)
    try:
        rbd = RBD(c.client, _rados._pool_id(pools, args.pool))
        for n in await rbd.list():
            print(n)
    finally:
        await c.stop()
    return 0


async def cmd_info(args) -> int:
    c, rbd, name, snap = await _open_ctx(args, args.image)
    try:
        img = await rbd.open(name, snap=snap)
        st = await img.stat()
        for k, v in st.items():
            print(f"{k}: {v}")
        await img.release_lock()
    finally:
        await c.stop()
    return 0


async def cmd_rm(args) -> int:
    c, rbd, name, _ = await _open_ctx(args, args.image)
    try:
        await rbd.remove(name)
        print(f"image '{name}' removed")
    finally:
        await c.stop()
    return 0


async def cmd_resize(args) -> int:
    c, rbd, name, _ = await _open_ctx(args, args.image)
    try:
        img = await _image_handle(rbd, name, None, args)
        await img.resize(_size(args.size))
        await img.release_lock()
        print(f"resized to {_size(args.size)}")
    finally:
        await c.stop()
    return 0


async def cmd_import(args) -> int:
    c, rbd, name, _ = await _open_ctx(args, args.image)
    try:
        img = await _image_handle(rbd, name, None, args)
        total = 0
        step = 4 << 20
        with open(args.infile, "rb") as f:  # constant-memory chunks
            while chunk := f.read(step):
                await img.write(total, chunk)
                total += len(chunk)
        await img.release_lock()
        print(f"imported {total} bytes into '{name}'")
    finally:
        await c.stop()
    return 0


async def cmd_export(args) -> int:
    c, rbd, name, snap = await _open_ctx(args, args.image)
    try:
        img = await _image_handle(rbd, name, snap, args)
        out = (sys.stdout.buffer if args.outfile == "-"
               else open(args.outfile, "wb"))
        step = 4 << 20
        for off in range(0, img.size, step):
            out.write(await img.read(off, min(step, img.size - off)))
        if out is not sys.stdout.buffer:
            out.close()
        await img.release_lock()
    finally:
        await c.stop()
    return 0


async def cmd_snap(args) -> int:
    if args.snap_cmd != "ls" and "@" not in args.image:
        raise SystemExit(
            f"snap {args.snap_cmd} needs pool/name@snap")
    c, rbd, name, snap = await _open_ctx(args, args.image)
    try:
        img = await rbd.open(name)
        if args.snap_cmd == "create":
            await img.snap_create(snap)
            print(f"snap '{snap}' created")
        elif args.snap_cmd == "ls":
            for s in await img.snap_list():
                print(s)
        elif args.snap_cmd == "rm":
            await img.snap_remove(snap)
            print(f"snap '{snap}' removed")
        elif args.snap_cmd == "rollback":
            await img.snap_rollback(snap)
            print(f"rolled back to '{snap}'")
        await img.release_lock()
    finally:
        await c.stop()
    return 0


async def cmd_clone(args) -> int:
    c, pools = await _rados.cluster_up(args)
    try:
        ppool, parent, snap = _split(args.parent)
        cpool, child, _ = _split(args.child)
        if ppool != cpool:
            raise SystemExit("clone must stay within one pool")
        if snap is None:
            raise SystemExit("clone needs parent@snap")
        rbd = RBD(c.client, _rados._pool_id(pools, ppool))
        await rbd.clone(parent, snap, child)
        print(f"cloned '{args.parent}' -> '{child}'")
    finally:
        await c.stop()
    return 0


async def cmd_flatten(args) -> int:
    c, rbd, name, _ = await _open_ctx(args, args.image)
    try:
        img = await rbd.open(name)
        await img.flatten()
        await img.release_lock()
        print(f"'{name}' flattened")
    finally:
        await c.stop()
    return 0


async def cmd_cp(args) -> int:
    c, pools = await _rados.cluster_up(args)
    try:
        spool, src, _ = _split(args.src)
        dpool, dst, _ = _split(args.dst)
        if spool != dpool:
            raise SystemExit("cp must stay within one pool")
        rbd = RBD(c.client, _rados._pool_id(pools, spool))
        await rbd.deep_copy(src, dst)
        print(f"copied '{src}' -> '{dst}'")
    finally:
        await c.stop()
    return 0


async def cmd_migration(args) -> int:
    c, pools = await _rados.cluster_up(args)
    try:
        if args.mig_cmd == "prepare":
            if not args.dst:
                raise SystemExit("migration prepare needs src AND dst")
            pool, src, _ = _split(args.src)
            _p2, dst, _ = _split(args.dst)
            rbd = RBD(c.client, _rados._pool_id(pools, pool))
            await rbd.migration_prepare(src, dst)
            print(f"migration prepared: '{src}' -> '{dst}'")
        else:
            pool, dst, _ = _split(args.src)
            rbd = RBD(c.client, _rados._pool_id(pools, pool))
            await getattr(rbd, f"migration_{args.mig_cmd}")(dst)
            print(f"migration {args.mig_cmd}: '{dst}'")
    finally:
        await c.stop()
    return 0


async def cmd_encryption(args) -> int:
    c, rbd, name, _ = await _open_ctx(args, args.image)
    try:
        with open(args.passfile) as f:
            pw = f.read().strip()
        await rbd_crypto.encryption_format(rbd, name, pw)
        print(f"'{name}' encryption-formatted")
    finally:
        await c.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--data-dir")
    ap.add_argument("--osds", type=int, default=4)
    ap.add_argument("--dev-size", type=int, default=256)
    ap.add_argument("--timeout", type=float, default=60.0)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("mkpool")  # delegates to the rados tool
    p.add_argument("pool")
    p.add_argument("size", type=int, nargs="?", default=3)
    p.add_argument("--pg-num", type=int, default=16)
    p.add_argument("--ec-k", type=int, default=0)
    p.add_argument("--ec-m", type=int, default=2)
    p.add_argument("--ec-plugin", default="rs_tpu")
    p.set_defaults(fn=_rados.cmd_mkpool)

    p = sub.add_parser("create")
    p.add_argument("image")
    p.add_argument("--size", required=True, help="e.g. 64M")
    p.add_argument("--stripe-unit", type=int, default=1 << 16)
    p.add_argument("--stripe-count", type=int, default=4)
    p.add_argument("--object-size", type=int, default=1 << 22)
    p.set_defaults(fn=cmd_create)

    p = sub.add_parser("ls")
    p.add_argument("pool")
    p.set_defaults(fn=cmd_ls)

    for n, fn in (("info", cmd_info), ("rm", cmd_rm),
                  ("flatten", cmd_flatten)):
        p = sub.add_parser(n)
        p.add_argument("image")
        p.set_defaults(fn=fn)

    p = sub.add_parser("resize")
    p.add_argument("image")
    p.add_argument("--size", required=True)
    p.add_argument("--passphrase-file")
    p.set_defaults(fn=cmd_resize)

    p = sub.add_parser("import")
    p.add_argument("image"), p.add_argument("infile")
    p.add_argument("--passphrase-file")
    p.set_defaults(fn=cmd_import)

    p = sub.add_parser("export")
    p.add_argument("image"), p.add_argument("outfile")
    p.add_argument("--passphrase-file")
    p.set_defaults(fn=cmd_export)

    p = sub.add_parser("snap")
    p.add_argument("snap_cmd",
                   choices=["create", "ls", "rm", "rollback"])
    p.add_argument("image", help="pool/name@snap (ls: pool/name)")
    p.set_defaults(fn=cmd_snap)

    p = sub.add_parser("clone")
    p.add_argument("parent", help="pool/name@snap")
    p.add_argument("child", help="pool/name")
    p.set_defaults(fn=cmd_clone)

    p = sub.add_parser("cp")
    p.add_argument("src"), p.add_argument("dst")
    p.set_defaults(fn=cmd_cp)

    p = sub.add_parser("migration")
    p.add_argument("mig_cmd",
                   choices=["prepare", "execute", "commit", "abort"])
    p.add_argument("src", help="pool/src (prepare) or pool/dst")
    p.add_argument("dst", nargs="?", help="pool/dst (prepare only)")
    p.set_defaults(fn=cmd_migration)

    p = sub.add_parser("encryption")
    p.add_argument("enc_cmd", choices=["format"])
    p.add_argument("image"), p.add_argument("passfile")
    p.set_defaults(fn=cmd_encryption)

    args = ap.parse_args(argv)
    return asyncio.run(args.fn(args))


if __name__ == "__main__":
    raise SystemExit(main())
