#!/usr/bin/env python3
"""ceph-daemon: talk to a daemon's admin socket (the `ceph daemon
<sock> <command>` role).

  ceph_daemon.py /path/osd0.sock help
  ceph_daemon.py /path/osd0.sock perf dump
  ceph_daemon.py /path/osd0.sock config set key=osd_subop_timeout value=5
  ceph_daemon.py /path/mgr.sock prometheus
"""
from __future__ import annotations

import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.utils.admin import admin_command  # noqa: E402


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print(__doc__)
        return 2
    sock = argv[0]
    words = []
    kwargs = {}
    for tok in argv[1:]:
        if "=" in tok:
            k, v = tok.split("=", 1)
            kwargs[k] = v
        else:
            words.append(tok)
    prefix = " ".join(words)
    result = asyncio.run(admin_command(sock, prefix, **kwargs))
    if isinstance(result, str):
        print(result, end="" if result.endswith("\n") else "\n")
    else:
        print(json.dumps(result, indent=2, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
