#!/usr/bin/env python3
"""ceph: the cluster admin CLI (the src/ceph.in + MonCommands.h role).

Commands are NOT parsed here: argv is matched against the descriptor
table the mon itself serves (get_command_descriptions), exactly the
reference's validate_command stance — the CLI stays dumb and the
command surface lives with the daemon that executes it.

Runs against a vstart-style in-process cluster; with --data-dir state
persists across invocations on BlueStoreLite (same convention as
tools/rados.py):

  ceph.py status
  ceph.py -f json df
  ceph.py osd tree
  ceph.py osd pool create mypool 32 replicated 3
  ceph.py osd pool set mypool quota_max_objects 1000
  ceph.py osd out 2
  ceph.py config set osd debug_level 5
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.cluster import TestCluster  # noqa: E402


async def main_async(args) -> int:
    kw = {}
    if args.data_dir:
        os.makedirs(args.data_dir, exist_ok=True)
        kw = dict(objectstore="bluestore", data_dir=args.data_dir,
                  size=args.dev_size << 20)
    c = TestCluster(n_osds=args.osds, **kw)
    await c.start()
    try:
        # for stats-backed commands, wait for one round of OSD
        # reports -> mgr digest -> mon to land (hb + 1 s digest tick)
        if args.command[0] in ("status", "df", "pg", "health"):
            for _ in range(40):
                if c.mon.mgr_digest.get("pg_states"):
                    break
                await asyncio.sleep(0.1)
        rc, outs, outb = await c.client.mon_command(args.command)
        if args.format == "json":
            print(outb.decode() if outb else "{}")
        else:
            if outs:
                print(outs)
            elif outb:
                print(outb.decode())
        if rc != 0:
            print(f"Error: {rc}", file=sys.stderr)
        return 0 if rc == 0 else 1
    finally:
        await c.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--data-dir", default=None,
                    help="durable cluster state dir (BlueStoreLite)")
    ap.add_argument("--osds", type=int, default=3)
    ap.add_argument("--dev-size", type=int, default=256,
                    help="per-OSD device MiB (durable mode)")
    ap.add_argument("-f", "--format", choices=("plain", "json"),
                    default="plain")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="mon command words (e.g. osd tree)")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given (try: status)")
    return asyncio.run(main_async(args))


if __name__ == "__main__":
    raise SystemExit(main())
