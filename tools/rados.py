#!/usr/bin/env python3
"""rados: object CLI + bench against a dev cluster (the src/tools/rados
role, with `bench` playing src/common/obj_bencher.h:64-113).

The cluster is vstart-style in-process; with --data-dir it runs on
durable BlueStoreLite stores, so state persists across invocations:

  rados.py --data-dir /tmp/c1 mkpool rep 3            # replicated size 3
  rados.py --data-dir /tmp/c1 mkpool ecp 5 --ec-k 3 --ec-m 2
  rados.py --data-dir /tmp/c1 put ecp myobj ./file
  rados.py --data-dir /tmp/c1 get ecp myobj -          # to stdout
  rados.py --data-dir /tmp/c1 ls ecp
  rados.py --data-dir /tmp/c1 stat ecp myobj
  rados.py --data-dir /tmp/c1 rm ecp myobj
  rados.py --data-dir /tmp/c1 df
  rados.py bench ecp 5 write --ec-k 3 --ec-m 2 -b 4194304 -t 8
  rados.py bench ecp 5 seq / rand    (reads the objects bench-write left)

Without --data-dir everything runs on MemStore and vanishes on exit
(useful for bench runs, which bring their own pool).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.cluster import TestCluster  # noqa: E402
from ceph_tpu.placement.osdmap import Pool  # noqa: E402

POOLS_META = "pools.json"  # pool registry, kept beside the stores


def _load_pools(data_dir: str | None) -> dict:
    if not data_dir:
        return {}
    p = os.path.join(data_dir, POOLS_META)
    if os.path.exists(p):
        with open(p) as f:
            return json.load(f)
    return {}


def _save_pools(data_dir: str | None, pools: dict) -> None:
    if data_dir:
        with open(os.path.join(data_dir, POOLS_META), "w") as f:
            json.dump(pools, f)


async def cluster_up(args) -> tuple[TestCluster, dict]:
    kw = {}
    if args.data_dir:
        os.makedirs(args.data_dir, exist_ok=True)
        kw = dict(objectstore="bluestore", data_dir=args.data_dir,
                  size=args.dev_size << 20)
    c = TestCluster(n_osds=args.osds, **kw)
    await c.start()
    c.client.op_timeout = args.timeout
    pools = _load_pools(args.data_dir)
    # re-register pools from the registry (mon state is not durable;
    # PGs re-peer onto the existing store collections)
    for name, spec in pools.items():
        await c.client.create_pool(Pool(**spec))
    if pools:
        await c.wait_active(args.timeout)
    return c, pools


def _pool_id(pools: dict, name: str) -> int:
    if name not in pools:
        raise SystemExit(f"pool '{name}' not found (mkpool first)")
    return pools[name]["id"]


async def cmd_mkpool(args) -> int:
    c, pools = await cluster_up(args)
    try:
        pid = max([p["id"] for p in pools.values()], default=1) + 1
        spec = dict(id=pid, name=args.pool, size=args.size,
                    min_size=max(1, args.size - 1), pg_num=args.pg_num,
                    crush_rule=0, type="replicated")
        if args.ec_k:
            spec.update(
                type="erasure", crush_rule=1,
                size=args.ec_k + args.ec_m,
                min_size=args.ec_k,
                ec_profile={"plugin": args.ec_plugin,
                            "k": str(args.ec_k), "m": str(args.ec_m),
                            "backend": "device"})
        await c.client.create_pool(Pool(**spec))
        await c.wait_active(args.timeout)
        pools[args.pool] = spec
        _save_pools(args.data_dir, pools)
        print(f"pool '{args.pool}' created (id {pid})")
    finally:
        await c.stop()
    return 0


def _parse_snapc(spec: str | None):
    """--snapc 'seq:id,id,...' -> (seq, [ids]) write SnapContext."""
    if not spec:
        return None
    seq_s, _, ids_s = spec.partition(":")
    ids = [int(x) for x in ids_s.split(",") if x]
    return (int(seq_s), ids)


async def cmd_put(args) -> int:
    data = (sys.stdin.buffer.read() if args.infile == "-"
            else open(args.infile, "rb").read())
    c, pools = await cluster_up(args)
    try:
        await c.client.write_full(_pool_id(pools, args.pool),
                                  args.obj.encode(), data,
                                  snapc=_parse_snapc(args.snapc))
    finally:
        await c.stop()
    return 0


async def cmd_get(args) -> int:
    c, pools = await cluster_up(args)
    try:
        data = await c.client.read(_pool_id(pools, args.pool),
                                   args.obj.encode(),
                                   snapid=args.snapid)
    finally:
        await c.stop()
    if args.outfile == "-":
        sys.stdout.buffer.write(data)
    else:
        with open(args.outfile, "wb") as f:
            f.write(data)
    return 0


async def cmd_rm(args) -> int:
    c, pools = await cluster_up(args)
    try:
        await c.client.delete(_pool_id(pools, args.pool),
                              args.obj.encode())
    finally:
        await c.stop()
    return 0


async def cmd_stat(args) -> int:
    c, pools = await cluster_up(args)
    try:
        size = await c.client.stat(_pool_id(pools, args.pool),
                                   args.obj.encode())
        print(f"{args.pool}/{args.obj} size {size}")
    finally:
        await c.stop()
    return 0


async def cmd_ls(args) -> int:
    c, pools = await cluster_up(args)
    try:
        for oid in await c.client.list_objects(_pool_id(pools, args.pool)):
            print(oid.decode(errors="replace"))
    finally:
        await c.stop()
    return 0


async def cmd_snap_create(args) -> int:
    c, pools = await cluster_up(args)
    try:
        snapid = await c.client.selfmanaged_snap_create(
            _pool_id(pools, args.pool))
        print(f"created snap {snapid} in pool '{args.pool}'")
    finally:
        await c.stop()
    return 0


async def cmd_snap_rm(args) -> int:
    c, pools = await cluster_up(args)
    try:
        await c.client.selfmanaged_snap_remove(
            _pool_id(pools, args.pool), args.snapid)
        print(f"removed snap {args.snapid} from pool '{args.pool}' "
              "(trimming is asynchronous)")
    finally:
        await c.stop()
    return 0


async def cmd_df(args) -> int:
    c, pools = await cluster_up(args)
    try:
        print(f"{'POOL':<16}{'ID':>4}{'OBJECTS':>9}{'BYTES':>14}")
        for name, spec in sorted(pools.items()):
            oids = await c.client.list_objects(spec["id"])
            total = 0
            for oid in oids:
                total += await c.client.stat(spec["id"], oid)
            print(f"{name:<16}{spec['id']:>4}{len(oids):>9}{total:>14}")
    finally:
        await c.stop()
    return 0


async def cmd_bench(args) -> int:
    """obj_bencher role: timed write / seq-read / rand-read with
    throughput and latency stats."""
    import random

    c, pools = await cluster_up(args)
    try:
        if args.pool in pools:
            pid = pools[args.pool]["id"]
        else:  # bench brings its own pool (rados bench convention)
            args.size = (args.ec_k + args.ec_m) if args.ec_k else 3
            args.pg_num = 16
            pid = max([p["id"] for p in pools.values()], default=1) + 1
            spec = dict(id=pid, name=args.pool, size=args.size,
                        min_size=max(1, args.size - 1), pg_num=16,
                        crush_rule=0, type="replicated")
            if args.ec_k:
                spec.update(type="erasure", crush_rule=1,
                            min_size=args.ec_k,
                            ec_profile={"plugin": args.ec_plugin,
                                        "k": str(args.ec_k),
                                        "m": str(args.ec_m),
                                        "backend": "device"})
            await c.client.create_pool(Pool(**spec))
            await c.wait_active(args.timeout)
            pools[args.pool] = spec
            _save_pools(args.data_dir, pools)

        lat: list[float] = []
        done = 0
        bytes_done = 0
        deadline = time.perf_counter() + args.seconds
        sem = asyncio.Semaphore(args.concurrency)
        payload = os.urandom(args.block_size)

        t_start = time.perf_counter()
        if args.mode == "write":
            async def one(i: int):
                nonlocal done, bytes_done
                async with sem:
                    t0 = time.perf_counter()
                    await c.client.write_full(pid, b"bench_%d" % i, payload)
                    lat.append(time.perf_counter() - t0)
                    done += 1
                    bytes_done += len(payload)

            i = 0
            pending: set = set()
            while time.perf_counter() < deadline:
                while len(pending) < args.concurrency:
                    pending.add(asyncio.ensure_future(one(i)))
                    i += 1
                fin, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for f in fin:
                    f.result()
            if pending:
                await asyncio.gather(*pending)
        else:  # seq / rand read over whatever bench_ objects exist
            objs = [o for o in await c.client.list_objects(pid)
                    if o.startswith(b"bench_")]
            if not objs:
                raise SystemExit("no bench_ objects; run bench write first")

            async def rd(oid: bytes):
                nonlocal done, bytes_done
                async with sem:
                    t0 = time.perf_counter()
                    data = await c.client.read(pid, oid)
                    lat.append(time.perf_counter() - t0)
                    done += 1
                    bytes_done += len(data)

            # listing is setup, not benched work: restart the clock
            t_start = time.perf_counter()
            deadline = t_start + args.seconds
            pending = set()
            i = 0
            while time.perf_counter() < deadline:
                oid = (random.choice(objs) if args.mode == "rand"
                       else objs[i % len(objs)])
                i += 1
                while len(pending) >= args.concurrency:
                    fin, pending = await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED)
                    for f in fin:
                        f.result()
                pending.add(asyncio.ensure_future(rd(oid)))
            if pending:
                await asyncio.gather(*pending)

        # actual elapsed, incl. the post-deadline drain (obj_bencher
        # divides by wall time, not the nominal window)
        secs = max(time.perf_counter() - t_start, 1e-9)
        lat.sort()
        out = {
            "mode": args.mode,
            "seconds": round(secs, 3),
            "ops": done,
            "bytes": bytes_done,
            "mb_per_sec": round(bytes_done / secs / 2**20, 2),
            "iops": round(done / secs, 2),
            "avg_lat_ms": round(sum(lat) / len(lat) * 1e3, 2) if lat else 0,
            "p50_lat_ms": round(lat[len(lat) // 2] * 1e3, 2) if lat else 0,
            "p99_lat_ms": (round(lat[int(len(lat) * 0.99)] * 1e3, 2)
                           if lat else 0),
            "max_lat_ms": round(lat[-1] * 1e3, 2) if lat else 0,
        }
        print(json.dumps(out))
    finally:
        await c.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--data-dir", help="durable cluster state dir "
                    "(BlueStoreLite per OSD); omit for throwaway MemStore")
    ap.add_argument("--osds", type=int, default=5)
    ap.add_argument("--dev-size", type=int, default=256,
                    help="per-OSD block device MiB (default 256)")
    ap.add_argument("--timeout", type=float, default=60.0)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("mkpool")
    p.add_argument("pool")
    p.add_argument("size", type=int, nargs="?", default=3)
    p.add_argument("--pg-num", type=int, default=16)
    p.add_argument("--ec-k", type=int, default=0)
    p.add_argument("--ec-m", type=int, default=2)
    p.add_argument("--ec-plugin", default="rs_tpu")
    p.set_defaults(fn=cmd_mkpool)

    p = sub.add_parser("put")
    p.add_argument("pool"), p.add_argument("obj"), p.add_argument("infile")
    p.add_argument("--snapc", default=None,
                   help="write SnapContext 'seq:id,id,...'")
    p.set_defaults(fn=cmd_put)

    p = sub.add_parser("get")
    p.add_argument("pool"), p.add_argument("obj"), p.add_argument("outfile")
    p.add_argument("--snapid", type=int, default=None,
                   help="read at this selfmanaged snap id")
    p.set_defaults(fn=cmd_get)

    p = sub.add_parser("snap-create")
    p.add_argument("pool")
    p.set_defaults(fn=cmd_snap_create)

    p = sub.add_parser("snap-rm")
    p.add_argument("pool"), p.add_argument("snapid", type=int)
    p.set_defaults(fn=cmd_snap_rm)

    p = sub.add_parser("rm")
    p.add_argument("pool"), p.add_argument("obj")
    p.set_defaults(fn=cmd_rm)

    p = sub.add_parser("stat")
    p.add_argument("pool"), p.add_argument("obj")
    p.set_defaults(fn=cmd_stat)

    p = sub.add_parser("ls")
    p.add_argument("pool")
    p.set_defaults(fn=cmd_ls)

    p = sub.add_parser("df")
    p.set_defaults(fn=cmd_df)

    p = sub.add_parser("bench")
    p.add_argument("pool")
    p.add_argument("seconds", type=int)
    p.add_argument("mode", choices=["write", "seq", "rand"])
    p.add_argument("-b", "--block-size", type=int, default=4 << 20)
    p.add_argument("-t", "--concurrency", type=int, default=16)
    p.add_argument("--ec-k", type=int, default=0)
    p.add_argument("--ec-m", type=int, default=2)
    p.add_argument("--ec-plugin", default="rs_tpu")
    p.set_defaults(fn=cmd_bench)

    args = ap.parse_args(argv)
    return asyncio.run(args.fn(args))


if __name__ == "__main__":
    # head-friendly CLI: a closed stdout pipe is a normal exit. Set
    # only when run as a program — at import time this would strip
    # the hosting process (e.g. pytest) of CPython's SIGPIPE ignore
    # and a later write to any dead socket would kill it (exit 141).
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
