#!/usr/bin/env python
"""tpulint CLI: run the ceph_tpu.analysis rules over the tree.

Usage:
    python tools/tpulint.py [paths...]            # lint (default:
                                                  #  ceph_tpu tools)
    python tools/tpulint.py --update-baseline     # grandfather current
                                                  #  findings
    python tools/tpulint.py --list-rules
    python tools/tpulint.py --json

Exit codes: 0 clean (or fully baselined), 1 non-baselined findings,
2 usage error. The tier-1 gate (tests/test_tpulint.py) runs the same
analysis in-process, so CI and this CLI can never disagree.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_REPO))

from ceph_tpu import analysis  # noqa: E402

DEFAULT_PATHS = ("ceph_tpu", "tools")
DEFAULT_BASELINE = _REPO / "tools" / "tpulint_baseline.json"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpulint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                    help="files/dirs to lint (default: ceph_tpu tools)")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baselined or not")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from a FULL run (any "
                         "--rules/path filters are ignored so a "
                         "partial run can never erase other entries)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    analysis.preload()
    if args.list_rules:
        for name in analysis.instance().names():
            print(name)
        return 0

    if args.update_baseline:
        # ALWAYS a full run: honoring --rules/path filters here would
        # rewrite the baseline from a subset and silently erase every
        # other grandfathered entry
        full = analysis.run_paths(DEFAULT_PATHS, _REPO)
        analysis.save_baseline(args.baseline, full)
        print(f"baseline updated: {len(full)} finding(s) -> "
              f"{args.baseline}")
        return 0

    only = args.rules.split(",") if args.rules else None
    findings = analysis.run_paths(args.paths, _REPO, only)

    if args.no_baseline:
        new = findings
    else:
        new = analysis.unbaselined(
            findings, analysis.load_baseline(args.baseline))

    if args.as_json:
        print(json.dumps([f.__dict__ for f in new], indent=1))
    else:
        for f in new:
            print(f.render())
        n_base = len(findings) - len(new)
        print(f"tpulint: {len(new)} finding(s)"
              + (f" ({n_base} baselined)" if n_base else ""),
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
