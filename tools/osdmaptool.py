#!/usr/bin/env python3
"""osdmaptool: inspect/build osdmaps, map objects, test PG distribution
(src/tools/osdmaptool.cc role).

  osdmaptool.py --createsimple 12 -o osdmap.bin [--pg-num 128]
  osdmaptool.py --print osdmap.bin
  osdmaptool.py --test-map-pgs osdmap.bin [--pool 1]
  osdmaptool.py --test-map-object foo --pool 1 osdmap.bin
"""
from __future__ import annotations

import argparse
import os
import signal
import sys


sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.placement import crushmap as cm  # noqa: E402
from ceph_tpu.placement import encoding as menc  # noqa: E402
from ceph_tpu.placement.osdmap import OSDMap, Pool  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("mapfile", nargs="?")
    ap.add_argument("--createsimple", type=int, metavar="N")
    ap.add_argument("--pg-num", type=int, default=128)
    ap.add_argument("-o", metavar="OUT")
    ap.add_argument("--print", dest="print_", action="store_true")
    ap.add_argument("--test-map-pgs", action="store_true")
    ap.add_argument("--test-map-object", metavar="NAME")
    ap.add_argument("--pool", type=int, default=1)
    args = ap.parse_args(argv)

    if args.createsimple:
        n = args.createsimple
        crush = cm.build_flat(n)
        crush.add_rule(cm.flat_firstn_rule(0))
        m = OSDMap(crush, n)
        m.add_pool(Pool(id=1, name="rbd", size=3, pg_num=args.pg_num,
                        crush_rule=0))
        out = args.o or "osdmap.bin"
        open(out, "wb").write(menc.encode_osdmap(m))
        print(f"osdmaptool: wrote {n}-osd map, pool 'rbd' "
              f"pg_num {args.pg_num} -> {out}")
        return 0

    if not args.mapfile:
        ap.error("need a mapfile (or --createsimple)")
    m, _ = menc.decode_osdmap(open(args.mapfile, "rb").read())

    if args.print_:
        print(f"epoch {m.epoch}")
        print(f"max_osd {m.n_osds}")
        for p in m.pools.values():
            print(f"pool {p.id} '{p.name}' {p.type} size {p.size} "
                  f"pg_num {p.pg_num} crush_rule {p.crush_rule}")
        ups = sum(1 for o in m.osds if o.up)
        print(f"osds: {ups} up / {m.n_osds} total")
        return 0

    if args.test_map_pgs:
        pool = m.pools[args.pool]
        counts: dict[int, int] = {}
        for ps in range(pool.pg_num):
            up, primary = m.pg_to_up_acting_osds((pool.id, ps))
            for o in up:
                if 0 <= o < m.n_osds:
                    counts[o] = counts.get(o, 0) + 1
        total = sum(counts.values())
        avg = total / max(len(counts), 1)
        print(f"pool {pool.id} pg_num {pool.pg_num}: {total} mappings "
              f"over {len(counts)} osds, avg {avg:.1f}")
        worst = max(counts.values()) / avg if counts else 0
        print(f"max/avg ratio {worst:.3f}")
        for o in sorted(counts):
            print(f"  osd.{o}\t{counts[o]}")
        return 0

    if args.test_map_object:
        oid = args.test_map_object.encode()
        pg = m.object_to_pg(args.pool, oid)
        up, primary = m.pg_to_up_acting_osds(pg)
        print(f"object '{args.test_map_object}' -> pg {pg[0]}.{pg[1]:x}"
              f" -> up {up} primary {primary}")
        return 0

    ap.print_help()
    return 2


if __name__ == "__main__":
    # head-friendly CLI: a closed stdout pipe is a normal exit. Set
    # only when run as a program — at import time this would strip
    # the hosting process (e.g. pytest) of CPython's SIGPIPE ignore
    # and a later write to any dead socket would kill it (exit 141).
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
