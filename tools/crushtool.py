#!/usr/bin/env python3
"""crushtool: compile/decompile/test crush maps (src/tools/crushtool.cc
role).

  crushtool.py -c map.txt -o map.bin         # compile text -> binary
  crushtool.py -d map.bin [-o map.txt]       # decompile binary -> text
  crushtool.py --build -o map.bin --num-osds 12 --per-host 3
  crushtool.py --test -i map.bin --rule 0 --num-rep 3 --max-x 1024 \
               [--show-utilization] [--show-bad-mappings] [--device]
"""
from __future__ import annotations

import argparse
import os
import signal
import sys


sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ceph_tpu.placement import compiler, crushmap as cm  # noqa: E402
from ceph_tpu.placement import encoding as menc  # noqa: E402
from ceph_tpu.placement.tester import test_rule  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-c", metavar="TXT", help="compile text map")
    ap.add_argument("-d", metavar="BIN", help="decompile binary map")
    ap.add_argument("-o", metavar="OUT", help="output file")
    ap.add_argument("-i", metavar="BIN", help="input binary map (--test)")
    ap.add_argument("--build", action="store_true",
                    help="build a simple host/osd hierarchy")
    ap.add_argument("--num-osds", type=int, default=12)
    ap.add_argument("--per-host", type=int, default=3)
    ap.add_argument("--test", action="store_true")
    ap.add_argument("--rule", type=int, default=0)
    ap.add_argument("--num-rep", type=int, default=3)
    ap.add_argument("--max-x", type=int, default=1024)
    ap.add_argument("--device", action="store_true",
                    help="run the batched device placement engine")
    ap.add_argument("--show-utilization", action="store_true")
    ap.add_argument("--show-bad-mappings", action="store_true")
    args = ap.parse_args(argv)

    if args.c:
        m = compiler.compile(open(args.c).read())
        blob = menc.encode_crushmap(m)
        out = args.o or args.c + ".bin"
        open(out, "wb").write(blob)
        print(f"wrote {len(blob)} bytes to {out}")
        return 0
    if args.d:
        m, _ = menc.decode_crushmap(open(args.d, "rb").read())
        text = compiler.decompile(m)
        if args.o:
            open(args.o, "w").write(text)
            print(f"wrote {args.o}")
        else:
            sys.stdout.write(text)
        return 0
    if args.build:
        n_hosts = -(-args.num_osds // args.per_host)
        m = cm.build_hierarchy(args.per_host, n_hosts)
        m.add_rule(cm.replicated_rule(0, failure_domain_type=1))
        m.add_rule(cm.ec_rule(1, failure_domain_type=1))
        out = args.o or "map.bin"
        open(out, "wb").write(menc.encode_crushmap(m))
        print(f"built {n_hosts} hosts x {args.per_host} osds -> {out}")
        return 0
    if args.test:
        if not args.i:
            ap.error("--test needs -i map.bin")
        m, _ = menc.decode_crushmap(open(args.i, "rb").read())
        rep = test_rule(m, args.rule, args.num_rep,
                        n_inputs=args.max_x, device=args.device)
        print(f"rule {args.rule}, num_rep {args.num_rep}, "
              f"{args.max_x} inputs: placed {rep.placed}, "
              f"{len(rep.bad_mappings)} bad mappings, "
              f"max deviation {rep.max_deviation(m):.4f}")
        if args.show_utilization:
            exp = rep.expected_utilization(m)
            for d, u in rep.utilization().items():
                print(f"  device {d}\tactual {u:.4f}\texpected "
                      f"{exp.get(d, 0.0):.4f}")
        if args.show_bad_mappings and rep.bad_mappings:
            print(f"  bad: {rep.bad_mappings[:20]}"
                  + (" ..." if len(rep.bad_mappings) > 20 else ""))
        return 1 if rep.bad_mappings else 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    # head-friendly CLI: a closed stdout pipe is a normal exit. Set
    # only when run as a program — at import time this would strip
    # the hosting process (e.g. pytest) of CPython's SIGPIPE ignore
    # and a later write to any dead socket would kill it (exit 141).
    signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    sys.exit(main())
