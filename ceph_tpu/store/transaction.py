"""Transaction: the redo-log of object store mutations.

Mirrors the reference op set (src/os/Transaction.h:110-155) with the ops
the data path needs: touch/write/zero/truncate/remove, xattr ops, clone
and clone_range, collection create/remove, and the omap family. A
Transaction is a list of op records built by fluent methods and applied
atomically by an ObjectStore (all-or-nothing, in order).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable

from ..utils.buffer import BufferList, as_view

# opcodes (names mirror Transaction.h)
OP_TOUCH = "touch"
OP_WRITE = "write"
OP_ZERO = "zero"
OP_TRUNCATE = "truncate"
OP_REMOVE = "remove"
OP_SETATTR = "setattr"
OP_SETATTRS = "setattrs"
OP_RMATTR = "rmattr"
OP_RMATTRS = "rmattrs"
OP_CLONE = "clone"
OP_CLONERANGE = "clone_range"
OP_MKCOLL = "mkcoll"
OP_RMCOLL = "rmcoll"
OP_SPLIT_COLL = "split_coll"
OP_MERGE_COLL = "merge_coll"
OP_SETALLOCHINT = "set_alloc_hint"
OP_OMAP_CLEAR = "omap_clear"
OP_OMAP_SETKEYS = "omap_setkeys"
OP_OMAP_RMKEYS = "omap_rmkeys"
OP_OMAP_RMKEYRANGE = "omap_rmkeyrange"
OP_OMAP_SETHEADER = "omap_setheader"


@dataclass
class Op:
    code: str
    cid: str
    oid: bytes | None = None
    args: dict[str, Any] = field(default_factory=dict)


@dataclass
class Transaction:
    """Ordered mutation log; composable via append()."""

    ops: list[Op] = field(default_factory=list)

    def _add(self, code: str, cid: str, oid: bytes | None = None, **args):
        self.ops.append(Op(code, cid, oid, args))
        return self

    # ------------------------------------------------------------ data ops

    def touch(self, cid: str, oid: bytes):
        return self._add(OP_TOUCH, cid, oid)

    def write(self, cid: str, oid: bytes, offset: int, data):
        """``data`` may be bytes, a memoryview, a contiguous ndarray,
        or a BufferList — views ride the transaction un-copied (the
        bufferlist stance); stores materialize at their own durability
        boundary. A bytearray is snapshotted (its owner may mutate)."""
        return self._add(OP_WRITE, cid, oid, offset=offset,
                         data=_as_payload(data))

    def zero(self, cid: str, oid: bytes, offset: int, length: int):
        return self._add(OP_ZERO, cid, oid, offset=offset, length=length)

    def truncate(self, cid: str, oid: bytes, size: int):
        return self._add(OP_TRUNCATE, cid, oid, size=size)

    def remove(self, cid: str, oid: bytes):
        return self._add(OP_REMOVE, cid, oid)

    def clone(self, cid: str, oid: bytes, dest: bytes):
        return self._add(OP_CLONE, cid, oid, dest=dest)

    def clone_range(
        self, cid: str, oid: bytes, dest: bytes,
        src_off: int, length: int, dst_off: int,
    ):
        return self._add(
            OP_CLONERANGE, cid, oid, dest=dest,
            src_off=src_off, length=length, dst_off=dst_off,
        )

    # ----------------------------------------------------------- xattr ops

    def setattr(self, cid: str, oid: bytes, name: str, value: bytes):
        return self._add(OP_SETATTR, cid, oid, name=name, value=bytes(value))

    def setattrs(self, cid: str, oid: bytes, attrs: dict[str, bytes]):
        return self._add(
            OP_SETATTRS, cid, oid,
            attrs={k: bytes(v) for k, v in attrs.items()},
        )

    def rmattr(self, cid: str, oid: bytes, name: str):
        return self._add(OP_RMATTR, cid, oid, name=name)

    def rmattrs(self, cid: str, oid: bytes):
        return self._add(OP_RMATTRS, cid, oid)

    # ------------------------------------------------------ collection ops

    def create_collection(self, cid: str):
        return self._add(OP_MKCOLL, cid)

    def remove_collection(self, cid: str):
        return self._add(OP_RMCOLL, cid)

    def split_collection(self, cid: str, bits: int, rem: int, dest: str):
        """PG split (Transaction::split_collection role): objects whose
        hash matches `rem` under a `bits`-wide mask move to `dest`."""
        return self._add(OP_SPLIT_COLL, cid, bits=bits, rem=rem,
                         dest_cid=dest)

    def merge_collection(self, cid: str, dest: str, bits: int = 0):
        """PG merge: every object of `cid` moves into `dest`, then
        `cid` is removed (Transaction::merge_collection role)."""
        return self._add(OP_MERGE_COLL, cid, bits=bits, dest_cid=dest)

    def set_alloc_hint(self, cid: str, oid: bytes,
                       expected_object_size: int,
                       expected_write_size: int, flags: int = 0):
        """Advisory allocation hint (OP_SETALLOCHINT role): recorded on
        the object for allocator-aware stores."""
        return self._add(OP_SETALLOCHINT, cid, oid,
                         expected_object_size=expected_object_size,
                         expected_write_size=expected_write_size,
                         flags=flags)

    # ------------------------------------------------------------ omap ops

    def omap_clear(self, cid: str, oid: bytes):
        return self._add(OP_OMAP_CLEAR, cid, oid)

    def omap_setkeys(self, cid: str, oid: bytes, kv: dict[bytes, bytes]):
        return self._add(
            OP_OMAP_SETKEYS, cid, oid,
            kv={bytes(k): bytes(v) for k, v in kv.items()},
        )

    def omap_rmkeys(self, cid: str, oid: bytes, keys: Iterable[bytes]):
        return self._add(OP_OMAP_RMKEYS, cid, oid, keys=[bytes(k) for k in keys])

    def omap_rmkeyrange(self, cid: str, oid: bytes, first: bytes, last: bytes):
        return self._add(
            OP_OMAP_RMKEYRANGE, cid, oid, first=bytes(first), last=bytes(last)
        )

    def omap_setheader(self, cid: str, oid: bytes, header: bytes):
        return self._add(OP_OMAP_SETHEADER, cid, oid, header=bytes(header))

    # -------------------------------------------------------------- compose

    def append(self, other: "Transaction"):
        self.ops.extend(other.ops)
        return self

    def __len__(self) -> int:
        return len(self.ops)

    @property
    def empty(self) -> bool:
        return not self.ops

    # --------------------------------------------------------------- wire

    def encode(self) -> bytes:
        """Explicit LE binary form (the denc role) for WAL/wire."""
        return bytes(self.encode_bl())

    def encode_bl(self, bl: BufferList | None = None) -> BufferList:
        """Wire/WAL form as a BufferList: op headers and small args
        marshal into byte segments, OP_WRITE payloads ride as views —
        the flatten happens at the WAL fsync / socket boundary, not
        here."""
        from ..utils import denc

        if bl is None:
            bl = BufferList()
        bl.append(denc.enc_u32(len(self.ops)))
        for op in self.ops:
            head = b"".join((
                denc.enc_str(op.code),
                denc.enc_str(op.cid),
                denc.enc_bytes(op.oid if op.oid is not None else b""),
                denc.enc_u8(op.oid is not None),
            ))
            if op.code == OP_WRITE:
                # schema order (offset, data): the data body is a view
                data = op.args["data"]
                n = len(data)
                bl.append(head + denc.enc_u64(op.args["offset"])
                          + denc.enc_u32(n))
                if n:
                    bl.append(data)
            else:
                bl.append(head + _encode_args(op.code, op.args))
        return bl

    @classmethod
    def decode(cls, buf: bytes, off: int = 0) -> tuple["Transaction", int]:
        from ..utils import denc

        n, off = denc.dec_u32(buf, off)
        t = cls()
        for _ in range(n):
            code, off = denc.dec_str(buf, off)
            cid, off = denc.dec_str(buf, off)
            oid, off = denc.dec_bytes(buf, off)
            has_oid, off = denc.dec_u8(buf, off)
            args, off = _decode_args(code, buf, off)
            t.ops.append(Op(code, cid, oid if has_oid else None, args))
        return t, off


def _as_payload(data):
    """Normalize a write payload to something with byte ``len()`` that
    the transaction can hold without copying: bytes and BufferList pass
    through, everything else goes through the buffer plane's one
    normalization (flat read-only view; bytearray snapshotted;
    non-contiguous storage rejected at the producer)."""
    if isinstance(data, (bytes, BufferList)):
        return data
    return as_view(data)


# arg schemas: name -> (encoder, decoder) pairs per op code
def _arg_schema():
    from ..utils import denc

    b = (denc.enc_bytes, denc.dec_bytes)
    s = (denc.enc_str, denc.dec_str)
    u = (denc.enc_u64, denc.dec_u64)
    kvmap = (
        lambda d: denc.enc_map(d, denc.enc_bytes, denc.enc_bytes),
        lambda buf, off: denc.dec_map(buf, off, denc.dec_bytes, denc.dec_bytes),
    )
    strmap = (
        lambda d: denc.enc_map(d, denc.enc_str, denc.enc_bytes),
        lambda buf, off: denc.dec_map(buf, off, denc.dec_str, denc.dec_bytes),
    )
    keylist = (
        lambda xs: denc.enc_list(xs, denc.enc_bytes),
        lambda buf, off: denc.dec_list(buf, off, denc.dec_bytes),
    )
    return {
        OP_TOUCH: {},
        OP_WRITE: {"offset": u, "data": b},
        OP_ZERO: {"offset": u, "length": u},
        OP_TRUNCATE: {"size": u},
        OP_REMOVE: {},
        OP_SETATTR: {"name": s, "value": b},
        OP_SETATTRS: {"attrs": strmap},
        OP_RMATTR: {"name": s},
        OP_RMATTRS: {},
        OP_CLONE: {"dest": b},
        OP_CLONERANGE: {"dest": b, "src_off": u, "length": u, "dst_off": u},
        OP_MKCOLL: {},
        OP_RMCOLL: {},
        OP_SPLIT_COLL: {"bits": u, "rem": u, "dest_cid": s},
        OP_MERGE_COLL: {"bits": u, "dest_cid": s},
        OP_SETALLOCHINT: {"expected_object_size": u,
                          "expected_write_size": u, "flags": u},
        OP_OMAP_CLEAR: {},
        OP_OMAP_SETKEYS: {"kv": kvmap},
        OP_OMAP_RMKEYS: {"keys": keylist},
        OP_OMAP_RMKEYRANGE: {"first": b, "last": b},
        OP_OMAP_SETHEADER: {"header": b},
    }


_SCHEMA_CACHE = None


def _schema():
    global _SCHEMA_CACHE
    if _SCHEMA_CACHE is None:
        _SCHEMA_CACHE = _arg_schema()
    return _SCHEMA_CACHE


def _encode_args(code: str, args: dict) -> bytes:
    schema = _schema()[code]
    return b"".join(schema[name][0](args[name]) for name in schema)


def _decode_args(code: str, buf: bytes, off: int):
    schema = _schema()[code]
    args = {}
    for name, (_, dec) in schema.items():
        args[name], off = dec(buf, off)
    return args, off
