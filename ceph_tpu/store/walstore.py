"""WalStore: durable file-backed ObjectStore (the BlueStore role).

Durability model mirrors the reference's txc lifecycle
(src/os/bluestore/BlueStore.cc:12636 _txc_state_proc): a transaction is
PREPAREd (encoded via Transaction.encode — the denc wire form doubles as
the WAL redo record), KV_SUBMITTED (appended to the write-ahead log with
length + CRC32C framing, optionally fsynced), then FINISHed (applied to
the in-memory state, on_commit fired). Crash recovery = replay: mount()
loads the last checkpoint snapshot then re-applies every intact WAL
record; a torn tail (short record or CRC mismatch on the final record)
is discarded, exactly the contract a kill -9 mid-append requires.

Blob checksums follow bluestore_blob_t::calc_csum/verify_csum
(src/os/bluestore/bluestore_types.cc:737,763): every object's data is
checksummed per csum block at checkpoint time through the batched
Checksummer (host SSE4.2 path by default, the TPU crc32c kernel with
device=True), and verified on mount (_verify_csum role,
BlueStore.cc:11277) so on-disk corruption is detected before the data
is served.

TPU-first stance: the store is the host side of the framework — its job
is to feed device-sized batches, so checkpoint checksumming is expressed
as ONE batched call over all blocks of all objects rather than a
per-object loop.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable

import numpy as np

from .. import native
from ..checksum import Checksummer
from ..utils import denc
from . import transaction as tx
from .base import (Collection, GroupCommitter, NotFound, Obj, ObjectStore,
                   StoreError)
from .memstore import MemStore

WAL_NAME = "wal.log"
SNAP_NAME = "snap"
SNAP_MAGIC = 0x53_50_55_54  # "TUPS" — snapshot header magic
SNAP_VERSION = 2  # v2: per-object compression flag byte
CSUM_BLOCK = 4096
MIN_COMPRESS_BLOB = 4096  # bluestore_compression_min_blob_size role


class WalStore(MemStore):
    """MemStore semantics + WAL durability + checkpoint snapshots."""

    def __init__(self, path: str, fsync: bool = False,
                 device_csum: bool = False,
                 wal_compact_bytes: int = 64 << 20,
                 compression: str | None = "zlib",
                 commit_window_ms: float = 0.0,
                 commit_max_txns: int = 64):
        super().__init__()
        self.path = path
        self.fsync = fsync
        self.device_csum = device_csum
        self.wal_compact_bytes = wal_compact_bytes
        # group commit (store_commit_window_ms/store_commit_max_txns
        # role): transactions arriving within the window append to the
        # WAL individually but pay ONE flush (+fsync) at the group
        # boundary, when their on_commit callbacks fire. 0 = flush per
        # transaction (the legacy durability shape).
        self._committer = GroupCommitter(
            self._flush_wal, stats=self.commit_stats,
            window_s=commit_window_ms / 1e3, max_txns=commit_max_txns)
        # checkpoint blob compression (bluestore_compression_algorithm
        # role); checksums stay over the RAW bytes so rot is attributed
        # to data, not codec framing
        self._comp = None
        if compression:
            from ..utils import compress as comp_mod

            self._comp = comp_mod.create(compression)
        self._wal = None
        self._wal_size = 0
        self._seq = 0  # last applied transaction sequence number
        self._csum = Checksummer(alg="crc32c", csum_block_size=CSUM_BLOCK)
        self._mounted = False
        self._compactor: threading.Thread | None = None

    # ---------------------------------------------------------- lifecycle

    def mount(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        snap = os.path.join(self.path, SNAP_NAME)
        if os.path.exists(snap):
            with open(snap, "rb") as f:
                self._load_snapshot(f.read())
        wal_path = os.path.join(self.path, WAL_NAME)
        valid_end = 0
        if os.path.exists(wal_path):
            with open(wal_path, "rb") as f:
                valid_end = self._replay_wal(f.read())
        # discard any torn tail NOW: appending after garbage would make
        # every later record unreachable to the next replay
        self._wal = open(wal_path, "ab")
        if self._wal.tell() != valid_end:
            self._wal.truncate(valid_end)
            self._wal.seek(valid_end)
            if self.fsync:
                os.fsync(self._wal.fileno())
        self._wal_size = valid_end
        self._mounted = True

    def umount(self) -> None:
        if not self._mounted:
            return
        self._committer.close()
        if self._compactor is not None:
            self._compactor.join()
        self.compact()
        self._wal.close()
        self._wal = None
        self._mounted = False

    # ------------------------------------------------------------- writes

    def commits_deferred(self) -> bool:
        return self._committer.window_s > 0

    def _flush_wal(self) -> None:
        """The group's ONE durability barrier: flush the buffered WAL
        records of every transaction in the group, fsync once."""
        with self.lock:
            if self._wal is None:
                return
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())

    def queue_transaction(
        self, t: tx.Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        if not self._mounted:
            raise StoreError("not mounted")
        with self.lock:
            # PREPARE: validate + stage copy-on-touch (all-or-nothing);
            # a rejected transaction must never reach the log
            staging = self._stage(t)
            seq = self._seq + 1
            body = denc.enc_u64(seq) + t.encode()
            rec = (
                denc.enc_u32(len(body))
                + denc.enc_u32(native.crc32c(np.frombuffer(body, np.uint8)))
                + body
            )
            # KV_SUBMITTED: the record hits the log BEFORE the visible
            # state flips, so a failed append (ENOSPC…) leaves memory and
            # log consistent; durable once the group's flush ran, only
            # then on_commit (a crash in between replays the flushed
            # prefix and discards the torn tail — exactly the per-txn
            # contract, amortized)
            self._wal.write(rec)
            grouped = self._committer.window_s > 0
            if not grouped:
                # legacy per-txn shape: the flush lands under the SAME
                # lock hold that makes the state visible — no reader
                # can ever serve bytes whose record is still buffered
                t0 = time.perf_counter()
                self._flush_wal()
                self.commit_stats.observe(
                    1, time.perf_counter() - t0)
            self._wal_size += len(rec)
            self._commit_stage(staging)
            self._seq = seq
        if grouped:
            # grouped: visibility precedes durability inside the
            # window by design — acks that promise durability ride the
            # on_commit barrier (cluster/osd.py queue_txn)
            self._committer.add(on_commit)
        elif on_commit:
            on_commit()
        if (self._wal_size >= self.wal_compact_bytes
                and (self._compactor is None
                     or not self._compactor.is_alive())):
            # checkpointing serializes the whole store: run it off the
            # caller's (reactor) thread; compact() takes self.lock
            self._compactor = threading.Thread(
                target=self.compact, daemon=True
            )
            self._compactor.start()

    # --------------------------------------------------------- checkpoint

    def compact(self) -> None:
        """Write a full snapshot, then truncate the WAL (the kv-compaction
        role; atomic via write-to-temp + rename)."""
        # settle the pending group first: its transactions are already
        # in the in-memory state the snapshot captures, so the snapshot
        # IS their durability — but their callbacks must fire before
        # the records that carried them vanish
        self._committer.flush_now()
        with self.lock:
            blob = self._encode_snapshot()
            snap = os.path.join(self.path, SNAP_NAME)
            # unique temp name: a lingering compactor of a crashed-and-
            # reopened instance must not clobber ours mid-publish
            tmp = f"{snap}.tmp.{os.getpid()}.{id(self):x}"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, snap)
            self._wal.truncate(0)
            self._wal.seek(0)
            if self.fsync:
                os.fsync(self._wal.fileno())
            self._wal_size = 0

    # ------------------------------------------------------ wal replay

    def _replay_wal(self, buf: bytes) -> int:
        """Re-apply intact records with seq beyond the snapshot watermark
        (records at or below it are pre-checkpoint history left behind by
        a crash inside compact(), between snapshot publish and WAL
        truncate — skipping by seq makes replay idempotent). Returns the
        byte offset one past the last intact record."""
        off = 0
        n = len(buf)
        while off + 8 <= n:
            length, o2 = denc.dec_u32(buf, off)
            want_crc, o3 = denc.dec_u32(buf, o2)
            if o3 + length > n:
                break  # torn tail: record was mid-append at crash
            body = buf[o3 : o3 + length]
            got = native.crc32c(np.frombuffer(body, np.uint8))
            if got != want_crc:
                break  # torn/corrupt tail record; discard from here on
            seq, boff = denc.dec_u64(body, 0)
            if seq > self._seq:
                t, _ = tx.Transaction.decode(body, boff)
                super().queue_transaction(t)
                self._seq = seq
            off = o3 + length
        return off

    # ------------------------------------------------------ snapshot denc

    def _encode_snapshot(self) -> bytes:
        parts = [
            denc.enc_u32(SNAP_MAGIC),
            denc.enc_u32(SNAP_VERSION),
            denc.enc_u64(self._seq),  # watermark: WAL records <= are stale
            denc.enc_u32(len(self.colls)),
        ]
        # one batched checksum dispatch over every csum block of every
        # object (bluestore_blob_t::calc_csum, batched TPU-style)
        blocks = []
        spans = []  # (#blocks, raw length) per object, in emission order
        for cid in sorted(self.colls):
            c = self.colls[cid]
            for oid in sorted(c.objects):
                data = bytes(c.objects[oid].data)
                nb = -(-len(data) // CSUM_BLOCK) if data else 0
                padded = data + b"\0" * (nb * CSUM_BLOCK - len(data))
                if nb:
                    blocks.append(
                        np.frombuffer(padded, np.uint8).reshape(nb, CSUM_BLOCK)
                    )
                spans.append((nb, len(data)))
        if blocks:
            all_blocks = np.concatenate(blocks, axis=0)
            crcs = self._csum.calculate(all_blocks, device=self.device_csum)
        else:
            crcs = np.zeros(0, np.uint32)
        bi = 0
        si = 0
        for cid in sorted(self.colls):
            c = self.colls[cid]
            parts.append(denc.enc_str(cid))
            parts.append(denc.enc_u32(len(c.objects)))
            for oid in sorted(c.objects):
                o = c.objects[oid]
                nb, raw_len = spans[si]
                si += 1
                obj_crcs = crcs[bi : bi + nb]
                bi += nb
                parts.append(denc.enc_bytes(oid))
                raw = bytes(o.data)
                stored, flag = raw, 0
                if self._comp is not None and len(raw) >= MIN_COMPRESS_BLOB:
                    from ..utils.compress import compress_blob

                    packed = compress_blob(self._comp, raw)
                    if packed is not None:
                        stored, flag = packed, 1
                parts.append(denc.enc_u8(flag))
                parts.append(denc.enc_bytes(stored))
                parts.append(
                    denc.enc_list(
                        [int(v) for v in obj_crcs],
                        lambda v: denc.enc_u32(v),
                    )
                )
                parts.append(
                    denc.enc_map(o.xattrs, denc.enc_str, denc.enc_bytes)
                )
                parts.append(
                    denc.enc_map(o.omap, denc.enc_bytes, denc.enc_bytes)
                )
                parts.append(denc.enc_bytes(o.omap_header))
        return b"".join(parts)

    def _load_snapshot(self, buf: bytes) -> None:
        magic, off = denc.dec_u32(buf, 0)
        if magic != SNAP_MAGIC:
            raise StoreError("bad snapshot magic")
        version, off = denc.dec_u32(buf, off)
        if version != SNAP_VERSION:
            raise StoreError(f"unsupported snapshot version {version}")
        self._seq, off = denc.dec_u64(buf, off)
        ncoll, off = denc.dec_u32(buf, off)
        colls: dict[str, Collection] = {}
        # gather everything first so verification is one batched dispatch
        pending = []  # (data, crc list)
        for _ in range(ncoll):
            cid, off = denc.dec_str(buf, off)
            nobj, off = denc.dec_u32(buf, off)
            c = Collection(cid)
            for _ in range(nobj):
                oid, off = denc.dec_bytes(buf, off)
                flag, off = denc.dec_u8(buf, off)
                data, off = denc.dec_bytes(buf, off)
                if flag:
                    if self._comp is None:
                        raise StoreError(
                            "snapshot is compressed but store opened "
                            "without compression"
                        )
                    data = self._comp.decompress(data)
                crc_list, off = denc.dec_list(buf, off, denc.dec_u32)
                xattrs, off = denc.dec_map(
                    buf, off, denc.dec_str, denc.dec_bytes
                )
                omap, off = denc.dec_map(
                    buf, off, denc.dec_bytes, denc.dec_bytes
                )
                header, off = denc.dec_bytes(buf, off)
                o = Obj()
                o.data = bytearray(data)
                o.xattrs = xattrs
                o.omap = omap
                o.omap_header = header
                c.objects[oid] = o
                pending.append((cid, oid, data, crc_list))
            colls[cid] = c
        self._verify_snapshot_csums(pending)
        self.colls = colls

    def _verify_snapshot_csums(self, pending) -> None:
        """_verify_csum role (BlueStore.cc:11277): recompute every blob
        checksum in one batch and fail the mount on any mismatch."""
        blocks = []
        index = []  # (cid, oid, block#, want)
        for cid, oid, data, crc_list in pending:
            nb = -(-len(data) // CSUM_BLOCK) if data else 0
            if nb != len(crc_list):
                raise StoreError(
                    f"snapshot csum count mismatch on {cid}/{oid!r}"
                )
            if not nb:
                continue
            padded = data + b"\0" * (nb * CSUM_BLOCK - len(data))
            blocks.append(
                np.frombuffer(padded, np.uint8).reshape(nb, CSUM_BLOCK)
            )
            for b, want in enumerate(crc_list):
                index.append((cid, oid, b, want))
        if not blocks:
            return
        got = self._csum.calculate(
            np.concatenate(blocks, axis=0), device=self.device_csum
        )
        want = np.array([w for (_, _, _, w) in index], dtype=np.uint32)
        bad = np.nonzero(got != want)[0]
        if bad.size:
            cid, oid, b, w = index[int(bad[0])]
            raise StoreError(
                f"snapshot csum mismatch on {cid}/{oid!r} block {b}: "
                f"stored {w:#x} != actual {int(got[int(bad[0])]):#x}"
            )
