"""Local object store layer.

Mirrors the reference's ObjectStore seam (src/os/ObjectStore.h:63):
collections (one per PG) hold objects with three facets — byte data,
xattrs, and omap (sorted key/value) — and all mutations flow through
transactional redo logs (src/os/Transaction.h:110-155 op set) applied
atomically by ``queue_transaction`` (ObjectStore.h:223).

Implementations:
- ``MemStore`` (memstore.py) — dict-backed test double, the reference's
  src/os/memstore role; used by OSD-lite processes and tests.
- ``WalStore`` (walstore.py) — persistent directory-backed store with a
  CRC-framed write-ahead log, checkpoint snapshots, and batched CRC32C
  blob checksums through the Checksummer (a FileStore-shaped middle
  tier: whole-store snapshots, data in the checkpoint file).
- ``BlueStoreLite`` (bluestore.py) — the BlueStore role proper: object
  data in 4 KiB blocks on a raw block device (native C++ IO thread
  pool, src/blk role) placed by a native bitmap allocator, metadata in
  the native embedded KV (src/kv role), COW writes, per-block crc32c
  verified on read.

Factory: ``create(kind, path)`` mirroring ObjectStore::create
(src/os/ObjectStore.cc:30-62).
"""
from __future__ import annotations

from .transaction import Transaction  # noqa: F401
from .base import ObjectStore, StoreError, NotFound, Collection  # noqa: F401
from .memstore import MemStore  # noqa: F401


def create(kind: str, path: str | None = None, **kw) -> ObjectStore:
    """ObjectStore::create-style factory (os/ObjectStore.cc:30)."""
    if kind == "memstore":
        return MemStore()
    if kind in ("walstore", "filestore"):
        from .walstore import WalStore

        s = WalStore(path, **kw)
        s.mount()
        return s
    if kind == "bluestore":
        from .bluestore import BlueStoreLite

        s = BlueStoreLite(path, **kw)
        s.mount()
        return s
    raise ValueError(f"unknown store kind {kind!r}")
