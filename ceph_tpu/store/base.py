"""ObjectStore interface + shared transaction application logic.

The contract of src/os/ObjectStore.h:63: collections of objects, each
object a (data, xattrs, omap) triple; reads are synchronous; writes are
queued transactions with an on_commit callback fired once durable.
Stores that keep state in memory can implement `_obj`/`_coll` accessors
and inherit the op interpreter, the way MemStore does in the reference
(src/os/memstore/MemStore.cc do_transaction).
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Iterable

from ..utils.buffer import BufferList
from . import transaction as tx


def write_payload(dst: bytearray, off: int, data) -> None:
    """Land a write payload (bytes / memoryview / BufferList) into the
    store's bytearray at ``off``: BufferList segments write directly at
    their offsets — the store boundary never joins them first."""
    if isinstance(data, BufferList):
        for seg in data.segments():
            n = len(seg)
            dst[off : off + n] = seg
            off += n
    else:
        dst[off : off + len(data)] = data


def payload_bytearray(data) -> bytearray:
    """A fresh bytearray holding the payload (the replacement-object
    fast path): one allocation, segments written in place."""
    if isinstance(data, BufferList):
        out = bytearray(len(data))
        write_payload(out, 0, data)
        return out
    return bytearray(data)


#: reserved oid prefix for snapshot clone objects (single source of
#: truth — cluster/snaps.py builds clone oids from this): CLONE_PREFIX +
#: 8-byte BE cloneid + NUL + head oid
CLONE_PREFIX = b"\x00s"

#: the per-PG metadata object (cluster/pg.py META_OID single source)
PGMETA_OID = b"_pgmeta"


def split_hash_oid(oid: bytes) -> bytes | None:
    """The oid a collection split hashes to decide placement, or None
    for objects pinned to their collection (per-PG metadata only — an
    exact match, so client oids that merely share the prefix still
    migrate). Snapshot clones hash by their embedded HEAD oid so they
    always migrate with their head (the reference's hobject hash is
    head-based)."""
    if oid == PGMETA_OID:
        return None
    if oid.startswith(CLONE_PREFIX):
        return oid[11:]
    return oid


class CommitStats:
    """Per-store group-commit accounting: every durable store bumps
    these at each commit boundary so the bench can report how well
    transactions amortize the flush (commits_grouped / txns_per_commit
    / commit_flush_us — the store-side occupancy counters next to the
    EC batcher's stripes_per_batch)."""

    __slots__ = ("commits", "commits_grouped", "txns", "flush_us_sum")

    def __init__(self) -> None:
        self.commits = 0          # flush boundaries paid
        self.commits_grouped = 0  # boundaries that covered > 1 txn
        self.txns = 0             # transactions committed
        self.flush_us_sum = 0.0   # total time inside the flush fn

    def observe(self, ntxns: int, flush_s: float) -> None:
        self.commits += 1
        if ntxns > 1:
            self.commits_grouped += 1
        self.txns += ntxns
        self.flush_us_sum += flush_s * 1e6

    def dump(self) -> dict:
        return {
            "commits": self.commits,
            "commits_grouped": self.commits_grouped,
            "txns": self.txns,
            "txns_per_commit": (self.txns / self.commits
                                if self.commits else 0.0),
            "commit_flush_us": (self.flush_us_sum / self.commits
                                if self.commits else 0.0),
        }


class GroupCommitter:
    """Window/size-bounded commit grouping (the BlueStore kv-sync
    thread role): transactions arriving within ``window_s`` share ONE
    durability flush (``flush_fn``), then their ``on_commit`` callbacks
    fire together; a group reaching ``max_txns`` flushes ahead of the
    deadline. ``window_s <= 0`` disables grouping — ``add`` flushes
    inline, reproducing per-transaction durability exactly.

    Locking contract: ``add``/``flush_now`` are called WITHOUT the
    store lock held for the flush part; ``flush_fn`` takes the store
    lock itself. The flusher thread never holds the group condition
    while flushing, so store-lock holders can always enqueue."""

    def __init__(self, flush_fn: Callable[[], None],
                 stats: CommitStats | None = None,
                 window_s: float = 0.0, max_txns: int = 64):
        self.flush_fn = flush_fn
        self.stats = stats
        self.window_s = float(window_s)
        self.max_txns = max(1, int(max_txns))
        self._cond = threading.Condition()
        self._cbs: list[Callable[[], None]] = []
        self._ntxns = 0
        self._deadline: float | None = None
        self._thread: threading.Thread | None = None
        self._stop = False

    # ------------------------------------------------------------- entry

    def add(self, on_commit: Callable[[], None] | None) -> None:
        """One committed-to-memory transaction wants durability. In
        grouped mode its flush (and callback) ride the group; inline
        mode flushes now — on_commit exceptions then propagate to the
        caller like the pre-group-commit path did."""
        if self.window_s <= 0:
            t0 = time.perf_counter()
            self.flush_fn()
            if self.stats is not None:
                self.stats.observe(1, time.perf_counter() - t0)
            if on_commit:
                on_commit()
            return
        with self._cond:
            self._ntxns += 1
            if on_commit:
                self._cbs.append(on_commit)
            now = time.monotonic()
            if self._deadline is None:
                self._deadline = now + self.window_s
            if self._ntxns >= self.max_txns:
                self._deadline = now  # size trigger: flush ahead of it
            self._ensure_thread()
            self._cond.notify()

    def _ensure_thread(self) -> None:  # _cond held
        if self._thread is None or not self._thread.is_alive():
            self._stop = False  # a closed committer revives on re-use
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True)
            self._thread.start()

    # ------------------------------------------------------------- flush

    def _steal(self) -> tuple[int, list]:  # _cond held
        cbs, self._cbs = self._cbs, []
        n, self._ntxns = self._ntxns, 0
        self._deadline = None
        return n, cbs

    def _do_flush(self, n: int, cbs: list) -> None:
        t0 = time.perf_counter()
        try:
            self.flush_fn()
        except Exception:
            # a failed flush must neither fire the callbacks (they
            # mean DURABLE) nor drop them (their waiters would hang
            # forever) nor kill the flusher: re-queue the group at the
            # front, re-arm a retry deadline, and report. A transient
            # error (EINTR, pressure) clears on the retry; a dead disk
            # keeps the callbacks honestly un-fired.
            print("group-commit flush failed (group re-queued):",
                  file=sys.stderr)
            traceback.print_exc()
            with self._cond:
                if self._stop:
                    return  # closing: nothing will retry — drop, the
                    #         callbacks were never durability-promised
                self._cbs[:0] = cbs
                self._ntxns += n
                if self._deadline is None:
                    self._deadline = (time.monotonic()
                                      + max(self.window_s, 0.05))
                self._ensure_thread()
                self._cond.notify()
            return
        if self.stats is not None:
            self.stats.observe(n, time.perf_counter() - t0)
        for cb in cbs:
            try:
                cb()
            except Exception:
                # a grouped callback has no caller stack to fail into;
                # its batch-mates' callbacks must still fire
                print("group-commit on_commit callback failed:",
                      file=sys.stderr)
                traceback.print_exc()

    def flush_now(self) -> None:
        """Explicit barrier (umount, checkpoint, tests): flush whatever
        is pending and fire its callbacks before returning."""
        with self._cond:
            n, cbs = self._steal()
        if n:
            self._do_flush(n, cbs)

    def close(self) -> None:
        self.flush_now()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._stop and self._ntxns == 0:
                    self._cond.wait()
                if self._stop and self._ntxns == 0:
                    return
                now = time.monotonic()
                while (not self._stop and self._deadline is not None
                       and now < self._deadline
                       and self._ntxns < self.max_txns):
                    self._cond.wait(self._deadline - now)
                    now = time.monotonic()
                n, cbs = self._steal()
            if n:
                self._do_flush(n, cbs)


class StoreError(Exception):
    pass


class NotFound(StoreError):
    pass


class Collection:
    """One collection (= one PG's objects). In-memory representation."""

    def __init__(self, cid: str):
        self.cid = cid
        self.objects: dict[bytes, Obj] = {}


class Obj:
    __slots__ = ("data", "xattrs", "omap", "omap_header")

    def __init__(self):
        self.data = bytearray()
        self.xattrs: dict[str, bytes] = {}
        self.omap: dict[bytes, bytes] = {}
        self.omap_header = b""

    def clone(self) -> "Obj":
        o = Obj()
        o.data = bytearray(self.data)
        o.xattrs = dict(self.xattrs)
        o.omap = dict(self.omap)
        o.omap_header = self.omap_header
        return o


class ObjectStore:
    """Abstract store; subclasses provide durability."""

    def __init__(self) -> None:
        #: group-commit occupancy counters (CommitStats): every store
        #: kind reports the same shape, so `txns_per_commit` means the
        #: same thing whether the flush is a WAL fsync or a kv batch
        self.commit_stats = CommitStats()

    def mount(self) -> None: ...

    def umount(self) -> None: ...

    # ------------------------------------------------------------- writes

    def queue_transaction(
        self, t: tx.Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        raise NotImplementedError

    def commits_deferred(self) -> bool:
        """True when queue_transaction may RETURN before the
        transaction is durable (a group-commit window is armed): an
        ack that implies durability must then ride on_commit instead
        of the call's return (cluster/osd.py queue_txn)."""
        return False

    def apply_transaction(self, t: tx.Transaction) -> None:
        """Synchronous convenience: queue + wait."""
        done = threading.Event()
        self.queue_transaction(t, done.set)
        done.wait()

    # -------------------------------------------------------------- reads

    def read(self, cid: str, oid: bytes, offset: int = 0, length: int = -1) -> bytes:
        raise NotImplementedError

    def stat(self, cid: str, oid: bytes) -> int:
        """Object size in bytes (raises NotFound)."""
        raise NotImplementedError

    def exists(self, cid: str, oid: bytes) -> bool:
        try:
            self.stat(cid, oid)
            return True
        except NotFound:
            return False

    def getattr(self, cid: str, oid: bytes, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, cid: str, oid: bytes) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid: str, oid: bytes) -> dict[bytes, bytes]:
        raise NotImplementedError

    def omap_get_header(self, cid: str, oid: bytes) -> bytes:
        raise NotImplementedError

    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def list_objects(self, cid: str) -> list[bytes]:
        raise NotImplementedError

    # --------------------------------------------- shared op interpreter

    def _get_coll(self, cid: str) -> Collection:
        raise NotImplementedError

    def _do_op(self, colls: dict[str, Collection], op: tx.Op) -> None:
        """Interpret one op against in-memory collections (the MemStore
        do_transaction role; FileStoreLite replays the same ops)."""
        if op.code == tx.OP_MKCOLL:
            if op.cid in colls:
                raise StoreError(f"collection {op.cid} exists")
            colls[op.cid] = Collection(op.cid)
            return
        if op.code == tx.OP_RMCOLL:
            c = colls.get(op.cid)
            if c is None:
                raise NotFound(op.cid)
            if c.objects:
                raise StoreError(f"collection {op.cid} not empty")
            del colls[op.cid]
            return
        if op.code == tx.OP_SPLIT_COLL:
            src = colls.get(op.cid)
            if src is None:
                raise NotFound(op.cid)
            dest = colls.get(op.args["dest_cid"])
            if dest is None:
                raise NotFound(op.args["dest_cid"])
            mask = (1 << op.args["bits"]) - 1
            from ..placement.osdmap import ceph_str_hash_rjenkins

            moving = []
            for oid in src.objects:
                key = split_hash_oid(oid)
                if key is not None and \
                        ceph_str_hash_rjenkins(key) & mask == op.args["rem"]:
                    moving.append(oid)
            for oid in moving:
                dest.objects[oid] = src.objects.pop(oid)
            return
        if op.code == tx.OP_MERGE_COLL:
            src = colls.get(op.cid)
            if src is None:
                raise NotFound(op.cid)
            dest = colls.get(op.args["dest_cid"])
            if dest is None:
                raise NotFound(op.args["dest_cid"])
            dest.objects.update(src.objects)
            del colls[op.cid]
            return
        c = colls.get(op.cid)
        if c is None:
            raise NotFound(f"collection {op.cid}")
        a = op.args
        # read-only lookups: peek avoids dragging untouched objects
        # through a staged overlay's copy-on-touch (plain dicts: get)
        peek = getattr(c.objects, "peek", c.objects.get)
        if op.code == tx.OP_WRITE and a["offset"] == 0:
            old = peek(op.oid)
            if old is not None and len(a["data"]) >= len(old.data):
                # full overwrite: build the replacement object from the
                # new bytes directly instead of copy-on-touch cloning
                # (and then fully overwriting) the old data — the EC
                # shard-rewrite shape pays this per sub-op, and the
                # clone was the write path's dominant memcpy
                o = Obj()
                o.data = payload_bytearray(a["data"])
                o.xattrs = dict(old.xattrs)
                o.omap = dict(old.omap)
                o.omap_header = old.omap_header
                c.objects[op.oid] = o
                return
        if op.code == tx.OP_TOUCH:
            if peek(op.oid) is None:
                c.objects[op.oid] = Obj()
            return
        if op.code == tx.OP_REMOVE:
            if op.oid not in c.objects:
                raise NotFound(repr(op.oid))
            del c.objects[op.oid]
            return
        if op.code == tx.OP_CLONE:
            src = peek(op.oid)
            if src is None:
                raise NotFound(repr(op.oid))
            c.objects[a["dest"]] = src.clone()
            return
        if op.code == tx.OP_CLONERANGE:
            src = peek(op.oid)
            if src is None:
                raise NotFound(repr(op.oid))
            dst = c.objects.setdefault(a["dest"], Obj())
            data = bytes(src.data[a["src_off"] : a["src_off"] + a["length"]])
            end = a["dst_off"] + len(data)
            if len(dst.data) < end:
                dst.data.extend(b"\0" * (end - len(dst.data)))
            dst.data[a["dst_off"] : end] = data
            return
        o = c.objects.get(op.oid)
        if o is None:
            # write-type ops create; read-modify ops demand existence
            if op.code in (
                tx.OP_WRITE, tx.OP_ZERO, tx.OP_TRUNCATE, tx.OP_SETATTR,
                tx.OP_SETATTRS, tx.OP_OMAP_SETKEYS, tx.OP_OMAP_SETHEADER,
                tx.OP_SETALLOCHINT,
            ):
                o = c.objects.setdefault(op.oid, Obj())
            else:
                raise NotFound(repr(op.oid))
        if op.code == tx.OP_WRITE:
            off = a["offset"]
            end = off + len(a["data"])
            if off >= len(o.data):
                # append shape (incl. a fresh object's first write):
                # no zero-fill of bytes the data is about to cover
                if off > len(o.data):
                    o.data.extend(b"\0" * (off - len(o.data)))
                if isinstance(a["data"], BufferList):
                    for seg in a["data"].segments():
                        o.data += seg
                else:
                    o.data += a["data"]
            else:
                if len(o.data) < end:
                    o.data.extend(b"\0" * (end - len(o.data)))
                write_payload(o.data, off, a["data"])
        elif op.code == tx.OP_ZERO:
            end = a["offset"] + a["length"]
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[a["offset"] : end] = b"\0" * a["length"]
        elif op.code == tx.OP_TRUNCATE:
            size = a["size"]
            if size < len(o.data):
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
        elif op.code == tx.OP_SETATTR:
            o.xattrs[a["name"]] = a["value"]
        elif op.code == tx.OP_SETATTRS:
            o.xattrs.update(a["attrs"])
        elif op.code == tx.OP_RMATTR:
            o.xattrs.pop(a["name"], None)
        elif op.code == tx.OP_RMATTRS:
            o.xattrs.clear()
        elif op.code == tx.OP_OMAP_CLEAR:
            o.omap.clear()
        elif op.code == tx.OP_OMAP_SETKEYS:
            o.omap.update(a["kv"])
        elif op.code == tx.OP_OMAP_RMKEYS:
            for k in a["keys"]:
                o.omap.pop(k, None)
        elif op.code == tx.OP_OMAP_RMKEYRANGE:
            for k in [k for k in o.omap if a["first"] <= k < a["last"]]:
                del o.omap[k]
        elif op.code == tx.OP_OMAP_SETHEADER:
            o.omap_header = a["header"]
        elif op.code == tx.OP_SETALLOCHINT:
            # advisory: recorded for allocator-aware stores
            o.xattrs["_alloc_hint"] = (
                a["expected_object_size"].to_bytes(8, "little")
                + a["expected_write_size"].to_bytes(8, "little")
                + a["flags"].to_bytes(4, "little")
            )
        else:
            raise StoreError(f"unknown op {op.code}")
