"""ObjectStore interface + shared transaction application logic.

The contract of src/os/ObjectStore.h:63: collections of objects, each
object a (data, xattrs, omap) triple; reads are synchronous; writes are
queued transactions with an on_commit callback fired once durable.
Stores that keep state in memory can implement `_obj`/`_coll` accessors
and inherit the op interpreter, the way MemStore does in the reference
(src/os/memstore/MemStore.cc do_transaction).
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable

from . import transaction as tx


#: reserved oid prefix for snapshot clone objects (single source of
#: truth — cluster/snaps.py builds clone oids from this): CLONE_PREFIX +
#: 8-byte BE cloneid + NUL + head oid
CLONE_PREFIX = b"\x00s"

#: the per-PG metadata object (cluster/pg.py META_OID single source)
PGMETA_OID = b"_pgmeta"


def split_hash_oid(oid: bytes) -> bytes | None:
    """The oid a collection split hashes to decide placement, or None
    for objects pinned to their collection (per-PG metadata only — an
    exact match, so client oids that merely share the prefix still
    migrate). Snapshot clones hash by their embedded HEAD oid so they
    always migrate with their head (the reference's hobject hash is
    head-based)."""
    if oid == PGMETA_OID:
        return None
    if oid.startswith(CLONE_PREFIX):
        return oid[11:]
    return oid


class StoreError(Exception):
    pass


class NotFound(StoreError):
    pass


class Collection:
    """One collection (= one PG's objects). In-memory representation."""

    def __init__(self, cid: str):
        self.cid = cid
        self.objects: dict[bytes, Obj] = {}


class Obj:
    __slots__ = ("data", "xattrs", "omap", "omap_header")

    def __init__(self):
        self.data = bytearray()
        self.xattrs: dict[str, bytes] = {}
        self.omap: dict[bytes, bytes] = {}
        self.omap_header = b""

    def clone(self) -> "Obj":
        o = Obj()
        o.data = bytearray(self.data)
        o.xattrs = dict(self.xattrs)
        o.omap = dict(self.omap)
        o.omap_header = self.omap_header
        return o


class ObjectStore:
    """Abstract store; subclasses provide durability."""

    def mount(self) -> None: ...

    def umount(self) -> None: ...

    # ------------------------------------------------------------- writes

    def queue_transaction(
        self, t: tx.Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        raise NotImplementedError

    def apply_transaction(self, t: tx.Transaction) -> None:
        """Synchronous convenience: queue + wait."""
        done = threading.Event()
        self.queue_transaction(t, done.set)
        done.wait()

    # -------------------------------------------------------------- reads

    def read(self, cid: str, oid: bytes, offset: int = 0, length: int = -1) -> bytes:
        raise NotImplementedError

    def stat(self, cid: str, oid: bytes) -> int:
        """Object size in bytes (raises NotFound)."""
        raise NotImplementedError

    def exists(self, cid: str, oid: bytes) -> bool:
        try:
            self.stat(cid, oid)
            return True
        except NotFound:
            return False

    def getattr(self, cid: str, oid: bytes, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, cid: str, oid: bytes) -> dict[str, bytes]:
        raise NotImplementedError

    def omap_get(self, cid: str, oid: bytes) -> dict[bytes, bytes]:
        raise NotImplementedError

    def omap_get_header(self, cid: str, oid: bytes) -> bytes:
        raise NotImplementedError

    def list_collections(self) -> list[str]:
        raise NotImplementedError

    def list_objects(self, cid: str) -> list[bytes]:
        raise NotImplementedError

    # --------------------------------------------- shared op interpreter

    def _get_coll(self, cid: str) -> Collection:
        raise NotImplementedError

    def _do_op(self, colls: dict[str, Collection], op: tx.Op) -> None:
        """Interpret one op against in-memory collections (the MemStore
        do_transaction role; FileStoreLite replays the same ops)."""
        if op.code == tx.OP_MKCOLL:
            if op.cid in colls:
                raise StoreError(f"collection {op.cid} exists")
            colls[op.cid] = Collection(op.cid)
            return
        if op.code == tx.OP_RMCOLL:
            c = colls.get(op.cid)
            if c is None:
                raise NotFound(op.cid)
            if c.objects:
                raise StoreError(f"collection {op.cid} not empty")
            del colls[op.cid]
            return
        if op.code == tx.OP_SPLIT_COLL:
            src = colls.get(op.cid)
            if src is None:
                raise NotFound(op.cid)
            dest = colls.get(op.args["dest_cid"])
            if dest is None:
                raise NotFound(op.args["dest_cid"])
            mask = (1 << op.args["bits"]) - 1
            from ..placement.osdmap import ceph_str_hash_rjenkins

            moving = []
            for oid in src.objects:
                key = split_hash_oid(oid)
                if key is not None and \
                        ceph_str_hash_rjenkins(key) & mask == op.args["rem"]:
                    moving.append(oid)
            for oid in moving:
                dest.objects[oid] = src.objects.pop(oid)
            return
        if op.code == tx.OP_MERGE_COLL:
            src = colls.get(op.cid)
            if src is None:
                raise NotFound(op.cid)
            dest = colls.get(op.args["dest_cid"])
            if dest is None:
                raise NotFound(op.args["dest_cid"])
            dest.objects.update(src.objects)
            del colls[op.cid]
            return
        c = colls.get(op.cid)
        if c is None:
            raise NotFound(f"collection {op.cid}")
        a = op.args
        # read-only lookups: peek avoids dragging untouched objects
        # through a staged overlay's copy-on-touch (plain dicts: get)
        peek = getattr(c.objects, "peek", c.objects.get)
        if op.code == tx.OP_TOUCH:
            if peek(op.oid) is None:
                c.objects[op.oid] = Obj()
            return
        if op.code == tx.OP_REMOVE:
            if op.oid not in c.objects:
                raise NotFound(repr(op.oid))
            del c.objects[op.oid]
            return
        if op.code == tx.OP_CLONE:
            src = peek(op.oid)
            if src is None:
                raise NotFound(repr(op.oid))
            c.objects[a["dest"]] = src.clone()
            return
        if op.code == tx.OP_CLONERANGE:
            src = peek(op.oid)
            if src is None:
                raise NotFound(repr(op.oid))
            dst = c.objects.setdefault(a["dest"], Obj())
            data = bytes(src.data[a["src_off"] : a["src_off"] + a["length"]])
            end = a["dst_off"] + len(data)
            if len(dst.data) < end:
                dst.data.extend(b"\0" * (end - len(dst.data)))
            dst.data[a["dst_off"] : end] = data
            return
        o = c.objects.get(op.oid)
        if o is None:
            # write-type ops create; read-modify ops demand existence
            if op.code in (
                tx.OP_WRITE, tx.OP_ZERO, tx.OP_TRUNCATE, tx.OP_SETATTR,
                tx.OP_SETATTRS, tx.OP_OMAP_SETKEYS, tx.OP_OMAP_SETHEADER,
                tx.OP_SETALLOCHINT,
            ):
                o = c.objects.setdefault(op.oid, Obj())
            else:
                raise NotFound(repr(op.oid))
        if op.code == tx.OP_WRITE:
            end = a["offset"] + len(a["data"])
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[a["offset"] : end] = a["data"]
        elif op.code == tx.OP_ZERO:
            end = a["offset"] + a["length"]
            if len(o.data) < end:
                o.data.extend(b"\0" * (end - len(o.data)))
            o.data[a["offset"] : end] = b"\0" * a["length"]
        elif op.code == tx.OP_TRUNCATE:
            size = a["size"]
            if size < len(o.data):
                del o.data[size:]
            else:
                o.data.extend(b"\0" * (size - len(o.data)))
        elif op.code == tx.OP_SETATTR:
            o.xattrs[a["name"]] = a["value"]
        elif op.code == tx.OP_SETATTRS:
            o.xattrs.update(a["attrs"])
        elif op.code == tx.OP_RMATTR:
            o.xattrs.pop(a["name"], None)
        elif op.code == tx.OP_RMATTRS:
            o.xattrs.clear()
        elif op.code == tx.OP_OMAP_CLEAR:
            o.omap.clear()
        elif op.code == tx.OP_OMAP_SETKEYS:
            o.omap.update(a["kv"])
        elif op.code == tx.OP_OMAP_RMKEYS:
            for k in a["keys"]:
                o.omap.pop(k, None)
        elif op.code == tx.OP_OMAP_RMKEYRANGE:
            for k in [k for k in o.omap if a["first"] <= k < a["last"]]:
                del o.omap[k]
        elif op.code == tx.OP_OMAP_SETHEADER:
            o.omap_header = a["header"]
        elif op.code == tx.OP_SETALLOCHINT:
            # advisory: recorded for allocator-aware stores
            o.xattrs["_alloc_hint"] = (
                a["expected_object_size"].to_bytes(8, "little")
                + a["expected_write_size"].to_bytes(8, "little")
                + a["flags"].to_bytes(4, "little")
            )
        else:
            raise StoreError(f"unknown op {op.code}")
