"""MemStore: dict-backed ObjectStore (the reference src/os/memstore role).

The cluster-free test double (SURVEY.md §4 tier 2): transactions apply
synchronously under one lock with all-or-nothing semantics. Staging is
object-granular copy-on-touch — an overlay of cloned objects over the
committed collections, folded in on success — so a transaction costs
O(objects it touches), not O(objects in the PG) (the same txc shape as
BlueStoreLite; the previous whole-collection deep clone made every
write linear in PG population, the dominant term of write latency
under the bench).
"""
from __future__ import annotations

import threading
from typing import Callable

from . import transaction as tx
from .base import Collection, NotFound, Obj, ObjectStore


class _TxnObjects:
    """Dict-like view of one collection's objects for the op
    interpreter: reads fall through to committed state, any access
    clones the object into the overlay first (the interpreter mutates
    in place), deletions are tombstones (None)."""

    def __init__(self, committed: dict[bytes, Obj] | None):
        self.committed = committed if committed is not None else {}
        self.overlay: dict[bytes, Obj | None] = {}

    def _live(self, oid: bytes) -> Obj | None:
        if oid in self.overlay:
            return self.overlay[oid]
        o = self.committed.get(oid)
        if o is not None:  # copy-on-first-touch
            o = o.clone()
            self.overlay[oid] = o
        return o

    def peek(self, oid: bytes) -> Obj | None:
        """Read-only view WITHOUT cloning into the overlay (for clone
        sources and existence probes — a pure read must not drag an
        untouched object through the commit fold)."""
        if oid in self.overlay:
            return self.overlay[oid]
        return self.committed.get(oid)

    def get(self, oid: bytes) -> Obj | None:
        return self._live(oid)

    def __contains__(self, oid: bytes) -> bool:
        if oid in self.overlay:
            return self.overlay[oid] is not None
        return oid in self.committed

    def __getitem__(self, oid: bytes) -> Obj:
        o = self._live(oid)
        if o is None:
            raise KeyError(oid)
        return o

    def __setitem__(self, oid: bytes, o: Obj) -> None:
        self.overlay[oid] = o

    def __delitem__(self, oid: bytes) -> None:
        if oid not in self:
            raise KeyError(oid)
        self.overlay[oid] = None

    def setdefault(self, oid: bytes, default: Obj) -> Obj:
        o = self._live(oid)
        if o is None:
            o = default
            self.overlay[oid] = o
        return o

    def pop(self, oid: bytes) -> Obj:
        o = self._live(oid)
        if o is None:
            raise KeyError(oid)
        self.overlay[oid] = None
        return o

    def update(self, other: "_TxnObjects") -> None:
        for oid in list(other):
            self[oid] = other[oid]

    def __iter__(self):
        for oid in self.committed:
            if self.overlay.get(oid, ...) is not None:
                yield oid
        for oid, o in self.overlay.items():
            if o is not None and oid not in self.committed:
                yield oid

    def __bool__(self) -> bool:
        return next(iter(self), None) is not None

    def keys(self):
        return iter(self)


class _TxnColl:
    """Collection stand-in handed to the shared op interpreter."""

    def __init__(self, cid: str, committed: Collection | None):
        self.cid = cid
        self.objects = _TxnObjects(
            committed.objects if committed is not None else None)


class _Staging(dict):
    """cid -> _TxnColl view over the committed coll map, with lazy view
    creation and add/remove tracking for commit time."""

    def __init__(self, store: "MemStore"):
        super().__init__()
        self.store = store
        self.removed: set[str] = set()
        self.added: set[str] = set()

    def __contains__(self, cid) -> bool:
        if dict.__contains__(self, cid):
            return True
        return cid not in self.removed and cid in self.store.colls

    def get(self, cid, default=None):
        if dict.__contains__(self, cid):
            return dict.__getitem__(self, cid)
        if cid in self.removed or cid not in self.store.colls:
            return default
        view = _TxnColl(cid, self.store.colls[cid])
        dict.__setitem__(self, cid, view)
        return view

    def __getitem__(self, cid):
        v = self.get(cid)
        if v is None:
            raise KeyError(cid)
        return v

    def __setitem__(self, cid, coll) -> None:
        # MKCOLL inserts a fresh empty Collection; a populated one
        # would silently lose its objects here, so refuse it loudly
        assert not coll.objects, "only empty collections can be staged"
        view = _TxnColl(cid, None)
        dict.__setitem__(self, cid, view)
        self.added.add(cid)
        self.removed.discard(cid)

    def __delitem__(self, cid) -> None:
        if dict.__contains__(self, cid):
            dict.__delitem__(self, cid)
        self.removed.add(cid)
        self.added.discard(cid)


class MemStore(ObjectStore):
    def __init__(self) -> None:
        super().__init__()
        self.colls: dict[str, Collection] = {}
        self.lock = threading.RLock()

    # ------------------------------------------------------------- writes

    def queue_transaction(
        self, t: tx.Transaction, on_commit: Callable[[], None] | None = None
    ) -> None:
        with self.lock:
            self._commit_stage(self._stage(t))
        if on_commit:
            on_commit()

    def _stage(self, t: tx.Transaction) -> _Staging:
        """All-or-nothing staging: run the ops against copy-on-touch
        views; nothing committed is mutated until _commit_stage."""
        with self.lock:
            staging = _Staging(self)
            for op in t.ops:
                self._do_op(staging, op)
            return staging

    def _commit_stage(self, staging: _Staging) -> None:
        for cid in staging.removed:
            self.colls.pop(cid, None)
        for cid in staging.added:
            self.colls[cid] = Collection(cid)
        for cid, view in staging.items():
            if cid in staging.removed:
                continue
            base = self.colls.get(cid)
            if base is None:  # re-created under an added cid above
                continue
            for oid, o in view.objects.overlay.items():
                if o is None:
                    base.objects.pop(oid, None)
                else:
                    base.objects[oid] = o


    # -------------------------------------------------------------- reads

    def _coll(self, cid: str) -> Collection:
        c = self.colls.get(cid)
        if c is None:
            raise NotFound(f"collection {cid}")
        return c

    def _obj(self, cid: str, oid: bytes):
        o = self._coll(cid).objects.get(oid)
        if o is None:
            raise NotFound(repr(oid))
        return o

    def read(self, cid: str, oid: bytes, offset: int = 0, length: int = -1) -> bytes:
        with self.lock:
            o = self._obj(cid, oid)
            if length < 0:
                return bytes(o.data[offset:])
            return bytes(o.data[offset : offset + length])

    def stat(self, cid: str, oid: bytes) -> int:
        with self.lock:
            return len(self._obj(cid, oid).data)

    def getattr(self, cid: str, oid: bytes, name: str) -> bytes:
        with self.lock:
            attrs = self._obj(cid, oid).xattrs
            if name not in attrs:
                raise NotFound(name)
            return attrs[name]

    def getattrs(self, cid: str, oid: bytes) -> dict[str, bytes]:
        with self.lock:
            return dict(self._obj(cid, oid).xattrs)

    def omap_get(self, cid: str, oid: bytes) -> dict[bytes, bytes]:
        with self.lock:
            return dict(self._obj(cid, oid).omap)

    def omap_get_header(self, cid: str, oid: bytes) -> bytes:
        with self.lock:
            return self._obj(cid, oid).omap_header

    def list_collections(self) -> list[str]:
        with self.lock:
            return sorted(self.colls)

    def list_objects(self, cid: str) -> list[bytes]:
        with self.lock:
            return sorted(self._coll(cid).objects)
